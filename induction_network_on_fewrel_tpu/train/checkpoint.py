"""Orbax checkpointing: params + opt state + step + sampler RNG + config.

Reference behavior (SURVEY.md §5.4): ``torch.save(state_dict)`` on best-val,
``--load_ckpt`` for test/finetune. Here: orbax with best-metric retention AND
full resume (optimizer state and step survive, which torch ckpts in the
reference family lose).

**Delta ring saves (round 6, ``cfg.ckpt_delta``).** The recovery ring is
pure redundancy written at every val boundary, and for the lazy-embed
flagship its payload was ~97% embedding state: table + two Adam moment
arrays + the per-row counts (~242 MB of the ~250 MB d2h that drove the
warm-soak all-in/windowed ratio to 54%, BASELINE.md round 5). Ring saves
therefore now write **base + touched-row deltas** when the state carries
the lazy-embed leaves:

* the FIRST ring save is a full **base** (flat-leaf format, its embedding
  leaves kept resident on device as the diff reference);
* every later ring save diffs the four embedding leaves against the base
  on device (one elementwise compare + ``nonzero``), and enqueues only
  the changed rows + the (small) non-embedding leaves. Never-touched rows
  are bitwise-equal to the base by the lazy invariant (m = v = 0 rows
  have exactly-zero updates), so the row set is exact — not a heuristic —
  and resume-from-delta reconstructs the identical state
  (tests/test_ckpt_delta.py pins trajectory equality).
* a delta that grows past half the table triggers a fresh base
  (re-snapshot), so pathological corpora degrade to the old full save,
  never to a larger one.

Best-checkpoint saves stay full: they are the durable artifact other
tools (test.py, serving, convert_lazy_ckpt) consume. Non-lazy states
(no emb leaves) keep full ring saves; ``ckpt_delta="off"`` forces them.

**Integrity chain (ISSUE 12).** Every save (best, full ring, base,
delta) writes an ``integrity_<step>.json`` sidecar next to its step dir:
per-leaf sha256 digests of the exact host tree handed to orbax, plus a
manifest digest. Restores verify the reassembled tree against the
manifest; a mismatch — or a restore that raises on a slot whose data
fails re-verification — is a **corrupt slot**:

* the slot (step dir + its manifest, in staging AND the real dir) is
  QUARANTINED: renamed aside with a ``.quarantined`` suffix, never
  silently purged — the evidence survives for a post-mortem, and orbax
  stops seeing the step so later saves at that number are accepted;
* a ``kind="fault"`` record (action="ckpt_quarantine") is emitted; the
  health watchdog latches a CRITICAL ``ckpt_corrupt`` per slot;
* ``restore_latest``/``restore_best`` walk to the next-newest intact
  slot — including quarantining a delta whose base died (the orphaned
  delta cannot resolve) — and the cursor sidecar follows the surviving
  step, so kill/corrupt/resume continues from the best surviving state
  instead of crashing (tests/test_ckpt_integrity.py).

Pre-integrity dirs (no manifest) keep the old behavior: restore errors
raise, nothing is quarantined — a structural mismatch against an intact
slot must stay a loud config error, which is also why a restore failure
WITH a manifest first re-verifies the raw stored data before declaring
corruption (intact data + failed restore = architecture mismatch, the
original error re-raises).
"""

from __future__ import annotations

import queue
import threading
from pathlib import Path
from typing import Any

import orbax.checkpoint as ocp

from induction_network_on_fewrel_tpu.config import ExperimentConfig


_jit_tree_copy = None


def _device_snapshot(state: Any) -> Any:
    """Copy device arrays on-device (HBM-speed, ONE async dispatch) so the
    snapshot is decoupled from buffer donation: the next train step donates
    the live state's buffers, and the d2h transfer happens later on the
    saver thread from this copy. Host arrays pass through untouched.

    The whole tree goes through ONE jitted copy program: per-leaf EAGER
    jnp.copy on the tunneled backend routed each big array through the
    host (measured: 78 s of blocking "enqueue" for the 250 MB lazy-soak
    state, round 4 — the boundary cost that capped the 10k soak's all-in
    throughput at ~20% of its windowed rate). jit is also the only legal
    path for leaves that span hosts (--zero_opt moments dp-sharded over a
    pod): output shardings are inferred from the inputs, so every leaf
    keeps its layout (advisor finding, round 2)."""
    import jax

    global _jit_tree_copy
    if _jit_tree_copy is None:
        import jax.numpy as jnp

        _jit_tree_copy = jax.jit(lambda leaves: [jnp.copy(l) for l in leaves])

    leaves, treedef = jax.tree_util.tree_flatten(state)
    dev = [i for i, l in enumerate(leaves) if isinstance(l, jax.Array)]
    copied = _jit_tree_copy([leaves[i] for i in dev])
    out = list(leaves)
    for i, c in zip(dev, copied):
        out[i] = c
    return jax.tree_util.tree_unflatten(treedef, out)

# Parameter-tree layout version, stored next to config.json. Bump whenever a
# module's param structure changes incompatibly so restores fail with THIS
# message instead of an opaque orbax tree mismatch.
#   v2: BiLSTM params became explicit w_ih/w_hh/bias (ops/lstm.py backends)
#       instead of flax RNN/OptimizedLSTMCell's nested tree.
#   v3: BiLSTM directions un-tied — w_ih/w_hh/bias grew a leading [2, ...]
#       direction axis (torch bidirectional parity: independent `*_reverse`
#       weights per direction).
#   v4: self-attention params renamed Dense_0/Dense_1 -> explicit
#       att_w1/att_w2 (shared by the two-pass and fused-kernel attention
#       paths, ops/attn.py). Shapes/init unchanged; names only.
FORMAT_VERSION = 4


def _format_compatible(stored: int, arch: ExperimentConfig) -> bool:
    """Whether a checkpoint written at ``stored`` restores under this build.

    Version bumps usually touch one module's tree, so older checkpoints whose
    architecture never instantiates that module are still valid — reject only
    the combinations that actually changed.
    """
    if stored == FORMAT_VERSION:
        return True
    if stored == 3:
        # v3 -> v4 only RENAMED the bilstm attention params
        # (Dense_0/Dense_1 -> att_w1/att_w2) — a pure rename, so restores
        # migrate in place (_restore's fallback path) instead of walling
        # off round-4 bilstm checkpoints (review finding, round 5).
        return True
    if stored in (1, 2):
        # v1 -> v2 changed only the BiLSTM encoder's param tree
        # (ops/lstm.py explicit w_ih/w_hh/bias); v2 -> v3 gave those params
        # a leading direction axis — real layout changes, no migration.
        # cnn/bert restore unchanged across these.
        return arch.encoder != "bilstm"
    return False


# --- v3 -> v4 attention-param rename migration -----------------------------
#
# The rename is detected STRUCTURALLY, not from the version file: the
# bilstm encoder's dict is the unique place where the attention params
# live next to w_ih, so "att_w1/att_w2 beside w_ih" <-> "Dense_0/Dense_1
# beside w_ih" converts in either direction without touching the other
# modules' Dense_0 entries (induction/relation). Adam moment trees mirror
# the param tree, so the same walk migrates them too.


def _rename_attn(tree, to_v3: bool):
    """Recursively rename the attention pair IN PLACE in the state tree,
    preserving every container type (TrainState dataclass, optax
    NamedTuple states, tuples/lists). Container preservation is the whole
    point: a flax to_state_dict round-trip turns the opt_state tuple into
    a dict, and orbax then refuses the restore with a dict-vs-list
    structure mismatch against a checkpoint saved from the real pytree
    (review-reproduced on a production-format v3 save, round 5).

    Returns (new_tree, changed)."""
    import dataclasses

    if isinstance(tree, dict):
        out = {}
        changed = False
        for k, v in tree.items():
            out[k], ch = _rename_attn(v, to_v3)
            changed |= ch
        if to_v3 and {"att_w1", "att_w2", "w_ih"} <= out.keys():
            out["Dense_0"] = {"kernel": out.pop("att_w1")}
            out["Dense_1"] = {"kernel": out.pop("att_w2")}
            changed = True
        elif not to_v3 and {"Dense_0", "Dense_1", "w_ih"} <= out.keys():
            out["att_w1"] = out.pop("Dense_0")["kernel"]
            out["att_w2"] = out.pop("Dense_1")["kernel"]
            changed = True
        return out, changed
    if isinstance(tree, tuple) and hasattr(tree, "_fields"):  # NamedTuple
        parts = [_rename_attn(v, to_v3) for v in tree]
        return type(tree)(*(p[0] for p in parts)), any(p[1] for p in parts)
    if isinstance(tree, (tuple, list)):
        parts = [_rename_attn(v, to_v3) for v in tree]
        return type(tree)(p[0] for p in parts), any(p[1] for p in parts)
    if dataclasses.is_dataclass(tree) and not isinstance(tree, type):
        parts = {
            f.name: _rename_attn(getattr(tree, f.name), to_v3)
            for f in dataclasses.fields(tree)
        }
        return (
            dataclasses.replace(tree, **{k: v[0] for k, v in parts.items()}),
            any(v[1] for v in parts.values()),
        )
    return tree, False


def _stage_root_for(real_dir: Path, mode: str) -> Path | None:
    """tmpfs staging root for ``real_dir``, or None when staging is off.

    Round-3 soak decomposition (BASELINE.md): with the async saver, the
    checkpoint DESTINATION still cost ~38% of sustained throughput on host
    disk vs tmpfs (the d2h fetch and the file writes contend on the host
    side). Staging keeps orbax writing at tmpfs speed; the saver thread
    then drains each completed save to the real directory — the durability contract
    (wait() implies durable in ``real_dir``) is unchanged.

    "auto" enables staging when /dev/shm exists, the process is the only
    JAX process (multi-host orbax needs a shared fs), and the real dir is
    not itself on tmpfs. The staging path is a pure function of the real
    path, so a resumed process finds (and reuses) its predecessor's
    staging.
    """
    if mode == "off":
        return None
    shm = Path("/dev/shm")
    if not shm.is_dir():
        return None
    real = str(real_dir)
    if real.startswith(str(shm)) or real.startswith("/tmp/ramdisk"):
        return None
    try:
        import jax

        if jax.process_count() > 1:
            return None
    except RuntimeError:
        # Backend init failed — cannot PROVE single-process, so stay off.
        # (Do not swallow broadly: a manager constructed before
        # jax.distributed.initialize on a pod would wrongly enable staging
        # and land multi-host orbax saves on non-shared local tmpfs; the
        # save-time re-check in _check_staging_safety backstops the case
        # where distributed init happens after construction.)
        return None
    import hashlib
    import os

    # Per-user tag + 0o700 creation (in __init__): the staging root must
    # be neither predictable-shared across users nor writable by others on
    # a multi-user host (advisor finding, round 4).
    uid = os.getuid()
    tag = hashlib.md5(f"{uid}:{real}".encode()).hexdigest()[:16]
    return shm / f"inftpu_ckpt_stage_u{uid}_{tag}"


def _claim_stage_root(path: Path) -> Path | None:
    """Create-or-validate the staging root; None when it cannot be owned.

    The path is computable by any local user (uid + real dir are not
    secret), so every way another user can pre-occupy it must degrade to
    staging OFF (slower checkpoints), never to a crash and never to
    writing checkpoint bytes somewhere attacker-chosen:

    * regular file / dangling symlink -> mkdir raises FileExistsError;
    * symlink to a victim-owned dir -> would pass a stat() uid check, so
      the check uses lstat and rejects any non-directory;
    * dir owned by someone else -> uid mismatch.

    Creation uses mode 0o700 — checkpoint bytes in world-shared /dev/shm
    must not be world-readable (advisor finding, round 4).
    """
    import os
    import stat as stat_mod
    import warnings

    try:
        path.mkdir(mode=0o700, parents=True, exist_ok=True)
        st = path.lstat()
    except OSError as e:  # FileExistsError (file/dangling-symlink), perms
        warnings.warn(
            f"staging root {path} unusable ({e}); disabling tmpfs "
            "checkpoint staging",
            stacklevel=3,
        )
        return None
    if not stat_mod.S_ISDIR(st.st_mode) or st.st_uid != os.getuid():
        warnings.warn(
            f"staging root {path} is a symlink/non-dir or owned by "
            "another user; disabling tmpfs checkpoint staging",
            stacklevel=3,
        )
        return None
    return path


# Live telemetry files the run APPENDS to while checkpointing runs. They
# must never enter the staging mirror: seeding (real -> staging) would
# snapshot them, and the next drain (staging -> real) would copy the stale
# snapshot back over the live file — observed on --resume as metrics.jsonl
# reverting to its pre-resume content (records written through the
# logger's persistent handle went to a replaced inode and were lost).
_NON_CHECKPOINT_FILES = frozenset({
    "metrics.jsonl", "flight_recorder.json", "metrics.prom",
})


def _sync_tree(src: Path, dst: Path, mirror_deletes: bool = True) -> None:
    """Copy files newer-or-missing from src -> dst. With
    ``mirror_deletes`` (the drain direction), NUMERIC step directories in
    dst absent from src are removed (mirrors orbax retention GC so the
    real dir does not accumulate every step ever saved); non-step files in
    dst that src lacks (config.json, metrics.jsonl, ...) are always left
    alone. Seeding (real -> staging) runs with mirror_deletes=False —
    staging may legitimately hold steps the real dir never received
    (crash between save and drain)."""
    import shutil

    dst.mkdir(parents=True, exist_ok=True)
    if mirror_deletes:
        src_names = {p.name for p in src.iterdir()}
        for p in dst.iterdir():
            if p.is_dir() and p.name.isdigit() and p.name not in src_names:
                shutil.rmtree(p, ignore_errors=True)
    for p in src.iterdir():
        if ".orbax-checkpoint-tmp" in p.name:
            continue  # in-progress orbax write: never drain partial steps
        if p.name in _NON_CHECKPOINT_FILES:
            continue  # live telemetry: not checkpoint state, never mirrored
        q = dst / p.name
        try:
            if p.is_dir():
                _sync_tree(p, q, mirror_deletes)
            else:
                s = p.stat()
                if (
                    not q.exists()
                    or q.stat().st_size != s.st_size
                    or q.stat().st_mtime < s.st_mtime
                ):
                    tmp = q.with_name(q.name + ".staging_tmp")
                    shutil.copy2(p, tmp)
                    tmp.replace(q)
        except FileNotFoundError:
            # Concurrent retention GC removed it mid-walk (belt-and-
            # suspenders: the drain is serialized with saves, but a
            # vanished source must never poison the run).
            continue


# --- integrity chain (ISSUE 12) -------------------------------------------


class CorruptCheckpointError(RuntimeError):
    """A slot failed integrity verification (digest mismatch, unreadable
    payload with an intact-manifest claim, or an injected restore fault).
    Carries the slot identity so the fallback walk can quarantine it."""

    def __init__(self, kind: str, step: int, reason: str):
        super().__init__(
            f"checkpoint slot {kind}/{step} corrupt: {reason}"
        )
        self.kind = kind
        self.step = step
        self.reason = reason


def _leaf_digest(leaf) -> str:
    """sha256 of one host leaf. 0-dim leaves hash by ``repr(item())`` —
    restore templates may legitimately re-type a scalar (np.int64 saved,
    python int template), and a dtype-sensitive digest would quarantine
    intact slots over a representation detail. Arrays hash dtype + shape
    + bytes: a bit-flip ANYWHERE in the payload changes the digest."""
    import hashlib

    import numpy as np

    a = np.asarray(leaf)
    h = hashlib.sha256()
    if a.ndim == 0:
        h.update(repr(a.item()).encode())
    else:
        h.update(a.dtype.str.encode())
        h.update(repr(a.shape).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def tree_manifest(tree) -> dict:
    """{leaves: {"00000": sha, ...}, manifest_sha} over the flat host
    tree — the per-leaf + manifest checksum chain every save writes and
    every restore verifies."""
    import hashlib

    import jax

    leaves = jax.tree_util.tree_leaves(tree)
    d = {_leafkey(i): _leaf_digest(l) for i, l in enumerate(leaves)}
    m = hashlib.sha256()
    for k in sorted(d):
        m.update(k.encode())
        m.update(d[k].encode())
    return {"leaves": d, "manifest_sha": m.hexdigest()}


# Manager-kind -> subdirectory under a root (best lives at the root).
_KIND_SUB = {
    "best": "", "ring": "latest",
    "ring_base": "ring_base", "ring_delta": "ring_delta",
}


def _integrity_name(step: int) -> str:
    return f"integrity_{int(step):08d}.json"


# --- delta-ring helpers ----------------------------------------------------
#
# Flat-leaf format: base/delta ring slots store ``{"leaves": {"00007":
# arr}}`` keyed by tree_flatten position instead of the state pytree.
# Restoring needs no target structure (orbax raw restore returns the dict
# as saved), and the caller's template supplies the treedef — so the
# format is independent of flax/optax container types, which a raw
# restore of a StandardSave(state) tree would lose.


def _leafkey(i: int) -> str:
    return f"{i:05d}"


def _ring_slots(tree) -> dict[str, int] | None:
    """Flat indices of the four lazy-embed leaves (word table + Adam row
    moments + per-row counts), or None when the tree carries no complete
    set (plain TrainState, BERT/feature-cache states)."""
    import jax

    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    slots: dict[str, int] = {}
    for i, (path, _) in enumerate(flat):
        ks = jax.tree_util.keystr(path)
        if ks.startswith(".params") and "'word_embedding'" in ks:
            slots["table"] = i
        elif ks == ".emb_m":
            slots["m"] = i
        elif ks == ".emb_v":
            slots["v"] = i
        elif ks == ".emb_last":
            slots["last"] = i
    return slots if set(slots) == {"table", "m", "v", "last"} else None


def _tree_bytes(tree) -> int:
    import jax
    import numpy as np

    return sum(
        int(np.asarray(x).nbytes) if not hasattr(x, "nbytes") else int(x.nbytes)
        for x in jax.tree_util.tree_leaves(tree)
    )


class CheckpointManager:
    def __init__(self, ckpt_dir: str | Path, cfg: ExperimentConfig,
                 max_to_keep: int = 3, stage: str | None = None,
                 logger=None):
        # Telemetry sink for integrity events (kind="fault" quarantine
        # records — the watchdog turns them into ckpt_corrupt criticals
        # through its logger hook). None = silent quarantine on the
        # stream side; the rename on disk still happens.
        self._logger = logger
        self.dir = Path(ckpt_dir).absolute()
        self.dir.mkdir(parents=True, exist_ok=True)
        if stage is None:
            stage = getattr(cfg, "ckpt_stage", "auto")
        self._stage_root = _stage_root_for(self.dir, stage)
        version_file = self.dir / "format_version"
        has_steps = any(
            p.name.isdigit() for p in self.dir.iterdir() if p.is_dir()
        )
        if version_file.exists() or has_steps:
            # A populated dir without a version file predates versioning: v1.
            stored = (
                int(version_file.read_text().strip() or 0)
                if version_file.exists() else 1
            )
            # Judge compatibility against the architecture of the weights
            # actually stored there (the dir's own config.json), not the
            # caller's runtime config.
            try:
                arch = self.load_config(self.dir)
            except FileNotFoundError:
                arch = cfg
            if not _format_compatible(stored, arch):
                raise ValueError(
                    f"checkpoint dir {self.dir} has param-tree format "
                    f"v{stored}, this build writes v{FORMAT_VERSION}; "
                    f"retrain or convert the checkpoint (param layouts "
                    f"changed incompatibly between these versions)"
                )
        else:
            version_file.write_text(str(FORMAT_VERSION))
        # Never clobber an existing config: restoring from a dir must not
        # rewrite the architecture record of the weights stored there.
        if not (self.dir / "config.json").exists():
            (self.dir / "config.json").write_text(cfg.to_json())
        # tmpfs staging (see _stage_root_for): orbax managers operate on the
        # staging root; each completed save is drained to self.dir on the
        # saver thread (inline, serialized with orbax writes). Seeding staging from the real dir (union merge — staging
        # wins, it is never behind) makes resumes/restores see every prior
        # save whichever side it durably lives on.
        root = self.dir
        if self._stage_root is not None:
            import shutil
            import uuid

            self._stage_root = _claim_stage_root(self._stage_root)
        if self._stage_root is not None:
            # Incarnation nonce: staging outlives a deleted-and-recreated
            # real dir (tmpfs vs disk lifetimes differ), and a stale
            # staging tree would shadow the fresh run — its old steps
            # would seed the dedupe ledger and silently swallow new saves
            # (caught live in round 4). The nonce ties a staging tree to
            # ONE real-dir incarnation: mismatch (or a fresh real dir)
            # discards staging; a crash-before-drain keeps both nonces
            # equal, so tmpfs durability across process crashes is kept.
            nonce_f = self.dir / ".staging_nonce"
            s_nonce_f = self._stage_root / ".staging_nonce"
            nonce = nonce_f.read_text() if nonce_f.exists() else None
            if nonce is None:
                nonce = uuid.uuid4().hex
                nonce_f.write_text(nonce)
            s_nonce = s_nonce_f.read_text() if s_nonce_f.exists() else None
            if s_nonce != nonce:
                # rmtree must actually SUCCEED: a partial failure silently
                # tolerated here would leave stale step dirs which the new
                # nonce then legitimizes — exactly the shadow-the-new-run
                # bug the nonce exists to stop. On any failure: staging off.
                try:
                    shutil.rmtree(self._stage_root)
                except OSError as e:
                    import warnings

                    warnings.warn(
                        f"could not clear stale staging {self._stage_root} "
                        f"({e}); disabling tmpfs checkpoint staging",
                        stacklevel=2,
                    )
                    self._stage_root = None
                else:
                    # Recreate through the same claim path as the first
                    # mkdir: keeps 0o700 and re-validates ownership — the
                    # rmtree -> mkdir window reopens the pre-create race.
                    self._stage_root = _claim_stage_root(self._stage_root)
                if self._stage_root is not None:
                    s_nonce_f.write_text(nonce)
        if self._stage_root is not None:
            if (
                any(
                    p.name.isdigit()
                    for p in self.dir.iterdir() if p.is_dir()
                )
                # Any secondary manager root counts as "populated": a
                # delta-mode dir may hold ONLY ring_base/ring_delta saves
                # (no best yet), and skipping the seed would make them
                # invisible to the staging-rooted managers on resume.
                or any(
                    (self.dir / d).exists()
                    for d in ("latest", "ring_base", "ring_delta")
                )
            ):
                _sync_tree(self.dir, self._stage_root, mirror_deletes=False)
            root = self._stage_root
        self.mngr = ocp.CheckpointManager(
            root,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                best_fn=lambda m: m["val_accuracy"],
                best_mode="max",
            ),
        )
        # Crash-recovery ring (SURVEY.md §5.3 failure detection / recovery):
        # the best-metric manager above only writes on improvement, so a
        # crash after a long plateau would lose everything since the last
        # best. A second single-slot manager under latest/ is written at
        # EVERY val boundary; --resume restores from whichever of the two
        # is newest.
        self.latest_mngr = ocp.CheckpointManager(
            root / "latest",
            options=ocp.CheckpointManagerOptions(max_to_keep=1),
        )
        # Delta ring (module docstring): base = full flat-leaf save whose
        # embedding leaves stay device-resident as the diff reference;
        # deltas = changed rows + non-embedding leaves. Both managers are
        # always constructed (cheap on empty dirs) so a delta-written dir
        # restores even under ckpt_delta="off".
        self._delta_on = getattr(cfg, "ckpt_delta", "auto") != "off"
        self.ring_base_mngr = ocp.CheckpointManager(
            root / "ring_base",
            options=ocp.CheckpointManagerOptions(max_to_keep=1),
        )
        self.ring_delta_mngr = ocp.CheckpointManager(
            root / "ring_delta",
            options=ocp.CheckpointManagerOptions(max_to_keep=1),
        )
        self._delta_base: dict | None = None

        # Async saver thread. Orbax's own async checkpointer still copies
        # device->host SYNCHRONOUSLY before returning, and on a tunneled
        # backend that d2h (hundreds of MB at the 400k-vocab config) IS the
        # boundary cost — so the whole save (d2h from a device-side
        # snapshot + orbax write) runs here, off the training loop.
        #
        # Bounded queue = backpressure: each enqueued item pins a full
        # on-device state snapshot, so an unbounded queue would grow HBM
        # without limit if boundaries outpace the saver; with maxsize=2 a
        # third save blocks (the old synchronous behavior) instead.
        #
        # Thread-safety: the orbax managers are NOT thread-safe, so after
        # construction they are touched ONLY on this thread or behind
        # wait() (restore_*/check_start_step); the save_latest dedupe reads
        # the python-side _enqueued record, never the managers.
        self._q: queue.Queue = queue.Queue(maxsize=2)
        self._save_error: Exception | None = None
        # Cursor sidecars of best saves are never pruned (see
        # _write_cursor); seed the ledger with the steps the best manager
        # already holds so a RESUMED process keeps protecting them too.
        # Safe here: __init__ runs before the saver thread touches the
        # managers (same rule as the _enqueued seeding below).
        self._protected_cursor_steps: set[int] = set(self.mngr.all_steps())
        self._enqueued = {
            "best": self.mngr.latest_step(),
            "ring": max(
                (
                    s for s in (
                        self.latest_mngr.latest_step(),
                        self.ring_base_mngr.latest_step(),
                        self.ring_delta_mngr.latest_step(),
                    ) if s is not None
                ),
                default=None,
            ),
        }
        self._worker = threading.Thread(target=self._drain, daemon=True)
        self._worker.start()
        # Durability on abnormal exits: the worker is a daemon (a wedged
        # device fetch must not block interpreter exit forever), so flush
        # enqueued saves at exit — covers exceptions and SIGINT, which the
        # old synchronous save() handled by construction.
        import atexit

        self._closed = False
        atexit.register(self._flush_at_exit)

    def _flush_at_exit(self) -> None:
        # Bounded, not wait(): an unbounded Queue.join() here could hang
        # interpreter exit on a wedged device fetch — the very case the
        # daemon-thread choice exists for (advisor finding, round 2).
        if self._closed:
            return
        deadline = 60.0
        try:
            import time

            t0 = time.monotonic()
            while (
                self._q.unfinished_tasks
                and time.monotonic() - t0 < deadline
            ):
                time.sleep(0.1)
            self.mngr.wait_until_finished()
            self.latest_mngr.wait_until_finished()
        except Exception:  # noqa: BLE001 — best-effort at interpreter exit
            pass

    def close(self) -> None:
        """Flush pending saves, stop the saver thread, and release the atexit
        handle. Idempotent. Without this, each instance pins a thread plus
        its queued HBM snapshots for process lifetime — test suites and
        repeated runs in one interpreter leak per instance (advisor finding,
        round 2)."""
        import atexit

        if self._closed:
            return
        self._closed = True
        try:
            self.wait()
        finally:
            self._q.put(None)
            self._worker.join(timeout=30.0)
            self.mngr.close()
            self.latest_mngr.close()
            self.ring_base_mngr.close()
            self.ring_delta_mngr.close()
            try:
                atexit.unregister(self._flush_at_exit)
            except Exception:  # noqa: BLE001 — unregister is best-effort
                pass

    def _drain(self) -> None:
        import jax

        while True:
            item = self._q.get()
            try:
                if item is None:
                    return
                kind, step, snap, metric = item
                # Fully-addressable leaves (single host) are fetched to
                # numpy here, keeping the d2h on this thread; leaves that
                # span hosts (e.g. --zero_opt moments dp-sharded over a
                # pod) go to orbax as jax.Arrays — it performs the
                # distributed write itself, and device_get on them would
                # raise.
                host = jax.tree.map(
                    lambda x: (
                        jax.device_get(x)
                        if not isinstance(x, jax.Array) or x.is_fully_addressable
                        else x
                    ),
                    snap,
                )
                mngr = {
                    "best": self.mngr,
                    "ring": self.latest_mngr,
                    "ring_base": self.ring_base_mngr,
                    "ring_delta": self.ring_delta_mngr,
                }[kind]
                # Integrity chain (module doc): per-leaf + manifest
                # digests of the EXACT host tree handed to orbax, written
                # as a sidecar the drain mirrors with its step.
                manifest = tree_manifest(host)
                if kind == "best":
                    mngr.save(
                        step,
                        args=ocp.args.StandardSave(host),
                        metrics={"val_accuracy": metric},
                    )
                else:
                    mngr.save(step, args=ocp.args.StandardSave(host))
                self._write_manifest(kind, step, manifest)
                self._prune_manifests(kind, mngr)
                self._chaos_corrupt(kind, step, mngr)
                if self._stage_root is not None:
                    # Drain staging -> real INLINE on this thread: the
                    # sync must see a quiescent staging tree, and a
                    # separate mover thread would race the NEXT save's
                    # orbax writes/retention GC (review finding, round
                    # 4). Serializing stretches per-save latency by the
                    # disk copy, which the adaptive ring-save skip
                    # already absorbs; saves still never block training.
                    mngr.wait_until_finished()
                    _sync_tree(self._stage_root, self.dir)
            except Exception as e:  # noqa: BLE001 — surfaced by wait()
                self._save_error = e
            finally:
                self._q.task_done()

    def save(self, step: int, state: Any, val_accuracy: float,
             cursor: dict | None = None) -> None:
        """ASYNC: snapshots the state on-device and returns; the d2h copy
        and the orbax write happen on the saver thread, off the training
        critical path. Durability points: restore_*() and wait() block
        first; the trainer calls wait() at run end.

        ``cursor``: the input-pipeline position (datapipe/cursor.py
        PipelineCursor.to_dict()) saved as a sidecar next to the step —
        resume then replays the exact episode stream."""
        self._check_save_error()
        self._check_staging_safety()
        self._enqueued["best"] = step
        self._write_cursor(step, cursor, protect=True)
        self._q.put(
            ("best", step, _device_snapshot(state), float(val_accuracy))
        )

    def save_latest(self, step: int, state: Any, force: bool = False,
                    cursor: dict | None = None) -> None:
        """Recovery save (single rotating slot), async like save(). Skipped
        when either side already holds (or was just enqueued with) this
        step — restore_latest consults both, so a best-save at the same
        boundary makes the ring write pure duplicate I/O. The dedupe reads
        only the python-side ledger (_enqueued, seeded from the managers at
        construction): the managers themselves belong to the saver thread.

        ADAPTIVE cadence: also skipped while a previous save is still in
        flight. Ring saves are pure recovery redundancy — when the d2h +
        write of one save takes longer than the boundary interval (this
        sandbox's tunnel: ~26 s for the 250 MB lazy-soak state vs ~6 s
        between boundaries), enqueueing every boundary fills the bounded
        queue and BLOCKS training on checkpoint I/O. Skipping keeps the
        newest completed ring slot restorable with staleness bounded by
        one save duration; on real hosts (PCIe d2h) the queue is always
        empty and every boundary saves. Best saves are never skipped, and
        callers that REQUIRE this exact step durable (the trainer's
        end-of-run save) pass ``force=True``.

        DELTA mode (module docstring): lazy-embed states enqueue base +
        touched-row deltas instead of the full tree. Returns an info dict
        ``{"mode": full|base|delta, "bytes": payload bytes, "rows":
        changed rows (delta only)}`` for telemetry, or None when the save
        was skipped/deduped."""
        self._check_save_error()
        self._check_staging_safety()
        if step in self._enqueued.values():
            return None
        if not force and self._q.unfinished_tasks > 0:
            return None
        kind, payload, info = self._ring_item(step, state)
        self._enqueued["ring"] = step
        self._write_cursor(step, cursor)
        self._q.put((kind, step, payload, None))
        return info

    # --- input-pipeline cursor sidecars (datapipe/cursor.py) --------------
    #
    # One small JSON per saved step, living at the managers' root (the
    # staging root when staging is on — the saver thread's drain then
    # copies it to the real dir together with its step). Sidecars are
    # written SYNCHRONOUSLY at enqueue time: they are a few hundred bytes,
    # and writing before the orbax save means a crash can leave an orphan
    # cursor (harmless) but never a restorable step without its cursor.

    _CURSOR_KEEP = 16  # newest RING sidecars retained (restores read one)

    def _cursor_name(self, step: int) -> str:
        return f"cursor_{step:08d}.json"

    def _write_cursor(self, step: int, cursor: dict | None,
                      protect: bool = False) -> None:
        """``protect`` marks the step's sidecar as belonging to a BEST
        save: those must survive pruning — on a long plateau the ring
        writes >_CURSOR_KEEP newer sidecars, and a divergence-guard purge
        followed by --resume restores exactly that old best step; losing
        its cursor would silently splice a seed-restarted stream (review
        finding, this round). The protected set is a python-side ledger
        (the orbax managers belong to the saver thread)."""
        if cursor is None:
            return
        import json

        if protect:
            self._protected_cursor_steps.add(int(step))
        root = self._stage_root or self.dir
        path = root / self._cursor_name(step)
        tmp = path.with_name(path.name + ".tmp")
        tmp.write_text(json.dumps(cursor, sort_keys=True))
        tmp.replace(path)  # atomic: a torn sidecar must never parse
        # Prune in BOTH roots: the drain mirrors new sidecars into the
        # real dir but (by design) never deletes non-step files there, so
        # a staging-only prune would leave the run dir accumulating one
        # sidecar per boundary forever (review finding, this round).
        for r in (self._stage_root, self.dir):
            if r is None:
                continue
            prunable = [
                p for p in sorted(r.glob("cursor_*.json"))
                if self._cursor_step_of(p) not in self._protected_cursor_steps
            ]
            for old in prunable[: -self._CURSOR_KEEP]:
                old.unlink(missing_ok=True)

    @staticmethod
    def _cursor_step_of(path: Path) -> int | None:
        try:
            return int(path.stem.split("_")[1])
        except (IndexError, ValueError):
            return None

    def load_cursor(self, step: int) -> dict | None:
        """The cursor sidecar for ``step``, or None (pre-datapipe dirs,
        pruned sidecars). Staging is checked first — it is never behind."""
        import json

        self.wait()  # a sidecar mid-drain counts once durable
        for root in (self._stage_root, self.dir):
            if root is None:
                continue
            path = root / self._cursor_name(step)
            if path.exists():
                return json.loads(path.read_text())
        return None

    # --- integrity chain (ISSUE 12) ---------------------------------------

    def _kind_dir(self, root: Path, kind: str) -> Path:
        sub = _KIND_SUB[kind]
        return root / sub if sub else root

    def _write_manifest(self, kind: str, step: int, manifest: dict) -> None:
        """Sidecar next to the step dir (at the managers' root — the
        drain mirrors it to the real dir with its step). Atomic: a torn
        manifest must never half-parse."""
        import json

        root = self._stage_root or self.dir
        d = self._kind_dir(root, kind)
        d.mkdir(parents=True, exist_ok=True)
        path = d / _integrity_name(step)
        tmp = path.with_name(path.name + ".tmp")
        tmp.write_text(json.dumps(
            {"step": int(step), "kind": kind, **manifest}, sort_keys=True
        ))
        tmp.replace(path)

    def _prune_manifests(self, kind: str, mngr) -> None:
        """Drop manifests whose step the manager no longer retains
        (orbax retention GC'd the dir) — saver-thread/quiescent only.
        Quarantined manifests (``*.json.quarantined``) don't match the
        glob and survive as evidence."""
        retained = {int(s) for s in mngr.all_steps()}
        for root in (self._stage_root, self.dir):
            if root is None:
                continue
            for p in self._kind_dir(root, kind).glob("integrity_*.json"):
                try:
                    s = int(p.stem.split("_")[1])
                except (IndexError, ValueError):
                    continue
                if s not in retained:
                    p.unlink(missing_ok=True)

    def _load_manifest(self, kind: str, step: int) -> dict | None:
        import json

        for root in (self._stage_root, self.dir):
            if root is None:
                continue
            p = self._kind_dir(root, kind) / _integrity_name(step)
            if p.exists():
                try:
                    return json.loads(p.read_text())
                except (json.JSONDecodeError, OSError):
                    return {"leaves": None}   # torn manifest: see _verify
        return None

    def _verify_tree(self, kind: str, step: int, tree: Any) -> None:
        """Compare the restored tree's per-leaf digests to the manifest;
        no manifest (pre-integrity dir) verifies nothing. Raises
        CorruptCheckpointError on any mismatch."""
        man = self._load_manifest(kind, step)
        if man is None:
            return
        stored = man.get("leaves")
        if not isinstance(stored, dict):
            raise CorruptCheckpointError(kind, step, "unreadable manifest")
        import jax

        leaves = jax.tree_util.tree_leaves(tree)
        if len(stored) != len(leaves):
            raise CorruptCheckpointError(
                kind, step,
                f"manifest records {len(stored)} leaves, restore "
                f"produced {len(leaves)}",
            )
        for i, leaf in enumerate(leaves):
            if stored.get(_leafkey(i)) != _leaf_digest(leaf):
                raise CorruptCheckpointError(
                    kind, step, f"leaf {_leafkey(i)} digest mismatch"
                )

    def _chaos_corrupt(self, kind: str, step: int, mngr) -> None:
        """ckpt.bitflip / ckpt.truncate fault points (obs/chaos.py):
        corrupt the just-written ring-family slot. Off = one module
        global check; firing waits for the write to be durable first."""
        from induction_network_on_fewrel_tpu.obs.chaos import (
            chaos_active,
            chaos_fire,
            corrupt_step_dir,
        )

        if not chaos_active() or kind == "best":
            return
        for point, mode in (
            ("ckpt.bitflip", "bitflip"), ("ckpt.truncate", "truncate"),
        ):
            if chaos_fire(point, kind=kind, step=int(step)) is not None:
                mngr.wait_until_finished()
                root = self._stage_root or self.dir
                corrupt_step_dir(
                    self._kind_dir(root, kind) / str(int(step)), mode
                )

    def _quarantine(self, kind: str, step: int, reason: str) -> None:
        """Rename the corrupt slot aside (never delete): step dir +
        manifest in staging AND the real dir get a ``.quarantined``
        suffix, orbax managers reload so the step disappears from their
        view (later saves at that number are accepted again), and —
        when no other manager still holds the step — the cursor sidecar
        follows, so a resumed stream can never pair the fallback state
        with the corrupt slot's position. Emits one kind="fault"
        record; the watchdog latches CRITICAL ``ckpt_corrupt``."""
        renamed = 0
        for root in (self._stage_root, self.dir):
            if root is None:
                continue
            d = self._kind_dir(root, kind)
            for name in (str(int(step)), _integrity_name(step)):
                p = d / name
                if not p.exists():
                    continue
                q = p.with_name(name + ".quarantined")
                n = 1
                while q.exists():
                    q = p.with_name(f"{name}.quarantined{n}")
                    n += 1
                p.rename(q)
                renamed += 1
        for m in (self.mngr, self.latest_mngr,
                  self.ring_base_mngr, self.ring_delta_mngr):
            try:
                m.reload()
            except Exception:  # noqa: BLE001 — reload is best-effort
                pass
        if self._delta_base is not None and kind == "ring_base" \
                and self._delta_base["step"] == int(step):
            self._delta_base = None   # the diff reference died with it
        still_held = any(
            int(step) in {int(s) for s in m.all_steps()}
            for m in (self.mngr, self.latest_mngr,
                      self.ring_base_mngr, self.ring_delta_mngr)
        )
        if not still_held:
            for root in (self._stage_root, self.dir):
                if root is None:
                    continue
                c = root / self._cursor_name(step)
                if c.exists():
                    q = c.with_name(c.name + ".quarantined")
                    if not q.exists():
                        c.rename(q)
        if self._logger is not None:
            self._logger.log(
                int(step), kind="fault", action="ckpt_quarantine",
                ckpt_kind=kind, ckpt_step=float(step), reason=reason,
                renamed=float(renamed),
            )

    def _restore_verified(self, mngr, kind: str, step: int, target: Any):
        """Restore + integrity verification. A restore that RAISES on a
        manifest-bearing slot re-verifies the raw stored data first:
        intact data means the failure is structural (wrong target
        architecture) and the original error re-raises; anything else is
        corruption. The ``ckpt.restore_raise`` chaos point models a
        flaky read and is contained exactly like corruption."""
        from induction_network_on_fewrel_tpu.obs.chaos import chaos_fire

        if chaos_fire("ckpt.restore_raise", kind=kind, step=int(step)):
            raise CorruptCheckpointError(
                kind, step, "injected restore fault (chaos)"
            )
        try:
            out = self._restore(mngr, step, target)
        except Exception as e:
            self._reverify_or_corrupt(mngr, kind, step, e)
        self._verify_tree(kind, step, out)
        return out

    def _reverify_or_corrupt(self, mngr, kind: str, step: int, exc) -> None:
        """Classify a restore exception. Pre-integrity slots (no
        manifest) re-raise — old behavior. With a manifest, the raw
        stored data re-verifies: intact data means the failure is
        STRUCTURAL (wrong target architecture — the original error
        re-raises, nothing is quarantined); a digest mismatch or an
        unreadable payload raises CorruptCheckpointError. Always
        raises."""
        if self._load_manifest(kind, step) is None:
            raise exc
        try:
            raw = mngr.restore(step, args=ocp.args.StandardRestore())
            self._verify_tree(kind, step, raw)
        except CorruptCheckpointError as ce:
            raise CorruptCheckpointError(
                kind, step, f"{ce.reason} (restore also failed: {exc})"
            ) from exc
        except Exception as re_err:
            raise CorruptCheckpointError(
                kind, step, f"unreadable payload: {re_err}"
            ) from exc
        raise exc   # data verified intact -> structural mismatch

    def _ring_item(self, step: int, state: Any) -> tuple[str, Any, dict]:
        """Build the ring-save queue item: ("ring", full snapshot) for
        non-lazy states or delta-off; ("ring_base"/"ring_delta", flat
        payload) in delta mode. The delta diff runs ON DEVICE (one
        elementwise compare over the four embedding leaves + nonzero);
        the nonzero forces a device sync, which the val boundary this is
        called from has already paid for eval."""
        import jax
        import numpy as np

        slots = _ring_slots(state) if self._delta_on else None
        if slots is None:
            snap = _device_snapshot(state)
            return "ring", snap, {"mode": "full", "bytes": _tree_bytes(snap)}
        import jax.numpy as jnp

        leaves = jax.tree_util.tree_leaves(state)
        table, m, v, last = (
            leaves[slots[k]] for k in ("table", "m", "v", "last")
        )
        base = self._delta_base
        if base is not None and np.shape(base["table"]) != np.shape(table):
            base = None  # different vocab restored into this manager
        idx = None
        if base is not None:
            changed = (
                jnp.any(jnp.asarray(table) != base["table"], axis=-1)
                | jnp.any(jnp.asarray(m) != base["m"], axis=-1)
                | jnp.any(jnp.asarray(v) != base["v"], axis=-1)
                | (jnp.asarray(last) != base["last"])
            )
            idx = jnp.nonzero(changed)[0].astype(jnp.int32)
            if 2 * int(idx.shape[0]) > int(np.shape(table)[0]):
                base = idx = None  # delta past half the table: rebase
            elif int(idx.shape[0]) == 0:
                # Zero changed rows (e.g. a boundary with no embedding
                # movement): orbax cannot save 0-length arrays ("params
                # missing in checkpoint"), and a poisoned saver error
                # would kill every later save. Pad to one row — row 0
                # re-scatters its own base value on restore, a no-op.
                idx = jnp.zeros((1,), jnp.int32)
        if base is None:
            # Fresh base: ONE on-device snapshot serves both the full save
            # and the resident diff reference (the saver thread's d2h
            # reads the same copies the next delta compares against).
            snap_leaves = _device_snapshot(list(leaves))
            nonce = np.int64(__import__("uuid").uuid4().int & ((1 << 63) - 1))
            payload = {
                "__ring_format__": np.int32(1),
                "step": np.int64(step),
                "nonce": nonce,
                "leaves": {
                    _leafkey(i): l for i, l in enumerate(snap_leaves)
                },
            }
            self._delta_base = {
                "step": int(step),
                "nonce": int(nonce),
                "table": snap_leaves[slots["table"]],
                "m": snap_leaves[slots["m"]],
                "v": snap_leaves[slots["v"]],
                "last": snap_leaves[slots["last"]],
            }
            return "ring_base", payload, {
                "mode": "base", "bytes": _tree_bytes(payload),
            }
        slot_set = set(slots.values())
        rest = _device_snapshot({
            _leafkey(i): l for i, l in enumerate(leaves) if i not in slot_set
        })
        payload = {
            "__ring_format__": np.int32(2),
            "step": np.int64(step),
            "base_step": np.int64(base["step"]),
            "base_nonce": np.int64(base["nonce"]),
            "idx": idx,
            "rows": {
                # Gathers produce fresh buffers — already donation-safe.
                "table": jnp.asarray(table)[idx],
                "m": jnp.asarray(m)[idx],
                "v": jnp.asarray(v)[idx],
                "last": jnp.asarray(last)[idx],
            },
            "leaves": rest,
        }
        return "ring_delta", payload, {
            "mode": "delta",
            "bytes": _tree_bytes(payload),
            "rows": int(idx.shape[0]),
        }

    def wait(self) -> None:
        """Block until every enqueued async save is durable on disk — in
        staging mode that means drained to the REAL directory, not just
        written to tmpfs."""
        self._q.join()
        self.mngr.wait_until_finished()
        self.latest_mngr.wait_until_finished()
        self.ring_base_mngr.wait_until_finished()
        self.ring_delta_mngr.wait_until_finished()
        self._check_save_error()
        # Quiescent point (saver idle, managers readable from this
        # thread — same rule as restore_*): re-derive cursor protection
        # from the best steps orbax actually RETAINS, so sidecars of
        # rotated-out best saves become prunable instead of accumulating
        # one per improvement for run lifetime (review finding). The
        # just-enqueued best stays protected via the ledger either way.
        retained = set(self.mngr.all_steps())
        if self._enqueued["best"] is not None:
            retained.add(int(self._enqueued["best"]))
        self._protected_cursor_steps = retained

    def _check_save_error(self) -> None:
        if self._save_error is not None:
            err, self._save_error = self._save_error, None
            raise RuntimeError("async checkpoint save failed") from err

    def _check_staging_safety(self) -> None:
        """Staging decided single-process at construction; if the process
        joined a multi-host cluster since (distributed init AFTER the
        manager was built), tmpfs staging would land multi-host orbax
        saves on non-shared local tmpfs — fail loudly at the first save
        instead of corrupting the checkpoint (advisor finding, round 4)."""
        if self._stage_root is None:
            return
        import jax

        if jax.process_count() > 1:
            raise RuntimeError(
                f"tmpfs checkpoint staging is active but jax.process_count()"
                f"=={jax.process_count()}: this CheckpointManager was "
                "constructed before jax.distributed.initialize. Construct "
                "it after distributed init (staging auto-disables), or "
                "pass stage='off'."
            )

    def check_start_step(self, start_step: int) -> None:
        """Guard a run numbering steps from ``start_step`` against a dir
        whose checkpoints are already ahead: orbax managers silently refuse
        saves at steps <= their latest (verified: ``save`` returns False),
        so every checkpoint of the new run would be dropped. Fail loudly at
        run start instead (advisor finding, round 1)."""
        self.wait()  # in-flight async saves count as existing
        existing = max(
            (
                s
                for m in (self.mngr, self.latest_mngr,
                          self.ring_base_mngr, self.ring_delta_mngr)
                for s in m.all_steps()
            ),
            default=None,
        )
        if existing is not None and start_step < existing:
            raise ValueError(
                f"checkpoint dir {self.dir} already holds step {existing}, "
                f"but this run numbers steps from {start_step}; orbax would "
                f"silently drop every new save. Pass --resume to continue "
                f"the existing run, or point --save_ckpt at a fresh directory"
            )

    def _restore(self, mngr, step: int, target: Any) -> Any:
        """Restore ``step`` into ``target``; on a structure mismatch, retry
        through the v3 attention-param rename (a v4 build reading a
        round-4 bilstm checkpoint — pure rename, bit-identical weights).
        Probing the actual stored structure per step (instead of trusting
        the dir-level version file) keeps mixed dirs working: a resumed
        v3 dir accumulates v4-named saves at later steps."""
        try:
            return mngr.restore(step, args=ocp.args.StandardRestore(target))
        except Exception as primary:
            target_v3, changed = _rename_attn(target, to_v3=True)
            if not changed:  # no attention pair in this tree: not ours
                raise
            try:
                out = mngr.restore(
                    step, args=ocp.args.StandardRestore(target_v3)
                )
            except Exception as secondary:
                # Chain BOTH: if the fallback also fails (e.g. genuine
                # corruption, not a rename mismatch), the original error
                # must stay visible, not be replaced by a phantom
                # migration problem.
                raise secondary from primary
            out_v4, _ = _rename_attn(out, to_v3=False)
            return out_v4

    def restore_best(self, target: Any) -> tuple[Any, int]:
        self.wait()  # a step mid-write is not restorable yet
        while True:
            step = self.mngr.best_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoint in {self.dir}")
            try:
                return (
                    self._restore_verified(self.mngr, "best", step, target),
                    step,
                )
            except CorruptCheckpointError as e:
                # Quarantine + fall back to the next-best retained step.
                self._quarantine(e.kind, e.step, e.reason)

    def restore_latest(self, target: Any) -> tuple[Any, int]:
        """Newest INTACT state across the best-tracked steps AND the
        recovery ring (full slots, delta bases, and delta slots alike).

        Step number IS save order here: check_start_step (enforced at every
        training start) refuses runs whose numbering would collide with a
        dir's existing checkpoints, so within any dir this build writes,
        higher step == later save. The ring wins ties (it is written at
        every val boundary; the best manager only on improvement).

        Integrity (ISSUE 12): each candidate verifies against its
        manifest; a corrupt slot is quarantined (renamed aside, fault
        record + CRITICAL ``ckpt_corrupt``) and the walk continues to the
        next-newest slot — a delta whose base died quarantines as
        orphaned and the walk re-resolves past it — so kill/corrupt/
        resume recovers the best surviving state instead of crashing."""
        self.wait()  # a step mid-write is not restorable yet
        while True:
            best_side = self.mngr.latest_step()
            ring_full = self.latest_mngr.latest_step()
            ring_flat = max(
                (
                    s for s in (
                        self.ring_base_mngr.latest_step(),
                        self.ring_delta_mngr.latest_step(),
                    ) if s is not None
                ),
                default=None,
            )
            ring_side = max(
                (s for s in (ring_full, ring_flat) if s is not None),
                default=None,
            )
            if best_side is None and ring_side is None:
                raise FileNotFoundError(f"no checkpoint in {self.dir}")
            try:
                if ring_side is not None and (
                    best_side is None or ring_side >= best_side
                ):
                    if ring_full is not None and ring_full >= ring_side:
                        return (
                            self._restore_verified(
                                self.latest_mngr, "ring", ring_full, target
                            ),
                            ring_full,
                        )
                    return (
                        self._restore_ring_flat(ring_side, target),
                        ring_side,
                    )
                return (
                    self._restore_verified(
                        self.mngr, "best", best_side, target
                    ),
                    best_side,
                )
            except CorruptCheckpointError as e:
                self._quarantine(e.kind, e.step, e.reason)

    def _restore_ring_flat(self, step: int, target: Any) -> Any:
        """Reassemble a delta-ring state: base leaves + (when ``step`` is a
        delta slot) the delta's non-embedding leaves and changed embedding
        rows scattered over the base's. Also re-arms the device-resident
        diff base so this manager's NEXT ring save deltas against the same
        base the directory already holds.

        Integrity (ISSUE 12): base AND delta payloads verify against
        their manifests before assembly. A corrupt base raises with the
        BASE's slot identity (the fallback walk quarantines it; the
        surviving delta is then orphaned and quarantines on the next
        pass); a delta referencing a stale/absent base is corruption-
        class too when it carries a manifest — a pre-integrity dir keeps
        the old loud errors."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from induction_network_on_fewrel_tpu.obs.chaos import chaos_fire

        base_step = self.ring_base_mngr.latest_step()
        if base_step is None:
            if self._load_manifest("ring_delta", step) is not None:
                raise CorruptCheckpointError(
                    "ring_delta", step,
                    "orphaned delta: its base save is missing/quarantined",
                )
            raise FileNotFoundError(
                f"delta ring in {self.dir} has no base save"
            )
        if chaos_fire("ckpt.restore_raise", kind="ring_base",
                      step=int(base_step)):
            raise CorruptCheckpointError(
                "ring_base", base_step, "injected restore fault (chaos)"
            )
        leaves_t, treedef = jax.tree_util.tree_flatten(target)
        n = len(leaves_t)
        # The base's leaves are exactly the target's (flat order), so the
        # caller's template types every restored array; only the delta
        # slots (dynamic row counts) restore untyped below. Numpy SCALAR
        # leaves (np.int32 step from a device_get'd state) must become
        # python scalars — orbax's template validator takes arrays and
        # python int/float, not np.generic.
        base_tpl = {
            "__ring_format__": 0,
            "step": 0,
            "nonce": 0,
            "leaves": {
                _leafkey(i): (l.item() if isinstance(l, np.generic) else l)
                for i, l in enumerate(leaves_t)
            },
        }
        try:
            raw_base = self.ring_base_mngr.restore(
                base_step, args=ocp.args.StandardRestore(base_tpl)
            )
        except Exception as e:
            self._reverify_or_corrupt(
                self.ring_base_mngr, "ring_base", base_step, e
            )
        self._verify_tree("ring_base", base_step, raw_base)
        if len(raw_base["leaves"]) != n:
            raise ValueError(
                f"delta-ring base in {self.dir} holds "
                f"{len(raw_base['leaves'])} leaves, target expects {n} — "
                "architecture mismatch"
            )
        leaves = [raw_base["leaves"][_leafkey(i)] for i in range(n)]
        slots = _ring_slots(target)
        if step != base_step:
            if slots is None:
                raise ValueError(
                    "delta ring slot exists but the restore target has no "
                    "lazy-embed leaves (embed_optimizer mismatch?)"
                )
            if chaos_fire("ckpt.restore_raise", kind="ring_delta",
                          step=int(step)):
                raise CorruptCheckpointError(
                    "ring_delta", step, "injected restore fault (chaos)"
                )
            try:
                raw_d = self.ring_delta_mngr.restore(
                    step, args=ocp.args.StandardRestore()
                )
            except Exception as e:
                self._reverify_or_corrupt(
                    self.ring_delta_mngr, "ring_delta", step, e
                )
            self._verify_tree("ring_delta", step, raw_d)
            if (
                int(raw_d["base_step"]) != int(base_step)
                or int(raw_d["base_nonce"]) != int(raw_base["nonce"])
            ):
                msg = (
                    f"delta ring slot {step} references base "
                    f"{int(raw_d['base_step'])}/"
                    f"{int(raw_d['base_nonce'])}, but {self.dir} holds "
                    f"{base_step}/{int(raw_base['nonce'])} — stale delta"
                )
                if self._load_manifest("ring_delta", step) is not None:
                    # Its true base was quarantined/replaced: the delta
                    # cannot resolve — corruption-class, walk past it.
                    raise CorruptCheckpointError("ring_delta", step, msg)
                raise ValueError(msg)
            slot_set = set(slots.values())
            for i in range(n):
                if i not in slot_set:
                    leaves[i] = raw_d["leaves"][_leafkey(i)]
            idx = np.asarray(raw_d["idx"])
            for name in ("table", "m", "v", "last"):
                arr = np.array(leaves[slots[name]])  # writable copy
                if idx.size:
                    arr[idx] = np.asarray(raw_d["rows"][name])
                leaves[slots[name]] = arr
        if self._delta_on and slots is not None:
            bl = raw_base["leaves"]
            self._delta_base = {
                "step": int(raw_base["step"]),
                "nonce": int(raw_base["nonce"]),
                "table": jnp.asarray(bl[_leafkey(slots["table"])]),
                "m": jnp.asarray(bl[_leafkey(slots["m"])]),
                "v": jnp.asarray(bl[_leafkey(slots["v"])]),
                "last": jnp.asarray(bl[_leafkey(slots["last"])]),
            }
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def purge_ring_newer_than(self, best_step: int) -> None:
        """Delete every ring slot (full, base, delta) newer than
        ``best_step`` — the divergence guard's restore path: orbax refuses
        re-saves at <= its latest step, so slots holding the post-collapse
        state would otherwise win every later --resume. Purging the base
        also drops the device diff reference, so the next ring save
        rebuilds a fresh base."""
        for kind, m in (("ring", self.latest_mngr),
                        ("ring_delta", self.ring_delta_mngr),
                        ("ring_base", self.ring_base_mngr)):
            for s in m.all_steps():
                if s > best_step:
                    m.delete(s)
            # Integrity sidecars of purged steps go with them (manifests
            # for steps the manager no longer retains).
            self._prune_manifests(kind, m)
        # Cursor sidecars newer than the restored best describe a stream
        # position the purged slots held — a later --resume must not
        # splice the post-collapse stream onto the restored state.
        for root in (self._stage_root, self.dir):
            if root is None:
                continue
            for p in root.glob("cursor_*.json"):
                s = self._cursor_step_of(p)
                if s is not None and s > best_step:
                    p.unlink(missing_ok=True)
        if self._delta_base is not None and self._delta_base["step"] > best_step:
            self._delta_base = None

    @staticmethod
    def load_config(ckpt_dir: str | Path) -> ExperimentConfig:
        path = Path(ckpt_dir) / "config.json"
        if not path.exists():
            raise FileNotFoundError(f"no config.json in {ckpt_dir}")
        return ExperimentConfig.from_json(path.read_text())
