"""Exact-parity lazy Adam for the word-embedding table.

The reference-shaped headline config (BASELINE.md round 2) is dominated by
dense Adam over the 400k-row GloVe table: the optimizer reads/writes the
table plus two moment arrays every step for gradients that touch <2% of
rows. ``--embed_optimizer sgd/frozen`` trade that cost away but change the
training dynamics. This module removes most of it while computing the SAME
update trajectory as dense Adam on the table (verified at 1e-6 over many
steps, untouched rows included — tests/test_lazy_embed.py).

The mathematical basis (why laziness can be exact here):

* Weight decay is EXCLUDED from the table in lazy mode (standard practice
  for embedding tables; the coupled-L2 term would couple every row's
  update to its own weight every step and make lazy evaluation impossible).
  The dense twin is therefore Adam with wd applied to everything EXCEPT the
  word table — that twin is what the equivalence test compares against.
* With wd off the table, a row's raw gradient is zero on steps that don't
  sample it, so its Adam state evolves in closed form: ``m <- b1*m``,
  ``v <- b2*v``, and the weight drifts by the bias-corrected momentum tail
  ``-lr_u * m_u-hat / (sqrt(v_u-hat) + eps)`` — a per-row recursion with NO
  dependence on the gradient history of other steps.
* Never-touched rows have m = v = 0 exactly, so their update is exactly 0:
  99.5% of the 400k table never moves and costs nothing.
* The momentum tail decays geometrically (b1^k); beyond ``CATCHUP_CAP``
  skipped steps the remaining drift is < 1e-33 (below f32 resolution, and
  TPUs flush subnormals to zero), so catch-up loops are capped there —
  numerically identical to the dense trajectory.

Per training step the body therefore:

1. DEDUPLICATES the batch's token ids on device (sort + first-occurrence
   compaction into a static ``[U]`` vector, pad = vocab_size so pad lanes
   gather-clamp harmlessly and scatter-DROP exactly) — measured on the
   reference-shaped config (v5e, 2026-07-31): per-occurrence [128k]-wide
   gathers/scatters ran at 1,862 eps/s vs 3,480 with compact ids;
2. catches the unique rows up through the previous step with a
   ``while_loop`` whose trip count is the largest gap among rows that have
   nonzero Adam state — at steady state 0-2 iterations;
3. runs forward/backward ON THE COMPACT LEAF: the caught-up ``[U, D]``
   rows are swapped in as the word-embedding param and token ids are
   remapped into them with ``searchsorted``, so autodiff produces a
   ``[U, D]`` cotangent — the dense ``[V, D]`` gradient (XLA's
   gather-grad scatter into a zeroed table) and the dense global-norm
   pass over it NEVER materialize. The compact row gradients are exactly
   the dense rows' sums, so the global clip norm is unchanged;
4. applies the real Adam update to the unique rows and scatters back
   rows + moments. The table and moment arrays are never read or written
   densely.

Two bodies ship. The LIVE-path body (make_lazy_update_body) dedups per
step with ``U = min(tokens per batch, vocab)`` — always sound, no
configuration. The TOKEN-CACHE body (make_lazy_cached_update_body) skips
per-step dedup entirely: the cache's corpus is static, so the distinct
word ids and every token's position in them are precomputed once at
cache build (augment_token_table) and the step trains the
corpus-restricted sub-table directly — measured 4,497 eps/s/chip vs
2,580 for per-step dedup and 3,532 for dense shared on the
reference-shaped config (BASELINE.md round 3).

Materialization (``make_materialize``): catch EVERY row up to the current
step — called at val/checkpoint boundaries so eval and saved checkpoints
see the exact dense-equivalent table. Between boundaries the table is
intentionally stale for rows not in recent batches.

Design constraints honored: fixed shapes (the per-occurrence [T] id vector
is static per config), no data-dependent Python control flow (the dynamic
gap bound is a ``lax.while_loop``), and the whole step remains one donated
jitted program (the fused ``lax.scan`` variants thread the extra state
through the carry untouched).

Supported: optimizer=adam, single-device and the token-cache paths (the
headline). Mesh/adv/feature-cache runs are refused at CLI validation —
their sharded/adversarial step factories keep the dense reference path.
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from induction_network_on_fewrel_tpu.config import ExperimentConfig

# Momentum-tail catch-up cap: b1^1024 ~ 1e-47 — the residual drift beyond
# this many skipped steps is far below f32 resolution (see module doc).
CATCHUP_CAP = 1024

# optax.adam defaults, replicated (make_optimizer uses optax.adam(schedule)
# with defaults for the dense path; these must match it exactly).
ADAM_B1 = 0.9
ADAM_B2 = 0.999
ADAM_EPS = 1e-8


class LazyHyper(NamedTuple):
    schedule: Any  # optax schedule: count -> lr (vectorizes over counts)
    clip: float


def make_hyper(cfg: ExperimentConfig) -> LazyHyper:
    """The schedule is the SAME optax object the dense optimizer would use
    (train/steps.make_optimizer), so staircase boundaries and float
    rounding match the dense twin bit-for-bit."""
    schedule = optax.exponential_decay(
        init_value=cfg.lr,
        transition_steps=cfg.lr_step_size,
        decay_rate=cfg.lr_gamma,
        staircase=True,
    )
    return LazyHyper(schedule=schedule, clip=cfg.grad_clip)


def find_emb_path(params) -> tuple:
    """Static path of the unique 'word_embedding' leaf in a params tree."""
    hits = [
        tuple(getattr(k, "key", k) for k in path)
        for path, _ in jax.tree_util.tree_flatten_with_path(params)[0]
        if any(getattr(k, "key", None) == "word_embedding" for k in path)
    ]
    if len(hits) != 1:
        raise ValueError(
            f"embed_optimizer=lazy needs exactly one 'word_embedding' param "
            f"(found {len(hits)}); BERT and feature-cache states have none"
        )
    return hits[0]


def tree_get(tree, path: tuple):
    for k in path:
        tree = tree[k]
    return tree


def tree_set(tree, path: tuple, value):
    """Functional nested-dict update (params trees are plain dicts)."""
    if not path:
        return value
    new = dict(tree)
    new[path[0]] = tree_set(tree[path[0]], path[1:], value)
    return new


def decay_catchup(W, m, v, last, t, hp: LazyHyper):
    """Apply the pure-decay Adam updates for steps ``last+1 .. t`` to rows
    whose state is current through ``last``.

    W, m, v: [N, D]; last: [N] int32 (per-row update count already
    applied); t: scalar int32 target count. Returns caught-up (W, m, v).
    The while_loop trip count is the largest capped gap present — 0 when
    every row is current (the steady-state fast path).
    """
    k = jnp.maximum(t - last, 0)
    # Rows with zero Adam state have exactly-zero decay updates (the fact
    # laziness exploits); skipping them is exact AND keeps never-touched /
    # pad rows from inflating the loop bound.
    alive = jnp.any(m != 0, axis=-1) | jnp.any(v != 0, axis=-1)
    kc = jnp.where(alive, jnp.minimum(k, CATCHUP_CAP), 0)
    jmax = jnp.max(kc)

    def cond(carry):
        return carry[0] <= jmax

    def body(carry):
        j, W, m, v = carry
        u = last + j  # 1-based update number this iteration applies
        active = (j <= kc)[:, None]
        m2 = ADAM_B1 * m
        v2 = ADAM_B2 * v
        uf = u.astype(jnp.float32)
        bc1 = 1.0 - ADAM_B1**uf
        bc2 = 1.0 - ADAM_B2**uf
        lr = hp.schedule(u - 1)  # optax counts are 0-based pre-update
        upd = (
            lr[:, None]
            * (m2 / bc1[:, None])
            / (jnp.sqrt(v2 / bc2[:, None]) + ADAM_EPS)
        )
        return (
            j + 1,
            jnp.where(active, W - upd, W),
            jnp.where(active, m2, m),
            jnp.where(active, v2, v),
        )

    _, W, m, v = jax.lax.while_loop(
        cond, body, (jnp.int32(1), W, m, v)
    )
    # Residual decay for gaps beyond the cap: the weight drift there is
    # below f32 resolution (module doc), but the moments keep decaying.
    resid = jnp.maximum(k - kc, 0).astype(jnp.float32)[:, None]
    return W, m * ADAM_B1**resid, v * ADAM_B2**resid


def touched_update(W, m, v, g, t, hp: LazyHyper):
    """The real Adam update (update number t+1) for rows with gradient g.
    Formula replicated from optax.scale_by_adam with defaults (eps_root=0);
    g must already carry the global-norm clip scale."""
    u = (t + 1).astype(jnp.float32)
    m2 = ADAM_B1 * m + (1.0 - ADAM_B1) * g
    v2 = ADAM_B2 * v + (1.0 - ADAM_B2) * g * g
    bc1 = 1.0 - ADAM_B1**u
    bc2 = 1.0 - ADAM_B2**u
    lr = hp.schedule(t)
    W2 = W - lr * (m2 / bc1) / (jnp.sqrt(v2 / bc2) + ADAM_EPS)
    return W2, m2, v2


def clip_grads_like_optax(grads, clip: float):
    """Bit-identical replication of optax.clip_by_global_norm (select on
    norm < max, else scale by max/norm) over the FULL grad tree — the dense
    emb cotangent included, so --grad_clip means exactly what shared-mode
    means."""
    g_norm = optax.global_norm(grads)
    trigger = g_norm < clip

    def clip_fn(g):
        return jax.lax.select(trigger, g, (g / g_norm.astype(g.dtype)) * clip)

    return jax.tree.map(clip_fn, grads)


def make_lazy_update_body(model, cfg: ExperimentConfig):
    """Lazy-embed twin of steps.make_update_body — same calling convention
    ``(state, (support, query, label)) -> (state, metrics)`` so every step
    factory (per-step, fused scan, token-cached) wraps it unchanged."""
    from induction_network_on_fewrel_tpu.train.steps import loss_and_metrics

    if cfg.optimizer != "adam":
        raise ValueError(
            "embed_optimizer=lazy replicates dense Adam's momentum tail; "
            f"it requires --optimizer adam (got {cfg.optimizer!r})"
        )
    hp = make_hyper(cfg)
    aux_w = cfg.moe_aux_weight if cfg.moe_experts > 0 else 0.0

    def body(state, batch):
        support, query, label = batch
        if not isinstance(support, dict):
            raise ValueError(
                "embed_optimizer=lazy needs token batches (the feature "
                "cache trains a head-only state with no word table)"
            )
        path = find_emb_path(state.params)
        table = tree_get(state.params, path)
        V = table.shape[0]
        w_s, w_q = support["word"], query["word"]
        ids = jnp.concatenate(
            [w_s.reshape(-1), w_q.reshape(-1)]
        ).astype(jnp.int32)
        T = ids.shape[0]
        U = min(T, V)  # sound: a batch can't touch more rows than either
        t = state.step.astype(jnp.int32)

        # 1. Dedup to a static [U] unique-id vector: sort, flag first
        # occurrences, compact by prefix-sum position. Duplicates get an
        # out-of-range position and are DROPPED by the scatter; unfilled
        # tail lanes stay at the pad value V (> every real id, so the
        # vector is sorted and searchsorted never lands on a pad).
        sorted_ids = jnp.sort(ids)
        first = jnp.concatenate(
            [jnp.ones((1,), bool), sorted_ids[1:] != sorted_ids[:-1]]
        )
        pos = jnp.where(first, jnp.cumsum(first) - 1, T)
        uids = jnp.full((U,), V, jnp.int32).at[pos].set(
            sorted_ids, mode="drop"
        )

        # 2. Catch the unique rows up through update t so the forward reads
        # exactly the values dense Adam would hold now. Pad lanes clamp to
        # row V-1 on gather; forcing their gap to 0 keeps a stale V-1 row
        # from inflating the loop bound (their results are dropped anyway).
        last_r = jnp.where(uids >= V, t, state.emb_last[uids])
        W_r, m_r, v_r = decay_catchup(
            table[uids], state.emb_m[uids], state.emb_v[uids], last_r, t, hp
        )

        # 3. Forward/backward on the COMPACT leaf: the caught-up [U, D]
        # rows ride the "lazy_embed" variable collection (models/embedding
        # prefers it over the dense param) and token ids are remapped into
        # them — the cotangent comes out [U, D] (the dense rows' exact
        # sums). The dense param is unread, so its grad is symbolic zeros
        # that XLA folds; no [V, D] gradient traffic ever exists.
        sup2 = {**support, "word": jnp.searchsorted(uids, w_s).astype(jnp.int32)}
        qry2 = {**query, "word": jnp.searchsorted(uids, w_q).astype(jnp.int32)}
        col: dict = {"rows": W_r}
        for key in reversed(path[1:-1]):  # mirror the module path
            col = {key: col}
        p_fwd = {**state.params, "lazy_embed": col}

        def loss_fn(p):
            return loss_and_metrics(
                model, p, sup2, qry2, label, cfg.loss, aux_w
            )

        grads, metrics = jax.grad(loss_fn, has_aux=True)(p_fwd)
        # Pad-lane grads are zero (no token maps to them) and the unread
        # dense param's grad leaf is zeros, so the global norm over this
        # tree equals dense mode's norm exactly.
        grads = clip_grads_like_optax(grads, hp.clip)

        # 4. Real Adam update for the unique rows; scatter back (pads drop).
        g_r = tree_get(grads["lazy_embed"], tuple(path[1:-1]) + ("rows",))
        W_new, m_new, v_new = touched_update(W_r, m_r, v_r, g_r, t, hp)

        # 5. Main params through optax (the emb partition is set_to_zero
        # there — see steps.make_optimizer).
        grads_main = {k: v for k, v in grads.items() if k != "lazy_embed"}
        state = state.apply_gradients(grads=grads_main)
        state = state.replace(
            params=tree_set(
                state.params, path,
                table.at[uids].set(W_new, mode="drop"),
            ),
            emb_m=state.emb_m.at[uids].set(m_new, mode="drop"),
            emb_v=state.emb_v.at[uids].set(v_new, mode="drop"),
            emb_last=state.emb_last.at[uids].set(t + 1, mode="drop"),
        )
        return state, metrics

    return body


def augment_token_table(table_np: dict) -> tuple[dict, "np.ndarray"]:
    """Precompute the token-cache lazy remap ONCE at cache build: the
    corpus's sorted distinct word ids (``uids [U]``) and every token's
    position in them (``winv [M, L]``, rides the per-row table dict so
    step-time gathers deliver it alongside the tokens).

    This removes ALL per-step dedup machinery from the cached lazy body:
    the measured v2 design (sort + searchsorted per step) spent more on the
    128k-wide sort pipeline than it saved (2,570 vs dense 3,532 eps/s on
    the reference-shaped config) — with the remap static, the step trains
    the corpus-restricted sub-table directly.
    """
    import numpy as np

    uids = np.unique(table_np["word"]).astype(np.int32)
    winv = np.searchsorted(uids, table_np["word"]).astype(np.int32)
    return {**table_np, "winv": winv}, uids


def _require_adam(cfg: ExperimentConfig):
    if cfg.optimizer != "adam":
        raise ValueError(
            "embed_optimizer=lazy replicates dense Adam's momentum tail; "
            f"it requires --optimizer adam (got {cfg.optimizer!r})"
        )


def _make_compact_step(model, cfg: ExperimentConfig, hp: LazyHyper,
                       mesh=None):
    """One fwd/bwd/update on the COMPACT [U, D] leaf: ``(state, rows,
    (support, query, label)) -> (state, rows, metrics)`` where rows =
    (W_r, m_r, v_r) are the caught-up corpus rows and support/query carry
    the precomputed ``winv`` remap. The single source of the cached lazy
    step math — the per-step body and the hoisted fused scan both wrap it,
    so they cannot diverge.

    ``mesh`` (token-cache factories thread theirs): lets
    ``cfg.grad_bucketing`` resolve — the fwd+bwd then runs per shard in
    shard_map and every gradient (the compact [U, D] rows leaf included,
    last bucket) reduces in an explicit, named, reverse-topological
    bucket psum (parallel/grad_buckets.py) instead of the partitioner's
    monolithic inserts. The clip/update math below is untouched: it
    consumes the same reduced tree either way."""
    from induction_network_on_fewrel_tpu.parallel.grad_buckets import (
        grad_buckets_for,
        make_bucketed_value_and_grad,
    )
    from induction_network_on_fewrel_tpu.train.steps import loss_and_metrics

    aux_w = cfg.moe_aux_weight if cfg.moe_experts > 0 else 0.0

    def loss_fn_of(p, batch):
        sup2, qry2, label = batch
        return loss_and_metrics(model, p, sup2, qry2, label, cfg.loss, aux_w)

    n_buckets = grad_buckets_for(cfg, mesh)
    # The dense [M, D] word table rides p_fwd only so flax finds the
    # declared param — the forward reads the compact lazy_embed rows.
    # Freeze it: its cotangent is identically zero, and letting the
    # bucketed wrapper stack/psum a full-table zeros leaf is an
    # 80 MB/step flagship all-reduce (the round-6 regression, re-measured
    # and caught by check_flagship's projection band in round 10).
    bucketed = (
        make_bucketed_value_and_grad(
            loss_fn_of, mesh, n_buckets,
            frozen=lambda p: (
                p.endswith("word_embedding") and "lazy_embed" not in p
            ),
        )
        if n_buckets else None
    )

    def compact_step(state, rows, batch):
        support, query, label = batch
        W_r, m_r, v_r = rows
        path = find_emb_path(state.params)
        t = state.step.astype(jnp.int32)

        sup2 = {**support, "word": support["winv"]}
        qry2 = {**query, "word": query["winv"]}
        col: dict = {"rows": W_r}
        for key in reversed(path[1:-1]):
            col = {key: col}
        p_fwd = {**state.params, "lazy_embed": col}

        def loss_fn(p):
            return loss_and_metrics(
                model, p, sup2, qry2, label, cfg.loss, aux_w
            )

        if bucketed is not None:
            grads, metrics = bucketed(p_fwd, (sup2, qry2, label))
        else:
            grads, metrics = jax.grad(loss_fn, has_aux=True)(p_fwd)
        grads = clip_grads_like_optax(grads, hp.clip)

        g_r = tree_get(grads["lazy_embed"], tuple(path[1:-1]) + ("rows",))
        W_new, m_new, v_new = touched_update(W_r, m_r, v_r, g_r, t, hp)

        grads_main = {k: v for k, v in grads.items() if k != "lazy_embed"}
        state = state.apply_gradients(grads=grads_main)
        return state, (W_new, m_new, v_new), metrics

    return compact_step


def make_lazy_cached_scan_fns(model, cfg: ExperimentConfig, mesh=None):
    """(prologue, compact_step, epilogue) for HOISTED fused token-cache
    scans. ``uids`` is static across a fused call, so the dense-table
    work moves to the call boundary: ``prologue(state, uids) -> rows``
    gathers + catches up the corpus rows ONCE, the compact rows then ride
    the ``lax.scan`` carry through S ``compact_step`` calls, and
    ``epilogue(state, rows, uids) -> state`` scatters rows/moments back
    once. Profiled motivation: the per-step body's three dense
    [400002, 50] scatter fusions were ~9% of headline device time
    (tools/profile_headline.py) for round-trips that are the identity
    inside the call (scatter(uids) then gather(uids) of the same rows).
    Equivalence with the per-step body is pinned at 1e-6 in
    tests/test_lazy_embed.py.
    """
    _require_adam(cfg)
    hp = make_hyper(cfg)
    compact = _make_compact_step(model, cfg, hp, mesh=mesh)

    def prologue(state, uids):
        path = find_emb_path(state.params)
        table = tree_get(state.params, path)
        t = state.step.astype(jnp.int32)
        return decay_catchup(
            table[uids], state.emb_m[uids], state.emb_v[uids],
            state.emb_last[uids], t, hp,
        )

    def epilogue(state, rows, uids):
        W, m, v = rows
        path = find_emb_path(state.params)
        table = tree_get(state.params, path)
        t = state.step.astype(jnp.int32)  # post-update count of the rows
        return state.replace(
            params=tree_set(state.params, path, table.at[uids].set(W)),
            emb_m=state.emb_m.at[uids].set(m),
            emb_v=state.emb_v.at[uids].set(v),
            emb_last=state.emb_last.at[uids].set(t),
        )

    return prologue, compact, epilogue


def make_lazy_cached_update_body(model, cfg: ExperimentConfig, mesh=None):
    """Token-cache twin of make_lazy_update_body: batch =
    ``(support, query, label, uids)`` where support/query carry the
    precomputed ``winv`` remapped ids and ``uids [U]`` is the STATIC
    sorted corpus vocabulary (augment_token_table).

    Exactness: every corpus row is "touched" every step — rows absent from
    the batch get the zero-gradient Adam update, which is EXACTLY what
    dense Adam applies to them (their momentum tail); non-corpus rows can
    never receive a gradient, and with weight decay excluded from the
    table their dense-Adam update is exactly zero forever. The catch-up
    loop therefore runs only on the first step after a restore (gap > 0)
    and is a no-op at steady state.

    This body pays the dense gather/scatter round-trip EVERY step; fused
    callers should prefer make_lazy_cached_scan_fns, which hoists it to
    the call boundary (identical trajectory).
    """
    prologue, compact, epilogue = make_lazy_cached_scan_fns(
        model, cfg, mesh=mesh
    )

    def body(state, batch):
        support, query, label, uids = batch
        rows = prologue(state, uids)
        state, rows, metrics = compact(state, rows, (support, query, label))
        return epilogue(state, rows, uids), metrics

    return body


def make_materialize(cfg: ExperimentConfig):
    """jitted (state) -> state with EVERY row caught up to state.step —
    the exact dense-equivalent table. Called at val/checkpoint boundaries
    (train/framework.py) so eval and saved checkpoints never see staleness.
    Cheap when gaps are short (the while_loop bound is the largest gap);
    never-touched rows (m=v=0) pass through with zero drift by
    construction."""
    hp = make_hyper(cfg)

    @partial(jax.jit, donate_argnums=(0,))
    def materialize(state):
        path = find_emb_path(state.params)
        table = tree_get(state.params, path)
        t = state.step.astype(jnp.int32)
        W, m, v = decay_catchup(
            table, state.emb_m, state.emb_v, state.emb_last, t, hp
        )
        return state.replace(
            params=tree_set(state.params, path, W),
            emb_m=m,
            emb_v=v,
            emb_last=jnp.full_like(state.emb_last, t),
        )

    return materialize
