"""Jitted train/eval steps and the optimizer chain.

Replaces the reference's per-step ``.cuda()`` + forward/backward/opt.step
Python loop (SURVEY.md §3.1): here the whole step — forward, loss, backward,
clip, update — is ONE jitted XLA program with donated state, so parameters
and optimizer state never round-trip to host and buffers are reused in-place.
The episode batch axis B is vmapped implicitly (all model ops are written
batched), matching "vmap over in-device episode batches" [BJ].
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax.training import train_state

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.models.losses import (
    accuracy,
    cross_entropy_loss,
    episode_metrics,
    mse_onehot_loss,
)

LOSS_FNS: dict[str, Callable] = {"mse": mse_onehot_loss, "ce": cross_entropy_loss}


class TrainState(train_state.TrainState):
    """Params + optimizer state + step; flax TrainState is already a pytree."""


class LazyEmbedTrainState(TrainState):
    """TrainState + the lazy word-table Adam state (train/lazy_embed.py):
    per-row first/second moments and the update count each row is current
    through. Rides the same pytree everywhere (scan carries, donation,
    orbax checkpoints); embed_optimizer is an ARCHITECTURE_FIELD, so
    restores always rebuild the matching tree."""

    emb_m: Any = None
    emb_v: Any = None
    emb_last: Any = None


def make_optimizer(cfg: ExperimentConfig) -> optax.GradientTransformation:
    """clip -> (adam|sgd) with StepLR-style staircase decay (SURVEY.md §2.1).

    ``cfg.embed_optimizer`` splits the word-embedding table off the main
    optimizer. With the real 400k-row GloVe table, dense Adam reads/writes
    the table plus two moment arrays every step — the dominant device cost
    in the XPlane profile (v5e, 2026-07-30) for gradients that touch <2%
    of rows. "sgd" drops the moment arrays and the Adam math (measured
    +15% end-to-end at 400k vocab; the dense grad itself still exists
    because clip_by_global_norm deliberately reduces over ALL gradients,
    preserving --grad_clip semantics). "frozen" keeps GloVe fixed via
    stop_gradient in the Embedding module — no table grad is built at all.
    "shared" (default) preserves reference parity: one optimizer for
    everything.
    """
    schedule = optax.exponential_decay(
        init_value=cfg.lr,
        transition_steps=cfg.lr_step_size,
        decay_rate=cfg.lr_gamma,
        staircase=True,
    )
    if cfg.optimizer == "adam":
        # Coupled L2 (decay added to the gradient BEFORE Adam's moment
        # normalization) — matches torch optim.Adam(weight_decay=...), the
        # reference family's optimizer. Decoupled AdamW is a different
        # trajectory and is exposed separately.
        opt = optax.chain(
            optax.add_decayed_weights(cfg.weight_decay), optax.adam(schedule)
        )
    elif cfg.optimizer == "adamw":
        opt = optax.adamw(schedule, weight_decay=cfg.weight_decay)
    elif cfg.optimizer == "sgd":
        opt = optax.chain(
            optax.add_decayed_weights(cfg.weight_decay), optax.sgd(schedule)
        )
    else:
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
    clip = optax.clip_by_global_norm(cfg.grad_clip)
    if cfg.embed_optimizer == "shared":
        return optax.chain(clip, opt)
    if cfg.embed_optimizer == "sgd":
        emb = optax.sgd(schedule)  # stateless: no moments to densify
    elif cfg.embed_optimizer in ("frozen", "lazy"):
        # frozen: the table never moves (Embedding stop_gradients it too).
        # lazy: the table IS updated, but by the sparse exact-parity path in
        # train/lazy_embed.py — optax must leave it alone here, and the
        # global-norm clip is replicated inside the lazy body (it has to
        # scale the dense emb cotangent before the row update), so the lazy
        # chain carries no clip of its own.
        emb = optax.set_to_zero()
    else:
        raise ValueError(f"unknown embed_optimizer {cfg.embed_optimizer!r}")

    def label_fn(params):
        def label(path, _):
            inside = any(
                getattr(p, "key", None) == "word_embedding" for p in path
            )
            return "emb" if inside else "main"

        labels = jax.tree_util.tree_map_with_path(label, params)
        if not any(v == "emb" for v in jax.tree.leaves(labels)):
            raise ValueError(
                f"embed_optimizer={cfg.embed_optimizer!r} but no "
                "'word_embedding' param exists in this model (BERT and "
                "feature-cache states have no GloVe table) — the flag "
                "would silently do nothing"
            )
        return labels

    if cfg.embed_optimizer == "lazy":
        # No clip in the chain: the lazy body applies the identical
        # global-norm clip manually so the emb row update sees the same
        # scaled gradient the main partition does.
        return optax.multi_transform({"main": opt, "emb": emb}, label_fn)
    # Clip OUTSIDE the split so the global norm covers every gradient,
    # exactly as in "shared" mode — the split changes only which update
    # rule each partition gets, not what --grad_clip means.
    return optax.chain(
        clip, optax.multi_transform({"main": opt, "emb": emb}, label_fn)
    )


def loss_and_metrics(
    model, params, support, query, label, loss_name: str,
    aux_weight: float = 0.0,
) -> tuple[jnp.ndarray, dict[str, jnp.ndarray]]:
    """``aux_weight`` > 0 collects sown auxiliary losses (the MoE
    load-balance term, models/moe.py) from the "losses" collection and adds
    them to the objective; metrics keep reporting the task loss alone."""
    if aux_weight > 0.0:
        logits, sown = model.apply(params, support, query, mutable="losses")
        aux = sum(jnp.sum(leaf) for leaf in jax.tree.leaves(sown))
        task_loss = LOSS_FNS[loss_name](logits, label)
        loss = task_loss + aux_weight * aux
    else:
        logits = model.apply(params, support, query)
        loss = task_loss = LOSS_FNS[loss_name](logits, label)
    return loss, {"loss": task_loss, "accuracy": accuracy(logits, label)}


def make_update_body(model, cfg: ExperimentConfig, update_shardings=None,
                     mesh=None):
    """The one fwd+bwd+update body every step factory wraps: single-device
    jit, GSPMD-sharded jit, and the lax.scan fused variants of both all call
    this — one source of truth for the update math, so the per-step and
    fused paths cannot diverge (tests assert they are bitwise-close).

    ``(state, (support, query, label)) -> (state, metrics)`` — the scan-body
    calling convention.

    ``update_shardings``: optional pytree of NamedShardings matching
    ``params`` (the GSPMD zero1 path passes its param shardings). When
    given, the optimizer update is spelled as ``tx.update`` + an explicit
    ``with_sharding_constraint`` pinning the param deltas back to the
    params' layout, inside ``jax.named_scope("opt/zero1_gather")`` — the
    SAME math ``apply_gradients`` runs (update, apply, step+1), but the
    dp-sharded-moments -> replicated-params re-gather now happens at a
    TRACED op carrying HLO metadata, so the ledger can attribute it
    (tools/comms_ledger.py; a bare named_scope cannot reach the
    partitioner-inserted collectives — they are not traced ops, which is
    how the zero1 leg's 232 KB of all-gathers stayed metadata-less
    through rounds 5-7, RUNBOOK §11 attribution debt).

    ``mesh``: the device mesh when the caller shards this body (the
    GSPMD step factories pass theirs). Lets ``cfg.grad_bucketing``
    resolve: on pure-dp meshes the gradient psums are spelled as
    explicit, named, reverse-topological bucket reductions hoisted out
    of a per-shard shard_map (parallel/grad_buckets.py) instead of the
    partitioner-inserted monolithic scatter — identical math, scheduler-
    visible collectives (COMMS_r10 overlap rows).
    """

    if cfg.embed_optimizer == "lazy":
        # The lazy table body has its own update spelling; zero1's
        # explicit-gather attribution covers the plain-TrainState path
        # only (remaining-debt note in BASELINE round 8). No mesh is
        # passed: the LIVE lazy path is single-device by CLI contract
        # (the token-cache factories thread their mesh to the cached
        # lazy body themselves).
        from induction_network_on_fewrel_tpu.train.lazy_embed import (
            make_lazy_update_body,
        )

        return make_lazy_update_body(model, cfg)

    from induction_network_on_fewrel_tpu.parallel.grad_buckets import (
        grad_buckets_for,
        make_bucketed_value_and_grad,
    )

    aux_w = cfg.moe_aux_weight if cfg.moe_experts > 0 else 0.0

    def loss_fn_of(params, batch):
        support, query, label = batch
        return loss_and_metrics(
            model, params, support, query, label, cfg.loss, aux_w
        )

    n_buckets = grad_buckets_for(cfg, mesh)
    bucketed = (
        make_bucketed_value_and_grad(loss_fn_of, mesh, n_buckets)
        if n_buckets else None
    )

    def body(state: TrainState, batch):
        support, query, label = batch

        def loss_fn(params):
            return loss_and_metrics(
                model, params, support, query, label, cfg.loss, aux_w
            )

        if bucketed is not None:
            grads, metrics = bucketed(state.params, batch)
        else:
            grads, metrics = jax.grad(loss_fn, has_aux=True)(state.params)
        if update_shardings is None:
            return state.apply_gradients(grads=grads), metrics
        # flax TrainState.apply_gradients, spelled out so the re-gather
        # of the sharded param deltas is a named, attributable op. The
        # outer scope also names the update MATH: GSPMD copies metadata
        # from the op it partitions, so gathers it fuses into the Adam
        # arithmetic surface as opt/zero1_update/... rows rather than a
        # bare "mul".
        with jax.named_scope("opt/zero1_update"):
            updates, new_opt_state = state.tx.update(
                grads, state.opt_state, state.params
            )
            with jax.named_scope("gather"):
                if n_buckets:
                    # Same hoisted, named spelling as the grad psums: the
                    # dp-sharded-delta -> replicated-params re-gathers pin
                    # per reverse-topological bucket, so each bucket's
                    # all-gather is its own attributed, schedulable op
                    # (opt/zero1_update/gather/bucket_k rows in the
                    # ledger) instead of one fused re-shard.
                    from induction_network_on_fewrel_tpu.parallel import (
                        grad_buckets as _gb,
                    )

                    flat_u, td = jax.tree_util.tree_flatten_with_path(
                        updates
                    )
                    flat_s = jax.tree_util.tree_leaves(
                        update_shardings,
                        is_leaf=lambda x: hasattr(x, "spec"),
                    )
                    gathered: list = [None] * len(flat_u)
                    for k in range(n_buckets):
                        with jax.named_scope(f"bucket_{k}"):
                            for i, (path, leaf) in enumerate(flat_u):
                                if _gb.bucket_index(
                                    _gb._path_str(path), n_buckets
                                ) == k:
                                    gathered[i] = (
                                        jax.lax.with_sharding_constraint(
                                            leaf, flat_s[i]
                                        )
                                    )
                    updates = jax.tree_util.tree_unflatten(td, gathered)
                else:
                    updates = jax.lax.with_sharding_constraint(
                        updates, update_shardings
                    )
            new_params = optax.apply_updates(state.params, updates)
        return (
            state.replace(
                step=state.step + 1, params=new_params,
                opt_state=new_opt_state,
            ),
            metrics,
        )

    return body


def make_train_step(model, cfg: ExperimentConfig):
    """Returns jitted (state, support, query, label) -> (state, metrics)."""
    body = make_update_body(model, cfg)

    @partial(jax.jit, donate_argnums=(0,))
    def train_step(state: TrainState, support, query, label):
        return body(state, (support, query, label))

    return train_step


def make_multi_train_step(model, cfg: ExperimentConfig):
    """Fused S-step training: one dispatch runs ``lax.scan`` over S stacked
    episode batches (leading axis S on every input array).

    The reference pays Python dispatch + H2D latency once per step
    (SURVEY.md §3.1 boundary #3); on this TPU (behind a high-latency tunnel)
    that overhead is ~25% of the step budget at B=8. Scanning S steps inside
    one jitted call amortizes it S-fold while computing the IDENTICAL
    sequence of SGD updates — same grads, same optimizer math, same step
    count (verified bitwise-close in tests/test_train.py).

    Returns jitted ``(state, support_s, query_s, label_s) -> (state,
    metrics)`` where each metric is stacked ``[S]``.
    """

    body = make_update_body(model, cfg)

    @partial(jax.jit, donate_argnums=(0,))
    def multi_train_step(state: TrainState, support_s, query_s, label_s):
        return jax.lax.scan(body, state, (support_s, query_s, label_s))

    return multi_train_step


def make_eval_step(model, cfg: ExperimentConfig):
    @jax.jit
    def eval_step(params, support, query, label) -> dict[str, jnp.ndarray]:
        logits = model.apply(params, support, query)
        return {
            "loss": LOSS_FNS[cfg.loss](logits, label),
            **episode_metrics(logits, label, cfg.na_rate > 0),
        }

    return eval_step


def make_multi_eval_step(model, cfg: ExperimentConfig):
    """Fused eval: one dispatch scores S stacked episode batches.

    Eval batches are independent (params fixed), so this is ``lax.map`` over
    the stacked axis — same per-call amortization as the fused train step
    (each eval dispatch costs a full tunnel round-trip otherwise). Returns
    metrics stacked ``[S]``.
    """

    @jax.jit
    def multi_eval_step(params, support_s, query_s, label_s):
        def body(xs):
            support, query, label = xs
            logits = model.apply(params, support, query)
            return {
                "loss": LOSS_FNS[cfg.loss](logits, label),
                **episode_metrics(logits, label, cfg.na_rate > 0),
            }

        return jax.lax.map(body, (support_s, query_s, label_s))

    return multi_eval_step


def init_state(model, cfg: ExperimentConfig, support, query, rng=None) -> TrainState:
    rng = rng if rng is not None else jax.random.key(cfg.seed)
    params = model.init(rng, support, query)
    if cfg.embed_optimizer == "lazy":
        from induction_network_on_fewrel_tpu.train.lazy_embed import (
            find_emb_path,
            tree_get,
        )

        table = tree_get(params, find_emb_path(params))
        return LazyEmbedTrainState.create(
            apply_fn=model.apply, params=params, tx=make_optimizer(cfg),
            emb_m=jnp.zeros_like(table), emb_v=jnp.zeros_like(table),
            emb_last=jnp.zeros((table.shape[0],), jnp.int32),
        )
    return TrainState.create(
        apply_fn=model.apply, params=params, tx=make_optimizer(cfg)
    )


def make_grad_probe(model, cfg: ExperimentConfig):
    """Periodic grad-health probe (VERDICT weak #7, obs/ integration).

    The production step may backprop through bf16 matmuls and the Pallas
    LSTM kernel (~10-15% mean relative grad error, ops/lstm.py) — a risk
    validated by exactly one quality A/B. This probe makes it visible in
    soaks: on the SAME batch and params, compute the run-config gradient
    and an all-f32 reference gradient (f32 compute, scan LSTM, XLA attn),
    and report the global norms plus their cosine. A drifting cosine is
    the early-warning signal that the approximate backward has entered a
    regime where it bites.

    Returns jitted ``(params, support, query, label) -> {grad_norm,
    grad_norm_f32, grad_cosine}``. Off the training path entirely: no
    state is touched, so running it every K steps costs one extra
    fwd+bwd pair per probe and nothing else.
    """
    from induction_network_on_fewrel_tpu.models.build import build_model

    ref_cfg = cfg.replace(
        compute_dtype="float32", head_dtype="float32",
        lstm_backend="scan", attn_backend="xla",
        # The reference backward must be the PLAIN two-pass attention:
        # with remat_attn left on, the probe would compare the run
        # gradient against another kernel-backward gradient and a drift
        # in the recompute path would be invisible. Same principle for
        # the round-8 lstm residual knobs: the scan backend keeps no
        # residuals (so these are already inert there), but pin them
        # explicitly so the reference stays exact if the backend pin
        # ever changes — this probe is the run-time police for
        # --lstm_residuals bf16 drift.
        # Bucketing off too: the probe's reference gradient must be the
        # plain monolithic jax.grad — a bucketed reference would compare
        # one restructured backward against another and mask drift in
        # the bucket spelling itself (probe runs meshless, where the
        # knob is inert anyway, but the pin keeps that true if the
        # probe ever gains a mesh).
        remat_attn=False, lstm_cs_window=0, lstm_residuals="f32",
        grad_bucketing="off",
    )
    ref_model = build_model(ref_cfg)
    aux_w = cfg.moe_aux_weight if cfg.moe_experts > 0 else 0.0

    def grads_of(m, params, support, query, label):
        def loss_fn(p):
            loss, _ = loss_and_metrics(
                m, p, support, query, label, cfg.loss, aux_w
            )
            return loss

        return jax.grad(loss_fn)(params)

    def flatten(tree):
        return jnp.concatenate([
            jnp.ravel(x).astype(jnp.float32) for x in jax.tree.leaves(tree)
        ])

    @jax.jit
    def probe(params, support, query, label):
        g_run = flatten(grads_of(model, params, support, query, label))
        g_ref = flatten(grads_of(ref_model, params, support, query, label))
        # All three inner products through the SAME reduction (vdot): the
        # f32 summation error over ~1e6 elements is then common-mode and
        # cancels in the ratio — norm-vs-vdot mixing measurably skewed the
        # cosine (~3e-3 on identical vectors, CPU sequential sums).
        d_rr = jnp.vdot(g_run, g_run)
        d_ff = jnp.vdot(g_ref, g_ref)
        d_rf = jnp.vdot(g_run, g_ref)
        # Shared epsilon in numerator AND denominator: two exactly-zero
        # gradients (the MSE-sigmoid dead zone) agree — cosine 1, not 0/0.
        cos = (d_rf + 1e-30) / (jnp.sqrt(d_rr * d_ff) + 1e-30)
        return {
            "grad_norm": jnp.sqrt(d_rr),
            "grad_norm_f32": jnp.sqrt(d_ff),
            "grad_cosine": cos,
        }

    return probe


# --- FewRel 2.0 adversarial domain adaptation (models/adversarial.py) ---


def init_disc_state(disc, cfg: ExperimentConfig, feat_dim: int, rng=None) -> TrainState:
    """Discriminator gets its own TrainState: it is a training-time-only
    adversary and stays out of the model checkpoint (the reference family
    likewise saves only the model state_dict)."""
    rng = rng if rng is not None else jax.random.key(cfg.seed + 17)
    params = disc.init(rng, jnp.zeros((1, feat_dim), jnp.float32))
    # The discriminator has no word-embedding table; always give it the
    # plain optimizer chain (an embed_optimizer split would refuse to init
    # against a tree with no 'word_embedding' leaf).
    return TrainState.create(
        apply_fn=disc.apply, params=params,
        tx=make_optimizer(cfg.replace(embed_optimizer="shared")),
    )


def make_adv_update_body(model, disc, cfg: ExperimentConfig):
    """The DANN fwd+bwd+update body shared by the per-step and fused
    factories: ``((state, disc_state), (support, query, label, src, tgt))
    -> ((state, disc_state), metrics)`` — the scan calling convention.
    """
    from induction_network_on_fewrel_tpu.models.base import FewShotModel
    from induction_network_on_fewrel_tpu.ops import gradient_reversal

    lam = cfg.adv_lambda
    aux_w = cfg.moe_aux_weight if cfg.moe_experts > 0 else 0.0

    def encode(params, batch):
        return model.apply(
            params, batch["word"], batch["pos1"], batch["pos2"], batch["mask"],
            method=FewShotModel.encode,
        )

    def body(carry, batch):
        state, disc_state = carry
        support, query, label, src, tgt = batch

        def loss_fn(params, disc_params):
            # Few-shot objective (incl. any sown MoE aux) comes from the
            # shared loss_and_metrics — the single source of aux handling.
            fs_loss, fs_metrics = loss_and_metrics(
                model, params, support, query, label, cfg.loss, aux_w
            )

            feat = jnp.concatenate(
                [encode(params, src), encode(params, tgt)], axis=0
            )
            dom_label = jnp.concatenate(
                [jnp.zeros(src["word"].shape[0], jnp.int32),
                 jnp.ones(tgt["word"].shape[0], jnp.int32)]
            )
            dom_logits = disc.apply(
                disc_params, gradient_reversal(feat, lam)
            )
            dom_loss = cross_entropy_loss(dom_logits[None], dom_label[None])
            metrics = {
                **fs_metrics,
                "domain_loss": dom_loss,
                "domain_accuracy": accuracy(dom_logits[None], dom_label[None]),
            }
            return fs_loss + dom_loss, metrics

        grads, metrics = jax.grad(loss_fn, argnums=(0, 1), has_aux=True)(
            state.params, disc_state.params
        )
        state = state.apply_gradients(grads=grads[0])
        disc_state = disc_state.apply_gradients(grads=grads[1])
        return (state, disc_state), metrics

    return body


def make_adv_train_step(model, disc, cfg: ExperimentConfig):
    """Jitted DANN step: few-shot loss + domain-confusion game in ONE pass.

    (state, disc_state, support, query, label, src, tgt) ->
    (state, disc_state, metrics); ``src``/``tgt`` are unlabeled instance
    dicts {word, pos1, pos2, mask}: [M, L]. The discriminator minimizes
    domain cross-entropy; ``ops.gradient_reversal`` hands the encoder the
    negated gradient so it maximizes it — one backward, one optimizer step
    each, no alternating schedule.
    """
    body = make_adv_update_body(model, disc, cfg)

    @partial(jax.jit, donate_argnums=(0, 1))
    def adv_train_step(state: TrainState, disc_state: TrainState,
                       support, query, label, src, tgt):
        (state, disc_state), metrics = body(
            (state, disc_state), (support, query, label, src, tgt)
        )
        return state, disc_state, metrics

    return adv_train_step


def make_adv_multi_train_step(model, disc, cfg: ExperimentConfig):
    """steps_per_call twin of the DANN step: scan S stacked (episode,
    src, tgt) batches in one dispatch — identical update sequence.

    (state, disc_state, support_s, query_s, label_s, src_s, tgt_s) ->
    (state, disc_state, metrics stacked [S]).
    """
    body = make_adv_update_body(model, disc, cfg)

    @partial(jax.jit, donate_argnums=(0, 1))
    def adv_multi_train_step(state, disc_state,
                             support_s, query_s, label_s, src_s, tgt_s):
        (state, disc_state), metrics = jax.lax.scan(
            body, (state, disc_state),
            (support_s, query_s, label_s, src_s, tgt_s),
        )
        return state, disc_state, metrics

    return adv_multi_train_step
