from induction_network_on_fewrel_tpu.train.steps import (  # noqa: F401
    make_eval_step,
    make_optimizer,
    make_train_step,
)
from induction_network_on_fewrel_tpu.train.framework import FewShotTrainer  # noqa: F401
