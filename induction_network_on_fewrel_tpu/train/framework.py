"""FewShotTrainer — the episode-loop training framework.

TPU-shaped analog of the reference's ``FewShotREFramework.train/eval``
(SURVEY.md §1 L5, §3.1): fetch host batch -> one jitted step (fwd+bwd+update,
donated state) -> periodic eval -> best-checkpoint save. Host<->device
traffic is exactly one batch per step in and two scalars out; JAX's async
dispatch overlaps the host-side sampling of step t+1 with device compute of
step t, replacing the reference's DataLoader worker processes.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Iterator

import jax
import numpy as np

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.models.build import batch_to_model_inputs
from induction_network_on_fewrel_tpu.obs.spans import get_tracker, span
from induction_network_on_fewrel_tpu.train.checkpoint import CheckpointManager
from induction_network_on_fewrel_tpu.train.steps import (
    init_state,
    make_eval_step,
    make_grad_probe,
    make_multi_eval_step,
    make_multi_train_step,
    make_train_step,
)
from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger


@dataclasses.dataclass
class AdvPieces:
    """Everything the DANN loop needs beyond the plain trainer: the jitted
    adversarial step (steps.make_adv_train_step), the discriminator's own
    TrainState (mutated across steps, never checkpointed), and unlabeled
    instance samplers for the source and target domains."""

    step: Callable
    disc_state: Any
    src_sampler: Any
    tgt_sampler: Any
    # Optional steps_per_call twin (steps.make_adv_multi_train_step): scans
    # S stacked (episode, src, tgt) batches per dispatch. None = per-step.
    multi_step: Callable | None = None


class FewShotTrainer:
    def __init__(
        self,
        model,
        cfg: ExperimentConfig,
        train_sampler,
        val_sampler=None,
        ckpt_dir: str | None = None,
        logger: MetricsLogger | None = None,
        train_step=None,
        eval_step=None,
        fused_step=None,
        fused_eval=None,
        initial_state=None,
        mesh=None,
        adv=None,
        profile_dir: str | None = None,
        profile_steps: int = 10,
        watchdog=None,
        recorder=None,
        comms_u_rows=None,
        comms_compact=None,
        perf=None,
        compile_watcher=None,
    ):
        self.model = model
        self.cfg = cfg
        self.train_sampler = train_sampler
        self.val_sampler = val_sampler
        self.logger = logger or MetricsLogger(quiet=True)
        # datapipe/ producer pipeline (duck-typed on the cursor surface):
        # when the train sampler is a PipelineFeed, the trainer (a) gives
        # it the logger so stall ticks reach the watchdog, (b) logs
        # kind="data" feed telemetry once per metric window, and (c) saves
        # the pipeline cursor into every checkpoint so --resume replays
        # the exact episode stream.
        self._feed = (
            train_sampler if hasattr(train_sampler, "cursor_state") else None
        )
        if self._feed is not None and getattr(self._feed, "logger", None) is None:
            self._feed.logger = self.logger
        # Telemetry spine (obs/): the watchdog and flight recorder observe
        # every record through MetricsLogger hooks — one emission point,
        # no per-site instrumentation. Both optional and host-side only.
        self.watchdog = watchdog
        self.recorder = recorder
        # Performance-attribution observability (ISSUE 11): the perf
        # observer decomposes each metric window into segments that tile
        # it (obs/perf.py, kind="perf"); the compile watcher stamps every
        # XLA compile with fn/shapes/elapsed/trigger (obs/compile.py,
        # kind="compile") and holds the train loop to the steady-state
        # zero-recompile invariant. Both optional, host-side only; the
        # trainer OWNS them once passed (closed/uninstalled in close()).
        self._perf = perf
        self._compile_watcher = compile_watcher
        # Hook ORDER is load-bearing: the recorder must see each record
        # BEFORE the watchdog, whose critical events dump the recorder —
        # else the dump's metrics window excludes the record that tripped.
        if recorder is not None:
            self.logger.add_hook(recorder.record_metric)
        if watchdog is not None:
            watchdog.logger = watchdog.logger or self.logger
            if watchdog.recorder is None:
                watchdog.recorder = recorder
            self.logger.add_hook(watchdog.observe_record)
        # Grad-health probe (cfg.grad_probe_every, VERDICT weak #7): only
        # on the stock live-token path — injected (mesh/cached) steps feed
        # index batches the probe's model.apply cannot consume, and the
        # DANN path has its own objective.
        self._grad_probe = None
        if cfg.grad_probe_every > 0:
            if train_step is None and adv is None:
                self._grad_probe = make_grad_probe(model, cfg)
            else:
                import warnings

                warnings.warn(
                    "--grad_probe_every is ignored with injected "
                    "(mesh-sharded/cached) steps or adversarial training",
                    stacklevel=2,
                )
        # Injectable steps so parallel/ can substitute mesh-sharded versions.
        self.train_step = train_step or make_train_step(model, cfg)
        self.eval_step = eval_step or make_eval_step(model, cfg)
        # logger threaded so integrity quarantines (ISSUE 12) land in the
        # telemetry stream — the watchdog hook turns them into CRITICAL
        # ckpt_corrupt events.
        self.ckpt = (
            CheckpointManager(ckpt_dir, cfg, logger=self.logger)
            if ckpt_dir else None
        )
        self.best_val = -1.0
        # Divergence-guard arming threshold, CONFIG-RELATIVE (a hardcoded
        # 0.5 left the guard inert exactly where collapse risk is highest:
        # 10-way and heavy-NOTA configs legitimately peak below 0.5). Arm
        # once best_val clears 2x the random-guess floor 1/(N + has_nota),
        # capped at the floor/1.0 midpoint so tiny-N configs (N=2: floor
        # 0.5) can still arm.
        guard_floor = 1.0 / (cfg.n + (1 if cfg.na_rate > 0 else 0))
        self.guard_arm = min(2.0 * guard_floor, 0.5 * (1.0 + guard_floor))
        self._initial_state = initial_state
        # Mesh the injected steps were built for (None = single device);
        # restored checkpoints must be re-placed onto it (see reshard_state).
        self.mesh = mesh
        # Per-window collective-traffic telemetry (ISSUE 5, kind="comms"):
        # the ledger arithmetic's bytes/step/device, computed once — the
        # SAME formulas tools/comms_ledger.py asserts the compiled HLO
        # against (utils/roofline.comms_components), so the stream and the
        # ledger can never disagree. dp read from the MESH (cfg.dp=0 means
        # "all devices" at the CLI and must not gate the record off);
        # BiLSTM runs only — the roofline formulas model the flagship
        # BiLSTM step, and emitting them for another encoder would be a
        # confident wrong number. --compact_demb off runs get the DENSE
        # arithmetic (the replicated-cotangent all-gather), so the A/B
        # leg's headline is honest. ``comms_u_rows``: the real corpus
        # distinct-row count when the caller knows it (cli threads the
        # token-cache lazy uids length); default = the synthetic bound.
        self._comms_record = None
        mesh_dp = mesh.shape.get("dp", 1) if mesh is not None else 1
        # Pure-dp meshes only: ZeRO-1 swaps the grad all-reduce for
        # reduce-scatter + param all-gather (~2x payload — the ledger
        # measured zero1 at 2.7x dp8) and tp/pp/ep/sp add collectives the
        # formulas don't carry; emitting the dp-only number there would
        # be the confident-wrong-number failure this gate exists to
        # prevent. Those legs stay ledger-only.
        pure_dp = (
            mesh is not None
            and mesh_dp > 1
            and not cfg.zero_opt
            and all(
                size == 1
                for ax, size in mesh.shape.items() if ax != "dp"
            )
        )
        # ...and TOKEN-CACHE lazy only: the demb terms model the compact
        # [U_corpus, D] row gradient of the cached-corpus leaf. A
        # shared-embed run's real demb collective is full-table-shaped,
        # and a NON-cached lazy run's leaf is batch-bounded at
        # U = min(T, V) (train/lazy_embed.py) — ~M*L rows at flagship-
        # like shapes, several-fold more than the corpus bound the
        # formulas would report. Both stay ledger-only (round-7 review
        # finding, pass 5).
        if (pure_dp and cfg.encoder == "bilstm"
                and cfg.embed_optimizer == "lazy" and cfg.token_cache):
            from induction_network_on_fewrel_tpu.utils.roofline import (
                comms_payload_bytes,
                comms_wire_bytes,
            )

            # ``comms_compact``: whether a compact demb_impl was ACTUALLY
            # resolved for this run's steps (cli passes it) — re-deriving
            # from the knob alone would report compact arithmetic on a
            # run whose resolver declined (round-7 review finding).
            compact = (
                comms_compact if comms_compact is not None
                else cfg.compact_demb != "off"
            )
            wire = comms_wire_bytes(
                cfg, dp=mesh_dp, compact=compact, corpus_rows=comms_u_rows
            )
            self._comms_record = {
                "payload_bytes_per_step": float(comms_payload_bytes(
                    cfg, dp=mesh_dp, compact=compact,
                    corpus_rows=comms_u_rows,
                )),
                "wire_bytes_per_step": float(wire),
                "wire_mb_per_step": round(wire / 1e6, 3),
                "dp": float(mesh_dp),
                "compact_demb": float(compact),
            }
            if comms_u_rows:
                self._comms_record["demb_u_rows"] = float(comms_u_rows)
        # Per-window HBM-roofline telemetry (ISSUE 6, kind="roofline"):
        # the shared step-byte arithmetic at this config's residual knobs
        # (utils/roofline.step_bytes — the formulas ROOFLINE_r*.json and
        # bench.py stamp; the tier-1 regression gate holds them to the
        # recorded round value). BiLSTM only — the formulas model the
        # flagship BiLSTM kernel step. Like bench's stamp and unlike the
        # comms record, this is the analytic MODEL of the step at this
        # config, not a measurement of this process's backend: the window
        # and dtype knobs come from cfg (what the kernel paths would run),
        # so a CPU-honest session reports the same diet arithmetic a chip
        # session verifies by wall clock.
        self._roofline_record = None
        if cfg.encoder == "bilstm":
            from induction_network_on_fewrel_tpu.utils.roofline import (
                lstm_residual_bytes,
                step_bytes,
            )

            sb = step_bytes(cfg, corpus_rows=comms_u_rows)
            self._roofline_record = {
                "step_bytes": float(sb),
                "step_mb": round(sb / 1e6, 3),
                "lstm_residual_bytes": float(lstm_residual_bytes(cfg)),
                "lstm_cs_window": float(getattr(cfg, "lstm_cs_window", 0)),
            }
            if comms_u_rows:
                # Carried so obs_report's rebuilt per-component table can
                # use the SAME corpus bound as the headline — without it
                # the lazy demb/optimizer rows would silently fall back to
                # the synthetic default and disagree with step_mb on a
                # real corpus (the round-7 understatement, resurfacing).
                self._roofline_record["corpus_rows"] = float(comms_u_rows)
        # FewRel 2.0 adversarial adaptation: AdvPieces bundle, or None. When
        # set, training runs the DANN step (few-shot loss + domain game)
        # instead of the plain step; eval/checkpointing are unchanged (the
        # discriminator is a training-time adversary, never saved).
        self.adv = adv
        # Tracing (SURVEY.md §5.1): profile steps [2, 2+profile_steps) into
        # a TensorBoard XPlane trace. Step 1 is excluded on purpose — it is
        # the compile, and a trace dominated by one 30 s XLA compilation
        # hides the steady-state picture the profile is for.
        self.profile_dir = profile_dir
        self.profile_steps = profile_steps
        # steps_per_call fusion (train/steps.py make_multi_train_step): only
        # for the stock single-device step — injected (mesh-sharded) steps
        # and the adversarial path keep per-step dispatch; fusing those means
        # building the scan into their own step factories, not wrapping here.
        self._fused_step = None
        if cfg.steps_per_call > 1:
            if adv is not None and fused_step is not None:
                # The fused loop would silently bypass the DANN step.
                raise ValueError(
                    "fused_step cannot be combined with adversarial "
                    "training; the fused loop skips the domain game"
                )
            if (
                val_sampler is not None
                and cfg.val_step
                and cfg.steps_per_call > cfg.val_step
            ):
                # A fused call may not skip val/checkpoint boundaries:
                # mid-chunk params no longer exist to evaluate.
                raise ValueError(
                    f"steps_per_call ({cfg.steps_per_call}) must not exceed "
                    f"val_step ({cfg.val_step}); lower it or raise val_step"
                )
            if fused_step is not None:
                # parallel/sharding.make_sharded_multi_train_step, built by
                # the caller against this trainer's mesh + state example.
                self._fused_step = fused_step
            elif train_step is None and adv is None:
                self._fused_step = make_multi_train_step(model, cfg)
            elif adv is not None and adv.multi_step is not None:
                pass  # fused DANN path; handled in the train loop
            else:
                import warnings

                reason = "adversarial training" if adv is not None else (
                    "an injected (mesh-sharded) train step"
                )
                warnings.warn(
                    f"steps_per_call={cfg.steps_per_call} is ignored with "
                    f"{reason}; training runs per-step dispatch",
                    stacklevel=2,
                )
        # Lazy-embed mode (train/lazy_embed.py): the word table is stale for
        # rows outside recent batches; materialize (exact catch-up of every
        # row) before anything that reads the table outside training —
        # eval, checkpoint saves, and the returned state.
        self._materialize = None
        if cfg.embed_optimizer == "lazy":
            from induction_network_on_fewrel_tpu.train.lazy_embed import (
                make_materialize,
            )

            self._materialize = make_materialize(cfg)
        # Fused eval: an injected fused step (the cached paths bind their
        # table into one — cli._wire_index_cache), else the stock
        # steps.make_multi_eval_step when the stock eval path is in use.
        self._fused_eval = None
        if cfg.steps_per_call > 1:
            if fused_eval is not None:
                self._fused_eval = fused_eval
            elif eval_step is None:
                self._fused_eval = make_multi_eval_step(model, cfg)

    def _can_sample_fused(self) -> bool:
        """Whether the train sampler fills a fused [S,B,*] stack in one
        call (index samplers; FeatureEpisodeSampler only in index mode)."""
        s = self.train_sampler
        return hasattr(s, "sample_fused") and getattr(s, "return_indices", True)

    def init_state(self):
        # Reuse a pre-built state when one was injected: mesh-sharded steps
        # are traced against its exact pytree metadata (optimizer function
        # identities included), so a fresh init_state would not match.
        if self._initial_state is not None:
            state, self._initial_state = self._initial_state, None
            return state
        batch = self.train_sampler.sample_batch()
        support, query, _ = batch_to_model_inputs(batch)
        return init_state(self.model, self.cfg, support, query)

    def reshard_state(self, state):
        """Place a restored state onto this trainer's mesh shardings (no-op
        on single device). Orbax commits restored arrays to one device and
        jit in_shardings refuses mismatched committed args."""
        if self.mesh is None:
            return state
        from induction_network_on_fewrel_tpu.parallel.sharding import shard_state

        return shard_state(state, self.mesh, zero_opt=self.cfg.zero_opt)

    def train(self, state=None, num_iters: int | None = None,
              start_step: int = 0):
        """Run ``num_iters`` optimizer steps, numbered globally from
        ``start_step`` (pass the restored step on --resume so checkpoint
        step numbers keep increasing across restarts — orbax retention and
        the recovery ring compare by step)."""
        if self.recorder is not None:
            # Any exception escaping the loop (incl. --fault_step's
            # injected crash) dumps the flight recorder before re-raising.
            with self.recorder.armed("train crash"):
                return self._train_impl(state, num_iters, start_step)
        return self._train_impl(state, num_iters, start_step)

    def _train_impl(self, state, num_iters, start_step):
        cfg = self.cfg
        if self.ckpt is not None:
            # A dir whose checkpoints are ahead of this run's numbering
            # would silently swallow every save — refuse up front.
            self.ckpt.check_start_step(start_step)
        state = state if state is not None else self.init_state()
        num_iters = num_iters or cfg.train_iter
        end_step = start_step + num_iters
        it = iter(self.train_sampler)
        t0 = time.monotonic()
        last_logged = start_step
        # Metric logging fetches values (a real device sync on tunneled
        # backends — see bench.py's hard-sync note); with fused calls, log
        # every metric_window_calls calls rather than every one so the
        # sync amortizes.
        window = max(50, cfg.metric_window_calls * cfg.steps_per_call)
        adv = self.adv
        profiling = profile_done = False
        diverged_stop = False
        step = start_step
        # Step-scoped trace ids (ISSUE 9): each loop iteration (one
        # dispatch — spc optimizer steps) runs under a fresh trace
        # context, so the train-side spans (sample/dispatch/eval/
        # checkpoint) carry trace ids and join the same ring/waterfall
        # machinery the serving data plane uses. Cost per iteration: one
        # tiny object + one string. Cleared at loop entry too — a prior
        # run that crashed mid-loop must not leak its last step's id
        # into this one's spans.
        tracker = get_tracker()
        tracker.set_trace(None)
        if self._perf is not None:
            # Open the first decomposition window at loop entry, bound to
            # THIS thread (only its spans tile the windows).
            self._perf.begin(step)
        while step < end_step:
            tracker.set_trace(tracker.new_context())
            if self._compile_watcher is not None:
                # One int store: compiles observed anywhere in this
                # iteration stamp the right step into kind="compile".
                self._compile_watcher.observe_step(step)
            # Trace steps [1, 1+profile_steps): the first call (the compile)
            # stays outside the trace so it doesn't drown the steady state.
            if self.profile_dir is not None:
                if not profiling and not profile_done and step >= start_step + 1:
                    jax.profiler.start_trace(self.profile_dir)
                    profiling = True
                elif profiling and step >= start_step + 1 + self.profile_steps:
                    jax.profiler.stop_trace()
                    profiling, profile_done = False, True
                    self.logger.log(step, "profile", written=1.0)
            spc = cfg.steps_per_call
            adv_fused = adv is not None and adv.multi_step is not None
            if self._fused_step is not None and end_step - step >= spc:
                with span("train/sample", steps=spc):
                    if self._can_sample_fused():
                        # Index samplers fill the whole [S,B,*] stack in one
                        # native call — the per-batch Python loop below was
                        # measurable host overhead at large steps_per_call.
                        sup_s, qry_s, lab_s = self.train_sampler.sample_fused(spc)
                    else:
                        batches = [
                            batch_to_model_inputs(next(it)) for _ in range(spc)
                        ]
                        sup_s, qry_s, lab_s = jax.tree.map(
                            lambda *xs: np.stack(xs), *batches
                        )
                with span("train/dispatch", steps=spc):
                    state, metrics = self._fused_step(state, sup_s, qry_s, lab_s)
                if self._grad_probe is not None:
                    probe_batch = jax.tree.map(
                        lambda x: x[0], (sup_s, qry_s, lab_s)
                    )
                prev, step = step, step + spc
            elif adv_fused and end_step - step >= spc:
                batches = [
                    batch_to_model_inputs(next(it)) for _ in range(spc)
                ]
                sup_s, qry_s, lab_s = jax.tree.map(
                    lambda *xs: np.stack(xs), *batches
                )
                srcs = [adv.src_sampler.sample_batch()._asdict()
                        for _ in range(spc)]
                tgts = [adv.tgt_sampler.sample_batch()._asdict()
                        for _ in range(spc)]
                src_s = jax.tree.map(lambda *xs: np.stack(xs), *srcs)
                tgt_s = jax.tree.map(lambda *xs: np.stack(xs), *tgts)
                state, adv.disc_state, metrics = adv.multi_step(
                    state, adv.disc_state, sup_s, qry_s, lab_s, src_s, tgt_s
                )
                prev, step = step, step + spc
            else:
                with span("train/sample", steps=1):
                    support, query, label = batch_to_model_inputs(next(it))
                with span("train/dispatch", steps=1):
                    if adv is not None:
                        src = adv.src_sampler.sample_batch()._asdict()
                        tgt = adv.tgt_sampler.sample_batch()._asdict()
                        state, adv.disc_state, metrics = adv.step(
                            state, adv.disc_state, support, query, label,
                            src, tgt
                        )
                    else:
                        state, metrics = self.train_step(
                            state, support, query, label
                        )
                if self._grad_probe is not None:
                    probe_batch = (support, query, label)
                prev, step = step, step + 1
            if step - last_logged >= window or step >= end_step:
                with span("train/metrics_fetch"):
                    m = jax.device_get(metrics)  # sync point, once per window
                dt = time.monotonic() - t0
                eps_per_s = (step - last_logged) * cfg.batch_size / max(dt, 1e-9)
                # Fused metrics are stacked [S]; report the window mean.
                scalars = {k: float(np.mean(v)) for k, v in m.items()}
                if cfg.nan_inject_step and last_logged < cfg.nan_inject_step <= step:
                    # Telemetry-failure injection (debug knob): corrupt the
                    # LOGGED loss only — the training state is untouched.
                    # Exercises watchdog trip + flight-recorder dump.
                    scalars["loss"] = float("nan")
                self.logger.log(
                    step, "train", episodes_per_s=eps_per_s, **scalars,
                )
                if self._feed is not None:
                    # Per-window feed telemetry (ISSUE 4 satellite): queue
                    # depth, episodes buffered, stall/produce seconds —
                    # obs_report's input-pipeline section reads this.
                    self.logger.log(step, "data", **self._feed.drain_stats())
                if self._comms_record is not None:
                    # Per-window collective bytes (ISSUE 5 satellite) from
                    # the shared ledger arithmetic — obs_report's comms
                    # section headline is wire_mb_per_step.
                    self.logger.log(step, "comms", **self._comms_record)
                if self._roofline_record is not None:
                    # Per-window step-byte arithmetic (ISSUE 6 satellite)
                    # — obs_report's roofline section headline is step_mb.
                    self.logger.log(
                        step, "roofline", **self._roofline_record
                    )
                if self._perf is not None:
                    # Step-time decomposition (ISSUE 11): close the perf
                    # window at this boundary — segments tile [last
                    # observe, now], which includes any eval/checkpoint
                    # spans since then (they get their own named tiles).
                    self._perf.observe_window(step)
                if self._compile_watcher is not None:
                    # First window done = warmup over: from here a seen
                    # fn compiling a NEW shape is a gated steady-state
                    # recompile (serving's warmup()/steady split).
                    self._compile_watcher.arm_steady()
                t0 = time.monotonic()
                last_logged = step
            if (
                self._grad_probe is not None
                and step // cfg.grad_probe_every > prev // cfg.grad_probe_every
            ):
                t_probe = time.monotonic()
                with span("train/grad_probe"):
                    out = jax.device_get(
                        self._grad_probe(state.params, *probe_batch)
                    )
                self.logger.log(
                    step, "health", event="grad_probe", severity="info",
                    **{k: float(v) for k, v in out.items()},
                )
                # Exclude probe wall time (first call includes its jit
                # compile — seconds) from the next episodes/sec window, or
                # the watchdog would read a phantom throughput drop.
                t0 += time.monotonic() - t_probe
            if cfg.fault_step and start_step == 0 and step >= cfg.fault_step:
                # Failure injection (SURVEY.md §5.3): simulate a crash
                # mid-run. Raised BEFORE the val boundary below, so the
                # latest recovery-ring checkpoint predates the fault —
                # exactly the state a real crash leaves behind. Fires only
                # on FRESH runs (start_step == 0): a --resume of the
                # crashed run continues past the fault step instead of
                # looping crash/resume forever.
                raise RuntimeError(
                    f"injected fault at step {step} (--fault_step "
                    f"{cfg.fault_step}); resume with --resume (resumed "
                    f"runs ignore the injection)"
                )
            crossed_val = (
                cfg.val_step
                and step // cfg.val_step > prev // cfg.val_step
            )
            if self.val_sampler is not None and crossed_val:
                if self._materialize is not None:
                    # Catch every table row up to the current step so eval
                    # and the boundary checkpoints see the exact
                    # dense-equivalent table (lazy-embed mode).
                    state = self._materialize(state)
                with span("train/eval", episodes=cfg.val_iter):
                    val_metrics = self.evaluate(
                        state.params, cfg.val_iter, return_metrics=True
                    )
                val_acc = val_metrics["accuracy"]
                # metrics.jsonl carries nota_precision/nota_recall when
                # na_rate > 0 (BASELINE config #5's evaluation depth),
                # and acc_ci95 always (VERDICT weak #8).
                self.logger.log(step, "val", **val_metrics)
                improved = val_acc > self.best_val
                if improved:
                    # Tracked even with no ckpt dir: the divergence guard
                    # below compares against it either way.
                    self.best_val = val_acc
                if self.ckpt is not None:
                    with span("train/checkpoint"):
                        cursor = self._feed_cursor()
                        if improved:
                            self.ckpt.save(step, state, val_acc,
                                           cursor=cursor)
                        # Recovery ring: saved at EVERY val boundary so a
                        # crash on a plateau resumes from here, not the
                        # stale best. In delta mode (ckpt_delta) the save
                        # is base + touched-row deltas; the kind="ckpt"
                        # record tracks the byte diet per boundary.
                        self._log_ring_save(
                            step, self.ckpt.save_latest(step, state,
                                                        cursor=cursor)
                        )
                # Divergence guard (SURVEY.md §5.3): the MSE-sigmoid loss
                # can fall into its saturation dead zone on long overfit
                # runs (all scores ~0, gradients vanished, unrecoverable —
                # see config.divergence_guard). Detect the collapse at the
                # val boundary; optionally restore the best checkpoint and
                # end the run instead of burning the remaining steps.
                if self.best_val > self.guard_arm and val_acc < 0.5 * self.best_val:
                    self.logger.log(
                        step, "divergence",
                        val_accuracy=val_acc, best_val=self.best_val,
                    )
                    if cfg.divergence_guard == "stop" and self.ckpt is not None:
                        try:
                            state, best_step = self.ckpt.restore_best(
                                jax.device_get(state)
                            )
                        except FileNotFoundError:
                            best_step = None
                        if self.mesh is not None:
                            state = self.reshard_state(state)
                        # Purge ring slots newer than the restored best:
                        # they hold the dead-zone state, and orbax refuses
                        # re-saves at <= its latest step, so a later
                        # --resume would otherwise restore the collapse.
                        # If no best checkpoint exists (e.g. the async best
                        # save failed), skip the purge — a ring slot with the
                        # collapse is still the only restorable state, and
                        # deleting it would leave the dir empty (advisor
                        # finding, round 2).
                        if best_step is not None:
                            self.ckpt.purge_ring_newer_than(best_step)
                        self.logger.log(
                            step, "divergence_stop",
                            restored_step=float(
                                best_step if best_step is not None else -1
                            ),
                        )
                        diverged_stop = True
                        break
                t0 = time.monotonic()
                last_logged = step
        tracker.set_trace(None)   # end of the last step's trace scope
        if profiling:
            jax.profiler.stop_trace()  # run ended inside the trace window
        if self._materialize is not None and not diverged_stop:
            # The returned state (and the final ring save) must hold the
            # fully caught-up table; a diverged-stop state was restored
            # from a checkpoint and is already materialized.
            state = self._materialize(state)
        if self.ckpt is not None:
            if not diverged_stop:
                # Final ring save (no-op if the last val boundary already
                # wrote this step): --resume continues from the end of this
                # run. force=True — the adaptive in-flight skip must not
                # drop the run's terminal state. Skipped after a divergence
                # stop — the returned state is the restored BEST (an
                # earlier step), and stamping it with the diverged run's
                # step number would corrupt resume ordering.
                self._log_ring_save(
                    step, self.ckpt.save_latest(
                        step, state, force=True, cursor=self._feed_cursor()
                    )
                )
            # Saves are async (off the val-boundary critical path); the
            # run's contract is that returning implies durable checkpoints.
            self.ckpt.wait()
        return state

    def _feed_cursor(self) -> dict | None:
        """The input-pipeline cursor to ride in a checkpoint (None when the
        train sampler is not a PipelineFeed — pre-datapipe wiring)."""
        if self._feed is None:
            return None
        return self._feed.cursor_state().to_dict()

    def restore_feed_cursor(self, mngr, step: int) -> bool:
        """Reposition the feed from the cursor saved with ``step`` in
        ``mngr`` (a CheckpointManager). Returns whether a cursor was found;
        layout/stream mismatches raise (datapipe/cursor.py). Called by the
        CLI on --resume after the state restore."""
        if self._feed is None:
            return False
        cur = mngr.load_cursor(step)
        if cur is None:
            return False
        from induction_network_on_fewrel_tpu.datapipe.cursor import (
            PipelineCursor,
        )

        self._feed.restore_cursor(PipelineCursor.from_dict(cur))
        return True

    def _log_ring_save(self, step: int, info: dict | None) -> None:
        """kind="ckpt" telemetry for ring saves (train/checkpoint.py
        save_latest's info dict): mode full/base/delta, payload bytes, and
        changed-row count for deltas — the observable form of the delta
        byte diet (tools/obs_report.py renders a ckpt section from it).
        None = the save was deduped/skipped; nothing to record."""
        if info is None:
            return
        extra = {"rows": float(info["rows"])} if "rows" in info else {}
        self.logger.log(
            step, "ckpt", event="ring_save", mode=info["mode"],
            bytes=float(info["bytes"]), **extra,
        )

    def close(self) -> None:
        """Release the checkpoint manager's saver thread + atexit handle and
        any native sampler handles. Safe to call repeatedly; trainers used
        as context-free objects in tests should call this to avoid leaking
        one thread / C++ handle per instance (advisor finding, round 2)."""
        if self.ckpt is not None:
            self.ckpt.close()
        for s in (self.train_sampler, self.val_sampler):
            if hasattr(s, "close"):
                s.close()
        if self._perf is not None:
            self._perf.close()          # gc.callbacks meter
        if self._compile_watcher is not None:
            self._compile_watcher.uninstall()
        self.logger.close()  # persistent metrics.jsonl handle

    def evaluate(self, params, num_episodes: int, sampler=None,
                 return_metrics: bool = False):
        """Mean episode accuracy over ``num_episodes`` episodes.

        ``return_metrics=True`` returns the full metric dict instead of the
        bare float — with ``na_rate > 0`` that adds ``nota_precision`` /
        ``nota_recall`` (aggregated exactly from the per-batch confusion
        fractions: all three share the all-queries denominator)."""
        sampler = sampler or self.val_sampler
        collected: dict[str, list] = {}
        n_batches = max(1, num_episodes // sampler.batch_size)
        it: Iterator = iter(sampler)
        # Right-sized eval fusion width (cfg.eval_steps_per_call; 0 = auto):
        # the TRAINING scan width (e.g. 256) is the wrong unit for a small
        # val split — see the config-field comment. One extra compile per
        # distinct width, paid once.
        spc = self.cfg.eval_steps_per_call or min(self.cfg.steps_per_call, 16)
        remaining = n_batches

        def collect(out):
            for k, v in out.items():
                collected.setdefault(k, []).append(v)

        while remaining > 0:
            # One dispatch per spc-batch group; a short tail pads by
            # repeating the last batch (same compiled shape, padded results
            # sliced off) rather than falling back to per-batch dispatches
            # (each a full tunnel round-trip). Below spc/8 real batches the
            # padded compute would outweigh the saved dispatches — tiny
            # evals keep the per-batch path.
            if self._fused_eval is not None and remaining >= max(1, spc // 8):
                take = min(spc, remaining)
                batches = [
                    batch_to_model_inputs(next(it)) for _ in range(take)
                ]
                batches += [batches[-1]] * (spc - take)
                sup_s, qry_s, lab_s = jax.tree.map(
                    lambda *xs: np.stack(xs), *batches
                )
                out = self._fused_eval(params, sup_s, qry_s, lab_s)  # [S]
                collect({k: v[:take] for k, v in out.items()})
                remaining -= take
            else:
                support, query, label = batch_to_model_inputs(next(it))
                collect(self.eval_step(params, support, query, label))
                remaining -= 1
        arrays = {
            k: np.concatenate(
                [np.atleast_1d(np.asarray(a)) for a in jax.device_get(v)]
            )
            for k, v in collected.items()
        }
        means = {k: float(np.mean(v)) for k, v in arrays.items()}
        if not return_metrics:
            return means["accuracy"]
        metrics = {"accuracy": means["accuracy"]}
        # ±1.96·σ/√n over per-batch accuracy means (VERDICT weak #8): a
        # 95% normal-approximation CI on the reported mean. n is the
        # batch count — the samples ARE batch means, so σ is already the
        # between-batch spread and dividing by √n_batches is the correct
        # standard error of their grand mean.
        accs = arrays["accuracy"]
        metrics["acc_ci95"] = (
            float(1.96 * np.std(accs, ddof=1) / np.sqrt(len(accs)))
            if len(accs) > 1 else 0.0
        )
        if "nota_tp" in means:
            metrics["nota_precision"] = means["nota_tp"] / max(
                means["nota_pred"], 1e-12
            )
            metrics["nota_recall"] = means["nota_tp"] / max(
                means["nota_true"], 1e-12
            )
        return metrics
