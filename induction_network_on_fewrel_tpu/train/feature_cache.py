"""Frozen-encoder feature cache: encode the dataset once, train on features.

The reference's frozen-BERT regime (SURVEY.md §2.1 "BERT encoder":
"frozen-then-finetuned") still runs the full 12-layer forward every step —
gradients stop, FLOPs don't. On TPU that inverts the cost structure: the
frozen backbone dominates the step (~15x the head) while producing the same
features for the same sentence every time. The TPU-native fix is a feature
cache:

1. ``encode_dataset`` — tokenize every instance once and push the whole
   dataset through the jitted encoder in fixed-size batches (one compile,
   MXU-saturating shapes), yielding one ``[M, H]`` feature block per
   relation.
2. ``FeatureEpisodeSampler`` — the ``EpisodeSampler`` twin that samples
   episodes of *feature vectors* (identical episode statistics: N distinct
   relations, disjoint K support / Q query draws, ``na_rate`` NOTA mixing).
3. The episode models take the features as-is: ``FewShotModel.
   encode_episode`` passes pre-encoded arrays straight through, so training
   steps run ONLY the head — and because flax creates parameters lazily,
   ``model.init`` on a feature episode builds a head-only TrainState (no
   110M frozen params in the optimizer state either).

Token-level models (``pair``) score query/support *sentence pairs* through
the backbone and cannot train on per-sentence features.
"""

from __future__ import annotations

from typing import Iterator, NamedTuple

import numpy as np

from induction_network_on_fewrel_tpu.data.fewrel import FewRelDataset


class FeatureEpisodeBatch(NamedTuple):
    """B feature episodes: support [B,N,K,H] f32, query [B,TQ,H], label [B,TQ]."""

    support: np.ndarray
    query: np.ndarray
    label: np.ndarray


class IndexEpisodeBatch(NamedTuple):
    """B index episodes: rows into the flat feature table.

    support_idx [B,N,K] int32, query_idx [B,TQ] int32, label [B,TQ] int32.
    ~1 KB per batch vs ~500 KB of materialized features — the H2D transfer
    drops 500x and the gather runs on device (make_cached_train_step).
    """

    support_idx: np.ndarray
    query_idx: np.ndarray
    label: np.ndarray


def make_encode_fn(model):
    """One jitted ``(params, word, pos1, pos2, mask) -> [M, H]`` encoder.

    Build this ONCE and pass it to every ``encode_dataset`` call — each call
    would otherwise define a fresh jit wrapper and recompile the backbone
    per dataset split. params is a jit ARGUMENT, not a closure: closed-over
    arrays bake into the program as constants, and a bert-base-sized
    constant blob blows past the compile-RPC payload limit on tunneled
    backends.
    """
    import jax

    from induction_network_on_fewrel_tpu.models.base import FewShotModel

    @jax.jit
    def encode(p, word, pos1, pos2, mask):
        return model.apply(
            p, word, pos1, pos2, mask, method=FewShotModel.encode
        )

    return encode


def encode_dataset(
    model,
    params,
    dataset: FewRelDataset,
    tokenizer,
    batch_size: int = 256,
    encode_fn=None,
) -> list[np.ndarray]:
    """Encode every instance of every relation once; [M_rel, H] per relation.

    One fixed ``[batch_size, L]`` compile serves the whole sweep (the last
    chunk is padded then sliced), so the cache build costs a single encoder
    compilation plus ceil(total/batch_size) MXU-dense forward passes. Pass
    the same ``encode_fn`` (from ``make_encode_fn``) across calls to reuse
    the compilation between dataset splits.
    """
    import functools

    encode = functools.partial(
        encode_fn if encode_fn is not None else make_encode_fn(model), params
    )

    toks, rel_sizes = [], []
    for rel in dataset.rel_names:
        insts = dataset.instances[rel]
        rel_sizes.append(len(insts))
        toks.extend(tokenizer(inst) for inst in insts)
    word = np.stack([t.word for t in toks])
    pos1 = np.stack([t.pos1 for t in toks])
    pos2 = np.stack([t.pos2 for t in toks])
    mask = np.stack([t.mask for t in toks])

    total = word.shape[0]
    feats = []
    for lo in range(0, total, batch_size):
        hi = min(lo + batch_size, total)
        pad = batch_size - (hi - lo)
        sl = lambda a: (
            np.concatenate([a[lo:hi], np.repeat(a[hi - 1 : hi], pad, 0)])
            if pad else a[lo:hi]
        )
        out = np.asarray(
            encode(sl(word), sl(pos1), sl(pos2), sl(mask)), np.float32
        )
        feats.append(out[: hi - lo])
    flat = np.concatenate(feats)

    blocks, off = [], 0
    for m in rel_sizes:
        blocks.append(flat[off : off + m])
        off += m
    return blocks


class FeatureEpisodeSampler:
    """``EpisodeSampler`` over precomputed per-relation feature blocks.

    Same episode statistics as sampling/episodes.py (N distinct relations,
    disjoint K+Q draws per class, NOTA negatives from outside relations at
    ``na_rate``, shuffled queries) — the per-episode work drops to float32
    row gathers.
    """

    def __init__(
        self,
        blocks: "list[np.ndarray] | list[int]",
        n: int,
        k: int,
        q: int,
        batch_size: int = 1,
        na_rate: int = 0,
        seed: int = 0,
        return_indices: bool = False,
    ):
        """``blocks`` is either per-relation feature arrays, or — for pure
        index sampling against an external table (train/token_cache.py) —
        per-relation ROW COUNTS, which forces ``return_indices`` mode (there
        is nothing here to gather from)."""
        from induction_network_on_fewrel_tpu.sampling.episodes import (
            check_episode_feasibility,
        )

        sizes_only = isinstance(blocks[0], (int, np.integer))
        sizes = (
            [int(b) for b in blocks] if sizes_only
            else [b.shape[0] for b in blocks]
        )
        check_episode_feasibility(sizes, n, k, q, na_rate)
        self.sizes = sizes
        self.n, self.k, self.q = n, k, q
        self.batch_size, self.na_rate = batch_size, na_rate
        self.rng = np.random.default_rng(seed)
        # Flat table + per-relation row offsets: index mode samples GLOBAL
        # row ids so the device-resident table (make_cached_train_step) can
        # be gathered with a single take.
        self.return_indices = return_indices or sizes_only
        self.offsets = np.cumsum([0] + sizes[:-1])
        self.table = (
            None if sizes_only else np.concatenate(blocks).astype(np.float32)
        )

    @property
    def total_q(self) -> int:
        return self.n * self.q + self.na_rate * self.q

    def _sample_episode(self):
        """One episode of GLOBAL row indices: ([N,K], [TQ], [TQ]) int32."""
        n, k, q = self.n, self.k, self.q
        rng = self.rng
        rel_ids = rng.choice(len(self.sizes), n, replace=False)

        sup, qry, labels = [], [], []
        for cls, rid in enumerate(rel_ids):
            rows = self.sizes[rid]
            idx = rng.choice(rows, k + q, replace=False) + self.offsets[rid]
            sup.append(idx[:k])
            qry.append(idx[k:])
            labels.extend([cls] * q)

        if self.na_rate > 0:
            outside = np.setdiff1d(np.arange(len(self.sizes)), rel_ids)
            for _ in range(self.na_rate * q):
                rid = int(rng.choice(outside))
                row = int(rng.integers(self.sizes[rid]))
                qry.append(np.asarray([row + self.offsets[rid]]))
                labels.append(n)

        support = np.stack(sup).astype(np.int32)          # [N, K]
        query = np.concatenate(qry).astype(np.int32)      # [TQ]
        label = np.asarray(labels, dtype=np.int32)
        perm = self.rng.permutation(label.shape[0])
        return support, query[perm], label[perm]

    def sample_fused(self, s: int):
        """S stacked index batches (interface twin of
        native.sampler.NativeIndexSampler.sample_fused): (sup [S,B,N,K],
        qry [S,B,TQ], label [S,B,TQ]). Index mode only."""
        if not self.return_indices:
            raise ValueError("sample_fused requires index mode")
        batches = [self.sample_batch() for _ in range(s)]
        return (
            np.stack([b.support_idx for b in batches]),
            np.stack([b.query_idx for b in batches]),
            np.stack([b.label for b in batches]),
        )

    def sample_batch(self):
        eps = [self._sample_episode() for _ in range(self.batch_size)]
        sup_idx = np.stack([e[0] for e in eps])
        qry_idx = np.stack([e[1] for e in eps])
        label = np.stack([e[2] for e in eps])
        if self.return_indices:
            return IndexEpisodeBatch(sup_idx, qry_idx, label)
        return FeatureEpisodeBatch(
            self.table[sup_idx], self.table[qry_idx], label
        )

    def __iter__(self) -> Iterator:
        while True:
            yield self.sample_batch()

    # --- datapipe cursor protocol (exact RNG-state resume) ---------------

    def feed_state(self) -> dict:
        from induction_network_on_fewrel_tpu.datapipe.cursor import (
            rng_feed_state,
        )

        return rng_feed_state(self.rng)

    def restore_feed_state(self, state: dict) -> None:
        from induction_network_on_fewrel_tpu.datapipe.cursor import (
            restore_rng_feed_state,
        )

        restore_rng_feed_state(self.rng, state)


# --- cached steps: device-resident table, index-only transfer --------------
#
# The table is a jit ARGUMENT (a device-committed jax.Array the caller
# device_puts once), never a closure: closed-over arrays bake into the
# program as constants and a real-dataset table (tens of MB) would blow the
# compile-RPC payload on tunneled backends. Per step only [B,N,K]+[B,TQ]
# int32 indices cross host->device; the feature gather is one take() on
# device feeding the episode head directly.


def make_cached_train_step(model, cfg, mesh=None, state_example=None):
    """jitted (state, table [M,H], sup_idx, qry_idx, label) -> (state, metrics).

    ``mesh``: optional — shards the episode axis over 'dp' and replicates
    the table; state follows parallel.sharding.state_shardings (requires
    ``state_example`` for the pytree metadata).
    """
    import jax

    from induction_network_on_fewrel_tpu.train.steps import make_update_body

    body = make_update_body(model, cfg)

    def step(state, table, sup_idx, qry_idx, label):
        return body(state, (table[sup_idx], table[qry_idx], label))

    if mesh is None:
        return jax.jit(step, donate_argnums=(0,))
    return _shard_cached(
        step, mesh, state_example, zero_opt=getattr(cfg, "zero_opt", False)
    )


def make_cached_multi_train_step(model, cfg, mesh=None, state_example=None):
    """steps_per_call twin: scan S stacked index batches against one table."""
    import jax

    from induction_network_on_fewrel_tpu.train.steps import make_update_body

    body = make_update_body(model, cfg)

    def multi_step(state, table, sup_idx_s, qry_idx_s, label_s):
        def scan_body(st, xs):
            si, qi, lab = xs
            return body(st, (table[si], table[qi], lab))

        return jax.lax.scan(scan_body, state, (sup_idx_s, qry_idx_s, label_s))

    if mesh is None:
        return jax.jit(multi_step, donate_argnums=(0,))
    return _shard_cached(
        multi_step, mesh, state_example, stacked=True,
        zero_opt=getattr(cfg, "zero_opt", False),
    )


def make_cached_eval_step(model, cfg, mesh=None, state_example=None):
    """jitted (params, table, sup_idx, qry_idx, label) -> metrics dict."""
    import jax

    step = _eval_batch_metrics(model, cfg)

    if mesh is None:
        return jax.jit(step)
    return _shard_cached(step, mesh, state_example, params_only=True, cfg=cfg)


def _eval_batch_metrics(model, cfg):
    """The per-batch cached eval body — ONE source for the single-dispatch
    eval step and its lax.map fused twin, so their metrics cannot drift."""
    from induction_network_on_fewrel_tpu.models.losses import episode_metrics
    from induction_network_on_fewrel_tpu.train.steps import LOSS_FNS

    def metrics(params, table, sup_idx, qry_idx, label):
        logits = model.apply(params, table[sup_idx], table[qry_idx])
        return {
            "loss": LOSS_FNS[cfg.loss](logits, label),
            **episode_metrics(logits, label, cfg.na_rate > 0),
        }

    return metrics


def make_cached_multi_eval_step(model, cfg, mesh=None, state_example=None):
    """Fused cached eval: ONE dispatch scores S stacked index batches via
    ``lax.map`` (params fixed, batches independent) — per-dispatch latency
    dominates cached eval otherwise (each eval batch is a full tunnel
    round-trip; at the default val_iter this was hundreds of dispatches
    per val boundary). (params, table, sup_s [S,B,N,K], qry_s [S,B,TQ],
    lab_s [S,B,TQ]) -> metrics stacked [S]."""
    import jax

    body = _eval_batch_metrics(model, cfg)

    def multi(params, table, sup_s, qry_s, lab_s):
        return jax.lax.map(
            lambda xs: body(params, table, *xs), (sup_s, qry_s, lab_s)
        )

    if mesh is None:
        return jax.jit(multi)
    return _shard_cached(
        multi, mesh, state_example, stacked=True, params_only=True, cfg=cfg
    )


def _shard_cached(fn, mesh, state_example, stacked=False, params_only=False,
                  cfg=None, zero_opt=False):
    """jit ``fn`` with cached-path shardings: state per the standard rules,
    table replicated, index/label episode axis over 'dp'."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from induction_network_on_fewrel_tpu.parallel.sharding import (
        state_shardings,
    )

    if state_example is None:
        raise ValueError("mesh-sharded cached steps need state_example")
    repl = NamedSharding(mesh, P())
    dp2 = NamedSharding(mesh, P("dp", None))
    dp3 = NamedSharding(mesh, P("dp", None, None))
    if stacked:  # leading scan axis S is never partitioned
        dp2 = NamedSharding(mesh, P(None, "dp", None))
        dp3 = NamedSharding(mesh, P(None, "dp", None, None))

    from induction_network_on_fewrel_tpu.models.losses import metric_keys

    st_sh = state_shardings(state_example, mesh, zero_opt=zero_opt)
    # Eval metric dicts grow NOTA keys when na_rate > 0 (losses.metric_keys);
    # train paths pass cfg=None and keep the base shape.
    keys = metric_keys(cfg) if cfg is not None else ("loss", "accuracy")
    metric_sh = {k: repl for k in keys}
    if params_only:
        return jax.jit(
            fn,
            in_shardings=(st_sh.params, repl, dp3, dp2, dp2),
            out_shardings=metric_sh,
        )
    return jax.jit(
        fn,
        in_shardings=(st_sh, repl, dp3, dp2, dp2),
        out_shardings=(st_sh, metric_sh),
        donate_argnums=(0,),
    )
