"""induction_network_on_fewrel_tpu — TPU-native few-shot relation classification.

A from-scratch JAX/XLA/Flax framework with the capability surface of the
reference PyTorch repo ``wws0815/Induction-Network-on-FewRel`` (see
/root/repo/SURVEY.md — the reference mount was empty, so parity is pinned to
SURVEY.md §0/§2 capability rows rather than file:line citations):

* Sentence encoders: CNN, BiLSTM + structured self-attention, BERT-base.
* Induction module: squash + dynamic-routing (fixed-trip ``lax.fori_loop``).
* Relation module: neural-tensor network scorer.
* Episodic N-way K-shot sampling with NA/NOTA mixing (FewRel 2.0).
* Training framework: jit + vmap-over-episodes on one chip, data-parallel
  ``shard_map``/NamedSharding over a ``jax.sharding.Mesh`` across chips.

Everything is designed TPU-first: static shapes, batched einsums onto the MXU,
``lax.scan``/``fori_loop`` control flow, XLA collectives over ICI — no CUDA,
no DataParallel, no NCCL.
"""

__version__ = "0.1.0"

from induction_network_on_fewrel_tpu.config import ExperimentConfig  # noqa: F401
