"""ctypes wrapper over the native episodic sampler + prefetch pipeline.

Drop-in replacement for ``sampling.EpisodeSampler`` (same ``EpisodeBatch``
output contract, same episode semantics — verified against it in
tests/test_native.py). Two modes:

* direct — each ``sample_batch()`` call fills numpy buffers synchronously
  in C++ (still ~10× the Python sampler's throughput);
* prefetch — a C++ thread pool keeps a ring buffer of ready batches so
  host-side assembly fully overlaps the device step. Batch ``i`` is a pure
  function of ``(seed, i)``, so the stream is deterministic for any thread
  count.
"""

from __future__ import annotations

import ctypes

import numpy as np

from induction_network_on_fewrel_tpu.data.fewrel import FewRelDataset
from induction_network_on_fewrel_tpu.data.tokenizer import GloveTokenizer
from induction_network_on_fewrel_tpu.native.lib import (
    NativeUnavailable,
    load_native_lib,
    native_available,
)
from induction_network_on_fewrel_tpu.sampling.episodes import (
    EpisodeBatch,
    EpisodeSampler,
    check_episode_feasibility,
)


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


class NativeEpisodeSampler:
    """Episodic sampler backed by native/episode_sampler.cpp."""

    def __init__(
        self,
        dataset: FewRelDataset,
        tokenizer: GloveTokenizer,
        n: int,
        k: int,
        q: int,
        batch_size: int = 1,
        na_rate: int = 0,
        seed: int = 0,
        prefetch: int = 0,       # ring-buffer depth; 0 = synchronous
        num_threads: int = 2,
    ):
        check_episode_feasibility(
            [len(dataset.instances[r]) for r in dataset.rel_names],
            n, k, q, na_rate, names=dataset.rel_names,
        )
        self._lib = load_native_lib()
        self.n, self.k, self.q = n, k, q
        self.batch_size, self.na_rate = batch_size, na_rate
        L = tokenizer.max_length

        # Tokenize the corpus once into flat [total, L] blocks (same
        # preprocessing as the Python sampler; per-episode work is pure
        # row copies on the C++ side).
        words, pos1, pos2, mask = [], [], [], []
        offsets = [0]
        for rel in dataset.rel_names:
            insts = dataset.instances[rel]
            for inst in insts:
                t = tokenizer(inst)
                words.append(t.word)
                pos1.append(t.pos1)
                pos2.append(t.pos2)
                mask.append(t.mask)
            offsets.append(len(words))

        # Keep alive: the C++ sampler borrows these buffers.
        self._words = np.ascontiguousarray(np.stack(words), dtype=np.int32)
        self._pos1 = np.ascontiguousarray(np.stack(pos1), dtype=np.int32)
        self._pos2 = np.ascontiguousarray(np.stack(pos2), dtype=np.int32)
        self._mask = np.ascontiguousarray(np.stack(mask), dtype=np.float32)
        self._offsets = np.asarray(offsets, dtype=np.int64)

        self._handle = self._lib.inf_sampler_create(
            _ptr(self._words, ctypes.c_int32),
            _ptr(self._pos1, ctypes.c_int32),
            _ptr(self._pos2, ctypes.c_int32),
            _ptr(self._mask, ctypes.c_float),
            _ptr(self._offsets, ctypes.c_int64),
            dataset.num_relations, L, n, k, q, na_rate, batch_size,
            ctypes.c_uint64(seed),
        )
        self._pipeline = None
        self._prefetch, self._num_threads = prefetch, num_threads
        # Stream position mirror (datapipe cursor): the C++ pipeline pulls
        # by its own sequence counter, so the Python wrapper tracks the
        # consumed position uniformly for both modes.
        self._pos = 0
        if prefetch > 0:
            if num_threads < 1:
                raise ValueError(
                    f"prefetch={prefetch} needs num_threads >= 1 "
                    f"(got {num_threads}); a zero-worker pipeline would "
                    f"block forever on the first sample_batch()"
                )
            self._pipeline = self._lib.inf_pipeline_create(
                self._handle, prefetch, num_threads
            )

        TQ = self.total_q
        self._out_shapes = dict(
            support=(batch_size, n, k, L), query=(batch_size, TQ, L),
            label=(batch_size, TQ),
        )

    @property
    def total_q(self) -> int:
        return self.n * self.q + self.na_rate * self.q

    def sample_batch(self) -> EpisodeBatch:
        s, qs, ls = (
            self._out_shapes["support"],
            self._out_shapes["query"],
            self._out_shapes["label"],
        )
        sup = [np.empty(s, np.int32) for _ in range(3)] + [np.empty(s, np.float32)]
        qry = [np.empty(qs, np.int32) for _ in range(3)] + [np.empty(qs, np.float32)]
        label = np.empty(ls, np.int32)
        args = (
            _ptr(sup[0], ctypes.c_int32), _ptr(sup[1], ctypes.c_int32),
            _ptr(sup[2], ctypes.c_int32), _ptr(sup[3], ctypes.c_float),
            _ptr(qry[0], ctypes.c_int32), _ptr(qry[1], ctypes.c_int32),
            _ptr(qry[2], ctypes.c_int32), _ptr(qry[3], ctypes.c_float),
            _ptr(label, ctypes.c_int32),
        )
        if self._pipeline is not None:
            self._lib.inf_pipeline_next(self._pipeline, *args)
        else:
            self._lib.inf_sampler_sample(self._handle, *args)
        self._pos += 1
        return EpisodeBatch(*sup, *qry, label)

    def __iter__(self):
        while True:
            yield self.sample_batch()

    # --- datapipe cursor protocol (batch i is pure in (seed, i)) ---------

    def feed_state(self) -> dict:
        return {"kind": "native", "next": int(self._pos)}

    def restore_feed_state(self, state: dict) -> None:
        pos = int(state["next"])
        self._pos = pos
        self._lib.inf_sampler_set_next(self._handle, pos)
        if self._pipeline is not None:
            # The C++ prefetch pipeline pulls by its own sequence counter;
            # recreate it at the restored position (queued-ahead batches
            # are simply re-produced — never skipped).
            self._lib.inf_pipeline_destroy(self._pipeline)
            self._pipeline = self._lib.inf_pipeline_create_at(
                self._handle, self._prefetch, self._num_threads, pos
            )

    def close(self) -> None:
        if getattr(self, "_pipeline", None) is not None:
            self._lib.inf_pipeline_destroy(self._pipeline)
            self._pipeline = None
        if getattr(self, "_handle", None) is not None:
            self._lib.inf_sampler_destroy(self._handle)
            self._handle = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


class NativeIndexSampler:
    """Index-only episodic sampler for the device-resident cache paths.

    Emits GLOBAL row ids (``sup_idx [B,N,K]``, ``qry_idx [B,TQ]``, labels)
    against a flat table the caller keeps on device — the host-side twin of
    ``train.feature_cache.FeatureEpisodeSampler`` in index mode, backed by
    the C++ sampler (same episode semantics; its own deterministic RNG
    stream, like every native-vs-python sampler pair in this repo).
    ``sample_fused(S)`` fills S batches stacked on a leading axis in one
    C call — the exact layout a steps_per_call-fused dispatch consumes;
    measured ~100x the Python index sampler's episodes/sec, which the
    round-1 bench showed was the flagship bottleneck once token transport
    moved on device.
    """

    def __init__(self, sizes, n, k, q, batch_size=1, na_rate=0, seed=0):
        sizes = [int(s) for s in sizes]
        check_episode_feasibility(sizes, n, k, q, na_rate)
        self._lib = load_native_lib()
        self.n, self.k, self.q = n, k, q
        self.batch_size, self.na_rate = batch_size, na_rate
        self._offsets = np.cumsum([0] + sizes).astype(np.int64)
        # Corpus pointers are NULL: index mode never touches token rows.
        self._handle = self._lib.inf_sampler_create(
            None, None, None, None,
            _ptr(self._offsets, ctypes.c_int64),
            len(sizes), 1, n, k, q, na_rate, batch_size,
            ctypes.c_uint64(seed),
        )

    @property
    def total_q(self) -> int:
        return self.n * self.q + self.na_rate * self.q

    def sample_fused(self, s: int):
        """S stacked batches: (sup [S,B,N,K], qry [S,B,TQ], label [S,B,TQ])."""
        B, TQ = self.batch_size, self.total_q
        sup = np.empty((s, B, self.n, self.k), np.int32)
        qry = np.empty((s, B, TQ), np.int32)
        lab = np.empty((s, B, TQ), np.int32)
        self._lib.inf_sampler_sample_indices(
            self._handle, s,
            _ptr(sup, ctypes.c_int32), _ptr(qry, ctypes.c_int32),
            _ptr(lab, ctypes.c_int32),
        )
        return sup, qry, lab

    # --- datapipe cursor protocol ----------------------------------------

    def feed_state(self) -> dict:
        return {
            "kind": "native",
            "next": int(self._lib.inf_sampler_get_next(self._handle)),
        }

    def restore_feed_state(self, state: dict) -> None:
        self._lib.inf_sampler_set_next(self._handle, int(state["next"]))

    def sample_batch(self):
        from induction_network_on_fewrel_tpu.train.feature_cache import (
            IndexEpisodeBatch,  # deferred: feature_cache imports jax-heavy deps
        )

        sup, qry, lab = self.sample_fused(1)
        return IndexEpisodeBatch(sup[0], qry[0], lab[0])

    def __iter__(self):
        while True:
            yield self.sample_batch()

    def close(self) -> None:
        if getattr(self, "_handle", None) is not None:
            self._lib.inf_sampler_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


def make_index_sampler(
    sizes, n, k, q, batch_size=1, na_rate=0, seed=0, backend: str = "auto"
):
    """Index-sampler factory: ``native`` | ``python`` | ``auto`` (native
    when the toolchain is present, else the numpy FeatureEpisodeSampler)."""
    if backend == "auto":
        backend = "native" if native_available() else "python"
    if backend == "native":
        return NativeIndexSampler(sizes, n, k, q, batch_size, na_rate, seed)
    if backend == "python":
        from induction_network_on_fewrel_tpu.train.feature_cache import (
            FeatureEpisodeSampler,
        )

        return FeatureEpisodeSampler(
            sizes, n, k, q, batch_size=batch_size, na_rate=na_rate, seed=seed
        )
    raise ValueError(f"unknown sampler backend {backend!r}")


def make_sampler(
    dataset,
    tokenizer,
    n,
    k,
    q,
    batch_size=1,
    na_rate=0,
    seed=0,
    backend: str = "auto",
    prefetch: int = 4,
    num_threads: int = 2,
):
    """Sampler factory: ``native`` (C++ prefetching), ``python``, or
    ``auto`` — native when the toolchain is present, else Python."""
    if backend == "auto":
        backend = "native" if native_available() else "python"
    if backend == "native":
        return NativeEpisodeSampler(
            dataset, tokenizer, n, k, q, batch_size, na_rate, seed,
            prefetch=prefetch, num_threads=num_threads,
        )
    if backend == "python":
        return EpisodeSampler(
            dataset, tokenizer, n, k, q, batch_size, na_rate, seed
        )
    raise ValueError(f"unknown sampler backend {backend!r}")
