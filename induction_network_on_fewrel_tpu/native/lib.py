"""Build + load the native episode-sampler shared library.

The C++ source lives at ``native/episode_sampler.cpp`` (repo root). It is
compiled once per source-hash into ``~/.cache/induction_network_tpu/`` and
loaded with ctypes; no pybind11/setuptools machinery is needed for a
C-ABI-only surface (environment has g++ but not pybind11).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from pathlib import Path

_REPO_ROOT = Path(__file__).resolve().parents[2]
_SOURCE = _REPO_ROOT / "native" / "episode_sampler.cpp"
_CACHE_DIR = Path(
    os.environ.get("INDUCTION_TPU_NATIVE_CACHE")
    or Path.home() / ".cache" / "induction_network_tpu"
)

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_error: Exception | None = None


class NativeUnavailable(RuntimeError):
    """The native library could not be built/loaded on this machine."""


def _declare(lib: ctypes.CDLL) -> ctypes.CDLL:
    i32p = ctypes.POINTER(ctypes.c_int32)
    f32p = ctypes.POINTER(ctypes.c_float)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.inf_sampler_create.restype = ctypes.c_void_p
    lib.inf_sampler_create.argtypes = [
        i32p, i32p, i32p, f32p, i64p,
        ctypes.c_int64,  # num_relations
        ctypes.c_int32,  # L
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,  # n, k, q
        ctypes.c_int32, ctypes.c_int32,  # na_rate, batch_size
        ctypes.c_uint64,  # seed
    ]
    lib.inf_sampler_destroy.argtypes = [ctypes.c_void_p]
    batch_args = [ctypes.c_void_p] + [i32p, i32p, i32p, f32p] * 2 + [i32p]
    lib.inf_sampler_sample.argtypes = batch_args
    lib.inf_sampler_sample_indices.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, i32p, i32p, i32p
    ]
    lib.inf_sampler_get_next.restype = ctypes.c_int64
    lib.inf_sampler_get_next.argtypes = [ctypes.c_void_p]
    lib.inf_sampler_set_next.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.inf_pipeline_create.restype = ctypes.c_void_p
    lib.inf_pipeline_create.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32
    ]
    lib.inf_pipeline_create_at.restype = ctypes.c_void_p
    lib.inf_pipeline_create_at.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int64
    ]
    lib.inf_pipeline_next.argtypes = batch_args
    lib.inf_pipeline_destroy.argtypes = [ctypes.c_void_p]
    return lib


def _build() -> Path:
    src = _SOURCE.read_bytes()
    tag = hashlib.sha256(src).hexdigest()[:16]
    out = _CACHE_DIR / f"episode_sampler_{tag}.so"
    if out.exists():
        return out
    _CACHE_DIR.mkdir(parents=True, exist_ok=True)
    tmp = out.with_suffix(f".tmp{os.getpid()}.so")
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        "-o", str(tmp), str(_SOURCE),
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    if proc.returncode != 0:
        raise NativeUnavailable(
            f"g++ failed ({proc.returncode}):\n{proc.stderr[-2000:]}"
        )
    os.replace(tmp, out)  # atomic: concurrent builders race benignly
    return out


def load_native_lib() -> ctypes.CDLL:
    """Build (if needed) and load the library; cached per process."""
    global _lib, _load_error
    with _lock:
        if _lib is not None:
            return _lib
        if _load_error is not None:
            raise NativeUnavailable(str(_load_error)) from _load_error
        try:
            _lib = _declare(ctypes.CDLL(str(_build())))
        except Exception as e:  # noqa: BLE001 — record any failure mode
            _load_error = e
            raise NativeUnavailable(str(e)) from e
        return _lib


def native_available() -> bool:
    try:
        load_native_lib()
        return True
    except NativeUnavailable:
        return False
