"""Native (C++) runtime components, loaded via ctypes.

The reference keeps all host-side work in Python (torch DataLoader workers,
SURVEY.md §2.3 records no native components). On TPU that is the wrong
trade: a single v5e consumes episode batches faster than a Python loop can
assemble them, so batch assembly is native here — ``native/episode_sampler.cpp``
compiled on demand with g++ into a cached shared library.

Everything degrades gracefully: if no C++ toolchain is available the public
constructors raise ``NativeUnavailable`` and callers fall back to the pure
numpy sampler (``sampling/episodes.py``), which is semantically identical.
"""

from induction_network_on_fewrel_tpu.native.lib import (
    NativeUnavailable,
    load_native_lib,
    native_available,
)
from induction_network_on_fewrel_tpu.native.sampler import (
    NativeEpisodeSampler,
    make_sampler,
)

__all__ = [
    "NativeUnavailable",
    "load_native_lib",
    "native_available",
    "NativeEpisodeSampler",
    "make_sampler",
]
