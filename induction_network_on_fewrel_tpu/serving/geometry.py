"""Geometry plane (ISSUE 19): (N, K) episode geometry as a first-class
serving + evaluation axis.

Serving half — **N-tier shape bucketing**. The query-program cache keys
on the resident class matrix's row count (serving/buckets.py): a fleet
whose tenants range from 3 to 40 relations would compile one program
set per distinct N — an unbounded family, defeating the
zero-steady-state-recompile discipline the stack is built on. The fix
mirrors what ``select_bucket`` already does for batch rows: resident
``[N, C]`` class stacks pad UP to a small fixed tier set (default
4/8/16/32/64) with all-zero pad rows, so the cache key becomes
``(n_tier, bucket, resident dtype)`` and the compiled-program count is
bounded by tiers x buckets x dtypes regardless of tenant count.

Why zero pad rows are safe end to end:

* The NTN relation scorer treats the class axis as a BATCH axis (both
  einsums in models/induction.RelationNTN contract over feature dims
  only), so pad rows cannot perturb real-row logits — tiered and
  exact-N programs agree bitwise in f32 (pinned in
  tests/test_geometry.py).
* Verdicts argmax ``row[:n_classes]`` and the NOTA logit is appended
  AFTER the matrix rows, i.e. at ``row[-1]`` for any tier — pad logits
  are structurally outside every verdict, margin, entropy, and NOTA
  calibration read (engine._verdict slices; the -inf mask the design
  calls for is realized as never reading the pad columns at all).
* int8 quantization: zero rows leave the tenant-wide max-abs scale
  unchanged and pass both degenerate-artifact gates (an all-zero pad
  row is not a COLLAPSED row, and its ``|q|.min()`` is 0, not 127) —
  the tiered int8 matrix is exactly the exact-N int8 matrix plus zero
  rows, same scale.

The one model family tiering must refuse: the ``nota_head="stats"``
NOTA head computes max/mean/std over the WHOLE class axis inside the
compiled program, so pad rows would shift its logit. ``supports_tiering``
gates it — such models fall back to exact-N residency (logged).

Eval half — **the paper grid**. Geng et al. 2019 and FewRel 2.0 report
across C-way K-shot, not one point: ``GRID`` names the headline
geometries (5w1s / 5w5s / 10w1s / 10w5s; 1-shot stresses the dynamic
routing hardest — K=1 collapses routing to a single support vector).
tools/scenarios.py evaluates its grid legs through ``grid_key`` /
``parse_grid_key`` so canary floors like ``grid_10w1s`` round-trip the
same spelling.
"""

from __future__ import annotations

import numpy as np

# The default tier ladder: powers of two from the smallest useful
# episode (FewRel's 3-relation toy tenants pad to 4) up past the
# paper's 10-way grid with headroom for production relation inventories
# (a 40-relation tenant lands on 64). Five tiers x five buckets x
# three resident dtypes bounds the whole fleet at 75 compiled query
# programs — vs one family per distinct N unbounded.
DEFAULT_TIERS: tuple[int, ...] = (4, 8, 16, 32, 64)

# The paper's headline (N, K) evaluation grid (PAPER.md pillar 7):
# 5-way 1-shot, the 5w5s flagship, and the 10-way pair FewRel 2.0
# reports. Order is presentation order, not difficulty.
GRID: tuple[tuple[int, int], ...] = ((5, 1), (5, 5), (10, 1), (10, 5))


def parse_tiers(spec) -> tuple[int, ...] | None:
    """Parse a tier-set spec ("4,8,16,32,64") into a validated ascending
    tuple. "off" / "" / None disable tiering (exact-N residency — the
    pre-ISSUE-19 behavior, kept as the loadgen A/B arm). An already-
    parsed tuple/list passes through validation unchanged."""
    if spec is None:
        return None
    if isinstance(spec, (tuple, list)):
        tiers = tuple(int(t) for t in spec)
    else:
        s = str(spec).strip().lower()
        if s in ("", "off", "none"):
            return None
        try:
            tiers = tuple(int(t) for t in s.split(","))
        except ValueError:
            raise ValueError(
                f"geometry_tiers must be comma-separated ints or 'off', "
                f"got {spec!r}"
            ) from None
    if not tiers:
        return None
    if any(t < 1 for t in tiers):
        raise ValueError(f"geometry tiers must be >= 1, got {tiers}")
    if list(tiers) != sorted(set(tiers)):
        raise ValueError(
            f"geometry tiers must be strictly increasing, got {tiers}"
        )
    return tiers


def tiers_spec(tiers: tuple[int, ...] | None) -> str:
    """Inverse of ``parse_tiers`` — the loggable knob spelling."""
    return "off" if not tiers else ",".join(str(t) for t in tiers)


def select_tier(n: int, tiers: tuple[int, ...] = DEFAULT_TIERS) -> int:
    """Smallest tier >= n — the class-axis twin of ``select_bucket``.
    Monotone in n by construction (pinned in tests); raises on n <= 0
    and on overflow past the largest tier (serving callers that want
    the exact-N fallback use ``tier_for``)."""
    if n <= 0:
        raise ValueError(f"class count must be >= 1, got {n}")
    for t in tiers:
        if n <= t:
            return t
    raise ValueError(
        f"{n} classes exceed the largest geometry tier {max(tiers)} — "
        f"extend the tier set or serve this tenant exact-N"
    )


def tier_for(n: int, tiers: tuple[int, ...] | None) -> int:
    """The serving spelling: the tier ``n`` classes pad to, or ``n``
    itself when tiering is off or the tenant overflows the ladder (an
    oversize tenant serves exact-N — correct, just unbounded for that
    one N; callers log it)."""
    if not tiers or n > tiers[-1]:
        return n
    return select_tier(n, tiers)


def pad_class_stack(stack: np.ndarray, tier: int) -> np.ndarray:
    """[N, C] f32 host stack -> [tier, C] with all-zero pad rows
    appended. Zero rows (not repeats, unlike ``pad_rows`` for query
    batches) on purpose: they are invisible to the per-class NTN score,
    leave the int8 tenant scale unchanged, and pass the degenerate-
    artifact gates — see the module doc."""
    n = stack.shape[0]
    if n == tier:
        return stack
    if n > tier:
        raise ValueError(f"cannot pad {n} class rows down to tier {tier}")
    pad = np.zeros((tier - n,) + stack.shape[1:], dtype=stack.dtype)
    return np.concatenate([stack, pad], axis=0)


def program_bound(
    tiers: tuple[int, ...], buckets: tuple[int, ...], n_dtypes: int = 1
) -> int:
    """The compiled-query-program ceiling a tiered fleet can reach:
    tiers x buckets x resident dtypes — the invariant the tier-1 gate
    asserts in-process (a cache exceeding it means some matrix reached
    the data plane un-tiered)."""
    return len(tiers) * len(buckets) * n_dtypes


def supports_tiering(model) -> bool:
    """False for models whose NOTA head reads statistics across the
    class axis inside the compiled program (``nota_head="stats"`` —
    max/mean/std over ALL rows, pads included): padding would shift
    the NOTA logit, so such checkpoints serve exact-N."""
    return getattr(model, "nota_head", "scalar") != "stats"


def grid_key(n: int, k: int) -> str:
    """(5, 1) -> "5w1s" — the paper's C-way K-shot spelling, used for
    scenario leg names, canary floors ("grid_5w1s"), and artifact keys."""
    return f"{n}w{k}s"


def parse_grid_key(name: str) -> tuple[int, int] | None:
    """Inverse of ``grid_key``; accepts the bare ("10w5s") and floor
    ("grid_10w5s") spellings. None when ``name`` is not a geometry leg
    — callers fall through to their default-geometry path."""
    s = name[5:] if name.startswith("grid_") else name
    if "w" not in s or not s.endswith("s"):
        return None
    left, right = s.split("w", 1)
    try:
        n, k = int(left), int(right[:-1])
    except ValueError:
        return None
    return (n, k) if n >= 1 and k >= 1 else None
