"""Versioned multi-tenant class-vector registry: support sets -> device-
resident [N, C] class vectors, published as immutable copy-on-write
snapshots.

The induction network distills a registered support set ONCE through
encoder + dynamic routing (``InductionNetwork.class_vectors``) into a [C]
class vector; steady-state serving then never re-encodes supports — each
query is one encoder pass plus the NTN score against the resident matrix.

Fleet semantics (ISSUE 7 / ROADMAP item 1 — the "millions of users" shape):

* **Tenants** — every registration belongs to a named tenant; each tenant
  owns an independent relation set and a per-tenant NOTA threshold
  (Gao et al. 2019's open-world setting is a per-workload knob, not a
  global one). The data plane reads per-tenant ``Snapshot`` objects.
* **Copy-on-write snapshots** — a ``Snapshot`` is immutable: names, slot
  ids, the stacked device matrix, the params it scores against, and the
  NOTA threshold, stamped with a monotonic version. Every mutation
  (register/unregister/threshold/publish) builds a NEW snapshot; the
  previous one stays valid for as long as anyone holds it, so in-flight
  batches finish on the exact (params, matrix, names) they started with.
  Mutations that do not touch membership (thresholds) share the parent's
  device matrix outright — copy-on-write, not copy-on-publish.
* **Shared resident slot pool** — distilled vectors live in one process-
  wide pool keyed by (params_version, support-row digest): two tenants
  registering the same support rows share one slot (distilled once,
  resident once); snapshots reference slots by id.
* **Lock-free data plane** — ``snapshot(tenant)`` is a GIL-atomic dict
  read of an immutable object: queries NEVER wait on the control-plane
  lock, no matter how long a registration or publish is running.
* **Atomic hot-swap publish** — ``publish_params(new_params)`` re-distills
  every live slot with the new weights and swaps every tenant's snapshot
  plus the registry's params in one control-plane transaction. Query
  programs take params and the class matrix as ARGUMENTS
  (serving/buckets.py), so a publish triggers ZERO recompiles; in-flight
  queries complete on their pinned snapshot and the next batch scores on
  the new weights — zero dropped queries by construction.

Registration is not the hot path, but it still respects the static-shape
discipline: every support set is normalized to exactly K shots (cycle-pad
when fewer arrive, truncate when more), so all registrations share ONE
compiled program per source shape instead of compiling per ragged K.
Corpus-backed registration (``register_dataset``) reuses the training
stack's token cache tokenization (train/token_cache.tokenize_dataset) —
including its compact position-offset form, which the shared encoder path
already understands — so a FewRel-schema support corpus registers through
the exact code the trainer feeds from.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
from functools import partial
from typing import Any

import numpy as np

from induction_network_on_fewrel_tpu.config import RESIDENT_DTYPE_CHOICES
from induction_network_on_fewrel_tpu.obs.spans import span
from induction_network_on_fewrel_tpu.serving.buckets import (
    QUERY_DTYPES,
    RESIDENT_DTYPES,
)
from induction_network_on_fewrel_tpu.serving.geometry import (
    pad_class_stack,
    supports_tiering,
    tier_for,
    tiers_spec,
)

DEFAULT_TENANT = "default"


class PublishError(RuntimeError):
    """A publish transaction was refused (validation gate) or failed
    mid-flight and rolled back: the registry generation is UNCHANGED and
    every tenant still serves its pre-publish snapshot. The caller's
    artifact is bad, the fleet is fine."""


class QuantArtifactError(ValueError):
    """int8 quantization of a tenant's class matrix produced a degenerate
    artifact (a row collapsed to all-zero under the tenant scale, or a
    fully saturated row): the same never-becomes-resident discipline as
    the NaN'd-artifact gate — registration is refused, a publish rolls
    back, and an operator re-quantization quarantines the tenant."""


def quantize_int8(stack: np.ndarray) -> tuple[np.ndarray, np.float32]:
    """[N, C] f32 host stack -> (int8 matrix, per-tenant symmetric f32
    scale). One scalar scale per tenant (max-abs / 127): the scale rides
    into the compiled program as an ARGUMENT, so re-quantizing never
    recompiles, and symmetric quantization needs no zero-point."""
    amax = float(np.max(np.abs(stack))) if stack.size else 0.0
    scale = np.float32(amax / 127.0) if amax > 0.0 else np.float32(1.0)
    q = np.clip(np.rint(stack / scale), -127, 127).astype(np.int8)
    return q, scale


def quant_artifact(stack: np.ndarray, q: np.ndarray) -> str | None:
    """Reason string when the int8 form of ``stack`` carries a degenerate
    artifact, else None. Two failure shapes (ISSUE 18 satellite): a class
    row whose magnitudes collapse to all-zero under the TENANT-wide scale
    (one outlier row eating the dynamic range of the others), and a fully
    saturated row (every element pinned at ±127 — an overflowed or
    corrupt source)."""
    for i in range(q.shape[0]):
        if np.abs(q[i]).max() == 0 and np.abs(stack[i]).max() > 0.0:
            return (
                f"int8 dynamic-range collapse: class row {i} quantized to "
                f"all-zero under the tenant scale"
            )
        if np.abs(q[i]).min() >= 127:
            return f"int8 overflow: class row {i} fully saturated"
    return None


@dataclasses.dataclass(frozen=True)
class Snapshot:
    """One tenant's published serving state — immutable, so holding a
    reference IS pinning it: the executing batch resolves verdicts against
    exactly this (params, matrix, names, threshold) even while newer
    versions publish underneath."""

    tenant: str
    version: int            # registry-wide monotonic publish counter
    params_version: int     # bumped by publish_params hot-swaps
    names: tuple[str, ...]
    slots: tuple[int, ...]  # slot-pool ids, parallel to names
    matrix: Any             # [N, C] float32 device array
    params: Any             # the weights this snapshot scores against
    nota_threshold: float | None = None
    k: int = 5
    # Degraded mode (ISSUE 12): a quarantined tenant's snapshot. The
    # data plane serves open-set-floor NOTA verdicts flagged
    # ``degraded=True`` instead of scoring against a suspect matrix —
    # zero device time, honest answers. Cleared by unquarantine or by
    # the next successful publish (a committed generation re-validates
    # every vector).
    degraded: bool = False
    # Quantized residency (ISSUE 18). ``matrix`` above is the RESIDENT
    # form — f32, bf16, or per-tenant-scaled symmetric int8; ``scale`` is
    # the int8 dequant scale (f32 scalar, passed into the compiled
    # program as an argument). ``shadow`` keeps the f32 host stack for
    # quantized tenants — the parity police's reference matrix (host
    # RAM, deliberately NOT counted as resident bytes).
    resident_dtype: str = "f32"
    scale: Any = None
    shadow: Any = None

    @property
    def n_classes(self) -> int:
        return len(self.names)

    @property
    def n_tier(self) -> int:
        """Row count of the RESIDENT matrix — the tier ``n_classes``
        padded up to (ISSUE 19), or ``n_classes`` itself under exact-N
        residency. The program-cache key's class axis; the NOTA logit
        sits at row index ``n_tier`` in every scored row (i.e. at
        ``row[-1]``)."""
        return int(self.matrix.shape[0])

    def index_of(self, name: str) -> int:
        return self.names.index(name)


@dataclasses.dataclass
class _Slot:
    """One resident class vector + the normalized support rows it was
    distilled from (kept so a params hot-swap can re-distill every live
    slot without the original corpus in hand)."""

    vec: np.ndarray                      # [C] float32 host copy
    rows: list[dict[str, np.ndarray]]    # exactly K tokenized shots
    digest: str


class TenantRegistry:
    """Named support sets distilled to class vectors, resident on device,
    versioned per tenant.

    Control plane (register/unregister/threshold/publish/clone) mutates
    under one lock, but the DISTILL device compute runs OUTSIDE it
    (ISSUE 11, paying down the BASELINE round-10 scale follow-up): a
    registration plans its cache misses under the lock, releases it for
    the device pass, then re-acquires and COMMITS with a params_version
    re-validation — a publish that raced the distill invalidates it and
    the registration re-distills against the new weights, so a committed
    snapshot can never mix old-params vectors with a new params_version
    (pinned in tests/test_serving_fleet.py::
    test_publish_vs_register_consistency). Publishes serialize among
    themselves on a dedicated ``_publish_serial`` lock held across their
    snapshot -> distill -> swap cycle; registrations only contend for
    the short plan/commit critical sections, so mass onboarding no
    longer queues behind a republish's device time. The data plane
    (``snapshot``) is a lock-free read of an immutable object.
    ``ClassVectorRegistry`` below is the single-tenant spelling of the
    same object (every method defaults to the "default" tenant), kept so
    pre-fleet callers and the simple CLI keep working.
    """

    def __init__(self, model, params, tokenizer, k: int = 5, logger=None,
                 resident_dtype: str = "f32",
                 tiers: tuple[int, ...] | None = None):
        import jax

        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if resident_dtype not in RESIDENT_DTYPE_CHOICES:
            raise ValueError(
                f"resident_dtype must be one of {RESIDENT_DTYPE_CHOICES}, "
                f"got {resident_dtype!r}"
            )
        self._model, self.params, self._tok, self.k = model, params, tokenizer, k
        self._logger = logger
        # Geometry plane (ISSUE 19): the N-tier ladder published class
        # matrices pad up to, or None for exact-N residency. A model
        # whose NOTA head reads stats across the class axis would see
        # pad rows shift its logit — such checkpoints force exact-N
        # (serving/geometry.supports_tiering), logged once here.
        self.tiers = tuple(tiers) if tiers else None
        if self.tiers is not None and not supports_tiering(model):
            if logger is not None:
                logger.log(
                    0, kind="serve", event="geometry_tiers_disabled",
                    reason="nota_head=stats reads class-axis statistics",
                    requested=tiers_spec(self.tiers),
                )
            self.tiers = None
        # Quantized residency (ISSUE 18): the registry-wide default dtype
        # for published class matrices plus per-tenant overrides (the
        # parity-alarm rollback path pins a single tenant back to f32
        # while the rest of the replica stays quantized).
        self.resident_dtype = resident_dtype
        self._tenant_dtype: dict[str, str] = {}
        self._lock = threading.Lock()
        # Publishes serialize among themselves here (held across their
        # whole snapshot -> distill -> swap cycle) WITHOUT holding the
        # control-plane lock through the distill — registrations keep
        # flowing during a republish's device time (ISSUE 11).
        self._publish_serial = threading.Lock()
        self._jax = jax
        # Optional pre-swap canary (ISSUE 12): callable(new_params) that
        # RAISES to veto a publish — callers wire the scenario-harness
        # miniature quality floor here, so a candidate that passes
        # finiteness but fails quality still rolls back.
        self.publish_canary = None
        self.params_version = 0
        self._version = 0                 # monotonic snapshot stamp
        self._tenants: dict[str, Snapshot] = {}
        self._pool: dict[int, _Slot] = {}
        self._next_slot = 0
        # Distill cache: (params_version, digest of K support rows) ->
        # slot id. Registering identical supports — same tenant or a
        # different one — reuses the resident vector instead of paying
        # another distill pass.
        self._by_digest: dict[tuple[int, str], int] = {}
        # One jitted distill program shared by every registration (shapes
        # are normalized to [1, n, K, L], so single registrations reuse the
        # n=1 compile and bulk registrations the n=N one).
        self._distill = jax.jit(
            partial(model.apply, method="class_vectors")
        )

    # --- registration (control plane) ------------------------------------

    def _normalize_shots(self, rows: list[dict[str, np.ndarray]]):
        """Cycle-pad/truncate a ragged shot list to exactly K entries."""
        if not rows:
            raise ValueError("support set must contain at least one instance")
        return [rows[i % len(rows)] for i in range(self.k)]

    def register(self, name: str, instances, tenant: str = DEFAULT_TENANT,
                 ) -> np.ndarray:
        """Register (or replace) a class from raw FewRel ``Instance``s;
        returns the distilled [C] class vector (host copy)."""
        rows = [self._tokenized_to_dict(self._tok(i)) for i in instances]
        return self.register_tokens(name, rows, tenant=tenant)

    def register_tokens(
        self, name: str, rows: list[dict[str, np.ndarray]],
        tenant: str = DEFAULT_TENANT,
    ) -> np.ndarray:
        """Register from already-tokenized [L]-leaf dicts (the token-cache
        wire form; position leaves may be compact per-sentence offsets).
        The distill runs OUTSIDE the control-plane lock; the commit
        re-validates params_version (see class doc)."""
        rows = self._normalize_shots(rows)

        def commit(slots: list[int]) -> np.ndarray:
            slot = slots[0]
            snap = self._tenants.get(tenant)
            names = list(snap.names) if snap else []
            cur = list(snap.slots) if snap else []
            if name in names:
                cur[names.index(name)] = slot
            else:
                names.append(name)
                cur.append(slot)
            self._publish_locked(tenant, names, cur)
            # Copy: the pool's array is shared across tenants and stacked
            # into every future publish — the caller must not be able to
            # mutate it.
            return self._pool[slot].vec.copy()

        return self._intern_classes([rows], commit)

    def register_dataset(
        self, dataset, max_classes: int | None = None,
        tenant: str = DEFAULT_TENANT,
    ) -> list[str]:
        """Register every relation of a FewRel dataset, support = its first
        K instances, tokenized ONCE through the training token cache. All
        classes distill in one batched [1, N, K] program call."""
        from induction_network_on_fewrel_tpu.train.token_cache import (
            tokenize_dataset,
        )

        table, sizes = tokenize_dataset(dataset, self._tok)
        names = list(dataset.rel_names)
        if max_classes is not None:
            names, sizes = names[:max_classes], sizes[:max_classes]
        starts = np.concatenate([[0], np.cumsum(sizes)])
        per_class = []
        for ci in range(len(names)):
            rows = [
                {k: v[starts[ci] + r] for k, v in table.items()}
                for r in range(sizes[ci])
            ]
            per_class.append(self._normalize_shots(rows))

        def commit(slots_new: list[int]) -> list[str]:
            snap = self._tenants.get(tenant)
            cur_names = list(snap.names) if snap else []
            cur_slots = list(snap.slots) if snap else []
            for name, slot in zip(names, slots_new):
                if name in cur_names:
                    cur_slots[cur_names.index(name)] = slot
                else:
                    cur_names.append(name)
                    cur_slots.append(slot)
            self._publish_locked(tenant, cur_names, cur_slots)
            return names

        return self._intern_classes(per_class, commit)

    def unregister(self, name: str, tenant: str = DEFAULT_TENANT) -> None:
        with self._lock:
            snap = self._require_locked(tenant)
            i = snap.names.index(name)
            names = [n for j, n in enumerate(snap.names) if j != i]
            slots = [s for j, s in enumerate(snap.slots) if j != i]
            if not names:
                self._drop_tenant_locked(tenant)
                return
            self._publish_locked(tenant, names, slots)

    def drop_tenant(self, tenant: str) -> None:
        with self._lock:
            self._require_locked(tenant)
            self._drop_tenant_locked(tenant)

    def clone_tenant(self, src: str, dst: str) -> Snapshot:
        """Zero-copy fork: ``dst`` starts from ``src``'s exact relation set,
        sharing its slots AND its device matrix (copy-on-write — the clone
        costs two tuples until one of them diverges). An existing ``dst``
        is REPLACED (re-cloning a template over a live tenant is the
        intended reset path); its diverged slots are collected."""
        with self._lock:
            s = self._require_locked(src)
            replaced = self._tenants.get(dst)
            self._version += 1
            snap = dataclasses.replace(
                s, tenant=dst, version=self._version
            )
            self._tenants[dst] = snap
            # The clone inherits src's residency override (or lack of
            # one) so its NEXT republish quantizes the way src does.
            if src in self._tenant_dtype:
                self._tenant_dtype[dst] = self._tenant_dtype[src]
            else:
                self._tenant_dtype.pop(dst, None)
            if replaced is not None and set(replaced.slots) - set(snap.slots):
                self._gc_slots_locked()
            return snap

    def quarantine_tenant(
        self, tenant: str, reason: str = "", _degraded: bool = True,
    ) -> Snapshot:
        """Mark the tenant's snapshot DEGRADED (ISSUE 12): its resident
        vectors are suspect (corrupt source checkpoint, operator call),
        so the data plane stops scoring against them and serves
        open-set-floor NOTA verdicts flagged ``degraded=True`` until an
        unquarantine or the next successful publish. Pure CoW — the
        matrix is kept (evidence, and unquarantine is free)."""
        with self._lock:
            s = self._require_locked(tenant)
            self._version += 1
            snap = dataclasses.replace(
                s, version=self._version, degraded=_degraded
            )
            self._tenants[tenant] = snap
        if self._logger is not None:
            self._logger.log(
                snap.version, kind="fault",
                action=(
                    "tenant_quarantine" if _degraded else "tenant_restore"
                ),
                tenant=tenant, reason=reason or "operator",
            )
        return snap

    def unquarantine_tenant(self, tenant: str, reason: str = "") -> Snapshot:
        return self.quarantine_tenant(tenant, reason=reason, _degraded=False)

    def set_nota_threshold(
        self, threshold: float | None, tenant: str = DEFAULT_TENANT
    ) -> Snapshot:
        """Per-tenant NOTA verdict knob, carried in the snapshot. With a
        trained NOTA head the threshold BIASES the no-relation logit; with
        no head it is an open-set floor on the best class logit (below it
        the verdict is ``no_relation``). Membership is untouched, so the
        new snapshot shares the parent's device matrix — pure CoW."""
        with self._lock:
            s = self._require_locked(tenant)
            self._version += 1
            snap = dataclasses.replace(
                s, version=self._version, nota_threshold=threshold
            )
            self._tenants[tenant] = snap
            return snap

    # --- distill-outside-lock interning (ISSUE 11) ------------------------

    # Plan/commit retries before the correctness escape hatch distills
    # UNDER the lock (guaranteed progress when publishes/registrations
    # churn faster than a device pass completes — pathological, but the
    # loop must terminate).
    _INTERN_RETRIES = 3

    def _intern_classes(self, per_class, commit):
        """Distill-or-reuse each class's K rows with the device pass
        OUTSIDE the control-plane lock, then run ``commit(slots)`` under
        it. The commit re-validates ``params_version``: a publish that
        landed mid-distill invalidates the vectors (they were computed
        against the old weights) and the loop re-plans against the new
        ones — a committed snapshot can never mix generations."""
        digests = [self._digest(rows) for rows in per_class]
        for attempt in range(self._INTERN_RETRIES):
            with self._lock:
                params, pv = self.params, self.params_version
                # Cache misses, deduped within the call (identical
                # digests share one distill row and one slot).
                missing = [
                    i for i, d in enumerate(digests)
                    if (pv, d) not in self._by_digest
                    and i == digests.index(d)
                ]
            vecs = ()
            if missing:
                sup = self._stack_support([per_class[i] for i in missing])
                # The device pass — the whole point: NO lock held here.
                with span("serve/distill", classes=len(missing)):
                    vecs = np.asarray(self._distill(params, sup))[0]
                if not np.isfinite(vecs).all():
                    # A non-finite vector must never become resident:
                    # it would be interned by digest and shared into
                    # every future publish (ISSUE 12 validation).
                    raise ValueError(
                        "registration refused: distilled class vectors "
                        "are non-finite (corrupt weights or poisoned "
                        "supports)"
                    )
            with self._lock:
                if self.params_version != pv:
                    continue    # a publish raced: re-distill on new weights
                for i, vec in zip(missing, vecs):
                    if (pv, digests[i]) in self._by_digest:
                        continue   # a concurrent registration beat us
                    slot = self._next_slot
                    self._next_slot += 1
                    self._pool[slot] = _Slot(
                        vec=vec.astype(np.float32), rows=per_class[i],
                        digest=digests[i],
                    )
                    self._by_digest[(pv, digests[i])] = slot
                if any((pv, d) not in self._by_digest for d in digests):
                    # A cached slot we planned to reuse was GC'd between
                    # plan and commit (concurrent unregister) — re-plan.
                    continue
                return commit([self._by_digest[(pv, d)] for d in digests])
        # Escape hatch: churn outran us — hold the lock through the
        # distill (the pre-ISSUE-11 behavior; correct, briefly blocking).
        with self._lock:
            slots = self._intern_bulk_locked(
                per_class, self.params, self.params_version
            )
            return commit(slots)

    # --- hot-swap publish -------------------------------------------------

    def publish_params(self, new_params) -> int:
        """Atomic hot-swap from a training artifact: re-distill every live
        slot with ``new_params`` and republish every tenant against the new
        weights in one control-plane transaction. Query programs take
        params as an argument, so NOTHING recompiles; queries in flight
        hold their old snapshot (old params, old matrix) and finish
        unperturbed; queries batched after the swap score on the new
        weights. Returns the new params_version.

        The re-distill runs OUTSIDE the control-plane lock (ISSUE 11):
        publishes serialize among themselves on ``_publish_serial``
        (params_version is therefore stable for the duration), snapshot
        the live slot set, distill, then swap under the lock — re-reading
        the live set at swap time: slots a concurrent registration added
        mid-distill are re-distilled in another pass before the swap
        commits, so the published transaction covers EVERY slot live at
        swap time (pinned in tests/test_serving_fleet.py).

        TRANSACTIONAL (ISSUE 12): a pre-swap validation gate (finite
        params, finite distilled vectors, the optional ``publish_canary``
        quality floor) plus a build-then-commit swap — every mutation of
        registry state is staged and applied by plain assignments at the
        very end, so ANY failure (validation veto, a raising distill, an
        injected ``publish.nan_params``/``publish.distill_raise`` fault)
        rolls back to the prior generation: params_version unchanged,
        every tenant on its old snapshot, in-flight batches untouched.
        Failures raise ``PublishError`` and emit one kind="fault"
        record (action="publish_rollback"); the watchdog latches a
        CRITICAL ``publish_rollback``, re-armed by the next committed
        publish."""
        txn = None
        try:
            # Literally prepare+commit: ONE home for the chaos point,
            # the serial-lock acquisition, and the staging logic —
            # fleet fan-outs and single-replica publishes cannot drift.
            txn = self.prepare_publish(new_params)
            return txn.commit()
        except BaseException as e:
            if txn is not None and txn.committed:
                # The COMMIT happened — the exception came from the
                # post-commit telemetry (a raising logger hook, disk
                # full on the jsonl write). The publish is LIVE: do
                # not log a rollback, do not claim one. Re-raise the
                # real error.
                raise
            # Nothing committed (build-then-commit): log the
            # rollback and surface a typed error. The registry
            # generation is unchanged. The version reported is the one
            # captured UNDER the serial lock (txn.version_before) —
            # a pre-lock read could be stale by a concurrent
            # publisher's commit; when prepare itself failed (txn
            # None) the lock has been released, so the live counter
            # is the honest answer.
            version_before = (txn.version_before if txn is not None
                              else self.params_version)
            if self._logger is not None:
                self._logger.log(
                    version_before, kind="fault",
                    action="publish_rollback",
                    reason=f"{type(e).__name__}: {e}",
                    params_version=float(version_before),
                )
            if isinstance(e, PublishError):
                raise
            raise PublishError(
                f"publish rolled back ({type(e).__name__}: {e}); "
                f"registry stays at params_version {version_before}"
            ) from e

    @staticmethod
    def _first_nonfinite(tree) -> str | None:
        """keystr of the first non-finite float leaf, or None."""
        import jax

        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            a = np.asarray(leaf)
            if np.issubdtype(a.dtype, np.floating) and not np.isfinite(
                a
            ).all():
                return jax.tree_util.keystr(path)
        return None

    # --- two-phase publish (fleet fan-out, ISSUE 13) ----------------------

    def prepare_publish(self, new_params,
                        target_version: int | None = None,
                        ) -> "PublishTransaction":
        """Phase 1 of a two-phase publish: acquire the publish-serial
        lock (HELD until ``commit()``/``abort()`` on the returned
        transaction), run the validation gate and every re-distill pass,
        and return the fully-staged transaction. On ANY failure the lock
        is released and the registry is untouched — nothing was staged
        into live state, so an abort-after-prepare-failure is a no-op by
        construction.

        This is the primitive the fleet control plane composes into an
        all-or-nothing fan-out (fleet/control.py): prepare on EVERY
        replica first, then commit everywhere only once every prepare
        succeeded — one replica's validation failure aborts the others
        before any of them moved, so params_version stays uniform across
        the fleet. Single-replica callers keep using ``publish_params``,
        which is now literally prepare+commit in one call.

        The commit phase re-distills only late-registered stragglers
        (bounded: the delta since prepare) and re-validates them; plain
        assignments then publish the generation. The serial lock is a
        plain (ownerless) mutex, so a transaction prepared on one
        thread may be committed/aborted from another — the socket
        transport's server prepares on one connection-handler thread
        and commits/aborts on whichever handler thread the phase-2 op
        arrives on (fleet/transport.py).

        ``target_version`` pins the generation the commit lands at
        (instead of the default ``params_version + 1``) — the recovery
        catch-up primitive (ISSUE 15): a restarted replica whose counter
        reset to 0 re-drives the journaled publish AT the fleet's
        committed version, restoring uniformity instead of forking a
        private version history. It must be ahead of the local counter;
        catching up "backwards" is a logic error, refused here."""
        self._publish_serial.acquire()
        try:
            from induction_network_on_fewrel_tpu.obs.chaos import chaos_fire

            if target_version is not None \
                    and target_version <= self.params_version:
                raise PublishError(
                    f"catch-up target_version {target_version} is not "
                    f"ahead of the local params_version "
                    f"{self.params_version}"
                )
            if chaos_fire("publish.nan_params",
                          step=self.params_version) is not None:
                from induction_network_on_fewrel_tpu.datapipe.faults import (
                    poison_tree,
                )

                new_params = poison_tree(new_params)
            staged = self._prepare_serialized(new_params, target_version)
        except BaseException:
            self._publish_serial.release()
            raise
        return PublishTransaction(self, staged)

    def _prepare_serialized(self, new_params,
                            target_version: int | None = None) -> dict:
        from induction_network_on_fewrel_tpu.obs.chaos import chaos_fire

        # Pre-swap validation gate, part 1 — BEFORE burning device time
        # on distills: a NaN'd artifact (bf16 blowup, corrupt restore)
        # must never reach the shared [N, C] matrix every tenant scores
        # against.
        bad = self._first_nonfinite(new_params)
        if bad is not None:
            raise PublishError(
                f"validation gate: non-finite params at {bad}"
            )
        if self.publish_canary is not None:
            # Optional quality floor (scenario-harness miniature): runs
            # outside every lock; a raise vetoes the publish.
            self.publish_canary(new_params)
        new_version = (int(target_version) if target_version is not None
                       else self.params_version + 1)
        # old slot id -> freshly distilled [C] vector (accumulated across
        # passes; slots never mutate in place, so a vector distilled in
        # pass 1 stays valid for the swap even if pass 2 adds more).
        vec_of: dict[int, np.ndarray] = {}
        # Bounded delta passes: registrations adding slots faster than a
        # device pass completes must not spin this loop forever — after
        # the bound, the swap's under-lock late path mops up the rest.
        for _pass in range(self._INTERN_RETRIES):
            with self._lock:
                live = sorted({
                    s for snap in self._tenants.values() for s in snap.slots
                })
                todo = [s for s in live if s not in vec_of]
                rows_of = {s: self._pool[s].rows for s in todo}
            if not todo:
                break
            # Group by leaf-shape signature: one tenant can mix
            # registration paths (token-cache compact position offsets vs
            # full per-token ids) and mixed forms cannot co-stack. Batched
            # per group so the [1, S, K] distill compiles match
            # registration's. NO lock held through the device pass.
            groups: dict[tuple, list[int]] = {}
            for s in todo:
                sig = tuple(
                    (k, np.shape(v)) for k, v in sorted(rows_of[s][0].items())
                )
                groups.setdefault(sig, []).append(s)
            for slots_g in groups.values():
                if chaos_fire("publish.distill_raise",
                              step=new_version) is not None:
                    from induction_network_on_fewrel_tpu.obs.chaos import (
                        ChaosError,
                    )

                    raise ChaosError(
                        "injected publish distill failure (chaos)"
                    )
                sup = self._stack_support([rows_of[s] for s in slots_g])
                with span("serve/distill", classes=len(slots_g)):
                    vecs = np.asarray(self._distill(new_params, sup))[0]
                for s, vec in zip(slots_g, vecs):
                    vec_of[s] = vec.astype(np.float32)
            # Loop: a registration may have added live slots mid-distill;
            # the next pass picks up exactly the delta.
        return {
            "new_params": new_params,
            "new_version": new_version,
            "vec_of": vec_of,
        }

    def _commit_prepared(self, staged: dict) -> int:
        new_params = staged["new_params"]
        new_version = staged["new_version"]
        vec_of = staged["vec_of"]
        with self._lock:
            # Swap — BUILD-THEN-COMMIT (ISSUE 12): everything below
            # stages into locals; registry state mutates only in the
            # final commit block of plain assignments, so a failure
            # anywhere before it (late distill, validation, device_put)
            # leaves every tenant on its old snapshot and the
            # generation unchanged.
            current = {
                s for snap in self._tenants.values() for s in snap.slots
            }
            if current - set(vec_of):
                # Late registration landed between the last pass and this
                # lock acquisition: distill the stragglers UNDER the lock
                # (bounded: only the delta) rather than looping forever.
                late = sorted(current - set(vec_of))
                for s in late:
                    sup = self._stack_support([self._pool[s].rows])
                    with span("serve/distill", classes=1):
                        vec_of[s] = np.asarray(
                            self._distill(new_params, sup)
                        )[0][0].astype(np.float32)
            # Pre-swap validation gate, part 2: every distilled vector
            # that would become resident must be finite — one NaN'd slot
            # would poison every tenant sharing it.
            for s in sorted(current):
                if not np.isfinite(vec_of[s]).all():
                    raise PublishError(
                        f"validation gate: non-finite distilled class "
                        f"vector for slot {s} "
                        f"(digest {self._pool[s].digest[:12]})"
                    )
            staged_pool: dict[int, _Slot] = {}
            live_map: dict[int, int] = {}   # old slot -> new slot
            by_digest_new: dict[str, int] = {}
            next_slot = self._next_slot
            for s in sorted(current):
                digest = self._pool[s].digest
                if digest in by_digest_new:
                    live_map[s] = by_digest_new[digest]
                    continue
                slot = next_slot
                next_slot += 1
                staged_pool[slot] = _Slot(
                    vec=vec_of[s], rows=self._pool[s].rows, digest=digest,
                )
                by_digest_new[digest] = slot
                live_map[s] = slot
            # Stage every tenant's new snapshot (device_put can raise —
            # still pre-commit). Version stamps pre-assigned; committed
            # as a block below.
            version = self._version
            staged_snaps: dict[str, Snapshot] = {}
            for tenant, snap in self._tenants.items():
                slots = [live_map[s] for s in snap.slots]
                stack = np.stack([staged_pool[by_digest_new[
                    self._pool[s].digest]].vec for s in snap.slots])
                try:
                    matrix, scale, shadow = self._residency(stack, tenant)
                except QuantArtifactError as e:
                    # Same rollback as a non-finite vector: the new
                    # weights produce class vectors this tenant's int8
                    # residency cannot represent — nothing committed.
                    raise PublishError(f"validation gate: {e}") from e
                version += 1
                staged_snaps[tenant] = Snapshot(
                    tenant=tenant, version=version,
                    params_version=new_version,
                    names=snap.names, slots=tuple(slots), matrix=matrix,
                    params=new_params,
                    nota_threshold=snap.nota_threshold, k=self.k,
                    resident_dtype=self.dtype_for(tenant), scale=scale,
                    shadow=shadow,
                )
            # COMMIT — plain assignments only; nothing below can raise.
            self._pool.update(staged_pool)
            for digest, slot in by_digest_new.items():
                self._by_digest[(new_version, digest)] = slot
            self._next_slot = next_slot
            self.params = new_params
            self.params_version = new_version
            self._tenants.update(staged_snaps)
            self._version = version
            self._gc_slots_locked()
            n_tenants, n_slots = len(self._tenants), len(live_map)
        if self._logger is not None:
            self._logger.log(
                new_version, kind="serve", event="snapshot_swap",
                params_version=new_version, tenants=n_tenants,
                slots=n_slots,
            )
        return new_version

    def publish_checkpoint(self, ckpt_dir: str) -> int:
        """Hot-swap from a checkpoint directory (the training run's publish
        path): restore the best/latest weights for THIS architecture and
        ``publish_params`` them into the live registry."""
        return self.publish_params(load_params(ckpt_dir, self._model))

    # --- data plane (lock-free) ------------------------------------------

    def snapshot(self, tenant: str = DEFAULT_TENANT) -> Snapshot:
        """The tenant's current Snapshot — a GIL-atomic dict read; never
        blocks on the control-plane lock. Raises for unknown tenants."""
        snap = self._tenants.get(tenant)
        if snap is None:
            raise ValueError(
                f"no classes registered for tenant {tenant!r} — register "
                "supports first"
            )
        return snap

    def has_tenant(self, tenant: str = DEFAULT_TENANT) -> bool:
        return tenant in self._tenants

    def tenants(self) -> tuple[str, ...]:
        return tuple(self._tenants)

    @property
    def names(self) -> tuple[str, ...]:
        snap = self._tenants.get(DEFAULT_TENANT)
        return snap.names if snap else ()

    def names_for(self, tenant: str) -> tuple[str, ...]:
        return self.snapshot(tenant).names

    def __len__(self) -> int:
        snap = self._tenants.get(DEFAULT_TENANT)
        return len(snap.names) if snap else 0

    def class_matrix(self, tenant: str = DEFAULT_TENANT):
        """Stacked [N, C] float32 device array of the current snapshot."""
        return self.snapshot(tenant).matrix

    def pool_size(self) -> int:
        """Resident slots in the shared pool (across tenants + versions
        still referenced)."""
        return len(self._pool)

    # --- quantized residency (ISSUE 18) -----------------------------------

    def dtype_for(self, tenant: str) -> str:
        """Resident dtype this tenant publishes at: the per-tenant
        override when one is set, else the registry default."""
        return self._tenant_dtype.get(tenant, self.resident_dtype)

    def set_resident_dtype(self, tenant: str, dtype: str) -> Snapshot:
        """Re-quantize a live tenant to ``dtype`` from the f32 slot-pool
        truth and republish (CoW version bump; no re-distill — the pool
        keeps every vector in f32). This is the parity-alarm ROLLBACK
        path (RUNBOOK): roll the tenant to "f32" and its next batch
        scores unquantized. A degenerate int8 artifact reverts the
        override, QUARANTINES the tenant (same guard behavior as the
        NaN'd-artifact gate), and raises QuantArtifactError."""
        if dtype not in RESIDENT_DTYPE_CHOICES:
            raise ValueError(
                f"resident_dtype must be one of {RESIDENT_DTYPE_CHOICES}, "
                f"got {dtype!r}"
            )
        artifact = None
        with self._lock:
            snap = self._require_locked(tenant)
            prev = self._tenant_dtype.get(tenant)
            self._tenant_dtype[tenant] = dtype
            try:
                snap = self._publish_locked(
                    tenant, list(snap.names), list(snap.slots), gc=False
                )
            except QuantArtifactError as e:
                if prev is None:
                    self._tenant_dtype.pop(tenant, None)
                else:
                    self._tenant_dtype[tenant] = prev
                artifact = e
        if artifact is not None:
            self.quarantine_tenant(tenant, reason=str(artifact))
            raise artifact
        if self._logger is not None:
            self._logger.log(
                snap.version, kind="serve", event="resident_dtype",
                tenant=tenant, dtype=dtype,
            )
        return snap

    def resident_bytes(self) -> dict[str, float]:
        """Per-tenant CHIP-resident bytes of the published snapshot: the
        [N, C] matrix in its resident dtype plus the f32 dequant scale.
        Host-side copies (slot pool, parity shadow) spend host RAM, not
        HBM, and are deliberately excluded — this gauge is the density
        denominator the capacity accounting divides by. GIL-atomic.
        Under N-tier residency (ISSUE 19) the matrix shape IS the
        padded [n_tier, C] stack, so capacity accounting prices the
        padding waste honestly by construction."""
        out: dict[str, float] = {}
        for tenant, snap in list(self._tenants.items()):
            nbytes = int(np.dtype(snap.matrix.dtype).itemsize)
            for dim in snap.matrix.shape:
                nbytes *= int(dim)
            if snap.scale is not None:
                nbytes += 4
            out[tenant] = float(nbytes)
        return out

    # --- internals (call with the lock held) ------------------------------

    def _require_locked(self, tenant: str) -> Snapshot:
        snap = self._tenants.get(tenant)
        if snap is None:
            raise ValueError(f"unknown tenant {tenant!r}")
        return snap

    def _drop_tenant_locked(self, tenant: str) -> None:
        del self._tenants[tenant]
        self._tenant_dtype.pop(tenant, None)
        self._gc_slots_locked()

    def tier_of(self, n: int) -> int:
        """The N-tier ``n`` class rows pad to on THIS registry — ``n``
        itself when tiering is off or ``n`` overflows the ladder (the
        oversize tenant serves exact-N: correct, just unbounded for
        that one N)."""
        return tier_for(n, self.tiers)

    def _residency(self, stack: np.ndarray, tenant: str):
        """Stage the RESIDENT form of a stacked [N, C] f32 class matrix
        (ISSUE 18): device_put in the tenant's resident dtype. Returns
        ``(matrix, scale, shadow)`` — scale is the int8 dequant scalar
        (else None), shadow the f32 host stack kept for the parity
        police (else None). Raises QuantArtifactError when int8
        quantization degenerates: a registration refuses, a publish
        rolls back, an operator re-quantization quarantines — a
        degenerate matrix never becomes resident, exactly like the
        NaN'd-artifact gate.

        Geometry plane (ISSUE 19): THE tier-padding insertion point.
        The stack pads to its N-tier with all-zero rows BEFORE any
        dtype conversion — zero rows leave the int8 tenant scale
        unchanged (same real-row quantized values as exact-N) and pass
        both degenerate-artifact gates — so every resident form
        (matrix AND shadow) is tier-shaped and the program cache,
        warmup, parity probe, and resident-bytes accounting all see
        the padded geometry with no further plumbing."""
        tier = self.tier_of(stack.shape[0])
        if tier != stack.shape[0]:
            stack = pad_class_stack(stack, tier)
        dtype = self.dtype_for(tenant)
        if dtype == "f32":
            return self._jax.device_put(stack), None, None
        if dtype == "bf16":
            mat = self._jax.device_put(stack.astype(RESIDENT_DTYPES["bf16"]))
            return mat, None, stack
        q, scale = quantize_int8(stack)
        reason = quant_artifact(stack, q)
        if reason is not None:
            raise QuantArtifactError(
                f"registration refused: {reason} (tenant {tenant!r}; "
                f"degenerate quantization must never become resident)"
            )
        return self._jax.device_put(q), scale, stack

    def _publish_locked(
        self, tenant: str, names: list[str], slots: list[int],
        nota_threshold: float | None = "inherit", gc: bool = True,
    ) -> Snapshot:
        prev = self._tenants.get(tenant)
        if nota_threshold == "inherit":
            nota_threshold = prev.nota_threshold if prev else None
        self._version += 1
        matrix, scale, shadow = self._residency(
            np.stack([self._pool[s].vec for s in slots]), tenant
        )
        snap = Snapshot(
            tenant=tenant, version=self._version,
            params_version=self.params_version,
            names=tuple(names), slots=tuple(slots), matrix=matrix,
            params=self.params, nota_threshold=nota_threshold, k=self.k,
            # A registration on a quarantined tenant does not clear the
            # quarantine — only unquarantine_tenant or a committed
            # publish (which re-validates every vector) does.
            degraded=prev.degraded if prev else False,
            resident_dtype=self.dtype_for(tenant), scale=scale,
            shadow=shadow,
        )
        self._tenants[tenant] = snap
        # GC only when this publish actually DROPPED slot references —
        # pure additions (the common registration path) skip the
        # every-tenant live-set scan entirely.
        if gc and prev is not None and set(prev.slots) - set(slots):
            self._gc_slots_locked()
        return snap

    def _gc_slots_locked(self) -> None:
        """Drop pool slots no CURRENT snapshot references. Pinned older
        snapshots keep working — their matrices are standalone device
        arrays; only the host-side re-distill source is collected."""
        live = {
            s for snap in self._tenants.values() for s in snap.slots
        }
        dead = {s for s in self._pool if s not in live}
        for slot in dead:
            del self._pool[slot]
        if dead:
            for key in [k for k, v in self._by_digest.items() if v in dead]:
                del self._by_digest[key]

    def _digest(self, rows: list[dict[str, np.ndarray]]) -> str:
        h = hashlib.sha1()
        for row in rows:
            for key in sorted(QUERY_DTYPES):
                h.update(key.encode())
                h.update(np.ascontiguousarray(row[key]).tobytes())
        return h.hexdigest()

    def _intern_bulk_locked(
        self, per_class: list[list[dict[str, np.ndarray]]], params,
        params_version: int,
    ) -> list[int]:
        """Distill-or-reuse each class's K rows; one batched [1, S, K]
        distill call covers every cache miss."""
        digests = [self._digest(rows) for rows in per_class]
        out: list[int | None] = [
            self._by_digest.get((params_version, d)) for d in digests
        ]
        # Dedup WITHIN the call too (e.g. one content under two class
        # names): identical digests share one distill row and one slot.
        missing = [
            i for i, (s, d) in enumerate(zip(out, digests))
            if s is None and i == digests.index(d)
        ]
        if missing:
            sup = self._stack_support([per_class[i] for i in missing])
            # Control-plane span: under a publish this inherits the
            # publish's trace context (obs/spans thread-local), so the
            # re-distill cost shows up inside the publish trace.
            with span("serve/distill", classes=len(missing)):
                vecs = np.asarray(self._distill(params, sup))[0]
            if not np.isfinite(vecs).all():
                raise ValueError(
                    "registration refused: distilled class vectors are "
                    "non-finite (corrupt weights or poisoned supports)"
                )
            for i, vec in zip(missing, vecs):
                slot = self._next_slot
                self._next_slot += 1
                self._pool[slot] = _Slot(
                    vec=vec.astype(np.float32), rows=per_class[i],
                    digest=digests[i],
                )
                self._by_digest[(params_version, digests[i])] = slot
            for i, (s, d) in enumerate(zip(out, digests)):
                if s is None:
                    out[i] = self._by_digest[(params_version, d)]
        return out  # type: ignore[return-value]

    # --- helpers ---------------------------------------------------------

    @staticmethod
    def _tokenized_to_dict(t) -> dict[str, np.ndarray]:
        return {"word": t.word, "pos1": t.pos1, "pos2": t.pos2, "mask": t.mask}

    @staticmethod
    def _stack_support(per_class: list[list[dict[str, np.ndarray]]]):
        """[N][K] row dicts -> one [1, N, K, ...] support dict in wire
        dtypes. Position leaves may be full per-token ids ([L]) or compact
        per-sentence offsets (scalar) — each key stacks to its own rank and
        the encoder's ``is_offset_form`` dispatch handles both."""
        sup = {}
        for key, dt in QUERY_DTYPES.items():
            sup[key] = np.asarray(
                [[np.asarray(row[key]) for row in shots] for shots in per_class],
                dtype=dt,
            )[None]
        return sup


class PublishTransaction:
    """A prepared (phase-1-complete) publish: validation passed, every
    live slot is re-distilled against the new weights, and the owning
    registry's publish-serial lock is held. Exactly one of ``commit()``
    or ``abort()`` must follow — from any thread; the serial mutex is
    ownerless precisely so phase 2 can arrive on a different thread
    than phase 1 (the socket transport's connection handlers).

    ``commit`` publishes the staged generation (the build-then-commit
    swap — it can still refuse on a late-registered straggler whose
    re-distill fails validation, in which case the registry is unchanged
    and the transaction counts as aborted). ``abort`` releases the
    serial lock and discards the staged vectors; the registry never
    learned the transaction existed. Either way the lock is released
    exactly once."""

    __slots__ = ("_registry", "_staged", "version_before", "_done",
                 "committed")

    def __init__(self, registry: TenantRegistry, staged: dict):
        self._registry = registry
        self._staged = staged
        self.version_before = registry.params_version
        self._done = False
        # True once the swap's plain-assignment block has run — the
        # exact "is the publish LIVE?" bit error handlers need (a
        # post-commit telemetry exception must never read as a
        # rollback, and a concurrent publisher moving params_version
        # must never make a prepare failure read as a commit).
        self.committed = False

    @property
    def new_version(self) -> int:
        return self._staged["new_version"]

    def commit(self) -> int:
        if self._done:
            raise RuntimeError("publish transaction already finished")
        try:
            version = self._registry._commit_prepared(self._staged)
            self.committed = True
            return version
        except BaseException:
            # _commit_prepared emits telemetry AFTER its plain-
            # assignment swap: if params_version reached our staged
            # version the swap IS live and only the telemetry raised.
            # Safe to read here — the serial lock is still held, so no
            # other publisher can have produced this version.
            if self._registry.params_version == self._staged["new_version"]:
                self.committed = True
            raise
        finally:
            self._done = True
            self._registry._publish_serial.release()

    def abort(self) -> None:
        if self._done:
            return
        self._done = True
        self._registry._publish_serial.release()


def load_params(ckpt_dir: str, model=None):
    """Restore just the params tree from a checkpoint directory (best
    falling back to latest) — the publish half of the train->serve
    hot-swap recipe. The stored config decides shapes; ``model`` is
    unused beyond interface symmetry (restore targets come from the
    stored config, exactly as ``InferenceEngine.from_checkpoint``)."""
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.serving.buckets import zero_batch
    from induction_network_on_fewrel_tpu.train.checkpoint import (
        CheckpointManager,
    )
    from induction_network_on_fewrel_tpu.train.steps import init_state

    cfg = CheckpointManager.load_config(ckpt_dir)
    mdl = build_model(cfg)
    state = init_state(
        mdl, cfg,
        zero_batch(cfg.max_length, (1, cfg.n, cfg.k)),
        zero_batch(cfg.max_length, (1, cfg.total_q)),
    )
    mngr = CheckpointManager(ckpt_dir, cfg)
    try:
        try:
            state, _ = mngr.restore_best(state)
        except FileNotFoundError:
            state, _ = mngr.restore_latest(state)
    finally:
        mngr.close()
    return state.params


# Single-tenant spelling, kept as the compatibility name: every pre-fleet
# caller (tests, the simple CLI path) talks to the "default" tenant of the
# same multi-tenant object.
ClassVectorRegistry = TenantRegistry
