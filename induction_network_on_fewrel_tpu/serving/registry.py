"""Class-vector registry: support sets -> device-resident [N, C] class vectors.

The induction network distills a registered support set ONCE through
encoder + dynamic routing (``InductionNetwork.class_vectors``) into a [C]
class vector; steady-state serving then never re-encodes supports — each
query is one encoder pass plus the NTN score against the resident matrix.

Registration is not the hot path, but it still respects the static-shape
discipline: every support set is normalized to exactly K shots (cycle-pad
when fewer arrive, truncate when more), so all registrations share ONE
compiled program per source shape instead of compiling per ragged K.
Corpus-backed registration (``register_dataset``) reuses the training
stack's token cache tokenization (train/token_cache.tokenize_dataset) —
including its compact position-offset form, which the shared encoder path
already understands — so a FewRel-schema support corpus registers through
the exact code the trainer feeds from.
"""

from __future__ import annotations

import threading
from functools import partial

import numpy as np

from induction_network_on_fewrel_tpu.serving.buckets import QUERY_DTYPES


class ClassVectorRegistry:
    """Named support sets distilled to class vectors, resident on device.

    ``class_matrix()`` returns the stacked [N, C] jax array (row order =
    registration order = verdict index order); it is cached and re-stacked
    only when the set of registered classes changes. Registration from
    multiple threads is serialized by a lock; the matrix swap is atomic, so
    in-flight query programs keep scoring against the matrix they were
    handed (consistent, possibly one registration stale — the standard
    serving tradeoff).
    """

    def __init__(self, model, params, tokenizer, k: int = 5):
        import jax

        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self._model, self.params, self._tok, self.k = model, params, tokenizer, k
        self._lock = threading.Lock()
        self._names: list[str] = []
        self._vecs: dict[str, np.ndarray] = {}   # name -> [C] float32
        self._matrix = None                       # stacked device cache
        self._jax = jax
        # One jitted distill program shared by every registration (shapes
        # are normalized to [1, n, K, L], so single registrations reuse the
        # n=1 compile and bulk registrations the n=N one).
        self._distill = jax.jit(
            partial(model.apply, method="class_vectors")
        )

    # --- registration ----------------------------------------------------

    def _normalize_shots(self, rows: list[dict[str, np.ndarray]]):
        """Cycle-pad/truncate a ragged shot list to exactly K entries."""
        if not rows:
            raise ValueError("support set must contain at least one instance")
        return [rows[i % len(rows)] for i in range(self.k)]

    def register(self, name: str, instances) -> np.ndarray:
        """Register (or replace) a class from raw FewRel ``Instance``s;
        returns the distilled [C] class vector (host copy)."""
        rows = [self._tokenized_to_dict(self._tok(i)) for i in instances]
        return self.register_tokens(name, rows)

    def register_tokens(
        self, name: str, rows: list[dict[str, np.ndarray]]
    ) -> np.ndarray:
        """Register from already-tokenized [L]-leaf dicts (the token-cache
        wire form; position leaves may be compact per-sentence offsets)."""
        rows = self._normalize_shots(rows)
        sup = self._stack_support([rows])           # [1, 1, K, ...]
        vec = np.asarray(self._distill(self.params, sup))[0, 0]
        with self._lock:
            if name not in self._vecs:
                self._names.append(name)
            self._vecs[name] = vec.astype(np.float32)
            self._matrix = None
        return vec

    def register_dataset(self, dataset, max_classes: int | None = None) -> list[str]:
        """Register every relation of a FewRel dataset, support = its first
        K instances, tokenized ONCE through the training token cache. All
        classes distill in one batched [1, N, K] program call."""
        from induction_network_on_fewrel_tpu.train.token_cache import (
            tokenize_dataset,
        )

        table, sizes = tokenize_dataset(dataset, self._tok)
        names = list(dataset.rel_names)
        if max_classes is not None:
            names, sizes = names[:max_classes], sizes[:max_classes]
        starts = np.concatenate([[0], np.cumsum(sizes)])
        per_class = []
        for ci in range(len(names)):
            rows = [
                {k: v[starts[ci] + r] for k, v in table.items()}
                for r in range(sizes[ci])
            ]
            per_class.append(self._normalize_shots(rows))
        sup = self._stack_support(per_class)        # [1, N, K, ...]
        vecs = np.asarray(self._distill(self.params, sup))[0]
        with self._lock:
            for name, vec in zip(names, vecs):
                if name not in self._vecs:
                    self._names.append(name)
                self._vecs[name] = vec.astype(np.float32)
            self._matrix = None
        return names

    def unregister(self, name: str) -> None:
        with self._lock:
            self._vecs.pop(name)
            self._names.remove(name)
            self._matrix = None

    # --- reading ---------------------------------------------------------

    @property
    def names(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(self._names)

    def __len__(self) -> int:
        return len(self._vecs)

    def class_matrix(self):
        """Stacked [N, C] float32 device array (cached until membership or a
        vector changes)."""
        return self.snapshot()[1]

    def snapshot(self):
        """(names, [N, C] matrix) captured ATOMICALLY — verdict index ->
        name mapping must come from the same registry state the scores were
        computed against, even while other threads register classes."""
        with self._lock:
            if not self._names:
                raise ValueError("no classes registered")
            if self._matrix is None:
                self._matrix = self._jax.device_put(
                    np.stack([self._vecs[n] for n in self._names])
                )
            return tuple(self._names), self._matrix

    # --- helpers ---------------------------------------------------------

    @staticmethod
    def _tokenized_to_dict(t) -> dict[str, np.ndarray]:
        return {"word": t.word, "pos1": t.pos1, "pos2": t.pos2, "mask": t.mask}

    @staticmethod
    def _stack_support(per_class: list[list[dict[str, np.ndarray]]]):
        """[N][K] row dicts -> one [1, N, K, ...] support dict in wire
        dtypes. Position leaves may be full per-token ids ([L]) or compact
        per-sentence offsets (scalar) — each key stacks to its own rank and
        the encoder's ``is_offset_form`` dispatch handles both."""
        sup = {}
        for key, dt in QUERY_DTYPES.items():
            sup[key] = np.asarray(
                [[np.asarray(row[key]) for row in shots] for shots in per_class],
                dtype=dt,
            )[None]
        return sup
