"""Serving observability: latency percentiles, queue depth, batch occupancy,
recompile counters — aggregate AND per tenant (fleet serving, ISSUE 7).

All counters are updated from two threads (submitters + the batcher worker),
so every mutation holds one lock; reads produce a consistent ``snapshot()``
dict that is also the record emitted through the existing
``utils.metrics.MetricsLogger`` (kind="serve" lines in metrics.jsonl — the
same machine-readable channel train/val metrics use). Per-tenant state
emits as ONE kind="serve" record per tenant carrying a ``tenant`` string
field (scalar-only schema preserved); the aggregate record has no tenant
field — tools/obs_report.py's serve section splits on that.
"""

from __future__ import annotations

import threading


class _Reservoir:
    """Bounded latency reservoir: deterministic round-robin replacement
    past the cap — percentiles then reflect a sliding window over recent
    traffic, which is the operationally useful view anyway."""

    __slots__ = ("cap", "ms", "nxt")

    def __init__(self, cap: int):
        self.cap = cap
        self.ms: list[float] = []
        self.nxt = 0

    def add(self, ms: float) -> None:
        if len(self.ms) < self.cap:
            self.ms.append(ms)
        else:
            self.ms[self.nxt] = ms
            self.nxt = (self.nxt + 1) % self.cap

    def percentile(self, q: float) -> float | None:
        lat = sorted(self.ms)
        if not lat:
            return None
        i = min(len(lat) - 1, max(0, int(round(q / 100.0 * len(lat))) - 1))
        return lat[i]


class _TenantStats:
    """Per-tenant slice of the counters (guarded by the owner's lock)."""

    __slots__ = ("served", "rejected", "shed", "deadline_missed", "lat")

    def __init__(self, reservoir_cap: int):
        self.served = 0
        self.rejected = 0
        self.shed = 0
        self.deadline_missed = 0
        self.lat = _Reservoir(reservoir_cap)


class ServingStats:
    """Thread-safe serving counters + bounded latency reservoirs."""

    # Long soaks must not grow host memory without limit.
    MAX_SAMPLES = 65536
    TENANT_SAMPLES = 8192   # per-tenant reservoirs are narrower

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._lat = _Reservoir(self.MAX_SAMPLES)
        self._tenants: dict[str, _TenantStats] = {}
        self.served = 0             # futures resolved with a verdict
        self.rejected = 0           # backpressure rejections at submit
        self.shed = 0               # per-tenant share breaches (shed-load)
        self.deadline_missed = 0    # expired before execution
        self.batches = 0            # bucket executions
        self.batch_rows = 0         # real (unpadded) rows executed
        self.batch_slots = 0        # bucket slots executed (incl. padding)
        self.exec_s_total = 0.0     # device time across batches
        self._exec_ewma_s: float | None = None
        self.warmup_compiles = 0    # programs compiled by warmup()
        self.steady_compiles = 0    # programs compiled AFTER warmup — the
        #                             zero-recompile acceptance counter
        self.swaps = 0              # atomic hot-swap publishes applied

    # --- recording -------------------------------------------------------

    def _tenant(self, tenant: str | None) -> _TenantStats | None:
        if tenant is None:
            return None
        ts = self._tenants.get(tenant)
        if ts is None:
            ts = self._tenants[tenant] = _TenantStats(self.TENANT_SAMPLES)
        return ts

    def record_done(self, latency_s: float, tenant: str | None = None) -> None:
        with self._lock:
            self.served += 1
            ms = latency_s * 1e3
            self._lat.add(ms)
            ts = self._tenant(tenant)
            if ts is not None:
                ts.served += 1
                ts.lat.add(ms)

    def record_rejected(self, tenant: str | None = None) -> None:
        with self._lock:
            self.rejected += 1
            ts = self._tenant(tenant)
            if ts is not None:
                ts.rejected += 1

    def record_shed(self, tenant: str) -> None:
        """A per-tenant share breach: THIS tenant sheds while the queue
        still admits others (counted in rejected too — a shed is a
        rejection, with attribution)."""
        with self._lock:
            self.rejected += 1
            self.shed += 1
            ts = self._tenant(tenant)
            ts.rejected += 1
            ts.shed += 1

    def record_swap(self) -> None:
        with self._lock:
            self.swaps += 1

    def record_deadline_miss(self, tenant: str | None = None) -> None:
        with self._lock:
            self.deadline_missed += 1
            ts = self._tenant(tenant)
            if ts is not None:
                ts.deadline_missed += 1

    def record_batch(self, rows: int, bucket: int, exec_s: float) -> None:
        with self._lock:
            self.batches += 1
            self.batch_rows += rows
            self.batch_slots += bucket
            self.exec_s_total += exec_s
            # EWMA of batch execution time: the batcher's deadline-pressure
            # slack estimate (how long collecting more rows can wait before
            # the oldest request would miss its deadline).
            a = 0.2
            self._exec_ewma_s = (
                exec_s if self._exec_ewma_s is None
                else a * exec_s + (1 - a) * self._exec_ewma_s
            )

    def record_compile(self, during_warmup: bool) -> None:
        with self._lock:
            if during_warmup:
                self.warmup_compiles += 1
            else:
                self.steady_compiles += 1

    # --- reading ---------------------------------------------------------

    def exec_estimate_s(self, default: float = 0.005) -> float:
        with self._lock:
            return self._exec_ewma_s if self._exec_ewma_s is not None else default

    def percentile_ms(self, q: float) -> float | None:
        """Nearest-rank percentile over the latency reservoir (no numpy
        import on the submit path; the reservoir is small)."""
        with self._lock:
            return self._lat.percentile(q)

    def bind_registry(self, registry=None, prefix: str = "serve") -> None:
        """Expose these counters through the shared obs/ CounterRegistry
        (default: the process-global one) as pull-style gauges — the
        Prometheus exposition then reads live values at render time and
        the hot recording path above stays untouched. The trainer's
        metrics and these serving counters land in ONE namespace."""
        from induction_network_on_fewrel_tpu.obs.export import get_registry

        reg = registry or get_registry()
        self._bound_registry = reg
        self._bound_fns: list[tuple[str, object]] = []

        def _register(full: str, f, help: str) -> None:
            self._bound_fns.append((full, f))
            reg.gauge_fn(full, f, help)

        def attr(name: str, help: str = "") -> None:
            _register(f"{prefix}_{name}", lambda n=name: getattr(self, n), help)

        attr("served", "futures resolved with a verdict")
        attr("rejected", "backpressure rejections at submit")
        attr("shed", "per-tenant share breaches (shed-load)")
        attr("swaps", "atomic hot-swap publishes applied")
        attr("deadline_missed", "requests expired before execution")
        attr("batches", "bucket executions")
        attr("warmup_compiles", "programs compiled by warmup()")
        attr("steady_compiles", "programs compiled after warmup")
        # Derived metrics read through snapshot(): occupancy/percentile
        # formulas live in ONE place, so metrics.jsonl kind="serve"
        # records and the Prometheus exposition cannot drift apart.
        def derived(name: str, help: str = "") -> None:
            _register(
                f"{prefix}_{name}", lambda k=name: self.snapshot()[k], help
            )

        derived("batch_occupancy", "real rows / bucket slots executed")
        derived("p50_ms", "median request latency")
        derived("p99_ms", "tail request latency")

    def unbind_registry(self) -> None:
        """Release this stats object's callbacks from the registry (engine
        close): the gauge_fn closures would otherwise pin the instance —
        latency reservoir included — and render stale values forever.
        Identity-checked per callback, so closing an old engine never
        deletes the gauges a successor re-registered under the same
        names."""
        reg = getattr(self, "_bound_registry", None)
        if reg is None:
            return
        for name, f in self._bound_fns:
            reg.unregister(name, fn=f)
        self._bound_registry = None
        self._bound_fns = []

    def snapshot(self, queue_depth: int | None = None) -> dict:
        with self._lock:
            p50 = self._lat.percentile(50)
            p99 = self._lat.percentile(99)
            occ = (
                self.batch_rows / self.batch_slots if self.batch_slots else 0.0
            )
            snap = {
                "served": self.served,
                "rejected": self.rejected,
                "shed": self.shed,
                "deadline_missed": self.deadline_missed,
                "batches": self.batches,
                "batch_occupancy": round(occ, 4),
                "p50_ms": round(p50, 3) if p50 is not None else 0.0,
                "p99_ms": round(p99, 3) if p99 is not None else 0.0,
                "warmup_compiles": self.warmup_compiles,
                "steady_recompiles": self.steady_compiles,
                "swaps": self.swaps,
            }
        if queue_depth is not None:
            snap["queue_depth"] = queue_depth
        return snap

    def tenant_snapshot(self) -> dict[str, dict]:
        """Consistent per-tenant view: {tenant: {served, rejected, shed,
        deadline_missed, p50_ms, p99_ms}}."""
        with self._lock:
            out = {}
            for name, ts in self._tenants.items():
                p50, p99 = ts.lat.percentile(50), ts.lat.percentile(99)
                out[name] = {
                    "served": ts.served,
                    "rejected": ts.rejected,
                    "shed": ts.shed,
                    "deadline_missed": ts.deadline_missed,
                    "p50_ms": round(p50, 3) if p50 is not None else 0.0,
                    "p99_ms": round(p99, 3) if p99 is not None else 0.0,
                }
            return out

    def emit(self, logger, step: int, queue_depth: int | None = None) -> None:
        """The aggregate kind="serve" record plus ONE kind="serve" record
        per tenant (distinguished by the ``tenant`` string field — every
        field stays a scalar, so the metrics.jsonl schema contract and
        ``obs_report --check`` hold unchanged)."""
        logger.log(step, kind="serve", **self.snapshot(queue_depth))
        for tenant, snap in sorted(self.tenant_snapshot().items()):
            logger.log(step, kind="serve", tenant=tenant, **snap)
