"""Serving observability: latency percentiles, queue depth, batch occupancy,
recompile counters — aggregate AND per tenant (fleet serving, ISSUE 7).

All counters are updated from two threads (submitters + the batcher worker),
so every mutation holds one lock; reads produce a consistent ``snapshot()``
dict that is also the record emitted through the existing
``utils.metrics.MetricsLogger`` (kind="serve" lines in metrics.jsonl — the
same machine-readable channel train/val metrics use). Per-tenant state
emits as ONE kind="serve" record per tenant carrying a ``tenant`` string
field (scalar-only schema preserved); the aggregate record has no tenant
field — tools/obs_report.py's serve section splits on that.

ISSUE 9 additions:

* Per-tenant latency accumulators are true fixed-size **reservoir
  samples** (Algorithm R, deterministic xorshift RNG): a thousand-tenant
  month-long soak holds exactly ``TENANT_SAMPLES`` floats per tenant, and
  the sample stays uniform over the tenant's whole history instead of a
  recency window. The percentile convention (nearest-rank) is unchanged
  and stays shared with ``tools/loadgen.py``'s ``pct``.
* ``record_done`` forwards each outcome to an attached **SLO engine**
  (obs/health.SLOEngine) and observes the bound latency **histogram**
  (obs/export.Histogram) with the request's exemplar trace_id when it was
  sampled — the Prometheus exposition then hands a scrape a concrete
  traced request per bucket.
* ``record_trace`` retains a bounded window of sampled per-request trace
  records; ``trace_summary()`` reduces them to segment-breakdown medians
  + exemplar ids for SERVE/BENCH artifacts.

ISSUE 10 addition: per-tenant **prediction-quality** counters — NOTA
verdict counts plus top-1-margin and score-entropy reservoirs, fed from
the verdict emit path — emitted as one ``kind="quality"`` record per
tenant alongside the serve records. These are the same features the
online drift detector (obs/drift.py) compares against its calibration
baseline; the stats copy exists so the quality stream is observable even
with no detector armed.
"""

from __future__ import annotations

import threading
from collections import deque


def nearest_rank(xs: list[float], q: float) -> float | None:
    """Nearest-rank percentile over unsorted samples; None when empty.
    THE percentile convention of the serving stack — shared by the
    reservoirs, trace summaries, and (by contract, asserted in
    tests/test_tracing.py) tools/loadgen.py's ``pct`` and
    tools/obs_report.py's ``_percentile``."""
    s = sorted(xs)
    if not s:
        return None
    i = min(len(s) - 1, max(0, int(round(q / 100.0 * len(s))) - 1))
    return s[i]


class _Reservoir:
    """Fixed-size uniform reservoir (Algorithm R) of latency samples.

    Below the cap it is exact; past the cap each new sample replaces a
    random slot with probability cap/n, so the retained set stays a
    uniform sample of EVERYTHING observed — bounded memory with honest
    long-run percentiles (a round-robin window would instead forget every
    sample older than the cap). The RNG is a tiny xorshift (no numpy on
    the hot path) seeded per reservoir, so runs are deterministic."""

    __slots__ = ("cap", "ms", "n", "_rng")

    def __init__(self, cap: int, seed: int = 0x9E3779B9):
        self.cap = cap
        self.ms: list[float] = []
        self.n = 0
        self._rng = (seed or 1) & 0xFFFFFFFF

    def _next_rand(self) -> int:
        # xorshift32: cheap, stateful, plenty for replacement sampling.
        x = self._rng
        x ^= (x << 13) & 0xFFFFFFFF
        x ^= x >> 17
        x ^= (x << 5) & 0xFFFFFFFF
        self._rng = x
        return x

    def add(self, ms: float) -> None:
        self.n += 1
        if len(self.ms) < self.cap:
            self.ms.append(ms)
            return
        j = self._next_rand() % self.n
        if j < self.cap:
            self.ms[j] = ms

    def percentile(self, q: float) -> float | None:
        return nearest_rank(self.ms, q)


class _TenantStats:
    """Per-tenant slice of the counters (guarded by the owner's lock).

    The quality slice (ISSUE 10): ``nota`` counts ``no_relation``
    verdicts, ``margin``/``entropy`` are reservoirs of the per-verdict
    top-1 margin and score entropy — the same three features the online
    drift detector (obs/drift.py) watches, kept here so the periodic
    ``kind="quality"`` record states what the tenant's traffic looks
    like even when no detector is armed."""

    __slots__ = ("served", "rejected", "shed", "deadline_missed", "lat",
                 "nota", "quality_n", "margin", "entropy",
                 "execute_errors", "breaker_shed", "degraded",
                 "quant_probes", "quant_rows", "quant_agree_rows",
                 "quant_margin_sum")

    def __init__(self, reservoir_cap: int):
        # Quantization parity police (ISSUE 18): sampled shadow-score
        # outcomes — probe launches, rows compared, rows whose VERDICT
        # (label + NOTA flag) agreed with f32, and the summed per-row
        # |margin drift| (means come out at read time).
        self.quant_probes = 0
        self.quant_rows = 0
        self.quant_agree_rows = 0
        self.quant_margin_sum = 0.0
        self.served = 0
        self.rejected = 0
        self.shed = 0
        self.deadline_missed = 0
        self.execute_errors = 0   # requests failed by a launch failure
        self.breaker_shed = 0     # submits shed by an open circuit breaker
        self.degraded = 0         # open-set-floor NOTA verdicts served
        #                           while the tenant was quarantined
        self.lat = _Reservoir(reservoir_cap)
        self.nota = 0
        self.quality_n = 0   # verdicts that CARRIED quality features —
        #                      the honest nota_rate denominator when
        #                      quality-less legacy completions mix in
        self.margin = _Reservoir(reservoir_cap, seed=0x51F15EED)
        self.entropy = _Reservoir(reservoir_cap, seed=0x5EED5EED)


class ServingStats:
    """Thread-safe serving counters + bounded latency reservoirs."""

    # Long soaks must not grow host memory without limit. Per-tenant
    # reservoirs are deliberately narrow: at 1024 floats each, a
    # thousand-tenant fleet holds ~8 MB of latency state total (and the
    # Algorithm-R reservoir keeps the percentile honest over the full
    # history at that size — nearest-rank p99 needs ~100+ samples, which
    # 1024 clears with margin).
    MAX_SAMPLES = 65536
    TENANT_SAMPLES = 1024
    MAX_TRACES = 512        # retained sampled per-request trace records

    def __init__(self, slo=None) -> None:
        self._lock = threading.Lock()
        self._lat = _Reservoir(self.MAX_SAMPLES)
        self._tenants: dict[str, _TenantStats] = {}
        # Optional obs/health.SLOEngine: every request outcome feeds the
        # per-tenant burn-rate windows. None (default) costs one `if`.
        self._slo = slo
        # Bounded window of sampled trace records (dicts) — the source
        # for trace_summary()'s segment medians + exemplar ids.
        self._traces: deque[dict] = deque(maxlen=self.MAX_TRACES)
        self._hist = None       # bound latency histogram (bind_registry)
        self.served = 0             # futures resolved with a verdict
        self.rejected = 0           # backpressure rejections at submit
        self.shed = 0               # per-tenant share breaches (shed-load)
        self.deadline_missed = 0    # expired before execution
        self.execute_errors = 0     # requests failed by launch failures
        #                             (typed ExecuteError — ISSUE 12)
        self.breaker_shed = 0       # submits shed by open circuit breakers
        self.degraded = 0           # degraded-mode NOTA verdicts served
        self.batches = 0            # bucket executions
        self.batch_rows = 0         # real (unpadded) rows executed
        self.batch_slots = 0        # bucket slots executed (incl. padding)
        self.exec_s_total = 0.0     # device time across batches
        self._exec_ewma_s: float | None = None
        self.warmup_compiles = 0    # programs compiled by warmup()
        self.steady_compiles = 0    # programs compiled AFTER warmup — the
        #                             zero-recompile acceptance counter
        self.swaps = 0              # atomic hot-swap publishes applied
        self.quant_probes = 0       # parity-police shadow-score launches
        # Resident-bytes provider (ISSUE 18 capacity accounting): the
        # engine binds registry.resident_bytes here; snapshots then carry
        # chip-resident bytes per tenant through the same spine as every
        # other counter. Called OUTSIDE this object's lock (the registry
        # has its own).
        self._resident = None

    # --- recording -------------------------------------------------------

    def _tenant(self, tenant: str | None) -> _TenantStats | None:
        if tenant is None:
            return None
        ts = self._tenants.get(tenant)
        if ts is None:
            ts = self._tenants[tenant] = _TenantStats(self.TENANT_SAMPLES)
        return ts

    def record_done(
        self, latency_s: float, tenant: str | None = None,
        trace_id: str | None = None,
        nota: bool | None = None,
        margin: float | None = None,
        entropy: float | None = None,
    ) -> None:
        """``nota``/``margin``/``entropy`` are the verdict's quality
        features (engine._verdict computes them from the logits row);
        None = caller has no quality signal (legacy paths)."""
        with self._lock:
            self.served += 1
            ms = latency_s * 1e3
            self._lat.add(ms)
            ts = self._tenant(tenant)
            if ts is not None:
                ts.served += 1
                ts.lat.add(ms)
                if nota is not None:
                    ts.quality_n += 1
                    if nota:
                        ts.nota += 1
                if margin is not None:
                    ts.margin.add(float(margin))
                if entropy is not None:
                    ts.entropy.add(float(entropy))
            hist = self._hist
        # Outside the counter lock: the histogram and SLO engine have
        # their own locks, and neither ever calls back into this object.
        if hist is not None:
            hist.observe(ms, exemplar=trace_id)
        if self._slo is not None and tenant is not None:
            self._slo.record(tenant, latency_ms=ms)

    def record_rejected(self, tenant: str | None = None) -> None:
        with self._lock:
            self.rejected += 1
            ts = self._tenant(tenant)
            if ts is not None:
                ts.rejected += 1
        if self._slo is not None and tenant is not None:
            self._slo.record(tenant, error=True)

    def record_shed(self, tenant: str) -> None:
        """A per-tenant share breach: THIS tenant sheds while the queue
        still admits others (counted in rejected too — a shed is a
        rejection, with attribution)."""
        with self._lock:
            self.rejected += 1
            self.shed += 1
            ts = self._tenant(tenant)
            ts.rejected += 1
            ts.shed += 1
        if self._slo is not None:
            self._slo.record(tenant, error=True)

    def record_swap(self) -> None:
        with self._lock:
            self.swaps += 1

    def record_execute_error(self, tenant: str | None, requests: int) -> None:
        """A failed launch: ``requests`` futures of ONE tenant's batch
        failed with a typed ExecuteError (the containment contract —
        nothing else fails). Each counts as a bad outcome for the
        tenant's SLO."""
        with self._lock:
            self.execute_errors += requests
            ts = self._tenant(tenant)
            if ts is not None:
                ts.execute_errors += requests
        if self._slo is not None and tenant is not None:
            for _ in range(requests):
                self._slo.record(tenant, error=True)

    def record_breaker_shed(self, tenant: str) -> None:
        """A submit shed by this tenant's OPEN circuit breaker: counted
        apart from share-based shed-load so the watchdog's shed_load
        signal keeps meaning 'over admission share' and breaker activity
        reads from its own counter (and its own breaker_open critical)."""
        with self._lock:
            self.rejected += 1
            self.breaker_shed += 1
            ts = self._tenant(tenant)
            ts.rejected += 1
            ts.breaker_shed += 1
        if self._slo is not None:
            self._slo.record(tenant, error=True)

    def record_degraded(self, tenant: str | None, requests: int) -> None:
        """Degraded-mode NOTA verdicts served for a quarantined tenant.
        Counted as SERVED for throughput/latency (record_done is called
        per request as usual); this counter is the degraded-traffic
        attribution on top."""
        with self._lock:
            self.degraded += requests
            ts = self._tenant(tenant)
            if ts is not None:
                ts.degraded += requests

    def record_deadline_miss(self, tenant: str | None = None) -> None:
        with self._lock:
            self.deadline_missed += 1
            ts = self._tenant(tenant)
            if ts is not None:
                ts.deadline_missed += 1
        if self._slo is not None and tenant is not None:
            self._slo.record(tenant, error=True)

    def record_trace(self, rec: dict) -> None:
        """Retain one sampled per-request trace record. Locked: appends
        alone are GIL-atomic, but trace_summary() iterates this deque
        from OTHER threads (loadgen reads it the moment the last future
        resolves, while the worker is still appending the batch's
        remaining records) and CPython raises on mutation-during-
        iteration."""
        with self._lock:
            self._traces.append(rec)

    def record_batch(self, rows: int, bucket: int, exec_s: float) -> None:
        with self._lock:
            self.batches += 1
            self.batch_rows += rows
            self.batch_slots += bucket
            self.exec_s_total += exec_s
            # EWMA of batch execution time: the batcher's deadline-pressure
            # slack estimate (how long collecting more rows can wait before
            # the oldest request would miss its deadline).
            a = 0.2
            self._exec_ewma_s = (
                exec_s if self._exec_ewma_s is None
                else a * exec_s + (1 - a) * self._exec_ewma_s
            )

    def bind_resident(self, provider) -> None:
        """Attach the resident-bytes provider: a callable returning
        {tenant: chip-resident bytes} (registry.resident_bytes)."""
        self._resident = provider

    def resident_bytes_snapshot(self) -> dict[str, float]:
        """Per-tenant chip-resident bytes from the bound provider ({} when
        none is bound). Never raises — capacity gauges must not take the
        serving path down with them."""
        prov = self._resident
        if prov is None:
            return {}
        try:
            return {t: float(b) for t, b in prov().items()}
        except Exception:  # noqa: BLE001 — gauge-only path
            return {}

    def record_quant_probe(
        self, tenant: str | None, agreement: float, margin_drift: float,
        rows: int,
    ) -> None:
        """One parity-police probe outcome: ``agreement`` is the fraction
        of ``rows`` whose quantized verdict matched the f32 shadow,
        ``margin_drift`` the mean per-row |margin delta|."""
        with self._lock:
            self.quant_probes += 1
            ts = self._tenant(tenant)
            if ts is not None:
                ts.quant_probes += 1
                ts.quant_rows += rows
                ts.quant_agree_rows += int(round(agreement * rows))
                ts.quant_margin_sum += float(margin_drift) * rows

    def record_compile(self, during_warmup: bool) -> None:
        with self._lock:
            if during_warmup:
                self.warmup_compiles += 1
            else:
                self.steady_compiles += 1

    # --- reading ---------------------------------------------------------

    def exec_estimate_s(self, default: float = 0.005) -> float:
        with self._lock:
            return self._exec_ewma_s if self._exec_ewma_s is not None else default

    def percentile_ms(self, q: float) -> float | None:
        """Nearest-rank percentile over the latency reservoir (no numpy
        import on the submit path; the reservoir is small)."""
        with self._lock:
            return self._lat.percentile(q)

    @property
    def slo(self):
        return self._slo

    def trace_summary(self) -> dict | None:
        """Segment-breakdown medians + exemplar trace_ids over the
        retained sampled traces (None with none recorded) — the stamp
        SERVE_r*.json and bench.py's serving leg carry per arm, so a
        scheduler A/B attributes WHICH stage moved, not just e2e p99.
        Medians use the shared nearest-rank convention."""
        with self._lock:
            traces = [t for t in self._traces if "total_ms" in t]
        if not traces:
            return None

        def med(key: str) -> float | None:
            xs = [
                float(t[key]) for t in traces
                if isinstance(t.get(key), (int, float))
            ]
            p = nearest_rank(xs, 50)
            return round(p, 3) if p is not None else None

        return {
            "sampled": len(traces),
            "queue_ms_p50": med("queue_ms"),
            "pack_ms_p50": med("pack_ms"),
            "execute_ms_p50": med("execute_ms"),
            "respond_ms_p50": med("respond_ms"),
            "total_ms_p50": med("total_ms"),
            # Exemplars: the most recent few — the ids an operator greps
            # in metrics.jsonl (kind="trace") for the full waterfall.
            "exemplar_trace_ids": [
                t["trace_id"] for t in traces[-5:] if "trace_id" in t
            ],
        }

    def bind_registry(self, registry=None, prefix: str = "serve") -> None:
        """Expose these counters through the shared obs/ CounterRegistry
        (default: the process-global one) as pull-style gauges — the
        Prometheus exposition then reads live values at render time and
        the hot recording path above stays untouched. The trainer's
        metrics and these serving counters land in ONE namespace. Also
        binds the ``{prefix}_latency_ms`` histogram (push-style: the
        record path observes into it) whose buckets carry exemplar
        trace_ids of sampled requests."""
        from induction_network_on_fewrel_tpu.obs.export import get_registry

        reg = registry or get_registry()
        self._bound_registry = reg
        self._bound_fns: list[tuple[str, object]] = []
        # Fresh histogram per bind (latest wins, like gauge_fn): a
        # successor engine must not inherit — or be deleted with — a
        # closed predecessor's counts.
        reg.unregister(f"{prefix}_latency_ms")
        self._hist = reg.histogram(
            f"{prefix}_latency_ms",
            help="request latency with exemplar trace_ids",
        )
        self._hist_name = f"{prefix}_latency_ms"

        def _register(full: str, f, help: str) -> None:
            self._bound_fns.append((full, f))
            reg.gauge_fn(full, f, help)

        def attr(name: str, help: str = "") -> None:
            _register(f"{prefix}_{name}", lambda n=name: getattr(self, n), help)

        attr("served", "futures resolved with a verdict")
        attr("rejected", "backpressure rejections at submit")
        attr("shed", "per-tenant share breaches (shed-load)")
        attr("swaps", "atomic hot-swap publishes applied")
        attr("deadline_missed", "requests expired before execution")
        attr("batches", "bucket executions")
        attr("warmup_compiles", "programs compiled by warmup()")
        attr("steady_compiles", "programs compiled after warmup")
        # Derived metrics read through snapshot(): occupancy/percentile
        # formulas live in ONE place, so metrics.jsonl kind="serve"
        # records and the Prometheus exposition cannot drift apart.
        def derived(name: str, help: str = "") -> None:
            _register(
                f"{prefix}_{name}", lambda k=name: self.snapshot()[k], help
            )

        derived("batch_occupancy", "real rows / bucket slots executed")
        derived("p50_ms", "median request latency")
        derived("p99_ms", "tail request latency")
        derived("resident_bytes", "chip-resident class-matrix bytes")
        derived("quant_agreement", "parity-police verdict agreement vs f32")

    def unbind_registry(self) -> None:
        """Release this stats object's callbacks from the registry (engine
        close): the gauge_fn closures would otherwise pin the instance —
        latency reservoir included — and render stale values forever.
        Identity-checked per callback, so closing an old engine never
        deletes the gauges a successor re-registered under the same
        names."""
        reg = getattr(self, "_bound_registry", None)
        if reg is None:
            return
        for name, f in self._bound_fns:
            reg.unregister(name, fn=f)
        if self._hist is not None:
            reg.unregister(self._hist_name, inst=self._hist)
            self._hist = None
        self._bound_registry = None
        self._bound_fns = []

    def snapshot(self, queue_depth: int | None = None) -> dict:
        # Provider call BEFORE taking our lock (it holds the registry's).
        resident = self.resident_bytes_snapshot()
        with self._lock:
            p50 = self._lat.percentile(50)
            p99 = self._lat.percentile(99)
            occ = (
                self.batch_rows / self.batch_slots if self.batch_slots else 0.0
            )
            agree_rows = sum(
                ts.quant_agree_rows for ts in self._tenants.values()
            )
            quant_rows = sum(ts.quant_rows for ts in self._tenants.values())
            snap = {
                "served": self.served,
                "rejected": self.rejected,
                "shed": self.shed,
                "deadline_missed": self.deadline_missed,
                "execute_errors": self.execute_errors,
                "breaker_shed": self.breaker_shed,
                "degraded": self.degraded,
                "batches": self.batches,
                "batch_occupancy": round(occ, 4),
                "p50_ms": round(p50, 3) if p50 is not None else 0.0,
                "p99_ms": round(p99, 3) if p99 is not None else 0.0,
                "warmup_compiles": self.warmup_compiles,
                "steady_recompiles": self.steady_compiles,
                "swaps": self.swaps,
                # Capacity accounting (ISSUE 18): total chip-resident
                # class-matrix bytes — the fleet rollup's density
                # numerator-per-replica. 0.0 with no provider bound.
                "resident_bytes": round(sum(resident.values()), 1),
                "quant_probes": self.quant_probes,
                # Rows-weighted verdict agreement across tenants; 1.0
                # with no probes (vacuous truth keeps floor checks
                # green for unquantized arms).
                "quant_agreement": round(
                    agree_rows / quant_rows, 4
                ) if quant_rows else 1.0,
            }
        if queue_depth is not None:
            snap["queue_depth"] = queue_depth
        return snap

    def tenant_snapshot(self) -> dict[str, dict]:
        """Consistent per-tenant view: {tenant: {served, rejected, shed,
        deadline_missed, p50_ms, p99_ms, resident_bytes}}."""
        resident = self.resident_bytes_snapshot()
        with self._lock:
            out = {}
            for name, ts in self._tenants.items():
                p50, p99 = ts.lat.percentile(50), ts.lat.percentile(99)
                out[name] = {
                    "served": ts.served,
                    "rejected": ts.rejected,
                    "shed": ts.shed,
                    "deadline_missed": ts.deadline_missed,
                    "execute_errors": ts.execute_errors,
                    "breaker_shed": ts.breaker_shed,
                    "degraded": ts.degraded,
                    "p50_ms": round(p50, 3) if p50 is not None else 0.0,
                    "p99_ms": round(p99, 3) if p99 is not None else 0.0,
                    "resident_bytes": resident.get(name, 0.0),
                }
            return out

    def quality_snapshot(self) -> dict[str, dict]:
        """Per-tenant prediction-quality view (ISSUE 10): {tenant:
        {served, nota_rate, margin_p50, entropy_p50}} for tenants whose
        verdicts carried quality features. The traffic-side half of the
        quality record — obs/drift.py's ``emit`` adds the drift-state
        half (baseline vs current vs band)."""
        with self._lock:
            out = {}
            for name, ts in self._tenants.items():
                if ts.quality_n == 0:
                    continue
                m50 = ts.margin.percentile(50)
                e50 = ts.entropy.percentile(50)
                out[name] = {
                    "served": ts.served,
                    # Rate over quality-BEARING verdicts only: mixing in
                    # legacy nota=None completions would dilute it.
                    "nota_rate": round(ts.nota / ts.quality_n, 4),
                    "margin_p50": round(m50, 4) if m50 is not None else 0.0,
                    "entropy_p50": round(e50, 4) if e50 is not None else 0.0,
                }
                if ts.quant_rows:
                    # Parity-police slice (ISSUE 18): verdict agreement
                    # vs the f32 shadow + mean |margin drift| over every
                    # probed row of this tenant.
                    out[name]["quant_agreement"] = round(
                        ts.quant_agree_rows / ts.quant_rows, 4
                    )
                    out[name]["quant_margin_drift"] = round(
                        ts.quant_margin_sum / ts.quant_rows, 4
                    )
            return out

    def emit(self, logger, step: int, queue_depth: int | None = None) -> None:
        """The aggregate kind="serve" record plus ONE kind="serve" record
        per tenant (distinguished by the ``tenant`` string field — every
        field stays a scalar, so the metrics.jsonl schema contract and
        ``obs_report --check`` hold unchanged), plus ONE ``kind="quality"``
        record per tenant with quality-bearing verdicts (nota_rate /
        margin_p50 / entropy_p50 — the model-quality stream next to the
        latency stream, ISSUE 10)."""
        logger.log(step, kind="serve", **self.snapshot(queue_depth))
        for tenant, snap in sorted(self.tenant_snapshot().items()):
            logger.log(step, kind="serve", tenant=tenant, **snap)
        for tenant, snap in sorted(self.quality_snapshot().items()):
            logger.log(step, kind="quality", tenant=tenant, **snap)
