"""InferenceEngine: checkpoint -> low-latency few-shot query answering.

Wires the serving pieces end to end: a ``ClassVectorRegistry`` (supports
distilled once, resident on device), a ``QueryProgramCache`` (AOT-compiled
per-bucket query programs), a ``DynamicBatcher`` (deadlines, backpressure,
partial flush), and ``ServingStats``. Steady state per query: host
tokenization + one pre-compiled program run (encoder pass + NTN score
against the resident class matrix) — no support work, no compiles.

NOTA (FewRel 2.0, Gao et al. 2019): checkpoints trained with ``na_rate > 0``
carry a learned none-of-the-above head; its logit is appended as class N,
and a query that lands there gets the explicit ``"no_relation"`` verdict —
the open-world answer a serving engine needs for traffic that matches no
registered relation.
"""

from __future__ import annotations

import sys
import time

import numpy as np

from induction_network_on_fewrel_tpu.obs.spans import span
from induction_network_on_fewrel_tpu.serving.batcher import DynamicBatcher, Request
from induction_network_on_fewrel_tpu.serving.buckets import (
    DEFAULT_BUCKETS,
    QueryProgramCache,
    select_bucket,
    stack_queries,
)
from induction_network_on_fewrel_tpu.serving.registry import ClassVectorRegistry
from induction_network_on_fewrel_tpu.serving.stats import ServingStats

NO_RELATION = "no_relation"


class InferenceEngine:
    def __init__(
        self,
        model,
        params,
        cfg,
        tokenizer,
        k: int | None = None,
        buckets: tuple[int, ...] = DEFAULT_BUCKETS,
        max_queue_depth: int = 64,
        batch_window_s: float = 0.002,
        default_deadline_s: float = 1.0,
        logger=None,
        watchdog=None,
        start: bool = True,
    ):
        if cfg.model != "induction":
            raise ValueError(
                f"class-vector serving requires --model induction (supports "
                f"distill to per-class vectors); got {cfg.model!r}. Other "
                f"episode heads re-read the support set per query."
            )
        if cfg.feature_cache:
            raise ValueError(
                "feature-cache checkpoints hold head-only params (no "
                "encoder) — the serving engine cannot tokenize queries "
                "through them; serve a full checkpoint instead"
            )
        self.cfg = cfg
        self.model = model
        self.params = params
        self.tokenizer = tokenizer
        self.nota = cfg.na_rate > 0
        self.max_length = cfg.max_length
        self.default_deadline_s = default_deadline_s
        self._logger = logger
        self._emit_step = 0
        # Telemetry spine (obs/): serving counters join the shared
        # counter registry (Prometheus exposition + run reports see train
        # and serving through one namespace); the optional watchdog gets
        # queue-stall observations on every stats emit.
        self.watchdog = watchdog
        if watchdog is not None and logger is not None:
            logger.add_hook(watchdog.observe_record)

        self.stats = ServingStats()
        self.stats.bind_registry()
        self.registry = ClassVectorRegistry(
            model, params, tokenizer, k=k if k is not None else cfg.k
        )
        self.programs = QueryProgramCache(model, stats=self.stats)
        self.batcher = DynamicBatcher(
            self._execute_batch,
            buckets=buckets,
            max_queue_depth=max_queue_depth,
            batch_window_s=batch_window_s,
            stats=self.stats,
            start=start,
        )

    # --- construction from a trained artifact ----------------------------

    @classmethod
    def from_checkpoint(
        cls, ckpt_dir: str, device: str | None = None,
        glove: str | None = None, glove_mat: str | None = None, **kw
    ) -> "InferenceEngine":
        """Build an engine from a checkpoint directory: the stored
        config.json decides the architecture (exactly as test.py does), the
        best checkpoint (falling back to the recovery ring) supplies the
        weights. ``device`` overrides the stored --device for serving."""
        import jax

        from induction_network_on_fewrel_tpu.data import make_synthetic_glove
        from induction_network_on_fewrel_tpu.data.glove import load_glove
        from induction_network_on_fewrel_tpu.data.tokenizer import GloveTokenizer
        from induction_network_on_fewrel_tpu.models import build_model
        from induction_network_on_fewrel_tpu.train.checkpoint import (
            CheckpointManager,
        )
        from induction_network_on_fewrel_tpu.train.steps import init_state

        cfg = CheckpointManager.load_config(ckpt_dir)
        if device is not None:
            cfg = cfg.replace(device=device)
        if cfg.encoder == "bert":
            from induction_network_on_fewrel_tpu.data.bert_tokenizer import (
                BertTokenizer,
            )

            vocab = None
            tok = BertTokenizer(
                cfg.max_length, vocab_path=cfg.bert_vocab_path,
                vocab_size=cfg.bert_vocab_size,
            )
        else:
            vocab = (
                load_glove(glove, glove_mat) if glove
                else make_synthetic_glove(
                    vocab_size=cfg.vocab_size - 2, word_dim=cfg.word_dim
                )
            )
            if (cfg.vocab_size, cfg.word_dim) != (vocab.vocab_size, vocab.word_dim):
                raise ValueError(
                    f"vocab {vocab.vocab_size}x{vocab.word_dim} does not "
                    f"match the checkpoint's embedding table "
                    f"{cfg.vocab_size}x{cfg.word_dim} — pass the GloVe file "
                    f"the model was trained with"
                )
            tok = GloveTokenizer(vocab, max_length=cfg.max_length)
        model = build_model(
            cfg, glove_init=vocab.vectors if vocab is not None else None
        )
        # Restore target: the same state tree training would build (shapes
        # only — the zero token ids never influence the restored weights).
        from induction_network_on_fewrel_tpu.serving.buckets import zero_batch

        state = init_state(
            model, cfg,
            zero_batch(cfg.max_length, (1, cfg.n, cfg.k)),
            zero_batch(cfg.max_length, (1, cfg.total_q)),
        )
        mngr = CheckpointManager(ckpt_dir, cfg)
        try:
            try:
                state, step = mngr.restore_best(state)
                which = "best"
            except FileNotFoundError:
                state, step = mngr.restore_latest(state)
                which = "latest"
        finally:
            mngr.close()
        print(
            f"serving {which} checkpoint step={step} from {ckpt_dir} "
            f"on {jax.default_backend()}",
            file=sys.stderr,
        )
        return cls(model, state.params, cfg, tok, **kw)

    # --- registration ----------------------------------------------------

    def register_class(self, name: str, instances) -> None:
        self.registry.register(name, instances)

    def register_dataset(self, dataset, max_classes: int | None = None) -> list[str]:
        return self.registry.register_dataset(dataset, max_classes=max_classes)

    @property
    def class_names(self) -> tuple[str, ...]:
        return self.registry.names

    def warmup(self) -> int:
        """AOT-compile every bucket's query program for the current class
        count; returns how many programs this call compiled. After warmup,
        steady-state traffic is zero-recompile (stats.steady_recompiles
        counts violations)."""
        mat = np.asarray(self.registry.class_matrix())
        n, c = mat.shape
        return self.programs.warmup(
            self.params, n, c, self.batcher.buckets, self.max_length
        )

    # --- query path ------------------------------------------------------

    def submit(self, instance, deadline_s: float | None = None):
        """Tokenize one query and enqueue it; returns a Future resolving to
        the verdict dict. Raises ``Saturated`` under backpressure."""
        if len(self.registry) == 0:
            raise ValueError("no classes registered — register supports first")
        t = self.tokenizer(self._as_instance(instance))
        query = {"word": t.word, "pos1": t.pos1, "pos2": t.pos2, "mask": t.mask}
        fut = self.batcher.submit(
            query,
            deadline_s if deadline_s is not None else self.default_deadline_s,
        )
        if self.watchdog is not None:
            # Stall observation from the CLIENT thread: the execute-path
            # observations below come from the worker itself, which is
            # exactly the thread that has wedged when a stall is real —
            # submitters are the independent observer that can still see
            # a deep queue with a frozen served counter.
            self.watchdog.observe_queue(
                self.batcher.queue_depth, self.stats.served
            )
        return fut

    def classify(self, instance, deadline_s: float | None = None) -> dict:
        """Synchronous submit + wait."""
        fut = self.submit(instance, deadline_s)
        timeout = (deadline_s or self.default_deadline_s) + 5.0
        return fut.result(timeout=timeout)

    def _execute_batch(self, batch: list[Request]) -> None:
        # Atomic (names, matrix) snapshot: concurrent registration must not
        # skew the verdict index -> name mapping (registry.snapshot doc).
        names, class_mat = self.registry.snapshot()
        bucket = select_bucket(len(batch), self.batcher.buckets)
        with span("serve/stack", rows=len(batch), bucket=bucket):
            query = stack_queries([r.query for r in batch], bucket)
        t0 = time.monotonic()
        with span("serve/execute", rows=len(batch), bucket=bucket):
            logits = self.programs.run(self.params, class_mat, query)
        exec_s = time.monotonic() - t0
        self.stats.record_batch(len(batch), bucket, exec_s)
        now = time.monotonic()
        for row, req in zip(logits, batch):   # zip drops the pad rows
            idx = int(np.argmax(row))
            is_nota = self.nota and idx == len(names)
            verdict = {
                "label": NO_RELATION if is_nota else names[idx],
                "class_index": -1 if is_nota else idx,
                "nota": is_nota,
                "logits": {n: float(row[i]) for i, n in enumerate(names)},
                "latency_ms": round((now - req.enqueued_at) * 1e3, 3),
            }
            if self.nota:
                verdict["logits"][NO_RELATION] = float(row[len(names)])
            self.stats.record_done(now - req.enqueued_at)
            req.future.set_result(verdict)
        self._maybe_emit()

    # --- observability / lifecycle ---------------------------------------

    def _maybe_emit(self, every: int = 50) -> None:
        if self.watchdog is not None:
            self.watchdog.observe_queue(
                self.batcher.queue_depth, self.stats.served
            )
        if self._logger is None:
            return
        if self.stats.batches - self._emit_step >= every:
            self._emit_step = self.stats.batches
            self.stats.emit(
                self._logger, self._emit_step,
                queue_depth=self.batcher.queue_depth,
            )

    def emit_stats(self) -> None:
        if self.watchdog is not None:
            self.watchdog.observe_queue(
                self.batcher.queue_depth, self.stats.served
            )
        if self._logger is not None:
            self.stats.emit(
                self._logger, self.stats.batches,
                queue_depth=self.batcher.queue_depth,
            )

    def close(self) -> None:
        self.batcher.close()
        self.emit_stats()
        # Unbinding drops this engine's gauges from the registry — any
        # final scrape (serve_main writes metrics.prom) must happen BEFORE
        # close. A closed engine must not stay pinned in (or serve stale
        # values from) the global registry for the rest of the process.
        self.stats.unbind_registry()

    @staticmethod
    def _as_instance(x):
        from induction_network_on_fewrel_tpu.data.fewrel import Instance

        if isinstance(x, Instance):
            return x
        if isinstance(x, dict):
            if "h" in x:                       # raw FewRel JSON schema
                return Instance.from_raw(x)
            return Instance(
                tokens=tuple(x["tokens"]),
                head_pos=tuple(x.get("head_pos", (0,))),
                tail_pos=tuple(x.get("tail_pos", (0,))),
            )
        raise TypeError(f"cannot interpret query of type {type(x).__name__}")
