"""InferenceEngine: checkpoint -> low-latency multi-tenant few-shot serving.

Wires the serving pieces end to end: a ``TenantRegistry`` (supports
distilled once into immutable copy-on-write snapshots, resident on
device), a ``QueryProgramCache`` (AOT-compiled per-bucket query programs,
optionally dp-sharded over a device mesh), a scheduler (the continuous
cross-bucket batcher by default; the per-bucket micro-batcher kept as the
A/B arm), and ``ServingStats`` (aggregate + per-tenant). Steady state per
query: host tokenization + one pre-compiled program run (encoder pass +
NTN score against the tenant's resident class matrix) — no support work,
no compiles.

Fleet behaviors (ISSUE 7):

* **Tenancy end to end** — ``submit(..., tenant=...)`` scopes a query to
  one tenant's snapshot: its relation set, its class matrix, its NOTA
  threshold. Batches never mix tenants (one program call scores against
  one class matrix).
* **Atomic hot-swap** — ``publish_params``/``publish_checkpoint`` push
  new weights from a training artifact into the live engine: in-flight
  batches hold their pinned snapshot and finish on the old weights; no
  query drops, nothing recompiles (programs take params as arguments).
* **NOTA per tenant** (FewRel 2.0, Gao et al. 2019): checkpoints trained
  with ``na_rate > 0`` carry a learned none-of-the-above head whose logit
  is appended as class N; the tenant threshold biases it. Tenants served
  by a no-NOTA checkpoint can still set an open-set floor: best-class
  logit below the threshold -> ``"no_relation"``.

Request-scoped tracing + SLOs (ISSUE 9):

* ``trace_sample=r`` head-samples 1-in-round(1/r) admissions: a sampled
  request mints a ``TraceContext`` at submit, carries it across the
  client->worker thread hop on the Request, and the execute path
  attributes its latency to four contiguous segments — **queue**
  (admission -> the worker starts stacking its batch), **pack** (host
  stacking/padding), **execute** (device program), **respond** (the
  post-execute host work: batch accounting + per-row verdict build;
  future DELIVERY falls after the stamp — a verdict cannot carry the
  time of its own resolution) — which sum to the request's measured
  end-to-end latency BY CONSTRUCTION (same timestamps). Each sampled
  request emits one ``kind="trace"`` record (buffered, flushed with the
  periodic stats emit and at close — the jsonl write is the one
  per-trace cost worth deferring; rendered as a waterfall by
  tools/obs_report.py) and its verdict carries ``trace_id``. The batch's
  ``serve/execute`` span links every sampled trace id it served (fan-in:
  N admissions -> one launch). Rate 0 (default) short-circuits to a
  no-op before any allocation — the tracing tax is gated < 2% of p50
  exec at the production sampling rate (tests/test_tracing.py).
* ``slo=SLOEngine(...)`` evaluates per-tenant availability+latency
  objectives as multi-window burn rates: every outcome (done, shed,
  rejected, deadline-missed) feeds it through ``ServingStats``, and the
  submit/emit paths tick its evaluation, so a burning tenant trips a
  CRITICAL (with auto-captured diagnostics) without any polling loop.

Prediction-quality observability (ISSUE 10):

* Every verdict carries its **quality features** — ``nota``, ``margin``
  (top-1 class score minus runner-up) and ``entropy`` (softmax entropy
  of the class scores) — computed in ``_verdict`` from the logits row
  already in hand. They feed the per-tenant quality reservoirs in
  ``ServingStats`` (one ``kind="quality"`` record per tenant per emit)
  and, when armed, the online drift detector.
* ``drift=DriftDetector(...)`` (obs/drift.py) compares windowed NOTA
  rate / margin / entropy against a calibration baseline captured from
  the first post-(re)arm traffic; a shift past band trips a once-latched
  WARNING/CRITICAL with auto-captured diagnostics. Every hot-swap
  publish **re-arms** the baseline (``rearm()`` in ``_traced_publish``)
  — new weights legitimately move the prediction distribution.
"""

from __future__ import annotations

import sys
import time

import numpy as np

from induction_network_on_fewrel_tpu.obs.drift import quality_features
from induction_network_on_fewrel_tpu.obs.spans import (
    TraceSampler,
    get_tracker,
    span,
)
from induction_network_on_fewrel_tpu.obs.chaos import (
    ChaosError,
    chaos_active,
    chaos_fire,
)
from induction_network_on_fewrel_tpu.serving.batcher import (
    ContinuousBatcher,
    DynamicBatcher,
    ExecuteError,
    Request,
    Saturated,
)
from induction_network_on_fewrel_tpu.serving.buckets import (
    DEFAULT_BUCKETS,
    QueryProgramCache,
    make_serving_mesh,
    select_bucket,
    stack_queries,
)
from induction_network_on_fewrel_tpu.serving.registry import (
    DEFAULT_TENANT,
    TenantRegistry,
)
from induction_network_on_fewrel_tpu.serving.stats import ServingStats

NO_RELATION = "no_relation"


class _QuantKnobs:
    """Adapter handing the engine's quant kwargs to the one-home
    ``config.resolve_quant_policy`` resolver (None = inherit)."""

    def __init__(self, resident_dtype, quant_probe_every):
        self.resident_dtype = resident_dtype
        self.quant_probe_every = quant_probe_every


class _GeomKnobs:
    """Adapter handing the engine's geometry kwarg to the one-home
    ``config.resolve_geometry_policy`` resolver (None = inherit the
    served config's stored tier ladder)."""

    def __init__(self, geometry_tiers):
        self.geometry_tiers = geometry_tiers
        self.geometry_tier_spread = None


def degraded_verdict(tenant: str, *, snapshot_version: int = -1,
                     latency_ms: float = 0.0,
                     failover: bool = False) -> dict:
    """The degraded-mode NOTA verdict — ONE shape home shared by the
    engine's quarantine path (``_serve_degraded``) and the fleet
    router's failover path (``fleet/router._degraded_future``), so the
    two spellings of "I cannot place this" can never drift apart.
    ``failover=True`` marks the router-side variant (clients and the
    quality stream tell router failover from replica quarantine by
    the flag)."""
    verdict = {
        "label": NO_RELATION,
        "class_index": -1,
        "nota": True,
        "degraded": True,
        "margin": 0.0,
        "entropy": 0.0,
        "tenant": tenant,
        "snapshot_version": snapshot_version,
        "logits": {},
        "latency_ms": latency_ms,
    }
    if failover:
        verdict["failover"] = True
    return verdict


class InferenceEngine:
    def __init__(
        self,
        model,
        params,
        cfg,
        tokenizer,
        k: int | None = None,
        buckets: tuple[int, ...] = DEFAULT_BUCKETS,
        max_queue_depth: int = 64,
        batch_window_s: float = 0.002,
        default_deadline_s: float = 1.0,
        scheduler: str = "continuous",
        tenant_share: float = 0.5,
        dp: int | None = None,
        logger=None,
        watchdog=None,
        slo=None,
        drift=None,
        breaker=None,
        trace_sample: float = 0.0,
        start: bool = True,
        resident_dtype: str | None = None,
        quant_probe_every: int | None = None,
        geometry_tiers: str | None = None,
    ):
        if cfg.model != "induction":
            raise ValueError(
                f"class-vector serving requires --model induction (supports "
                f"distill to per-class vectors); got {cfg.model!r}. Other "
                f"episode heads re-read the support set per query."
            )
        if cfg.feature_cache:
            raise ValueError(
                "feature-cache checkpoints hold head-only params (no "
                "encoder) — the serving engine cannot tokenize queries "
                "through them; serve a full checkpoint instead"
            )
        if scheduler not in ("continuous", "microbatch"):
            raise ValueError(
                f"scheduler must be 'continuous' or 'microbatch', "
                f"got {scheduler!r}"
            )
        self.cfg = cfg
        self.model = model
        self.tokenizer = tokenizer
        self.nota = cfg.na_rate > 0
        self.max_length = cfg.max_length
        self.default_deadline_s = default_deadline_s
        self.scheduler = scheduler
        self._logger = logger
        self._emit_step = 0
        # Telemetry spine (obs/): serving counters join the shared
        # counter registry (Prometheus exposition + run reports see train
        # and serving through one namespace); the optional watchdog gets
        # queue-stall observations on every stats emit.
        self.watchdog = watchdog
        if watchdog is not None and logger is not None:
            logger.add_hook(watchdog.observe_record)
        # Request-scoped tracing (ISSUE 9): deterministic head sampler.
        # Rate 0 = maybe_trace() is a no-op returning None — no trace
        # contexts, no records, nothing on the hot path.
        self._tracer = TraceSampler(trace_sample)
        # Per-tenant SLO burn-rate engine (obs/health.SLOEngine): every
        # outcome ServingStats records feeds its windows; the engine's
        # logger/recorder default to ours when unset.
        self.slo = slo
        if slo is not None and slo.logger is None:
            slo.logger = logger
        # Online prediction-drift detector (obs/drift.py, ISSUE 10): fed
        # one observation per verdict from the emit path; re-armed on
        # every hot-swap publish. None (default) costs one `if`.
        self.drift = drift
        if drift is not None and drift.logger is None:
            drift.logger = logger
        # Per-tenant circuit breaker (ISSUE 12, serving/breaker.py): a
        # repeatedly failing tenant sheds at submit (zero device time)
        # until a half-open probe proves recovery. None (default) costs
        # one `if` per submit. Transitions emit kind="fault" records —
        # the watchdog latches CRITICAL breaker_open per tenant,
        # re-armed by the close transition.
        self.breaker = breaker
        if breaker is not None and breaker.on_transition is None:
            breaker.on_transition = self._on_breaker_transition

        # Quantized serving knobs (ISSUE 18): None inherits the served
        # config's stored values through the one-home resolver — a train
        # run that stamped resident_dtype serves quantized with no flag.
        from induction_network_on_fewrel_tpu.config import (
            resolve_geometry_policy,
            resolve_quant_policy,
        )

        quant = resolve_quant_policy(
            _QuantKnobs(resident_dtype, quant_probe_every), base=cfg
        )
        # Geometry plane (ISSUE 19): the N-tier ladder resident class
        # stacks pad to. None inherits the served config's stored spec
        # through the one-home resolver, exactly like the quant knobs.
        geom = resolve_geometry_policy(_GeomKnobs(geometry_tiers), base=cfg)
        self.quant_probe_every = quant["probe_every"]
        # Parity-probe cadence counter: only the single batcher worker
        # thread touches it (_run_group), so a plain int is race-free.
        self._quant_batches = 0

        self.stats = ServingStats(slo=slo)
        self.stats.bind_registry()
        # Sampled trace records awaiting their deferred jsonl flush
        # (_emit_trace / _flush_traces).
        self._pending_traces: list[dict] = []
        self.registry = TenantRegistry(
            model, params, tokenizer,
            k=k if k is not None else cfg.k, logger=logger,
            resident_dtype=quant["resident_dtype"],
            tiers=geom["tiers"],
        )
        # Read the ladder BACK from the registry: a stats-head NOTA
        # checkpoint forces exact-N there (supports_tiering), and the
        # engine's tier-crossing warmup must agree with what the
        # registry actually publishes.
        self.tiers = self.registry.tiers
        # Capacity accounting (ISSUE 18): the density denominator. The
        # stats object exposes chip-resident bytes per tenant through
        # the same snapshot/registry-gauge spine as every other serving
        # counter — fleet rollups read it off stats_snapshot rows.
        self.stats.bind_resident(self.registry.resident_bytes)
        self._mesh = make_serving_mesh(dp) if dp and dp > 1 else None
        self.programs = QueryProgramCache(
            model, stats=self.stats, mesh=self._mesh
        )
        if scheduler == "continuous":
            self.batcher = ContinuousBatcher(
                self._execute_group,
                buckets=buckets,
                max_queue_depth=max_queue_depth,
                tenant_share=tenant_share,
                stats=self.stats,
                start=start,
            )
        else:
            self.batcher = DynamicBatcher(
                self._execute_batch,
                buckets=buckets,
                max_queue_depth=max_queue_depth,
                batch_window_s=batch_window_s,
                stats=self.stats,
                start=start,
            )

    # ``params`` stays readable for compat (loadgen parity harness, tests)
    # but the truth lives in the registry — hot-swaps move it.
    @property
    def params(self):
        return self.registry.params

    # --- construction from a trained artifact ----------------------------

    @classmethod
    def from_checkpoint(
        cls, ckpt_dir: str, device: str | None = None,
        glove: str | None = None, glove_mat: str | None = None, **kw
    ) -> "InferenceEngine":
        """Build an engine from a checkpoint directory: the stored
        config.json decides the architecture (exactly as test.py does), the
        best checkpoint (falling back to the recovery ring) supplies the
        weights. ``device`` overrides the stored --device for serving."""
        import jax

        from induction_network_on_fewrel_tpu.data import make_synthetic_glove
        from induction_network_on_fewrel_tpu.data.glove import load_glove
        from induction_network_on_fewrel_tpu.data.tokenizer import GloveTokenizer
        from induction_network_on_fewrel_tpu.models import build_model
        from induction_network_on_fewrel_tpu.train.checkpoint import (
            CheckpointManager,
        )
        from induction_network_on_fewrel_tpu.train.steps import init_state

        cfg = CheckpointManager.load_config(ckpt_dir)
        if device is not None:
            cfg = cfg.replace(device=device)
        if cfg.encoder == "bert":
            from induction_network_on_fewrel_tpu.data.bert_tokenizer import (
                BertTokenizer,
            )

            vocab = None
            tok = BertTokenizer(
                cfg.max_length, vocab_path=cfg.bert_vocab_path,
                vocab_size=cfg.bert_vocab_size,
            )
        else:
            vocab = (
                load_glove(glove, glove_mat) if glove
                else make_synthetic_glove(
                    vocab_size=cfg.vocab_size - 2, word_dim=cfg.word_dim
                )
            )
            if (cfg.vocab_size, cfg.word_dim) != (vocab.vocab_size, vocab.word_dim):
                raise ValueError(
                    f"vocab {vocab.vocab_size}x{vocab.word_dim} does not "
                    f"match the checkpoint's embedding table "
                    f"{cfg.vocab_size}x{cfg.word_dim} — pass the GloVe file "
                    f"the model was trained with"
                )
            tok = GloveTokenizer(vocab, max_length=cfg.max_length)
        model = build_model(
            cfg, glove_init=vocab.vectors if vocab is not None else None
        )
        # Restore target: the same state tree training would build (shapes
        # only — the zero token ids never influence the restored weights).
        from induction_network_on_fewrel_tpu.serving.buckets import zero_batch

        state = init_state(
            model, cfg,
            zero_batch(cfg.max_length, (1, cfg.n, cfg.k)),
            zero_batch(cfg.max_length, (1, cfg.total_q)),
        )
        mngr = CheckpointManager(ckpt_dir, cfg)
        try:
            try:
                state, step = mngr.restore_best(state)
                which = "best"
            except FileNotFoundError:
                state, step = mngr.restore_latest(state)
                which = "latest"
        finally:
            mngr.close()
        print(
            f"serving {which} checkpoint step={step} from {ckpt_dir} "
            f"on {jax.default_backend()}",
            file=sys.stderr,
        )
        return cls(model, state.params, cfg, tok, **kw)

    # --- registration / tenant lifecycle ----------------------------------

    def register_class(
        self, name: str, instances, tenant: str = DEFAULT_TENANT
    ) -> None:
        self._warm_tier_crossing(tenant, (name,))
        self.registry.register(name, instances, tenant=tenant)
        self._drift_rearm(tenant, f"register_class {name!r}")

    def register_dataset(
        self, dataset, max_classes: int | None = None,
        tenant: str = DEFAULT_TENANT,
    ) -> list[str]:
        adding = list(dataset.rel_names)
        if max_classes is not None:
            adding = adding[:max_classes]
        self._warm_tier_crossing(tenant, adding)
        names = self.registry.register_dataset(
            dataset, max_classes=max_classes, tenant=tenant
        )
        self._drift_rearm(tenant, f"register_dataset ({len(names)} classes)")
        return names

    def _warm_tier_crossing(self, tenant: str, adding) -> int:
        """Warm-before-swap on N-tier crossings (ISSUE 19): when a
        registration will push a LIVE tenant across a tier boundary
        (its 9th relation migrates the 8-tier stack to 16), compile the
        new tier's bucket programs FIRST — counted as warmup, exactly
        like ``set_resident_dtype`` warms a dtype roll — so the
        tenant's next batch after the republish hits a ready
        executable and the zero-steady-state-recompile gate holds
        across the crossing. First registrations are untouched: setup
        flows call ``warmup()`` after registering, the existing
        discipline. Returns the programs compiled (0 = no crossing)."""
        if self.tiers is None or not self.registry.has_tenant(tenant):
            return 0
        snap = self.registry.snapshot(tenant)
        cur_tier, c = snap.matrix.shape
        new_names = set(snap.names) | set(adding)
        new_tier = self.registry.tier_of(len(new_names))
        if new_tier <= cur_tier:
            return 0
        dtypes = [snap.resident_dtype]
        if self.quant_probe_every > 0 and snap.resident_dtype != "f32":
            dtypes.append("f32")
        return self.programs.warmup(
            snap.params, new_tier, c, self.batcher.buckets,
            self.max_length, dtypes=tuple(dtypes),
        )

    def set_nota_threshold(
        self, threshold: float | None, tenant: str = DEFAULT_TENANT
    ) -> None:
        self.registry.set_nota_threshold(threshold, tenant=tenant)
        self._drift_rearm(tenant, "nota_threshold change")

    def _drift_rearm(self, tenant: str, reason: str) -> None:
        """Per-tenant control-plane changes (new classes, a threshold
        adjustment) legitimately move THAT tenant's prediction
        distribution just like a publish moves everyone's — the drift
        baseline re-arms so a routine registry action never reads as a
        model-quality incident. No-op (and event-free) when the tenant
        has no accumulated drift state, so setup-time registration stays
        silent."""
        if self.drift is not None:
            self.drift.rearm(tenant, reason=reason)

    @property
    def class_names(self) -> tuple[str, ...]:
        return self.registry.names

    def warmup(self) -> int:
        """AOT-compile every bucket's query program for every registered
        tenant's (class count, resident dtype); returns how many programs
        this call compiled (tenants sharing both share programs). When the
        parity police is armed, a quantized tenant's f32 SHADOW programs
        compile here too — a steady-state probe must never be the first
        caller of an f32 signature. After warmup, steady-state traffic is
        zero-recompile (stats.steady_recompiles counts violations)."""
        compiled = 0
        for tenant in self.registry.tenants():
            snap = self.registry.snapshot(tenant)
            n, c = snap.matrix.shape
            dtypes = [snap.resident_dtype]
            if self.quant_probe_every > 0 and snap.resident_dtype != "f32":
                dtypes.append("f32")
            compiled += self.programs.warmup(
                snap.params, n, c, self.batcher.buckets, self.max_length,
                dtypes=tuple(dtypes),
            )
        return compiled

    def set_resident_dtype(self, tenant: str, dtype: str):
        """Re-quantize one live tenant to ``dtype`` — the parity-alarm
        rollback path (RUNBOOK: roll the tenant to "f32" when the quant
        parity alarm fires). Compiles the new dtype's bucket programs
        FIRST (counted as warmup), then swaps the registry snapshot, so
        the tenant's next batch hits a ready executable: the
        zero-steady-state-recompile gate holds across the roll. Re-arms
        the tenant's drift baseline — residency changes the margin
        distribution by construction, and the parity latches must clear
        once the regression is rolled away."""
        snap = self.registry.snapshot(tenant)
        n, c = snap.matrix.shape
        dtypes = [dtype]
        if self.quant_probe_every > 0 and dtype != "f32":
            dtypes.append("f32")
        self.programs.warmup(
            snap.params, n, c, self.batcher.buckets, self.max_length,
            dtypes=tuple(dtypes),
        )
        snap = self.registry.set_resident_dtype(tenant, dtype)
        self._drift_rearm(tenant, reason=f"resident_dtype {dtype}")
        return snap

    # --- hot-swap publish -------------------------------------------------

    def _traced_publish(self, publish_fn, **span_attrs) -> int:
        """Control-plane tracing shared by both publish spellings: the
        publish runs under its own trace context (always — this is not
        the hot path), so the publish span and the registry's re-distill
        spans share one trace id, and a ``kind="trace"`` control record
        (op="publish") lands next to the request waterfalls it may have
        perturbed."""
        tracker = get_tracker()
        t0 = time.monotonic()
        with tracker.trace() as ctx:
            with tracker.span("serve/publish", **span_attrs):
                version = publish_fn()
        self.stats.record_swap()
        if self.drift is not None:
            # A publish legitimately moves the prediction distribution
            # (new weights, re-distilled class vectors): drop baselines +
            # windows + latches and re-calibrate from the first
            # post-publish traffic — a publish must never read as drift,
            # and post-publish drift must be judged against the NEW
            # normal.
            self.drift.rearm(reason=f"snapshot_swap v{version}")
        self._emit_trace({
            "trace_id": ctx.trace_id,
            "op": "publish",
            "publish_ms": round((time.monotonic() - t0) * 1e3, 3),
            "params_version": float(version),
            "tenants": float(len(self.registry.tenants())),
        })
        return version

    def publish_params(self, new_params) -> int:
        """Atomic hot-swap: every tenant's class vectors re-distill with
        ``new_params`` and republish; in-flight batches finish on their
        pinned snapshot; zero recompiles. Returns the params_version."""
        return self._traced_publish(
            lambda: self.registry.publish_params(new_params)
        )

    def publish_checkpoint(self, ckpt_dir: str) -> int:
        """Hot-swap straight from a training checkpoint directory (traced
        like publish_params — the restore rides the same publish span)."""
        return self._traced_publish(
            lambda: self.registry.publish_checkpoint(ckpt_dir),
            source=ckpt_dir,
        )

    # Two-phase publish (fleet fan-out, ISSUE 13): the control plane
    # prepares EVERY replica before committing ANY (fleet/control.py).
    # Commit runs through _traced_publish so a fan-out publish gets the
    # same span, swap counter, and drift re-arm a local publish gets.

    def prepare_publish(self, new_params, target_version=None):
        """Phase 1 on this replica: validation gate + full re-distill,
        nothing visible to the data plane yet. Returns the registry's
        ``PublishTransaction``; the caller must ``commit_publish`` or
        abort it (same thread). ``target_version`` pins the generation
        the commit lands at — the recovery catch-up spelling (a
        restarted replica re-drives the journaled publish AT the
        fleet's committed version, ISSUE 15)."""
        return self.registry.prepare_publish(
            new_params, target_version=target_version
        )

    def commit_publish(self, txn) -> int:
        """Phase 2: commit a prepared transaction with the engine-side
        publish bookkeeping (trace span, stats.record_swap, drift
        re-arm) a plain ``publish_params`` performs."""
        return self._traced_publish(txn.commit)

    # --- query path ------------------------------------------------------

    def submit(
        self, instance, deadline_s: float | None = None,
        tenant: str = DEFAULT_TENANT, trace=None,
    ):
        """Tokenize one query and enqueue it for ``tenant``; returns a
        Future resolving to the verdict dict. Raises ``Saturated`` under
        backpressure (with ``.tenant`` set when the breach is this
        tenant's share — shed-load). ``trace`` adopts a TraceContext a
        caller already minted (the fleet router's front door, ISSUE 13)
        instead of head-sampling here — the request's segments then join
        the router's trace id across the hop."""
        self.registry.snapshot(tenant)   # raises for unknown tenants
        if self.breaker is not None:
            # Open breaker = shed at the door (ISSUE 12): a repeatedly
            # failing tenant must not occupy launches other tenants
            # could use. Deterministic half-open probes pass through.
            retry = self.breaker.admit(tenant)
            if retry is not None:
                self.stats.record_breaker_shed(tenant)
                if self.slo is not None:
                    # Same discipline as the finally-tick below: a
                    # fully-shed tenant is exactly the one whose SLO
                    # windows must still evaluate.
                    self.slo.maybe_evaluate()
                raise Saturated(retry, tenant=tenant)
        if trace is None:
            trace = self._tracer.maybe_trace()   # None when unsampled
        if trace is None:
            t = self.tokenizer(self._as_instance(instance))
        else:
            # The admission span: the first span of a fresh trace becomes
            # its originating span (ctx.span_id), so the worker-side
            # execute spans stitch back to it across the thread hop.
            tracker = get_tracker()
            with tracker.trace(trace):
                # xplane=False: host-only tokenization — the named-scope
                # bridge would name nothing in a device profile and its
                # jit-dispatch perturbation was the dominant tracing tax.
                with tracker.span("serve/submit", xplane=False,
                                  tenant=tenant):
                    t = self.tokenizer(self._as_instance(instance))
        query = {"word": t.word, "pos1": t.pos1, "pos2": t.pos2, "mask": t.mask}
        try:
            fut = self.batcher.submit(
                query,
                deadline_s if deadline_s is not None
                else self.default_deadline_s,
                tenant=tenant,
                trace=trace,
            )
        finally:
            if self.slo is not None:
                # Burn-rate tick from the client thread (throttled to
                # once per bucket internally), in a finally ON PURPOSE:
                # a rejected/shed submit raises Saturated AFTER the
                # batcher recorded the bad outcome, and a fully-shed
                # tenant — the tenant MOST likely to be burning — would
                # otherwise never get its windows evaluated (no batches
                # execute, so the emit-path tick never fires either).
                self.slo.maybe_evaluate()
        if self.watchdog is not None:
            # Stall observation from the CLIENT thread: the execute-path
            # observations below come from the worker itself, which is
            # exactly the thread that has wedged when a stall is real —
            # submitters are the independent observer that can still see
            # a deep queue with a frozen served counter.
            self.watchdog.observe_queue(
                self.batcher.queue_depth, self.stats.served
            )
        return fut

    def classify(
        self, instance, deadline_s: float | None = None,
        tenant: str = DEFAULT_TENANT,
    ) -> dict:
        """Synchronous submit + wait."""
        fut = self.submit(instance, deadline_s, tenant=tenant)
        timeout = (deadline_s or self.default_deadline_s) + 5.0
        return fut.result(timeout=timeout)

    def _execute_group(self, tenant: str, batch: list[Request]) -> None:
        """Continuous-scheduler callback: one tenant's batch."""
        try:
            self._run_group(tenant, batch)
        except BaseException as e:  # noqa: BLE001 — contain, never wedge
            self._contain_execute_failure(tenant, batch, e)
        self._maybe_emit()

    def _execute_batch(self, batch: list[Request]) -> None:
        """Micro-batcher callback: the collected batch may mix tenants
        (the old scheduler's single queue is tenant-blind) — split and run
        one program call per tenant sub-batch. This is exactly the
        occupancy tax the continuous scheduler removes, kept as the honest
        A/B baseline."""
        by_tenant: dict[str, list[Request]] = {}
        for r in batch:
            by_tenant.setdefault(r.tenant, []).append(r)
        for tenant, group in by_tenant.items():
            try:
                self._run_group(tenant, group)
            except BaseException as e:  # noqa: BLE001 — isolate per tenant
                # One tenant's failure (dropped mid-flight, bad matrix)
                # fails ITS futures only; the other tenants' sub-batches
                # still execute.
                self._contain_execute_failure(tenant, group, e)
        self._maybe_emit()

    def _contain_execute_failure(
        self, tenant: str, batch: list[Request], exc: BaseException
    ) -> None:
        """Fault containment for one failed launch (ISSUE 12): the
        batch's futures fail with a TYPED ``ExecuteError`` carrying a
        retry-after hint — never the raw exception, never a wedged
        worker, never another tenant's batch — the failure feeds the
        tenant's circuit breaker, and one kind="fault" record attributes
        it. Exceptions escaping THIS method would hit the batcher's
        last-resort catch (worker still survives)."""
        retry = (
            self.breaker.open_s if self.breaker is not None
            else 2.0 * self.stats.exec_estimate_s()
        )
        err = ExecuteError(tenant, retry_after_s=retry, cause=exc)
        for r in batch:
            if not r.future.done():
                r.future.set_exception(err)
        self.stats.record_execute_error(tenant, len(batch))
        if self.breaker is not None:
            self.breaker.record_failure(tenant)
        if self._logger is not None:
            self._logger.log(
                self.stats.served, kind="fault", action="execute_error",
                tenant=tenant, requests=float(len(batch)),
                cause=f"{type(exc).__name__}: {exc}",
            )

    def _run_group(self, tenant: str, batch: list[Request]) -> None:
        # Pinned snapshot: (params, matrix, names, threshold) captured
        # atomically — concurrent registration or a hot-swap publish must
        # not skew the verdict index -> name mapping mid-batch, and the
        # batch must score against the weights its matrix was distilled
        # with (registry.Snapshot doc).
        snap = self.registry.snapshot(tenant)
        if snap.degraded:
            # Fleet degraded mode (ISSUE 12): the tenant's snapshot is
            # quarantined — serve open-set-floor NOTA verdicts flagged
            # degraded=True instead of scoring against a suspect matrix.
            # Zero device time; clients get an honest answer, not an
            # error.
            self._serve_degraded(tenant, batch, snap)
            if self.breaker is not None:
                # A degraded serve ANSWERS its requests — it must count
                # as a breaker outcome, or a half-open probe routed here
                # would report nothing and wedge the breaker in
                # half_open (probes exhausted, no launch ever runs to
                # close it), shedding the tenant forever.
                self.breaker.record_success(tenant)
            return
        if chaos_active() and chaos_fire(
            "serve.execute_raise", tenant=tenant, step=self.stats.served
        ) is not None:
            raise ChaosError(
                f"injected execute failure for tenant {tenant!r} (chaos)"
            )
        bucket = select_bucket(len(batch), self.batcher.buckets)
        # Fan-in: the sampled requests this launch serves. Their trace
        # ids link into the batch spans, and each gets a per-request
        # segment record after the futures resolve. The untraced fast
        # path is one list-comp over fields already in hand.
        traced = [r for r in batch if r.trace is not None]
        links = tuple(r.trace.trace_id for r in traced)
        t_stack = time.monotonic()
        with span("serve/stack", links=links, rows=len(batch), bucket=bucket):
            query = stack_queries([r.query for r in batch], bucket)
        t0 = time.monotonic()
        with span("serve/execute", links=links, rows=len(batch),
                  bucket=bucket):
            logits = self.programs.run(
                snap.params, snap.matrix, query, scale=snap.scale
            )
        t_exec_end = time.monotonic()
        exec_s = t_exec_end - t0
        self.stats.record_batch(len(batch), bucket, exec_s)
        if self.breaker is not None:
            # A completed launch: resets the failure streak; in
            # half-open, the successful probe CLOSES the breaker.
            self.breaker.record_success(tenant)
        # Two passes on purpose: the verdict BUILD (per-row argmax + an
        # N-class logits dict — the O(batch) host work after execute)
        # happens before ``now`` so the respond segment and latency_ms
        # include it; only the set_result delivery itself falls after
        # the stamp (a verdict cannot carry the time of its own
        # delivery).
        resolved = [
            (req, self._verdict(row, snap))
            for row, req in zip(logits, batch)   # zip drops the pad rows
        ]
        now = time.monotonic()
        for req, verdict in resolved:
            verdict["latency_ms"] = round((now - req.enqueued_at) * 1e3, 3)
            if req.trace is not None:
                verdict["trace_id"] = req.trace.trace_id
            self.stats.record_done(
                now - req.enqueued_at, tenant=tenant,
                trace_id=req.trace.trace_id if req.trace is not None else None,
                nota=verdict["nota"], margin=verdict["margin"],
                entropy=verdict["entropy"],
            )
            req.future.set_result(verdict)
        if self.drift is not None:
            # AFTER the resolution loop on purpose: a drift CRITICAL
            # writes its diagnostics capture synchronously on this
            # thread, and doing that mid-loop would stall delivery of
            # the batch's remaining futures on disk I/O. Detection lags
            # by at most one batch; clients never wait on a capture.
            for _, verdict in resolved:
                self.drift.observe(
                    tenant, nota=verdict["nota"],
                    margin=verdict["margin"], entropy=verdict["entropy"],
                )
        if self.quant_probe_every > 0 and snap.shadow is not None:
            # Parity police (ISSUE 18, the grad_probe_every of serving):
            # every K-th quantized batch re-scores the SAME padded query
            # block against the tenant's f32 shadow matrix and compares
            # VERDICTS (the FewRel 2.0 acceptance bar — NOTA flips and
            # label flips — not raw logit equality) plus margin drift.
            # Also after the resolution loop: the probe pays a second
            # program launch and may write a drift capture; clients
            # never wait on either.
            self._quant_batches += 1
            if self._quant_batches % self.quant_probe_every == 0:
                self._parity_probe(tenant, snap, query, logits, len(batch))
        if traced:
            # now - enqueued_at == queue + pack + execute + respond by
            # construction: the four segments tile [enqueued_at, now]
            # with the SAME timestamps the latency is measured from, so
            # the waterfall obs_report renders sums to the measured
            # latency exactly (the acceptance bar allows 5%; this is 0).
            pack_ms = (t0 - t_stack) * 1e3
            exec_ms = (t_exec_end - t0) * 1e3
            respond_ms = (now - t_exec_end) * 1e3
            for req in traced:
                self._emit_trace({
                    "trace_id": req.trace.trace_id,
                    "tenant": tenant,
                    "scheduler": self.scheduler,
                    "bucket": float(bucket),
                    "rows": float(len(batch)),
                    "queue_ms": round((t_stack - req.enqueued_at) * 1e3, 3),
                    "pack_ms": round(pack_ms, 3),
                    "execute_ms": round(exec_ms, 3),
                    "respond_ms": round(respond_ms, 3),
                    "total_ms": round((now - req.enqueued_at) * 1e3, 3),
                })

    def _serve_degraded(self, tenant: str, batch: list[Request],
                        snap) -> None:
        """Degraded-mode verdicts for a quarantined tenant: every request
        resolves ``no_relation`` with ``degraded=True`` (the open-set
        floor's honest "I cannot place this" answer), no device time, no
        drift/quality observation (degraded traffic says nothing about
        the model), one kind="fault" record per batch."""
        now = time.monotonic()
        for req in batch:
            verdict = degraded_verdict(
                tenant, snapshot_version=snap.version,
                latency_ms=round((now - req.enqueued_at) * 1e3, 3),
            )
            if req.trace is not None:
                verdict["trace_id"] = req.trace.trace_id
            # nota=None on purpose: degraded verdicts must not skew the
            # tenant's quality stream or a drift baseline.
            self.stats.record_done(
                now - req.enqueued_at, tenant=tenant,
                trace_id=(
                    req.trace.trace_id if req.trace is not None else None
                ),
            )
            req.future.set_result(verdict)
        self.stats.record_degraded(tenant, len(batch))
        if self._logger is not None:
            self._logger.log(
                self.stats.served, kind="fault",
                action="degraded_verdicts", tenant=tenant,
                served=float(len(batch)),
            )

    def _parity_probe(self, tenant: str, snap, query, logits, rows) -> None:
        """One sampled shadow-score: re-run the padded query block against
        the tenant's f32 shadow matrix, compare per-row VERDICTS (label +
        NOTA flag) and margins, and feed the results to stats and the
        drift detector's parity bands — a quantization regression trips
        the SAME alarm path as model drift. Probe failures are contained
        here (one fault record): the batch already answered its clients,
        so a broken probe must not fail futures or feed the breaker."""
        try:
            ref = self.programs.run(snap.params, snap.shadow, query)
            agree, drift_sum = 0, 0.0
            for i in range(rows):
                vq = self._verdict(logits[i], snap)
                vf = self._verdict(ref[i], snap)
                if vq["label"] == vf["label"] and vq["nota"] == vf["nota"]:
                    agree += 1
                drift_sum += abs(vq["margin"] - vf["margin"])
            agreement = agree / rows
            margin_drift = drift_sum / rows
            self.stats.record_quant_probe(
                tenant, agreement, margin_drift, rows
            )
            if self.drift is not None:
                self.drift.observe_parity(
                    tenant, agreement=agreement,
                    margin_drift=margin_drift, rows=rows,
                )
        except Exception as e:  # noqa: BLE001 — probe must not hurt serving
            if self._logger is not None:
                self._logger.log(
                    self.stats.served, kind="fault",
                    action="quant_probe_error", tenant=tenant,
                    cause=f"{type(e).__name__}: {e}",
                )

    def _on_breaker_transition(self, tenant, frm, to, failures, now) -> None:
        """Breaker transitions -> one kind="fault" record each; the
        watchdog latches CRITICAL ``breaker_open`` on to="open" and
        re-arms on to="closed"."""
        if self._logger is not None:
            self._logger.log(
                self.stats.served, kind="fault", action="breaker",
                tenant=tenant, **{"from": frm, "to": to},
                failures=float(failures),
            )

    def quarantine_tenant(self, tenant: str, reason: str = "") -> None:
        """Degrade one tenant (registry.quarantine_tenant): its traffic
        gets open-set-floor NOTA verdicts flagged degraded=True until
        unquarantine or the next successful publish."""
        self.registry.quarantine_tenant(tenant, reason=reason)

    def unquarantine_tenant(self, tenant: str, reason: str = "") -> None:
        self.registry.unquarantine_tenant(tenant, reason=reason)
        self._drift_rearm(tenant, f"unquarantine {reason}".strip())

    def _emit_trace(self, rec: dict) -> None:
        """One sampled request's segment record: retained for artifact
        summaries (stats) immediately; the kind="trace" jsonl line is
        BUFFERED and flushed with the periodic stats emit — the logger's
        per-record write+flush (crash-visibility for metrics) is the
        single biggest per-trace cost, and deferring it keeps the
        execute path's tracing tax under the 2%-of-p50-exec gate. List
        appends are GIL-atomic; ``_flush_traces`` swaps the buffer out."""
        self.stats.record_trace(rec)
        if self._logger is not None:
            self._pending_traces.append(rec)

    def _flush_traces(self) -> None:
        if self._logger is None or not self._pending_traces:
            return
        pending, self._pending_traces = self._pending_traces, []
        for rec in pending:
            self._logger.log(self.stats.served, kind="trace", **rec)

    def _verdict(self, row: np.ndarray, snap) -> dict:
        """One logits row -> verdict dict under the tenant's NOTA policy.

        With a trained NOTA head the snapshot threshold BIASES the
        no-relation logit (0.0 = the head's own calibration, the
        pre-fleet behavior); without one, a set threshold is an open-set
        floor on the best class logit. Ties resolve toward the class —
        matching the plain-argmax convention the pre-tenant engine had.

        N-tier residency (ISSUE 19): ``row`` carries ``n_tier`` class
        scores (+1 NOTA) but only the first ``n_classes`` are real —
        the argmax, quality features, logits dict, and NOTA comparison
        all slice to the real columns (the pad "mask" is never reading
        them), and the NOTA logit is appended AFTER the matrix rows so
        it lives at ``row[-1]`` for every tier (== ``row[n]`` under
        exact-N). A pad class can therefore never win a verdict at any
        threshold — pinned in tests/test_geometry.py."""
        names = snap.names
        n = len(names)
        best = int(np.argmax(row[:n]))
        thr = snap.nota_threshold
        if self.nota:
            is_nota = float(row[-1]) + (thr or 0.0) > float(row[best])
        else:
            is_nota = thr is not None and float(row[best]) < thr
        # Quality features (ISSUE 10): shared formula home in
        # obs/drift.quality_features (class scores only — see its doc),
        # so the offline calibration baseline and this online path can
        # never disagree. O(n) numpy on the row in hand.
        m_arr, e_arr = quality_features(row[:n])
        margin, entropy = float(m_arr), float(e_arr)
        verdict = {
            "label": NO_RELATION if is_nota else names[best],
            "class_index": -1 if is_nota else best,
            "nota": is_nota,
            "margin": round(margin, 6),
            "entropy": round(entropy, 6),
            "tenant": snap.tenant,
            "snapshot_version": snap.version,
            "logits": {nm: float(row[i]) for i, nm in enumerate(names)},
        }
        if self.nota:
            verdict["logits"][NO_RELATION] = float(row[-1])
        return verdict

    # --- observability / lifecycle ---------------------------------------

    def _maybe_emit(self, every: int = 50) -> None:
        if self.watchdog is not None:
            self.watchdog.observe_queue(
                self.batcher.queue_depth, self.stats.served
            )
        if self.slo is not None:
            self.slo.maybe_evaluate()
        if self._logger is None:
            return
        if self.stats.batches - self._emit_step >= every:
            self._emit_step = self.stats.batches
            self._flush_traces()
            self.stats.emit(
                self._logger, self._emit_step,
                queue_depth=self.batcher.queue_depth,
            )
            if self.drift is not None:
                self.drift.emit(self._logger, self._emit_step)

    def emit_stats(self) -> None:
        if self.watchdog is not None:
            self.watchdog.observe_queue(
                self.batcher.queue_depth, self.stats.served
            )
        if self.slo is not None:
            self.slo.evaluate()
        self._flush_traces()
        if self._logger is not None:
            self.stats.emit(
                self._logger, self.stats.batches,
                queue_depth=self.batcher.queue_depth,
            )
            if self.drift is not None:
                self.drift.emit(self._logger, self.stats.batches)

    def close(self) -> None:
        self.batcher.close()
        self.emit_stats()
        # Unbinding drops this engine's gauges from the registry — any
        # final scrape (serve_main writes metrics.prom) must happen BEFORE
        # close. A closed engine must not stay pinned in (or serve stale
        # values from) the global registry for the rest of the process.
        self.stats.unbind_registry()

    @staticmethod
    def _as_instance(x):
        from induction_network_on_fewrel_tpu.data.fewrel import Instance

        if isinstance(x, Instance):
            return x
        if isinstance(x, dict):
            if "h" in x:                       # raw FewRel JSON schema
                return Instance.from_raw(x)
            return Instance(
                tokens=tuple(x["tokens"]),
                head_pos=tuple(x.get("head_pos", (0,))),
                tail_pos=tuple(x.get("tail_pos", (0,))),
            )
        raise TypeError(f"cannot interpret query of type {type(x).__name__}")
