"""Shape buckets + AOT-compiled query programs (zero steady-state recompiles).

XLA compiles one program per input shape, and a serving engine that jits on
whatever batch arrives pays a multi-second compile whenever a new batch size
shows up — unacceptable at request latency. So the query path runs against a
SMALL FIXED SET of batch-size buckets: every micro-batch is padded up to the
nearest bucket, each bucket's program is lowered + compiled ahead of time
(``warmup``), and steady-state serving touches only those executables.

The TPU static-shape discipline is the same one the training stack lives by
(fixed ``max_length``, fixed episode geometry per compile); buckets extend it
to the request axis. Compiles are COUNTED — the acceptance gate for the
engine is "zero recompiles after warmup", and ``tools/loadgen.py`` asserts
it — so this module owns the executables explicitly (jax AOT: lower ->
compile keyed by (n_classes, bucket, resident dtype)) instead of hiding
them in jit's cache.

Geometry plane (ISSUE 19): the cache derives its class axis from the
resident matrix's ROW COUNT, so the key is whatever geometry the registry
publishes. Under N-tier residency (serving/geometry.py) the registry pads
every [N, C] stack up to a small fixed tier ladder before it becomes
resident — the key here becomes ``(n_tier, bucket, resident dtype)`` with
no cache-side changes, and the compiled-program count is bounded by
tiers x buckets x dtypes regardless of how many distinct relation counts
the fleet's tenants carry (``geometry.program_bound``, asserted by the
tier-1 gate).
"""

from __future__ import annotations

from typing import Any

import ml_dtypes
import numpy as np

# Powers of two up to 16: at CPU/TPU serving shapes the encoder matmuls for
# a 16-row bucket are still tiny, and 5 programs keep warmup around a second
# on CPU. Override per engine for heavier traffic.
DEFAULT_BUCKETS = (1, 2, 4, 8, 16)

# Wire dtypes for query leaves — the same narrowing the training path uses
# (models/build.batch_to_model_inputs): pos offsets fit int16, mask int8.
# The AOT executables are shape- AND dtype-exact, so there is exactly one
# owner of this contract.
QUERY_DTYPES = {
    "word": np.int32, "pos1": np.int16, "pos2": np.int16, "mask": np.int8,
}

# Resident class-matrix dtypes (ISSUE 18 quantized serving). The AOT
# executables are dtype-exact, so the resident dtype is PART of the program
# cache key — mixed-precision tenants co-resident on one replica each hit
# their own compiled program instead of colliding in one signature. int8
# programs additionally take the per-tenant symmetric dequant scale (f32
# scalar) as an argument, so re-quantizing a tenant never recompiles.
RESIDENT_DTYPES = {
    "f32": np.dtype(np.float32),
    "bf16": np.dtype(ml_dtypes.bfloat16),
    "int8": np.dtype(np.int8),
}
_DTYPE_NAMES = {v: k for k, v in RESIDENT_DTYPES.items()}


def resident_dtype_name(dtype) -> str:
    """np dtype of a resident class matrix -> its knob name ("f32"/...)."""
    name = _DTYPE_NAMES.get(np.dtype(dtype))
    if name is None:
        raise ValueError(
            f"class matrix dtype {np.dtype(dtype)} is not a resident dtype "
            f"(expected one of {sorted(RESIDENT_DTYPES)})"
        )
    return name


def zero_batch(max_length: int, lead: tuple[int, ...]) -> dict[str, np.ndarray]:
    """All-zeros token batch with leading shape ``lead`` in the wire dtypes
    — the shared init/restore-target shape builder (model init only reads
    shapes, and token id 0 is always valid)."""
    return {
        k: np.zeros(lead + (max_length,), dt) for k, dt in QUERY_DTYPES.items()
    }


def select_bucket(n: int, buckets: tuple[int, ...] = DEFAULT_BUCKETS) -> int:
    """Smallest bucket that fits ``n`` rows (callers cap collection at
    ``max(buckets)``, so a fit always exists)."""
    if n <= 0:
        raise ValueError(f"bucket request for {n} rows")
    for b in sorted(buckets):
        if n <= b:
            return b
    raise ValueError(f"{n} rows exceed the largest bucket {max(buckets)}")


def pad_rows(arr: np.ndarray, bucket: int) -> np.ndarray:
    """Pad axis 0 with repeats of row 0 up to ``bucket`` rows. Repeating a
    REAL row (not zeros) keeps the pad rows on the same numerical path as
    live traffic — no special-case token patterns reaching the encoder —
    and their outputs are sliced off before verdicts."""
    n = arr.shape[0]
    if n == bucket:
        return arr
    pad = np.broadcast_to(arr[:1], (bucket - n,) + arr.shape[1:])
    return np.concatenate([arr, pad], axis=0)


def stack_queries(
    queries: list[dict[str, np.ndarray]], bucket: int
) -> dict[str, np.ndarray]:
    """[L]-leaf query dicts -> one padded [bucket, L] dict in wire dtypes."""
    out = {}
    for k, dt in QUERY_DTYPES.items():
        out[k] = pad_rows(
            np.stack([np.asarray(q[k]) for q in queries]).astype(dt), bucket
        )
    return out


def make_serving_mesh(dp: int):
    """A 1-axis ("dp",) mesh over the first ``dp`` local devices for
    sharded query scoring. Kept here (not parallel/mesh.py) because the
    serving mesh has exactly one axis role: split the request batch."""
    import jax
    import numpy as _np
    from jax.sharding import Mesh

    devs = jax.devices()
    if dp > len(devs):
        raise ValueError(
            f"serving dp={dp} exceeds the {len(devs)} visible devices"
        )
    return Mesh(_np.asarray(devs[:dp]), ("dp",))


class QueryProgramCache:
    """AOT-compiled ``score_queries`` executables keyed by
    (n_classes, bucket, resident dtype).

    The program signature is ``(params, class_mat [N, C], query leaves
    [bucket, L]) -> logits [bucket, N(+1)]``: params and the class matrix are
    ARGUMENTS, not closure constants (constants bake into the program — the
    same tunneled-backend lesson train/token_cache.py records), so
    re-registering a class never invalidates a compiled program — and a
    params hot-swap (serving/registry.publish_params) reuses every
    executable untouched, which is what makes the swap recompile-free.

    ``mesh`` (fleet serving): a ``make_serving_mesh`` over dp devices.
    Buckets divisible by dp compile with the request axis sharded over
    ``dp`` (params + class matrix replicated, logits gathered at the
    output) — the multi-device engine scores one batch across the mesh.
    Smaller buckets fall back to single-device programs; the cache key is
    unchanged, so the bucket set still compiles once each.
    """

    def __init__(self, model, stats=None, mesh=None):
        import jax

        self._jax = jax
        self._stats = stats
        self._mesh = mesh
        self._exe: dict[tuple[int, int, str], Any] = {}
        self.compiles = 0
        self.in_warmup = False

        def score(params, class_mat, query):
            logits = model.apply(
                params, class_mat[None],
                {k: v[None] for k, v in query.items()},
                method="score_queries",
            )
            return logits[0]  # [bucket, N(+1)]

        def score_int8(params, class_mat, scale, query):
            logits = model.apply(
                params, class_mat[None],
                {k: v[None] for k, v in query.items()},
                scale,
                method="score_queries",
            )
            return logits[0]  # [bucket, N(+1)]

        self._score = score
        self._score_int8 = score_int8

    def _compile(self, params, n_classes: int, class_dim: int,
                 bucket: int, max_length: int, dtype: str):
        jax = self._jax
        aval = lambda s, d: jax.ShapeDtypeStruct(s, d)  # noqa: E731
        p_avals = jax.tree.map(lambda x: aval(x.shape, x.dtype), params)
        mat = aval((n_classes, class_dim), RESIDENT_DTYPES[dtype])
        query = {
            k: aval((bucket, max_length), dt) for k, dt in QUERY_DTYPES.items()
        }
        fn = self._score_int8 if dtype == "int8" else self._score
        if self._mesh is not None and bucket % self._mesh.shape["dp"] == 0:
            from jax.sharding import NamedSharding, PartitionSpec as P

            rep = NamedSharding(self._mesh, P())
            row = NamedSharding(self._mesh, P("dp", None))
            mat_shardings = (rep, rep) if dtype == "int8" else (rep,)
            jitted = jax.jit(
                fn,
                in_shardings=(
                    jax.tree.map(lambda _: rep, p_avals),
                    *mat_shardings,
                    {k: row for k in query},
                ),
                out_shardings=rep,
            )
        else:
            jitted = jax.jit(fn)
        if dtype == "int8":
            scale = aval((), np.float32)
            exe = jitted.lower(p_avals, mat, scale, query).compile()
        else:
            exe = jitted.lower(p_avals, mat, query).compile()
        self.compiles += 1
        if self._stats is not None:
            self._stats.record_compile(during_warmup=self.in_warmup)
        return exe

    def get(self, params, n_classes: int, class_dim: int, bucket: int,
            max_length: int, dtype: str = "f32"):
        key = (n_classes, bucket, dtype)
        exe = self._exe.get(key)
        if exe is None:
            exe = self._exe[key] = self._compile(
                params, n_classes, class_dim, bucket, max_length, dtype
            )
        return exe

    def warmup(self, params, n_classes: int, class_dim: int,
               buckets: tuple[int, ...], max_length: int,
               dtypes: tuple[str, ...] = ("f32",)) -> int:
        """Compile every bucket's program for the current class count, one
        per resident dtype in ``dtypes``; returns the number of programs
        compiled by this call."""
        before = self.compiles
        self.in_warmup = True
        try:
            for dt in dtypes:
                for b in buckets:
                    self.get(params, n_classes, class_dim, b, max_length, dt)
        finally:
            self.in_warmup = False
        return self.compiles - before

    def run(self, params, class_mat, query: dict[str, np.ndarray],
            scale=None) -> np.ndarray:
        """Execute the (n_classes, bucket, dtype) program — the dtype comes
        off the class matrix itself, so mixed-precision tenants sharing
        this cache can never hit each other's signatures. Compiles on miss
        (counted as a steady-state recompile unless inside warmup). int8
        matrices require their per-tenant f32 ``scale``."""
        bucket, max_length = query["word"].shape
        n, c = class_mat.shape
        dtype = resident_dtype_name(class_mat.dtype)
        exe = self.get(params, n, c, bucket, max_length, dtype)
        if dtype == "int8":
            if scale is None:
                raise ValueError(
                    "int8 resident class matrix scored without its dequant "
                    "scale"
                )
            return np.asarray(
                exe(params, class_mat, np.float32(scale), query)
            )
        return np.asarray(exe(params, class_mat, query))
