"""``serve.py`` entrypoint — the serving CLI next to train.py/test.py.

Flow: restore a checkpoint (or fresh-init synthetic weights for demos),
register support sets (a FewRel-schema JSON via --support_file, or the
synthetic fixtures), AOT-warm the bucket programs, then answer queries —
JSON-lines from --input (or stdin with ``--input -``), or a built-in demo
batch sampled from the registered corpus. One verdict JSON per line on
stdout; serving metrics go to stderr and metrics.jsonl (kind="serve").
``--trace_sample`` adds per-request kind="trace" segment records (verdicts
carry trace_id); ``--slo_latency_ms`` arms the per-tenant SLO burn-rate
engine, whose fast-window CRITICAL auto-captures diagnostics to
``--run_dir`` (RUNBOOK §14); ``--drift`` arms the online prediction-drift
detector (per-tenant NOTA rate / margin / entropy vs a calibration
baseline, re-armed on every publish — RUNBOOK §15); ``--replicas N``
runs N engine replicas behind the fleet router (rendezvous placement,
fleet-share fairness, breaker-fed failover — RUNBOOK §18).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def build_serve_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="TPU-native few-shot inference engine (induction network)"
    )
    p.add_argument("--load_ckpt", default=None,
                   help="checkpoint directory to serve; omitted = fresh-init "
                        "synthetic weights (demo/loadgen only — verdicts are "
                        "untrained)")
    p.add_argument("--support_file", default=None,
                   help="FewRel-schema JSON of support sets; each relation "
                        "registers with its first K instances (synthetic "
                        "fixtures when omitted)")
    p.add_argument("--K", type=int, default=5, help="shots per registered class")
    p.add_argument("--max_classes", type=int, default=None,
                   help="register at most this many relations")
    p.add_argument("--input", default=None, metavar="FILE|-",
                   help="JSON-lines queries (FewRel instance schema or "
                        "{'tokens': [...]}); '-' = stdin; omitted = demo "
                        "queries sampled from the support corpus")
    p.add_argument("--glove", default=None, help="GloVe json (word2id or combined)")
    p.add_argument("--glove_mat", default=None, help=".npy matrix for word2id json")
    p.add_argument("--device", default="cpu", choices=["tpu", "cpu"],
                   help="serving defaults to cpu; pass tpu for real traffic")
    p.add_argument("--compile_cache", default="auto", metavar="DIR|off",
                   help="persistent XLA compile cache (see train.py --help)")
    p.add_argument("--buckets", default="1,2,4,8,16",
                   help="comma-separated micro-batch shape buckets (each is "
                        "one AOT-compiled program)")
    p.add_argument("--scheduler", default="continuous",
                   choices=["continuous", "microbatch"],
                   help="continuous = cross-bucket launch-on-free scheduler "
                        "(fleet default); microbatch = the per-bucket "
                        "coalescing batcher (A/B baseline)")
    p.add_argument("--dp", type=int, default=None,
                   help="shard query scoring over this many local devices "
                        "(buckets divisible by dp compile dp-sharded)")
    p.add_argument("--tenant_share", type=float, default=0.5,
                   help="per-tenant fraction of --queue_depth before that "
                        "tenant sheds (continuous scheduler; binds only "
                        "once a second tenant has submitted)")
    p.add_argument("--nota_threshold", type=float, default=None,
                   help="NOTA threshold for the default tenant: biases the "
                        "learned no-relation logit (na_rate>0 checkpoints) "
                        "or sets an open-set floor on the best class logit")
    p.add_argument("--queue_depth", type=int, default=64,
                   help="bounded request-queue depth (backpressure bound)")
    p.add_argument("--batch_window_ms", type=float, default=2.0,
                   help="max time to wait coalescing a bucket "
                        "(microbatch scheduler only)")
    p.add_argument("--deadline_ms", type=float, default=1000.0,
                   help="default per-request deadline")
    p.add_argument("--demo_queries", type=int, default=32,
                   help="queries for the built-in demo (no --input)")
    p.add_argument("--run_dir", default=None,
                   help="metrics.jsonl dir for kind='serve' records")
    p.add_argument("--watchdog", action="store_true",
                   help="run-health watchdog (obs/health.py): queue-stall "
                        "detection + NaN checks over the serve stream; "
                        "critical events dump flight_recorder.json to "
                        "--run_dir")
    p.add_argument("--trace_sample", type=float, default=0.0,
                   help="request-trace head-sampling rate (0 = off, the "
                        "zero-overhead default; 0.1 traces every 10th "
                        "request). Sampled requests emit kind='trace' "
                        "segment records to --run_dir, rendered as "
                        "waterfalls by tools/obs_report.py")
    p.add_argument("--slo_latency_ms", type=float, default=None,
                   help="per-request latency objective; setting it turns "
                        "on the per-tenant SLO burn-rate engine (requests "
                        "slower than this, or shed/rejected/expired, burn "
                        "the error budget; a fast-window burn CRITICAL "
                        "auto-captures diagnostics to --run_dir)")
    p.add_argument("--slo_availability", type=float, default=0.99,
                   help="SLO good-fraction target (error budget = 1 - "
                        "this); only meaningful with --slo_latency_ms")
    p.add_argument("--slo_fast_s", type=float, default=300.0,
                   help="fast burn window seconds (5m-equivalent; shrink "
                        "for drills)")
    p.add_argument("--slo_slow_s", type=float, default=3600.0,
                   help="slow burn window seconds (1h-equivalent)")
    p.add_argument("--drift", action="store_true",
                   help="arm the online prediction-drift detector "
                        "(obs/drift.py): per-tenant NOTA rate / top-1 "
                        "margin / score entropy vs a calibration baseline "
                        "captured from the first post-arm traffic; a "
                        "shift past band trips a once-latched WARNING/"
                        "CRITICAL with diagnostics captured to --run_dir; "
                        "every publish re-arms the baseline (RUNBOOK §15)")
    p.add_argument("--drift_window", type=int, default=128,
                   help="drift detection window (verdicts per tenant)")
    p.add_argument("--drift_baseline", type=int, default=64,
                   help="verdicts that form the calibration baseline "
                        "after (re-)arming")
    p.add_argument("--drift_band", type=float, default=4.0,
                   help="alert band width in standard errors of the "
                        "window mean (CRITICAL at 2x)")
    p.add_argument("--breaker_threshold", type=int, default=0,
                   help="per-tenant circuit breaker (serving/breaker.py): "
                        "open after this many consecutive launch failures "
                        "and shed that tenant's submits until a half-open "
                        "probe succeeds (kind='fault' transitions; "
                        "CRITICAL breaker_open once-latched). 0 = off")
    p.add_argument("--breaker_open_s", type=float, default=5.0,
                   help="seconds an open breaker sheds before admitting "
                        "its half-open probe")
    p.add_argument("--chaos", default="",
                   help="chaos-injection plan (obs/chaos.py, RUNBOOK §17): "
                        "POINT@AT[*COUNT][:ARG] directives, e.g. "
                        "'serve.execute_raise@0*3:default'. Deterministic "
                        "drills for the containment layer; '' = off")
    p.add_argument("--replicas", type=int, default=1,
                   help="fleet mode (ISSUE 13, fleet/): run this many "
                        "in-process engine replicas behind the fleet "
                        "router — rendezvous tenant placement, fleet-"
                        "level shed fairness, replica breaker/failover, "
                        "fan-out publish. 1 (default) = the single-"
                        "engine path")
    p.add_argument("--router", action="store_true",
                   help="route through the fleet router even with "
                        "--replicas 1 (exercises the fleet front door "
                        "on a single-replica deployment)")
    p.add_argument("--journal", default=None, metavar="DIR",
                   help="durable control plane (ISSUE 15, fleet modes): "
                        "write-ahead-log every control-plane op to this "
                        "directory and, when it already holds records, "
                        "RECOVER the tenant directory + committed "
                        "params_version from it at startup (bitwise "
                        "replay; stale replicas caught up via the "
                        "journaled publish). RUNBOOK §20")
    p.add_argument("--journal_fsync", default="commit",
                   choices=["always", "commit", "off"],
                   help="journal fsync policy: every append / committed "
                        "publishes + compactions only (default) / leave "
                        "it to the OS (RUNBOOK §20 tradeoff)")
    p.add_argument("--journal_compact_every", type=int, default=512,
                   help="auto-fold the WAL into snapshot.json past this "
                        "many records (0 = manual compaction only)")
    p.add_argument("--autoscale", action="store_true",
                   help="elasticity (ISSUE 16, fleet modes): arm the "
                        "SLO-driven autoscaler — occupancy/shed/burn "
                        "target band with hysteresis + cool-down, "
                        "journaled scale-out (spawn, catch-up, pre-warm, "
                        "join) and drain-in (drain, replace, wait-for-"
                        "inflight, retire). RUNBOOK §21")
    p.add_argument("--autoscale_min", type=int, default=1,
                   help="autoscaler floor: never drain below this many "
                        "replicas")
    p.add_argument("--autoscale_max", type=int, default=4,
                   help="autoscaler ceiling: never scale past this many "
                        "replicas")
    p.add_argument("--autoscale_interval_s", type=float, default=5.0,
                   help="seconds between autoscaler policy ticks")
    p.add_argument("--standby", action="store_true",
                   help="hot-standby mode (ISSUE 16): instead of "
                        "serving, TAIL the --journal WAL read-only "
                        "(applying ops as they commit), then PROMOTE — "
                        "take the single-writer lease (fencing the old "
                        "primary), final catch-up replay, rebuild + "
                        "warm the fleet, and serve. With "
                        "--control_socket, promotion waits for a "
                        "{\"op\": \"promote\"} command; without, it "
                        "happens after the initial catch-up. RUNBOOK §21")
    p.add_argument("--standby_poll_s", type=float, default=0.5,
                   help="seconds between standby WAL tail polls")
    p.add_argument("--control_socket", default=None, metavar="PATH",
                   help="fleet/standby modes: serve operator commands on "
                        "this unix socket (JSON lines): drain / forgive "
                        "/ revive / retire / stats (fleet), status / "
                        "promote (standby) — journaled like every other "
                        "control op")
    p.add_argument("--send", default=None, metavar="JSON",
                   help="client mode: send one JSON command (e.g. "
                        "'{\"op\": \"drain\", \"replica\": \"r01\"}') "
                        "to --control_socket, print the reply, exit")
    p.add_argument("--slo_profile", action="store_true",
                   help="also attempt a jax.profiler trace in the SLO "
                        "auto-capture (default off on this image — a "
                        "profiler session concurrent with the serving "
                        "worker corrupts the heap at exit, RUNBOOK §14; "
                        "span snapshot + flight dump always capture)")
    # Self-healing adaptation (obs/adapt.py, ISSUE 14, RUNBOOK §19):
    # knobs resolved in ONE home (config.resolve_adapt_policy, shared
    # with train.py); values left unset fall back to the served
    # checkpoint's stamped policy, then the config defaults.
    p.add_argument("--adapt", action="store_true",
                   help="arm the drift-triggered adaptation controller: "
                        "a drift CRITICAL kicks off a bounded mixture-"
                        "ramp fine-tune from the served checkpoint, "
                        "canary-gated on the scenario-harness floors, "
                        "published through the (fan-out) hot-swap with "
                        "automatic rollback; requires --drift")
    p.add_argument("--adapt_mixture", default=None, metavar="FILE",
                   help="FewRel-schema JSON of the remediation (target-"
                        "domain) corpus the fine-tune ramps in; required "
                        "with --adapt + --support_file (the demo path "
                        "falls back to a synthetic shifted twin)")
    p.add_argument("--adapt_retries", type=int, default=None,
                   help="flap damper: failed adaptation loops before the "
                        "permanent adapt_exhausted CRITICAL + quarantine")
    p.add_argument("--adapt_backoff_s", type=float, default=None,
                   help="base retry backoff seconds (doubles per fail)")
    p.add_argument("--adapt_cooldown_s", type=float, default=None,
                   help="post-success trigger suppression seconds")
    p.add_argument("--adapt_step_budget", type=int, default=None,
                   help="fine-tune optimizer-step budget")
    p.add_argument("--adapt_wall_s", type=float, default=None,
                   help="fine-tune wall-clock budget (breach = timeout-"
                        "kill + candidate checkpoint cleanup)")
    p.add_argument("--adapt_verify_s", type=float, default=None,
                   help="post-publish verification window (drift "
                        "re-trip inside it rolls back to the prior "
                        "artifact)")
    p.add_argument("--adapt_canary", default=None,
                   help="pre-publish canary plan 'leg:floor[,...]' over "
                        "legs in_domain/target (tools/scenarios."
                        "run_canary floors), or 'off'")
    # Quantized serving data plane (ISSUE 18): knobs resolved in ONE
    # home (config.resolve_quant_policy) — None inherits the checkpoint
    # config, same discipline as the adapt knobs above.
    p.add_argument("--resident_dtype", default=None,
                   choices=["f32", "bf16", "int8"],
                   help="storage dtype for resident class vectors: bf16 "
                        "halves, int8 quarters resident bytes per tenant "
                        "(per-tenant symmetric scale, f32 accumulation; "
                        "default f32 or the checkpoint config)")
    p.add_argument("--quant_probe_every", type=int, default=None,
                   help="shadow-score every Nth quantized batch against "
                        "f32 and feed verdict agreement + margin drift "
                        "into the drift detector's parity bands "
                        "(0 = off; the --grad_probe_every of serving)")
    # Geometry plane (ISSUE 19): knobs resolved in ONE home
    # (config.resolve_geometry_policy) — None inherits the checkpoint
    # config, same discipline as the quant knobs.
    p.add_argument("--geometry_tiers", default=None,
                   help="N-tier ladder resident class stacks pad up to "
                        "(comma-separated ascending ints, e.g. "
                        "'4,8,16,32,64'), bounding compiled query "
                        "programs by tiers x buckets x dtypes; 'off' = "
                        "exact-N residency (default: the checkpoint "
                        "config, then 4,8,16,32,64)")
    p.add_argument("--tier_spread", type=int, default=None,
                   dest="geometry_tier_spread",
                   help="fleet mode: concentrate each N-tier's tenants "
                        "onto this many rendezvous 'home' replicas so "
                        "no replica compiles every tier's programs "
                        "(0 = tier-blind placement)")
    p.add_argument("--seed", type=int, default=0)
    return p


def _build_breaker(args):
    if getattr(args, "breaker_threshold", 0) <= 0:
        return None
    from induction_network_on_fewrel_tpu.serving.breaker import (
        CircuitBreaker,
    )

    return CircuitBreaker(
        failure_threshold=args.breaker_threshold,
        open_s=args.breaker_open_s,
    )


def _build_engine(args, buckets, logger=None, watchdog=None, slo=None,
                  drift=None, breaker=None, trace_sample=0.0):
    """ONE home for CLI engine construction — the from_checkpoint /
    fresh-init fork plus every shared kwarg — used by the single-engine
    path AND each fleet replica (which passes trace_sample=0.0: the
    ROUTER head-samples and hands the context across the hop)."""
    from induction_network_on_fewrel_tpu.serving.engine import (
        InferenceEngine,
    )

    if args.load_ckpt:
        return InferenceEngine.from_checkpoint(
            args.load_ckpt, device=args.device,
            glove=args.glove, glove_mat=args.glove_mat,
            k=args.K, buckets=buckets,
            max_queue_depth=args.queue_depth,
            batch_window_s=args.batch_window_ms / 1e3,
            default_deadline_s=args.deadline_ms / 1e3,
            scheduler=args.scheduler, tenant_share=args.tenant_share,
            dp=args.dp, logger=logger, watchdog=watchdog,
            slo=slo, drift=drift, breaker=breaker,
            trace_sample=trace_sample,
            resident_dtype=args.resident_dtype,
            quant_probe_every=args.quant_probe_every,
            geometry_tiers=args.geometry_tiers,
        )
    return _fresh_engine(args, buckets, logger=logger, watchdog=watchdog,
                         slo=slo, drift=drift, breaker=breaker,
                         trace_sample=trace_sample)


def _fresh_engine(args, buckets, logger=None, watchdog=None, slo=None,
                  drift=None, breaker=None, trace_sample=0.0):
    """Demo path: synthetic vocab + fresh-init induction weights (no
    checkpoint on disk). The serving machinery is identical; only the
    verdict quality is untrained."""
    import jax

    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.data import make_synthetic_glove
    from induction_network_on_fewrel_tpu.data.tokenizer import GloveTokenizer
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.serving.engine import InferenceEngine
    from induction_network_on_fewrel_tpu.train.steps import init_state

    cfg = ExperimentConfig(
        device=args.device, k=args.K, vocab_size=2002, seed=args.seed
    )
    vocab = make_synthetic_glove(vocab_size=cfg.vocab_size - 2,
                                 word_dim=cfg.word_dim)
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    from induction_network_on_fewrel_tpu.serving.buckets import zero_batch

    model = build_model(cfg, glove_init=vocab.vectors)
    state = init_state(
        model, cfg, zero_batch(cfg.max_length, (1, cfg.n, cfg.k)),
        zero_batch(cfg.max_length, (1, cfg.total_q)),
        rng=jax.random.key(cfg.seed),
    )
    print("no --load_ckpt: serving FRESH-INIT synthetic weights (demo only)",
          file=sys.stderr)
    return InferenceEngine(
        model, state.params, cfg, tok, k=args.K, buckets=buckets,
        max_queue_depth=args.queue_depth,
        batch_window_s=args.batch_window_ms / 1e3,
        default_deadline_s=args.deadline_ms / 1e3,
        scheduler=args.scheduler, tenant_share=args.tenant_share,
        dp=args.dp, logger=logger, watchdog=watchdog,
        slo=slo, drift=drift, breaker=breaker,
        trace_sample=trace_sample,
        resident_dtype=args.resident_dtype,
        quant_probe_every=args.quant_probe_every,
        geometry_tiers=args.geometry_tiers,
    )


def _adapt_target_dataset(args, k: int):
    """The remediation (target-domain) corpus the adaptation fine-tune
    ramps in: --adapt_mixture when given; the demo path (synthetic
    supports) falls back to the synthetic shifted twin — the same
    relations with the trigger signal moved to a disjoint vocab block
    (data/synthetic.make_domain_shifted_fewrel, the wiki -> pubmed shift
    in miniature). A real --support_file without --adapt_mixture is
    refused: the CLI must not invent a target corpus."""
    if args.adapt_mixture:
        from induction_network_on_fewrel_tpu.data import load_fewrel_json

        return load_fewrel_json(args.adapt_mixture)
    if args.support_file:
        raise SystemExit(
            "--adapt with --support_file needs --adapt_mixture (the "
            "target-domain corpus the mixture-ramp fine-tune adapts "
            "toward); only the synthetic demo path can derive one"
        )
    from induction_network_on_fewrel_tpu.data import (
        make_domain_shifted_fewrel,
    )

    return make_domain_shifted_fewrel(
        num_relations=10, instances_per_relation=max(k + 10, 20),
        vocab_size=2000, shift=1.0, seed=args.seed,
    )


def _save_base_checkpoint(engine, out_dir: str) -> str:
    """Demo path: the fresh-init weights saved through the real
    CheckpointManager, so the adaptation loop has a live artifact to
    fine-tune from and roll back to (a --load_ckpt deployment uses the
    served directory itself). The directory is derived state (fresh-init
    weights) — a restart with the same --run_dir rebuilds it rather than
    colliding with the previous run's step-0 save (orbax refuses step
    re-saves)."""
    import shutil

    import jax

    from induction_network_on_fewrel_tpu.serving.buckets import zero_batch
    from induction_network_on_fewrel_tpu.train.checkpoint import (
        CheckpointManager,
    )
    from induction_network_on_fewrel_tpu.train.steps import init_state

    cfg = engine.cfg
    state = init_state(
        engine.model, cfg,
        zero_batch(cfg.max_length, (1, cfg.n, cfg.k)),
        zero_batch(cfg.max_length, (1, cfg.total_q)),
        rng=jax.random.key(cfg.seed),
    )
    state = state.replace(params=engine.registry.params)
    shutil.rmtree(out_dir, ignore_errors=True)
    mngr = CheckpointManager(out_dir, cfg, stage="off")
    try:
        mngr.save(0, state, val_accuracy=0.0)
        mngr.wait()
    finally:
        mngr.close()
    return out_dir


def _build_adapt(args, policy, *, drift, model, cfg, tok, src_ds, tgt_ds,
                 base_ckpt, publish_fn, quarantine_fn, logger=None,
                 recorder=None, capture=None, journal=None):
    """Assemble the AdaptationController from the serving context: the
    fine-tune reads the live artifact + the two corpora, the canary is
    tools/scenarios.run_canary over {in_domain, target} legs at the
    resolved floors, publish goes through the caller's (fan-out)
    publish, and rollback republishes whatever was live before."""
    import tempfile

    from induction_network_on_fewrel_tpu.obs.adapt import (
        AdaptationController,
        make_checkpoint_loop,
    )

    work = tempfile.mkdtemp(prefix="adapt_candidates_")

    def finetune(src_ckpt, out, seq, attempt, step_budget, wall_budget_s):
        from induction_network_on_fewrel_tpu.train.finetune import (
            mixture_finetune,
        )

        return mixture_finetune(
            src_ckpt, out, src_ds, tgt_ds, tok,
            steps=step_budget, wall_budget_s=wall_budget_s,
            seed=args.seed + seq, logger=logger,
        )

    train_fn, publish, cleanup, current_fn = make_checkpoint_loop(
        base_ckpt, work, finetune, publish_fn,
    )

    canary_fn = None
    floors = policy["canary_floors"]
    if floors:
        # Startup-time fail-fast, both halves: the canary entrypoint
        # import (an unresolvable tools/ must not be silently converted
        # into N failed canaries + a permanent quarantine) AND the plan's
        # leg names (a floor naming a leg this deployment doesn't wire
        # would fail every candidate at the first drift CRITICAL — the
        # same quarantine-by-typo outcome).
        legs = {"in_domain": src_ds, "target": tgt_ds}
        # Geometry grid legs (ISSUE 19): a floor named grid_<N>w<K>s
        # evaluates the in-domain corpus at THAT episode geometry
        # (run_canary parses the name) — an adaptation that recovers
        # the flagship 5w5s but regresses 10w1s must not publish.
        from induction_network_on_fewrel_tpu.serving.geometry import (
            parse_grid_key,
        )

        for name in floors:
            g = parse_grid_key(name) if name.startswith("grid_") else None
            if g is None:
                continue
            if g[0] > len(src_ds.rel_names):
                raise SystemExit(
                    f"--adapt_canary leg {name!r} needs {g[0]} relations "
                    f"but the in-domain corpus has "
                    f"{len(src_ds.rel_names)}"
                )
            legs[name] = src_ds
        unknown = sorted(set(floors) - set(legs))
        if unknown:
            raise SystemExit(
                f"--adapt_canary names unknown leg(s) {unknown}: this "
                f"deployment wires legs {sorted(legs)} plus "
                f"grid_<N>w<K>s geometry legs"
            )
        # Evaluate ONLY the legs the plan floors: a floorless leg is
        # recorded-not-judged by canary_verdict, so evaluating it would
        # burn publish-critical device time with zero verdict effect.
        legs = {k: v for k, v in legs.items() if k in floors}
        _repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        )))
        if _repo not in sys.path:
            sys.path.insert(0, _repo)
        from tools.scenarios import run_canary

        def canary_fn(candidate):
            from induction_network_on_fewrel_tpu.serving.registry import (
                load_params,
            )

            return run_canary(
                model, load_params(candidate), cfg, tok,
                legs=legs, floors=floors, seed=args.seed,
            )

    return AdaptationController(
        train_fn, canary_fn, publish,
        drift=drift, current_fn=current_fn,
        cleanup_fn=cleanup, quarantine_fn=quarantine_fn,
        retry_budget=policy["retry_budget"],
        backoff_s=policy["backoff_s"],
        cooldown_s=policy["cooldown_s"],
        verify_window_s=policy["verify_window_s"],
        step_budget=policy["step_budget"],
        wall_budget_s=policy["wall_budget_s"],
        logger=logger, recorder=recorder, capture=capture,
        journal=journal,
    )


def _write_prometheus(run_dir) -> None:
    """Prometheus text exposition of the shared counter registry
    (obs/export.py) — the scrape-format twin of the final kind="serve"
    record; an HTTP server would serve this string. Call BEFORE
    engine/router close: close unbinds the stats callbacks from the
    registry (fleet mode binds several replicas under one prefix; the
    exposition reflects the latest bind — documented latest-wins
    behavior of the shared registry)."""
    from induction_network_on_fewrel_tpu.obs import get_registry
    from pathlib import Path

    Path(run_dir, "metrics.prom").write_text(
        get_registry().to_prometheus()
    )


def _support_dataset(args, cfg_k: int, seed: int = 0):
    from induction_network_on_fewrel_tpu.data import (
        load_fewrel_json,
        make_synthetic_fewrel,
    )

    if args.support_file:
        return load_fewrel_json(args.support_file)
    return make_synthetic_fewrel(
        num_relations=10, instances_per_relation=max(cfg_k + 10, 20),
        vocab_size=2000, seed=seed,
    )


def serve_main(argv=None) -> int:
    parser = build_serve_arg_parser()
    args = parser.parse_args(argv)
    if args.adapt and not args.drift:
        parser.error("--adapt needs --drift (the controller subscribes "
                     "to the drift detector's CRITICALs)")
    if args.send is not None:
        if not args.control_socket:
            parser.error("--send needs --control_socket (the server "
                         "address to talk to)")
        return _control_send(args.control_socket, args.send)
    if args.standby and not args.journal:
        parser.error("--standby needs --journal (the WAL directory "
                     "to tail)")
    if args.autoscale and not (args.replicas > 1 or args.router):
        parser.error("--autoscale needs fleet mode (--router or "
                     "--replicas > 1)")
    buckets = tuple(int(b) for b in args.buckets.split(","))

    # Device selection must happen before any jax backend init — reuse the
    # train CLI's helper (it owns the axon-sitecustomize workaround).
    from induction_network_on_fewrel_tpu.cli import select_device
    from induction_network_on_fewrel_tpu.config import ExperimentConfig

    select_device(ExperimentConfig(device=args.device), args.compile_cache)

    from induction_network_on_fewrel_tpu.serving.engine import InferenceEngine
    from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

    # One logger owned HERE (not per-engine): serve_main closes its
    # persistent metrics.jsonl handle on exit.
    logger = MetricsLogger(args.run_dir) if args.run_dir else None
    if logger is not None:
        # Process identity (ISSUE 17): every record this process emits
        # carries proc_role/proc_pid (+ t_unix), so
        # tools/fleet_report.py can merge the fleet's streams into one
        # causally-ordered timeline. An in-process fleet is ONE process
        # wearing the router hat; a real multi-process deployment gives
        # each replica its own run dir and "serve" role.
        logger.set_identity(
            "standby" if args.standby
            else "router" if (args.replicas > 1 or args.router)
            else "serve"
        )
    watchdog = None
    recorder = None
    needs_obs = (
        args.watchdog or args.slo_latency_ms is not None or args.drift
    )
    if needs_obs:
        from induction_network_on_fewrel_tpu.obs import FlightRecorder

        recorder = FlightRecorder(out_dir=args.run_dir)
        recorder.install_sigterm_handler()
        if logger is not None:
            logger.add_hook(recorder.record_metric)
    if args.watchdog:
        from induction_network_on_fewrel_tpu.obs import HealthWatchdog

        watchdog = HealthWatchdog(logger=logger, recorder=recorder)
    # One DiagnosticsCapture shared by the SLO and drift engines: its
    # per-capture counter keeps their snapshots distinct on disk.
    capture = None
    if args.slo_latency_ms is not None or args.drift:
        from induction_network_on_fewrel_tpu.obs import DiagnosticsCapture

        capture = DiagnosticsCapture(args.run_dir or ".",
                                     recorder=recorder,
                                     profile=args.slo_profile)
    slo = None
    if args.slo_latency_ms is not None:
        from induction_network_on_fewrel_tpu.obs import (
            SLOEngine,
            SLOObjective,
        )

        slo = SLOEngine(
            SLOObjective(availability=args.slo_availability,
                         latency_ms=args.slo_latency_ms),
            fast_window_s=args.slo_fast_s, slow_window_s=args.slo_slow_s,
            logger=logger, recorder=recorder, capture=capture,
        )
    drift = None
    if args.drift:
        from induction_network_on_fewrel_tpu.obs import DriftDetector

        drift = DriftDetector(
            window=args.drift_window, baseline_n=args.drift_baseline,
            band_sigma=args.drift_band,
            logger=logger, recorder=recorder, capture=capture,
        )
    if watchdog is not None and capture is not None:
        # Fault criticals (ckpt_corrupt / breaker_open /
        # publish_rollback) get the same auto-capture evidence as SLO
        # burns and drift (ISSUE 12).
        watchdog.capture = capture
    breaker = _build_breaker(args)
    if args.chaos:
        from induction_network_on_fewrel_tpu.obs.chaos import ChaosRegistry

        reg = ChaosRegistry.parse(args.chaos, logger=logger)
        if reg is not None:
            reg.install()
            print(f"chaos plan armed: {args.chaos}", file=sys.stderr)
    if args.standby:
        return _serve_standby(args, buckets, logger=logger,
                              watchdog=watchdog, slo=slo, drift=drift,
                              recorder=recorder, capture=capture)
    if args.replicas > 1 or args.router:
        return _serve_fleet(args, buckets, logger=logger,
                            watchdog=watchdog, slo=slo, drift=drift,
                            recorder=recorder, capture=capture)
    engine = _build_engine(args, buckets, logger=logger,
                           watchdog=watchdog, slo=slo, drift=drift,
                           breaker=breaker,
                           trace_sample=args.trace_sample)

    adapt = None
    try:
        ds = _support_dataset(args, engine.registry.k, seed=args.seed)
        names = engine.register_dataset(ds, max_classes=args.max_classes)
        if args.nota_threshold is not None:
            engine.set_nota_threshold(args.nota_threshold)
        print(f"registered {len(names)} classes x {engine.registry.k} shots "
              f"(scheduler={args.scheduler})",
              file=sys.stderr)
        compiled = engine.warmup()
        print(f"warmup: {compiled} bucket programs compiled "
              f"(buckets={list(engine.batcher.buckets)})", file=sys.stderr)

        if args.adapt:
            from induction_network_on_fewrel_tpu.config import (
                resolve_adapt_policy,
            )
            from induction_network_on_fewrel_tpu.train.checkpoint import (
                CheckpointManager,
            )

            # Knob resolution base: the served checkpoint's stamped
            # policy (train.py --adapt rides in config.json), then the
            # config defaults — ONE home, config.resolve_adapt_policy.
            base_cfg = (
                CheckpointManager.load_config(args.load_ckpt)
                if args.load_ckpt else engine.cfg
            )
            policy = resolve_adapt_policy(args, base=base_cfg)
            tgt_ds = _adapt_target_dataset(args, engine.registry.k)
            base_ckpt = args.load_ckpt or _save_base_checkpoint(
                engine,
                os.path.join(args.run_dir or ".", "adapt_base_ckpt"),
            )
            adapt = _build_adapt(
                args, policy, drift=drift, model=engine.model,
                cfg=engine.cfg, tok=engine.tokenizer, src_ds=ds,
                tgt_ds=tgt_ds, base_ckpt=base_ckpt,
                publish_fn=engine.publish_checkpoint,
                quarantine_fn=lambda t, reason="": (
                    engine.quarantine_tenant(t, reason=reason)
                ),
                logger=logger, recorder=recorder, capture=capture,
            )
            adapt.start()
            print("adaptation controller armed "
                  f"(retries={policy['retry_budget']}, "
                  f"step_budget={policy['step_budget']})",
                  file=sys.stderr)

        if args.input:
            stream = sys.stdin if args.input == "-" else open(args.input)
            try:
                for line in stream:
                    line = line.strip()
                    if not line:
                        continue
                    verdict = engine.classify(json.loads(line))
                    print(json.dumps(verdict), flush=True)
            finally:
                if stream is not sys.stdin:
                    stream.close()
        else:
            _demo(engine.submit, ds, list(engine.class_names),
                  engine.registry.k, args.demo_queries, seed=args.seed)

        snap = engine.stats.snapshot(queue_depth=engine.batcher.queue_depth)
        print("serve stats: " + json.dumps(snap), file=sys.stderr)
        return 0
    finally:
        if args.run_dir:
            _write_prometheus(args.run_dir)
        if adapt is not None:
            adapt.close()
        engine.close()
        if logger is not None:
            logger.close()


def _start_control_server(path: str, handlers: dict, stop_evt):
    """The operator escape hatch (ISSUE 16 satellite): a unix-socket
    JSON-lines command server. One request line in, one
    ``{"ok": bool, ...}`` line out; every mutating handler goes through
    the journaled ``FleetControl`` ops, so ``drain r01`` from the CLI
    leaves the same audit trail as the in-process call."""
    import socket
    import threading

    if os.path.exists(path):
        os.unlink(path)
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    srv.bind(path)
    srv.listen(4)
    srv.settimeout(0.25)

    def run():
        try:
            while not stop_evt.is_set():
                try:
                    conn, _ = srv.accept()
                except socket.timeout:
                    continue
                with conn:
                    f = conn.makefile("rwb")
                    line = f.readline()
                    if not line:
                        continue
                    try:
                        req = json.loads(line)
                        fn = handlers.get(req.get("op"))
                        if fn is None:
                            resp = {
                                "ok": False,
                                "error": (
                                    f"unknown op {req.get('op')!r} "
                                    f"(known: {sorted(handlers)})"
                                ),
                            }
                        else:
                            resp = {"ok": True, "result": fn(req)}
                    except Exception as e:  # noqa: BLE001 — reported
                        resp = {"ok": False,
                                "error": f"{type(e).__name__}: {e}"}
                    f.write((json.dumps(resp) + "\n").encode())
                    f.flush()
        finally:
            srv.close()
            try:
                os.unlink(path)
            except OSError:
                pass

    t = threading.Thread(target=run, name="fleet-control-socket",
                         daemon=True)
    t.start()
    return t


def _control_send(path: str, payload: str) -> int:
    """``--send`` client: one command to a --control_socket server."""
    import socket

    req = json.loads(payload)
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
        s.connect(path)
        s.sendall((json.dumps(req) + "\n").encode())
        line = s.makefile("rb").readline()
    print(line.decode().strip())
    return 0 if json.loads(line).get("ok") else 1


def _serve_standby(args, buckets, logger=None, watchdog=None, slo=None,
                   drift=None, recorder=None, capture=None) -> int:
    """Hot-standby mode (ISSUE 16): tail the primary's WAL read-only,
    then promote — lease takeover (fencing the old primary's appends),
    final catch-up replay, fleet rebuild + warm from the tailed state —
    and serve. With ``--control_socket`` the tail loop runs until a
    ``{"op": "promote"}`` command arrives; without one, promotion
    happens right after the initial catch-up (the scripted/drill
    spelling). Replica handles are rebuilt in-process from the journaled
    membership — a multi-host deployment would dial its transport
    handles here instead; everything downstream is identical."""
    import threading

    from induction_network_on_fewrel_tpu.fleet import (
        HotStandby,
        InProcessReplica,
    )
    from induction_network_on_fewrel_tpu.serving.breaker import (
        CircuitBreaker,
    )

    standby = HotStandby(args.journal, logger=logger)
    standby.poll()
    print(f"standby: tailing {args.journal} — {standby.applied} op(s) "
          f"applied, {len(standby.tenants())} tenant(s)", file=sys.stderr)
    promote_evt = threading.Event()
    stop_evt = threading.Event()
    if args.control_socket:
        _start_control_server(args.control_socket, {
            "status": lambda req: {
                "applied": standby.applied,
                "tenants": len(standby.tenants()),
                "promoted": standby.promoted,
            },
            "promote": lambda req: (
                promote_evt.set(), {"promoting": True}
            )[1],
        }, stop_evt)
        while not promote_evt.wait(args.standby_poll_s):
            standby.poll()

    def mk_engine():
        return _build_engine(
            args, buckets, logger=logger, watchdog=watchdog, slo=slo,
            drift=drift, breaker=_build_breaker(args),
        )

    handles = {
        rid: InProcessReplica(rid, mk_engine())
        for rid in sorted(standby.state.replicas)
    }
    if not handles:
        print("standby: the journal names no replicas — nothing to "
              "promote", file=sys.stderr)
        stop_evt.set()
        return 1
    summary = standby.promote(
        handles,
        breaker=CircuitBreaker(failure_threshold=3,
                               open_s=args.breaker_open_s),
        queue_capacity_per_replica=args.queue_depth,
        trace_sample=args.trace_sample,
    )
    router = standby.router
    # The promoted router IS the fleet front door now: expose the same
    # rollup gauges the primary served (ISSUE 17).
    router.bind_registry()
    print(f"standby: PROMOTED in {summary['promote_s']:.3f}s — "
          f"{summary['tenants']} tenant(s), reregistered "
          f"{summary['reregistered']}, caught up {summary['caught_up']} "
          f"replica(s) to v{summary['params_version']} "
          f"(lease epoch {summary['lease_epoch']})", file=sys.stderr)
    try:
        if args.input:
            stream = sys.stdin if args.input == "-" else open(args.input)
            try:
                for line in stream:
                    line = line.strip()
                    if line:
                        print(json.dumps(router.classify(
                            json.loads(line), args.deadline_ms / 1e3,
                            tenant="default",
                        )), flush=True)
            finally:
                if stream is not sys.stdin:
                    stream.close()
        else:
            entry = router.directory.get("default")
            if entry is not None and entry.source is not None:
                ds = entry.source
                names = list(ds.rel_names)
                if entry.max_classes is not None:
                    names = names[: entry.max_classes]
                k = handles[sorted(handles)[0]].engine.registry.k
                _demo(
                    lambda inst: router.submit(
                        inst, args.deadline_ms / 1e3, tenant="default"
                    ),
                    ds, names, k, args.demo_queries, seed=args.seed,
                )
        router.emit_stats()
        print("standby stats: " + json.dumps(router.snapshot()),
              file=sys.stderr)
        return 0
    finally:
        stop_evt.set()
        if args.run_dir:
            _write_prometheus(args.run_dir)
        router.close()
        standby.journal.close()
        if logger is not None:
            logger.close()


def _serve_fleet(args, buckets, logger=None, watchdog=None, slo=None,
                 drift=None, recorder=None, capture=None) -> int:
    """Fleet-mode serving (ISSUE 13): ``--replicas`` in-process engine
    replicas behind the fleet router. The support corpus registers as
    the ``default`` tenant on its rendezvous owner through the control
    plane; queries (``--input`` or the demo batch) route through the
    router front door — placement resolution, fleet-share fairness,
    breaker-fed failover — exactly the path a multi-process deployment
    takes (fleet/transport.py swaps the replica handles, nothing else).
    Shared obs objects (slo/drift/watchdog) are per-tenant keyed, so
    every replica feeding them is by design."""
    from induction_network_on_fewrel_tpu.fleet import (
        FleetControl,
        FleetRouter,
        InProcessReplica,
    )

    def mk_engine():
        return _build_engine(
            args, buckets, logger=logger, watchdog=watchdog, slo=slo,
            drift=drift, breaker=_build_breaker(args),
        )

    from induction_network_on_fewrel_tpu.serving.breaker import (
        CircuitBreaker,
    )

    n = max(args.replicas, 1)
    replicas = {
        f"r{i:02d}": InProcessReplica(f"r{i:02d}", mk_engine())
        for i in range(n)
    }
    router = FleetRouter(
        replicas, logger=logger,
        breaker=CircuitBreaker(failure_threshold=3,
                               open_s=args.breaker_open_s),
        queue_capacity_per_replica=args.queue_depth,
        trace_sample=args.trace_sample,
    )
    # Fleet rollup gauges (ISSUE 17): per-replica labeled families +
    # aggregate gauge_fns land in the same registry _write_prometheus
    # renders — one metrics.prom scrape shows the whole fleet.
    router.bind_registry()
    journal = None
    if args.journal:
        from induction_network_on_fewrel_tpu.fleet import FleetJournal

        journal = FleetJournal(
            args.journal, fsync=args.journal_fsync,
            compact_every=args.journal_compact_every, logger=logger,
        )
        # Single-writer latch (ISSUE 16): hold the lease so a standby's
        # promotion fences THIS process — a zombie primary's next append
        # raises instead of split-braining the WAL.
        epoch = journal.acquire_lease("primary")
        print(f"fleet: journal lease acquired (epoch {epoch})",
              file=sys.stderr)
    control = FleetControl(router, journal=journal)
    adapt = None
    scaler = None
    import threading

    stop_evt = threading.Event()
    try:
        first = replicas[sorted(replicas)[0]].engine
        recovered_state = None
        if journal is not None and journal.seq > 0:
            # Cold-start recovery: the journal IS the directory. Every
            # journaled tenant re-registers on its rendezvous owner and
            # stale replicas catch up to the committed generation —
            # re-registering "default" below would only double-journal.
            # One materialize serves both recovery and the adaptation
            # latch read-back further down.
            recovered_state = journal.materialize()
            summary = router.recover(journal, state=recovered_state)
            print(f"fleet: recovered {summary['tenants']} tenant(s) from "
                  f"{args.journal} (reregistered "
                  f"{summary['reregistered']}, caught up "
                  f"{summary['caught_up']} replica(s) to "
                  f"v{summary['params_version']})", file=sys.stderr)
        entry = router.directory.get("default")
        if entry is None:
            ds = _support_dataset(args, first.registry.k, seed=args.seed)
            owner = control.register_tenant(
                "default", ds, max_classes=args.max_classes,
                nota_threshold=args.nota_threshold,
            )
        else:
            # The recovered fleet serves the JOURNALED corpus — never a
            # freshly rebuilt one (digest parity with the pre-crash
            # registrations); fall back to a rebuild only for a
            # params-only row with no recoverable source.
            owner = entry.owner
            ds = (entry.source if entry.source is not None
                  else _support_dataset(args, first.registry.k,
                                        seed=args.seed))
        compiled = sum(h.warmup() for h in router.replicas.values())
        print(f"fleet: {n} replica(s), default tenant placed on {owner}, "
              f"{compiled} bucket programs compiled", file=sys.stderr)

        if args.autoscale:
            from induction_network_on_fewrel_tpu.fleet import (
                FleetAutoscaler,
            )

            scaler = FleetAutoscaler(
                control,
                lambda rid: InProcessReplica(rid, mk_engine()),
                slo=slo,
                min_replicas=args.autoscale_min,
                max_replicas=args.autoscale_max,
                logger=logger,
            )

            def _tick_loop():
                while not stop_evt.wait(args.autoscale_interval_s):
                    try:
                        scaler.tick()
                    except Exception as e:  # noqa: BLE001 — the loop
                        # must outlive one bad tick; stuck decisions
                        # already latch their own CRITICAL.
                        print(f"autoscaler tick failed: "
                              f"{type(e).__name__}: {e}",
                              file=sys.stderr)

            threading.Thread(target=_tick_loop, name="fleet-autoscaler",
                             daemon=True).start()
            print(f"autoscaler armed: {args.autoscale_min}.."
                  f"{args.autoscale_max} replicas, tick every "
                  f"{args.autoscale_interval_s}s", file=sys.stderr)

        if args.control_socket:
            def _drain(req):
                control.drain_replica(req["replica"])
                return {"replica": req["replica"],
                        "moved": control.replace_tenants()}

            def _forgive(req):
                control.forgive_replica(req["replica"])
                return {"replica": req["replica"]}

            def _revive(req):
                control.revive_replica(req["replica"],
                                       reason="operator")
                return {"replica": req["replica"],
                        "moved": control.replace_tenants()}

            def _retire(req):
                control.retire_replica(req["replica"])
                return {"replica": req["replica"],
                        "replicas": len(router.replicas)}

            _start_control_server(args.control_socket, {
                "drain": _drain,
                "forgive": _forgive,
                "revive": _revive,
                "retire": _retire,
                "stats": lambda req: router.snapshot(),
            }, stop_evt)
            print(f"control socket listening on {args.control_socket} "
                  "(drain/forgive/revive/retire/stats)", file=sys.stderr)

        if args.adapt:
            from induction_network_on_fewrel_tpu.config import (
                resolve_adapt_policy,
            )
            from induction_network_on_fewrel_tpu.train.checkpoint import (
                CheckpointManager,
            )

            base_cfg = (
                CheckpointManager.load_config(args.load_ckpt)
                if args.load_ckpt else first.cfg
            )
            policy = resolve_adapt_policy(args, base=base_cfg)
            tgt_ds = _adapt_target_dataset(args, first.registry.k)
            base_ckpt = args.load_ckpt or _save_base_checkpoint(
                first,
                os.path.join(args.run_dir or ".", "adapt_base_ckpt"),
            )
            adapt = _build_adapt(
                args, policy, drift=drift, model=first.model,
                cfg=first.cfg, tok=first.tokenizer, src_ds=ds,
                tgt_ds=tgt_ds, base_ckpt=base_ckpt,
                # Survivors publish into the LIVE FLEET through the
                # existing all-or-nothing fan-out: any replica's refusal
                # rolls every replica back before anything moved.
                publish_fn=control.publish_checkpoint,
                quarantine_fn=lambda t, reason="": (
                    control.quarantine_tenant(t, reason=reason)
                ),
                logger=logger, recorder=recorder, capture=capture,
                journal=journal,
            )
            if recovered_state is not None \
                    and recovered_state.adapt_exhausted:
                # The journaled PERMANENT exhaustion latches must
                # survive the restart: re-prime them before the
                # controller takes its first drift event.
                adapt.restore_exhausted(recovered_state.adapt_exhausted)
            adapt.start()
            print("adaptation controller armed over the fleet fan-out "
                  f"(retries={policy['retry_budget']})", file=sys.stderr)

        def answer(instance) -> dict:
            return router.classify(
                instance, args.deadline_ms / 1e3, tenant="default"
            )

        if args.input:
            stream = sys.stdin if args.input == "-" else open(args.input)
            try:
                for line in stream:
                    line = line.strip()
                    if line:
                        print(json.dumps(
                            answer(json.loads(line))
                        ), flush=True)
            finally:
                if stream is not sys.stdin:
                    stream.close()
        else:
            names = list(ds.rel_names)
            if args.max_classes is not None:
                names = names[: args.max_classes]
            _demo(
                lambda inst: router.submit(
                    inst, args.deadline_ms / 1e3, tenant="default"
                ),
                ds, names, first.registry.k, args.demo_queries,
                seed=args.seed,
            )

        router.emit_stats()
        print("fleet stats: " + json.dumps(router.snapshot()),
              file=sys.stderr)
        return 0
    finally:
        stop_evt.set()
        if args.run_dir:
            _write_prometheus(args.run_dir)
        if adapt is not None:
            adapt.close()
        router.close()
        if journal is not None:
            journal.close()
        if logger is not None:
            logger.close()


def _demo(submit, ds, names, k: int, num_queries: int,
          seed: int = 0) -> None:
    """Self-contained demo: classify held-out instances of the registered
    corpus (instances AFTER the K supports, so the engine has not seen
    them) and print one verdict line each. ``submit`` is any
    Future-returning entry — the engine's submit or the fleet router's
    (one demo, both transports)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    registered = set(names)
    pool = [
        (rel, inst)
        for rel in ds.rel_names if rel in registered
        for inst in ds.instances[rel][k:]
    ]
    if not pool:
        pool = [(rel, ds.instances[rel][0]) for rel in registered]
    from induction_network_on_fewrel_tpu.serving.batcher import Saturated

    futures = []
    shed = 0
    for i in rng.choice(len(pool), size=min(num_queries, len(pool)),
                        replace=False):
        rel, inst = pool[int(i)]
        try:
            futures.append((rel, submit(inst)))
        except Saturated as e:
            # A well-behaved client under backpressure/breaker shed: the
            # demo reports it instead of dying on the typed error
            # (containment drills run through this path — RUNBOOK §17).
            shed += 1
            print(json.dumps({"true": rel, "shed": True,
                              "retry_after_s": e.retry_after_s}),
                  flush=True)
    hits = errors = 0
    for true_rel, fut in futures:
        try:
            verdict = fut.result(timeout=30.0)
        except Exception as e:  # noqa: BLE001 — typed ExecuteError et al.
            errors += 1
            print(json.dumps({"true": true_rel,
                              "error": f"{type(e).__name__}: {e}"}),
                  flush=True)
            continue
        hits += verdict["label"] == true_rel
        print(json.dumps({"true": true_rel, **verdict}), flush=True)
    tail = "".join(
        [f", {shed} shed" if shed else "",
         f", {errors} errors" if errors else ""]
    )
    print(f"demo accuracy: {hits}/{len(futures)}{tail}", file=sys.stderr)
