"""TPU-native few-shot inference engine (serving/).

Turns a trained induction-network checkpoint into a low-latency
query-answering engine. The induction network's structure makes serving
cheap (ISSUE 1 / Geng et al. 2019): a support set is distilled ONCE by the
dynamic-routing loop into per-class vectors, after which each query costs
one encoder pass plus the neural-tensor score. The pieces:

* ``registry``  — ClassVectorRegistry: support sets -> device-resident
  [N, C] class vectors (encoded once, never re-encoded at query time).
* ``buckets``   — fixed shape buckets + AOT-compiled query programs, so
  steady-state serving runs with ZERO recompiles.
* ``batcher``   — dynamic micro-batcher: request queue with deadlines,
  bounded-depth backpressure, and partial-bucket flush under pressure.
* ``stats``     — p50/p99 latency, queue depth, batch occupancy, recompile
  counters, emitted through utils.metrics.MetricsLogger.
* ``engine``    — InferenceEngine: wires the above behind submit()/classify(),
  including the FewRel 2.0 NOTA "no_relation" verdict (Gao et al. 2019).
* ``cli``       — the ``serve.py`` entrypoint next to train.py/test.py.
"""

from induction_network_on_fewrel_tpu.serving.batcher import (  # noqa: F401
    DeadlineExceeded,
    DynamicBatcher,
    Saturated,
)
from induction_network_on_fewrel_tpu.serving.buckets import (  # noqa: F401
    DEFAULT_BUCKETS,
    QueryProgramCache,
    pad_rows,
    select_bucket,
)
from induction_network_on_fewrel_tpu.serving.engine import (  # noqa: F401
    InferenceEngine,
)
from induction_network_on_fewrel_tpu.serving.registry import (  # noqa: F401
    ClassVectorRegistry,
)
from induction_network_on_fewrel_tpu.serving.stats import (  # noqa: F401
    ServingStats,
)
