"""TPU-native few-shot inference engine (serving/) — fleet-scale.

Turns a trained induction-network checkpoint into a low-latency
multi-tenant query-answering engine. The induction network's structure
makes serving cheap (ISSUE 1 / Geng et al. 2019): a support set is
distilled ONCE by the dynamic-routing loop into per-class vectors, after
which each query costs one encoder pass plus the neural-tensor score.
ISSUE 7 takes it to fleet shape: the registry is versioned, multi-tenant,
copy-on-write — the system's public API surface — and the scheduler is a
continuous cross-bucket batcher. The pieces:

* ``registry``  — TenantRegistry: tenant x relation-set support sets ->
  device-resident [N, C] class vectors published as immutable CoW
  ``Snapshot``s (shared slot pool, per-tenant NOTA thresholds, atomic
  zero-recompile hot-swap from training checkpoints).
* ``buckets``   — fixed shape buckets + AOT-compiled query programs
  (optionally dp-sharded over a serving mesh), so steady-state serving
  runs with ZERO recompiles.
* ``batcher``   — ContinuousBatcher (fleet default): one admission
  structure over all buckets, launch-on-free, deadline-aware cross-tenant
  ordering, per-tenant shed-load; DynamicBatcher — the per-bucket
  micro-batcher, kept as the A/B comparison arm.
* ``stats``     — p50/p99 latency (aggregate + per tenant), queue depth,
  batch occupancy, shed/swap/recompile counters, emitted through
  utils.metrics.MetricsLogger.
* ``engine``    — InferenceEngine: wires the above behind
  submit()/classify()/publish_params(), including the FewRel 2.0 NOTA
  "no_relation" verdict (Gao et al. 2019) under per-tenant thresholds.
* ``cli``       — the ``serve.py`` entrypoint next to train.py/test.py.
"""

from induction_network_on_fewrel_tpu.serving.batcher import (  # noqa: F401
    ContinuousBatcher,
    DeadlineExceeded,
    DynamicBatcher,
    Saturated,
)
from induction_network_on_fewrel_tpu.serving.buckets import (  # noqa: F401
    DEFAULT_BUCKETS,
    QueryProgramCache,
    make_serving_mesh,
    pad_rows,
    select_bucket,
)
from induction_network_on_fewrel_tpu.serving.engine import (  # noqa: F401
    InferenceEngine,
)
from induction_network_on_fewrel_tpu.serving.registry import (  # noqa: F401
    DEFAULT_TENANT,
    ClassVectorRegistry,
    Snapshot,
    TenantRegistry,
)
from induction_network_on_fewrel_tpu.serving.stats import (  # noqa: F401
    ServingStats,
)
