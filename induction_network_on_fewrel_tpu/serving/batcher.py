"""Serving schedulers: the continuous cross-bucket batcher (fleet default)
and the per-bucket dynamic micro-batcher (the pre-fleet baseline, kept as
the A/B comparison arm).

**ContinuousBatcher** (ISSUE 7 tentpole) — one admission structure feeds
every shape bucket: per-group (tenant) deadline-ordered heaps behind a
single condition variable. The worker launches the next bucket program the
MOMENT it frees — no per-bucket flush barrier, no coalescing window on the
hot path: while the device executes one batch, admissions accumulate, so
the next launch packs whatever is pending into the largest fitting bucket
(continuous batching's classic property: light load = immediate launch =
minimum latency; heavy load = full buckets = maximum throughput, with no
knob to tune between them). Scheduling is deadline-aware ACROSS groups —
each launch serves the group holding the globally most-urgent request, so
one tenant's backlog can never head-of-line-block another tenant's urgent
query. Backpressure is two-level: a global queue bound plus a per-tenant
share; an overloaded tenant sheds (``Saturated``) while others keep
admitting — shed-load fairness, tested in tests/test_serving_fleet.py.

**DynamicBatcher** — the original single-queue micro-batcher: coalesce up
to ``max(buckets)`` requests (waiting up to ``batch_window_s`` for
stragglers), flush early under deadline pressure. Three robustness
behaviors, each tested in tests/test_serving.py:

* **Deadlines** — every request carries an absolute deadline. Requests that
  expire before execution fail fast with ``DeadlineExceeded`` (never run a
  query whose client has given up); a partial bucket is flushed EARLY when
  the oldest request's slack (deadline - now - estimated execution time)
  runs out, trading batch occupancy for meeting the deadline.
* **Backpressure** — the queue has a hard depth bound. When it is full,
  ``submit`` raises ``Saturated`` carrying a retry-after estimate instead of
  queueing unbounded work (the client sheds load; the engine stays at a
  bounded latency).
* **Fault isolation** — an execution error fails that batch's futures, not
  the worker thread.

Both expose the same surface (``submit``/``drain_once``/``close``/
``queue_depth``/``buckets``), so the engine selects one by the
``scheduler`` knob and everything downstream is agnostic.
"""

from __future__ import annotations

import dataclasses
import heapq
import queue
import threading
import time
from concurrent.futures import Future
from typing import Callable

from induction_network_on_fewrel_tpu.serving.buckets import DEFAULT_BUCKETS


class Saturated(RuntimeError):
    """Queue at capacity — retry after ``retry_after_s``. ``tenant`` names
    the shed scope: a per-tenant share breach sheds THAT tenant while the
    queue still admits others; ``None`` means the global bound."""

    def __init__(self, retry_after_s: float, tenant: str | None = None):
        scope = f"tenant {tenant!r}" if tenant else "serving queue"
        super().__init__(
            f"{scope} saturated; retry after {retry_after_s:.3f}s"
        )
        self.retry_after_s = retry_after_s
        self.tenant = tenant


class DeadlineExceeded(TimeoutError):
    """The request's deadline expired before it reached the device."""


class TransportTimeout(DeadlineExceeded):
    """A TRANSPORT-side deadline: the replica never answered a socket
    call within the per-call deadline (fleet/transport.SocketReplica,
    ISSUE 15). Typed as ``DeadlineExceeded`` so clients handle both the
    same way, but distinguishable on purpose: a server-side deadline
    miss is LOAD (the batcher expired the request — never fed to the
    replica breaker), while a wedged peer that answers nothing is
    HEALTH (the router's breaker counts it toward replica death)."""


class ExecuteError(RuntimeError):
    """A launch failed on the device/host side: the batch's futures fail
    with THIS (typed, retry-after-bearing) error and nothing else — the
    worker survives, other tenants' batches are untouched (ISSUE 12
    fault containment). ``retry_after_s`` tells an adaptive client when
    resubmitting is worth trying (the breaker's open window when one is
    armed, else the drain estimate — same convention as ``Saturated``);
    ``cause`` carries the original exception."""

    def __init__(self, tenant: str, retry_after_s: float,
                 cause: BaseException | None = None):
        super().__init__(
            f"execution failed for tenant {tenant!r} "
            f"({type(cause).__name__ if cause is not None else 'unknown'}: "
            f"{cause}); retry after {retry_after_s:.3f}s"
        )
        self.tenant = tenant
        self.retry_after_s = retry_after_s
        self.cause = cause


@dataclasses.dataclass
class Request:
    query: dict                 # [L]-leaf tokenized query dict
    deadline: float             # absolute time.monotonic() deadline
    future: Future
    enqueued_at: float
    tenant: str = "default"     # verdict/registry scope (fleet serving)
    # Request-scoped tracing (ISSUE 9): the TraceContext minted at
    # admission when this request was head-sampled, carried across the
    # client->worker thread hop so the execute path can attribute its
    # queue/pack/execute/respond segments to one trace id. None (the
    # default, and always with sampling off) costs the hot path nothing.
    trace: object | None = None


class DynamicBatcher:
    def __init__(
        self,
        execute: Callable[[list[Request]], None],
        buckets: tuple[int, ...] = DEFAULT_BUCKETS,
        max_queue_depth: int = 64,
        batch_window_s: float = 0.002,
        stats=None,
        start: bool = True,
    ):
        """``execute(batch)`` fulfills (or fails) every future in ``batch``.
        ``start=False`` skips the worker thread — unit tests then drive
        ``drain_once()`` directly for deterministic scheduling."""
        self._execute = execute
        self.buckets = tuple(sorted(buckets))
        self.batch_window_s = batch_window_s
        self._stats = stats
        self._q: queue.Queue = queue.Queue(maxsize=max_queue_depth)
        self._closed = False
        self._worker = None
        if start:
            self._worker = threading.Thread(target=self._run, daemon=True)
            self._worker.start()

    # --- client side -----------------------------------------------------

    @property
    def queue_depth(self) -> int:
        return self._q.qsize()

    def _retry_after_s(self) -> float:
        """How long a rejected client should back off: the time to drain the
        queue at the observed per-batch execution rate."""
        est = self._stats.exec_estimate_s() if self._stats else 0.005
        batches_ahead = self._q.maxsize / max(self.buckets) + 1
        return batches_ahead * max(est, 1e-4)

    def submit(
        self, query: dict, deadline_s: float, tenant: str = "default",
        trace=None,
    ) -> Future:
        """Enqueue one tokenized query; returns its Future. Raises
        ``Saturated`` (with a retry-after hint) when the queue is full."""
        if self._closed:
            raise RuntimeError("batcher is closed")
        now = time.monotonic()
        req = Request(
            query=query, deadline=now + deadline_s, future=Future(),
            enqueued_at=now, tenant=tenant, trace=trace,
        )
        try:
            self._q.put_nowait(req)
        except queue.Full:
            if self._stats:
                self._stats.record_rejected(tenant)
            raise Saturated(self._retry_after_s()) from None
        return req.future

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            # Sentinel unblocks an idle worker. put_nowait, not put: a FULL
            # queue (closing under saturation) must not block close —
            # the worker re-checks _closed within its 0.1 s poll anyway.
            self._q.put_nowait(None)
        except queue.Full:
            pass
        if self._worker is not None:
            self._worker.join(timeout=10.0)

    # --- worker side -----------------------------------------------------

    def _repost_sentinel(self) -> None:
        # NEVER a blocking put: a racing submitter can refill the slot the
        # sentinel just freed, and this thread is the queue's only consumer
        # — a blocking re-post would deadlock it. _closed is already set,
        # so a dropped sentinel only costs one 0.1 s poll.
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass

    def _collect(self, first: Request) -> list[Request]:
        """Coalesce up to ``max(buckets)`` requests starting from ``first``.

        Waits at most ``batch_window_s`` for stragglers, and LESS when the
        oldest collected request's deadline slack is smaller — that early
        return is the partial-bucket flush under deadline pressure.
        """
        batch = [first]
        cap = self.buckets[-1]
        window_end = time.monotonic() + self.batch_window_s
        exec_est = self._stats.exec_estimate_s() if self._stats else 0.005
        while len(batch) < cap:
            now = time.monotonic()
            slack = min(r.deadline for r in batch) - now - exec_est
            wait = min(window_end - now, slack)
            if wait <= 0:
                break
            try:
                nxt = self._q.get(timeout=wait)
            except queue.Empty:
                break
            if nxt is None:          # close() sentinel mid-collection:
                self._repost_sentinel()  # for the outer loop; flush now
                break
            batch.append(nxt)
        return batch

    def split_expired(
        self, batch: list[Request], now: float | None = None
    ) -> tuple[list[Request], list[Request]]:
        """(live, expired) partition; expired futures fail immediately."""
        return _split_expired(batch, self._stats, now)

    def drain_once(self, block_s: float = 0.1) -> int:
        """One worker iteration: collect, expire, execute. Returns the number
        of requests executed (0 when idle). Public so tests and synchronous
        callers can drive the batcher without the thread."""
        try:
            first = self._q.get(timeout=block_s)
        except queue.Empty:
            return 0
        if first is None:
            self._repost_sentinel()
            return 0
        batch = self._collect(first)
        live, _ = self.split_expired(batch)
        if not live:
            return 0
        try:
            self._execute(live)
        except BaseException as e:  # noqa: BLE001 — fail the batch, not the worker
            for r in live:
                if not r.future.done():
                    r.future.set_exception(e)
        return len(live)

    def _run(self) -> None:
        while not self._closed:
            self.drain_once()
        # Closed: fail anything still queued so no client blocks forever.
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                return
            if req is not None and not req.future.done():
                req.future.set_exception(RuntimeError("batcher closed"))


def _split_expired(
    batch: list[Request], stats, now: float | None = None
) -> tuple[list[Request], list[Request]]:
    """(live, expired) partition shared by both schedulers; expired
    futures fail immediately with ``DeadlineExceeded``."""
    now = time.monotonic() if now is None else now
    live = [r for r in batch if r.deadline > now]
    dead = [r for r in batch if r.deadline <= now]
    for r in dead:
        if stats:
            stats.record_deadline_miss(r.tenant)
        r.future.set_exception(
            DeadlineExceeded(
                f"deadline exceeded after {now - r.enqueued_at:.3f}s in queue"
            )
        )
    return live, dead


class ContinuousBatcher:
    """Continuous cross-bucket scheduler: one admission structure, per-group
    deadline heaps, launch-on-free.

    ``execute(group, batch)`` fulfills (or fails) every future in ``batch``
    — all requests of one call belong to one ``group`` (the engine keys
    groups by tenant: one tenant = one class matrix = one program call).

    Scheduling invariants (tests/test_serving_fleet.py):

    * **Launch the moment capacity frees** — no coalescing window, no
      per-bucket flush barrier: the worker pops the most urgent group and
      executes immediately; batch size is whatever accumulated while the
      device was busy (capped at ``max(buckets)``).
    * **Deadline-aware cross-group ordering** — each launch serves the
      group whose head request has the globally earliest deadline, so a
      deep backlog in one tenant never head-of-line-blocks another
      tenant's urgent query.
    * **Two-level backpressure** — a global ``max_queue_depth`` bound plus
      a per-tenant share (``tenant_share`` of the global bound): an
      overloaded tenant gets ``Saturated(tenant=...)`` (shed-load) while
      other tenants keep admitting. The share binds only once a SECOND
      tenant has ever submitted — a single-tenant deployment keeps the
      full queue instead of silently halving its capacity and reporting
      plain saturation as shed-load.
    * **Zero steady-state recompiles** — padding to the fixed bucket set
      is unchanged; this class only reorders WHICH requests share a
      program launch, never the program shapes.
    """

    # A waiting head becomes urgent once it has burned this fraction of
    # its deadline budget — the anti-starvation bound (_pop_group_locked).
    STALE_BUDGET_FRAC = 0.25

    def __init__(
        self,
        execute: Callable[[str, list[Request]], None],
        buckets: tuple[int, ...] = DEFAULT_BUCKETS,
        max_queue_depth: int = 256,
        tenant_share: float = 0.5,
        stats=None,
        start: bool = True,
        batch_window_s: float = 0.0,
    ):
        """``batch_window_s`` is accepted for interface parity with the
        micro-batcher but intentionally unused: continuous batching's whole
        point is that the execute path itself is the coalescing window."""
        self._execute = execute
        self.buckets = tuple(sorted(buckets))
        self._stats = stats
        self.max_queue_depth = max_queue_depth
        self.tenant_cap = max(1, int(max_queue_depth * tenant_share))
        self._cv = threading.Condition()
        # Every tenant that has EVER submitted: the per-tenant share only
        # binds in actual multi-tenant use (see class doc).
        self._seen: set[str] = set()
        # group -> deadline-ordered heap of (deadline, seq, Request); seq
        # breaks deadline ties FIFO (Requests don't order).
        self._pending: dict[str, list] = {}
        # Indexed selection (ISSUE 11, the BASELINE round-9 scale
        # paydown): the per-launch pop used to scan EVERY active group
        # under the admission lock — O(active groups), the known ceiling
        # of a 10k-tenant soak. Two lazy heaps replace the scan:
        #
        # * ``_urgent``  — global [deadline, seq, Request] min-heap, one
        #   entry per ADMISSION, the SAME mutable list object the group
        #   heap holds (deadline+seq order; seq is unique, so comparison
        #   never reaches the Request slot). The globally-earliest
        #   still-pending entry is necessarily the head of its group's
        #   own deadline-ordered heap, so peeking it IS the urgent-group
        #   lookup. Popping a batch NULLS each entry's Request slot in
        #   place — the stale marker AND the memory release (a retained
        #   tuple would pin the executed request's query payload +
        #   result future until the entry drifted to the heap top, ~the
        #   deadline horizon at high qps); stale entries are discarded
        #   lazily, each pushed once and discarded at most once, so the
        #   amortized pop cost is O(log pending).
        # * ``_depth``   — lazy (-depth, seq, group) max-heap; a group is
        #   (re)pushed when its depth GROWS. A popped entry whose stored
        #   depth disagrees with the group's live depth is stale: it is
        #   discarded and, when the group still has pending work, one
        #   accurate entry is re-pushed before continuing — every stale
        #   entry is consumed exactly once, so this also amortizes to
        #   O(log) per selection instead of O(groups).
        self._urgent: list = []
        self._depth: list = []
        self._count = 0
        self._seq = 0
        self._closed = False
        self._worker = None
        if start:
            self._worker = threading.Thread(target=self._run, daemon=True)
            self._worker.start()

    # --- client side -----------------------------------------------------

    @property
    def queue_depth(self) -> int:
        with self._cv:
            return self._count

    def group_depth(self, group: str) -> int:
        with self._cv:
            return len(self._pending.get(group, ()))

    def _retry_after_s(self, pending: int) -> float:
        """Backoff hint: time to drain ``pending`` requests at the observed
        per-batch execution rate and full-bucket packing."""
        est = self._stats.exec_estimate_s() if self._stats else 0.005
        batches_ahead = pending / self.buckets[-1] + 1
        return batches_ahead * max(est, 1e-4)

    def submit(
        self, query: dict, deadline_s: float, tenant: str = "default",
        trace=None,
    ) -> Future:
        """Admit one tokenized query for ``tenant``; returns its Future.
        Raises ``Saturated`` when the global queue is at bound, or
        ``Saturated(tenant=...)`` when this tenant exceeds its share while
        others still have room (per-tenant shed-load; binds only once a
        second tenant has ever submitted)."""
        now = time.monotonic()
        req = Request(
            query=query, deadline=now + deadline_s, future=Future(),
            enqueued_at=now, tenant=tenant, trace=trace,
        )
        with self._cv:
            if self._closed:
                raise RuntimeError("batcher is closed")
            mine = self._pending.get(tenant)
            depth_mine = len(mine) if mine else 0
            if len(self._seen) > 1 and depth_mine >= self.tenant_cap:
                if self._stats:
                    self._stats.record_shed(tenant)
                raise Saturated(
                    self._retry_after_s(depth_mine), tenant=tenant
                )
            if self._count >= self.max_queue_depth:
                if self._stats:
                    self._stats.record_rejected(tenant)
                raise Saturated(self._retry_after_s(self._count))
            # Seen = ADMITTED at least once: a rejected stray submit must
            # not permanently activate the share for the resident tenant.
            self._seen.add(tenant)
            if mine is None:
                mine = self._pending[tenant] = []
            self._seq += 1
            entry = [req.deadline, self._seq, req]
            heapq.heappush(mine, entry)
            heapq.heappush(self._urgent, entry)
            heapq.heappush(self._depth, (-len(mine), self._seq, tenant))
            self._count += 1
            self._cv.notify()
        return req.future

    def close(self) -> None:
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._cv.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=10.0)
        # Fail anything still admitted so no client blocks forever.
        with self._cv:
            for heap in self._pending.values():
                for entry in heap:
                    req, entry[2] = entry[2], None
                    if req is not None and not req.future.done():
                        req.future.set_exception(
                            RuntimeError("batcher closed")
                        )
            self._pending.clear()
            self._urgent.clear()
            self._depth.clear()
            self._count = 0

    # --- worker side -----------------------------------------------------

    def _urgent_head_locked(self) -> Request | None:
        """The globally most-urgent pending request, via the lazy global
        deadline heap: discard stale (nulled-at-pop) entries from the
        top, then peek. The surviving minimum is necessarily the head of
        its own group's deadline-ordered heap — group heaps hold only
        pending entries, ordered by the same (deadline, seq) key."""
        heap = self._urgent
        while heap and heap[0][2] is None:
            heapq.heappop(heap)
        return heap[0][2] if heap else None

    def _deepest_group_locked(self) -> str | None:
        """The group with the most pending requests, via the lazy depth
        max-heap: a top entry whose stored depth disagrees with the live
        depth is stale — consume it and, while the group still has work,
        re-push ONE accurate entry before re-examining. Every admission
        pushes one entry and every stale entry is consumed exactly once,
        so the amortized cost is O(log pending) per selection — never a
        scan over active groups."""
        heap = self._depth
        while heap:
            d, _, group = heap[0]
            live_heap = self._pending.get(group)
            live = len(live_heap) if live_heap else 0
            if live and -d == live:
                return group
            heapq.heappop(heap)
            if live:
                self._seq += 1
                heapq.heappush(heap, (-live, self._seq, group))
        return None

    def _pop_group_locked(self) -> tuple[str, list[Request]] | None:
        """Pop up to ``max(buckets)`` requests of the scheduled group (call
        with the cv lock held).

        Slot-level packing policy: serve the group with the globally
        earliest head deadline when that request is URGENT — its deadline
        at risk (slack under ~two executions: it must go now or it
        expires) OR it has burned more than ``STALE_BUDGET_FRAC`` of its
        deadline budget waiting (a sparse tenant's lone query must not
        idle behind a busy tenant's standing backlog until its deadline
        nearly expires); otherwise serve the DEEPEST group, maximizing
        slots filled per launch. Deadline-awareness is what prevents
        head-of-line blocking across tenants; largest-group packing is
        what keeps occupancy high when nothing is urgent — without it,
        launch-on-free degenerates into single-row launches at
        sub-saturation arrival rates and the per-launch fixed cost caps
        throughput (measured in the round-9 loadgen A/B). The staleness
        trigger is deliberately BUDGET-relative, not exec-relative: an
        exec-estimate multiple looks natural but self-tightens as urgent
        launches shrink batches (smaller batches -> smaller estimate ->
        more urgency), collapsing the scheduler into oldest-first
        single-row launches under open-loop load (measured: open p99
        3.5x WORSE). Budget fraction is load-independent: healthy
        steady-state waits never approach it, and a starved request is
        still served within ~STALE_BUDGET_FRAC of its deadline instead
        of at its deadline.

        Selection is INDEXED (ISSUE 11, paying down the round-9 scale
        follow-up): the urgent head comes off the lazy global deadline
        heap and the deepest group off the lazy depth heap — both
        amortized O(log pending) — so the per-launch cost under the
        admission lock no longer scales with active groups (the 10k-
        tenant soak ceiling). Pinned structurally in
        tests/test_serving_fleet.py::test_pop_never_scans_groups."""
        head = self._urgent_head_locked()
        if head is None:
            return None
        exec_est = self._stats.exec_estimate_s() if self._stats else 0.005
        now = time.monotonic()
        slack = head.deadline - now - exec_est
        budget = head.deadline - head.enqueued_at
        stale = (now - head.enqueued_at) > self.STALE_BUDGET_FRAC * budget
        if slack < 2 * exec_est or stale:
            group = head.tenant
        else:
            group = self._deepest_group_locked()
            if group is None:       # urgent head exists => impossible,
                group = head.tenant  # but never crash the worker on it
        heap = self._pending[group]
        cap = self.buckets[-1]
        batch = []
        while heap and len(batch) < cap:
            entry = heapq.heappop(heap)
            batch.append(entry[2])
            # Null the shared slot: marks the _urgent twin stale AND
            # releases the executed request the moment it leaves the
            # queue (see the index comment in __init__).
            entry[2] = None
        if not heap:
            del self._pending[group]
        self._count -= len(batch)
        return group, batch

    def drain_once(self, block_s: float = 0.1) -> int:
        """One scheduler iteration: wait for admissions (at most
        ``block_s``), pop the most urgent group, expire, execute. Returns
        requests executed (0 when idle). Public so tests and synchronous
        callers drive the scheduler without the thread."""
        with self._cv:
            if self._count == 0 and not self._closed:
                self._cv.wait(timeout=block_s)
            popped = self._pop_group_locked()
        if popped is None:
            return 0
        group, batch = popped
        live, _ = _split_expired(batch, self._stats)
        if not live:
            return 0
        try:
            self._execute(group, live)
        except BaseException as e:  # noqa: BLE001 — fail the batch, not the worker
            for r in live:
                if not r.future.done():
                    r.future.set_exception(e)
        return len(live)

    def _run(self) -> None:
        while True:
            with self._cv:
                if self._closed:
                    # Prompt-fail close (the DynamicBatcher contract): the
                    # backlog is NOT drained — close() fails every still-
                    # admitted future after the join. Only a batch already
                    # mid-execute finishes.
                    return
            self.drain_once()
