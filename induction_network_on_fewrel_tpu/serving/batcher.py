"""Dynamic micro-batcher: coalesce requests into shape buckets under
deadline pressure, with bounded-depth backpressure.

One worker thread owns the device: it pulls requests off a bounded queue,
coalesces up to ``max(buckets)`` of them (waiting at most ``batch_window_s``
for stragglers), and hands the batch to the engine's execute callback. Three
robustness behaviors, each tested in tests/test_serving.py:

* **Deadlines** — every request carries an absolute deadline. Requests that
  expire before execution fail fast with ``DeadlineExceeded`` (never run a
  query whose client has given up); a partial bucket is flushed EARLY when
  the oldest request's slack (deadline - now - estimated execution time)
  runs out, trading batch occupancy for meeting the deadline.
* **Backpressure** — the queue has a hard depth bound. When it is full,
  ``submit`` raises ``Saturated`` carrying a retry-after estimate instead of
  queueing unbounded work (the client sheds load; the engine stays at a
  bounded latency).
* **Fault isolation** — an execution error fails that batch's futures, not
  the worker thread.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from concurrent.futures import Future
from typing import Callable

from induction_network_on_fewrel_tpu.serving.buckets import DEFAULT_BUCKETS


class Saturated(RuntimeError):
    """Queue at capacity — retry after ``retry_after_s``."""

    def __init__(self, retry_after_s: float):
        super().__init__(
            f"serving queue saturated; retry after {retry_after_s:.3f}s"
        )
        self.retry_after_s = retry_after_s


class DeadlineExceeded(TimeoutError):
    """The request's deadline expired before it reached the device."""


@dataclasses.dataclass
class Request:
    query: dict                 # [L]-leaf tokenized query dict
    deadline: float             # absolute time.monotonic() deadline
    future: Future
    enqueued_at: float


class DynamicBatcher:
    def __init__(
        self,
        execute: Callable[[list[Request]], None],
        buckets: tuple[int, ...] = DEFAULT_BUCKETS,
        max_queue_depth: int = 64,
        batch_window_s: float = 0.002,
        stats=None,
        start: bool = True,
    ):
        """``execute(batch)`` fulfills (or fails) every future in ``batch``.
        ``start=False`` skips the worker thread — unit tests then drive
        ``drain_once()`` directly for deterministic scheduling."""
        self._execute = execute
        self.buckets = tuple(sorted(buckets))
        self.batch_window_s = batch_window_s
        self._stats = stats
        self._q: queue.Queue = queue.Queue(maxsize=max_queue_depth)
        self._closed = False
        self._worker = None
        if start:
            self._worker = threading.Thread(target=self._run, daemon=True)
            self._worker.start()

    # --- client side -----------------------------------------------------

    @property
    def queue_depth(self) -> int:
        return self._q.qsize()

    def _retry_after_s(self) -> float:
        """How long a rejected client should back off: the time to drain the
        queue at the observed per-batch execution rate."""
        est = self._stats.exec_estimate_s() if self._stats else 0.005
        batches_ahead = self._q.maxsize / max(self.buckets) + 1
        return batches_ahead * max(est, 1e-4)

    def submit(self, query: dict, deadline_s: float) -> Future:
        """Enqueue one tokenized query; returns its Future. Raises
        ``Saturated`` (with a retry-after hint) when the queue is full."""
        if self._closed:
            raise RuntimeError("batcher is closed")
        now = time.monotonic()
        req = Request(
            query=query, deadline=now + deadline_s, future=Future(),
            enqueued_at=now,
        )
        try:
            self._q.put_nowait(req)
        except queue.Full:
            if self._stats:
                self._stats.record_rejected()
            raise Saturated(self._retry_after_s()) from None
        return req.future

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            # Sentinel unblocks an idle worker. put_nowait, not put: a FULL
            # queue (closing under saturation) must not block close —
            # the worker re-checks _closed within its 0.1 s poll anyway.
            self._q.put_nowait(None)
        except queue.Full:
            pass
        if self._worker is not None:
            self._worker.join(timeout=10.0)

    # --- worker side -----------------------------------------------------

    def _repost_sentinel(self) -> None:
        # NEVER a blocking put: a racing submitter can refill the slot the
        # sentinel just freed, and this thread is the queue's only consumer
        # — a blocking re-post would deadlock it. _closed is already set,
        # so a dropped sentinel only costs one 0.1 s poll.
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass

    def _collect(self, first: Request) -> list[Request]:
        """Coalesce up to ``max(buckets)`` requests starting from ``first``.

        Waits at most ``batch_window_s`` for stragglers, and LESS when the
        oldest collected request's deadline slack is smaller — that early
        return is the partial-bucket flush under deadline pressure.
        """
        batch = [first]
        cap = self.buckets[-1]
        window_end = time.monotonic() + self.batch_window_s
        exec_est = self._stats.exec_estimate_s() if self._stats else 0.005
        while len(batch) < cap:
            now = time.monotonic()
            slack = min(r.deadline for r in batch) - now - exec_est
            wait = min(window_end - now, slack)
            if wait <= 0:
                break
            try:
                nxt = self._q.get(timeout=wait)
            except queue.Empty:
                break
            if nxt is None:          # close() sentinel mid-collection:
                self._repost_sentinel()  # for the outer loop; flush now
                break
            batch.append(nxt)
        return batch

    def split_expired(
        self, batch: list[Request], now: float | None = None
    ) -> tuple[list[Request], list[Request]]:
        """(live, expired) partition; expired futures fail immediately."""
        now = time.monotonic() if now is None else now
        live = [r for r in batch if r.deadline > now]
        dead = [r for r in batch if r.deadline <= now]
        for r in dead:
            if self._stats:
                self._stats.record_deadline_miss()
            r.future.set_exception(
                DeadlineExceeded(
                    f"deadline exceeded after {now - r.enqueued_at:.3f}s in queue"
                )
            )
        return live, dead

    def drain_once(self, block_s: float = 0.1) -> int:
        """One worker iteration: collect, expire, execute. Returns the number
        of requests executed (0 when idle). Public so tests and synchronous
        callers can drive the batcher without the thread."""
        try:
            first = self._q.get(timeout=block_s)
        except queue.Empty:
            return 0
        if first is None:
            self._repost_sentinel()
            return 0
        batch = self._collect(first)
        live, _ = self.split_expired(batch)
        if not live:
            return 0
        try:
            self._execute(live)
        except BaseException as e:  # noqa: BLE001 — fail the batch, not the worker
            for r in live:
                if not r.future.done():
                    r.future.set_exception(e)
        return len(live)

    def _run(self) -> None:
        while not self._closed:
            self.drain_once()
        # Closed: fail anything still queued so no client blocks forever.
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                return
            if req is not None and not req.future.done():
                req.future.set_exception(RuntimeError("batcher closed"))
