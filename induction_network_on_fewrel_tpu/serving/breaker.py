"""Per-tenant circuit breaker: shed a repeatedly failing tenant before it
burns device time (ISSUE 12 tentpole, piece 3).

Classic three-state machine, per tenant:

* **closed**    — traffic flows; consecutive launch failures count. At
  ``failure_threshold`` the breaker OPENS (a tenant whose every launch
  raises — poisoned support matrix, pathological queries — must stop
  occupying launches other tenants could use).
* **open**      — submits shed immediately (``Saturated(tenant=...)``
  with the remaining open window as retry-after): zero device time,
  bounded client latency. After ``open_s`` the breaker HALF-OPENS.
* **half-open** — exactly ``half_open_probes`` probe requests admit
  (deterministic: the first N submits after the transition, a counter,
  never a coin flip — drills and tests replay exactly); everything else
  keeps shedding. A probe SUCCESS closes the breaker (failure counter
  reset); a probe FAILURE re-opens it with a fresh window.

The clock is injectable (``clock=``) like every detector in obs/, so
tests compress the open window to whatever wall-time they have. Every
transition invokes ``on_transition(tenant, frm, to, failures, now)`` —
the engine emits one ``kind="fault"`` record per transition
(action="breaker") and the health watchdog latches a CRITICAL
``breaker_open`` per tenant, re-armed by the close transition.

Thread-safety: ``admit`` runs on client threads, ``record_*`` on the
batcher worker — one lock, no I/O under it (transition callbacks fire
after release, in order)."""

from __future__ import annotations

import threading
import time
from typing import Callable

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class _TenantBreaker:
    __slots__ = ("state", "failures", "opened_at", "probes_admitted")

    def __init__(self):
        self.state = CLOSED
        self.failures = 0          # consecutive launch failures (closed)
        self.opened_at = 0.0
        self.probes_admitted = 0   # since the half-open transition


class CircuitBreaker:
    def __init__(
        self,
        failure_threshold: int = 5,
        open_s: float = 5.0,
        half_open_probes: int = 1,
        clock: Callable[[], float] = time.monotonic,
        on_transition=None,
    ):
        if failure_threshold < 1 or half_open_probes < 1 or open_s <= 0:
            raise ValueError(
                "failure_threshold/half_open_probes must be >= 1 and "
                "open_s > 0"
            )
        self.failure_threshold = failure_threshold
        self.open_s = open_s
        self.half_open_probes = half_open_probes
        self._clock = clock
        self.on_transition = on_transition
        self._lock = threading.Lock()
        self._tenants: dict[str, _TenantBreaker] = {}

    # --- client side (submit path) ----------------------------------------

    def admit(self, tenant: str, now: float | None = None) -> float | None:
        """None = admitted; a float = shed, retry after that many
        seconds. Open -> half-open happens lazily here (no timer
        thread): the first arrival past the window becomes the probe."""
        now = self._clock() if now is None else now
        pending = None
        with self._lock:
            tb = self._tenants.get(tenant)
            if tb is None or tb.state == CLOSED:
                return None
            if tb.state == OPEN:
                remaining = tb.opened_at + self.open_s - now
                if remaining > 0:
                    return max(remaining, 1e-3)
                pending = (tenant, OPEN, HALF_OPEN, tb.failures, now)
                tb.state = HALF_OPEN
                tb.probes_admitted = 0
            # HALF_OPEN (possibly just transitioned): deterministic probe
            # admission — the first half_open_probes submits go through.
            if tb.probes_admitted < self.half_open_probes:
                tb.probes_admitted += 1
                out = None
            else:
                out = self.open_s
        if pending is not None:
            self._fire(*pending)
        return out

    def state(self, tenant: str) -> str:
        with self._lock:
            tb = self._tenants.get(tenant)
            return tb.state if tb is not None else CLOSED

    def reset(self, tenant: str) -> None:
        """Forget this key's breaker history (state back to CLOSED, no
        transition callback). For SUPERVISED restarts (fleet/supervisor,
        ISSUE 15): the replacement process shares nothing with the
        process whose failures opened the breaker, so carrying the open
        window over would shed a healthy replica."""
        with self._lock:
            self._tenants.pop(tenant, None)

    # --- worker side (launch outcomes) ------------------------------------

    def record_success(self, tenant: str, now: float | None = None) -> None:
        pending = None
        with self._lock:
            tb = self._tenants.get(tenant)
            if tb is None:
                return
            if tb.state == HALF_OPEN:
                pending = (tenant, HALF_OPEN, CLOSED, tb.failures,
                           self._clock() if now is None else now)
                tb.state = CLOSED
            tb.failures = 0
        if pending is not None:
            self._fire(*pending)

    def record_failure(self, tenant: str, now: float | None = None) -> None:
        now = self._clock() if now is None else now
        pending = None
        with self._lock:
            tb = self._tenants.setdefault(tenant, _TenantBreaker())
            if tb.state == HALF_OPEN:
                # The probe failed: re-open with a fresh window.
                pending = (tenant, HALF_OPEN, OPEN, tb.failures, now)
                tb.state = OPEN
                tb.opened_at = now
            elif tb.state == CLOSED:
                tb.failures += 1
                if tb.failures >= self.failure_threshold:
                    pending = (tenant, CLOSED, OPEN, tb.failures, now)
                    tb.state = OPEN
                    tb.opened_at = now
            # OPEN: a straggler failure from a launch admitted before the
            # open is context, not a new transition.
        if pending is not None:
            self._fire(*pending)

    def _fire(self, tenant, frm, to, failures, now) -> None:
        if self.on_transition is not None:
            self.on_transition(tenant, frm, to, failures, now)
