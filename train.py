#!/usr/bin/env python3
"""Train entrypoint (reference parity: train.py, SURVEY.md §1 L6).

Example:
    python train.py --encoder bilstm --N 5 --K 5 --Q 5 --train_iter 10000 \
        --device tpu --save_ckpt ./ckpt/bilstm_5w5s
"""
import sys

from induction_network_on_fewrel_tpu.cli import train_main

if __name__ == "__main__":
    sys.exit(train_main())
