#!/usr/bin/env python3
"""Eval entrypoint (reference parity: test.py, SURVEY.md §1 L6).

Example:
    python test.py --load_ckpt ./ckpt/bilstm_5w5s --N 5 --K 5 --test_iter 3000
"""
import sys

from induction_network_on_fewrel_tpu.cli import test_main

if __name__ == "__main__":
    sys.exit(test_main())
