#!/usr/bin/env python3
"""Serving entrypoint — the query-answering engine next to train.py/test.py.

Example:
    python serve.py --load_ckpt ./ckpt/bilstm_5w5s \
        --support_file data/val_wiki.json --K 5 --input queries.jsonl

No checkpoint / no data? `python serve.py` runs a fully synthetic demo
(fresh-init weights, synthetic support corpus, built-in demo queries).
"""
import sys

from induction_network_on_fewrel_tpu.serving.cli import serve_main

if __name__ == "__main__":
    sys.exit(serve_main())
