#!/usr/bin/env python3
"""Serving entrypoint — the query-answering engine next to train.py/test.py.

Example:
    python serve.py --load_ckpt ./ckpt/bilstm_5w5s \
        --support_file data/val_wiki.json --K 5 --input queries.jsonl

Observability (ISSUE 9): add `--run_dir out --trace_sample 0.1` for
per-request trace waterfalls (tools/obs_report.py) and
`--slo_latency_ms 250` for the per-tenant SLO burn-rate engine with
auto-captured diagnostics on a fast-window CRITICAL.

No checkpoint / no data? `python serve.py` runs a fully synthetic demo
(fresh-init weights, synthetic support corpus, built-in demo queries).
"""
import sys

from induction_network_on_fewrel_tpu.serving.cli import serve_main

if __name__ == "__main__":
    sys.exit(serve_main())
