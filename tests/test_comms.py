"""Comms-diet tests (ISSUE 5): compact demb parity + the HLO regression gate.

The round-6 flagship comms compile found GSPMD replicating the
[L, M, word_dim] f32 embedding cotangent across dp — 26.1 MB/step/device,
77% of the wire payload (COMMS_r06). Round 7 restructured the demb
backward (ops/segsum.py reshape-free contraction;
parallel/sharding.make_compact_demb_lookup shard-local segment-sum + one
compact [U, D] all-reduce). Pinned here:

* PARITY: the compact path computes the same training trajectory as the
  dense path on the 8-virtual-device CPU mesh — losses tight, params at
  1e-5 (float associativity only: per-shard partial sums reduce in a
  different order) — for dp8, dp4×tp2, and dp8+ZeRO-1.
* REGRESSION GATE (tier-1, fast leg): the compiled production step has NO
  collective moving >= L·M·word_dim·4 bytes (the dense all-gather's
  size), every collective is attributed, and the compact demb all-reduce
  is present and named. A future sharding change cannot silently
  reintroduce the dense all-gather.
* RESUME: delta ring checkpoints (--ckpt_delta) are unaffected by the new
  demb representation — base+delta save/restore mid-run continues the
  sharded compact-demb trajectory bitwise.
* The ledger's attribution parser itself (tools/comms_ledger.py
  collective_rows/attributed_rows/check_attribution): labels, direction,
  aggregation, and the unattributed-collective warning that exists so a
  payload term can never sit anonymous for two rounds again.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import tools.comms_ledger as cl
from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    GloveTokenizer,
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.native.sampler import make_index_sampler
from induction_network_on_fewrel_tpu.parallel import make_mesh
from induction_network_on_fewrel_tpu.parallel.sharding import demb_impl_for
from induction_network_on_fewrel_tpu.train.lazy_embed import augment_token_table
from induction_network_on_fewrel_tpu.train.steps import init_state
from induction_network_on_fewrel_tpu.train.token_cache import (
    make_token_cached_train_step,
    tokenize_dataset,
)

L = 12
CFG = ExperimentConfig(
    encoder="bilstm", train_n=3, n=3, k=2, q=2, batch_size=8, max_length=L,
    vocab_size=302, compute_dtype="float32", lstm_hidden=16, att_dim=8,
    induction_dim=16, ntn_slices=8, token_cache=True, steps_per_call=1,
    embed_optimizer="lazy", lr=1e-3, weight_decay=0.0, ckpt_stage="off",
)
STEPS = 3


@pytest.fixture(scope="module")
def corpus():
    vocab = make_synthetic_glove(vocab_size=CFG.vocab_size - 2)
    # Token vocab << table vocab: the touched-row set stays far under the
    # half-table rebase threshold, so ring saves in the resume test take
    # the DELTA path (same bound test_ckpt_delta.py uses).
    ds = make_synthetic_fewrel(
        num_relations=6, instances_per_relation=CFG.k + CFG.q + 2,
        vocab_size=35,
    )
    tok = GloveTokenizer(vocab, max_length=CFG.max_length)
    table_np, sizes = tokenize_dataset(ds, tok)
    table_np, uids = augment_token_table(table_np)
    table_np = {**table_np, "uids": uids}
    idx = make_index_sampler(
        sizes, CFG.n, CFG.k, CFG.q, batch_size=CFG.batch_size, seed=0,
        backend="python",
    )
    batches = []
    for _ in range(STEPS + 2):
        si, qi, lab = idx.sample_fused(1)
        batches.append((si[0], qi[0], lab[0]))
    return vocab, table_np, batches


def _make_step(cfg, mesh, corpus, compact: bool):
    """(step, table_on_mesh, state0) for the token-cache lazy cached path —
    the production (flagship) configuration at test shapes. ``compact``
    toggles the demb path the way cfg.compact_demb does."""
    vocab, table_np, _ = corpus
    use = cfg if compact else cfg.replace(compact_demb="off")
    model = build_model(
        use, glove_init=vocab.vectors, demb_impl=demb_impl_for(use, mesh)
    )
    table = {
        k: jax.device_put(v, NamedSharding(mesh, P()))
        for k, v in table_np.items()
    }
    si, qi, _ = corpus[2][0]
    sup = {k: v[si] for k, v in table_np.items() if k != "uids"}
    qry = {k: v[qi] for k, v in table_np.items() if k != "uids"}
    state = init_state(model, use, sup, qry)
    step = make_token_cached_train_step(model, use, mesh, state)
    return step, table, state


def _run(step, table, state, batches):
    losses = []
    for si, qi, lab in batches:
        state, metrics = step(state, table, si, qi, lab)
        losses.append(float(jax.device_get(metrics["loss"])))
    return state, losses


def _assert_parity(mesh, corpus, cfg=CFG):
    _, _, batches = corpus
    step_c, table_c, state_c = _make_step(cfg, mesh, corpus, compact=True)
    step_d, table_d, state_d = _make_step(cfg, mesh, corpus, compact=False)
    sc, lc = _run(step_c, table_c, state_c, batches[:STEPS])
    sd, ld = _run(step_d, table_d, state_d, batches[:STEPS])
    # Forward values are identical (same gather); the loss differs only
    # through the previous steps' grads, whose per-shard partial sums
    # reduce in a different order — same band as the dense GSPMD paths.
    np.testing.assert_allclose(lc, ld, rtol=0, atol=1e-5)
    for (pa, va), (_, vb) in zip(
        jax.tree_util.tree_flatten_with_path(jax.device_get(sc.params))[0],
        jax.tree_util.tree_flatten_with_path(jax.device_get(sd.params))[0],
    ):
        np.testing.assert_allclose(
            np.asarray(va), np.asarray(vb), atol=1e-5, rtol=1e-5,
            err_msg=f"param {jax.tree_util.keystr(pa)} diverged",
        )


def test_compact_demb_parity_dp8(corpus):
    _assert_parity(make_mesh(dp=8), corpus)


@pytest.mark.slow
def test_compact_demb_parity_dp4_tp2(corpus):
    _assert_parity(make_mesh(dp=4, tp=2), corpus)


@pytest.mark.slow
def test_compact_demb_parity_zero1(corpus):
    _assert_parity(make_mesh(dp=8), corpus, cfg=CFG.replace(zero_opt=True))


def test_hlo_gate_no_dense_embedding_collective(corpus):
    """The tier-1 regression gate (ISSUE 5 satellite): compile the
    production cached-lazy step on the dp8 mesh and assert the compiled
    HLO (a) moves no single collective >= L·M·word_dim·4 bytes — the
    dense [L, M, word_dim] all-gather's size at THIS shape, the exact
    payload that hid at tiny shapes for two rounds — (b) attributes every
    collective, and (c) carries the named compact-demb all-reduce."""
    mesh = make_mesh(dp=8)
    _, _, batches = corpus
    step, table, state = _make_step(CFG, mesh, corpus, compact=True)
    si, qi, lab = batches[0]
    txt = step.lower(state, table, si, qi, lab).compile().as_text()

    rows = cl.collective_rows(txt)
    assert rows, "no collectives found — the dp8 compile should have some"
    gate = cl.dense_allgather_bytes(CFG)
    biggest = max(r["bytes"] for r in rows)
    assert biggest < gate, (
        f"a collective moves {biggest} B >= the dense embedding "
        f"all-gather size {gate} B — the replicated [L, M, word_dim] "
        "gather is back (see parallel/sharding.make_compact_demb_lookup)"
    )
    anon = [r for r in rows if r["source"] is None]
    assert not anon, f"unattributed collectives on the production path: {anon}"
    assert any(
        "demb/compact_allreduce" in (r["source"] or "") for r in rows
    ), "the compact demb all-reduce is missing from the compiled step"
    # And the step actually runs on the mesh.
    state2, metrics = step(state, table, si, qi, lab)
    assert np.isfinite(float(jax.device_get(metrics["loss"])))


@pytest.mark.slow
def test_compact_demb_scatter_branch_parity_and_gate(corpus, monkeypatch):
    """Above the matmul-grad crossover the compact backward switches to a
    shard-local SCATTER-ADD (real corpora run 40-60k rows — gating the
    whole compact path behind MATMUL_GRAD_MAX_ROWS would deactivate the
    comms fix exactly where it matters; round-7 review finding). Force
    the crossover down so the branch runs at test shapes: parity vs the
    dense twin AND the no-dense-collective gate must hold."""
    import induction_network_on_fewrel_tpu.ops.segsum as segsum

    monkeypatch.setattr(segsum, "MATMUL_GRAD_MAX_ROWS", 8)
    mesh = make_mesh(dp=8)
    _, _, batches = corpus
    step_c, table_c, state_c = _make_step(CFG, mesh, corpus, compact=True)
    si, qi, lab = batches[0]
    txt = step_c.lower(state_c, table_c, si, qi, lab).compile().as_text()
    rows = cl.collective_rows(txt)
    assert max(r["bytes"] for r in rows) < cl.dense_allgather_bytes(CFG)
    assert any(
        "demb/compact_allreduce" in (r["source"] or "") for r in rows
    )

    step_d, table_d, state_d = _make_step(CFG, mesh, corpus, compact=False)
    sc, lc = _run(step_c, table_c, state_c, batches[:2])
    sd, ld = _run(step_d, table_d, state_d, batches[:2])
    np.testing.assert_allclose(lc, ld, rtol=0, atol=1e-5)


def test_large_dense_shared_table_keeps_native_path(corpus, monkeypatch):
    """A LARGE dense SHARED word table must NOT take the compact path:
    psumming its full [vocab, D] gradient (~80 MB at 400k rows) would
    out-cost the gather it replaces (round-7 review finding, pass 3).
    The crossover is forced down so the 302-row shared table counts as
    'large'; the spy proves demb_impl is never invoked during tracing —
    while a lazy run at the same patched crossover DOES take it (the
    lazy rows leaf is compact at any size)."""
    import induction_network_on_fewrel_tpu.models.embedding as emb_mod

    monkeypatch.setattr(emb_mod, "MATMUL_GRAD_MAX_ROWS", 8)
    mesh = make_mesh(dp=8)
    vocab, table_np, batches = corpus
    cfg = CFG.replace(embed_optimizer="shared")
    calls = []
    real = demb_impl_for(cfg, mesh)

    def spy(table, ids, batch_dim):
        calls.append(tuple(table.shape))
        return real(table, ids, batch_dim)

    model = build_model(cfg, glove_init=vocab.vectors, demb_impl=spy)
    tab_np = {k: v for k, v in table_np.items() if k not in ("uids", "winv")}
    table = {
        k: jax.device_put(v, NamedSharding(mesh, P()))
        for k, v in tab_np.items()
    }
    si, qi, lab = batches[0]
    sup = {k: v[si] for k, v in tab_np.items()}
    qry = {k: v[qi] for k, v in tab_np.items()}
    state = init_state(model, cfg, sup, qry)
    step = make_token_cached_train_step(model, cfg, mesh, state)
    step.lower(state, table, si, qi, lab)  # traces fwd+bwd
    assert calls == [], (
        f"compact demb engaged on a large dense shared table: {calls}"
    )

    # Control: the lazy twin at the same patched crossover takes the spy
    # (rows leaf is compact regardless of the crossover).
    calls_lazy = []

    def spy_lazy(table, ids, batch_dim):
        calls_lazy.append(tuple(table.shape))
        return real(table, ids, batch_dim)

    model_l = build_model(CFG, glove_init=vocab.vectors, demb_impl=spy_lazy)
    table_l = {
        k: jax.device_put(v, NamedSharding(mesh, P()))
        for k, v in table_np.items()
    }
    sup_l = {k: v[si] for k, v in table_np.items() if k != "uids"}
    qry_l = {k: v[qi] for k, v in table_np.items() if k != "uids"}
    state_l = init_state(model_l, CFG, sup_l, qry_l)
    step_l = make_token_cached_train_step(model_l, CFG, mesh, state_l)
    step_l.lower(state_l, table_l, si, qi, lab)
    assert calls_lazy, "lazy rows leaf should take the compact path"


def test_delta_ring_resume_with_compact_demb(corpus, tmp_path):
    """Delta ring checkpoints are unaffected by the compact demb
    representation: base -> delta -> restore into a fresh manager ->
    continue == the uninterrupted sharded run, bitwise (the demb change
    touches only the gradient computation, never the state tree)."""
    from induction_network_on_fewrel_tpu.parallel.sharding import shard_state
    from induction_network_on_fewrel_tpu.train.checkpoint import (
        CheckpointManager,
    )

    mesh = make_mesh(dp=8)
    _, _, batches = corpus
    step, table, state = _make_step(CFG, mesh, corpus, compact=True)
    template = jax.device_get(state)

    mgr = CheckpointManager(tmp_path, CFG)
    state, _ = step(state, table, *batches[0])
    assert mgr.save_latest(1, state, force=True)["mode"] == "base"
    mgr.wait()
    state, _ = step(state, table, *batches[1])
    info = mgr.save_latest(2, state, force=True)
    assert info["mode"] == "delta"
    mgr.close()

    mgr2 = CheckpointManager(tmp_path, CFG)
    restored, step_no = mgr2.restore_latest(template)
    mgr2.close()
    assert step_no == 2
    restored = shard_state(restored, mesh)

    cont_live, m_live = step(state, table, *batches[2])
    cont_rest, m_rest = step(restored, table, *batches[2])
    assert float(jax.device_get(m_live["loss"])) == float(
        jax.device_get(m_rest["loss"])
    )
    for (pa, va), (_, vb) in zip(
        jax.tree_util.tree_flatten_with_path(jax.device_get(cont_live))[0],
        jax.tree_util.tree_flatten_with_path(jax.device_get(cont_rest))[0],
    ):
        np.testing.assert_array_equal(
            np.asarray(va), np.asarray(vb),
            err_msg=f"leaf {jax.tree_util.keystr(pa)} diverged after resume",
        )


# --- attribution parser units (no compiles) --------------------------------

_HLO_SNIPPET = """\
HloModule jit_step
ENTRY %main {
  %ag = f32[16,96,50]{2,0,1} all-gather(f32[16,12,50]{2,0,1} %x), channel_id=16, dimensions={1}, metadata={op_name="jit(step)/jit(main)/while/body/transpose(jvp(Net))/encoder/embedding/reshape" source_file="a.py"}
  %ar = f32[237,50]{1,0} all-reduce(f32[237,50]{1,0} %y), channel_id=1, to_apply=%add, metadata={op_name="jit(step)/jit(main)/transpose(jvp(Net))/demb/compact_allreduce/psum" source_file="b.py"}
  %anon = f32[64]{0} all-reduce(f32[64]{0} %z), channel_id=2, to_apply=%add
  %ars = f32[8]{0} all-reduce-start(f32[8]{0} %w), channel_id=3, to_apply=%add, metadata={op_name="jit(step)/loss/reduce_sum"}
  %ard = f32[8]{0} all-reduce-done(f32[8]{0} %ars)
}
"""


def test_collective_rows_attribution():
    rows = cl.collective_rows(_HLO_SNIPPET)
    by_op = {(r["op"], r["bytes"]): r for r in rows}
    # Direction + meaningful tail; scaffolding (while/body, jit, jvp,
    # transpose) stripped.
    ag = by_op[("all-gather", 16 * 96 * 50 * 4)]
    assert ag["source"] == "bwd:encoder/embedding/reshape"
    ar = by_op[("all-reduce", 237 * 50 * 4)]
    assert ar["source"] == "bwd:demb/compact_allreduce/psum"
    # Async pair: -start carries the shape and is counted once; -done
    # is skipped.
    assert ("all-reduce", 32) in by_op
    assert by_op[("all-reduce", 32)]["source"] == "fwd:loss/reduce_sum"
    # Anonymous op -> source None (NOT dropped: bytes still counted).
    assert by_op[("all-reduce", 256)]["source"] is None
    assert len(rows) == 4


def test_attributed_rows_aggregation_and_strict_warning(capsys):
    rows = cl.collective_rows(_HLO_SNIPPET)
    agg = cl.attributed_rows(rows)
    assert agg[0]["bytes"] >= agg[-1]["bytes"]  # largest first
    anon_bytes = cl.check_attribution("unit", rows)
    assert anon_bytes == 256
    err = capsys.readouterr().err
    assert "unattributed" in err and "306 KiB" in err
    # A fully-attributed leg stays silent.
    clean = [r for r in rows if r["source"] is not None]
    assert cl.check_attribution("unit2", clean) == 0
    assert capsys.readouterr().err == ""


def test_collective_bytes_matches_rows():
    per_op = cl.collective_bytes(_HLO_SNIPPET)
    assert per_op["all-gather"]["bytes"] == 16 * 96 * 50 * 4
    assert per_op["all-reduce"]["count"] == 3


_HLO_PROVENANCE = """\
HloModule jit_step
ENTRY %main {
  %p0 = f32[64,8]{1,0} parameter(0)
  %named = f32[64,8]{1,0} add(f32[64,8]{1,0} %p0, f32[64,8]{1,0} %p0), metadata={op_name="jit(step)/jit(main)/opt/zero1_update/add" source_file="s.py"}
  %fused = f32[64,8]{1,0} fusion(f32[64,8]{1,0} %named), kind=kLoop, calls=%fc.1
  %reshard = f32[64,16]{1,0} all-gather(f32[64,8]{1,0} %fused), channel_id=9, dimensions={1}
  %orphan.1 = f32[4]{0} parameter(1)
  %orphan.2 = f32[8]{0} all-gather(f32[4]{0} %orphan.1), channel_id=10, dimensions={0}
}
"""


def test_provenance_resolves_gspmd_reshards():
    """A metadata-less collective (GSPMD-inserted reshard) attributes via
    its operand chain to the nearest op_name — labeled reshard:<producer>
    and marked derived — so the four round-8 debt legs (zero1/dp4_tp2/
    sp/ep) name every row and full-suite --strict can gate tier-1. A
    collective whose ancestors carry NO metadata stays None (still a
    strict failure): provenance is a resolution mechanism, not a blanket
    pass."""
    rows = cl.collective_rows(_HLO_PROVENANCE)
    by_bytes = {r["bytes"]: r for r in rows}
    resolved = by_bytes[64 * 16 * 4]
    assert resolved["source"] == "reshard:fwd:opt/zero1_update/add"
    assert resolved["derived"] is True
    # Operand chain dead-ends at a parameter -> genuinely unattributable.
    assert by_bytes[8 * 4]["source"] is None
    assert cl.check_attribution("prov", rows) == 8 * 4


def test_provenance_never_rewrites_direct_attribution():
    """Ops with their own op_name keep it verbatim — the derived label
    only fills gaps (the _HLO_SNIPPET expectations above already pin
    this; here the explicit invariant)."""
    rows = cl.collective_rows(_HLO_SNIPPET)
    for r in rows:
        if r["source"] is not None:
            assert not r["source"].startswith("reshard:") or r.get("derived")


# --- overlap parser units (ISSUE 20, no compiles) --------------------------

_HLO_OVERLAP = """\
HloModule jit_step
ENTRY %main (p0: f32[1000,100]) -> f32[1000,100] {
  %p0 = f32[1000,100]{1,0} parameter(0)
  %z = f32[] constant(0)
  %big = f32[1000,100]{1,0} add(f32[1000,100]{1,0} %p0, f32[1000,100]{1,0} %p0), metadata={op_name="jit(step)/jit(main)/indep/add"}
  %ar = f32[1000,100]{1,0} all-reduce(f32[1000,100]{1,0} %p0), channel_id=1, replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%add, metadata={op_name="jit(step)/jit(main)/grad/bucket_0/reduce_sum"}
  ROOT %dep = f32[1000,100]{1,0} multiply(f32[1000,100]{1,0} %ar, f32[1000,100]{1,0} %big), metadata={op_name="jit(step)/jit(main)/opt/update/mul"}
}
"""

_HLO_OVERLAP_ASYNC = """\
HloModule jit_step
ENTRY %main (p0: f32[64], w0: f32[512,512]) -> f32[64] {
  %p0 = f32[64]{0} parameter(0)
  %w0 = f32[512,512]{1,0} parameter(1)
  %mm = f32[512,512]{1,0} dot(f32[512,512]{1,0} %w0, f32[512,512]{1,0} %w0), metadata={op_name="jit(step)/jit(main)/encoder/matmul"}
  %ars = f32[64]{0} all-reduce-start(f32[64]{0} %p0), channel_id=3, replica_groups=[4,2]<=[8], to_apply=%add, metadata={op_name="jit(step)/jit(main)/grad/bucket_1/reduce_sum"}
  %ard = f32[64]{0} all-reduce-done(f32[64]{0} %ars)
  ROOT %use = f32[64]{0} add(f32[64]{0} %ard, f32[64]{0} %p0), metadata={op_name="jit(step)/jit(main)/opt/update/add"}
}
"""

_HLO_OVERLAP_TWO = """\
HloModule jit_step
ENTRY %main (p0: f32[1000,1000], p1: f32[100,100], p2: f32[10]) -> (f32[1000,1000], f32[10]) {
  %p0 = f32[1000,1000]{1,0} parameter(0)
  %p1 = f32[100,100]{1,0} parameter(1)
  %p2 = f32[10]{0} parameter(2)
  %ind = f32[100,100]{1,0} add(f32[100,100]{1,0} %p1, f32[100,100]{1,0} %p1), metadata={op_name="jit(step)/jit(main)/indep/add"}
  %ar_big = f32[1000,1000]{1,0} all-reduce(f32[1000,1000]{1,0} %p0), channel_id=1, replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%add, metadata={op_name="jit(step)/jit(main)/grad/bucket_0/reduce_sum"}
  %ar_small = f32[10]{0} all-reduce(f32[10]{0} %p2), channel_id=2, replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%add, metadata={op_name="jit(step)/jit(main)/loss/reduce_sum"}
  ROOT %t = (f32[1000,1000]{1,0}, f32[10]{0}) tuple(f32[1000,1000]{1,0} %ar_big, f32[10]{0} %ar_small)
}
"""


def test_overlap_rows_dataflow_windows_and_cost_model():
    """The round-10 overlap walker prices each collective's hideability
    from its DATAFLOW windows, not print position: %big prints BEFORE the
    all-reduce yet is independent work (neither ancestor nor descendant —
    the CPU scheduler prints free-floating psums right before their
    consumers, so a later-printed-only window under-measures exactly the
    restructure this plane ships). Wire bytes price at the op's OWN
    replica_groups via the ring factor; the frac is overlappable HBM time
    over wire time at the v5e HBM:ICI ratio."""
    from induction_network_on_fewrel_tpu.utils.roofline import (
        NOMINAL_V5E_BW,
        NOMINAL_V5E_ICI,
    )

    [row] = cl.overlap_rows(_HLO_OVERLAP, participants=8)
    assert row["kind"] == "all-reduce"
    assert row["source"] == "fwd:grad/bucket_0/reduce_sum"
    assert row["bytes"] == 1000 * 100 * 4
    assert row["group_size"] == 8
    assert row["wire_bytes"] == int(2 * 7 / 8 * 400000)  # AR ring factor
    # %dep is the only dependent; %z (free, 0 B) and %big are independent.
    assert row["dependent_ops_after"] == 1
    assert row["independent_ops_after"] == 2
    assert row["dependent_bytes_after"] == 400000
    assert row["independent_bytes_after"] == 400000  # %z contributes 0 B
    expect = (400000 / NOMINAL_V5E_BW) / (700000 / NOMINAL_V5E_ICI)
    assert row["overlap_frac"] == pytest.approx(expect, abs=1e-4)
    s = cl.overlap_summary(_HLO_OVERLAP, participants=8)
    assert s["unoverlapped_frac"] == pytest.approx(1 - expect, abs=1e-4)


def test_overlap_async_spelling_and_iota_groups():
    """The async -start/-done pair is ONE collective: -start carries the
    shape, groups, and metadata; -done is a dependent, never a second
    row. Iota replica_groups=[G,S]<=[N] size at S (the tp=2 reshard on a
    mixed mesh must price at d=2, not the mesh's 8)."""
    [row] = cl.overlap_rows(_HLO_OVERLAP_ASYNC, participants=8)
    assert row["async"] is True
    assert row["kind"] == "all-reduce"
    assert row["group_size"] == 2                 # iota [4,2]<=[8]
    assert row["bytes"] == 64 * 4
    assert row["wire_bytes"] == 256               # 2*(1/2)*256
    assert row["dependent_ops_after"] == 2        # -done + its consumer
    assert row["independent_bytes_after"] == 512 * 512 * 4  # the dot
    assert row["overlap_frac"] == 1.0             # 1 MB hides 256 B easily
    s = cl.overlap_summary(_HLO_OVERLAP_ASYNC, participants=8)
    assert s["async_collectives"] == 1
    assert len(s["collectives"]) == 1


def test_overlap_summary_is_wire_weighted():
    """The leg headline weights per-collective fracs by WIRE bytes: a
    fully-hidden 40 B metric all-reduce cannot rescue a naked 7 MB
    gradient all-reduce (an unweighted mean would report ~0.5)."""
    rows = cl.overlap_rows(_HLO_OVERLAP_TWO, participants=8)
    assert len(rows) == 2
    by_src = {r["source"]: r for r in rows}
    big = by_src["fwd:grad/bucket_0/reduce_sum"]
    small = by_src["fwd:loss/reduce_sum"]
    assert small["overlap_frac"] == 1.0
    assert big["overlap_frac"] < 0.01   # only 40 KB + 40 B independent
    s = cl.overlap_summary(_HLO_OVERLAP_TWO, participants=8)
    wire = sum(r["wire_bytes"] for r in rows)
    weighted = sum(r["wire_bytes"] * r["overlap_frac"] for r in rows) / wire
    assert s["overlap_frac"] == pytest.approx(weighted, abs=1e-3)
    assert s["overlap_frac"] < 0.01     # bytes weighting held the line
    assert s["total_wire_bytes"] == wire


# --- round-10 artifact + compiled-leg gates (ISSUE 20) ---------------------


def test_comms_r10_committed_overlap_gates():
    """The committed round-10 ledger artifact is the regression bar:
    flagship un-overlapped <= 8% (the acceptance line, vs the ~22%
    hand-derived round-7 number), zero unattributed bytes, all four
    bucket psums present and named — and each bucketed arm no worse than
    its monolithic control on BOTH the overlap headline and the payload
    diet (the GSPMD resharding permutes the shard_map restructure
    deletes)."""
    import json
    from pathlib import Path

    root = Path(cl.__file__).resolve().parent.parent
    data = json.loads((root / "COMMS_r10.json").read_text())
    flag = data["dp8_tokencache_lazy_flagship"]
    ov = flag["overlap"]
    assert ov["unoverlapped_frac"] <= 0.08
    assert flag["unattributed_bytes"] == 0
    srcs = {r["source"] for r in ov["collectives"]}
    assert {f"fwd:grad/bucket_{k}/reduce_sum" for k in range(4)} <= srcs
    for bucketed, mono in (
        ("dp8_bucketed", "dp8"),
        ("dp8_lazy_bucketed", "dp8_tokencache_lazy"),
    ):
        b, m = data[bucketed], data[mono]
        assert (b["overlap"]["unoverlapped_frac"]
                <= m["overlap"]["unoverlapped_frac"] + 1e-9), (
            f"{bucketed} overlaps worse than {mono}"
        )
        assert (b["total_bytes_per_step_per_device"]
                <= m["total_bytes_per_step_per_device"]), (
            f"{bucketed} moves more payload than {mono}"
        )


def test_bucketed_grad_parity_and_overlap_gate_dp8(corpus):
    """Tier-1 gate for the bucketed-collective restructure: compile the
    production cached-lazy step with --grad_bucketing on at the dp8 mesh
    and assert (a) every gradient psum lands in a named reverse-
    topological bucket, fully attributed; (b) the frozen dense word
    table stays SILENT — no collective at or above the [M, D] table size
    (stacking its zero cotangent was an 80 MB/step all-reduce when first
    measured, the round-6 regression shape); (c) the measured whole-step
    overlap keeps the flagship discipline at test shapes (1.5% measured,
    3x headroom); and (d) the training trajectory matches the monolithic
    compact path at 1e-5 — identical math, restructured collectives."""
    mesh = make_mesh(dp=8)
    _, _, batches = corpus
    cfg_b = CFG.replace(grad_bucketing="on")
    step_b, table_b, state_b = _make_step(cfg_b, mesh, corpus, compact=True)
    si, qi, lab = batches[0]
    txt = step_b.lower(state_b, table_b, si, qi, lab).compile().as_text()

    rows = cl.collective_rows(txt)
    anon = [r for r in rows if r["source"] is None]
    assert not anon, f"unattributed collectives on the bucketed path: {anon}"
    srcs = {r["source"] for r in rows}
    assert {f"fwd:grad/bucket_{k}/reduce_sum" for k in range(4)} <= srcs
    assert max(r["bytes"] for r in rows) < cl.dense_allgather_bytes(CFG)
    table_bytes = CFG.vocab_size * 50 * 4  # the dense [M, D] word table
    big = [r for r in rows if r["bytes"] >= table_bytes]
    assert not big, (
        f"full-table-sized collectives on the bucketed path: {big} — the "
        "frozen dense-table leaf is being stacked/psummed again"
    )
    ov = cl.overlap_summary(txt, participants=8)
    assert ov["unoverlapped_frac"] <= 0.05, (
        f"bucketed dp8 leg un-overlapped {ov['unoverlapped_frac']:.1%} "
        "— the scheduler lost its independent windows"
    )

    sb, lb = _run(step_b, table_b, state_b, batches[:STEPS])
    step_m, table_m, state_m = _make_step(CFG, mesh, corpus, compact=True)
    sm, lm = _run(step_m, table_m, state_m, batches[:STEPS])
    np.testing.assert_allclose(lb, lm, rtol=0, atol=1e-5)
    for (pa, va), (_, vb) in zip(
        jax.tree_util.tree_flatten_with_path(jax.device_get(sb.params))[0],
        jax.tree_util.tree_flatten_with_path(jax.device_get(sm.params))[0],
    ):
        np.testing.assert_allclose(
            np.asarray(va), np.asarray(vb), atol=1e-5, rtol=1e-5,
            err_msg=f"param {jax.tree_util.keystr(pa)} diverged (bucketed)",
        )


def test_comms_ledger_full_suite_strict(monkeypatch, capsys):
    """ROADMAP item 5 closed: the dryrun ledger's attribution-debt legs
    run --strict and exit 0 — zero unattributed collective bytes,
    including the four formerly metadata-less GSPMD reshard legs (zero1
    49 KB, dp4_tp2 12.7 KB, sp 6.1 KB, ep 1.6 KB) now resolved by
    dataflow provenance, plus gpipe (not compiled anywhere else in
    tier-1). The dp8 / bucketed / lazy legs are strict-gated by their
    own compiled tier-1 tests above and the flagship by its twin in
    tests/test_roofline.py — together tier-1 still covers every leg
    family while this sweep stays inside the round-21 wall-clock budget
    (the full 9-leg set runs in every committed COMMS_r*.json)."""
    import sys as _sys

    monkeypatch.setattr(
        _sys, "argv", [
            "comms_ledger.py", "--skip-flagship", "--strict", "--legs",
            "dp8_zero1,dp4_tp2,dp2_sp4_ring,dp2_ep4_moe,dp2_pp4_gpipe",
        ]
    )
    rc = cl.main()
    out = capsys.readouterr()
    assert rc == 0, f"full-suite strict ledger failed:\n{out.err}\n{out.out}"
    assert "UNATTRIBUTED" not in out.out
    assert "reshard:" in out.out or "zero1" in out.out
