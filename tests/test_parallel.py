"""Multi-device tests on 8 virtual CPU devices (SURVEY.md §4.5).

Checks that the mesh-sharded steps (GSPMD NamedSharding and explicit
shard_map+pmean) produce the same training trajectory as the single-device
jitted step: same metrics, same params after k steps.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    GloveTokenizer,
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.models.build import batch_to_model_inputs
from induction_network_on_fewrel_tpu.parallel import make_mesh
from induction_network_on_fewrel_tpu.parallel.sharding import (
    make_shard_map_train_step,
    make_sharded_eval_step,
    make_sharded_train_step,
    state_shardings,
)
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler
from induction_network_on_fewrel_tpu.train.steps import init_state, make_train_step

L = 16
CFG = ExperimentConfig(
    encoder="cnn", n=3, k=2, q=2, batch_size=8, max_length=L, vocab_size=302,
    compute_dtype="float32", lr=1e-3, weight_decay=0.0,
)


@pytest.fixture(scope="module")
def setup():
    vocab = make_synthetic_glove(vocab_size=300)
    ds = make_synthetic_fewrel(num_relations=6, instances_per_relation=12, vocab_size=300)
    tok = GloveTokenizer(vocab, max_length=L)
    sampler = EpisodeSampler(ds, tok, CFG.n, CFG.k, CFG.q, CFG.batch_size, seed=0)
    model = build_model(CFG, glove_init=vocab.vectors)
    batches = [batch_to_model_inputs(sampler.sample_batch()) for _ in range(3)]
    state = init_state(model, CFG, batches[0][0], batches[0][1])
    return model, batches, state


def test_eight_devices_available():
    assert len(jax.devices()) == 8


def _copy_state(state):
    return jax.tree.map(lambda x: jnp.array(x, copy=True), state)


def _run_steps(step_fn, state, batches):
    for sup, qry, label in batches:
        state, metrics = step_fn(state, sup, qry, label)
    return state, jax.device_get(metrics)


def _params_allclose(a, b, atol):
    flat_a, flat_b = jax.tree.leaves(a.params), jax.tree.leaves(b.params)
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=atol, rtol=1e-5)


@pytest.mark.slow
def test_gspmd_matches_single_device(setup):
    model, batches, state0 = setup
    single = make_train_step(model, CFG)
    s1, m1 = _run_steps(single, _copy_state(state0), batches)

    mesh = make_mesh(dp=4, tp=2)
    sharded = make_sharded_train_step(model, CFG, mesh, state0)
    s2, m2 = _run_steps(sharded, _copy_state(state0), batches)

    assert abs(m1["loss"] - m2["loss"]) < 1e-5
    _params_allclose(s1, s2, atol=1e-5)
    # params actually carry the intended shardings, matching the rules
    ntn = s2.params["params"]["relation"]["tensor_slices"]
    assert "tp" in str(ntn.sharding.spec)
    expect = state_shardings(s2, mesh).params["params"]["relation"]["tensor_slices"]
    assert ntn.sharding.spec == expect.spec


def test_shard_map_matches_single_device(setup):
    model, batches, state0 = setup
    single = make_train_step(model, CFG)
    s1, m1 = _run_steps(single, _copy_state(state0), batches)

    mesh = make_mesh(dp=8, tp=1)
    smstep = make_shard_map_train_step(model, CFG, mesh)
    s2, m2 = _run_steps(smstep, _copy_state(state0), batches)

    assert abs(m1["loss"] - m2["loss"]) < 1e-5
    _params_allclose(s1, s2, atol=1e-5)


def test_gspmd_bucketed_matches_monolithic(setup):
    """--grad_bucketing on (forced; CPU auto resolves off) reroutes the
    dense GSPMD step's gradient psums through the named reverse-
    topological bucket reductions of parallel/grad_buckets.py — same
    math, restructured collectives (ISSUE 20). The training trajectory
    must match the monolithic partitioner-scheduled step at the usual
    1e-5 float-associativity band on the pure-dp mesh."""
    model, batches, state0 = setup
    mesh = make_mesh(dp=8, tp=1)
    mono = make_sharded_train_step(model, CFG, mesh, state0)
    s1, m1 = _run_steps(mono, _copy_state(state0), batches)
    bucketed = make_sharded_train_step(
        model, CFG.replace(grad_bucketing="on"), mesh, state0
    )
    s2, m2 = _run_steps(bucketed, _copy_state(state0), batches)
    assert abs(m1["loss"] - m2["loss"]) < 1e-5
    _params_allclose(s1, s2, atol=1e-5)


@pytest.mark.slow
def test_gspmd_zero1_bucketed_gather_matches(setup):
    """ZeRO-1 + bucketed grads exercises the per-bucket re-gather branch
    in make_update_body (opt/zero1_update/gather/bucket_k): the dp-
    sharded param deltas come back through per-bucket sharding
    constraints instead of one fused reshard. Trajectory parity vs the
    monolithic zero1 step; every collective stays attributed."""
    import tools.comms_ledger as cl

    model, batches, state0 = setup
    mesh = make_mesh(dp=8, tp=1)
    cfg_z = CFG.replace(zero_opt=True)
    mono = make_sharded_train_step(model, cfg_z, mesh, state0)
    s1, m1 = _run_steps(mono, _copy_state(state0), batches)
    cfg_zb = cfg_z.replace(grad_bucketing="on")
    bucketed = make_sharded_train_step(model, cfg_zb, mesh, state0)
    txt = bucketed.lower(_copy_state(state0), *batches[0]).compile().as_text()
    rows = cl.collective_rows(txt)
    assert rows and not [r for r in rows if r["source"] is None]
    s2, m2 = _run_steps(bucketed, _copy_state(state0), batches)
    assert abs(m1["loss"] - m2["loss"]) < 1e-5
    _params_allclose(s1, s2, atol=1e-5)


@pytest.mark.xfail(
    strict=False,
    reason="pre-existing GSPMD-numerics drift on jax 0.4.37 CPU (seed "
    "failure, CHANGES.md PR 1): the dp=2,tp=2 partitioned eval reduces in "
    "a different order than single-device XLA and exceeds the 1e-5 loss "
    "tolerance; passes on TPU. strict=False so a fixed jax turns it green.",
)
def test_sharded_eval_matches(setup):
    model, batches, state0 = setup
    mesh = make_mesh(dp=2, tp=2)
    ev = make_sharded_eval_step(model, CFG, mesh, state0)
    sup, qry, label = batches[0]
    out = jax.device_get(ev(state0.params, sup, qry, label))

    from induction_network_on_fewrel_tpu.train.steps import make_eval_step

    ref = jax.device_get(make_eval_step(model, CFG)(state0.params, sup, qry, label))
    assert abs(out["loss"] - ref["loss"]) < 1e-5
    assert abs(out["accuracy"] - ref["accuracy"]) < 1e-6


def test_mesh_validation():
    with pytest.raises(ValueError):
        make_mesh(dp=16, tp=1)
    m = make_mesh(tp=2)  # dp inferred = 4
    assert dict(m.shape) == {"dp": 4, "pp": 1, "ep": 1, "tp": 2, "sp": 1}
    m = make_mesh(tp=2, sp=2)  # dp inferred = 2
    assert dict(m.shape) == {"dp": 2, "pp": 1, "ep": 1, "tp": 2, "sp": 2}
    m = make_mesh(pp=2, ep=2)  # dp inferred = 2
    assert dict(m.shape) == {"dp": 2, "pp": 2, "ep": 2, "tp": 1, "sp": 1}


@pytest.mark.xfail(
    strict=False,
    reason="pre-existing GSPMD-numerics drift on jax 0.4.37 CPU (seed "
    "failure, CHANGES.md PR 1): the dp=4,tp=2 fused-scan trajectory "
    "diverges from sequential beyond atol after reduction reordering; "
    "passes on TPU. strict=False so a fixed jax turns it green.",
)
def test_sharded_fused_step_matches_sequential(setup):
    """GSPMD fused S-step scan == S sequential GSPMD steps == single-device
    sequential steps: dispatch amortization must not change the math."""
    from induction_network_on_fewrel_tpu.parallel.sharding import (
        make_sharded_multi_train_step,
        shard_state,
    )

    model, batches, state0 = setup
    mesh = make_mesh(dp=4, tp=2)

    seq_step = make_sharded_train_step(model, CFG, mesh, state0)
    state_a = shard_state(_copy_state(state0), mesh)
    state_a, _ = _run_steps(seq_step, state_a, batches)

    multi = make_sharded_multi_train_step(model, CFG, mesh, state0)
    state_b = shard_state(_copy_state(state0), mesh)
    sup_s, qry_s, lab_s = jax.tree.map(lambda *xs: np.stack(xs), *batches)
    state_b, metrics = multi(state_b, sup_s, qry_s, lab_s)

    assert np.asarray(metrics["loss"]).shape == (len(batches),)
    assert int(state_b.step) == int(state_a.step) == len(batches)
    _params_allclose(state_a, state_b, atol=1e-6)

    single = make_train_step(model, CFG)
    state_c, _ = _run_steps(single, _copy_state(state0), batches)
    _params_allclose(state_b, state_c, atol=1e-5)


@pytest.mark.slow
def test_pallas_interpret_under_mesh():
    """The PRODUCTION kernel composed with the PRODUCTION distribution
    (round-5 VERDICT item 2): the Pallas BiLSTM — via the interpreter, the
    same kernel code that compiles on TPU — runs under the 8-device dp
    GSPMD mesh and produces the SAME trajectory as the scan backend.
    Checkpoints are backend-interchangeable, so identical params must give
    identical losses/params whichever backend the mesh step compiles."""
    cfg = ExperimentConfig(
        encoder="bilstm", n=3, k=2, q=2, batch_size=8, max_length=L,
        vocab_size=302, compute_dtype="float32", lstm_hidden=16, att_dim=8,
        induction_dim=16, ntn_slices=8, lr=1e-3, weight_decay=0.0,
        lstm_backend="interpret", attn_backend="interpret", dp=8,
    )
    vocab = make_synthetic_glove(vocab_size=300)
    ds = make_synthetic_fewrel(
        num_relations=6, instances_per_relation=12, vocab_size=300
    )
    tok = GloveTokenizer(vocab, max_length=L)
    sampler = EpisodeSampler(
        ds, tok, cfg.n, cfg.k, cfg.q, cfg.batch_size, seed=0
    )
    model = build_model(cfg, glove_init=vocab.vectors)
    batches = [batch_to_model_inputs(sampler.sample_batch()) for _ in range(2)]
    state0 = init_state(model, cfg, batches[0][0], batches[0][1])
    mesh = make_mesh(dp=8)

    step = make_sharded_train_step(model, cfg, mesh, state0)
    s_pl, m_pl = _run_steps(step, _copy_state(state0), batches)

    cfg_s = cfg.replace(lstm_backend="scan", attn_backend="xla")
    model_s = build_model(cfg_s, glove_init=vocab.vectors)
    step_s = make_sharded_train_step(model_s, cfg_s, mesh, state0)
    s_sc, m_sc = _run_steps(step_s, _copy_state(state0), batches)

    assert abs(float(m_pl["loss"]) - float(m_sc["loss"])) < 1e-5
    _params_allclose(s_pl, s_sc, atol=1e-5)


@pytest.mark.slow
def test_sharded_fused_eval_nota_matches_single_device():
    """Mesh-sharded fused eval with NOTA (round-5 VERDICT item 7): the
    production eval path — token-cache fused lax.map eval, episode axis
    over dp, NOTA confusion fractions aggregated across devices — equals
    the single-device fused eval metric-for-metric (incl. nota_tp/pred/
    true, whose shared denominator makes aggregation exact)."""
    from induction_network_on_fewrel_tpu.native.sampler import (
        make_index_sampler,
    )
    from induction_network_on_fewrel_tpu.train.token_cache import (
        make_token_cached_multi_eval_step,
        tokenize_dataset,
    )

    cfg = CFG.replace(encoder="bilstm", lstm_hidden=16, att_dim=8,
                      induction_dim=16, ntn_slices=8, na_rate=2,
                      token_cache=True, steps_per_call=3, dp=8)
    vocab = make_synthetic_glove(vocab_size=300)
    ds = make_synthetic_fewrel(
        num_relations=8, instances_per_relation=12, vocab_size=300
    )
    tok = GloveTokenizer(vocab, max_length=L)
    table_np, sizes = tokenize_dataset(ds, tok)
    model = build_model(cfg, glove_init=vocab.vectors)
    idx = make_index_sampler(
        sizes, cfg.n, cfg.k, cfg.q, batch_size=cfg.batch_size,
        na_rate=cfg.na_rate, seed=3, backend="python",
    )
    si, qi, lab = idx.sample_fused(cfg.steps_per_call)
    sup = {k: v[si[0]] for k, v in table_np.items()}
    qry = {k: v[qi[0]] for k, v in table_np.items()}
    state = init_state(model, cfg, sup, qry)
    assert lab.max() == cfg.n  # NOTA label present in the sampled batches

    single = make_token_cached_multi_eval_step(model, cfg)
    ref = jax.device_get(single(state.params, table_np, si, qi, lab))

    mesh = make_mesh(dp=8)
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P_

    table_dev = {
        k: jax.device_put(v, NamedSharding(mesh, P_()))
        for k, v in table_np.items()
    }
    sharded = make_token_cached_multi_eval_step(model, cfg, mesh, state)
    out = jax.device_get(sharded(state.params, table_dev, si, qi, lab))

    assert set(out) == set(ref) >= {"loss", "accuracy", "nota_tp",
                                    "nota_pred", "nota_true"}
    for k in ref:
        np.testing.assert_allclose(out[k], ref[k], atol=1e-6, err_msg=k)


def test_distributed_init_failure_is_clean(monkeypatch):
    """A failed pod rendezvous surfaces as an actionable RuntimeError, not a
    raw gRPC traceback (SURVEY.md §5.3 failure detection)."""
    import pytest

    from induction_network_on_fewrel_tpu.parallel.distributed import (
        maybe_initialize_distributed,
    )

    # Off-pod: no env vars, no force -> no-op (clear the vars first, in
    # case this machine's environment carries them).
    for v in ("COORDINATOR_ADDRESS", "TPU_WORKER_ID",
              "MEGASCALE_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(v, raising=False)
    monkeypatch.setattr(jax.distributed, "is_initialized", lambda: False,
                        raising=False)  # attr absent on older jax
    assert maybe_initialize_distributed() is False

    monkeypatch.setenv("COORDINATOR_ADDRESS", "127.0.0.1:1")  # nothing there
    monkeypatch.setattr(
        jax.distributed, "initialize",
        lambda **kw: (_ for _ in ()).throw(TimeoutError("deadline exceeded")),
    )
    monkeypatch.setattr(jax.distributed, "is_initialized", lambda: False,
                        raising=False)  # attr absent on older jax
    with pytest.raises(RuntimeError, match="multi-host initialization"):
        maybe_initialize_distributed()


def test_zero_opt_moments_sharded_and_trajectory_identical():
    """zero_opt (ZeRO-1): Adam moments shard over dp — 1/dp of the
    optimizer HBM per device — with the training trajectory unchanged
    vs the replicated single-device step (1e-5 after 3 steps)."""
    from jax.sharding import PartitionSpec as P

    from induction_network_on_fewrel_tpu.parallel.sharding import shard_state

    # Dims chosen divisible by dp=8 so the per-leaf axis search shards the
    # moment matrices (the embedding table 302x50 stays replicated — no
    # divisible axis — which the best-effort rule must tolerate).
    cfg = CFG.replace(hidden_size=32, induction_dim=16, ntn_slices=16)
    vocab = make_synthetic_glove(vocab_size=300)
    ds = make_synthetic_fewrel(num_relations=6, instances_per_relation=12,
                               vocab_size=300)
    tok = GloveTokenizer(vocab, max_length=L)
    sampler = EpisodeSampler(ds, tok, cfg.n, cfg.k, cfg.q, cfg.batch_size, seed=0)
    model = build_model(cfg, glove_init=vocab.vectors)
    batches = [batch_to_model_inputs(sampler.sample_batch()) for _ in range(3)]
    state0 = init_state(model, cfg, batches[0][0], batches[0][1])

    cfg_z = cfg.replace(dp=8, zero_opt=True)
    mesh = make_mesh(dp=8)

    single_step = make_train_step(model, cfg)
    ref_state, _ = _run_steps(single_step, _copy_state(state0), batches)

    z_state = shard_state(_copy_state(state0), mesh, zero_opt=True)
    z_step = make_sharded_train_step(model, cfg_z, mesh, z_state)
    z_state, _ = _run_steps(z_step, z_state, batches)
    _params_allclose(ref_state, jax.device_get(z_state), atol=1e-5)

    # The moments must ACTUALLY be sharded: every mu matrix with an
    # 8-divisible axis carries dp in its spec; params stay replicated.
    def path_str(path):
        return "/".join(
            str(getattr(p, "key", getattr(p, "name", p))) for p in path
        )

    mu_leaves = [
        (path_str(path), leaf)
        for path, leaf in jax.tree_util.tree_leaves_with_path(z_state.opt_state)
        if "/mu/" in path_str(path)
    ]
    assert mu_leaves
    sharded = [
        leaf for path, leaf in mu_leaves
        if any(s >= 8 and s % 8 == 0 for s in leaf.shape)
        # tensor_slices' mu keeps a tp-rule spec only when tp > 1; on this
        # tp=1 mesh it is effectively replicated, so the dp rule claims it
        # too — no exclusions needed, every shardable mu must carry dp.
    ]
    assert sharded, "no shardable mu leaves in this model"
    assert all("dp" in str(leaf.sharding.spec) for leaf in sharded)
    param_specs = {
        leaf.sharding.spec for leaf in jax.tree.leaves(z_state.params)
    }
    # Params: replicated except the standing tp rule on tensor_slices
    # (tp=1 on this mesh, so that spec is replication in practice).
    assert param_specs <= {P(), P("tp", None, None)}, param_specs
