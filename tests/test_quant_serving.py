"""Quantized serving data plane (ISSUE 18): bf16/int8 resident class
vectors + NTN scoring with drift-gated parity.

Covers: int8 quantize/artifact math, dtype-keyed program-cache signatures
(mixed-precision tenants never collide), resident-byte accounting and the
>= 3.5x int8 density win, verdict parity of the quantized paths against
f32 on a BRIEFLY-TRAINED model (an untrained model scores near-ties
everywhere — agreement on it gauges tie-breaking, not quantization), the
parity police tripping the SAME drift alarm path as model drift on an
injected bad-scale tenant, degenerate-quantization quarantine, the
zero-steady-state-recompile gate under mixed-dtype co-residency, and
byte-derived fleet placement capacity.
"""

import dataclasses
import time

import numpy as np
import pytest

from induction_network_on_fewrel_tpu.config import (
    ExperimentConfig,
    resolve_quant_policy,
)
from induction_network_on_fewrel_tpu.data import (
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.data.tokenizer import GloveTokenizer
from induction_network_on_fewrel_tpu.fleet.control import FleetControl
from induction_network_on_fewrel_tpu.fleet.router import FleetRouter
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.obs import DriftDetector
from induction_network_on_fewrel_tpu.obs.health import CRITICAL
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler
from induction_network_on_fewrel_tpu.serving.buckets import (
    RESIDENT_DTYPES,
    resident_dtype_name,
)
from induction_network_on_fewrel_tpu.serving.engine import InferenceEngine
from induction_network_on_fewrel_tpu.serving.registry import (
    QuantArtifactError,
    quant_artifact,
    quantize_int8,
)
from induction_network_on_fewrel_tpu.serving.stats import ServingStats
from induction_network_on_fewrel_tpu.train import FewShotTrainer
from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

# Tiny flagship-shaped config (the tests/test_serving.py world) + the
# training fields the parity fixture needs.
CFG = ExperimentConfig(
    model="induction", encoder="cnn", hidden_size=16,
    vocab_size=122, word_dim=8, pos_dim=2, max_length=16,
    induction_dim=8, ntn_slices=4, routing_iters=2,
    n=3, train_n=3, k=2, q=2, batch_size=2, lr=5e-3, val_step=0,
    device="cpu",
)


@pytest.fixture(scope="module")
def trained_world():
    """(tok, model, params, ds): ~150 optimizer steps on the synthetic
    corpus — enough for REAL verdict margins (test_train.py overfits the
    same generator in 200), so parity floors measure quantization."""
    vocab = make_synthetic_glove(vocab_size=CFG.vocab_size - 2,
                                 word_dim=CFG.word_dim)
    tok = GloveTokenizer(vocab, max_length=CFG.max_length)
    ds = make_synthetic_fewrel(
        num_relations=5, instances_per_relation=12,
        vocab_size=CFG.vocab_size - 2, seed=7,
    )
    model = build_model(CFG, glove_init=vocab.vectors)
    trainer = FewShotTrainer(
        model, CFG,
        EpisodeSampler(ds, tok, n=CFG.n, k=CFG.k, q=CFG.q,
                       batch_size=CFG.batch_size, seed=3),
        logger=MetricsLogger(quiet=True),
    )
    state = trainer.train(num_iters=150)
    return tok, model, state.params, ds


def _engine(trained_world, **kw):
    tok, model, params, ds = trained_world
    eng = InferenceEngine(
        model, params, CFG, tok, k=CFG.k,
        buckets=kw.pop("buckets", (1, 2, 4)),
        start=kw.pop("start", True), **kw,
    )
    return eng, ds


def _held_out(ds):
    return [i for r in ds.rel_names for i in ds.instances[r][CFG.k:]]


def _wait_for(cond, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return cond()


# --- quantization math ----------------------------------------------------


def test_quantize_int8_roundtrip_and_scale():
    rng = np.random.default_rng(0)
    stack = rng.normal(size=(6, 32)).astype(np.float32)
    q, scale = quantize_int8(stack)
    assert q.dtype == np.int8 and scale.dtype == np.float32
    assert np.abs(q).max() <= 127
    # Symmetric rounding: dequantized error bounded by half a step.
    np.testing.assert_allclose(
        q.astype(np.float32) * scale, stack, atol=float(scale) / 2 + 1e-7
    )
    # All-zero stack: scale falls back to 1.0 (no divide-by-zero).
    qz, sz = quantize_int8(np.zeros((2, 4), np.float32))
    assert float(sz) == 1.0 and not qz.any()
    assert quant_artifact(np.zeros((2, 4), np.float32), qz) is None


def test_quant_artifact_flags_degenerate_rows():
    # Dynamic-range collapse: one huge (healthy-spread) row sets the
    # per-tenant scale, a small (but live) row quantizes to all zeros.
    stack = np.ones((2, 8), np.float32)
    stack[0] = np.linspace(1e3, 1e4, 8)
    stack[1] *= 1e-3
    q, _ = quantize_int8(stack)
    reason = quant_artifact(stack, q)
    assert reason is not None and "collapse" in reason
    # Full saturation: a constant row pins every element at the clip.
    flat = np.full((1, 8), 100.0, np.float32)
    qf, _ = quantize_int8(flat)
    reason = quant_artifact(flat, qf)
    assert reason is not None and "saturated" in reason
    # Healthy spread: no artifact.
    healthy = np.linspace(-1.0, 1.0, 16, dtype=np.float32).reshape(2, 8)
    qh, _ = quantize_int8(healthy)
    assert quant_artifact(healthy, qh) is None


def test_resident_dtype_name_rejects_unknown():
    assert resident_dtype_name(np.int8) == "int8"
    assert resident_dtype_name(RESIDENT_DTYPES["bf16"]) == "bf16"
    with pytest.raises(ValueError, match="not a resident dtype"):
        resident_dtype_name(np.float64)


def test_resolve_quant_policy_one_home():
    class Knobs:
        resident_dtype = "int8"
        quant_probe_every = 3

    q = resolve_quant_policy(Knobs())
    assert q == {"resident_dtype": "int8", "probe_every": 3}
    # None inherits base (the checkpoint config), default f32/off.
    class NoneKnobs:
        resident_dtype = None
        quant_probe_every = None

    assert resolve_quant_policy(NoneKnobs()) == {
        "resident_dtype": "f32", "probe_every": 0,
    }
    assert resolve_quant_policy(NoneKnobs(), base=Knobs()) == {
        "resident_dtype": "int8", "probe_every": 3,
    }
    class Bad:
        resident_dtype = "fp4"
        quant_probe_every = None

    with pytest.raises(ValueError, match="resident_dtype"):
        resolve_quant_policy(Bad())


# --- residency + accounting -----------------------------------------------


def test_resident_bytes_density(trained_world):
    """int8 residency must be >= 3.5x smaller than f32 per tenant — the
    tenant-density headline (bytes derive placement capacity)."""
    eng, ds = _engine(trained_world, start=False)
    try:
        eng.register_dataset(ds)
        f32_bytes = eng.registry.resident_bytes()["default"]
        n, c = np.asarray(eng.registry.snapshot().matrix).shape
        assert f32_bytes == n * c * 4
        eng.warmup()
        eng.set_resident_dtype("default", "int8")
        snap = eng.registry.snapshot()
        assert np.asarray(snap.matrix).dtype == np.int8
        assert snap.shadow is not None and snap.scale is not None
        int8_bytes = eng.registry.resident_bytes()["default"]
        assert int8_bytes == n * c + 4          # + the f32 scale scalar
        assert f32_bytes / int8_bytes >= 3.5
        # The stats gauge restates the registry sum.
        assert eng.stats.snapshot()["resident_bytes"] == int8_bytes
        # bf16 residency halves f32 and needs no scale.
        eng.set_resident_dtype("default", "bf16")
        assert eng.registry.resident_bytes()["default"] == n * c * 2
        assert eng.registry.snapshot().scale is None
    finally:
        eng.close()


def test_degenerate_quantization_quarantines(trained_world, monkeypatch):
    """A dtype flip whose quantization comes out degenerate must never
    become resident: the registry refuses it, reverts the override, and
    quarantines the tenant (served degraded — same containment as a
    NaN'd artifact)."""
    import induction_network_on_fewrel_tpu.serving.registry as regmod

    eng, ds = _engine(trained_world, start=False)
    try:
        eng.register_dataset(ds)
        eng.warmup()

        def collapse(stack):
            return np.zeros_like(stack, dtype=np.int8), np.float32(1.0)

        monkeypatch.setattr(regmod, "quantize_int8", collapse)
        with pytest.raises(QuantArtifactError, match="refused"):
            eng.set_resident_dtype("default", "int8")
        snap = eng.registry.snapshot()
        assert snap.degraded                       # quarantined
        assert eng.registry.dtype_for("default") == "f32"  # reverted
        assert np.asarray(snap.matrix).dtype == np.float32
        # A healthy re-flip after the fix makes the int8 form resident,
        # but — same discipline as registration on a quarantined
        # tenant — does NOT clear the quarantine; the explicit
        # unquarantine (or a committed publish) does.
        monkeypatch.undo()
        eng.set_resident_dtype("default", "int8")
        snap = eng.registry.snapshot()
        assert snap.degraded
        assert np.asarray(snap.matrix).dtype == np.int8
        eng.registry.unquarantine_tenant("default", reason="scale fixed")
        assert not eng.registry.snapshot().degraded
    finally:
        eng.close()


# --- parity ---------------------------------------------------------------


@pytest.mark.parametrize("dtype", ["bf16", "int8"])
def test_quantized_parity_vs_f32(trained_world, dtype):
    """Seeded held-out episodes through the quantized data plane with the
    parity police shadow-scoring EVERY batch: verdict agreement >= 99%,
    margin drift inside the band, zero steady-state recompiles."""
    eng, ds = _engine(trained_world, resident_dtype=dtype,
                      quant_probe_every=1)
    try:
        eng.register_dataset(ds)
        eng.warmup()
        queries = _held_out(ds)
        for inst in queries:
            v = eng.classify(inst)
            assert "label" in v
        assert _wait_for(
            lambda: eng.stats.snapshot()["quant_probes"]
            >= len(queries) - 1
        )
        snap = eng.stats.snapshot()
        assert snap["quant_agreement"] >= 0.99
        assert snap["steady_recompiles"] == 0
        quality = eng.stats.quality_snapshot()["default"]
        assert quality["quant_margin_drift"] <= 0.25
    finally:
        eng.close()


def test_bad_scale_trips_drift_alarm(trained_world):
    """The drill the parity police exists for: a tenant whose resident
    int8 scale is corrupted (here: inflated 50x in place) must trip the
    SAME once-latched prediction_drift CRITICAL path as model drift —
    the PR 13/14 adaptation loop triggers on exactly this selector."""
    det = DriftDetector(window=16, baseline_n=8, min_count=8)
    eng, ds = _engine(trained_world, resident_dtype="int8",
                      quant_probe_every=1, drift=det)
    try:
        eng.register_dataset(ds)
        eng.warmup()
        snap = eng.registry.snapshot()
        eng.registry._tenants["default"] = dataclasses.replace(
            snap, scale=np.float32(float(snap.scale) * 50.0)
        )
        for inst in _held_out(ds):
            eng.classify(inst)
        assert _wait_for(lambda: det.tripped)
        quant_crits = [
            ev for ev in det.events
            if ev.event == "prediction_drift" and ev.severity == CRITICAL
            and str(ev.data.get("feature", "")).startswith("quant_")
        ]
        assert quant_crits, [e.data for e in det.events]
        # Once-latched: the stream is not spammed while still bad.
        n = len(quant_crits)
        eng.classify(_held_out(ds)[0])
        time.sleep(0.2)
        assert len([
            ev for ev in det.events
            if ev.severity == CRITICAL
            and str(ev.data.get("feature", "")).startswith("quant_")
        ]) == n
        # The corrupted scale inflates every margin ~50x: the drift
        # shows up in the margin band (verdicts can still agree — NTN
        # argmax is not scale-invariant but often survives).
        state = det.parity_state("default")
        assert state is not None
        assert state["margin_drift"] > 0.25
        # rearm (the publish/rollback path) clears the parity latches.
        det.rearm("default", reason="test rollback")
        assert det.parity_state("default") is None
    finally:
        eng.close()


def test_observe_parity_bands_direct():
    """Unit-level band math: in-band probes emit nothing; a shortfall
    past crit_factor x band goes straight to CRITICAL; back-in-band
    probes re-arm the latch."""
    det = DriftDetector(window=16, baseline_n=8, min_count=8)
    assert det.observe_parity("t", agreement=1.0, margin_drift=0.01,
                              rows=8) == []
    evs = det.observe_parity("t", agreement=0.5, margin_drift=2.0,
                             rows=64)
    feats = {e.data["feature"] for e in evs}
    assert feats == {"quant_agreement", "quant_margin_drift"}
    assert all(e.severity == CRITICAL for e in evs)
    assert det.tripped
    # Latched: same breach, no new events.
    assert det.observe_parity("t", agreement=0.5, margin_drift=2.0,
                              rows=64) == []
    # Flush the window back in band -> latch released, next breach fires.
    for _ in range(16):
        det.observe_parity("t", agreement=1.0, margin_drift=0.0,
                           rows=1000)
    evs = det.observe_parity("t", agreement=0.0, margin_drift=5.0,
                             rows=10**6)
    assert evs and all(e.severity == CRITICAL for e in evs)


# --- mixed-dtype co-residency ---------------------------------------------


def test_mixed_dtype_zero_recompile_soak(trained_world):
    """Two tenants at different resident dtypes on ONE engine: the dtype
    is part of the program-cache key, so they can never collide in a
    compiled signature — interleaved traffic stays at zero steady-state
    recompiles."""
    eng, ds = _engine(trained_world, resident_dtype="f32",
                      quant_probe_every=1)
    try:
        eng.register_dataset(ds, tenant="plain")
        eng.register_dataset(ds, tenant="dense")
        eng.warmup()
        eng.set_resident_dtype("dense", "int8")
        keys = set(eng.programs._exe)
        # The cache keys on the PUBLISHED class axis — the N-tier the
        # registry pads to (ISSUE 19), == len(rel_names) under exact-N.
        n = eng.registry.snapshot("plain").n_tier
        assert any(k[0] == n and k[2] == "f32" for k in keys)
        assert any(k[0] == n and k[2] == "int8" for k in keys)
        queries = _held_out(ds)[:10]
        verdicts = {}
        for tenant in ("plain", "dense"):
            verdicts[tenant] = [
                eng.classify(inst, tenant=tenant) for inst in queries
            ]
        snap = eng.stats.snapshot()
        assert snap["steady_recompiles"] == 0
        assert snap["served"] == 2 * len(queries)
        # Same corpus, same params: the quantized tenant agrees with its
        # f32 co-resident on these held-out rows.
        agree = sum(
            a["label"] == b["label"]
            for a, b in zip(verdicts["plain"], verdicts["dense"])
        )
        assert agree >= 9
        # Rolling dense back to f32 (the RUNBOOK parity-alarm remedy)
        # reuses the warmed f32 programs: still zero recompiles.
        eng.set_resident_dtype("dense", "f32")
        for inst in queries[:4]:
            eng.classify(inst, tenant="dense")
        assert eng.stats.snapshot()["steady_recompiles"] == 0
    finally:
        eng.close()


# --- stats plumbing -------------------------------------------------------


def test_stats_quant_gauges():
    stats = ServingStats()
    snap = stats.snapshot()
    assert snap["quant_probes"] == 0
    assert snap["quant_agreement"] == 1.0   # vacuous without probes
    assert snap["resident_bytes"] == 0.0    # no provider bound
    stats.bind_resident(lambda: {"a": 100.0, "b": 28.0})
    # quality_snapshot only lists tenants with quality-bearing verdicts
    # (the engine always serves before it probes).
    stats.record_done(0.001, tenant="a", nota=False, margin=0.5,
                      entropy=0.1)
    stats.record_quant_probe("a", agreement=0.75, margin_drift=0.1,
                             rows=4)
    stats.record_quant_probe("a", agreement=1.0, margin_drift=0.3,
                             rows=4)
    snap = stats.snapshot()
    assert snap["quant_probes"] == 2
    assert snap["quant_agreement"] == pytest.approx(0.875)
    assert snap["resident_bytes"] == 128.0
    per = stats.tenant_snapshot()
    assert per["a"]["resident_bytes"] == 100.0
    quality = stats.quality_snapshot()["a"]
    assert quality["quant_agreement"] == pytest.approx(0.875)
    assert quality["quant_margin_drift"] == pytest.approx(0.2)


# --- byte-derived fleet capacity ------------------------------------------


class _FakeHandle:
    """Minimal ReplicaHandle for placement-capacity tests: carries a
    settable resident_bytes gauge and records registrations."""

    def __init__(self):
        self.resident = 0.0
        self.registered = []

    def register_dataset(self, dataset, tenant, max_classes=None):
        self.registered.append(tenant)

    def set_nota_threshold(self, threshold, tenant):
        pass

    def stats_snapshot(self):
        return {"served": 0, "resident_bytes": self.resident}

    def close(self):
        pass


def test_fleet_capacity_derived_from_bytes():
    handles = {"r0": _FakeHandle(), "r1": _FakeHandle()}
    router = FleetRouter(handles, resident_budget_bytes=100.0)
    try:
        control = FleetControl(router)
        # Rendezvous placement is a pure function of the ids: find one
        # tenant per owner.
        by_owner = {}
        for i in range(32):
            name = f"t{i}"
            owner = router.placement.place(name)
            by_owner.setdefault(owner, name)
            if len(by_owner) == 2:
                break
        assert set(by_owner) == {"r0", "r1"}
        # Under budget: placement admits the tenant.
        assert control.register_tenant(by_owner["r0"], None) == "r0"
        # Owner at its byte budget: registration refused up front, and
        # the directory never learns the tenant.
        handles["r0"].resident = 150.0
        victim = next(
            f"u{i}" for i in range(64)
            if router.placement.place(f"u{i}") == "r0"
        )
        with pytest.raises(RuntimeError, match="resident-byte budget"):
            control.register_tenant(victim, None)
        assert victim not in router.directory
        # The other replica still has headroom.
        assert control.register_tenant(by_owner["r1"], None) == "r1"
        # Per-replica gauge the rollup restates.
        assert router.replica_resident_bytes("r0") == 150.0
    finally:
        router.close()


def test_fleet_budget_validation():
    with pytest.raises(ValueError, match="resident_budget_bytes"):
        FleetRouter({"r0": _FakeHandle()}, resident_budget_bytes=0.0)
