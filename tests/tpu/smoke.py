#!/usr/bin/env python3
"""TPU smoke (SURVEY.md §4.6): the jitted flagship step runs on the real
chip with NO recompilation across steps. Run manually: needs the tunneled
v5e, so it stays out of the default pytest collection (tests/tpu/README.md).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))


def main() -> int:
    import jax

    backend = jax.default_backend()
    if backend != "tpu":
        print(f"SKIP: default backend is {backend!r}, not tpu")
        return 0
    dev = jax.devices()[0]
    print(f"device: {dev.device_kind} ({dev.platform})")

    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.data import (
        GloveTokenizer,
        make_synthetic_fewrel,
        make_synthetic_glove,
    )
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.models.build import batch_to_model_inputs
    from induction_network_on_fewrel_tpu.sampling import EpisodeSampler
    from induction_network_on_fewrel_tpu.train.steps import init_state, make_train_step

    cfg = ExperimentConfig(
        encoder="bilstm", n=5, k=5, q=5, batch_size=4, max_length=40,
        vocab_size=2002, compute_dtype="bfloat16", lstm_backend="pallas",
    )
    ds = make_synthetic_fewrel(
        num_relations=10, instances_per_relation=cfg.k + cfg.q + 2,
        vocab_size=cfg.vocab_size - 2,
    )
    vocab = make_synthetic_glove(vocab_size=cfg.vocab_size - 2)
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    sampler = EpisodeSampler(
        ds, tok, cfg.n, cfg.k, cfg.q, batch_size=cfg.batch_size, seed=0
    )
    model = build_model(cfg, glove_init=vocab.vectors)
    sup, qry, label = batch_to_model_inputs(sampler.sample_batch())
    state = init_state(model, cfg, sup, qry)
    step = make_train_step(model, cfg)

    t0 = time.monotonic()
    state, metrics = step(state, sup, qry, label)
    loss = float(jax.device_get(metrics["loss"]))  # hard sync (BASELINE.md)
    compile_s = time.monotonic() - t0
    print(f"step 1 (compile): {compile_s:.1f}s, loss={loss:.4f}")
    assert loss == loss, "loss is NaN"

    # One extra cache entry is expected between call 1 and 2 (the fresh
    # numpy/uncommitted state vs. the committed donated output buffers);
    # after that the executable must be stable across steps.
    warm = []
    baseline_cache = None
    for i in range(4):
        sup, qry, label = batch_to_model_inputs(sampler.sample_batch())
        t0 = time.monotonic()
        state, metrics = step(state, sup, qry, label)
        loss = float(jax.device_get(metrics["loss"]))
        warm.append(time.monotonic() - t0)
        assert loss == loss, f"loss is NaN at warm step {i}"
        if baseline_cache is None:
            baseline_cache = step._cache_size()

    cache_size = step._cache_size()
    print(f"warm steps: {[f'{t * 1e3:.0f}ms' for t in warm]}, "
          f"jit cache entries: {cache_size} (after-first-warm: {baseline_cache})")
    assert cache_size == baseline_cache, (
        f"recompilation across warm steps ({baseline_cache} -> {cache_size})"
    )
    assert min(warm) < max(compile_s / 5.0, 2.0), (
        f"warm step {min(warm):.2f}s suspiciously close to compile "
        f"{compile_s:.2f}s — recompiling?"
    )

    # Kernel x GSPMD on silicon (round-5 VERDICT item 2): the SAME pallas
    # backend compiled through the mesh-sharded step on a 1-device mesh —
    # proves the compiled-kernel + GSPMD-partitioner composition on TPU
    # (the 8-virtual-device equality half runs in tests/test_parallel.py
    # via the interpreter; this half is the real-toolchain compile).
    from induction_network_on_fewrel_tpu.parallel import make_mesh
    from induction_network_on_fewrel_tpu.parallel.sharding import (
        make_sharded_train_step,
    )

    # attn pallas here on purpose: the sharded leg doubles as the
    # on-silicon kernel x GSPMD composition check for BOTH kernels (the
    # production default resolves to xla attention — BASELINE.md round-5
    # A/B — but the kernel must keep compiling under the mesh).
    cfg_m = cfg.replace(dp=1, attn_backend="pallas")
    mesh = make_mesh(dp=1, devices=jax.devices()[:1])
    # REBUILD the model from cfg_m: attn_backend is consumed at
    # build_model time, so reusing `model` would silently run the xla
    # attention and this leg would guard nothing (review finding, r5).
    model_m = build_model(cfg_m, glove_init=vocab.vectors)
    state_m = init_state(model_m, cfg_m, sup, qry)
    sstep = make_sharded_train_step(model_m, cfg_m, mesh, state_m)
    t0 = time.monotonic()
    state_m, m_m = sstep(state_m, sup, qry, label)
    loss_m = float(jax.device_get(m_m["loss"]))
    print(f"sharded pallas step 1 (compile): {time.monotonic() - t0:.1f}s, "
          f"loss={loss_m:.4f}")
    assert loss_m == loss_m, "sharded pallas loss is NaN"
    sh_cache = None
    for i in range(3):
        sup, qry, label = batch_to_model_inputs(sampler.sample_batch())
        state_m, m_m = sstep(state_m, sup, qry, label)
        loss_m = float(jax.device_get(m_m["loss"]))
        assert loss_m == loss_m, f"sharded pallas NaN at warm step {i}"
        if sh_cache is None:
            sh_cache = sstep._cache_size()
    assert sstep._cache_size() == sh_cache, (
        f"sharded pallas step recompiled ({sh_cache} -> "
        f"{sstep._cache_size()})"
    )
    print(f"sharded pallas warm steps stable (cache entries: {sh_cache})")
    print("TPU SMOKE OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
