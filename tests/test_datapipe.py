"""datapipe/ — pipelined, checkpointable episode input pipeline (ISSUE 4).

The contracts under test:

* **Stream invariance** — the sequence of batches the feed hands out is
  bitwise-identical at every prefetch depth, and ``prefetch_depth=0``
  degrades to the exact synchronous path (bitwise-equal metrics stream
  from a real trainer).
* **Cursor resume** — kill/restore mid-epoch through the in-process
  CheckpointManager path reproduces the exact episode sequence, with and
  without ``--ckpt_delta``, across prefetch depths and mid-unit positions.
* **Mixture** — deterministic source picks from (seed, index), schedule
  curricula, shape validation, cursor round-trip.
* **Faults** — slow/stall/poison drills surface as telemetry + watchdog
  events instead of silent wedges.
"""

import json

import numpy as np
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    GloveTokenizer,
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.datapipe import (
    FeedFaults,
    MixtureSampler,
    MixtureSchedule,
    PipelineFeed,
)
from induction_network_on_fewrel_tpu.datapipe.cursor import PipelineCursor
from induction_network_on_fewrel_tpu.datapipe.producer import FeedError
from induction_network_on_fewrel_tpu.native.sampler import make_index_sampler
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler
from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

SIZES = [12] * 6
DEPTHS = (0, 2, 4)


def _index_sampler(seed=7, backend="python"):
    return make_index_sampler(
        SIZES, 3, 2, 2, batch_size=2, seed=seed, backend=backend
    )


def _batches_equal(a, b):
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _token_setup(seed=0):
    vocab = make_synthetic_glove(vocab_size=300)
    ds = make_synthetic_fewrel(
        num_relations=6, instances_per_relation=12, vocab_size=300, seed=seed
    )
    tok = GloveTokenizer(vocab, max_length=12)
    return vocab, ds, tok


# --- stream invariance -----------------------------------------------------


def test_stream_identical_across_depths():
    """The load-bearing invariant: prefetch depth changes WHEN batches are
    produced, never WHICH batches (nor their order)."""
    ref = _index_sampler()
    want = [ref.sample_batch() for _ in range(12)]
    for depth in DEPTHS:
        feed = PipelineFeed(_index_sampler(), prefetch_depth=depth)
        try:
            got = [feed.sample_batch() for _ in range(12)]
        finally:
            feed.close()
        for a, b in zip(want, got):
            _batches_equal(a, b)


def test_fused_and_single_interleave_preserve_stream():
    """Mixed consumption (single draws + fused stacks) walks the same
    per-batch sequence the synchronous sampler produces."""
    ref = _index_sampler()
    flat = [ref.sample_batch() for _ in range(9)]
    feed = PipelineFeed(_index_sampler(), prefetch_depth=2, unit=4)
    try:
        one = feed.sample_batch()                 # batch 0
        stack = feed.sample_fused(4)              # batches 1..4
        two = feed.sample_batch()                 # batch 5
        _batches_equal(one, flat[0])
        _batches_equal(two, flat[5])
        for i in range(4):
            _batches_equal(
                tuple(np.asarray(s[i]) for s in stack), flat[1 + i]
            )
    finally:
        feed.close()


def test_depth0_bitwise_equal_metrics_stream(tmp_path):
    """ISSUE 4 satellite: --prefetch_depth 0 degrades gracefully to the
    current synchronous path — a real trainer run produces a bitwise-equal
    train metrics stream with and without the feed wrapper."""
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.train import FewShotTrainer

    cfg = ExperimentConfig(
        encoder="cnn", n=2, k=2, q=2, batch_size=2, max_length=12,
        vocab_size=302, hidden_size=16, compute_dtype="float32",
        train_iter=4, val_step=0,
    )
    vocab, ds, tok = _token_setup()
    model = build_model(cfg, glove_init=vocab.vectors)

    def run(wrap, out):
        sampler = EpisodeSampler(
            ds, tok, cfg.n, cfg.k, cfg.q, cfg.batch_size, seed=5
        )
        if wrap:
            sampler = PipelineFeed(sampler, prefetch_depth=0)
        trainer = FewShotTrainer(
            model, cfg, sampler, logger=MetricsLogger(out, quiet=True)
        )
        try:
            trainer.train(num_iters=4)
        finally:
            trainer.close()
        recs = [
            json.loads(line)
            for line in (out / "metrics.jsonl").read_text().splitlines()
        ]
        return [
            # wall_s / episodes_per_s are wall-clock measurements; every
            # numeric TRAINING field must match bitwise.
            {k: v for k, v in r.items()
             if k not in ("wall_s", "episodes_per_s")}
            for r in recs if r["kind"] == "train"
        ]

    bare = run(False, tmp_path / "bare")
    fed = run(True, tmp_path / "fed")
    assert bare == fed and bare  # identical losses/steps, wall time aside


# --- cursor resume ---------------------------------------------------------


@pytest.mark.parametrize("depth", DEPTHS)
@pytest.mark.parametrize("backend", ["python", "native"])
def test_cursor_resume_exact(depth, backend):
    if backend == "native":
        pytest.importorskip("ctypes")
        from induction_network_on_fewrel_tpu.native.lib import native_available

        if not native_available():
            pytest.skip("native toolchain unavailable")
    feed = PipelineFeed(_index_sampler(backend=backend), prefetch_depth=depth)
    try:
        for _ in range(5):
            feed.sample_batch()
        cur = feed.cursor_state()
        want = [feed.sample_batch() for _ in range(6)]
    finally:
        feed.close()
    assert cur.consumed == 5
    # Serialization round-trip: the cursor rides in a checkpoint as JSON.
    cur = PipelineCursor.from_json(cur.to_json())
    resumed = PipelineFeed(
        _index_sampler(backend=backend), prefetch_depth=2
    )
    try:
        resumed.restore_cursor(cur)
        got = [resumed.sample_batch() for _ in range(6)]
    finally:
        resumed.close()
    for a, b in zip(want, got):
        _batches_equal(a, b)


def test_cursor_resume_mid_unit_fused():
    """A cursor taken mid-unit (after an odd single draw) still restores
    the exact stream — the replay covers the intra-unit offset."""
    feed = PipelineFeed(_index_sampler(), prefetch_depth=2, unit=4)
    try:
        feed.sample_fused(4)
        feed.sample_batch()                      # consumed = 5, mid-unit
        cur = feed.cursor_state()
        want = feed.sample_fused(4)
    finally:
        feed.close()
    assert cur.consumed == 5
    resumed = PipelineFeed(_index_sampler(), prefetch_depth=4, unit=4)
    try:
        resumed.restore_cursor(cur)
        got = resumed.sample_fused(4)
    finally:
        resumed.close()
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)


def test_cursor_layout_mismatch_raises():
    feed = PipelineFeed(_index_sampler(), prefetch_depth=0)
    try:
        cur = feed.cursor_state()
        bad = PipelineCursor.from_dict(cur.to_dict())
        bad.layout["global_batch"] = 64
        with pytest.raises(ValueError, match="layout mismatch"):
            feed.restore_cursor(bad)
        tagged = PipelineCursor.from_dict(cur.to_dict())
        tagged.stream_tag = "mixture=other;seed=1"
        with pytest.raises(ValueError, match="stream tag"):
            feed.restore_cursor(tagged)
    finally:
        feed.close()


def _trainer_pieces(cfg, seed=3):
    from induction_network_on_fewrel_tpu.models import build_model

    vocab, ds, tok = _token_setup(seed=1)
    model = build_model(cfg, glove_init=vocab.vectors)

    def make(depth):
        sampler = PipelineFeed(
            EpisodeSampler(
                ds, tok, cfg.n, cfg.k, cfg.q, cfg.batch_size, seed=seed
            ),
            prefetch_depth=depth,
        )
        val = EpisodeSampler(
            ds, tok, cfg.n, cfg.k, cfg.q, cfg.batch_size, seed=seed + 1
        )
        return model, sampler, val

    return make


@pytest.mark.parametrize("ckpt_delta", ["auto", "off"])
def test_kill_restore_reproduces_episode_stream(tmp_path, ckpt_delta):
    """ISSUE 4 acceptance: kill mid-epoch, restore through the in-process
    CheckpointManager path, and the resumed feed replays the EXACT episode
    sequence the uninterrupted run consumed — with and without the
    delta-ring checkpoint format, at different prefetch depths. The lazy
    embed config makes ``auto`` take the real delta path."""
    from induction_network_on_fewrel_tpu.train import FewShotTrainer
    from induction_network_on_fewrel_tpu.train.checkpoint import (
        CheckpointManager,
    )

    cfg = ExperimentConfig(
        encoder="cnn", n=2, k=2, q=2, batch_size=2, max_length=12,
        vocab_size=302, hidden_size=16, compute_dtype="float32",
        embed_optimizer="lazy", ckpt_delta=ckpt_delta, ckpt_stage="off",
        val_step=2, val_iter=2, weight_decay=0.0,
    )
    make = _trainer_pieces(cfg)

    # Uninterrupted twin: train 4 steps, then record the NEXT 6 batches
    # the stream would feed.
    model, sampler_a, val_a = make(depth=2)
    trainer_a = FewShotTrainer(model, cfg, sampler_a, val_a)
    try:
        trainer_a.train(num_iters=4)
        want = [sampler_a.sample_batch() for _ in range(6)]
    finally:
        trainer_a.close()

    # Interrupted run: same stream, train 4 steps with checkpoints at the
    # val boundaries, then "die".
    model, sampler_b, val_b = make(depth=3)
    trainer_b = FewShotTrainer(
        model, cfg, sampler_b, val_b, ckpt_dir=tmp_path / "ckpt"
    )
    try:
        state = trainer_b.train(num_iters=4)
        import jax

        template = jax.device_get(state)
    finally:
        trainer_b.close()

    # Resumed process: fresh manager + fresh feed, cursor from the
    # restored step, at a different prefetch depth again.
    mngr = CheckpointManager(tmp_path / "ckpt", cfg, stage="off")
    try:
        _, step = mngr.restore_latest(template)
        assert step == 4
        cur = mngr.load_cursor(step)
        assert cur is not None, "checkpoint must carry the pipeline cursor"
        model, sampler_c, _ = make(depth=0)
        sampler_c.restore_cursor(PipelineCursor.from_dict(cur))
        got = [sampler_c.sample_batch() for _ in range(6)]
        sampler_c.close()
    finally:
        mngr.close()
    for a, b in zip(want, got):
        _batches_equal(a, b)


def test_cursor_sidecar_purged_with_ring(tmp_path):
    """The divergence-guard purge drops cursor sidecars newer than the
    restored best — a later resume must not splice the purged stream."""
    from induction_network_on_fewrel_tpu.train.checkpoint import (
        CheckpointManager,
    )

    cfg = ExperimentConfig(ckpt_stage="off")
    mngr = CheckpointManager(tmp_path, cfg, stage="off")
    try:
        cur = {"version": 1, "consumed": 9, "captured_at": 9,
               "sampler_state": {"kind": "native", "next": 9},
               "layout": {}, "stream_tag": ""}
        state = {"x": np.zeros(3, np.float32)}
        mngr.save_latest(5, state, force=True, cursor={**cur, "consumed": 5})
        mngr.wait()
        mngr.save_latest(9, state, force=True, cursor=cur)
        mngr.wait()
        assert mngr.load_cursor(9)["consumed"] == 9
        mngr.purge_ring_newer_than(5)
        assert mngr.load_cursor(9) is None
        assert mngr.load_cursor(5)["consumed"] == 5
    finally:
        mngr.close()


def test_cursor_prune_spares_best_and_bounds_ring(tmp_path):
    """Ring-cursor retention is bounded, but a BEST save's cursor survives
    any number of later ring saves — the divergence-guard + --resume path
    restores that old best step and needs its stream position (review
    finding)."""
    from induction_network_on_fewrel_tpu.train.checkpoint import (
        CheckpointManager,
    )

    cfg = ExperimentConfig(ckpt_stage="off")
    mngr = CheckpointManager(tmp_path, cfg, stage="off")
    keep = CheckpointManager._CURSOR_KEEP
    try:
        state = {"x": np.zeros(3, np.float32)}

        def cur(step):
            return {"version": 1, "consumed": step, "captured_at": step,
                    "sampler_state": {"kind": "native", "next": step},
                    "layout": {}, "stream_tag": ""}

        mngr.save(1, state, 0.9, cursor=cur(1))  # best at step 1
        for s in range(2, keep + 6):             # >keep later ring saves
            mngr.save_latest(s, state, force=True, cursor=cur(s))
        mngr.wait()
        assert mngr.load_cursor(1) is not None, "best cursor pruned"
        sidecars = sorted(tmp_path.glob("cursor_*.json"))
        assert len(sidecars) <= keep + 1  # keep ring + the protected best
    finally:
        mngr.close()


# --- mixture ---------------------------------------------------------------


def test_mixture_schedule_parse_and_weights():
    sched = MixtureSchedule.parse("train:1.0;other:0.0@0,1.0@100")
    assert sched.names == ("train", "other")
    assert sched.weights_at(0) == [1.0, 0.0]
    assert sched.weights_at(50) == [1.0, 0.5]
    assert sched.weights_at(1000) == [1.0, 1.0]
    # Canonical round-trip.
    assert MixtureSchedule.parse(sched.to_spec()) == sched
    with pytest.raises(ValueError, match="unknown|must be"):
        MixtureSchedule.parse("nocolon")
    with pytest.raises(ValueError, match="repeats"):
        MixtureSchedule.parse("a:1@0,2@0")


def test_mixture_pick_deterministic_and_weighted():
    sched = MixtureSchedule.parse("a:3.0;b:1.0")
    picks = [sched.pick(11, i) for i in range(2000)]
    assert picks == [sched.pick(11, i) for i in range(2000)]  # pure
    frac_a = picks.count(0) / len(picks)
    assert 0.70 < frac_a < 0.80  # 3:1 weights -> ~75% source a


def test_mixture_sampler_stream_and_cursor():
    def mk():
        return MixtureSampler(
            [("a", _index_sampler(seed=1)), ("b", _index_sampler(seed=2))],
            MixtureSchedule.parse("a:1.0;b:1.0"),
            seed=4,
        )

    ref = mk()
    want = [ref.sample_batch() for _ in range(10)]
    assert set(ref.counts.values()) != {0}  # both sources actually serve

    # Through a feed, with a cursor mid-stream, restored into a fresh tree.
    feed = PipelineFeed(mk(), prefetch_depth=2)
    try:
        for _ in range(4):
            feed.sample_batch()
        cur = feed.cursor_state()
        upcoming = [feed.sample_batch() for _ in range(6)]
    finally:
        feed.close()
    for a, b in zip(want[4:], upcoming):
        _batches_equal(a, b)
    resumed = PipelineFeed(mk(), prefetch_depth=0)
    try:
        resumed.restore_cursor(PipelineCursor.from_json(cur.to_json()))
        got = [resumed.sample_batch() for _ in range(6)]
    finally:
        resumed.close()
    for a, b in zip(upcoming, got):
        _batches_equal(a, b)


def test_mixture_rejects_shape_mismatch():
    small = _index_sampler(seed=1)
    big = make_index_sampler(SIZES, 3, 2, 3, batch_size=2, seed=2,
                             backend="python")
    with pytest.raises(ValueError, match="identically-shaped"):
        MixtureSampler(
            [("a", small), ("b", big)],
            MixtureSchedule.parse("a:1.0;b:1.0"),
        )


# --- faults + watchdog -----------------------------------------------------


def test_fault_spec_parse():
    f = FeedFaults.parse("slow:0.05,poison:30")
    assert f.slow_s == 0.05 and f.poison_at == 30 and f.stall_at is None
    assert not FeedFaults.parse("").active
    with pytest.raises(ValueError, match="unknown feed fault"):
        FeedFaults.parse("explode:1")


@pytest.mark.parametrize("depth", [0, 2])
def test_poisoned_batch_refused_and_reported(depth, tmp_path):
    """A poisoned batch must never reach the train step: the feed raises,
    and the kind='data' poison tick trips the watchdog."""
    from induction_network_on_fewrel_tpu.obs import HealthWatchdog

    logger = MetricsLogger(tmp_path, quiet=True)
    watchdog = HealthWatchdog(logger=logger)
    logger.add_hook(watchdog.observe_record)
    feed = PipelineFeed(
        _index_sampler(), prefetch_depth=depth,
        faults=FeedFaults.parse("poison:3"), logger=logger,
    )
    try:
        for _ in range(3):
            feed.sample_batch()
        with pytest.raises(FeedError, match="poisoned"):
            for _ in range(3):
                feed.sample_batch()
    finally:
        feed.close()
        logger.close()
    events = [e.event for e in watchdog.events]
    assert "feed_poisoned" in events
    # Depth 0 has no producer thread by design — the poison tick must not
    # mis-diagnose a dead producer (review finding).
    assert "feed_dead" not in events
    assert watchdog.tripped


def test_producer_stall_trips_watchdog():
    """Injectable-clock check of the generalized feed-stall detector."""
    from induction_network_on_fewrel_tpu.obs import HealthWatchdog

    wd = HealthWatchdog(queue_stall_s=5.0)
    # First sight of the counter arms nothing; the stall clock starts at
    # the first NON-advancing observation (103) — same convention as the
    # serving queue-stall detector.
    wd.observe_feed(produced=8, consumed=8, waiting=True, now=100.0)
    wd.observe_feed(produced=8, consumed=8, waiting=True, now=103.0)
    assert not wd.tripped
    wd.observe_feed(produced=8, consumed=8, waiting=True, now=106.5)
    assert not wd.tripped
    wd.observe_feed(produced=8, consumed=8, waiting=True, now=109.0)
    assert wd.tripped
    assert [e.event for e in wd.events] == ["feed_stall"]
    # An advancing producer re-arms.
    wd2 = HealthWatchdog(queue_stall_s=5.0)
    wd2.observe_feed(produced=8, consumed=8, waiting=True, now=100.0)
    wd2.observe_feed(produced=12, consumed=8, waiting=True, now=106.0)
    assert not wd2.tripped


def test_stalled_producer_emits_ticks_and_event(tmp_path):
    """End-to-end stall drill: a stall:N fault wedges the producer; the
    consumer's ticks surface it as a feed_stall critical event instead of
    a silent hang. stall_tick_s is shrunk so the test stays fast."""
    from induction_network_on_fewrel_tpu.obs import HealthWatchdog

    logger = MetricsLogger(tmp_path, quiet=True)
    watchdog = HealthWatchdog(logger=logger, queue_stall_s=0.3)
    logger.add_hook(watchdog.observe_record)
    feed = PipelineFeed(
        _index_sampler(), prefetch_depth=2,
        faults=FeedFaults.parse("stall:2"), logger=logger,
        stall_tick_s=0.1,
    )
    try:
        feed.sample_batch()
        feed.sample_batch()
        import threading

        # The third pop blocks forever (producer wedged); run it on a side
        # thread and wait for the watchdog to trip via the stall ticks.
        t = threading.Thread(target=lambda: _swallow(feed), daemon=True)
        t.start()
        for _ in range(100):
            if watchdog.tripped:
                break
            import time

            time.sleep(0.05)
        assert watchdog.tripped
        assert any(e.event == "feed_stall" for e in watchdog.events)
    finally:
        feed.close()
        logger.close()


def _swallow(feed):
    try:
        feed.sample_batch()
    except Exception:
        pass  # close() aborts the blocked pop — expected


def test_slow_fault_accumulates_stall_telemetry():
    feed = PipelineFeed(
        _index_sampler(), prefetch_depth=0,
        faults=FeedFaults.parse("slow:0.02"),
    )
    try:
        for _ in range(3):
            feed.sample_batch()
        stats = feed.drain_stats()
    finally:
        feed.close()
    assert stats["stall_s"] >= 0.05  # 3 x 20 ms inline delay
    assert stats["consumed"] == 3.0


# --- telemetry schema ------------------------------------------------------


def test_data_records_pass_schema_and_report(tmp_path):
    """kind='data' records are schema-legal and obs_report renders an
    input-pipeline section with the stall-fraction headline."""
    import os
    import sys

    tools = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
    )
    if tools not in sys.path:
        sys.path.insert(0, tools)
    from obs_report import check_schema, data_summary, load_records

    logger = MetricsLogger(tmp_path, quiet=True)
    feed = PipelineFeed(_index_sampler(), prefetch_depth=2, logger=logger)
    try:
        for _ in range(6):
            feed.sample_batch()
        logger.log(6, "data", **feed.drain_stats())
    finally:
        feed.close()
        logger.close()
    n, errors = check_schema(tmp_path / "metrics.jsonl")
    assert errors == [] and n >= 1
    summary = data_summary(load_records(tmp_path / "metrics.jsonl"))
    assert summary is not None
    assert summary["consumed"] == 6.0
    assert "feed_stall_frac" in summary
