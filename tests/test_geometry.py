"""Geometry plane (ISSUE 19): first-class (N, K) episode geometry.

Covers: the pure tier ladder (select_tier monotonicity/minimality,
spec grammar roundtrip, pad math, the bounded-program-count arithmetic),
tier-weighted rendezvous placement (per-tier home-set bound, tier-blind
equivalence), the grid-leg canary verdict (a candidate recovering the
flagship but regressing 10w1s is NOT published), and the served data
plane on a BRIEFLY-TRAINED model: padded-tier logits equal the exact-N
program on real rows (f32 bitwise, bf16/int8 in-band), pad classes never
win a verdict even at NOTA threshold 0, mixed-N tenant co-residency with
zero steady-state recompiles under the tiers x buckets x dtypes program
bound, warm-before-swap tier crossings, and the stats-NOTA-head refusal.
"""

import dataclasses
import json
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.config import (
    ExperimentConfig,
    resolve_geometry_policy,
)
from induction_network_on_fewrel_tpu.data import (
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.data.tokenizer import GloveTokenizer
from induction_network_on_fewrel_tpu.fleet.placement import FleetPlacement
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler
from induction_network_on_fewrel_tpu.serving.buckets import zero_batch
from induction_network_on_fewrel_tpu.serving.engine import InferenceEngine
from induction_network_on_fewrel_tpu.serving.geometry import (
    DEFAULT_TIERS,
    GRID,
    grid_key,
    pad_class_stack,
    parse_grid_key,
    parse_tiers,
    program_bound,
    select_tier,
    supports_tiering,
    tier_for,
    tiers_spec,
)
from induction_network_on_fewrel_tpu.train import FewShotTrainer
from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

_REPO = Path(__file__).resolve().parent.parent
_TOOLS = str(_REPO / "tools")
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

from scenarios import canary_verdict, floors_from_headline  # noqa: E402

# Tiny flagship-shaped config (the tests/test_serving.py world) + the
# training fields the parity fixture needs.
CFG = ExperimentConfig(
    model="induction", encoder="cnn", hidden_size=16,
    vocab_size=122, word_dim=8, pos_dim=2, max_length=16,
    induction_dim=8, ntn_slices=4, routing_iters=2,
    n=3, train_n=3, k=2, q=2, batch_size=2, lr=5e-3, val_step=0,
    device="cpu",
)


@pytest.fixture(scope="module")
def trained_world():
    """(vocab, tok, model, params, ds): ~150 optimizer steps on the
    synthetic corpus — real verdict margins, so tiered-vs-exact parity
    measures the padding, not tie-breaking noise."""
    vocab = make_synthetic_glove(vocab_size=CFG.vocab_size - 2,
                                 word_dim=CFG.word_dim)
    tok = GloveTokenizer(vocab, max_length=CFG.max_length)
    ds = make_synthetic_fewrel(
        num_relations=5, instances_per_relation=12,
        vocab_size=CFG.vocab_size - 2, seed=7,
    )
    model = build_model(CFG, glove_init=vocab.vectors)
    trainer = FewShotTrainer(
        model, CFG,
        EpisodeSampler(ds, tok, n=CFG.n, k=CFG.k, q=CFG.q,
                       batch_size=CFG.batch_size, seed=3),
        logger=MetricsLogger(quiet=True),
    )
    state = trainer.train(num_iters=150)
    return vocab, tok, model, state.params, ds


def _engine(trained_world, **kw):
    _, tok, model, params, ds = trained_world
    eng = InferenceEngine(
        model, params, CFG, tok, k=CFG.k,
        buckets=kw.pop("buckets", (1, 2, 4)),
        start=kw.pop("start", True), **kw,
    )
    return eng, ds


def _held_out(ds):
    return [i for r in ds.rel_names for i in ds.instances[r][CFG.k:]]


def _wait_for(cond, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return cond()


# --- tier ladder (pure) ---------------------------------------------------


def test_select_tier_monotone_minimal():
    """The tier-1 gate ISSUE 19 names: select_tier is monotone in n,
    always covers n, and is minimal over the ladder."""
    prev = 0
    for n in range(1, DEFAULT_TIERS[-1] + 1):
        t = select_tier(n, DEFAULT_TIERS)
        assert t in DEFAULT_TIERS
        assert t >= n, f"tier {t} cannot hold {n} classes"
        # Minimality: every smaller rung is too small for n.
        assert all(r < n for r in DEFAULT_TIERS if r < t)
        assert t >= prev, "select_tier must be monotone in n"
        prev = t
    with pytest.raises(ValueError):
        select_tier(0, DEFAULT_TIERS)
    with pytest.raises(ValueError):
        select_tier(DEFAULT_TIERS[-1] + 1, DEFAULT_TIERS)


def test_tier_for_overflow_and_off():
    # Exact-N passthrough when tiering is off...
    assert tier_for(7, None) == 7
    assert tier_for(7, ()) == 7
    # ...and graceful overflow past the ladder top (served exact-N).
    assert tier_for(DEFAULT_TIERS[-1] + 6, DEFAULT_TIERS) \
        == DEFAULT_TIERS[-1] + 6
    assert tier_for(5, DEFAULT_TIERS) == 8
    assert tier_for(8, DEFAULT_TIERS) == 8


def test_parse_tiers_grammar_roundtrip():
    assert parse_tiers("4,8,16,32,64") == (4, 8, 16, 32, 64)
    assert parse_tiers(" 4, 8 ") == (4, 8)
    for off in (None, "", "off", "none", "OFF"):
        assert parse_tiers(off) is None
    # Roundtrip through the spec spelling (the config/CLI knob).
    assert parse_tiers(tiers_spec(DEFAULT_TIERS)) == DEFAULT_TIERS
    assert tiers_spec(None) == "off"
    for bad in ("8,4", "4,4", "0,8", "-1", "4,x"):
        with pytest.raises(ValueError):
            parse_tiers(bad)


def test_pad_class_stack_zero_rows():
    rng = np.random.default_rng(0)
    stack = rng.normal(size=(5, 16)).astype(np.float32)
    padded = pad_class_stack(stack, 8)
    assert padded.shape == (8, 16)
    # Real rows bitwise-preserved; pad rows exactly zero.
    assert np.array_equal(padded[:5], stack)
    assert not padded[5:].any()
    # Already at tier: no copy games, just the same rows back.
    assert np.array_equal(pad_class_stack(stack, 5), stack)
    with pytest.raises(ValueError):
        pad_class_stack(stack, 4)


def test_program_bound_arithmetic():
    assert program_bound(DEFAULT_TIERS, (1, 2, 4), n_dtypes=1) == 15
    assert program_bound(DEFAULT_TIERS, (1, 2, 4), n_dtypes=2) == 30
    assert program_bound((4, 8), (1,), n_dtypes=3) == 6


def test_grid_key_roundtrip():
    assert grid_key(5, 1) == "5w1s"
    assert [grid_key(n, k) for n, k in GRID] \
        == ["5w1s", "5w5s", "10w1s", "10w5s"]
    assert parse_grid_key("5w1s") == (5, 1)
    assert parse_grid_key("grid_10w5s") == (10, 5)
    assert parse_grid_key("in_domain") is None
    assert parse_grid_key("grid_w1s") is None


def test_resolve_geometry_policy_one_home():
    base = dataclasses.replace(CFG, geometry_tiers="4,8",
                               geometry_tier_spread=2)
    # None inherits the served config; an explicit knob overrides it.
    assert resolve_geometry_policy(
        type("K", (), {"geometry_tiers": None})(), base=base
    ) == {"tiers": (4, 8), "tier_spread": 2}
    assert resolve_geometry_policy(
        type("K", (), {"geometry_tiers": "off"})(), base=base
    )["tiers"] is None
    assert resolve_geometry_policy(
        type("K", (), {"geometry_tiers": "16,32"})(), base=base
    )["tiers"] == (16, 32)


# --- tier-weighted placement ----------------------------------------------


def test_tier_weighted_placement_home_set_bound():
    """With tier_spread=s, every tenant of one N-tier lands on at most s
    replicas (the tier's rendezvous home set), and tier-blind placement
    is unchanged from the plain rendezvous map."""
    fp = FleetPlacement([f"replica-{i}" for i in range(8)])
    tenants = [f"tenant-{i}" for i in range(48)]
    tier_by_tenant = {t: DEFAULT_TIERS[i % 3] for i, t in enumerate(tenants)}

    owners = fp.owners(tenants, tier_of=tier_by_tenant.get, tier_spread=2)
    by_tier = {}
    for t, owner in owners.items():
        assert owner is not None
        by_tier.setdefault(tier_by_tenant[t], set()).add(owner)
    for tier, homes in by_tier.items():
        assert len(homes) <= 2, f"tier {tier} spread over {homes}"
    # Same map from the single-tenant spelling.
    for t in tenants:
        assert fp.place(t, tier=tier_by_tenant[t], tier_spread=2) \
            == owners[t]

    # Tier-blind (tier_of=None / tier=None / spread=0) == plain map.
    blind = fp.owners(tenants)
    assert fp.owners(tenants, tier_of=lambda t: None, tier_spread=2) \
        == blind
    assert fp.owners(tenants, tier_of=tier_by_tenant.get,
                     tier_spread=0) == blind
    for t in tenants[:8]:
        assert fp.place(t) == blind[t]


# --- grid canary verdict --------------------------------------------------


def test_canary_grid_regression_blocks_publish():
    """ISSUE 19's adaptation gate: a candidate recovering the flagship
    5w5s leg but regressing 10w1s must NOT publish."""
    headline = {
        "in_domain_accuracy": 0.90,
        "grid": {"5w5s": 0.90, "10w1s": 0.70},
    }
    floors = floors_from_headline(headline, band={"accuracy_abs": 0.05})
    assert floors["grid_10w1s"] == 0.65

    regressed = canary_verdict(
        {
            "in_domain_accuracy": {"accuracy": 0.95},
            "grid_5w5s": {"accuracy": 0.92},
            "grid_10w1s": {"accuracy": 0.20},
        },
        floors,
    )
    assert not regressed["passed"]
    assert any("grid_10w1s" in f for f in regressed["failures"])

    healthy = canary_verdict(
        {
            "in_domain_accuracy": {"accuracy": 0.95},
            "grid_5w5s": {"accuracy": 0.92},
            "grid_10w1s": {"accuracy": 0.71},
        },
        floors,
    )
    assert healthy["passed"], healthy["failures"]

    # A floor whose leg was never evaluated fails loudly, not silently.
    missing = canary_verdict({"in_domain": {"accuracy": 0.95}}, floors)
    assert not missing["passed"]
    assert any("no evaluated leg" in f for f in missing["failures"])


# --- served data plane: parity --------------------------------------------


def test_tiered_parity_f32_bitwise(trained_world):
    """Padded-tier logits equal the exact-N program on real rows,
    bitwise: the class axis is a batch axis in the NTN einsums, so zero
    pad rows cannot perturb real-row arithmetic."""
    tiered, ds = _engine(trained_world, geometry_tiers="4,8,16,32,64")
    exact, _ = _engine(trained_world, geometry_tiers="off")
    try:
        for eng in (tiered, exact):
            eng.register_dataset(ds)
            eng.warmup()
        assert tiered.registry.snapshot().n_tier == 8
        assert exact.registry.snapshot().n_tier == len(ds.rel_names)
        for inst in _held_out(ds):
            vt = tiered.classify(inst)
            ve = exact.classify(inst)
            assert set(vt["logits"]) == set(ve["logits"])
            for name, logit in vt["logits"].items():
                assert logit == ve["logits"][name], (
                    f"{name}: tiered {logit!r} != exact "
                    f"{ve['logits'][name]!r}"
                )
            assert vt["label"] == ve["label"]
            assert vt["nota"] == ve["nota"]
    finally:
        tiered.close()
        exact.close()


@pytest.mark.parametrize("dtype", ["bf16", "int8"])
def test_tiered_parity_quantized_in_band(trained_world, dtype):
    """Same pin for the quantized residents: zero pad rows leave the
    int8 max-abs scale (and every bf16 real row) untouched, so tiered
    vs exact-N stays inside the quant parity band."""
    tiered, ds = _engine(trained_world, geometry_tiers="4,8,16,32,64",
                         resident_dtype=dtype)
    exact, _ = _engine(trained_world, geometry_tiers="off",
                       resident_dtype=dtype)
    try:
        for eng in (tiered, exact):
            eng.register_dataset(ds)
            eng.warmup()
        agree, delta = 0, 0.0
        queries = _held_out(ds)
        for inst in queries:
            vt = tiered.classify(inst)
            ve = exact.classify(inst)
            agree += vt["label"] == ve["label"]
            delta = max(delta, max(
                abs(vt["logits"][name] - ve["logits"][name])
                for name in ve["logits"]
            ))
        assert agree >= 0.99 * len(queries)
        assert delta <= 0.25, f"{dtype} tiered-vs-exact drift {delta}"
    finally:
        tiered.close()
        exact.close()


# --- served data plane: pads and NOTA -------------------------------------


def test_pad_classes_never_win_verdict(trained_world):
    """Even at NOTA threshold 0 (the most NOTA-favorable calibration),
    a verdict is always a REAL class or no_relation — pad columns are
    sliced out before argmax and excluded from the logits dict."""
    tiered, ds = _engine(trained_world, geometry_tiers="4,8,16,32,64")
    try:
        tiered.register_dataset(ds)
        tiered.warmup()
        tiered.set_nota_threshold(0.0)
        real = set(ds.rel_names)
        for inst in _held_out(ds):
            v = tiered.classify(inst)
            assert v["label"] in real | {"no_relation"}
            assert -1 <= v["class_index"] < len(ds.rel_names)
            # Logits expose exactly the real classes (+ the NOTA row
            # when the head exists) — never a pad column.
            assert set(v["logits"]) - {"no_relation"} == real
    finally:
        tiered.close()


def test_pad_never_wins_with_forced_nota_head(trained_world):
    """The adversarial spelling: a scalar-NOTA checkpoint whose NOTA
    logit is forced sky-high. Under tiering the NOTA row rides BEHIND
    the pad rows (row[-1]), so the verdict must still be no_relation —
    a pad column absorbing the argmax would break this."""
    vocab, tok, _, _, ds = trained_world
    cfg = CFG.replace(na_rate=1)
    model = build_model(cfg, glove_init=vocab.vectors)
    params = model.init(
        jax.random.key(0),
        zero_batch(cfg.max_length, (1, cfg.n, cfg.k)),
        zero_batch(cfg.max_length, (1, 2)),
    )
    inner = dict(params["params"])
    inner["nota_logit"] = jnp.full((1,), 50.0)
    params = {"params": inner}
    eng = InferenceEngine(model, params, cfg, tok, k=cfg.k,
                          buckets=(1, 2), start=False,
                          geometry_tiers="4,8,16,32,64")
    try:
        eng.register_dataset(ds)
        assert eng.registry.snapshot().n_tier == 8
        fut = eng.submit(ds.instances[ds.rel_names[0]][-1], deadline_s=30.0)
        eng.batcher.drain_once()
        v = fut.result(timeout=10.0)
        assert v["nota"] and v["label"] == "no_relation"
        assert v["class_index"] == -1
        assert set(v["logits"]) == set(ds.rel_names) | {"no_relation"}
    finally:
        eng.close()


def test_stats_nota_head_refuses_tiering(trained_world):
    """nota_head='stats' reads class-axis statistics — pad rows WOULD
    shift its calibration, so the registry must force exact-N."""
    vocab, tok, _, _, _ = trained_world
    cfg = CFG.replace(na_rate=1, nota_head="stats")
    model = build_model(cfg, glove_init=vocab.vectors)
    assert not supports_tiering(model)
    params = model.init(
        jax.random.key(0),
        zero_batch(cfg.max_length, (1, cfg.n, cfg.k)),
        zero_batch(cfg.max_length, (1, 2)),
    )
    eng = InferenceEngine(model, params, cfg, tok, k=cfg.k,
                          buckets=(1, 2), start=False,
                          geometry_tiers="4,8,16,32,64")
    try:
        assert eng.registry.tiers is None
        assert eng.tiers is None
    finally:
        eng.close()


# --- served data plane: recompiles and the program bound ------------------


def test_mixed_n_soak_zero_recompiles_bounded(trained_world):
    """Mixed-N tenants co-resident on one engine: zero steady-state
    recompiles through serving, tier crossings, and a dtype flip, with
    the compiled-program count held under tiers x buckets x dtypes."""
    eng, ds = _engine(trained_world, geometry_tiers="4,8,16,32,64")
    try:
        worlds = {}
        for t, n in (("small", 3), ("mid", 5), ("wide", 14)):
            tds = make_synthetic_fewrel(
                num_relations=n, instances_per_relation=CFG.k + 3,
                vocab_size=CFG.vocab_size - 2, seed=100 + n,
            )
            eng.register_dataset(tds, tenant=t)
            worlds[t] = tds
        assert {t: eng.registry.snapshot(t).n_tier for t in worlds} \
            == {"small": 4, "mid": 8, "wide": 16}
        eng.warmup()

        def soak():
            for t, tds in worlds.items():
                for r in tds.rel_names:
                    v = eng.classify(tds.instances[r][-1], tenant=t)
                    assert v["label"] in tds.rel_names \
                        or v["label"] == "no_relation"

        soak()
        # Tier crossing mid-soak: "mid" grows 5 -> 9 classes (tier
        # 8 -> 16). Warm-before-swap compiles the 16-tier programs
        # BEFORE the registry publishes, so nothing lands on the
        # query path.
        grown = make_synthetic_fewrel(
            num_relations=9, instances_per_relation=CFG.k + 3,
            vocab_size=CFG.vocab_size - 2, seed=105,
        )
        eng.register_dataset(grown, tenant="mid")
        worlds["mid"] = grown
        assert eng.registry.snapshot("mid").n_tier == 16
        soak()
        # Dtype flip mid-soak (warm-first too).
        eng.set_resident_dtype("small", "bf16")
        soak()

        snap = eng.stats.snapshot()
        assert snap["steady_recompiles"] == 0, snap
        bound = program_bound(DEFAULT_TIERS, (1, 2, 4), n_dtypes=2)
        assert len(eng.programs._exe) <= bound, (
            f"{len(eng.programs._exe)} programs exceed bound {bound}"
        )
    finally:
        eng.close()


def test_tier_crossing_reregistration_no_steady_recompile(trained_world):
    """The ISSUE's named drill: a tenant registering past its tier
    boundary (here 3 -> 5 classes, tier 4 -> 8) migrates without a
    steady-state recompile."""
    eng, ds = _engine(trained_world, geometry_tiers="4,8,16,32,64")
    try:
        eng.register_dataset(ds, max_classes=3)
        assert eng.registry.snapshot().n_tier == 4
        eng.warmup()
        for inst in _held_out(ds)[:4]:
            eng.classify(inst)
        eng.register_dataset(ds)  # now all 5 relations: crosses to 8
        assert eng.registry.snapshot().n_tier == 8
        for inst in _held_out(ds):
            eng.classify(inst)
        assert eng.stats.snapshot()["steady_recompiles"] == 0
    finally:
        eng.close()


# --- committed GEOM artifact ----------------------------------------------


def test_geom_artifact_gate():
    """The committed tiered-vs-exact A/B holds its zero bands, the
    program bound, and carries the paper grid with CIs."""
    data = json.loads((_REPO / "GEOM_r01.json").read_text())
    assert data["passed"] is True and not data["check_failures"]
    assert all(v == 0 for v in data["zero_bands"].values())
    arms = data["arms"]
    assert set(arms) == {"tiered", "exact"}
    assert arms["tiered"]["steady_recompiles"] == 0
    assert arms["tiered"]["program_cache_keys"] \
        <= data["program_bound_tiered"]
    # The tax the A/B documents: exact-N pays crossing recompiles and
    # holds MORE distinct programs than the tier ladder.
    assert arms["exact"]["steady_recompiles"] >= 1
    assert arms["tiered"]["program_cache_keys"] \
        < arms["exact"]["program_cache_keys"]
    for arm in arms.values():
        assert arm["parity_max_delta"] <= arm["parity_tol"]
        flip = arm["dtype_flip"]
        assert flip["parity_max_delta"] <= flip["parity_tol"]
    assert data["grid"], "grid legs missing from GEOM artifact"
    for key, leg in data["grid"].items():
        assert parse_grid_key(key) == (leg["n"], leg["k"])
        assert 0.0 <= leg["accuracy"] <= 1.0
        assert leg["acc_ci95"] >= 0.0
