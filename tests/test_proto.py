"""Prototypical-network sibling model: shapes, metric math, NOTA, training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    GloveTokenizer,
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.models.build import batch_to_model_inputs
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler

L = 16
BASE = ExperimentConfig(
    model="proto", encoder="cnn", n=4, k=2, q=3, batch_size=2, max_length=L,
    vocab_size=302, compute_dtype="float32",
)


@pytest.fixture(scope="module")
def episode():
    vocab = make_synthetic_glove(vocab_size=300)
    ds = make_synthetic_fewrel(num_relations=8, instances_per_relation=10, vocab_size=300)
    tok = GloveTokenizer(vocab, max_length=L)
    s = EpisodeSampler(ds, tok, n=4, k=2, q=3, batch_size=2, seed=0)
    return vocab, batch_to_model_inputs(s.sample_batch())


@pytest.mark.parametrize("metric", ["euclid", "dot"])
def test_proto_forward_shapes(episode, metric):
    vocab, (sup, qry, label) = episode
    model = build_model(BASE.replace(proto_metric=metric), glove_init=vocab.vectors)
    params = model.init(jax.random.key(0), sup, qry)
    logits = model.apply(params, sup, qry)
    assert logits.shape == (2, 12, 4)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_proto_euclid_matches_bruteforce(episode):
    """-‖q-p‖² via the matmul expansion == the direct loop computation."""
    vocab, (sup, qry, _) = episode
    model = build_model(BASE, glove_init=vocab.vectors)
    params = model.init(jax.random.key(0), sup, qry)
    logits = np.asarray(model.apply(params, sup, qry))

    # Recompute from the encoded vectors directly.
    bound = model.bind(params)
    sup_enc, qry_enc = bound.encode_episode(
        {k: jnp.asarray(v) for k, v in sup.items()},
        {k: jnp.asarray(v) for k, v in qry.items()},
    )
    proto = np.asarray(jnp.mean(sup_enc, axis=2))
    q = np.asarray(qry_enc)
    want = np.stack(
        [
            -np.sum((q[b, :, None, :] - proto[b, None, :, :]) ** 2, axis=-1)
            for b in range(q.shape[0])
        ]
    )
    np.testing.assert_allclose(logits, want, rtol=2e-4, atol=2e-4)


def test_proto_nota_head(episode):
    vocab, (sup, qry, _) = episode
    cfg = BASE.replace(na_rate=1)
    model = build_model(cfg, glove_init=vocab.vectors)
    params = model.init(jax.random.key(0), sup, qry)
    logits = model.apply(params, sup, qry)
    assert logits.shape == (2, 12, 5)  # N+1 classes


def test_proto_trains_end_to_end():
    """A few steps of training reduce loss (overfit smoke on tiny data)."""
    from induction_network_on_fewrel_tpu.train.steps import init_state, make_train_step

    cfg = BASE.replace(n=2, k=2, q=2, batch_size=2, loss="ce", lr=5e-2)
    vocab = make_synthetic_glove(vocab_size=300)
    ds = make_synthetic_fewrel(num_relations=4, instances_per_relation=8, vocab_size=300)
    tok = GloveTokenizer(vocab, max_length=L)
    sampler = EpisodeSampler(ds, tok, n=2, k=2, q=2, batch_size=2, seed=0)
    model = build_model(cfg, glove_init=vocab.vectors)
    sup, qry, label = batch_to_model_inputs(sampler.sample_batch())
    state = init_state(model, cfg, sup, qry)
    step = make_train_step(model, cfg)
    first = None
    for _ in range(30):
        state, metrics = step(state, sup, qry, label)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first
