"""FewRel 2.0 adversarial domain adaptation: gradient reversal + DANN step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    GloveTokenizer,
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.models.adversarial import DomainDiscriminator
from induction_network_on_fewrel_tpu.models.build import (
    batch_to_model_inputs,
    encoder_output_dim,
)
from induction_network_on_fewrel_tpu.ops import gradient_reversal
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler, InstanceSampler
from induction_network_on_fewrel_tpu.train.steps import (
    init_disc_state,
    init_state,
    make_adv_train_step,
)

L = 16
CFG = ExperimentConfig(
    model="proto", encoder="cnn", train_n=3, n=3, k=2, q=2, batch_size=2,
    max_length=L, vocab_size=302, compute_dtype="float32", hidden_size=64,
    loss="ce", lr=3e-3, adv=True, adv_lambda=0.5, adv_dis_hidden=32,
    adv_batch=8,
)


def test_gradient_reversal_vjp():
    """Forward identity; backward -scale * g."""
    x = jnp.arange(6.0).reshape(2, 3)
    y, vjp = jax.vjp(lambda t: gradient_reversal(t, 0.25), x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x))
    (g,) = vjp(jnp.ones_like(x))
    np.testing.assert_allclose(np.asarray(g), -0.25 * np.ones((2, 3)))


def test_discriminator_shapes():
    disc = DomainDiscriminator(hidden=32)
    params = disc.init(jax.random.key(0), jnp.zeros((4, 64)))
    out = disc.apply(params, jnp.ones((7, 64)))
    assert out.shape == (7, 2) and out.dtype == jnp.float32


def _pieces():
    vocab = make_synthetic_glove(vocab_size=300)
    src_ds = make_synthetic_fewrel(num_relations=6, instances_per_relation=10,
                                   vocab_size=300, seed=0)
    tgt_ds = make_synthetic_fewrel(num_relations=6, instances_per_relation=10,
                                   vocab_size=300, seed=97)
    tok = GloveTokenizer(vocab, max_length=L)
    ep = EpisodeSampler(src_ds, tok, n=3, k=2, q=2, batch_size=2, seed=0)
    src = InstanceSampler(src_ds, tok, batch_size=8, seed=1)
    tgt = InstanceSampler(tgt_ds, tok, batch_size=8, seed=2)
    model = build_model(CFG, glove_init=vocab.vectors)
    return model, ep, src, tgt


def test_adv_step_trains_and_reports_domain_metrics():
    model, ep, src, tgt = _pieces()
    disc = DomainDiscriminator(hidden=CFG.adv_dis_hidden)
    sup, qry, label = batch_to_model_inputs(ep.sample_batch())
    state = init_state(model, CFG, sup, qry)
    disc_state = init_disc_state(disc, CFG, encoder_output_dim(CFG))
    step = make_adv_train_step(model, disc, CFG)

    first = None
    for _ in range(25):
        s, t = src.sample_batch()._asdict(), tgt.sample_batch()._asdict()
        state, disc_state, metrics = step(state, disc_state, sup, qry, label, s, t)
        if first is None:
            first = float(metrics["loss"])
    m = jax.device_get(metrics)
    assert set(m) >= {"loss", "accuracy", "domain_loss", "domain_accuracy"}
    assert float(m["loss"]) < first           # few-shot objective advanced
    assert np.isfinite(float(m["domain_loss"]))


def test_disc_state_stays_out_of_model_state():
    """The discriminator has its own TrainState; the model state's param
    tree is identical with and without adversarial training (checkpoint
    compatibility: adv checkpoints restore in plain eval)."""
    model, ep, *_ = _pieces()
    sup, qry, _ = batch_to_model_inputs(ep.sample_batch())
    plain = init_state(model, CFG.replace(adv=False), sup, qry)
    adv = init_state(model, CFG, sup, qry)
    assert jax.tree_util.tree_structure(plain.params) == jax.tree_util.tree_structure(adv.params)


@pytest.mark.slow
def test_sharded_adv_step_matches_single_device():
    """GSPMD DANN step on a dp=4 mesh == the single-device step (same
    inputs, same init): loss/metrics equal, updated params equal."""
    from induction_network_on_fewrel_tpu.parallel import make_mesh
    from induction_network_on_fewrel_tpu.parallel.sharding import (
        make_sharded_adv_train_step,
        shard_state,
    )

    cfg = CFG.replace(batch_size=4, adv_batch=8)
    vocab = make_synthetic_glove(vocab_size=300)
    src_ds = make_synthetic_fewrel(num_relations=6, instances_per_relation=10,
                                   vocab_size=300, seed=0)
    tgt_ds = make_synthetic_fewrel(num_relations=6, instances_per_relation=10,
                                   vocab_size=300, seed=97)
    tok = GloveTokenizer(vocab, max_length=L)
    ep = EpisodeSampler(src_ds, tok, n=3, k=2, q=2, batch_size=4, seed=0)
    src = InstanceSampler(src_ds, tok, batch_size=8, seed=1)
    tgt = InstanceSampler(tgt_ds, tok, batch_size=8, seed=2)
    model = build_model(cfg, glove_init=vocab.vectors)
    disc = DomainDiscriminator(hidden=cfg.adv_dis_hidden)

    sup, qry, label = batch_to_model_inputs(ep.sample_batch())
    s, t = src.sample_batch()._asdict(), tgt.sample_batch()._asdict()

    ref_state = init_state(model, cfg, sup, qry)
    ref_disc = init_disc_state(disc, cfg, encoder_output_dim(cfg))
    ref_step = make_adv_train_step(model, disc, cfg)
    ref_state, ref_disc, ref_m = ref_step(ref_state, ref_disc, sup, qry, label, s, t)

    mesh = make_mesh(dp=4, tp=1)
    st = shard_state(init_state(model, cfg, sup, qry), mesh)
    dst = shard_state(init_disc_state(disc, cfg, encoder_output_dim(cfg)), mesh)
    step = make_sharded_adv_train_step(model, disc, cfg, mesh, st, dst)
    st, dst, m = step(st, dst, sup, qry, label, s, t)

    for k in ref_m:
        np.testing.assert_allclose(float(m[k]), float(ref_m[k]), atol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        jax.device_get(st.params), jax.device_get(ref_state.params),
    )


def test_adv_multi_step_matches_sequential():
    """Fused DANN scan == S sequential DANN steps on the same batches."""
    from induction_network_on_fewrel_tpu.train.steps import (
        make_adv_multi_train_step,
    )

    model, ep, src, tgt = _pieces()
    disc = DomainDiscriminator(hidden=CFG.adv_dis_hidden)
    sup, qry, lab = batch_to_model_inputs(ep.sample_batch())
    state_a = init_state(model, CFG, sup, qry)
    disc_a = init_disc_state(disc, CFG, encoder_output_dim(CFG))
    copy = lambda t: jax.tree.map(lambda x: jnp.array(x, copy=True), t)
    state_b, disc_b = copy(state_a), copy(disc_a)

    batches = [
        (*batch_to_model_inputs(ep.sample_batch()),
         src.sample_batch()._asdict(), tgt.sample_batch()._asdict())
        for _ in range(3)
    ]
    step = make_adv_train_step(model, disc, CFG)
    for b in batches:
        state_a, disc_a, m_a = step(state_a, disc_a, *b)

    multi = make_adv_multi_train_step(model, disc, CFG)
    stacked = jax.tree.map(lambda *xs: np.stack(xs), *batches)
    state_b, disc_b, m_s = multi(state_b, disc_b, *stacked)

    assert np.asarray(m_s["loss"]).shape == (3,)
    np.testing.assert_allclose(
        float(np.asarray(m_s["loss"])[-1]), float(m_a["loss"]), rtol=1e-5
    )
    for a, b in ((state_a, state_b), (disc_a, disc_b)):
        jax.tree.map(
            lambda x, y: np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-5, atol=1e-6
            ),
            a.params, b.params,
        )


def test_adv_with_embed_optimizer_sgd_initializes():
    """The discriminator has no word-embedding leaf; its TrainState must
    init with the plain optimizer chain even when --embed_optimizer splits
    the main model's table off (regression: label_fn raised at startup)."""
    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.models.adversarial import DomainDiscriminator
    from induction_network_on_fewrel_tpu.train.steps import init_disc_state

    cfg = ExperimentConfig(embed_optimizer="sgd", adv=True)
    disc = DomainDiscriminator(hidden=32)
    state = init_disc_state(disc, cfg, feat_dim=16)
    assert state is not None
