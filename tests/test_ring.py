"""Ring attention (parallel/ring.py) + transformer encoder (long-context).

Exactness is the whole point: ring attention over the sp mesh axis must
equal dense attention bit-for-tolerance, forward AND gradient, including
key-padding masks — on the 8-virtual-device CPU mesh from conftest.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    GloveTokenizer,
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.models.build import batch_to_model_inputs
from induction_network_on_fewrel_tpu.parallel import make_mesh
from induction_network_on_fewrel_tpu.parallel.ring import (
    dense_attention,
    make_ring_attention,
)
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler

L = 16


def _qkvm(key, B=2, H=4, Lq=16, D=8, pad=3):
    ks = jax.random.split(key, 3)
    q, k, v = (jax.random.normal(kk, (B, H, Lq, D), jnp.float32) for kk in ks)
    mask = np.ones((B, Lq), np.float32)
    mask[:, Lq - pad:] = 0.0  # padded key positions
    return q, k, v, jnp.asarray(mask)


@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_matches_dense_forward(sp):
    mesh = make_mesh(dp=1, tp=1, sp=sp)
    ring = make_ring_attention(mesh)
    q, k, v, mask = _qkvm(jax.random.key(0))
    got = jax.jit(ring)(q, k, v, mask)
    want = dense_attention(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_ring_matches_dense_gradient():
    mesh = make_mesh(dp=1, tp=1, sp=4)
    ring = make_ring_attention(mesh)
    q, k, v, mask = _qkvm(jax.random.key(1))

    def loss(fn, q, k, v):
        out = fn(q, k, v, mask)
        # weighted sum -> nontrivial cotangents
        w = jnp.arange(out.size, dtype=jnp.float32).reshape(out.shape) / out.size
        return jnp.sum(out * w)

    g_ring = jax.grad(lambda *a: loss(ring, *a), argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(lambda *a: loss(dense_attention, *a), argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd), atol=1e-5)


def test_ring_with_dp_batch_axis():
    """dp x sp composition: independent rings per dp group."""
    mesh = make_mesh(dp=2, tp=1, sp=4)
    ring = make_ring_attention(mesh, batch_axis="dp")
    q, k, v, mask = _qkvm(jax.random.key(2), B=4)
    got = jax.jit(ring)(q, k, v, mask)
    want = dense_attention(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


# --- transformer encoder ----------------------------------------------------

TFM = ExperimentConfig(
    model="proto", encoder="transformer", train_n=3, n=3, k=2, q=2,
    batch_size=2, max_length=L, vocab_size=302, compute_dtype="float32",
    tfm_layers=2, tfm_model=64, tfm_heads=4, tfm_ff=128, loss="ce",
)


@pytest.fixture(scope="module")
def episode():
    vocab = make_synthetic_glove(vocab_size=300)
    ds = make_synthetic_fewrel(num_relations=6, instances_per_relation=10, vocab_size=300)
    tok = GloveTokenizer(vocab, max_length=L)
    s = EpisodeSampler(ds, tok, n=3, k=2, q=2, batch_size=2, seed=0)
    return vocab, batch_to_model_inputs(s.sample_batch())


def test_transformer_encoder_shapes(episode):
    vocab, (sup, qry, _) = episode
    model = build_model(TFM, glove_init=vocab.vectors)
    params = model.init(jax.random.key(0), sup, qry)
    logits = model.apply(params, sup, qry)
    assert logits.shape == (2, 6, 3)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_transformer_ring_equals_dense_end_to_end(episode):
    """The SAME params, dense single-device vs ring-under-sp: identical
    logits. Sequence parallelism must be invisible to the model."""
    vocab, (sup, qry, _) = episode
    dense_model = build_model(TFM, glove_init=vocab.vectors)
    params = dense_model.init(jax.random.key(0), sup, qry)
    want = dense_model.apply(params, sup, qry)

    mesh = make_mesh(dp=1, tp=1, sp=8)
    ring_model = build_model(
        TFM, glove_init=vocab.vectors, attn_impl=make_ring_attention(mesh)
    )
    got = ring_model.apply(params, sup, qry)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)


def test_transformer_trains_end_to_end():
    from induction_network_on_fewrel_tpu.train.steps import init_state, make_train_step

    cfg = TFM.replace(lr=1e-3)
    vocab = make_synthetic_glove(vocab_size=300)
    ds = make_synthetic_fewrel(num_relations=6, instances_per_relation=10, vocab_size=300)
    tok = GloveTokenizer(vocab, max_length=L)
    sampler = EpisodeSampler(ds, tok, n=3, k=2, q=2, batch_size=2, seed=0)
    model = build_model(cfg, glove_init=vocab.vectors)
    sup, qry, label = batch_to_model_inputs(sampler.sample_batch())
    state = init_state(model, cfg, sup, qry)
    step = make_train_step(model, cfg)
    first = None
    for _ in range(30):
        state, metrics = step(state, sup, qry, label)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first


def test_sp_train_step_runs_sharded(episode):
    """Full GSPMD train step with the ring-attention transformer on a
    (dp=2, sp=4) mesh: compiles, executes, finite loss."""
    from induction_network_on_fewrel_tpu.parallel.sharding import (
        make_sharded_train_step,
    )
    from induction_network_on_fewrel_tpu.train.steps import init_state

    vocab, (sup, qry, label) = episode
    mesh = make_mesh(dp=2, tp=1, sp=4)
    model = build_model(
        TFM, glove_init=vocab.vectors,
        attn_impl=make_ring_attention(mesh, batch_axis=None),
    )
    state = init_state(model, TFM, sup, qry)
    step = make_sharded_train_step(model, TFM, mesh, state)
    state, metrics = step(state, sup, qry, label)
    assert np.isfinite(float(metrics["loss"]))
