"""Fused self-attention kernel (ops/attn.py): interpret-mode Pallas vs the
two-pass XLA reference — forward, backward, masking edge cases, and the
encoder-level backend equivalence (same params -> same outputs, so
checkpoints are attn_backend-interchangeable like lstm_backend).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.ops.attn import masked_selfattn_tm

L, M, D, A = 7, 10, 12, 8  # deliberately NOT tile-aligned (exercises padding)


@pytest.fixture(scope="module")
def inputs():
    rng = np.random.default_rng(0)
    H = jnp.asarray(rng.normal(size=(L, M, D)).astype(np.float32))
    mask = (rng.random((M, L)) > 0.25).astype(np.float32)
    mask[:, 0] = 1.0
    mask[3] = 0.0  # one fully-masked row: output and grads must be zero
    w1 = jnp.asarray((rng.normal(size=(D, A)) / np.sqrt(D)).astype(np.float32))
    w2 = jnp.asarray((rng.normal(size=(A, 1)) / np.sqrt(A)).astype(np.float32))
    return H, jnp.asarray(mask), w1, w2


def test_forward_parity(inputs):
    H, mask, w1, w2 = inputs
    ref = masked_selfattn_tm(H, mask, w1, w2, backend="xla")
    out = masked_selfattn_tm(H, mask, w1, w2, backend="interpret")
    assert out.shape == (M, D)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)
    # Fully-masked row: EXACT zeros (the online normalizer is 0 there).
    assert float(jnp.abs(out[3]).max()) == 0.0


def test_backward_parity(inputs):
    H, mask, w1, w2 = inputs
    ct = jnp.asarray(
        np.random.default_rng(1).normal(size=(M, D)).astype(np.float32)
    )

    def loss(backend):
        return lambda H_, w1_, w2_: jnp.sum(
            masked_selfattn_tm(H_, mask, w1_, w2_, backend=backend) * ct
        )

    g_ref = jax.grad(loss("xla"), argnums=(0, 1, 2))(H, w1, w2)
    g_pl = jax.grad(loss("interpret"), argnums=(0, 1, 2))(H, w1, w2)
    for name, a, b in zip(("dH", "dw1", "dw2"), g_ref, g_pl):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=1e-5, err_msg=name
        )
    # Masked row's dH must be exactly zero.
    assert float(jnp.abs(g_pl[0][:, 3]).max()) == 0.0


def test_bf16_io_close_to_f32(inputs):
    H, mask, w1, w2 = inputs
    out32 = masked_selfattn_tm(H, mask, w1, w2, backend="interpret")
    out16 = masked_selfattn_tm(
        H.astype(jnp.bfloat16), mask, w1, w2, backend="interpret"
    )
    assert out16.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out16, np.float32), np.asarray(out32), rtol=0.05, atol=0.05
    )


def test_unknown_backend(inputs):
    H, mask, w1, w2 = inputs
    with pytest.raises(ValueError):
        masked_selfattn_tm(H, mask, w1, w2, backend="cuda")


# --- recompute-in-backward hybrid (--remat_attn, round 6) ------------------


def test_xla_remat_forward_identical_to_xla(inputs):
    """The remat forward IS the two-pass form (the primal runs
    _attn_reference verbatim): f32 outputs are bitwise-equal, so flipping
    --remat_attn cannot move eval metrics at all."""
    H, mask, w1, w2 = inputs
    ref = masked_selfattn_tm(H, mask, w1, w2, backend="xla")
    out = masked_selfattn_tm(H, mask, w1, w2, backend="xla_remat_interpret")
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    assert float(jnp.abs(out[3]).max()) == 0.0  # fully-masked row


def test_xla_remat_backward_parity_f32(inputs):
    """Gradients of the remat path (kernel backward recomputing the tanh
    projection + attention weights from stats) match the two-pass XLA
    autodiff at 1e-5 — the same bar the full-kernel parity test holds.
    Masked rows keep exactly-zero cotangents."""
    H, mask, w1, w2 = inputs
    ct = jnp.asarray(
        np.random.default_rng(2).normal(size=(M, D)).astype(np.float32)
    )

    def loss(backend):
        return lambda H_, w1_, w2_: jnp.sum(
            masked_selfattn_tm(H_, mask, w1_, w2_, backend=backend) * ct
        )

    g_ref = jax.grad(loss("xla"), argnums=(0, 1, 2))(H, w1, w2)
    g_rm = jax.grad(loss("xla_remat_interpret"), argnums=(0, 1, 2))(H, w1, w2)
    for name, a, b in zip(("dH", "dw1", "dw2"), g_ref, g_rm):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=1e-5, err_msg=name
        )
    assert float(jnp.abs(g_rm[0][:, 3]).max()) == 0.0


def test_xla_remat_backward_bf16_band(inputs):
    """bf16 inputs: remat gradients stay within the documented Pallas
    band of the f32 reference (the kernel recomputes in f32 from
    bf16-rounded H — same contract as --attn_backend pallas)."""
    H, mask, w1, w2 = inputs
    ct = jnp.asarray(
        np.random.default_rng(4).normal(size=(M, D)).astype(np.float32)
    )

    def loss(backend, h):
        return lambda w1_, w2_: jnp.sum(
            masked_selfattn_tm(h, mask, w1_, w2_, backend=backend) * ct
        )

    g_ref = jax.grad(loss("xla", H), argnums=(0, 1))(w1, w2)
    g_rm = jax.grad(
        loss("xla_remat_interpret", H.astype(jnp.bfloat16)), argnums=(0, 1)
    )(w1, w2)
    for name, a, b in zip(("dw1", "dw2"), g_ref, g_rm):
        np.testing.assert_allclose(
            np.asarray(b, np.float32), np.asarray(a, np.float32),
            rtol=0.05, atol=0.05, err_msg=name,
        )


@pytest.mark.parametrize(
    "dtype,atol",
    [
        (jnp.float32, 1e-5),
        # bf16: the fused kernel computes its projection/softmax in f32
        # while the xla path runs proj/tanh in compute_dtype, so backend
        # interchange is equivalent only within bf16 quantization (ADVICE
        # round 5; the --attn_backend help text documents the delta). The
        # loose bound pins "same model within bf16 noise", not bitwise.
        (jnp.bfloat16, 0.04),
    ],
    ids=["f32", "bf16"],
)
def test_encoder_attn_backend_equivalence(dtype, atol):
    """Same params -> same encoder output for xla and fused attention
    (attn_backend checkpoints interchange, like lstm_backend's)."""
    from induction_network_on_fewrel_tpu.models.encoders import (
        BiLSTMSelfAttnEncoder,
    )

    rng = np.random.default_rng(3)
    emb = jnp.asarray(rng.normal(size=(6, L, D)).astype(np.float32))
    mask = (rng.random((6, L)) > 0.2).astype(np.float32)
    mask[:, 0] = 1.0
    mask = jnp.asarray(mask)

    enc_x = BiLSTMSelfAttnEncoder(
        lstm_hidden=16, att_dim=A, lstm_backend="scan", attn_backend="xla",
        compute_dtype=dtype,
    )
    params = enc_x.init(jax.random.key(0), emb, mask)
    out_x = enc_x.apply(params, emb, mask)
    assert out_x.shape == (6, 32)
    # Every non-xla backend (fused kernel AND the remat hybrid) must
    # produce the same encoder output from the same params.
    for backend in ("interpret", "xla_remat_interpret"):
        enc_f = BiLSTMSelfAttnEncoder(
            lstm_hidden=16, att_dim=A, lstm_backend="scan",
            attn_backend=backend, compute_dtype=dtype,
        )
        out_f = enc_f.apply(params, emb, mask)
        assert out_x.dtype == out_f.dtype
        np.testing.assert_allclose(
            np.asarray(out_f, np.float32), np.asarray(out_x, np.float32),
            atol=atol, err_msg=backend,
        )
