"""Checkpoint interchange matrix (VERDICT r1 #7; SURVEY.md §5.4).

One file pinning that a checkpoint written under one runtime configuration
restores under its counterpart with identical model behavior, across four
axes:

1. LSTM backend: scan <-> pallas(interpret) — same param tree, different
   kernels.
2. Transport: live token batches <-> device-resident token cache — same
   state tree, different data path.
3. Placement: single device <-> 8-device (dp) mesh via shard_state.
4. Pipeline: pp=1 <-> pp=4 layer-stacked transformer (the deep variant
   lives in tests/test_pipeline.py; here the save/restore round-trip).

Every test goes through CheckpointManager (orbax on disk), not in-memory
param passing — the artifact under test is the serialized checkpoint.
"""

import jax
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    GloveTokenizer,
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.models.build import batch_to_model_inputs
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler
from induction_network_on_fewrel_tpu.train.checkpoint import CheckpointManager
from induction_network_on_fewrel_tpu.train.steps import init_state

L = 16


def _setup(cfg, seed=0):
    vocab = make_synthetic_glove(vocab_size=cfg.vocab_size - 2)
    ds = make_synthetic_fewrel(
        num_relations=6, instances_per_relation=cfg.k + cfg.q + 4,
        vocab_size=cfg.vocab_size - 2, seed=seed,
    )
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    sampler = EpisodeSampler(
        ds, tok, cfg.n, cfg.k, cfg.q, batch_size=cfg.batch_size, seed=seed
    )
    model = build_model(cfg, glove_init=vocab.vectors)
    return model, sampler, ds, tok


def _round_trip(tmp_path, cfg, state):
    """state -> orbax save -> restore into a zeros-like target."""
    mgr = CheckpointManager(tmp_path, cfg)
    mgr.save(1, jax.device_get(state), val_accuracy=0.5)
    mgr.wait()
    target = jax.tree.map(np.zeros_like, jax.device_get(state))
    restored, step = mgr.restore_best(target)
    assert step == 1
    return restored


@pytest.mark.slow
def test_interchange_scan_vs_pallas_backend(tmp_path):
    """A scan-backend checkpoint drives the pallas(interpret) encoder to
    identical outputs — kernels are interchangeable over one param tree."""
    cfg = ExperimentConfig(
        encoder="bilstm", n=3, k=2, q=2, batch_size=2, max_length=L,
        vocab_size=302, compute_dtype="float32", lstm_hidden=16, att_dim=8,
        induction_dim=16, ntn_slices=8, lstm_backend="scan",
    )
    model, sampler, _, _ = _setup(cfg)
    sup, qry, _ = batch_to_model_inputs(sampler.sample_batch())
    state = init_state(model, cfg, sup, qry)
    restored = _round_trip(tmp_path, cfg, state)

    out_scan = model.apply(restored.params, sup, qry)
    other = build_model(
        cfg.replace(lstm_backend="interpret"),
        glove_init=np.zeros((cfg.vocab_size, cfg.word_dim), np.float32),
    )
    out_pl = other.apply(restored.params, sup, qry)
    np.testing.assert_allclose(
        np.asarray(out_scan), np.asarray(out_pl), atol=1e-5
    )


def test_interchange_live_vs_token_cache(tmp_path):
    """A live-path checkpoint scores identically through the token-cache
    eval step (same episode, device-resident table)."""
    from induction_network_on_fewrel_tpu.train.feature_cache import (
        FeatureEpisodeSampler,
    )
    from induction_network_on_fewrel_tpu.train.steps import make_eval_step
    from induction_network_on_fewrel_tpu.train.token_cache import (
        make_token_cached_eval_step,
        tokenize_dataset,
    )

    cfg = ExperimentConfig(
        encoder="cnn", n=3, k=2, q=2, batch_size=2, max_length=L,
        vocab_size=302, compute_dtype="float32", hidden_size=32,
        induction_dim=16, ntn_slices=8,
    )
    model, sampler, ds, tok = _setup(cfg)
    sup, qry, _ = batch_to_model_inputs(sampler.sample_batch())
    state = init_state(model, cfg, sup, qry)
    restored = _round_trip(tmp_path, cfg, state)

    table_np, sizes = tokenize_dataset(ds, tok)
    idx = FeatureEpisodeSampler(
        sizes, cfg.n, cfg.k, cfg.q, batch_size=cfg.batch_size, seed=3
    )
    b = idx.sample_batch()
    # The SAME episode through both transports: live batches are the token
    # rows the cache gathers on device.
    sup_live = {k: v[b.support_idx] for k, v in table_np.items()}
    qry_live = {k: v[b.query_idx] for k, v in table_np.items()}
    live = make_eval_step(model, cfg)(
        restored.params, sup_live, qry_live, b.label
    )
    cached = make_token_cached_eval_step(model, cfg.replace(token_cache=True))(
        restored.params, jax.device_put(table_np), b.support_idx,
        b.query_idx, b.label,
    )
    np.testing.assert_allclose(
        float(live["accuracy"]), float(cached["accuracy"]), atol=1e-6
    )
    np.testing.assert_allclose(
        float(live["loss"]), float(cached["loss"]), atol=1e-5
    )


def test_interchange_single_device_vs_mesh(tmp_path):
    """A single-device checkpoint resharded onto an 8-device dp mesh
    (shard_state) evaluates identically under the GSPMD eval step."""
    from induction_network_on_fewrel_tpu.parallel import make_mesh
    from induction_network_on_fewrel_tpu.parallel.sharding import (
        make_sharded_eval_step,
        shard_state,
    )
    from induction_network_on_fewrel_tpu.train.steps import make_eval_step

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg = ExperimentConfig(
        encoder="cnn", n=3, k=2, q=2, batch_size=8, max_length=L,
        vocab_size=302, compute_dtype="float32", hidden_size=32,
        induction_dim=16, ntn_slices=8, dp=8,
    )
    model, sampler, _, _ = _setup(cfg)
    sup, qry, label = batch_to_model_inputs(sampler.sample_batch())
    state = init_state(model, cfg, sup, qry)
    restored = _round_trip(tmp_path, cfg, state)

    single = make_eval_step(model, cfg)(restored.params, sup, qry, label)
    mesh = make_mesh(dp=8)
    sharded_state = shard_state(restored, mesh)
    sharded = make_sharded_eval_step(model, cfg, mesh, sharded_state)(
        sharded_state.params, sup, qry, label
    )
    np.testing.assert_allclose(
        float(single["accuracy"]), float(sharded["accuracy"]), atol=1e-6
    )
    np.testing.assert_allclose(
        float(single["loss"]), float(sharded["loss"]), atol=1e-5
    )


@pytest.mark.slow
def test_interchange_pp1_vs_pp4(tmp_path):
    """A pp=1 layer-stacked-transformer checkpoint restores and runs under
    a (dp=2, pp=4) GPipe mesh with identical eval results."""
    from induction_network_on_fewrel_tpu.parallel import make_mesh
    from induction_network_on_fewrel_tpu.parallel.pipeline import make_gpipe
    from induction_network_on_fewrel_tpu.parallel.sharding import (
        make_sharded_eval_step,
        shard_state,
    )
    from induction_network_on_fewrel_tpu.train.steps import make_eval_step

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    base = dict(
        model="proto", encoder="transformer", train_n=3, n=3, k=2, q=2,
        batch_size=4, max_length=L, vocab_size=302, compute_dtype="float32",
        tfm_layers=4, tfm_model=32, tfm_heads=2, tfm_ff=64, tfm_stacked=True,
    )
    cfg1 = ExperimentConfig(**base)                  # single device, pp=1
    model1, sampler, _, _ = _setup(cfg1)
    sup, qry, label = batch_to_model_inputs(sampler.sample_batch())
    state = init_state(model1, cfg1, sup, qry)
    restored = _round_trip(tmp_path, cfg1, state)
    single = make_eval_step(model1, cfg1)(restored.params, sup, qry, label)

    cfg4 = ExperimentConfig(**base, dp=2, pp=4, pp_microbatches=2)
    mesh = make_mesh(dp=2, pp=4)
    gp = make_gpipe(mesh, microbatches=cfg4.pp_microbatches, batch_axis="dp")
    model4 = build_model(
        cfg4,
        glove_init=np.zeros((cfg4.vocab_size, cfg4.word_dim), np.float32),
        pipeline_impl=gp,
    )
    sharded_state = shard_state(restored, mesh)
    piped = make_sharded_eval_step(model4, cfg4, mesh, sharded_state)(
        sharded_state.params, sup, qry, label
    )
    np.testing.assert_allclose(
        float(single["accuracy"]), float(piped["accuracy"]), atol=1e-6
    )
    np.testing.assert_allclose(
        float(single["loss"]), float(piped["loss"]), atol=1e-5
    )
