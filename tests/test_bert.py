"""BERT encoder: tokenizer contract, forward shapes, frozen-backbone
gradients, end-to-end training with the induction head."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import make_synthetic_fewrel
from induction_network_on_fewrel_tpu.data.bert_tokenizer import (
    E1_ID,
    E2_ID,
    BertTokenizer,
)
from induction_network_on_fewrel_tpu.data.fewrel import Instance
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.models.bert import BertEncoder
from induction_network_on_fewrel_tpu.models.build import batch_to_model_inputs
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler

L = 24
TINY = dict(
    bert_layers=2, bert_hidden=32, bert_heads=4, bert_intermediate=64,
    bert_vocab_size=500,
)
CFG = ExperimentConfig(
    encoder="bert", n=3, k=2, q=2, batch_size=2, max_length=L,
    compute_dtype="float32", **TINY,
)


@pytest.fixture(scope="module")
def episode():
    ds = make_synthetic_fewrel(num_relations=6, instances_per_relation=10, vocab_size=300)
    tok = BertTokenizer(max_length=L, vocab_size=CFG.bert_vocab_size)
    sampler = EpisodeSampler(ds, tok, CFG.n, CFG.k, CFG.q, CFG.batch_size, seed=0)
    return batch_to_model_inputs(sampler.sample_batch())


def test_tokenizer_markers_and_shapes():
    tok = BertTokenizer(max_length=L, vocab_size=500)
    inst = Instance(tokens=("alpha", "beta", "gamma"), head_pos=(0,), tail_pos=(2,))
    t = tok(inst)
    assert t.word.shape == (L,)
    ids = t.word[t.mask > 0]
    assert ids[0] == tok.cls_id
    assert E1_ID in ids and E2_ID in ids
    assert (t.word[t.mask == 0] == 0).all()
    # deterministic hash fallback
    t2 = BertTokenizer(max_length=L, vocab_size=500)(inst)
    np.testing.assert_array_equal(t.word, t2.word)
    # all hashed ids stay inside the vocab
    assert int(t.word.max()) < 500


def test_wordpiece_with_vocab(tmp_path):
    vocab = ["[PAD]", "[unused0]", "[unused1]", "x", "[UNK]", "[CLS]", "[SEP]",
             "al", "##pha", "beta"]
    vp = tmp_path / "vocab.txt"
    vp.write_text("\n".join(vocab))
    tok = BertTokenizer(max_length=L, vocab_path=vp)
    inst = Instance(tokens=("alpha", "beta", "zzz"), head_pos=(0,), tail_pos=(1,))
    t = tok(inst)
    ids = list(t.word[t.mask > 0])
    assert ids[0] == vocab.index("[CLS]")
    assert vocab.index("al") in ids and vocab.index("##pha") in ids  # split
    assert vocab.index("beta") in ids
    assert vocab.index("[UNK]") in ids  # zzz
    assert ids[-1] == vocab.index("[SEP]")


@pytest.mark.slow
def test_bert_forward_shapes(episode):
    sup, qry, label = episode
    model = build_model(CFG)
    params = model.init(jax.random.key(0), sup, qry)
    logits = model.apply(params, sup, qry)
    assert logits.shape == (CFG.batch_size, CFG.n * CFG.q, CFG.n)
    assert np.isfinite(np.asarray(logits)).all()


def test_frozen_backbone_has_zero_grads(episode):
    sup, qry, label = episode
    model = build_model(CFG)  # bert_frozen=True by default

    params = model.init(jax.random.key(0), sup, qry)

    def loss_fn(p):
        from induction_network_on_fewrel_tpu.models.losses import mse_onehot_loss

        return mse_onehot_loss(model.apply(p, sup, qry), label)

    grads = jax.grad(loss_fn)(params)
    backbone = grads["params"]["encoder"]["backbone"]
    assert all(
        float(jnp.abs(g).max()) == 0.0 for g in jax.tree.leaves(backbone)
    ), "frozen backbone leaked gradients"
    head = grads["params"]["relation"]
    assert any(float(jnp.abs(g).max()) > 0 for g in jax.tree.leaves(head))


def test_unfrozen_backbone_gets_grads(episode):
    sup, qry, label = episode
    model = build_model(CFG.replace(bert_frozen=False))
    params = model.init(jax.random.key(0), sup, qry)

    def loss_fn(p):
        from induction_network_on_fewrel_tpu.models.losses import mse_onehot_loss

        return mse_onehot_loss(model.apply(p, sup, qry), label)

    grads = jax.grad(loss_fn)(params)
    backbone = grads["params"]["encoder"]["backbone"]
    assert any(float(jnp.abs(g).max()) > 0 for g in jax.tree.leaves(backbone))


# ---------------------------------------------------------------------------
# Numerical golden twins vs transformers.BertModel (torch CPU).
#
# SURVEY.md §4.2 mandates a torch golden twin per module; these pin the BERT
# port's GELU variant (exact erf, not tanh), LayerNorm eps (1e-12), attention
# scaling, and pooling against the HF reference implementation numerically.
# ---------------------------------------------------------------------------


def _hf_bert(vocab_size, hidden, layers, heads, intermediate, seed=0):
    import torch
    from transformers import BertConfig, BertModel

    torch.manual_seed(seed)
    cfg = BertConfig(
        vocab_size=vocab_size, hidden_size=hidden, num_hidden_layers=layers,
        num_attention_heads=heads, intermediate_size=intermediate,
        max_position_embeddings=512, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
    )
    return BertModel(cfg).eval()


def _export_npz(hf_model, path):
    # BertModel.state_dict() keys lack the "bert." prefix that BertFor*
    # state_dicts (and load_hf_weights) use; add it on export.
    raw = {
        "bert." + k: v.detach().numpy()
        for k, v in hf_model.state_dict().items()
    }
    np.savez(path, **raw)


def _golden_inputs(vocab_size, batch, length, seed=1):
    rng = np.random.default_rng(seed)
    # ids in [3, vocab) keep clear of the entity-marker ids 1/2 so the
    # backbone test is marker-free; mask has a ragged padded tail.
    ids = rng.integers(3, vocab_size, size=(batch, length)).astype(np.int32)
    mask = np.ones((batch, length), np.float32)
    mask[0, -5:] = 0.0
    mask[1, -1:] = 0.0
    ids[mask == 0] = 0
    return ids, mask


def _loaded_encoder(hf_model, tmp_path, vocab_size, hidden, layers, heads,
                    intermediate, length):
    from induction_network_on_fewrel_tpu.models.bert import load_hf_weights

    npz = tmp_path / "hf.npz"
    _export_npz(hf_model, npz)
    enc = BertEncoder(
        vocab_size=vocab_size, num_layers=layers, hidden_size=hidden,
        num_heads=heads, intermediate_size=intermediate, max_length=length,
    )
    params = enc.init(
        jax.random.key(0), jnp.ones((1, length), jnp.int32),
        jnp.ones((1, length), jnp.float32),
    )
    return enc, load_hf_weights(params, str(npz))


def _torch_hidden(hf_model, ids, mask):
    import torch

    with torch.no_grad():
        out = hf_model(
            input_ids=torch.from_numpy(np.asarray(ids, np.int64)),
            attention_mask=torch.from_numpy(np.asarray(mask)),
        )
    return out.last_hidden_state.numpy()


TINY_GOLD = dict(vocab_size=64, hidden=32, layers=3, heads=4, intermediate=64)


@pytest.mark.slow
def test_golden_hf_backbone(tmp_path):
    """BertBackbone matches transformers.BertModel last_hidden_state at 1e-4
    (f32 compute, random init exported through the real weight mapping)."""
    from induction_network_on_fewrel_tpu.models.bert import BertBackbone

    hf = _hf_bert(**TINY_GOLD)
    L2 = 16
    ids, mask = _golden_inputs(TINY_GOLD["vocab_size"], 2, L2)
    _, loaded = _loaded_encoder(
        hf, tmp_path, TINY_GOLD["vocab_size"], TINY_GOLD["hidden"],
        TINY_GOLD["layers"], TINY_GOLD["heads"], TINY_GOLD["intermediate"], L2,
    )
    bb = BertBackbone(
        vocab_size=TINY_GOLD["vocab_size"], num_layers=TINY_GOLD["layers"],
        hidden_size=TINY_GOLD["hidden"], num_heads=TINY_GOLD["heads"],
        intermediate_size=TINY_GOLD["intermediate"],
    )
    ours = np.asarray(bb.apply({"params": loaded["params"]["backbone"]}, ids, mask))
    theirs = _torch_hidden(hf, ids, mask)
    # Padded positions attend over the same masked keys in both impls but are
    # not meaningful outputs; compare only live positions.
    live = mask > 0
    np.testing.assert_allclose(ours[live], theirs[live], atol=1e-4, rtol=1e-4)


def test_golden_hf_encoder_pooling(tmp_path):
    """BertEncoder end-to-end (pooling included) matches the same pooling
    computed from torch hidden states — both the entity-marker path and the
    no-marker [CLS] fallback."""
    hf = _hf_bert(**TINY_GOLD)
    L2 = 16
    ids, mask = _golden_inputs(TINY_GOLD["vocab_size"], 2, L2)
    # Row 0: markers present (E1 at 3, E2 at 7). Row 1: no markers.
    ids[0, 3] = E1_ID
    ids[0, 7] = E2_ID
    enc, loaded = _loaded_encoder(
        hf, tmp_path, TINY_GOLD["vocab_size"], TINY_GOLD["hidden"],
        TINY_GOLD["layers"], TINY_GOLD["heads"], TINY_GOLD["intermediate"], L2,
    )
    ours = np.asarray(enc.apply(loaded, ids, mask))

    hidden = _torch_hidden(hf, ids, mask)
    cls = hidden[:, 0]
    expect = np.stack([
        (cls[0] + hidden[0, 3] + hidden[0, 7]) / 3.0,  # marker pooling
        cls[1],                                         # CLS fallback
    ])
    np.testing.assert_allclose(ours, expect, atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_golden_hf_backbone_base_shape(tmp_path):
    """Once at the real bert-base shape (12x768, vocab 30522): the full-size
    mapping and numerics hold, not just the tiny proxy."""
    from induction_network_on_fewrel_tpu.models.bert import BertBackbone

    shape = dict(vocab_size=30522, hidden=768, layers=12, heads=12,
                 intermediate=3072)
    hf = _hf_bert(**shape)
    L2 = 32
    ids, mask = _golden_inputs(shape["vocab_size"], 2, L2)
    _, loaded = _loaded_encoder(
        hf, tmp_path, shape["vocab_size"], shape["hidden"], shape["layers"],
        shape["heads"], shape["intermediate"], L2,
    )
    bb = BertBackbone(
        vocab_size=shape["vocab_size"], num_layers=shape["layers"],
        hidden_size=shape["hidden"], num_heads=shape["heads"],
        intermediate_size=shape["intermediate"],
    )
    ours = np.asarray(bb.apply({"params": loaded["params"]["backbone"]}, ids, mask))
    theirs = _torch_hidden(hf, ids, mask)
    live = mask > 0
    # 12 layers of f32 accumulation: slightly looser tolerance than the tiny
    # twin but still tight enough to catch any variant/eps mismatch.
    np.testing.assert_allclose(ours[live], theirs[live], atol=5e-4, rtol=5e-4)


@pytest.mark.parametrize("ln_style", [("gamma", "beta"), ("weight", "bias")])
@pytest.mark.slow
def test_hf_weight_mapping_roundtrip(tmp_path, ln_style):
    """load_hf_weights maps a synthetic HF-style npz onto the param tree and
    the fused qkv equals the concatenation of q/k/v. Both TF-era
    (LayerNorm.gamma/beta) and torch (LayerNorm.weight/bias) namings work."""
    enc = BertEncoder(vocab_size=50, num_layers=1, hidden_size=8, num_heads=2,
                      intermediate_size=16, max_length=L)
    ids = jnp.ones((2, L), jnp.int32)
    mask = jnp.ones((2, L), jnp.float32)
    params = enc.init(jax.random.key(0), ids, mask)

    rng = np.random.default_rng(0)
    raw = {
        "bert.embeddings.word_embeddings.weight": rng.normal(size=(50, 8)).astype(np.float32),
        "bert.embeddings.position_embeddings.weight": rng.normal(size=(512, 8)).astype(np.float32),
        "bert.embeddings.token_type_embeddings.weight": rng.normal(size=(2, 8)).astype(np.float32),
        f"bert.embeddings.LayerNorm.{ln_style[0]}": np.ones(8, np.float32),
        f"bert.embeddings.LayerNorm.{ln_style[1]}": np.zeros(8, np.float32),
    }
    lp = "bert.encoder.layer.0."
    for n in ("query", "key", "value"):
        raw[lp + f"attention.self.{n}.weight"] = rng.normal(size=(8, 8)).astype(np.float32)
        raw[lp + f"attention.self.{n}.bias"] = rng.normal(size=8).astype(np.float32)
    raw[lp + "attention.output.dense.weight"] = rng.normal(size=(8, 8)).astype(np.float32)
    raw[lp + "attention.output.dense.bias"] = rng.normal(size=8).astype(np.float32)
    raw[lp + f"attention.output.LayerNorm.{ln_style[0]}"] = np.ones(8, np.float32)
    raw[lp + f"attention.output.LayerNorm.{ln_style[1]}"] = np.zeros(8, np.float32)
    raw[lp + "intermediate.dense.weight"] = rng.normal(size=(16, 8)).astype(np.float32)
    raw[lp + "intermediate.dense.bias"] = rng.normal(size=16).astype(np.float32)
    raw[lp + "output.dense.weight"] = rng.normal(size=(8, 16)).astype(np.float32)
    raw[lp + "output.dense.bias"] = rng.normal(size=8).astype(np.float32)
    raw[lp + f"output.LayerNorm.{ln_style[0]}"] = np.ones(8, np.float32)
    raw[lp + f"output.LayerNorm.{ln_style[1]}"] = np.zeros(8, np.float32)
    npz = tmp_path / "bert.npz"
    np.savez(npz, **raw)

    from induction_network_on_fewrel_tpu.models.bert import load_hf_weights

    loaded = load_hf_weights(params, str(npz))
    qkv = loaded["params"]["backbone"]["layer_0"]["attention"]["qkv"]["kernel"]
    expect = np.concatenate(
        [raw[lp + f"attention.self.{n}.weight"].T for n in ("query", "key", "value")],
        axis=1,
    )
    np.testing.assert_array_equal(np.asarray(qkv), expect)
    # loaded params still run
    out = enc.apply(loaded, ids, mask)
    assert out.shape == (2, 8) and np.isfinite(np.asarray(out)).all()
