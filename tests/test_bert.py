"""BERT encoder: tokenizer contract, forward shapes, frozen-backbone
gradients, end-to-end training with the induction head."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import make_synthetic_fewrel
from induction_network_on_fewrel_tpu.data.bert_tokenizer import (
    E1_ID,
    E2_ID,
    BertTokenizer,
)
from induction_network_on_fewrel_tpu.data.fewrel import Instance
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.models.bert import BertEncoder
from induction_network_on_fewrel_tpu.models.build import batch_to_model_inputs
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler

L = 24
TINY = dict(
    bert_layers=2, bert_hidden=32, bert_heads=4, bert_intermediate=64,
    bert_vocab_size=500,
)
CFG = ExperimentConfig(
    encoder="bert", n=3, k=2, q=2, batch_size=2, max_length=L,
    compute_dtype="float32", **TINY,
)


@pytest.fixture(scope="module")
def episode():
    ds = make_synthetic_fewrel(num_relations=6, instances_per_relation=10, vocab_size=300)
    tok = BertTokenizer(max_length=L, vocab_size=CFG.bert_vocab_size)
    sampler = EpisodeSampler(ds, tok, CFG.n, CFG.k, CFG.q, CFG.batch_size, seed=0)
    return batch_to_model_inputs(sampler.sample_batch())


def test_tokenizer_markers_and_shapes():
    tok = BertTokenizer(max_length=L, vocab_size=500)
    inst = Instance(tokens=("alpha", "beta", "gamma"), head_pos=(0,), tail_pos=(2,))
    t = tok(inst)
    assert t.word.shape == (L,)
    ids = t.word[t.mask > 0]
    assert ids[0] == tok.cls_id
    assert E1_ID in ids and E2_ID in ids
    assert (t.word[t.mask == 0] == 0).all()
    # deterministic hash fallback
    t2 = BertTokenizer(max_length=L, vocab_size=500)(inst)
    np.testing.assert_array_equal(t.word, t2.word)
    # all hashed ids stay inside the vocab
    assert int(t.word.max()) < 500


def test_wordpiece_with_vocab(tmp_path):
    vocab = ["[PAD]", "[unused0]", "[unused1]", "x", "[UNK]", "[CLS]", "[SEP]",
             "al", "##pha", "beta"]
    vp = tmp_path / "vocab.txt"
    vp.write_text("\n".join(vocab))
    tok = BertTokenizer(max_length=L, vocab_path=vp)
    inst = Instance(tokens=("alpha", "beta", "zzz"), head_pos=(0,), tail_pos=(1,))
    t = tok(inst)
    ids = list(t.word[t.mask > 0])
    assert ids[0] == vocab.index("[CLS]")
    assert vocab.index("al") in ids and vocab.index("##pha") in ids  # split
    assert vocab.index("beta") in ids
    assert vocab.index("[UNK]") in ids  # zzz
    assert ids[-1] == vocab.index("[SEP]")


def test_bert_forward_shapes(episode):
    sup, qry, label = episode
    model = build_model(CFG)
    params = model.init(jax.random.key(0), sup, qry)
    logits = model.apply(params, sup, qry)
    assert logits.shape == (CFG.batch_size, CFG.n * CFG.q, CFG.n)
    assert np.isfinite(np.asarray(logits)).all()


def test_frozen_backbone_has_zero_grads(episode):
    sup, qry, label = episode
    model = build_model(CFG)  # bert_frozen=True by default

    params = model.init(jax.random.key(0), sup, qry)

    def loss_fn(p):
        from induction_network_on_fewrel_tpu.models.losses import mse_onehot_loss

        return mse_onehot_loss(model.apply(p, sup, qry), label)

    grads = jax.grad(loss_fn)(params)
    backbone = grads["params"]["encoder"]["backbone"]
    assert all(
        float(jnp.abs(g).max()) == 0.0 for g in jax.tree.leaves(backbone)
    ), "frozen backbone leaked gradients"
    head = grads["params"]["relation"]
    assert any(float(jnp.abs(g).max()) > 0 for g in jax.tree.leaves(head))


def test_unfrozen_backbone_gets_grads(episode):
    sup, qry, label = episode
    model = build_model(CFG.replace(bert_frozen=False))
    params = model.init(jax.random.key(0), sup, qry)

    def loss_fn(p):
        from induction_network_on_fewrel_tpu.models.losses import mse_onehot_loss

        return mse_onehot_loss(model.apply(p, sup, qry), label)

    grads = jax.grad(loss_fn)(params)
    backbone = grads["params"]["encoder"]["backbone"]
    assert any(float(jnp.abs(g).max()) > 0 for g in jax.tree.leaves(backbone))


@pytest.mark.parametrize("ln_style", [("gamma", "beta"), ("weight", "bias")])
def test_hf_weight_mapping_roundtrip(tmp_path, ln_style):
    """load_hf_weights maps a synthetic HF-style npz onto the param tree and
    the fused qkv equals the concatenation of q/k/v. Both TF-era
    (LayerNorm.gamma/beta) and torch (LayerNorm.weight/bias) namings work."""
    enc = BertEncoder(vocab_size=50, num_layers=1, hidden_size=8, num_heads=2,
                      intermediate_size=16, max_length=L)
    ids = jnp.ones((2, L), jnp.int32)
    mask = jnp.ones((2, L), jnp.float32)
    params = enc.init(jax.random.key(0), ids, mask)

    rng = np.random.default_rng(0)
    raw = {
        "bert.embeddings.word_embeddings.weight": rng.normal(size=(50, 8)).astype(np.float32),
        "bert.embeddings.position_embeddings.weight": rng.normal(size=(512, 8)).astype(np.float32),
        "bert.embeddings.token_type_embeddings.weight": rng.normal(size=(2, 8)).astype(np.float32),
        f"bert.embeddings.LayerNorm.{ln_style[0]}": np.ones(8, np.float32),
        f"bert.embeddings.LayerNorm.{ln_style[1]}": np.zeros(8, np.float32),
    }
    lp = "bert.encoder.layer.0."
    for n in ("query", "key", "value"):
        raw[lp + f"attention.self.{n}.weight"] = rng.normal(size=(8, 8)).astype(np.float32)
        raw[lp + f"attention.self.{n}.bias"] = rng.normal(size=8).astype(np.float32)
    raw[lp + "attention.output.dense.weight"] = rng.normal(size=(8, 8)).astype(np.float32)
    raw[lp + "attention.output.dense.bias"] = rng.normal(size=8).astype(np.float32)
    raw[lp + f"attention.output.LayerNorm.{ln_style[0]}"] = np.ones(8, np.float32)
    raw[lp + f"attention.output.LayerNorm.{ln_style[1]}"] = np.zeros(8, np.float32)
    raw[lp + "intermediate.dense.weight"] = rng.normal(size=(16, 8)).astype(np.float32)
    raw[lp + "intermediate.dense.bias"] = rng.normal(size=16).astype(np.float32)
    raw[lp + "output.dense.weight"] = rng.normal(size=(8, 16)).astype(np.float32)
    raw[lp + "output.dense.bias"] = rng.normal(size=8).astype(np.float32)
    raw[lp + f"output.LayerNorm.{ln_style[0]}"] = np.ones(8, np.float32)
    raw[lp + f"output.LayerNorm.{ln_style[1]}"] = np.zeros(8, np.float32)
    npz = tmp_path / "bert.npz"
    np.savez(npz, **raw)

    from induction_network_on_fewrel_tpu.models.bert import load_hf_weights

    loaded = load_hf_weights(params, str(npz))
    qkv = loaded["params"]["backbone"]["layer_0"]["attention"]["qkv"]["kernel"]
    expect = np.concatenate(
        [raw[lp + f"attention.self.{n}.weight"].T for n in ("query", "key", "value")],
        axis=1,
    )
    np.testing.assert_array_equal(np.asarray(qkv), expect)
    # loaded params still run
    out = enc.apply(loaded, ids, mask)
    assert out.shape == (2, 8) and np.isfinite(np.asarray(out)).all()
