"""Pipeline parallelism: GPipe schedule == sequential layer scan, exactly.

The pipelined transformer (models/pipeline_transformer.py +
parallel/pipeline.py) must be a pure execution-strategy change: same param
tree, same forward values, same training trajectory as the single-device
sequential scan. Pinned here on the 8-virtual-CPU mesh, the same way the
ring suite pins sequence parallelism.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    GloveTokenizer,
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.models.build import batch_to_model_inputs
from induction_network_on_fewrel_tpu.models.pipeline_transformer import (
    PipelinedTransformerEncoder,
)
from induction_network_on_fewrel_tpu.parallel import make_gpipe, make_mesh
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler

D_MODEL = 32


def _encoders(pp: int, microbatches: int = 4):
    """(sequential encoder, pipelined encoder over a pp-stage mesh)."""
    mesh = make_mesh(dp=1, pp=pp, devices=jax.devices()[:pp])
    seq = PipelinedTransformerEncoder(
        num_layers=4, d_model=D_MODEL, num_heads=2, d_ff=64, max_length=12
    )
    piped = seq.copy(pipeline_impl=make_gpipe(mesh, microbatches=microbatches))
    return seq, piped


def test_gpipe_forward_matches_sequential():
    seq, piped = _encoders(pp=4)
    emb = jax.random.normal(jax.random.key(0), (8, 12, 20))
    mask = jnp.ones((8, 12), jnp.int32).at[:, 9:].set(0)
    params = seq.init(jax.random.key(1), emb, mask)
    y_seq = seq.apply(params, emb, mask)
    y_pipe = piped.apply(params, emb, mask)  # identical param tree
    np.testing.assert_allclose(
        np.asarray(y_seq), np.asarray(y_pipe), rtol=1e-5, atol=1e-6
    )


@pytest.mark.slow
def test_gpipe_gradient_matches_sequential():
    seq, piped = _encoders(pp=4)
    emb = jax.random.normal(jax.random.key(2), (8, 12, 20))
    mask = jnp.ones((8, 12), jnp.int32)
    params = seq.init(jax.random.key(3), emb, mask)

    def loss(p, enc):
        return jnp.sum(enc.apply(p, emb, mask) ** 2)

    g_seq = jax.grad(lambda p: loss(p, seq))(params)
    g_pipe = jax.grad(lambda p: loss(p, piped))(params)
    for a, b in zip(jax.tree.leaves(g_seq), jax.tree.leaves(g_pipe)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-5, atol=1e-5
        )


@pytest.mark.slow
def test_gpipe_bubble_ticks_do_not_pollute():
    """Microbatches > stages and microbatches == stages both stay exact
    (inject/drain bubbles carry zeros that must never reach outputs)."""
    for m in (2, 4, 8):
        seq, piped = _encoders(pp=2, microbatches=m)
        emb = jax.random.normal(jax.random.key(4), (8, 12, 20))
        mask = jnp.ones((8, 12), jnp.int32)
        params = seq.init(jax.random.key(5), emb, mask)
        np.testing.assert_allclose(
            np.asarray(seq.apply(params, emb, mask)),
            np.asarray(piped.apply(params, emb, mask)),
            rtol=1e-5, atol=1e-6,
            err_msg=f"microbatches={m}",
        )


@pytest.fixture(scope="module")
def pp_episode_setup():
    # Support rows = B*N*K = 4*6 = 24? No: 4 episodes * 3-way * 2-shot = 24;
    # query rows = 4 * 6 = 24; both divisible by microbatches=4.
    cfg = ExperimentConfig(
        model="proto", encoder="transformer", train_n=3, n=3, k=2, q=2,
        batch_size=4, max_length=12, vocab_size=302, compute_dtype="float32",
        tfm_layers=4, tfm_model=D_MODEL, tfm_heads=2, tfm_ff=64,
        tfm_stacked=True, pp=4, pp_microbatches=4,
        lr=1e-3, weight_decay=0.0,
    )
    vocab = make_synthetic_glove(vocab_size=300)
    ds = make_synthetic_fewrel(
        num_relations=6, instances_per_relation=8, vocab_size=300
    )
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    sampler = EpisodeSampler(ds, tok, cfg.train_n, cfg.k, cfg.q,
                             batch_size=cfg.batch_size, seed=0)
    return cfg, vocab, sampler


@pytest.mark.slow
def test_pp_sharded_training_matches_single_device(pp_episode_setup):
    """Full GSPMD train step with the pipeline executor on a (dp=2, pp=4)
    mesh == single-device sequential-scan training, for 3 steps."""
    from induction_network_on_fewrel_tpu.parallel.sharding import (
        make_sharded_train_step,
    )
    from induction_network_on_fewrel_tpu.train.steps import (
        init_state, make_train_step,
    )

    cfg, vocab, sampler = pp_episode_setup
    sup, qry, label = batch_to_model_inputs(sampler.sample_batch())

    model_seq = build_model(cfg.replace(pp=1), glove_init=vocab.vectors)
    mesh = make_mesh(dp=2, pp=4, devices=jax.devices()[:8])
    model_pp = build_model(
        cfg, glove_init=vocab.vectors,
        pipeline_impl=make_gpipe(
            mesh, microbatches=cfg.pp_microbatches, batch_axis="dp"
        ),
    )

    state_a = init_state(model_seq, cfg, sup, qry)
    state_b = jax.tree.map(
        lambda x: x.copy() if hasattr(x, "copy") else x, state_a
    )
    single = make_train_step(model_seq, cfg)
    sharded = make_sharded_train_step(model_pp, cfg, mesh, state_a)

    for _ in range(3):
        sup_b, qry_b, label_b = batch_to_model_inputs(sampler.sample_batch())
        state_a, m_a = single(state_a, sup_b, qry_b, label_b)
        state_b, m_b = sharded(state_b, sup_b, qry_b, label_b)
        np.testing.assert_allclose(
            float(m_a["loss"]), float(m_b["loss"]), rtol=1e-5, atol=1e-6
        )

    # Looser than the forward/grad tests above: dp-psum + pipeline reduction
    # order shifts grads by float-epsilon and Adam's rsqrt amplifies that on
    # near-zero second moments over 3 steps. Real sharding bugs are orders
    # of magnitude beyond these bounds (forward/grad exactness is pinned
    # tight above).
    for a, b in zip(
        jax.tree.leaves(jax.device_get(state_a.params)),
        jax.tree.leaves(jax.device_get(state_b.params)),
    ):
        np.testing.assert_allclose(a, b, rtol=5e-3, atol=5e-4)


def test_stacked_checkpoint_restores_across_pp():
    """pp=1 (sequential) and pp=4 (pipelined) share one param tree: a
    checkpoint from either restores into the other bit-for-bit."""
    seq, piped = _encoders(pp=4)
    emb = jax.random.normal(jax.random.key(6), (4, 12, 20))
    mask = jnp.ones((4, 12), jnp.int32)
    params = seq.init(jax.random.key(7), emb, mask)
    # Same tree structure and shapes — restoration is trivially valid.
    p2 = piped.init(jax.random.key(8), emb, mask)
    assert jax.tree_util.tree_structure(params) == jax.tree_util.tree_structure(p2)
    assert all(
        a.shape == b.shape
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(p2))
    )
