"""Checkpoint integrity chain (ISSUE 12, train/checkpoint.py).

The contract under test:

* every save writes per-leaf + manifest checksums; a clean restore
  verifies silently (no quarantine, no fault records);
* a corrupt slot is QUARANTINED (renamed aside, never deleted) with a
  kind="fault" record and a once-latched CRITICAL ``ckpt_corrupt``, and
  ``restore_latest`` walks to the newest INTACT slot with a bitwise-
  correct restore — the delta-slot case falls back to its base, the
  corrupt-BASE case orphans the delta and falls back further, the full-
  ring case (``ckpt_delta=off``) falls back to the best save;
* the cursor sidecar follows the surviving step;
* the ``ckpt.restore_raise`` chaos point is contained exactly like
  corruption (deterministic injection, off = zero cost).

All states are captured with np.array COPIES: on the CPU backend
``jax.device_get`` returns views of device buffers which later DONATING
train steps reuse — comparing against a view would test allocator
timing, not the restore.
"""

import jax
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    GloveTokenizer,
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.models.build import batch_to_model_inputs
from induction_network_on_fewrel_tpu.obs.chaos import (
    ChaosRegistry,
    corrupt_step_dir,
    install,
)
from induction_network_on_fewrel_tpu.obs.health import HealthWatchdog
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler
from induction_network_on_fewrel_tpu.train.checkpoint import CheckpointManager
from induction_network_on_fewrel_tpu.train.steps import init_state, make_train_step
from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

VOCAB = 402
CFG = ExperimentConfig(
    encoder="cnn", n=3, k=2, q=2, batch_size=2, max_length=12,
    vocab_size=VOCAB, hidden_size=16, induction_dim=16, ntn_slices=4,
    lr=3e-3, weight_decay=0.0,
    embed_optimizer="lazy", compute_dtype="float32", ckpt_stage="off",
)


@pytest.fixture(scope="module")
def world():
    vocab = make_synthetic_glove(vocab_size=VOCAB - 2)
    ds = make_synthetic_fewrel(
        num_relations=6, instances_per_relation=6, vocab_size=35
    )
    tok = GloveTokenizer(vocab, max_length=CFG.max_length)
    sampler = EpisodeSampler(
        ds, tok, CFG.n, CFG.k, CFG.q, CFG.batch_size, seed=3
    )
    batches = [
        batch_to_model_inputs(sampler.sample_batch()) for _ in range(8)
    ]
    model = build_model(CFG, glove_init=vocab.vectors)
    return model, batches


def _copy(tree):
    return jax.tree.map(lambda x: np.array(x), jax.device_get(tree))


def _trees_equal(a, b) -> bool:
    return all(
        np.array_equal(np.asarray(va), np.asarray(vb))
        for (_, va), (_, vb) in zip(
            jax.tree_util.tree_flatten_with_path(a)[0],
            jax.tree_util.tree_flatten_with_path(b)[0],
        )
    )


class _Capture:
    """Logger + watchdog pair capturing fault records and health events."""

    def __init__(self, tmp_path):
        self.logger = MetricsLogger(tmp_path, quiet=True)
        self.watchdog = HealthWatchdog(logger=self.logger)
        self.logger.add_hook(self.watchdog.observe_record)
        self.faults: list[dict] = []
        self.logger.add_hook(
            lambda rec: self.faults.append(rec)
            if rec.get("kind") == "fault" else None
        )


def _train_and_save(model, batches, cfg, ckpt_dir, logger=None):
    """2 steps -> ring save @2 (base in delta mode), 2 more -> save @4
    (delta). Returns (mgr, state@2 copy, state@4 copy, save modes)."""
    step_fn = make_train_step(model, cfg)
    state = init_state(model, cfg, batches[0][0], batches[0][1])
    mgr = CheckpointManager(ckpt_dir, cfg, logger=logger)
    for sup, qry, lab in batches[:2]:
        state, _ = step_fn(state, sup, qry, lab)
    m2 = mgr.save_latest(2, state, cursor={"pos": 2})["mode"]
    mgr.wait()
    state2 = _copy(state)
    for sup, qry, lab in batches[2:4]:
        state, _ = step_fn(state, sup, qry, lab)
    m4 = mgr.save_latest(4, state, cursor={"pos": 4})["mode"]
    mgr.wait()
    return mgr, state2, _copy(state), (m2, m4)


def _template(model, batches, cfg):
    return _copy(init_state(model, cfg, batches[0][0], batches[0][1]))


def test_clean_restore_verifies_silently(world, tmp_path):
    """Manifests are written with every save and a clean restore verifies
    against them without quarantining anything."""
    model, batches = world
    cap = _Capture(tmp_path / "run")
    mgr, _, state4, modes = _train_and_save(
        model, batches, CFG, tmp_path / "ckpt", logger=cap.logger
    )
    assert modes == ("base", "delta")
    assert (tmp_path / "ckpt/ring_base/integrity_00000002.json").exists()
    assert (tmp_path / "ckpt/ring_delta/integrity_00000004.json").exists()
    restored, step = mgr.restore_latest(_template(model, batches, CFG))
    assert step == 4
    assert _trees_equal(state4, restored)
    assert cap.faults == []
    assert not any(e.event == "ckpt_corrupt" for e in cap.watchdog.events)
    mgr.close()


def test_corrupt_delta_quarantines_and_falls_back_to_base(world, tmp_path):
    """Bit-flipped delta slot: quarantined (renamed, never deleted; fault
    record + ONE ckpt_corrupt CRITICAL), restore falls back to the base
    bitwise, and the cursor sidecar follows the surviving step."""
    model, batches = world
    cap = _Capture(tmp_path / "run")
    mgr, state2, _, _ = _train_and_save(
        model, batches, CFG, tmp_path / "ckpt", logger=cap.logger
    )
    mgr.close()
    assert corrupt_step_dir(tmp_path / "ckpt/ring_delta/4", "bitflip")

    mgr2 = CheckpointManager(tmp_path / "ckpt", CFG, logger=cap.logger)
    restored, step = mgr2.restore_latest(_template(model, batches, CFG))
    assert step == 2
    assert _trees_equal(state2, restored)
    # Quarantined, not purged: the evidence survives on disk.
    assert (tmp_path / "ckpt/ring_delta/4.quarantined").exists()
    assert not (tmp_path / "ckpt/ring_delta/4").exists()
    q = [f for f in cap.faults if f.get("action") == "ckpt_quarantine"]
    assert len(q) == 1 and q[0]["ckpt_kind"] == "ring_delta"
    crits = [e for e in cap.watchdog.events if e.event == "ckpt_corrupt"]
    assert len(crits) == 1 and crits[0].severity == "critical"
    # Cursor follows: the surviving step's sidecar loads, the corrupt
    # slot's was renamed aside with it.
    assert mgr2.load_cursor(2) == {"pos": 2}
    assert mgr2.load_cursor(4) is None
    # The dir stays WRITABLE at the freed step numbers (orbax would
    # refuse saves <= its latest step had the slot not been renamed).
    step_fn = make_train_step(model, CFG)
    state = restored
    for sup, qry, lab in batches[4:5]:
        state, _ = step_fn(jax.device_put(state), sup, qry, lab)
    assert mgr2.save_latest(3, state, force=True)["mode"] == "delta"
    mgr2.wait()
    mgr2.close()


def test_corrupt_base_orphans_delta_falls_back_to_best(world, tmp_path):
    """The delta-whose-base-died case: corrupting the BASE quarantines it,
    the surviving delta is orphaned (quarantined too — it cannot
    resolve), and the walk falls back to the best save."""
    model, batches = world
    cap = _Capture(tmp_path / "run")
    step_fn = make_train_step(model, CFG)
    state = init_state(model, CFG, batches[0][0], batches[0][1])
    mgr = CheckpointManager(tmp_path / "ckpt", CFG, logger=cap.logger)
    state, _ = step_fn(state, *batches[0])
    mgr.save(1, state, val_accuracy=0.5, cursor={"pos": 1})   # best@1
    mgr.wait()
    state1 = _copy(state)
    state, _ = step_fn(state, *batches[1])
    assert mgr.save_latest(2, state, force=True)["mode"] == "base"
    mgr.wait()
    state, _ = step_fn(state, *batches[2])
    assert mgr.save_latest(3, state, force=True)["mode"] == "delta"
    mgr.wait()
    mgr.close()
    assert corrupt_step_dir(tmp_path / "ckpt/ring_base/2", "bitflip")

    mgr2 = CheckpointManager(tmp_path / "ckpt", CFG, logger=cap.logger)
    restored, step = mgr2.restore_latest(_template(model, batches, CFG))
    assert step == 1
    assert _trees_equal(state1, restored)
    kinds = [
        (f["ckpt_kind"], int(f["ckpt_step"])) for f in cap.faults
        if f.get("action") == "ckpt_quarantine"
    ]
    assert ("ring_base", 2) in kinds and ("ring_delta", 3) in kinds
    # Two slots, two incidents (latched per slot).
    crits = [e for e in cap.watchdog.events if e.event == "ckpt_corrupt"]
    assert len(crits) == 2
    assert mgr2.load_cursor(1) == {"pos": 1}
    mgr2.close()


def test_truncated_full_ring_falls_back_to_best(world, tmp_path):
    """ckpt_delta=off: a TRUNCATED full ring slot (the restore itself
    raises) is classified corrupt via the manifest re-verify and the walk
    falls back to the best save."""
    model, batches = world
    cfg = CFG.replace(ckpt_delta="off")
    cap = _Capture(tmp_path / "run")
    step_fn = make_train_step(model, cfg)
    state = init_state(model, cfg, batches[0][0], batches[0][1])
    mgr = CheckpointManager(tmp_path / "ckpt", cfg, logger=cap.logger)
    state, _ = step_fn(state, *batches[0])
    mgr.save(1, state, val_accuracy=0.5)
    mgr.wait()
    state1 = _copy(state)
    state, _ = step_fn(state, *batches[1])
    assert mgr.save_latest(2, state, force=True)["mode"] == "full"
    mgr.wait()
    mgr.close()
    assert corrupt_step_dir(tmp_path / "ckpt/latest/2", "truncate")

    mgr2 = CheckpointManager(tmp_path / "ckpt", cfg, logger=cap.logger)
    restored, step = mgr2.restore_latest(_template(model, batches, cfg))
    assert step == 1
    assert _trees_equal(state1, restored)
    assert (tmp_path / "ckpt/latest/2.quarantined").exists()
    mgr2.close()


def test_chaos_restore_raise_contained_like_corruption(world, tmp_path):
    """The ckpt.restore_raise fault point: an injected restore failure on
    the delta slot quarantines it and falls back to the base — the drill
    path for flaky-read containment, deterministic by plan."""
    model, batches = world
    mgr, state2, _, _ = _train_and_save(
        model, batches, CFG, tmp_path / "ckpt"
    )
    mgr.close()
    reg = ChaosRegistry.parse("ckpt.restore_raise@0:ring_delta")
    reg.install()
    try:
        mgr2 = CheckpointManager(tmp_path / "ckpt", CFG)
        restored, step = mgr2.restore_latest(
            _template(model, batches, CFG)
        )
        assert step == 2
        assert _trees_equal(state2, restored)
        assert (tmp_path / "ckpt/ring_delta/4.quarantined").exists()
        assert reg.directives[0].fired == 1
        mgr2.close()
    finally:
        install(None)
