"""Per-host data feeding (parallel/hostfeed.py).

A real pod cannot be spawned here, so the two halves are verified
separately on the 8-virtual-device CPU mesh (VERDICT round-2 item 2's
prescribed fallback): the episode-partition math with injected
device->process maps, and the global-array assembly + trainer integration
on a single process (identical code path; only jax.process_count()
changes on a pod).
"""

import os

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    GloveTokenizer,
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.models.build import batch_to_model_inputs
from induction_network_on_fewrel_tpu.parallel import make_mesh
from induction_network_on_fewrel_tpu.parallel.hostfeed import (
    GlobalBatchAssembler,
    PerHostSampler,
    episode_ranges_by_process,
    local_episode_range,
    process_seed,
)
from induction_network_on_fewrel_tpu.parallel.sharding import (
    make_sharded_train_step,
)
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler
from induction_network_on_fewrel_tpu.train.steps import init_state

CFG = ExperimentConfig(
    encoder="cnn", n=3, k=2, q=2, batch_size=8, max_length=12,
    vocab_size=52, hidden_size=16, dp=8,
)


def _fixture():
    vocab = make_synthetic_glove(vocab_size=50)
    ds = make_synthetic_fewrel(
        num_relations=6, instances_per_relation=8, vocab_size=35
    )
    tok = GloveTokenizer(vocab, max_length=CFG.max_length)
    model = build_model(CFG, glove_init=vocab.vectors)
    return vocab, ds, tok, model


def test_episode_partition_math_simulated_processes():
    """With a simulated 2-process (and 4-process) layout, each process owns
    a contiguous, disjoint, covering slice of the global episode axis."""
    mesh = make_mesh(dp=8)
    for n_proc in (2, 4):
        per = 8 // n_proc
        ranges = episode_ranges_by_process(
            mesh, 16, process_of=lambda d: d.id // per
        )
        assert set(ranges) == set(range(n_proc))
        rows = []
        for pid in range(n_proc):
            start, count = ranges[pid]
            assert count == 16 // n_proc
            rows.extend(range(start, start + count))
        assert sorted(rows) == list(range(16))  # disjoint + covering
        # contiguity per process in process-major order
        assert ranges[0][0] == 0
        for pid in range(1, n_proc):
            assert ranges[pid][0] == ranges[pid - 1][0] + ranges[pid - 1][1]


def test_interleaved_device_order_refused():
    mesh = make_mesh(dp=8)
    with pytest.raises(ValueError, match="non-contiguous"):
        episode_ranges_by_process(mesh, 16, process_of=lambda d: d.id % 2)


def test_single_process_owns_everything():
    mesh = make_mesh(dp=8)
    assert local_episode_range(mesh, 16) == (0, 16)
    assert process_seed(5) == 5  # process 0: stream unchanged


def test_process_seed_streams_are_distinct(monkeypatch):
    """splitmix64 domain separation: per-process seeds are pairwise
    distinct and decorrelated, and the episode streams they drive draw
    different index sequences (statistical independence, the property the
    derivation actually guarantees — see process_seed's docstring)."""
    import jax as _jax

    from induction_network_on_fewrel_tpu.native.sampler import (
        make_index_sampler,
    )

    seeds = []
    for pid in range(8):
        monkeypatch.setattr(_jax, "process_index", lambda p=pid: p)
        seeds.append(process_seed(42))
    assert len(set(seeds)) == 8
    # Decorrelation (a linear stride would fail this): successive deltas
    # must not be constant.
    deltas = {b - a for a, b in zip(seeds, seeds[1:])}
    assert len(deltas) > 1
    # The streams themselves differ: same sampler config, per-process
    # seeds, first fused index batch.
    batches = []
    for s in seeds[:3]:
        smp = make_index_sampler(
            [30] * 6, 3, 2, 2, batch_size=4, seed=s, backend="python"
        )
        si, qi, lab = smp.sample_fused(4)
        batches.append(np.asarray(si).ravel())
    assert not np.array_equal(batches[0], batches[1])
    assert not np.array_equal(batches[0], batches[2])
    assert not np.array_equal(batches[1], batches[2])


def test_assembler_values_and_sharding():
    mesh = make_mesh(dp=8)
    _, ds, tok, _ = _fixture()
    sampler = EpisodeSampler(ds, tok, CFG.n, CFG.k, CFG.q, CFG.batch_size, seed=0)
    sup, qry, lab = batch_to_model_inputs(sampler.sample_batch())
    asm = GlobalBatchAssembler(mesh, CFG.batch_size)
    g_sup, g_qry, g_lab = asm(sup, qry, lab)
    for name, local, global_ in (
        ("word", sup["word"], g_sup["word"]),
        ("mask", qry["mask"], g_qry["mask"]),
        ("label", lab, g_lab),
    ):
        assert isinstance(global_, jax.Array), name
        np.testing.assert_array_equal(np.asarray(global_), local)
        assert global_.sharding.spec[0] == "dp", name


def test_assembler_index_mode():
    mesh = make_mesh(dp=8)
    asm = GlobalBatchAssembler(mesh, 8, index_mode=True)
    sup = np.arange(8 * 3 * 2, dtype=np.int32).reshape(8, 3, 2)
    qry = np.arange(8 * 6, dtype=np.int32).reshape(8, 6)
    lab = np.zeros((8, 6), np.int32)
    g_sup, g_qry, g_lab = asm(sup, qry, lab)
    np.testing.assert_array_equal(np.asarray(g_sup), sup)
    assert g_qry.sharding.spec[0] == "dp"


@pytest.mark.slow
def test_per_host_index_sampler_feeds_cached_mesh_step():
    """The token-cache (index) path under per-host feeding: assembled
    global index batches drive the mesh-sharded cached step identically to
    direct numpy feeding."""
    import jax.numpy as jnp

    from induction_network_on_fewrel_tpu.native.sampler import (
        make_index_sampler,
    )
    from induction_network_on_fewrel_tpu.parallel.sharding import shard_state
    from induction_network_on_fewrel_tpu.train.token_cache import (
        make_token_cached_train_step,
        tokenize_dataset,
    )

    vocab, ds, tok, model = _fixture()
    mesh = make_mesh(dp=8)
    table_np, sizes = tokenize_dataset(ds, tok)
    table = jax.device_put(table_np)

    base = EpisodeSampler(ds, tok, CFG.n, CFG.k, CFG.q, CFG.batch_size, seed=0)
    sup, qry, _ = batch_to_model_inputs(base.sample_batch())
    state = init_state(model, CFG, sup, qry)
    step = make_token_cached_train_step(model, CFG, mesh, state)
    s0 = shard_state(state, mesh)
    s_a = jax.tree.map(jnp.copy, s0)
    s_b = jax.tree.map(jnp.copy, s0)

    mk = lambda: make_index_sampler(
        sizes, CFG.n, CFG.k, CFG.q, batch_size=CFG.batch_size,
        na_rate=0, seed=process_seed(7), backend="python",
    )
    wrapped = PerHostSampler(
        mk(), GlobalBatchAssembler(mesh, CFG.batch_size, index_mode=True)
    )
    direct = mk()
    for _ in range(3):
        di, dq, dl = batch_to_model_inputs(direct.sample_batch())
        s_a, m_a = step(s_a, table, di, dq, dl)
        wi, wq, wl = batch_to_model_inputs(wrapped.sample_batch())
        s_b, m_b = step(s_b, table, wi, wq, wl)
    assert float(m_a["loss"]) == float(m_b["loss"])
    for a, b in zip(jax.tree.leaves(s_a.params), jax.tree.leaves(s_b.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_real_two_process_distributed_cluster():
    """The REAL thing (not the single-process simulation): spawn TWO
    processes, each with 4 virtual CPU devices, joined into one 8-device
    dp mesh via jax.distributed (Gloo over localhost). Each samples only
    its own episode rows and assembles global batches; 3 mesh-sharded
    cached train steps later both processes must agree bitwise on the
    loss and the global param norm — impossible unless the per-host feed
    and the cross-process collectives composed correctly."""
    import json
    import socket
    import subprocess
    import sys as _sys

    with socket.socket() as s:  # free localhost port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "hostfeed_worker.py")
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    # Drain each worker on its own thread: sequential communicate() leaves
    # the sibling's pipes unread, and a full stderr pipe would block it
    # mid-collective, deadlocking both. The finally reaps BOTH workers on
    # any failure so no orphan holds the coordinator for the rest of the
    # pytest session.
    import threading

    procs = [
        subprocess.Popen(
            [_sys.executable, worker, str(pid), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    results: dict[int, tuple] = {}

    def drain(i):
        try:
            results[i] = procs[i].communicate(timeout=420)
        except subprocess.TimeoutExpired:
            results[i] = None

    try:
        threads = [threading.Thread(target=drain, args=(i,)) for i in (0, 1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        outs = []
        for i, p in enumerate(procs):
            assert results[i] is not None, f"worker {i} timed out"
            out, err = results[i]
            assert p.returncode == 0, err[-3000:]
            outs.append(json.loads(out.strip().splitlines()[-1]))
        assert outs[0]["loss"] == outs[1]["loss"], outs
        assert outs[0]["norm"] == outs[1]["norm"], outs
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()


def test_per_host_fused_stack_assembly():
    """sample_fused on a wrapped index sampler returns global [S, B, ...]
    arrays with the scan axis unpartitioned and dp on axis 1 — the fused
    sharded steps' exact input layout."""
    from induction_network_on_fewrel_tpu.native.sampler import (
        make_index_sampler,
    )

    _, ds, tok, _ = _fixture()
    mesh = make_mesh(dp=8)
    from induction_network_on_fewrel_tpu.train.token_cache import (
        tokenize_dataset,
    )

    _, sizes = tokenize_dataset(ds, tok)
    wrapped = PerHostSampler(
        make_index_sampler(
            sizes, CFG.n, CFG.k, CFG.q, batch_size=CFG.batch_size,
            seed=1, backend="python",
        ),
        GlobalBatchAssembler(mesh, CFG.batch_size, index_mode=True),
    )
    sup_s, qry_s, lab_s = wrapped.sample_fused(4)
    assert sup_s.shape[:2] == (4, CFG.batch_size)
    assert qry_s.sharding.spec[0] is None and qry_s.sharding.spec[1] == "dp"
    assert isinstance(lab_s, jax.Array)
    # Live (token-dict) samplers stack per-batch samples host-side.
    vocab, ds2, tok2, _ = _fixture()
    live = PerHostSampler(
        EpisodeSampler(ds2, tok2, CFG.n, CFG.k, CFG.q, CFG.batch_size, seed=2),
        GlobalBatchAssembler(mesh, CFG.batch_size),
    )
    sup_s, qry_s, lab_s = live.sample_fused(3)
    assert sup_s["word"].shape[:2] == (3, CFG.batch_size)
    assert sup_s["word"].sharding.spec[1] == "dp"


@pytest.mark.slow
def test_per_host_sampler_matches_direct_feed():
    """Training through PerHostSampler (assembled global arrays) computes
    the IDENTICAL trajectory as feeding the same sampler's numpy batches
    straight into the sharded step."""
    vocab, ds, tok, model = _fixture()
    mesh = make_mesh(dp=8)

    def make_local():
        return EpisodeSampler(
            ds, tok, CFG.n, CFG.k, CFG.q, CFG.batch_size,
            seed=process_seed(CFG.seed),
        )

    base = make_local()
    sup, qry, _ = batch_to_model_inputs(base.sample_batch())
    state = init_state(model, CFG, sup, qry)
    from induction_network_on_fewrel_tpu.parallel.sharding import shard_state

    step = make_sharded_train_step(model, CFG, mesh, state)

    wrapped = PerHostSampler(
        make_local(), GlobalBatchAssembler(mesh, CFG.batch_size)
    )
    assert wrapped.batch_size == CFG.batch_size

    import jax.numpy as jnp

    s0 = shard_state(state, mesh)
    # Two leaf-copies of ONE state: init_state builds a fresh optimizer
    # closure each call, and the jitted step is traced against this exact
    # pytree (function identities included).
    s_a = jax.tree.map(jnp.copy, s0)
    s_b = jax.tree.map(jnp.copy, s0)
    direct = make_local()
    for _ in range(3):
        ds_sup, ds_qry, ds_lab = batch_to_model_inputs(direct.sample_batch())
        s_a, m_a = step(s_a, ds_sup, ds_qry, ds_lab)
        w_sup, w_qry, w_lab = batch_to_model_inputs(wrapped.sample_batch())
        s_b, m_b = step(s_b, w_sup, w_qry, w_lab)
    assert float(m_a["loss"]) == float(m_b["loss"])
    for a, b in zip(jax.tree.leaves(s_a.params), jax.tree.leaves(s_b.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
