"""Scenario-harness tier-1 gates (ISSUE 10, tools/scenarios.py).

The same artifact discipline as tests/test_roofline.py: the committed
``SCENARIOS_r*.json`` carries a ``tier1`` section (the miniature DA+NOTA
run + regression band), and this file REPLAYS that run in-process — a
change that silently tanks in-domain accuracy, cross-domain accuracy,
DA-mixture recovery, NOTA calibration F1, or adversarial robustness
fails tier-1 before it ships. Re-emitting the artifact
(``python tools/scenarios.py --artifact SCENARIOS_r<next>.json``) is the
ONE sanctioned way to move the recorded numbers.

Plus the pure-math pins: NOTA sweep monotonicity/endpoints/determinism,
query-perturbation shape/dtype discipline, and the domain-shifted
dataset's trigger disjointness.
"""

import glob
import json
import os
import sys

import numpy as np
import pytest

from induction_network_on_fewrel_tpu.data import (
    GloveTokenizer,
    make_domain_shifted_fewrel,
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.datapipe.faults import (
    PerturbedSampler,
    parse_perturbation,
    perturb_query_batch,
)
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler
from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOLS = os.path.join(_REPO, "tools")
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

import obs_report  # noqa: E402
import scenarios  # noqa: E402


def _latest_artifact() -> dict:
    paths = sorted(glob.glob(os.path.join(_REPO, "SCENARIOS_r*.json")))
    assert paths, "no SCENARIOS_r*.json artifact in the repo root"
    with open(paths[-1]) as f:
        return json.load(f)


# --- NOTA sweep math --------------------------------------------------------


def test_nota_operating_points_monotone_and_endpoints():
    """The decision is NOTA iff tau > gap, so the predicted set grows
    with tau: recall and nota_rate nondecreasing. Endpoints: below every
    gap nothing is predicted (precision-1.0-by-convention, recall 0);
    above every gap everything is (recall 1.0)."""
    rng = np.random.default_rng(0)
    gap = rng.normal(0.0, 1.0, 400)
    truth = gap < rng.normal(0.2, 1.0, 400)   # correlated ground truth
    taus = scenarios.default_tau_grid(gap)
    ops = scenarios.nota_operating_points(gap, truth, taus)
    recalls = [o["recall"] for o in ops]
    rates = [o["nota_rate"] for o in ops]
    assert recalls == sorted(recalls)
    assert rates == sorted(rates)
    assert ops[0]["nota_rate"] == 0.0 and ops[0]["precision"] == 1.0
    assert ops[0]["recall"] == 0.0
    assert ops[-1]["recall"] == 1.0 and ops[-1]["nota_rate"] == 1.0
    assert 0.0 in [o["tau"] for o in ops]    # the head's own calibration
    # Deterministic: same inputs -> identical grid and points.
    assert scenarios.nota_operating_points(gap, truth, taus) == ops
    assert scenarios.default_tau_grid(gap) == taus


# --- query perturbations ----------------------------------------------------


def test_parse_perturbation_grammar():
    assert parse_perturbation("token_noise:0.3") == ("token_noise", 0.3)
    assert parse_perturbation("blank") == ("blank", 1.0)
    with pytest.raises(ValueError):
        parse_perturbation("gamma_rays:0.5")
    with pytest.raises(ValueError):
        parse_perturbation("token_noise:1.5")


def _tiny_sampler(seed=0):
    vocab = make_synthetic_glove(vocab_size=120)
    ds = make_synthetic_fewrel(
        num_relations=4, instances_per_relation=8, vocab_size=120, seed=seed
    )
    tok = GloveTokenizer(vocab, max_length=12)
    return EpisodeSampler(ds, tok, n=2, k=2, q=2, batch_size=2, seed=seed)


def test_perturb_query_batch_shapes_and_supports_untouched():
    sampler = _tiny_sampler()
    batch = sampler.sample_batch()
    for mode, rate in (("token_noise", 0.5), ("mask_drop", 0.5),
                       ("blank", 1.0)):
        rng = np.random.default_rng(7)
        out = perturb_query_batch(batch, mode, rate, rng)
        for f in batch._fields:
            assert getattr(out, f).shape == getattr(batch, f).shape
            assert getattr(out, f).dtype == getattr(batch, f).dtype
            if f.startswith("support") or f == "label":
                assert np.array_equal(getattr(out, f), getattr(batch, f)), f
        # Determinism under a fixed rng seed.
        out2 = perturb_query_batch(batch, mode, rate,
                                   np.random.default_rng(7))
        assert np.array_equal(out.query_word, out2.query_word)
        assert np.array_equal(out.query_mask, out2.query_mask)
    noisy = perturb_query_batch(batch, "token_noise", 1.0,
                                np.random.default_rng(3))
    on = batch.query_mask > 0
    assert (noisy.query_word[on] != batch.query_word[on]).mean() > 0.5
    dropped = perturb_query_batch(batch, "mask_drop", 0.5,
                                  np.random.default_rng(3))
    assert dropped.query_mask.sum() < batch.query_mask.sum()


def test_perturbed_sampler_wraps_and_closes():
    ps = PerturbedSampler(_tiny_sampler(), "blank:1.0", seed=5)
    assert ps.batch_size == 2 and ps.total_q == 4
    b = ps.sample_batch()
    on = b.query_mask > 0
    # Every unmasked query token collapsed to one fill value.
    assert len(np.unique(b.query_word[on])) == 1
    ps.close()


# --- domain-shifted twin ----------------------------------------------------


def test_domain_shifted_fewrel_trigger_disjointness():
    src = make_synthetic_fewrel(num_relations=3, instances_per_relation=6,
                                vocab_size=120, seed=4)
    tgt = make_domain_shifted_fewrel(num_relations=3,
                                     instances_per_relation=6,
                                     vocab_size=120, shift=1.0, seed=4)
    assert tgt.rel_names == src.rel_names
    n_trigger = 3 * 3
    src_block = {f"w{i}" for i in range(n_trigger)}
    tgt_tokens = {
        t for rel in tgt.rel_names for inst in tgt.instances[rel]
        for t in inst.tokens
    }
    # At shift=1.0 the source trigger block never appears in the target
    # domain — the signal the source-trained model keys on is GONE.
    assert not (tgt_tokens & src_block)
    shifted_block = {f"w{i}" for i in range(n_trigger, 2 * n_trigger)}
    assert tgt_tokens & shifted_block
    with pytest.raises(ValueError):
        make_domain_shifted_fewrel(shift=1.5)


# --- the tier-1 regression gate --------------------------------------------


def test_scenarios_tier1_regression_gate(tmp_path):
    """Replay the committed artifact's miniature leg in-process; every
    gated quality number must stay within its band (one-sided: quality
    may improve, never silently regress). Also proves the harness emits
    schema-clean kind='scenario' records."""
    art = _latest_artifact()
    t1 = art["tier1"]
    band = t1["band"]["accuracy_abs"]
    f1_band = t1["band"]["f1_abs"]
    logger = MetricsLogger(tmp_path, quiet=True)
    try:
        res = scenarios.run_tier1(seed=int(t1["seed"]), logger=logger)
    finally:
        logger.close()
    head = scenarios.tier1_headline(res)
    for key in ("in_domain_accuracy", "cross_domain_accuracy",
                "da_mixture_accuracy"):
        assert head[key] >= t1[key] - band, (
            f"{key} {head[key]} fell below the recorded {t1[key]} - "
            f"{band} band — a model/loss/sampler change regressed "
            f"scenario quality; re-emit the artifact "
            f"(tools/scenarios.py --artifact) if intended"
        )
    assert head["nota_best_f1"] >= t1["nota_best_f1"] - f1_band
    for spec, acc in t1["adversarial_accuracy"].items():
        assert head["adversarial_accuracy"][spec] >= acc - band, spec
    # Structure: the miniature world still exhibits the cross-domain
    # cliff the harness exists to observe (disjoint triggers at
    # shift=1.0 are untransferable without DA).
    assert head["in_domain_accuracy"] >= \
        head["cross_domain_accuracy"] + 0.2
    # And the DA-mixture arm recovers a real fraction of it.
    assert head["da_mixture_accuracy"] >= \
        head["cross_domain_accuracy"] + 0.2

    # Telemetry: every leg landed as a schema-clean kind="scenario"
    # record, rendered by the obs_report scenarios section.
    n, errors = obs_report.check_schema(tmp_path / "metrics.jsonl")
    assert errors == [], errors
    recs = obs_report.load_records(tmp_path / "metrics.jsonl")
    scen = obs_report.scenario_summary(recs)
    legs = scen["legs"]
    # Grid legs carry their discriminator in the key (cross_domain per
    # shift, nota_calibration per na_rate) so a grid run keeps every row.
    for leg in ("in_domain", "cross_domain", "da_mixture",
                "nota_calibration"):
        assert any(k == leg or k.startswith(leg + "[") for k in legs), (
            leg, sorted(legs),
        )
    assert scen["cross_domain_gap"] >= 0.2
    assert any(leg.startswith("token_noise") for leg in legs)


def test_scenarios_artifact_complete():
    """Acceptance shape: the committed artifact carries cross-domain
    accuracy + CI, the NOTA precision/recall sweep, adversarial legs,
    and the tier1 band block the gate above replays."""
    art = _latest_artifact()
    full = art["full"]
    ind = full["cross_domain"]["in_domain"]
    assert {"accuracy", "acc_ci95"} <= set(ind)
    assert full["cross_domain"]["by_shift"]
    for leg in full["cross_domain"]["by_shift"].values():
        assert {"accuracy", "acc_ci95", "shift"} <= set(leg)
    assert "da_mixture" in full["cross_domain"]
    for na, block in full["nota"].items():
        ops = block["operating_points"]
        assert len(ops) >= 5
        assert all({"tau", "precision", "recall", "f1"} <= set(o)
                   for o in ops)
        assert {"nota_rate", "margin", "entropy"} <= set(block["baseline"])
    adv = [k for k in full["adversarial"] if k != "clean"]
    assert len(adv) >= 2
    t1 = art["tier1"]
    assert {"in_domain_accuracy", "cross_domain_accuracy",
            "da_mixture_accuracy", "nota_best_f1", "band"} <= set(t1)


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
