"""Regression-gated bench trajectory (ISSUE 11, tools/bench_trend.py):
the committed TREND.json must cover every committed perf artifact, the
--check gate must run green against the repo as committed, and a
synthetic out-of-band leg must fail it — so the trajectory can never be
empty or silently regress again.
"""

import glob
import json
import os
import shutil
import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent
_TOOLS = str(_REPO / "tools")
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

import bench_trend  # noqa: E402


def _copy_artifacts(dst: Path) -> None:
    for pattern, _ in bench_trend._EXTRACTORS:
        for f in glob.glob(str(_REPO / pattern)):
            shutil.copy(f, dst)


def test_trend_covers_every_committed_artifact():
    """Every BENCH/ROOFLINE/COMMS/SERVE artifact in the repo contributes
    at least one point (zero-contribution = extractor drift, a problem),
    and the headline series exist with the committed history."""
    trend, problems = bench_trend.build_trend(_REPO)
    assert not problems, problems
    n_artifacts = sum(
        len(glob.glob(str(_REPO / p))) for p, _ in bench_trend._EXTRACTORS
    )
    assert n_artifacts >= 15            # 5 BENCH + 3 ROOFLINE + 5 COMMS + 2 SERVE
    assert len(trend["inputs"]) == n_artifacts
    series = trend["series"]
    assert any(k.startswith("bench.eps_per_s[") for k in series)
    assert any(k.startswith("bench.mfu[") for k in series)
    # The round-6 -> round-8 byte diet is IN the trajectory.
    sb = [p["value"] for p in series["roofline.step_bytes"]["points"]]
    assert sb == [798687980, 634847980]
    # The comms diet (round-6 dense flagship -> round-7 compact ->
    # round-10 bucketed shard_map, which also deletes the partitioner's
    # resharding permutes) likewise.
    comms = [
        p["value"]
        for p in series["comms.flagship_payload_bytes"]["points"]
    ]
    assert comms[0] == 33719548 and comms[-1] == 5188148
    assert 7746548 in comms
    # And the round-10 measured overlap headline is banded at its floor.
    ovf = series["comms.flagship_overlap_frac"]
    assert [p["value"] for p in ovf["points"]] == [1.0]
    assert ovf["band"] == {"rule": "floor", "tol": 0.92}
    # Scheduler-A/B ratio present for both SERVE rounds.
    assert len(series["serve.closed_qps_ratio"]["points"]) == 2


def test_trend_json_committed_and_fresh():
    """TREND.json is committed and regenerating it yields the committed
    ARTIFACT-ONLY content — the staleness half of --check (live
    TREND_INPUT.jsonl rows are machine-local and excluded from the
    equality on both sides, so a local bench run never fails this)."""
    committed = json.loads((_REPO / "TREND.json").read_text())
    trend, _ = bench_trend.build_trend(_REPO)
    assert bench_trend._strip_live(committed) == \
        bench_trend._strip_live(trend), (
        "TREND.json is stale — re-run tools/bench_trend.py and commit"
    )


def test_check_green_on_committed_repo():
    """The tier-1 gate: --check exits 0 against the repo as committed."""
    assert bench_trend.main(["--root", str(_REPO), "--check"]) == 0


def test_check_fails_on_stale_trend(tmp_path):
    """A new artifact without a TREND.json regeneration is a staleness
    failure — committing a bench round WITHOUT refreshing the trajectory
    can never pass tier-1."""
    _copy_artifacts(tmp_path)
    assert bench_trend.main(["--root", str(tmp_path)]) == 0
    r5 = json.loads((_REPO / "BENCH_r05.json").read_text())
    (tmp_path / "BENCH_r06.json").write_text(
        json.dumps({"n": 6, "parsed": r5["parsed"]})
    )
    rc = bench_trend.main(["--root", str(tmp_path), "--check"])
    assert rc == 1


def test_check_fails_on_synthetic_out_of_band_leg(tmp_path, capsys):
    """The demonstrated failure the acceptance asks for: a fresh BENCH
    leg 50% below the committed band (same config string, so it shares
    the series) fails --check even after the trajectory is regenerated."""
    _copy_artifacts(tmp_path)
    r5 = json.loads((_REPO / "BENCH_r05.json").read_text())
    bad = dict(r5["parsed"], value=8000.0, mfu=0.10)
    (tmp_path / "BENCH_r06.json").write_text(
        json.dumps({"n": 6, "parsed": bad})
    )
    assert bench_trend.main(["--root", str(tmp_path)]) == 0  # regenerate
    rc = bench_trend.main(["--root", str(tmp_path), "--check"])
    assert rc == 1
    err = capsys.readouterr().err
    assert "out of band" in err and "eps_per_s" in err


def test_candidate_gate(tmp_path):
    """--candidate validates a fresh bench summary against committed
    bands without requiring a commit: in-band passes, out-of-band fails.
    (The committed trend in tmp is a faithful copy, so the candidate's
    config series exists.)"""
    _copy_artifacts(tmp_path)
    assert bench_trend.main(["--root", str(tmp_path)]) == 0
    r5 = json.loads((_REPO / "BENCH_r05.json").read_text())
    good = tmp_path / "cand_good.json"
    good.write_text(json.dumps(dict(r5["parsed"], value=16900.0)))
    assert bench_trend.main(
        ["--root", str(tmp_path), "--check", "--candidate", str(good)]
    ) == 0
    bad = tmp_path / "cand_bad.json"
    bad.write_text(json.dumps(dict(r5["parsed"], value=8000.0)))
    assert bench_trend.main(
        ["--root", str(tmp_path), "--check", "--candidate", str(bad)]
    ) == 1


def test_bench_appends_live_rows_and_trend_folds_them(tmp_path, monkeypatch):
    """bench.py's trajectory-input append (the from-this-PR-onward
    population path): a run summary appended to TREND_INPUT.jsonl is
    folded into the trajectory as a live point, keyed by its own metric
    bracket (a CPU fallback row never shares a TPU band)."""
    sys.path.insert(0, str(_REPO))
    import bench

    dest = tmp_path / "TREND_INPUT.jsonl"
    monkeypatch.setenv("BENCH_TREND_FILE", str(dest))
    summary = {
        "metric": "train_episodes_per_sec_per_chip[5w5s,bilstm,cpu,test]",
        "value": 123.4, "mfu": None,
    }
    bench._append_trend_input(summary, "cpu")
    bench._append_trend_input(dict(summary, value=125.0), "cpu")
    rows = [json.loads(x) for x in dest.read_text().splitlines()]
    assert [r["value"] for r in rows] == [123.4, 125.0]
    assert rows[0]["backend"] == "cpu"

    _copy_artifacts(tmp_path)   # dest already IS tmp_path/TREND_INPUT.jsonl
    trend, problems = bench_trend.build_trend(tmp_path)
    assert not problems
    assert trend["live_rows"] == 2
    live = trend["series"]["bench.eps_per_s[5w5s,bilstm,cpu,test]"]
    assert [p["value"] for p in live["points"]] == [123.4, 125.0]
    assert all(p["round"] is None for p in live["points"])
    # BENCH_TREND_FILE='' disables the append (read-only checkouts).
    monkeypatch.setenv("BENCH_TREND_FILE", "")
    os.remove(dest)
    bench._append_trend_input(summary, "cpu")
    assert not dest.exists()


def test_local_bench_run_does_not_trip_staleness_or_bands(tmp_path):
    """A machine-local bench run (live rows in TREND_INPUT.jsonl with no
    TREND.json regeneration) must NOT fail --check — neither the
    staleness gate (artifact-only equality) nor the BAND gate (two
    local runs under different sandbox weather must not fail tier-1 on
    one machine; fresh runs gate via --candidate). A new committed
    artifact still does (test_check_fails_on_stale_trend)."""
    _copy_artifacts(tmp_path)
    assert bench_trend.main(["--root", str(tmp_path)]) == 0
    r5 = json.loads((_REPO / "BENCH_r05.json").read_text())
    rows = [
        {"metric": "train_episodes_per_sec_per_chip[5w5s,local,test]",
         "value": 99.0, "backend": "cpu"},
        # Wildly out of band for a COMMITTED config's series: still must
        # not gate (live rows are recorded, not banded).
        dict(r5["parsed"], value=10.0),
    ]
    (tmp_path / bench_trend.LIVE_NAME).write_text(
        "\n".join(json.dumps(r) for r in rows) + "\n"
    )
    assert bench_trend.main(["--root", str(tmp_path), "--check"]) == 0
