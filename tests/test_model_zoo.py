"""Sibling few-shot models (proto_hatt, gnn, snail): shapes, NOTA, training.

SURVEY.md §2.1 "Few-shot model": the toolkit family ships sibling episode
models next to the induction network; each exposes the same
``(support, query) -> logits [B, TQ, N(+1)]`` surface, so one parametrized
suite covers all of them.
"""

import jax
import jax.numpy as jnp
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    GloveTokenizer,
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.models.build import batch_to_model_inputs
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler

L = 16
MODELS = ["proto_hatt", "siamese", "gnn", "snail", "metanet"]
BASE = ExperimentConfig(
    encoder="cnn", train_n=4, n=4, k=2, q=3, batch_size=2, max_length=L,
    vocab_size=302, compute_dtype="float32", hidden_size=64,
    gnn_dim=16, gnn_adj_hidden=16, snail_tc_filters=16,
)


@pytest.fixture(scope="module")
def episode():
    vocab = make_synthetic_glove(vocab_size=300)
    ds = make_synthetic_fewrel(num_relations=8, instances_per_relation=10, vocab_size=300)
    tok = GloveTokenizer(vocab, max_length=L)
    s = EpisodeSampler(ds, tok, n=4, k=2, q=3, batch_size=2, seed=0)
    return vocab, batch_to_model_inputs(s.sample_batch())


@pytest.mark.parametrize("name", MODELS)
def test_forward_shapes(episode, name):
    vocab, (sup, qry, label) = episode
    model = build_model(BASE.replace(model=name), glove_init=vocab.vectors)
    params = model.init(jax.random.key(0), sup, qry)
    logits = model.apply(params, sup, qry)
    assert logits.shape == (2, 12, 4)
    assert logits.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(logits)))


@pytest.mark.parametrize("name", MODELS)
def test_nota_head(episode, name):
    vocab, (sup, qry, _) = episode
    model = build_model(
        BASE.replace(model=name, na_rate=1), glove_init=vocab.vectors
    )
    params = model.init(jax.random.key(0), sup, qry)
    logits = model.apply(params, sup, qry)
    assert logits.shape == (2, 12, 5)  # N+1 classes


@pytest.mark.parametrize("name", MODELS)
def test_jit_forward(episode, name):
    vocab, (sup, qry, _) = episode
    model = build_model(BASE.replace(model=name), glove_init=vocab.vectors)
    params = model.init(jax.random.key(0), sup, qry)
    jitted = jax.jit(model.apply)
    logits = jitted(params, sup, qry)
    assert logits.shape == (2, 12, 4)


def test_snail_reads_the_support_prefix(episode):
    """The query position must actually read the support prefix through the
    causal attention: permuting which encodings sit in which class slot
    (labels are positional) must change the query logits."""
    import numpy as np

    vocab, (sup, qry, _) = episode
    model = build_model(BASE.replace(model="snail"), glove_init=vocab.vectors)
    params = model.init(jax.random.key(0), sup, qry)
    logits = model.apply(params, sup, qry)

    perm = [1, 0, 3, 2]  # swap class slots 0<->1 and 2<->3
    sup_perm = {k: v[:, perm] for k, v in sup.items()}
    logits_perm = model.apply(params, sup_perm, qry)
    assert not np.allclose(np.asarray(logits), np.asarray(logits_perm)), (
        "query logits ignored the support set"
    )


def test_siamese_matches_naive_pair_metric(episode):
    """The einsum-expanded metric must equal the naive [B,TQ,N,K,H] pair
    computation: s(q,e) = -Σ w (q-e)² + Σ v q e + b, class = mean over K."""
    import numpy as np

    vocab, (sup, qry, _) = episode
    model = build_model(BASE.replace(model="siamese"), glove_init=vocab.vectors)
    params = model.init(jax.random.key(1), sup, qry)
    logits = np.asarray(model.apply(params, sup, qry))

    enc_fn = lambda s, q: model.apply(params, s, q, method=model.encode_episode)
    sup_enc, qry_enc = map(np.asarray, enc_fn(sup, qry))
    p = params["params"]
    w, v, b = map(np.asarray, (p["metric_w"], p["metric_v"], p["metric_b"]))
    B, N, K, H = sup_enc.shape
    naive = np.zeros_like(logits)
    for bi in range(B):
        for qi in range(qry_enc.shape[1]):
            for ni in range(N):
                scores = [
                    -np.sum(w * (qry_enc[bi, qi] - sup_enc[bi, ni, ki]) ** 2)
                    + np.sum(v * qry_enc[bi, qi] * sup_enc[bi, ni, ki]) + b
                    for ki in range(K)
                ]
                naive[bi, qi, ni] = np.mean(scores)
    np.testing.assert_allclose(logits, naive, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("name", ["gnn", "snail", "metanet"])
def test_n_mismatch_rejected(name):
    """These models bake N into param shapes; trainN != N must fail fast."""
    with pytest.raises(ValueError, match="trainN"):
        build_model(BASE.replace(model=name, train_n=6, n=4))


def test_gnn_adjacency_forms_equivalent():
    """The one-hot adjacency form and its large-T broadcast fallback
    (gnn._AdjacencyMLP.one_hot_max_t size guard) compute the same
    row-stochastic adjacency from the same params."""
    import numpy as np

    from induction_network_on_fewrel_tpu.models.gnn import _AdjacencyMLP

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(3, 7, 10)).astype(np.float32))
    onehot = _AdjacencyMLP(hidden=8, compute_dtype=jnp.float32)
    bcast = _AdjacencyMLP(hidden=8, compute_dtype=jnp.float32,
                          one_hot_max_t=4)  # T=7 > 4 forces the fallback
    params = onehot.init(jax.random.key(0), x)
    a1 = onehot.apply(params, x)
    a2 = bcast.apply(params, x)  # identical param tree: forms interchange
    np.testing.assert_allclose(np.asarray(a1), np.asarray(a2),
                               rtol=1e-5, atol=1e-6)
    # Both keep self-edges masked and rows stochastic.
    for a in (a1, a2):
        np.testing.assert_allclose(np.asarray(a).sum(-1), 1.0, rtol=1e-5)
        assert float(np.abs(np.asarray(a)[:, np.arange(7), np.arange(7)]).max()) < 1e-6


def test_checkpoint_merge_carries_model_geometry():
    """Geometry fields that shape params (k for proto_hatt, n for gnn) ride
    along in merge_architecture_from so restores don't hit shape errors."""
    saved = BASE.replace(model="proto_hatt", k=5)
    runtime = BASE.replace(model="proto_hatt", k=1)
    assert runtime.merge_architecture_from(saved).k == 5

    saved = BASE.replace(model="gnn", train_n=10, n=10)
    runtime = BASE.replace(model="gnn", train_n=5, n=5)
    merged = runtime.merge_architecture_from(saved)
    assert (merged.train_n, merged.n) == (10, 10)

    # induction stays N/K-agnostic: eval geometry is the runtime's own.
    saved = BASE.replace(model="induction", k=5)
    runtime = BASE.replace(model="induction", k=1)
    assert runtime.merge_architecture_from(saved).k == 1


@pytest.mark.parametrize("name", MODELS)
def test_trains_end_to_end(name):
    from induction_network_on_fewrel_tpu.train.steps import init_state, make_train_step

    cfg = BASE.replace(
        model=name, train_n=2, n=2, k=2, q=2, batch_size=2, loss="ce", lr=1e-2
    )
    vocab = make_synthetic_glove(vocab_size=300)
    ds = make_synthetic_fewrel(num_relations=4, instances_per_relation=8, vocab_size=300)
    tok = GloveTokenizer(vocab, max_length=L)
    sampler = EpisodeSampler(ds, tok, n=2, k=2, q=2, batch_size=2, seed=0)
    model = build_model(cfg, glove_init=vocab.vectors)
    sup, qry, label = batch_to_model_inputs(sampler.sample_batch())
    state = init_state(model, cfg, sup, qry)
    step = make_train_step(model, cfg)
    first = None
    for _ in range(30):
        state, metrics = step(state, sup, qry, label)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first


# --- BERT-PAIR -------------------------------------------------------------

PAIR = BASE.replace(
    model="pair", encoder="bert", bert_layers=2, bert_hidden=32,
    bert_heads=2, bert_intermediate=64, bert_vocab_size=64, bert_frozen=False,
)


def _pair_episode():
    from induction_network_on_fewrel_tpu.data.bert_tokenizer import BertTokenizer

    ds = make_synthetic_fewrel(num_relations=8, instances_per_relation=10, vocab_size=300)
    tok = BertTokenizer(L, vocab_size=64)
    s = EpisodeSampler(ds, tok, n=4, k=2, q=3, batch_size=2, seed=0)
    return batch_to_model_inputs(s.sample_batch())


def test_pair_forward_shapes():
    sup, qry, label = _pair_episode()
    model = build_model(PAIR)
    params = model.init(jax.random.key(0), sup, qry)
    logits = model.apply(params, sup, qry)
    assert logits.shape == (2, 12, 4)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_pair_nota_head():
    sup, qry, _ = _pair_episode()
    model = build_model(PAIR.replace(na_rate=1))
    params = model.init(jax.random.key(0), sup, qry)
    assert model.apply(params, sup, qry).shape == (2, 12, 5)


def test_pair_requires_bert():
    with pytest.raises(ValueError, match="encoder bert"):
        build_model(BASE.replace(model="pair", encoder="cnn"))


def test_pair_trains_end_to_end():
    from induction_network_on_fewrel_tpu.train.steps import init_state, make_train_step

    cfg = PAIR.replace(loss="ce", lr=1e-3)
    sup, qry, label = _pair_episode()
    model = build_model(cfg)
    state = init_state(model, cfg, sup, qry)
    step = make_train_step(model, cfg)
    first = None
    for _ in range(20):
        state, metrics = step(state, sup, qry, label)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first
