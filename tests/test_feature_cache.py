"""Frozen-encoder feature cache (train/feature_cache.py): encode-once parity,
sampler statistics, head-only training."""

import jax
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import make_synthetic_fewrel
from induction_network_on_fewrel_tpu.data.bert_tokenizer import BertTokenizer
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.models.base import FewShotModel
from induction_network_on_fewrel_tpu.models.build import batch_to_model_inputs
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler
from induction_network_on_fewrel_tpu.train.feature_cache import (
    FeatureEpisodeSampler,
    encode_dataset,
)

L = 16
CFG = ExperimentConfig(
    model="proto", encoder="bert", n=3, k=2, q=2, batch_size=2, max_length=L,
    bert_layers=2, bert_hidden=32, bert_heads=2, bert_intermediate=64,
    bert_vocab_size=64, bert_frozen=True, compute_dtype="float32", lr=1e-2,
)


@pytest.fixture(scope="module")
def setup():
    ds = make_synthetic_fewrel(num_relations=6, instances_per_relation=9, vocab_size=300)
    tok = BertTokenizer(L, vocab_size=64)
    model = build_model(CFG)
    sampler = EpisodeSampler(ds, tok, CFG.n, CFG.k, CFG.q, CFG.batch_size, seed=0)
    sup, qry, _ = batch_to_model_inputs(sampler.sample_batch())
    full_params = model.init(jax.random.key(0), sup, qry)
    return ds, tok, model, full_params, sampler


def test_encode_dataset_matches_direct_encode(setup):
    """Cache rows == encoding the same instance directly (incl. the padded
    final chunk: batch_size 4 does not divide 6*9=54 instances)."""
    ds, tok, model, params, _ = setup
    blocks = encode_dataset(model, params, ds, tok, batch_size=4)
    assert len(blocks) == 6 and all(b.shape == (9, 32) for b in blocks)

    rel = ds.rel_names[2]
    t = tok(ds.instances[rel][5])
    direct = model.apply(
        params, t.word[None], t.pos1[None], t.pos2[None], t.mask[None],
        method=FewShotModel.encode,
    )
    np.testing.assert_allclose(blocks[2][5], np.asarray(direct)[0], atol=1e-5)


def test_feature_episode_parity_with_token_episode(setup):
    """Model logits on a feature episode == logits on the token episode the
    features came from (same params; the head math is identical)."""
    ds, tok, model, params, sampler = setup
    sup, qry, label = batch_to_model_inputs(sampler.sample_batch())
    logits_tok = model.apply(params, sup, qry)

    def enc(d, lead):
        flat = lambda a: a.reshape(-1, L)
        out = model.apply(
            params, flat(d["word"]), flat(d["pos1"]), flat(d["pos2"]),
            flat(d["mask"]), method=FewShotModel.encode,
        )
        return np.asarray(out).reshape(*lead, -1)

    sup_f = enc(sup, sup["word"].shape[:-1])
    qry_f = enc(qry, qry["word"].shape[:-1])
    logits_feat = model.apply(params, sup_f, qry_f)
    np.testing.assert_allclose(
        np.asarray(logits_tok), np.asarray(logits_feat), atol=1e-5
    )


def test_feature_sampler_statistics():
    rng = np.random.default_rng(0)
    blocks = [rng.normal(size=(8, 16)).astype(np.float32) for _ in range(6)]
    s = FeatureEpisodeSampler(blocks, n=3, k=2, q=2, batch_size=4, na_rate=1, seed=1)
    b = s.sample_batch()
    assert b.support.shape == (4, 3, 2, 16)
    assert b.query.shape == (4, s.total_q, 16) == (4, 8, 16)
    assert b.label.shape == (4, 8)
    # NOTA negatives labeled N, exactly na_rate*q of them per episode
    assert (b.label == 3).sum(axis=1).tolist() == [2, 2, 2, 2]
    # determinism: same seed -> same batch
    b2 = FeatureEpisodeSampler(blocks, 3, 2, 2, 4, na_rate=1, seed=1).sample_batch()
    np.testing.assert_array_equal(b.label, b2.label)
    np.testing.assert_array_equal(b.support, b2.support)

    with pytest.raises(ValueError, match="K\\+Q"):
        FeatureEpisodeSampler([np.zeros((3, 4), np.float32)] * 4, 3, 2, 2)


def test_head_only_training_converges(setup):
    """init on a feature episode builds a HEAD-ONLY state (no backbone
    params) and the head overfits a fixed feature batch.

    Uses the induction model: its head (squash transform + NTN) has real
    parameters. proto-euclid with a frozen encoder has NOTHING trainable —
    see test_proto_frozen_cache_has_no_trainable_params below.
    """
    from induction_network_on_fewrel_tpu.train.steps import init_state, make_train_step

    ds, tok, _, _, _ = setup
    cfg = CFG.replace(model="induction", induction_dim=32, ntn_slices=16)
    model = build_model(cfg)
    # Full init (token inputs) for the cache build.
    tok_sampler = EpisodeSampler(ds, tok, cfg.n, cfg.k, cfg.q, cfg.batch_size, seed=0)
    sup_t, qry_t, _ = batch_to_model_inputs(tok_sampler.sample_batch())
    full_params = model.init(jax.random.key(0), sup_t, qry_t)

    blocks = encode_dataset(model, full_params, ds, tok, batch_size=16)
    fs = FeatureEpisodeSampler(blocks, cfg.n, cfg.k, cfg.q, cfg.batch_size, seed=3)
    b = fs.sample_batch()

    state = init_state(model, cfg, b.support, b.query)
    assert "backbone" not in str(jax.tree_util.tree_structure(state.params))
    n_params = sum(x.size for x in jax.tree.leaves(state.params))
    n_full = sum(x.size for x in jax.tree.leaves(full_params))
    assert 0 < n_params < n_full / 2  # head only, but not empty

    step = make_train_step(model, cfg)
    first = None
    for _ in range(40):  # fixed batch: loss must monotonically-ish fall
        state, metrics = step(state, b.support, b.query, b.label)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < 0.5 * first


def test_proto_frozen_cache_has_no_trainable_params(setup):
    """proto-euclid + frozen encoder = zero trainable parameters: training
    is a no-op (true in the reference family too — proto has no head
    weights). Pinned so the degenerate combo is a documented fact, not a
    surprise."""
    from induction_network_on_fewrel_tpu.train.steps import init_state

    ds, tok, model, full_params, _ = setup
    blocks = encode_dataset(model, full_params, ds, tok, batch_size=16)
    fs = FeatureEpisodeSampler(blocks, CFG.n, CFG.k, CFG.q, CFG.batch_size, seed=3)
    b = fs.sample_batch()
    state = init_state(model, CFG, b.support, b.query)
    assert sum(x.size for x in jax.tree.leaves(state.params)) == 0


def test_index_mode_matches_feature_mode():
    """Same seed => index-mode episodes gather to exactly the feature-mode
    batches (one RNG stream, two output forms)."""
    rng = np.random.default_rng(0)
    blocks = [rng.normal(size=(8, 16)).astype(np.float32) for _ in range(6)]
    fa = FeatureEpisodeSampler(blocks, 3, 2, 2, 4, na_rate=1, seed=7)
    fi = FeatureEpisodeSampler(blocks, 3, 2, 2, 4, na_rate=1, seed=7,
                               return_indices=True)
    a, b = fa.sample_batch(), fi.sample_batch()
    np.testing.assert_array_equal(a.label, b.label)
    np.testing.assert_array_equal(a.support, fi.table[b.support_idx])
    np.testing.assert_array_equal(a.query, fi.table[b.query_idx])


@pytest.mark.slow
def test_cached_steps_match_feature_steps(setup):
    """Device-side gather (make_cached_train_step) == materialized-feature
    step: same updates, same metrics; fused twin matches sequential."""
    import jax.numpy as jnp

    from induction_network_on_fewrel_tpu.train.feature_cache import (
        make_cached_multi_train_step,
        make_cached_train_step,
    )
    from induction_network_on_fewrel_tpu.train.steps import init_state, make_train_step

    ds, tok, _, _, _ = setup
    cfg = CFG.replace(model="induction", induction_dim=32, ntn_slices=16)
    model = build_model(cfg)
    tok_sampler = EpisodeSampler(ds, tok, cfg.n, cfg.k, cfg.q, cfg.batch_size, seed=0)
    sup_t, qry_t, _ = batch_to_model_inputs(tok_sampler.sample_batch())
    full_params = model.init(jax.random.key(0), sup_t, qry_t)
    blocks = encode_dataset(model, full_params, ds, tok, batch_size=16)
    fs = FeatureEpisodeSampler(blocks, cfg.n, cfg.k, cfg.q, cfg.batch_size,
                               seed=5, return_indices=True)
    table = jnp.asarray(fs.table)
    batches = [fs.sample_batch() for _ in range(3)]

    state_a = init_state(model, cfg, fs.table[batches[0].support_idx],
                         fs.table[batches[0].query_idx])
    state_b = jax.tree.map(lambda x: jnp.array(x, copy=True), state_a)
    state_c = jax.tree.map(lambda x: jnp.array(x, copy=True), state_a)

    feat_step = make_train_step(model, cfg)
    cached_step = make_cached_train_step(model, cfg)
    for b in batches:
        state_a, m_a = feat_step(
            state_a, fs.table[b.support_idx], fs.table[b.query_idx], b.label
        )
        state_b, m_b = cached_step(
            state_b, table, b.support_idx, b.query_idx, b.label
        )
    np.testing.assert_allclose(float(m_a["loss"]), float(m_b["loss"]), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        state_a.params, state_b.params,
    )

    multi = make_cached_multi_train_step(model, cfg)
    si = np.stack([b.support_idx for b in batches])
    qi = np.stack([b.query_idx for b in batches])
    ls = np.stack([b.label for b in batches])
    state_c, m_s = multi(state_c, table, si, qi, ls)
    assert np.asarray(m_s["loss"]).shape == (3,)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        state_b.params, state_c.params,
    )
