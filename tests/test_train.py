"""Training framework: jitted step updates, overfit integration (SURVEY.md
§4.4), eval loop, metrics logging."""

import jax
import jax.numpy as jnp
import numpy as np

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    GloveTokenizer,
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.models.build import batch_to_model_inputs
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler
from induction_network_on_fewrel_tpu.train import FewShotTrainer
from induction_network_on_fewrel_tpu.train.steps import init_state, make_train_step
from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

L = 16


def _setup(cfg, num_relations=4, seed=0):
    vocab = make_synthetic_glove(vocab_size=300)
    ds = make_synthetic_fewrel(
        num_relations=num_relations, instances_per_relation=20, vocab_size=300, seed=seed
    )
    tok = GloveTokenizer(vocab, max_length=L)
    sampler = EpisodeSampler(
        ds, tok, n=cfg.n, k=cfg.k, q=cfg.q, batch_size=cfg.batch_size,
        na_rate=cfg.na_rate, seed=seed,
    )
    model = build_model(cfg, glove_init=vocab.vectors)
    return model, sampler


def test_train_step_updates_params():
    cfg = ExperimentConfig(
        encoder="cnn", n=2, k=2, q=2, batch_size=2, max_length=L, vocab_size=302,
        compute_dtype="float32", lr=1e-2,
    )
    model, sampler = _setup(cfg)
    sup, qry, label = batch_to_model_inputs(sampler.sample_batch())
    state = init_state(model, cfg, sup, qry)
    step = make_train_step(model, cfg)
    p0 = jax.tree.map(lambda x: np.asarray(x).copy(), state.params)
    state, metrics = step(state, sup, qry, label)
    assert int(state.step) == 1
    assert np.isfinite(float(metrics["loss"]))
    changed = jax.tree.map(
        lambda a, b: not np.array_equal(np.asarray(a), b), state.params, p0
    )
    assert any(jax.tree.leaves(changed))


def test_overfit_two_relations(tmp_path):
    """2-way synthetic episodes must overfit to ~1.0 accuracy (SURVEY §4.4).

    Asserted on the BEST eval across training chunks, not the final state:
    MSE+sigmoid trajectories peak and then drift toward the all-same-score
    optimum (the BASELINE.md degenerate-optimum finding), and WHERE the
    400-step mark lands on that arc is chaotic — any fp-reassociation
    change (XLA version, device count, an exact-gradient rewrite) moves
    it. Best-across-training is also what the production trainer ships
    (best-val checkpoint selection), so this mirrors real semantics.
    """
    cfg = ExperimentConfig(
        encoder="cnn", n=2, k=2, q=2, batch_size=4, max_length=L, vocab_size=302,
        compute_dtype="float32", lr=5e-3, loss="mse", val_step=0, weight_decay=0.0,
    )
    model, sampler = _setup(cfg, num_relations=4)
    trainer = FewShotTrainer(
        model, cfg, sampler, logger=MetricsLogger(tmp_path, quiet=True)
    )
    best, state = 0.0, None
    for _ in range(4):
        state = trainer.train(state=state, num_iters=200)
        best = max(
            best, trainer.evaluate(state.params, num_episodes=40, sampler=sampler)
        )
        if best > 0.9:
            break
    assert best > 0.9, f"best overfit accuracy {best}"
    assert (tmp_path / "metrics.jsonl").exists()


def test_ce_loss_also_trains():
    cfg = ExperimentConfig(
        encoder="cnn", n=2, k=2, q=2, batch_size=4, max_length=L, vocab_size=302,
        compute_dtype="float32", lr=5e-3, loss="ce", val_step=0,
    )
    model, sampler = _setup(cfg)
    trainer = FewShotTrainer(model, cfg, sampler)
    state = trainer.train(num_iters=100)
    acc = trainer.evaluate(state.params, num_episodes=20, sampler=sampler)
    assert acc > 0.8, f"ce accuracy {acc}"


def test_checkpoint_format_version_guard(tmp_path):
    """A populated ckpt dir from an older param-tree layout must fail with a
    clear versioning error, not an opaque orbax tree mismatch."""
    import pytest

    from induction_network_on_fewrel_tpu.train.checkpoint import (
        FORMAT_VERSION,
        CheckpointManager,
    )

    cfg = ExperimentConfig(encoder="bilstm", vocab_size=102)
    d = tmp_path / "ck"
    CheckpointManager(d, cfg)  # fresh dir: stamps the current version
    assert (d / "format_version").read_text() == str(FORMAT_VERSION)
    CheckpointManager(d, cfg)  # same version: fine

    (d / "format_version").write_text("1")
    with pytest.raises(ValueError, match="format"):
        CheckpointManager(d, cfg)

    # Pre-versioning dir: has step dirs but no version file -> treated as v1.
    legacy = tmp_path / "legacy"
    (legacy / "7").mkdir(parents=True)
    (legacy / "config.json").write_text(cfg.to_json())
    with pytest.raises(ValueError, match="format"):
        CheckpointManager(legacy, cfg)

    # v1 -> v2 changed only the BiLSTM tree: a v1 *cnn* checkpoint still
    # restores, so the guard must let it through.
    cnn = ExperimentConfig(encoder="cnn", vocab_size=102)
    ok = tmp_path / "cnn_legacy"
    (ok / "7").mkdir(parents=True)
    (ok / "config.json").write_text(cnn.to_json())
    CheckpointManager(ok, cnn)  # no raise


def test_v3_attention_rename_migration(tmp_path):
    """A v3 (round-4) bilstm checkpoint — attention params still named
    Dense_0/Dense_1 — restores into the v4 build bit-for-bit via the
    structural rename fallback (a pure rename must not wall off trained
    weights; review finding, round 5)."""
    import orbax.checkpoint as ocp

    from induction_network_on_fewrel_tpu.train.checkpoint import (
        CheckpointManager,
        _rename_attn,
    )

    cfg = ExperimentConfig(
        encoder="bilstm", n=2, k=2, q=2, batch_size=2, max_length=L,
        vocab_size=302, compute_dtype="float32", lstm_hidden=8, att_dim=4,
        induction_dim=8, ntn_slices=4,
    )
    model, sampler = _setup(cfg)
    sup, qry, _ = batch_to_model_inputs(sampler.sample_batch())
    state = init_state(model, cfg, sup, qry)

    # Write a checkpoint the way the REAL v3 build saved it: StandardSave
    # of the host TrainState PYTREE (containers intact — the opt_state
    # tuple must survive as a tuple; a state-dict-shaped fixture would
    # hide the container mismatch the migration has to handle — review
    # finding, round 5), with the attention pair under its old names in
    # params AND the mirrored Adam moment trees.
    host_v3, changed = _rename_attn(jax.device_get(state), to_v3=True)
    assert changed  # params + mu + nu all carry the pair
    d = tmp_path / "ck"
    d.mkdir()
    raw = ocp.CheckpointManager(
        d,
        options=ocp.CheckpointManagerOptions(
            best_fn=lambda m: m["val_accuracy"], best_mode="max"
        ),
    )
    raw.save(
        7, args=ocp.args.StandardSave(host_v3),
        metrics={"val_accuracy": 0.5},
    )
    raw.wait_until_finished()
    raw.close()
    (d / "format_version").write_text("3")
    (d / "config.json").write_text(cfg.to_json())

    mgr = CheckpointManager(d, cfg)  # v3 + migration: must not raise
    try:
        restored, step = mgr.restore_best(jax.device_get(state))
    finally:
        mgr.close()
    assert step == 7
    for a, b in zip(
        jax.tree.leaves(jax.device_get(state)), jax.tree.leaves(restored)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # The v4 names are present on the restored tree.
    assert "att_w1" in restored.params["params"]["encoder"]


def test_fused_multi_step_matches_sequential():
    """steps_per_call fusion must compute the IDENTICAL update sequence:
    S scanned steps == S sequential single steps on the same batches."""
    from induction_network_on_fewrel_tpu.train.steps import make_multi_train_step

    cfg = ExperimentConfig(
        encoder="cnn", n=2, k=2, q=2, batch_size=2, max_length=L, vocab_size=302,
        compute_dtype="float32", lr=1e-2,
    )
    model, sampler = _setup(cfg)
    batches = [batch_to_model_inputs(sampler.sample_batch()) for _ in range(4)]
    sup0, qry0, _ = batches[0]

    state_a = init_state(model, cfg, sup0, qry0)
    step = make_train_step(model, cfg)
    seq_metrics = []
    for sup, qry, lab in batches:
        state_a, m = step(state_a, sup, qry, lab)
        seq_metrics.append(float(m["loss"]))

    state_b = init_state(model, cfg, sup0, qry0)
    multi = make_multi_train_step(model, cfg)
    sup_s, qry_s, lab_s = jax.tree.map(lambda *xs: np.stack(xs), *batches)
    state_b, m_s = multi(state_b, sup_s, qry_s, lab_s)

    assert int(state_b.step) == int(state_a.step) == 4
    np.testing.assert_allclose(
        np.asarray(m_s["loss"]), np.asarray(seq_metrics), rtol=1e-5, atol=1e-6
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        state_a.params, state_b.params,
    )


def test_trainer_with_steps_per_call(tmp_path):
    """Trainer runs fused chunks + a single-step remainder, crosses val_step
    boundaries, and finishes at exactly train_iter optimizer steps."""
    cfg = ExperimentConfig(
        encoder="cnn", n=2, k=2, q=2, batch_size=2, max_length=L, vocab_size=302,
        compute_dtype="float32", lr=1e-2, train_iter=10, val_step=4,
        val_iter=4, steps_per_call=4,
    )
    import json

    model, sampler = _setup(cfg)
    logger = MetricsLogger(out_dir=tmp_path, quiet=True)
    trainer = FewShotTrainer(model, cfg, sampler, val_sampler=sampler, logger=logger)
    state = trainer.train()
    assert int(state.step) == 10  # 4 + 4 + 1 + 1 (remainder unfused)
    records = [
        json.loads(line) for line in (tmp_path / "metrics.jsonl").open()
    ]
    vals = [r for r in records if r["kind"] == "val"]
    assert [r["step"] for r in vals] == [4, 8]  # val_step crossings


def test_steps_per_call_guards():
    """spc > val_step is rejected; spc with mesh/adv-injected step warns."""
    import warnings

    import pytest

    cfg = ExperimentConfig(
        encoder="cnn", n=2, k=2, q=2, batch_size=2, max_length=L, vocab_size=302,
        compute_dtype="float32", val_step=4, steps_per_call=8,
    )
    model, sampler = _setup(cfg)
    with pytest.raises(ValueError, match="steps_per_call"):
        FewShotTrainer(model, cfg, sampler, val_sampler=sampler)

    # No val sampler -> val_step is irrelevant; big spc is fine.
    FewShotTrainer(model, cfg, sampler)

    # An injected fused step may not silently bypass adversarial training.
    with pytest.raises(ValueError, match="adversarial"):
        FewShotTrainer(
            model, cfg.replace(val_step=100), sampler,
            fused_step=lambda *a: a, adv=object(),
        )

    from induction_network_on_fewrel_tpu.train.steps import make_train_step

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        FewShotTrainer(
            model, cfg.replace(val_step=100), sampler,
            train_step=make_train_step(model, cfg),
        )
    assert any("steps_per_call" in str(x.message) for x in w)


def test_fused_eval_matches_per_batch():
    """make_multi_eval_step == per-batch eval on the same batches, and the
    trainer's evaluate() mixes fused chunks + remainder correctly."""
    from induction_network_on_fewrel_tpu.train.steps import (
        init_state,
        make_eval_step,
        make_multi_eval_step,
    )

    cfg = ExperimentConfig(
        encoder="cnn", n=2, k=2, q=2, batch_size=2, max_length=L, vocab_size=302,
        compute_dtype="float32", steps_per_call=4, val_step=100,
    )
    model, sampler = _setup(cfg)
    batches = [batch_to_model_inputs(sampler.sample_batch()) for _ in range(4)]
    state = init_state(model, cfg, batches[0][0], batches[0][1])

    single = make_eval_step(model, cfg)
    accs = [float(single(state.params, *b)["accuracy"]) for b in batches]

    multi = make_multi_eval_step(model, cfg)
    sup_s, qry_s, lab_s = jax.tree.map(lambda *xs: np.stack(xs), *batches)
    out = multi(state.params, sup_s, qry_s, lab_s)
    np.testing.assert_allclose(np.asarray(out["accuracy"]), accs, rtol=1e-6)

    # evaluate(): 10 batches = 2 fused chunks of 4 + 2 singles; the mean
    # must weight every batch equally.
    trainer = FewShotTrainer(model, cfg, sampler, val_sampler=sampler)
    acc = trainer.evaluate(state.params, num_episodes=20)  # 10 batches of 2
    assert 0.0 <= acc <= 1.0


def test_trainer_adv_fused_runs():
    """Trainer + AdvPieces.multi_step: fused DANN chunks train end-to-end."""
    from induction_network_on_fewrel_tpu.models.adversarial import (
        DomainDiscriminator,
    )
    from induction_network_on_fewrel_tpu.models.build import encoder_output_dim
    from induction_network_on_fewrel_tpu.sampling import InstanceSampler
    from induction_network_on_fewrel_tpu.train.framework import AdvPieces
    from induction_network_on_fewrel_tpu.train.steps import (
        init_disc_state,
        make_adv_multi_train_step,
        make_adv_train_step,
    )

    cfg = ExperimentConfig(
        encoder="cnn", n=2, k=2, q=2, batch_size=2, max_length=L, vocab_size=302,
        compute_dtype="float32", adv=True, adv_dis_hidden=16, adv_batch=4,
        steps_per_call=4, val_step=100, train_iter=10, loss="ce",
    )
    model, sampler = _setup(cfg)
    from induction_network_on_fewrel_tpu.data import make_synthetic_fewrel
    from induction_network_on_fewrel_tpu.data import make_synthetic_glove
    from induction_network_on_fewrel_tpu.data import GloveTokenizer

    tgt_ds = make_synthetic_fewrel(
        num_relations=4, instances_per_relation=10, vocab_size=300, seed=97
    )
    vocab = make_synthetic_glove(vocab_size=300)
    tok = GloveTokenizer(vocab, max_length=L)
    disc = DomainDiscriminator(hidden=cfg.adv_dis_hidden)
    adv = AdvPieces(
        step=make_adv_train_step(model, disc, cfg),
        disc_state=init_disc_state(disc, cfg, encoder_output_dim(cfg)),
        src_sampler=InstanceSampler(
            make_synthetic_fewrel(num_relations=4, instances_per_relation=10,
                                  vocab_size=300), tok, 4, seed=1),
        tgt_sampler=InstanceSampler(tgt_ds, tok, 4, seed=2),
        multi_step=make_adv_multi_train_step(model, disc, cfg),
    )
    trainer = FewShotTrainer(model, cfg, sampler, adv=adv)
    state = trainer.train()
    assert int(state.step) == 10  # 4+4 fused + 2 per-step remainder


def test_recovery_ring_saves_latest_on_plateau(tmp_path):
    """The crash-recovery ring (checkpoint.py save_latest) must advance at
    every val boundary even when val accuracy never improves, and
    restore_latest must pick the ring over a stale best."""
    from induction_network_on_fewrel_tpu.train.checkpoint import CheckpointManager

    cfg = ExperimentConfig(
        encoder="cnn", n=2, k=2, q=2, batch_size=2, max_length=L,
        vocab_size=302, compute_dtype="float32", lr=1e-3,
        val_step=5, val_iter=4,
    )
    model, sampler = _setup(cfg)
    trainer = FewShotTrainer(
        model, cfg, sampler, val_sampler=sampler, ckpt_dir=tmp_path,
        logger=MetricsLogger(quiet=True),
    )
    # Force a permanent plateau: no val accuracy ever beats +inf, so the
    # best manager never saves and ONLY the ring advances — the scenario
    # the ring exists for, made deterministic.
    trainer.best_val = float("inf")
    state = trainer.train(num_iters=15)

    mgr = trainer.ckpt
    # Ring holds the final step regardless of where the best landed.
    assert mgr.latest_mngr.latest_step() == 15
    restored, step = mgr.restore_latest(jax.device_get(state))
    assert step == 15
    np.testing.assert_allclose(
        np.asarray(jax.tree.leaves(restored.params)[0]),
        np.asarray(jax.tree.leaves(jax.device_get(state).params)[0]),
    )
    # The forced plateau means the best manager never saved anything.
    import pytest

    with pytest.raises(FileNotFoundError):
        mgr.restore_best(jax.device_get(state))

    # Dedupe: saving the same step twice is a no-op, not an orbax error.
    mgr.save_latest(15, jax.device_get(state))

    # Resumed training continues GLOBAL step numbering, so the ring keeps
    # advancing across restarts instead of rewriting stale lower steps.
    state = trainer.train(state, num_iters=5, start_step=15)
    assert mgr.latest_mngr.latest_step() == 20


def test_stale_checkpoint_dir_guard(tmp_path):
    """Orbax silently refuses saves at steps <= a dir's existing latest
    (verified: ``save`` returns False), so a run that restarts step
    numbering into a populated dir would lose EVERY checkpoint. The
    check_start_step guard must refuse such runs up front with flag
    guidance; legitimate resumes pass (advisor finding, r1)."""
    import pytest

    from induction_network_on_fewrel_tpu.train.checkpoint import CheckpointManager

    cfg = ExperimentConfig(
        encoder="cnn", n=2, k=2, q=2, batch_size=2, max_length=L,
        vocab_size=302, compute_dtype="float32",
    )
    model, sampler = _setup(cfg)
    sup, qry, _ = batch_to_model_inputs(sampler.sample_batch())
    state = jax.device_get(init_state(model, cfg, sup, qry))

    mgr = CheckpointManager(tmp_path, cfg)
    mgr.save(500, state, val_accuracy=0.9)  # prior run's best, step 500
    # force=True: a prior run's TERMINAL ring save (the trainer forces its
    # end-of-run save past the adaptive in-flight skip).
    mgr.save_latest(700, state, force=True)  # prior run's ring, saved later

    with pytest.raises(ValueError, match="resume"):
        mgr.check_start_step(0)             # fresh fine-tune into old dir
    mgr.check_start_step(700)               # legitimate --resume: fine

    # Under the guard, step order == save order: restore_latest picks the
    # newest (the ring here).
    _, step = mgr.restore_latest(state)
    assert step == 700


def test_nota_metrics_math():
    """episode_metrics confusion fractions -> exact precision/recall."""
    from induction_network_on_fewrel_tpu.models.losses import episode_metrics

    # 2-way + NOTA (class 2). preds: [2, 2, 0, 1]; labels: [2, 0, 0, 2].
    logits = jnp.asarray([[
        [0.0, 0.1, 9.0],   # pred 2, true 2 -> tp
        [0.2, 0.1, 5.0],   # pred 2, true 0 -> fp
        [3.0, 0.1, 0.0],   # pred 0, true 0
        [0.0, 2.0, 0.1],   # pred 1, true 2 -> fn
    ]])
    label = jnp.asarray([[2, 0, 0, 2]])
    m = episode_metrics(logits, label, nota=True)
    assert float(m["nota_tp"]) == 0.25     # 1 of 4 queries
    assert float(m["nota_pred"]) == 0.5    # 2 predicted NOTA
    assert float(m["nota_true"]) == 0.5    # 2 actually NOTA
    # precision = tp/pred = 0.5, recall = tp/true = 0.5
    assert float(m["accuracy"]) == 0.5
    m2 = episode_metrics(logits, label, nota=False)
    assert set(m2) == {"accuracy"}


def test_nota_threshold_learns_on_overfit():
    """The learned NOTA threshold logit must separate in-episode queries
    from outside ones: recall > 0.8 on the overfit fixture (VERDICT r1 #6).

    Best-across-chunks, same rationale as test_overfit_two_relations: the
    MSE fixture's step-500 snapshot is trajectory-chaotic; the capability
    being tested is that the head CAN learn the separation.

    seed=1 is PINNED (round-6 deflake, measured on the CPU backend):
    seed 0's init lands this fixture in the MSE-sigmoid loss's documented
    all-NOTA degenerate optimum — accuracy pinned at the NOTA fraction
    (1/3) with recall 1.0 / precision 1/3, the exact signature the CLI's
    mse+na_rate guard and config.divergence_guard describe — and never
    escapes (6 chunks probed, bit-for-bit deterministic, so this was a
    hard fail on this backend, not a flake). That basin is a property of
    the LOSS (inherent, CE is immune), not of the threshold head this
    test exists to exercise; seed 1 starts outside it and clears all
    three bars by chunk 2 (acc 0.897 / recall 0.842 / precision 0.990).
    """
    cfg = ExperimentConfig(
        encoder="cnn", train_n=2, n=2, k=2, q=2, na_rate=1, batch_size=4,
        max_length=L, vocab_size=302, compute_dtype="float32", lr=5e-3,
        loss="mse", val_step=0, weight_decay=0.0, seed=1,
    )
    model, sampler = _setup(cfg, num_relations=5)
    trainer = FewShotTrainer(model, cfg, sampler)
    passed, m, state = None, None, None
    for _ in range(4):
        state = trainer.train(state=state, num_iters=250)
        m = trainer.evaluate(
            state.params, num_episodes=60, sampler=sampler, return_metrics=True
        )
        # A SINGLE snapshot must clear all three bars (accuracy-keyed "best"
        # could shadow a later all-clearing chunk).
        if (
            m["accuracy"] > 0.8
            and m["nota_recall"] > 0.8
            and m["nota_precision"] > 0.8
        ):
            passed = m
            break
    assert passed is not None, f"no chunk cleared all bars; last={m}"


def test_nota_stats_head_mse_smoke():
    """MSE + stats head is a LEGAL config (the cli guard only refuses mse
    at na_rate >= 3): it must run without NaN/crash even though its
    convergence is a documented coin flip (see the CE test below). Smoke
    only — no convergence bar (advisor finding, round 3)."""
    cfg = ExperimentConfig(
        encoder="cnn", train_n=2, n=2, k=2, q=2, na_rate=1, batch_size=4,
        max_length=L, vocab_size=302, compute_dtype="float32", lr=5e-3,
        loss="mse", val_step=0, weight_decay=0.0, nota_head="stats",
    )
    model, sampler = _setup(cfg, num_relations=5)
    trainer = FewShotTrainer(model, cfg, sampler)
    state = trainer.train(num_iters=60)
    m = trainer.evaluate(
        state.params, num_episodes=24, sampler=sampler, return_metrics=True
    )
    assert np.isfinite(m["accuracy"]), m
    assert all(
        np.all(np.isfinite(leaf)) for leaf in jax.tree.leaves(state.params)
    )


def test_nota_stats_head_learns_on_overfit():
    """--nota_head stats (per-query affine over class-score statistics)
    learns NOTA detection on the overfit fixture; its params live under
    distinct names so checkpoints can't silently cross-load. CE loss: the
    framework's own guidance (BASELINE.md, the cli mse+na guard) is that
    NOTA training belongs on CE — under MSE the stats head can fall into
    the documented all-non-NOTA degenerate optimum depending on fp
    ordering alone (observed when an exact-gradient rewrite shifted the
    trajectory), which makes an MSE fixture a coin flip, not a test. On
    CE it converges to 1.0/1.0/1.0 by ~500 iters (measured); the heads
    are compared properly at the heavy-NOTA CE recipe in BASELINE.md."""
    cfg = ExperimentConfig(
        encoder="cnn", train_n=2, n=2, k=2, q=2, na_rate=1, batch_size=4,
        max_length=L, vocab_size=302, compute_dtype="float32", lr=5e-3,
        loss="ce", val_step=0, weight_decay=0.0, nota_head="stats",
    )
    model, sampler = _setup(cfg, num_relations=5)
    trainer = FewShotTrainer(model, cfg, sampler)
    state = trainer.train(num_iters=500)
    leaves = {
        "/".join(str(getattr(k, "key", k)) for k in p)
        for p, _ in jax.tree_util.tree_flatten_with_path(state.params)[0]
    }
    assert any("nota_stats_w" in s for s in leaves), leaves
    assert not any("nota_logit" in s for s in leaves)
    m = trainer.evaluate(
        state.params, num_episodes=60, sampler=sampler, return_metrics=True
    )
    assert m["accuracy"] > 0.8, m
    assert m["nota_recall"] > 0.6, m
    assert m["nota_precision"] > 0.8, m


def test_divergence_guard_stops_and_restores_best(tmp_path, monkeypatch):
    """divergence_guard=stop: a >2x val collapse ends the run with the best
    checkpoint restored (the MSE-sigmoid dead zone is unrecoverable, so
    the remaining steps would be wasted)."""
    cfg = ExperimentConfig(
        encoder="cnn", n=2, k=2, q=2, batch_size=2, max_length=L,
        vocab_size=302, compute_dtype="float32", val_step=5, val_iter=4,
        divergence_guard="stop",
    )
    model, sampler = _setup(cfg)
    trainer = FewShotTrainer(
        model, cfg, sampler, val_sampler=sampler, ckpt_dir=tmp_path,
        logger=MetricsLogger(quiet=True),
    )
    vals = iter([0.9, 0.2, 0.2, 0.2, 0.2, 0.2])
    monkeypatch.setattr(
        trainer, "evaluate", lambda *a, **k: {"accuracy": next(vals)}
    )
    state = trainer.train(num_iters=30)
    # Val 0.9 at step 5 (best saved), collapse 0.2 at step 10 -> stop and
    # restore: fewer than 30 steps ran and the returned state is step 5.
    assert trainer.ckpt.mngr.best_step() == 5
    assert int(state.step) == 5


def test_divergence_guard_arms_below_half_accuracy(tmp_path, monkeypatch):
    """Config-relative arming (round-3 VERDICT weak item 3): a 10-way run
    peaking at 0.35 val — legitimately below the old hardcoded 0.5 arming
    bar — still arms the guard (floor 1/10, arm at 0.2) and a collapse to
    near-random fires it."""
    cfg = ExperimentConfig(
        encoder="cnn", n=10, k=1, q=1, batch_size=2, max_length=L,
        vocab_size=302, compute_dtype="float32", val_step=5, val_iter=4,
        divergence_guard="stop",
    )
    model, sampler = _setup(cfg, num_relations=12)
    trainer = FewShotTrainer(
        model, cfg, sampler, val_sampler=sampler, ckpt_dir=tmp_path,
        logger=MetricsLogger(quiet=True),
    )
    assert abs(trainer.guard_arm - 0.2) < 1e-9
    vals = iter([0.35, 0.12, 0.12, 0.12, 0.12, 0.12])
    monkeypatch.setattr(
        trainer, "evaluate", lambda *a, **k: {"accuracy": next(vals)}
    )
    state = trainer.train(num_iters=30)
    assert trainer.ckpt.mngr.best_step() == 5
    assert int(state.step) == 5


def test_embed_optimizer_frozen_keeps_table_fixed():
    """embed_optimizer=frozen: GloVe rows never move; other params train."""
    cfg = ExperimentConfig(
        encoder="cnn", n=2, k=2, q=2, batch_size=2, max_length=L,
        vocab_size=302, compute_dtype="float32", lr=1e-2,
        embed_optimizer="frozen",
    )
    model, sampler = _setup(cfg)
    sup, qry, label = batch_to_model_inputs(sampler.sample_batch())
    state = init_state(model, cfg, sup, qry)

    def emb_leaf(params):
        return [
            np.asarray(leaf)
            for path, leaf in jax.tree_util.tree_leaves_with_path(params)
            if "word_embedding" in jax.tree_util.keystr(path)
        ][0]

    before = emb_leaf(state.params).copy()
    other_before = np.asarray(jax.tree.leaves(state.params)[-1]).copy()
    step = make_train_step(model, cfg)
    state, _ = step(state, sup, qry, label)
    np.testing.assert_array_equal(emb_leaf(state.params), before)
    assert not np.array_equal(
        np.asarray(jax.tree.leaves(state.params)[-1]), other_before
    )


def test_embed_optimizer_sgd_moves_only_touched_rows():
    """embed_optimizer=sgd: rows of tokens absent from the batch stay put
    (the update is a scatter, not a dense table op)."""
    cfg = ExperimentConfig(
        encoder="cnn", n=2, k=2, q=2, batch_size=2, max_length=L,
        vocab_size=302, compute_dtype="float32", lr=1e-2,
        embed_optimizer="sgd",
    )
    model, sampler = _setup(cfg)
    batch = sampler.sample_batch()
    sup, qry, label = batch_to_model_inputs(batch)
    state = init_state(model, cfg, sup, qry)

    def emb_leaf(params):
        return [
            np.asarray(leaf)
            for path, leaf in jax.tree_util.tree_leaves_with_path(params)
            if "word_embedding" in jax.tree_util.keystr(path)
        ][0]

    before = emb_leaf(state.params).copy()
    step = make_train_step(model, cfg)
    state, _ = step(state, sup, qry, label)
    after = emb_leaf(state.params)
    touched = np.unique(
        np.concatenate([
            np.asarray(batch.support_word).ravel(),
            np.asarray(batch.query_word).ravel(),
        ])
    )
    untouched = np.setdiff1d(np.arange(cfg.vocab_size), touched)
    np.testing.assert_array_equal(after[untouched], before[untouched])
    assert not np.array_equal(after[touched], before[touched])


def test_evaluate_fused_tail_padding_exact():
    """evaluate()'s fused path pads short tails with a repeated batch and
    slices the padding off — the reported mean must EQUAL the per-batch
    path's on batch counts that don't divide steps_per_call."""
    cfg = ExperimentConfig(
        encoder="cnn", n=2, k=2, q=2, batch_size=2, max_length=L,
        vocab_size=302, compute_dtype="float32", steps_per_call=4,
    )
    model, sampler = _setup(cfg)
    sup, qry, _ = batch_to_model_inputs(sampler.sample_batch())
    params = init_state(model, cfg, sup, qry).params

    fused = FewShotTrainer(model, cfg, sampler, val_sampler=sampler)
    plain = FewShotTrainer(
        model, cfg.replace(steps_per_call=1), sampler, val_sampler=sampler
    )
    # 7 batches = one full group of 4 + a tail of 3 (>= spc//8 -> fused,
    # padded to 4). Same seed stream on both sides.
    for n_batches in (7, 3, 1):
        a = FewShotTrainer(
            model, cfg, _setup(cfg)[1], val_sampler=None
        )
        b = FewShotTrainer(
            model, cfg.replace(steps_per_call=1), _setup(cfg)[1],
            val_sampler=None,
        )
        acc_fused = a.evaluate(
            params, n_batches * cfg.batch_size, sampler=_setup(cfg)[1]
        )
        acc_plain = b.evaluate(
            params, n_batches * cfg.batch_size, sampler=_setup(cfg)[1]
        )
        assert abs(acc_fused - acc_plain) < 1e-6, (n_batches, acc_fused, acc_plain)
    assert fused._fused_eval is not None and plain._fused_eval is None


def test_ckpt_tmpfs_staging_drains_to_real_dir(tmp_path):
    """ckpt_stage=auto (round-3 VERDICT item 7): orbax writes land in
    /dev/shm staging, the mover drains them to the real dir — wait()
    means durable in the REAL dir; a fresh manager on the real dir alone
    (staging wiped, simulating a reboot) restores every save; retention
    GC mirrors to the real dir."""
    import shutil

    from induction_network_on_fewrel_tpu.train.checkpoint import (
        CheckpointManager,
        _stage_root_for,
    )

    if _stage_root_for(tmp_path / "d", "auto") is None:
        pytest.skip("no /dev/shm on this host")
    cfg = ExperimentConfig(
        encoder="cnn", n=2, k=2, q=2, batch_size=2, max_length=L,
        vocab_size=302, compute_dtype="float32",
    )
    model, sampler = _setup(cfg)
    sup, qry, _ = batch_to_model_inputs(sampler.sample_batch())
    state = jax.device_get(init_state(model, cfg, sup, qry))

    d = tmp_path / "d"
    mgr = CheckpointManager(d, cfg)
    stage = mgr._stage_root
    assert stage is not None and str(stage).startswith("/dev/shm")
    mgr.save(5, state, val_accuracy=0.5)
    mgr.save_latest(7, state, force=True)  # past the adaptive in-flight skip
    mgr.wait()
    # Durable in the REAL dir, not just tmpfs.
    assert (d / "5").is_dir()
    assert (d / "latest" / "7").is_dir()
    mgr.close()

    # Reboot simulation: staging wiped, only the real dir survives.
    shutil.rmtree(stage)
    mgr2 = CheckpointManager(d, cfg)
    restored, step = mgr2.restore_latest(state)
    assert step == 7
    _, best = mgr2.restore_best(state)
    assert best == 5
    # Retention GC mirrors: save 3 more bests (max_to_keep=3) and the
    # oldest real-dir step dir disappears after the drain.
    for s, acc in ((8, 0.6), (9, 0.7), (10, 0.8)):
        mgr2.save(s, restored, val_accuracy=acc)
    mgr2.wait()
    assert not (d / "5").is_dir()
    assert (d / "10").is_dir()
    mgr2.close()

    # Stale-staging shadow (round-4 live bug): the REAL dir is wiped and
    # recreated while the tmpfs staging survives — the old staging steps
    # must NOT seed the new incarnation's dedupe ledger (they silently
    # swallowed fresh saves before the incarnation nonce).
    shutil.rmtree(d)
    mgr3 = CheckpointManager(d, cfg)
    assert mgr3.mngr.latest_step() is None  # stale staging discarded
    mgr3.save(1, state, val_accuracy=0.1)
    mgr3.wait()
    assert (d / "1").is_dir()
    mgr3.close()
