"""Fleet-serving tier-1 tests (ISSUE 7): versioned multi-tenant CoW
registry semantics, continuous cross-bucket scheduling, hot-swap under
live load, per-tenant NOTA routing, shed-load fairness, dp-sharded query
scoring, per-tenant telemetry, and the loadgen parity + zero-recompile
gate.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.data.tokenizer import GloveTokenizer
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.serving.batcher import (
    ContinuousBatcher,
    Saturated,
)
from induction_network_on_fewrel_tpu.serving.buckets import (
    QueryProgramCache,
    make_serving_mesh,
    zero_batch,
)
from induction_network_on_fewrel_tpu.serving.engine import InferenceEngine
from induction_network_on_fewrel_tpu.serving.stats import ServingStats

# Tiny flagship-shaped config: cnn encoder (fast CPU compiles), small dims.
CFG = ExperimentConfig(
    model="induction", encoder="cnn", hidden_size=16,
    vocab_size=122, word_dim=8, pos_dim=2, max_length=16,
    induction_dim=8, ntn_slices=4, routing_iters=2,
    n=3, train_n=3, k=2, q=2, device="cpu",
)


@pytest.fixture(scope="module")
def world():
    vocab = make_synthetic_glove(vocab_size=CFG.vocab_size - 2,
                                 word_dim=CFG.word_dim)
    tok = GloveTokenizer(vocab, max_length=CFG.max_length)
    model = build_model(CFG, glove_init=vocab.vectors)
    params = model.init(
        jax.random.key(0),
        zero_batch(CFG.max_length, (1, CFG.n, CFG.k)),
        zero_batch(CFG.max_length, (1, 2)),
    )
    ds_a = make_synthetic_fewrel(
        num_relations=4, instances_per_relation=8,
        vocab_size=CFG.vocab_size - 2, seed=1,
    )
    ds_b = make_synthetic_fewrel(
        num_relations=3, instances_per_relation=8,
        vocab_size=CFG.vocab_size - 2, seed=2,
    )
    return vocab, tok, model, params, ds_a, ds_b


def _engine(world, start=False, **kw):
    _, tok, model, params, _, _ = world
    return InferenceEngine(
        model, params, CFG, tok, k=CFG.k,
        buckets=kw.pop("buckets", (1, 2, 4)), start=start, **kw,
    )


# --- registry: CoW snapshots, slot pool, versions --------------------------


def test_snapshot_cow_isolation(world):
    """A held snapshot is immutable: registering more classes (or another
    tenant) publishes NEW snapshots and never mutates the pinned one —
    scoring against it keeps producing the pinned-era results."""
    eng = _engine(world)
    try:
        _, _, _, _, ds_a, ds_b = world
        eng.register_dataset(ds_a, tenant="acme")
        snap0 = eng.registry.snapshot("acme")
        mat0 = np.asarray(snap0.matrix).copy()

        # Mutate the tenant AND the registry around it.
        # mask=1: a fully-masked row distills to NaN (masked_max -inf)
        # and the ISSUE-12 registration validation rightly refuses it.
        eng.registry.register_tokens(
            "extra",
            [{k: np.asarray(v) for k, v in row.items()} for row in
             [dict(word=np.zeros(CFG.max_length, np.int32),
                   pos1=np.zeros(CFG.max_length, np.int16),
                   pos2=np.zeros(CFG.max_length, np.int16),
                   mask=np.ones(CFG.max_length, np.int8))]],
            tenant="acme",
        )
        eng.register_dataset(ds_b, tenant="globex")
        snap1 = eng.registry.snapshot("acme")

        assert snap1.version > snap0.version
        assert snap0.names == tuple(ds_a.rel_names)          # unchanged
        assert snap1.names == tuple(ds_a.rel_names) + ("extra",)
        np.testing.assert_array_equal(np.asarray(snap0.matrix), mat0)
        # CoW row sharing: the unchanged classes kept their slot ids.
        assert snap1.slots[: len(snap0.slots)] == snap0.slots
    finally:
        eng.close()


def test_slot_pool_shared_across_tenants(world):
    """Two tenants registering IDENTICAL support rows share one distilled
    slot (the resident pool interns by content digest)."""
    eng = _engine(world)
    try:
        _, _, _, _, ds_a, _ = world
        eng.register_dataset(ds_a, tenant="a")
        eng.register_dataset(ds_a, tenant="b")
        sa = eng.registry.snapshot("a")
        sb = eng.registry.snapshot("b")
        assert sa.slots == sb.slots
        assert eng.registry.pool_size() == len(ds_a.rel_names)
        np.testing.assert_array_equal(
            np.asarray(sa.matrix), np.asarray(sb.matrix)
        )
    finally:
        eng.close()


def test_clone_and_threshold_share_matrix(world):
    """clone_tenant and set_nota_threshold are zero-copy CoW: membership
    is untouched, so the device matrix object itself is shared."""
    eng = _engine(world)
    try:
        _, _, _, _, ds_a, _ = world
        eng.register_dataset(ds_a, tenant="src")
        s0 = eng.registry.snapshot("src")
        clone = eng.registry.clone_tenant("src", "fork")
        assert clone.matrix is s0.matrix
        assert clone.slots == s0.slots
        s1 = eng.registry.set_nota_threshold(2.5, tenant="src")
        assert s1.matrix is s0.matrix
        assert s1.nota_threshold == 2.5
        assert s1.version > s0.version
        # The fork did NOT inherit the later threshold change.
        assert eng.registry.snapshot("fork").nota_threshold is None
    finally:
        eng.close()


def test_unregister_and_drop_tenant(world):
    eng = _engine(world)
    try:
        _, _, _, _, ds_a, _ = world
        eng.register_dataset(ds_a, tenant="t")
        n = len(ds_a.rel_names)
        assert eng.registry.pool_size() == n
        eng.registry.unregister(ds_a.rel_names[0], tenant="t")
        snap = eng.registry.snapshot("t")
        assert len(snap.names) == n - 1
        assert eng.registry.pool_size() == n - 1   # orphaned slot collected
        eng.registry.drop_tenant("t")
        assert not eng.registry.has_tenant("t")
        assert eng.registry.pool_size() == 0
        with pytest.raises(ValueError, match="no classes registered"):
            eng.registry.snapshot("t")
    finally:
        eng.close()


# --- hot-swap publish ------------------------------------------------------


def test_publish_params_rescores_and_pins_old_snapshot(world):
    """publish_params re-distills every tenant against the new weights
    (scores change), while a snapshot pinned BEFORE the swap still scores
    with its old params/matrix — byte-identical to pre-swap results. Zero
    new query-program compiles across the swap."""
    vocab, tok, model, params, ds_a, ds_b = world
    eng = _engine(world)
    try:
        eng.register_dataset(ds_a, tenant="a")
        eng.register_dataset(ds_b, tenant="b")
        eng.warmup()
        compiles_before = eng.programs.compiles

        pinned = eng.registry.snapshot("a")
        inst = ds_a.instances[ds_a.rel_names[0]][-1]
        t = tok(inst)
        from induction_network_on_fewrel_tpu.serving.buckets import (
            QUERY_DTYPES,
        )
        qp = {
            k: np.asarray(getattr(t, k))[None].astype(dt)
            for k, dt in QUERY_DTYPES.items()
        }
        before = eng.programs.run(pinned.params, pinned.matrix, qp)

        params2 = model.init(
            jax.random.key(123),
            zero_batch(CFG.max_length, (1, CFG.n, CFG.k)),
            zero_batch(CFG.max_length, (1, 2)),
        )
        version = eng.publish_params(params2)
        assert version == 1
        assert eng.registry.params_version == 1
        for tenant in ("a", "b"):
            assert eng.registry.snapshot(tenant).params_version == 1

        # New snapshot scores differently (different weights)...
        fresh = eng.registry.snapshot("a")
        after = eng.programs.run(fresh.params, fresh.matrix, qp)
        assert not np.allclose(before, after)
        # ...the pinned snapshot still reproduces pre-swap scores...
        again = eng.programs.run(pinned.params, pinned.matrix, qp)
        np.testing.assert_array_equal(before, again)
        # ...and nothing recompiled (params are arguments, shapes equal).
        assert eng.programs.compiles == compiles_before
        assert eng.stats.steady_compiles == 0
        assert eng.stats.swaps == 1

        # Mixed registration paths in ONE tenant: register_dataset rows
        # carry token-cache compact position offsets, register() rows full
        # per-token ids — shapes that cannot co-stack, so the batched
        # publish must group its distill calls by leaf-shape signature
        # (caught live by the round-9 verify drive).
        eng.register_class(
            "mixed_form", ds_b.instances[ds_b.rel_names[0]][: CFG.k],
            tenant="a",
        )
        version = eng.publish_params(params)
        assert version == 2
        snap = eng.registry.snapshot("a")
        assert "mixed_form" in snap.names and snap.params_version == 2
    finally:
        eng.close()


def test_hot_swap_under_live_load(world):
    """The acceptance drill: publish a new params version while threaded
    multi-tenant load is in flight — zero dropped queries, zero
    recompiles, and post-swap verdicts come from the new snapshot
    version."""
    vocab, tok, model, params, ds_a, ds_b = world
    eng = _engine(world, start=True)
    try:
        eng.register_dataset(ds_a, tenant="a")
        eng.register_dataset(ds_b, tenant="b")
        eng.warmup()

        pools = {
            "a": [ds_a.instances[r][-1] for r in ds_a.rel_names],
            "b": [ds_b.instances[r][-1] for r in ds_b.rel_names],
        }
        results, errors = [], []
        stop = time.monotonic() + 2.0
        lock = threading.Lock()

        def client(seed):
            i = seed
            while time.monotonic() < stop:
                tenant = ("a", "b")[i % 2]
                i += 1
                try:
                    v = eng.classify(
                        pools[tenant][i % len(pools[tenant])],
                        deadline_s=30.0, tenant=tenant,
                    )
                    with lock:
                        results.append(v)
                except Exception as e:  # noqa: BLE001 — any error is a drop
                    with lock:
                        errors.append(e)

        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(3)
        ]
        for th in threads:
            th.start()
        time.sleep(0.4)
        params2 = model.init(
            jax.random.key(99),
            zero_batch(CFG.max_length, (1, CFG.n, CFG.k)),
            zero_batch(CFG.max_length, (1, 2)),
        )
        eng.publish_params(params2)
        for th in threads:
            th.join()

        assert errors == []                      # zero dropped
        assert eng.stats.steady_compiles == 0    # zero recompiles
        assert eng.stats.swaps == 1
        versions = {v["snapshot_version"] for v in results}
        assert len(versions) >= 2, "no traffic spanned the swap"
        assert all(isinstance(v["label"], str) for v in results)
    finally:
        eng.close()


# --- per-tenant NOTA routing -----------------------------------------------


def test_per_tenant_nota_threshold_open_set(world):
    """No NOTA head (na_rate=0): a tenant-set threshold is an open-set
    floor on the best class logit — the SAME query gets a real label for
    the default tenant and no_relation for the thresholded one."""
    _, _, _, _, ds_a, _ = world
    eng = _engine(world)
    try:
        eng.register_dataset(ds_a, tenant="open")
        eng.register_dataset(ds_a, tenant="strict")
        eng.registry.set_nota_threshold(1e9, tenant="strict")
        inst = ds_a.instances[ds_a.rel_names[0]][-1]

        fut_open = eng.submit(inst, deadline_s=30.0, tenant="open")
        fut_strict = eng.submit(inst, deadline_s=30.0, tenant="strict")
        eng.batcher.drain_once()
        eng.batcher.drain_once()
        v_open = fut_open.result(timeout=10.0)
        v_strict = fut_strict.result(timeout=10.0)
        assert not v_open["nota"] and v_open["label"] in ds_a.rel_names
        assert v_strict["nota"] and v_strict["label"] == "no_relation"
        assert v_strict["tenant"] == "strict"
    finally:
        eng.close()


def test_per_tenant_nota_threshold_biases_head(world):
    """With a trained NOTA head the threshold BIASES the no-relation
    logit: a hugely negative tenant threshold suppresses even a dominant
    NOTA head; the default tenant keeps the head's verdict."""
    vocab, tok, _, _, ds_a, _ = world
    cfg = CFG.replace(na_rate=1)
    model = build_model(cfg, glove_init=vocab.vectors)
    params = model.init(
        jax.random.key(0),
        zero_batch(cfg.max_length, (1, cfg.n, cfg.k)),
        zero_batch(cfg.max_length, (1, 2)),
    )
    inner = dict(params["params"])
    inner["nota_logit"] = jnp.full((1,), 50.0)  # head screams NOTA
    params = {"params": inner}
    eng = InferenceEngine(model, params, cfg, tok, k=cfg.k,
                          buckets=(1, 2), start=False)
    try:
        eng.register_dataset(ds_a, tenant="default")
        eng.register_dataset(ds_a, tenant="trusting")
        eng.registry.set_nota_threshold(-1e9, tenant="trusting")
        inst = ds_a.instances[ds_a.rel_names[0]][-1]

        f_def = eng.submit(inst, deadline_s=30.0)
        f_trust = eng.submit(inst, deadline_s=30.0, tenant="trusting")
        eng.batcher.drain_once()
        eng.batcher.drain_once()
        assert f_def.result(timeout=10.0)["nota"]
        v = f_trust.result(timeout=10.0)
        assert not v["nota"] and v["label"] in ds_a.rel_names
    finally:
        eng.close()


# --- continuous scheduler ---------------------------------------------------


def test_continuous_no_hol_blocking():
    """Deadline-aware cross-group ordering: a deep backlog for tenant A
    must not cost tenant B's at-risk request its deadline — when B's slack
    drops under ~two executions, the next launch serves B first, backlog
    or not."""
    order = []

    def execute(group, batch):
        order.append((group, len(batch)))
        for r in batch:
            r.future.set_result(group)

    stats = ServingStats()
    stats.record_batch(1, 1, 0.05)    # exec estimate: 50 ms
    b = ContinuousBatcher(execute, buckets=(1, 2, 4), start=False,
                          stats=stats)
    for _ in range(4):
        b.submit({"q": 0}, deadline_s=10.0, tenant="bulk")
    # slack = 0.12 - 0.05 = 0.07 < 2 * 0.05 -> at risk, must go now.
    fb = b.submit({"q": 1}, deadline_s=0.12, tenant="urgent")
    assert b.drain_once() == 1
    assert order[0] == ("urgent", 1)
    assert fb.result(timeout=1.0) == "urgent"
    assert b.drain_once() == 4        # then the backlog, packed into one
    assert order[1] == ("bulk", 4)
    b.close()


def test_continuous_stale_budget_beats_standing_backlog():
    """A sparse tenant's lone query must not idle behind a busy tenant's
    standing backlog until its deadline nearly expires: once it has
    burned STALE_BUDGET_FRAC of its deadline budget waiting it is urgent
    by staleness and the next launch serves it, even though its absolute
    slack is still comfortable. (The trigger is budget-relative, NOT an
    exec-estimate multiple — see _pop_group_locked for why the latter
    collapses open-loop throughput.)"""
    order = []

    def execute(group, batch):
        order.append((group, len(batch)))
        for r in batch:
            r.future.set_result(group)

    stats = ServingStats()
    stats.record_batch(1, 1, 0.005)   # slack stays comfortable throughout
    b = ContinuousBatcher(execute, buckets=(1, 2, 4), start=False,
                          stats=stats)
    assert b.STALE_BUDGET_FRAC == 0.25
    fs = b.submit({"q": 1}, deadline_s=0.5, tenant="sparse")
    for _ in range(4):                # busy keeps the deeper backlog
        b.submit({"q": 0}, deadline_s=60.0, tenant="busy")
    # Fresh: deepest wins (sparse head has burned ~0% of its budget).
    assert b.drain_once() == 4
    assert order[0] == ("busy", 4)
    for _ in range(4):
        b.submit({"q": 0}, deadline_s=60.0, tenant="busy")
    time.sleep(0.15)                  # sparse head now > 25% of 0.5 s budget
    assert b.drain_once() == 1
    assert order[1] == ("sparse", 1), (
        "stale sparse query lost to a deeper backlog"
    )
    assert fs.result(timeout=1.0) == "sparse"
    assert b.drain_once() == 4        # then the backlog, still packed
    assert order[2] == ("busy", 4)
    b.close()


def test_continuous_packs_deepest_group_when_nothing_urgent():
    """Slot-level packing: with every deadline comfortable, the launch
    serves the DEEPEST group (maximum slots per program call), not the
    oldest — single-row launches at sub-saturation rates are the failure
    mode this policy removes."""
    order = []

    def execute(group, batch):
        order.append((group, len(batch)))
        for r in batch:
            r.future.set_result(group)

    stats = ServingStats()
    # exec estimate 50 ms: deadlines comfortable AND the age bound (2
    # executions = 100 ms) far beyond this test's submit->drain latency.
    stats.record_batch(1, 1, 0.05)
    b = ContinuousBatcher(execute, buckets=(1, 2, 4), start=False,
                          stats=stats)
    b.submit({"q": 0}, deadline_s=10.0, tenant="old_small")
    for _ in range(3):
        b.submit({"q": 1}, deadline_s=10.0, tenant="deep")
    assert b.drain_once() == 3
    assert order[0] == ("deep", 3)
    assert b.drain_once() == 1
    assert order[1] == ("old_small", 1)
    b.close()


def test_continuous_packs_without_window():
    """Slot-level packing with NO coalescing wait: pending requests launch
    together immediately — one program call, no window tax."""
    calls = []

    def execute(group, batch):
        calls.append(len(batch))
        for r in batch:
            r.future.set_result("ok")

    b = ContinuousBatcher(execute, buckets=(1, 2, 4, 8), start=False,
                          stats=ServingStats())
    futs = [b.submit({"q": i}, deadline_s=5.0) for i in range(3)]
    t0 = time.monotonic()
    assert b.drain_once() == 3
    assert time.monotonic() - t0 < 1.0
    assert calls == [3]
    for f in futs:
        assert f.result(timeout=1.0) == "ok"
    # An idle drain returns promptly (bounded block) with nothing to do.
    assert b.drain_once(block_s=0.01) == 0
    b.close()


def test_continuous_caps_at_largest_bucket():
    calls = []

    def execute(group, batch):
        calls.append(len(batch))
        for r in batch:
            r.future.set_result("ok")

    b = ContinuousBatcher(execute, buckets=(1, 2), start=False)
    for i in range(5):
        b.submit({"q": i}, deadline_s=5.0)
    assert b.drain_once() == 2
    assert b.drain_once() == 2
    assert b.drain_once() == 1
    assert calls == [2, 2, 1]
    b.close()


def test_shed_load_fairness():
    """Per-tenant share: an overloaded tenant sheds (Saturated carries the
    tenant) while another tenant keeps admitting; per-tenant stats
    attribute the sheds to the offender only."""
    stats = ServingStats()
    b = ContinuousBatcher(lambda g, batch: None, buckets=(1, 2, 4),
                          max_queue_depth=8, tenant_share=0.5,
                          start=False, stats=stats)
    # Single-tenant regime: the share does NOT bind — a lone tenant keeps
    # the full queue (the pre-fleet capacity) until a second tenant shows
    # up, and plain saturation is a global Saturated, not shed-load.
    for i in range(8):
        b.submit({"q": i}, deadline_s=5.0, tenant="solo")
    with pytest.raises(Saturated) as es:
        b.submit({"q": 99}, deadline_s=5.0, tenant="solo")
    assert es.value.tenant is None and stats.shed == 0
    b.close()

    stats = ServingStats()
    b = ContinuousBatcher(lambda g, batch: None, buckets=(1, 2, 4),
                          max_queue_depth=8, tenant_share=0.5,
                          start=False, stats=stats)
    b.submit({"q": 0}, deadline_s=5.0, tenant="polite")   # 2nd tenant seen
    for i in range(4):                    # tenant cap = 8 * 0.5 = 4
        b.submit({"q": i}, deadline_s=5.0, tenant="hog")
    with pytest.raises(Saturated) as ei:
        b.submit({"q": 99}, deadline_s=5.0, tenant="hog")
    assert ei.value.tenant == "hog"
    assert ei.value.retry_after_s > 0
    # The other tenant still admits up to its own share.
    for i in range(3):
        b.submit({"q": i}, deadline_s=5.0, tenant="polite")
    snap = stats.tenant_snapshot()
    assert snap["hog"]["shed"] == 1 and snap["hog"]["rejected"] == 1
    assert "polite" not in snap or snap["polite"]["shed"] == 0
    assert stats.shed == 1
    # Global bound: the queue is now full (8) — ANY tenant bounces, with
    # no tenant attribution on the global breach.
    with pytest.raises(Saturated) as eg:
        b.submit({"q": 0}, deadline_s=5.0, tenant="third")
    assert eg.value.tenant is None
    b.close()


def test_continuous_engine_zero_recompiles(world):
    """The acceptance gate on the continuous path: warmup compiles each
    bucket once per distinct N-TIER (ISSUE 19 — the 4- and 3-class
    tenants share the 4-tier programs, halving the old per-class-count
    set); steady multi-tenant traffic of every size then recompiles
    NOTHING."""
    _, _, _, _, ds_a, ds_b = world
    eng = _engine(world)
    try:
        eng.register_dataset(ds_a, tenant="a")   # 4 classes -> tier 4
        eng.register_dataset(ds_b, tenant="b")   # 3 classes -> tier 4
        assert eng.registry.snapshot("a").n_tier == 4
        assert eng.registry.snapshot("b").n_tier == 4
        compiled = eng.warmup()
        assert compiled == 3                      # 3 buckets x 1 shared tier
        insts = {
            "a": ds_a.instances[ds_a.rel_names[0]][-1],
            "b": ds_b.instances[ds_b.rel_names[0]][-1],
        }
        for size in (1, 3, 4, 2, 4):
            futs = [
                eng.submit(insts[t], deadline_s=30.0, tenant=t)
                for t in ("a", "b") for _ in range(size)
            ]
            while any(not f.done() for f in futs):
                if eng.batcher.drain_once(block_s=0.01) == 0 and all(
                    f.done() for f in futs
                ):
                    break
            for f in futs:
                assert f.result(timeout=10.0)["label"]
        assert eng.stats.steady_compiles == 0
        assert eng.programs.compiles == 3
    finally:
        eng.close()


# --- dp-sharded scoring ------------------------------------------------------


def test_dp_sharded_scoring_parity(world):
    """Query programs compiled over the 8-virtual-device serving mesh
    reproduce the single-device logits (params/matrix replicated, request
    axis sharded) — the replicated-engine scoring path."""
    _, tok, model, params, ds_a, _ = world
    eng_1 = _engine(world, buckets=(8,))
    eng_8 = _engine(world, buckets=(8,), dp=8)
    try:
        eng_1.register_dataset(ds_a)
        eng_8.register_dataset(ds_a)
        assert eng_8.programs._mesh is not None
        eng_1.warmup()
        eng_8.warmup()
        insts = [ds_a.instances[r][-1] for r in ds_a.rel_names] * 2
        futs_1 = [eng_1.submit(i, deadline_s=30.0) for i in insts]
        futs_8 = [eng_8.submit(i, deadline_s=30.0) for i in insts]
        eng_1.batcher.drain_once()
        eng_8.batcher.drain_once()
        for f1, f8 in zip(futs_1, futs_8):
            v1, v8 = f1.result(timeout=10.0), f8.result(timeout=10.0)
            assert v1["label"] == v8["label"]
            for k in v1["logits"]:
                assert abs(v1["logits"][k] - v8["logits"][k]) < 1e-5
        assert eng_8.stats.steady_compiles == 0
    finally:
        eng_1.close()
        eng_8.close()


def test_serving_mesh_guards():
    with pytest.raises(ValueError, match="exceeds"):
        make_serving_mesh(len(jax.devices()) + 1)


# --- telemetry: per-tenant emit, obs_report, watchdog ------------------------


def test_per_tenant_emit_and_obs_report(tmp_path, world):
    """stats.emit writes the aggregate + one per-tenant kind="serve"
    record; obs_report --check passes and the serve section carries the
    per-tenant table + swap counters."""
    from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

    import tools.obs_report as obs

    _, _, model, _, ds_a, ds_b = world
    logger = MetricsLogger(tmp_path, quiet=True)
    eng = _engine(world, logger=logger)
    try:
        eng.register_dataset(ds_a, tenant="a")
        eng.register_dataset(ds_b, tenant="b")
        eng.warmup()
        for t, ds in (("a", ds_a), ("b", ds_b)):
            fut = eng.submit(
                ds.instances[ds.rel_names[0]][-1], deadline_s=30.0, tenant=t
            )
            eng.batcher.drain_once()
            fut.result(timeout=10.0)
        eng.publish_params(eng.params)   # emits the snapshot_swap record
        eng.emit_stats()
    finally:
        eng.close()
        logger.close()

    n, errors = obs.check_schema(tmp_path / "metrics.jsonl")
    assert errors == [], errors
    recs = obs.load_records(tmp_path / "metrics.jsonl")
    serve = obs.serve_summary(recs)
    assert serve["swaps"] == 1
    assert serve["swap_events"] == 1
    assert serve["params_version"] == 1
    assert set(serve["tenants"]) == {"a", "b"}
    for t in ("a", "b"):
        assert serve["tenants"][t]["served"] == 1
        assert serve["tenants"][t]["p99_ms"] >= 0
    # The rendered report prints the tenant table without blowing up.
    text = obs.render({
        "run_dir": str(tmp_path),
        "schema": {"records": n, "errors": []},
        "serve": serve,
    })
    assert "tenants:" in text and "a:" in text


def test_watchdog_shed_and_swap_events():
    """kind="serve" records drive the watchdog: a growing shed counter is
    a latched critical; a snapshot_swap event surfaces as a warning."""
    from induction_network_on_fewrel_tpu.obs.health import HealthWatchdog

    wd = HealthWatchdog()
    base = {"kind": "serve", "step": 1, "wall_s": 0.0, "served": 10,
            "queue_depth": 0}
    wd.observe_record({**base, "shed": 0, "rejected": 0})
    assert not wd.tripped
    wd.observe_record({**base, "shed": 3, "rejected": 3})
    assert wd.tripped
    sheds = [e for e in wd.events if e.event == "shed_load"]
    assert len(sheds) == 1 and sheds[0].severity == "critical"
    # Latched: continued shedding is the same incident...
    wd.observe_record({**base, "shed": 5, "rejected": 5})
    assert len([e for e in wd.events if e.event == "shed_load"]) == 1
    # ...a shed-free window re-arms, a new burst is a new incident.
    wd.observe_record({**base, "shed": 5, "rejected": 5})
    wd.observe_record({**base, "shed": 7, "rejected": 7})
    assert len([e for e in wd.events if e.event == "shed_load"]) == 2
    # Per-tenant records must NOT feed the aggregate shed detector.
    wd.observe_record({**base, "shed": 50, "rejected": 50, "tenant": "x"})
    assert len([e for e in wd.events if e.event == "shed_load"]) == 2

    wd.observe_record({
        "kind": "serve", "step": 2, "wall_s": 0.0,
        "event": "snapshot_swap", "params_version": 3, "tenants": 2,
    })
    swaps = [e for e in wd.events if e.event == "snapshot_swap"]
    assert len(swaps) == 1 and swaps[0].severity == "warning"


# --- the loadgen gate (satellite 6) -----------------------------------------


def test_loadgen_parity_and_zero_recompile_gate(world):
    """The tier-1 spelling of the loadgen harness: per-tenant registry ==
    direct forward parity, then mixed-size continuous traffic with zero
    steady-state recompiles — the same checks tools/loadgen.py FAILs on,
    importable and fast."""
    from tools.loadgen import check_registry_parity

    _, _, _, _, ds_a, ds_b = world
    eng = _engine(world)
    try:
        eng.register_dataset(ds_a, tenant="a")
        eng.register_dataset(ds_b, tenant="b")
        eng.warmup()
        for tenant, ds in (("a", ds_a), ("b", ds_b)):
            delta = check_registry_parity(eng, ds, tenant=tenant)
            assert delta < 1e-4, f"parity[{tenant}] broke: {delta}"
        insts = {
            "a": ds_a.instances[ds_a.rel_names[0]][-1],
            "b": ds_b.instances[ds_b.rel_names[0]][-1],
        }
        for size in (1, 2, 4, 3):
            futs = [
                eng.submit(insts[t], deadline_s=30.0, tenant=t)
                for t in ("a", "b") for _ in range(size)
            ]
            for _ in range(8):
                if all(f.done() for f in futs):
                    break
                eng.batcher.drain_once(block_s=0.01)
            for f in futs:
                f.result(timeout=10.0)
        assert eng.stats.steady_compiles == 0, (
            "the continuous query path recompiled after warmup"
        )
    finally:
        eng.close()

    # Mixed-GEOMETRY parity (ISSUE 19): the same gate with the N-tier
    # ladder on and the tenants landing on DIFFERENT tiers (4 classes
    # pad to tier 6 with two pad rows, 3 classes sit at tier 3) — the
    # served tier-padded program must still match the exact-N direct
    # forward.
    eng = _engine(world, geometry_tiers="3,6")
    try:
        eng.register_dataset(ds_a, tenant="a")   # 4 classes -> tier 6
        eng.register_dataset(ds_b, tenant="b")   # 3 classes -> tier 3
        eng.warmup()
        assert eng.registry.snapshot("a").n_tier == 6
        assert eng.registry.snapshot("b").n_tier == 3
        for tenant, ds in (("a", ds_a), ("b", ds_b)):
            delta = check_registry_parity(eng, ds, tenant=tenant)
            assert delta < 1e-4, (
                f"tiered parity[{tenant}] broke: {delta}"
            )
    finally:
        eng.close()


# --- indexed pop (ISSUE 11: the round-9 O(active-groups) scan paydown) ----


class _NoScanDict(dict):
    """A _pending stand-in that forbids ITERATION (the O(groups) scan the
    indexed pop replaced) while allowing keyed access. Structural pin:
    if a future refactor reintroduces a per-launch sweep over active
    groups, these raises fail the test immediately."""

    def __iter__(self):
        raise AssertionError("_pop_group_locked iterated _pending")

    def items(self):
        raise AssertionError("_pop_group_locked scanned _pending.items()")

    def values(self):
        raise AssertionError("_pop_group_locked scanned _pending.values()")

    def keys(self):
        raise AssertionError("_pop_group_locked scanned _pending.keys()")


def test_pop_never_scans_groups():
    """The per-launch pop must be indexed (lazy urgency + depth heaps),
    never a scan over active groups — with 64 tenants admitted, popping
    every batch touches _pending only by key."""
    done = []

    def execute(group, batch):
        done.append((group, len(batch)))
        for r in batch:
            r.future.set_result(group)

    b = ContinuousBatcher(execute, buckets=(1, 2, 4), start=False,
                          max_queue_depth=4096, tenant_share=1.0)
    b._pending = _NoScanDict(b._pending)
    futs = []
    for g in range(64):
        for _ in range(3):
            futs.append(
                b.submit({"q": g}, deadline_s=30.0, tenant=f"t{g:02d}")
            )
    while b.queue_depth:
        assert b.drain_once(block_s=0.01) > 0
    for f in futs:
        f.result(timeout=1.0)
    assert len(done) == 64            # 3 rows per tenant, one launch each
    assert all(n == 3 for _, n in done)
    # close() legitimately sweeps _pending to fail leftover futures — the
    # pin is on the POP path only (everything is drained here anyway).
    b._pending = {}
    b.close()


def test_pop_index_consistency_under_mixed_urgency():
    """The lazy heaps must stay consistent through interleaved urgent
    overrides, deep-pack pops, and re-submissions: every admitted request
    resolves exactly once, no launch exceeds the bucket cap, and the
    index sees a group again after it empties and refills."""
    served = []

    def execute(group, batch):
        served.append((group, len(batch)))
        for r in batch:
            r.future.set_result(group)

    stats = ServingStats()
    stats.record_batch(1, 1, 0.05)    # 50 ms exec estimate
    b = ContinuousBatcher(execute, buckets=(1, 2, 4), start=False,
                          stats=stats)
    # Deep backlog + an at-risk head elsewhere: urgent wins the slot.
    for _ in range(4):
        b.submit({"q": 0}, deadline_s=10.0, tenant="bulk")
    fu = b.submit({"q": 1}, deadline_s=0.12, tenant="urgent")
    assert b.drain_once() == 1 and served[0] == ("urgent", 1)
    assert fu.result(timeout=1.0) == "urgent"
    # Depth entries for "bulk" are now stale-high; the lazy re-sync must
    # still find it, pack the full backlog, and drop the group cleanly.
    assert b.drain_once() == 4 and served[1] == ("bulk", 4)
    assert b.queue_depth == 0 and not b._pending
    # Refill the SAME group: fresh index entries, fresh pop.
    fr = [b.submit({"q": 2}, deadline_s=10.0, tenant="bulk")
          for _ in range(2)]
    assert b.drain_once() == 2 and served[2] == ("bulk", 2)
    for f in fr:
        f.result(timeout=1.0)
    b.close()


# --- distill-outside-lock registry (ISSUE 11, round-10 scale paydown) -----


def test_distill_runs_outside_control_plane_lock(world):
    """Structural pin: the distill device pass must NEVER run while the
    control-plane lock is held (registrations and publishes both) — the
    exact serialization the round-10 follow-up recorded. The escape
    hatch (_intern_bulk_locked after repeated plan/commit races) is the
    one sanctioned exception and is not reachable without concurrent
    churn."""
    _, _, _, _, ds_a, _ = world
    eng = _engine(world)
    try:
        reg = eng.registry
        real = reg._distill
        locked_calls = []

        def spy(params, sup):
            locked_calls.append(reg._lock.locked())
            return real(params, sup)

        reg._distill = spy
        eng.register_dataset(ds_a, tenant="acme")
        assert locked_calls and not any(locked_calls), (
            "registration distilled under the control-plane lock"
        )
        locked_calls.clear()
        reg.publish_params(reg.params)
        assert locked_calls and not any(locked_calls), (
            "publish distilled under the control-plane lock"
        )
    finally:
        eng.close()


def test_register_retries_when_publish_races_distill(world):
    """A publish landing MID-DISTILL of a registration must invalidate
    the in-flight vectors: the commit's params_version check fails, the
    registration re-distills against the NEW weights, and the committed
    snapshot is coherent — new params_version, vectors from the new
    params. Deterministic: the distill spy triggers the publish from
    another thread on its first registration call."""
    _, _, _, _, ds_a, ds_b = world
    eng = _engine(world)
    try:
        reg = eng.registry
        eng.register_dataset(ds_b, tenant="resident")  # publish has work
        real = reg._distill
        state = {"fired": False, "calls": 0}

        def spy(params, sup):
            state["calls"] += 1
            if not state["fired"]:
                state["fired"] = True
                t = threading.Thread(
                    target=reg.publish_params, args=(reg.params,)
                )
                t.start()
                t.join()          # the publish fully lands mid-"distill"
            return real(params, sup)

        reg._distill = spy
        eng.register_dataset(ds_a, tenant="acme")
        # The racing publish bumped the version; the registration must
        # have retried (>= 2 distill calls for its single bulk group,
        # plus the publish's own re-distill of the resident tenant).
        assert reg.params_version == 1
        snap = reg.snapshot("acme")
        assert snap.params_version == 1
        assert snap.params is reg.params
        assert all(s in reg._pool for s in snap.slots)
        # Every pool slot the snapshot references was interned at the
        # CURRENT version (no old-generation vector survived the race).
        for s in snap.slots:
            assert reg._by_digest[(1, reg._pool[s].digest)] == s
    finally:
        eng.close()


def test_publish_vs_register_consistency(world):
    """Concurrency storm: registrations and publishes interleaving freely
    must end with every tenant snapshot at the registry's params_version,
    every referenced slot live in the pool, and every tenant's classes
    intact — the publish-vs-snapshot consistency contract."""
    _, _, _, _, ds_a, ds_b = world
    eng = _engine(world)
    try:
        reg = eng.registry
        eng.register_dataset(ds_a, tenant="seed")
        errs = []

        def registrar(ds, tenant):
            try:
                for _ in range(3):
                    eng.register_dataset(ds, tenant=tenant)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        def publisher():
            try:
                for _ in range(3):
                    reg.publish_params(reg.params)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [
            threading.Thread(target=registrar, args=(ds_a, "acme")),
            threading.Thread(target=registrar, args=(ds_b, "globex")),
            threading.Thread(target=publisher),
            threading.Thread(target=publisher),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120.0)
        assert not errs, errs
        assert reg.params_version == 6
        for tenant, n_classes in (("seed", 4), ("acme", 4), ("globex", 3)):
            snap = reg.snapshot(tenant)
            assert snap.params_version == reg.params_version, tenant
            assert snap.params is reg.params, tenant
            assert len(snap.names) == n_classes, tenant
            assert all(s in reg._pool for s in snap.slots), tenant
    finally:
        eng.close()
