"""Durable control plane tier-1 tests (ISSUE 15): journal framing /
determinism / torn-tail truncation / compaction equivalence, supervisor
backoff + budget on the injectable clock, socket per-call deadlines +
bounded idempotent retry + net.* chaos, the router's deadline-vs-health
breaker accounting, and the miniature recovery drill replayed against
the committed RECOVERY_r*.json band (the fleet-miniature discipline)."""

import glob
import json
import os
import socket
import socketserver
import sys
import threading
from concurrent.futures import Future

import pytest

from induction_network_on_fewrel_tpu.fleet import (
    DEAD,
    UP,
    FleetControl,
    FleetJournal,
    FleetRouter,
    JournalError,
    ReplicaHandle,
    ReplicaSupervisor,
)
from induction_network_on_fewrel_tpu.fleet.journal import WAL_NAME
from induction_network_on_fewrel_tpu.fleet.supervisor import (
    deterministic_jitter,
)
from induction_network_on_fewrel_tpu.fleet.transport import SocketReplica
from induction_network_on_fewrel_tpu.obs.chaos import ChaosRegistry, install
from induction_network_on_fewrel_tpu.serving.batcher import (
    DeadlineExceeded,
    TransportTimeout,
)
from induction_network_on_fewrel_tpu.serving.breaker import CircuitBreaker
from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOLS = os.path.join(_REPO, "tools")
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

import loadgen  # noqa: E402
import obs_report  # noqa: E402


def _ops(journal):
    journal.append("tenant_register", tenant="t0", source=None,
                   max_classes=None, nota_threshold=0.5)
    journal.append("replica_add", replica="r0")
    journal.append("tenant_threshold", tenant="t0", threshold=0.25)
    journal.append("publish_commit", params_version=1, ckpt_dir="/x/ckpt")
    journal.append("tenant_quarantine", tenant="t0", reason="op")


# --- journal: framing, determinism, torn tail, compaction -------------------


def test_journal_replay_is_deterministic_and_byte_identical(tmp_path):
    """Same ops -> byte-identical WAL files AND byte-identical
    materialized state (json.dumps of the canonical dict) — the
    invariant every recovery path leans on."""
    a, b = FleetJournal(tmp_path / "a"), FleetJournal(tmp_path / "b")
    _ops(a), _ops(b)
    a.close(), b.close()
    assert (tmp_path / "a" / WAL_NAME).read_bytes() == \
        (tmp_path / "b" / WAL_NAME).read_bytes()
    sa = json.dumps(a.materialize().to_dict(), sort_keys=True)
    sb = json.dumps(b.materialize().to_dict(), sort_keys=True)
    assert sa == sb
    # And replaying the SAME journal twice is stable.
    assert sa == json.dumps(a.materialize().to_dict(), sort_keys=True)
    st = a.materialize()
    assert st.tenants["t0"] == {
        "source": None, "max_classes": None, "nota_threshold": 0.25,
        "quarantined": True,
    }
    assert st.committed == {"params_version": 1, "ckpt_dir": "/x/ckpt"}
    assert st.replicas == {"r0": "up"}


def test_journal_torn_tail_truncates_and_recovers_prefix(tmp_path):
    """A short tail (crash mid-write) AND a CRC-corrupt record both
    truncate at the bad record: everything before replays, the file is
    repaired in place, and appends land cleanly afterward."""
    j = FleetJournal(tmp_path / "j", logger=None)
    _ops(j)
    j.close()
    wal = tmp_path / "j" / WAL_NAME
    # Tear: drop the last 5 bytes of the final record.
    blob = wal.read_bytes()
    wal.write_bytes(blob[:-5])
    logger = MetricsLogger(tmp_path / "run", quiet=True)
    j2 = FleetJournal(tmp_path / "j", logger=logger)
    st = j2.materialize()
    assert st.applied == 4                      # the 5th op is gone
    assert st.tenants["t0"]["quarantined"] is False
    # The repair happened on disk; a fresh append then replays.
    j2.append("tenant_quarantine", tenant="t0", reason="again")
    assert j2.materialize().tenants["t0"]["quarantined"] is True
    j2.close()
    logger.close()
    recs = [json.loads(line) for line in
            (tmp_path / "run" / "metrics.jsonl").read_text().splitlines()]
    trunc = [r for r in recs if r.get("action") == "journal_truncated"]
    assert len(trunc) == 1 and trunc[0]["records_kept"] == 4.0
    # CRC corruption MID-file: replay keeps only the records before it.
    blob = wal.read_bytes()
    flipped = bytearray(blob)
    flipped[len(blob) // 2] ^= 0xFF
    wal.write_bytes(bytes(flipped))
    j3 = FleetJournal(tmp_path / "j")
    assert 0 < j3.materialize().applied < 5
    j3.close()


def test_journal_snapshot_compaction_equivalence(tmp_path):
    """compacted replay == full replay, including ops appended AFTER
    the compaction — and auto-compaction triggers past compact_every."""
    full = FleetJournal(tmp_path / "full")
    compacted = FleetJournal(tmp_path / "compacted")
    _ops(full), _ops(compacted)
    compacted.compact()
    assert compacted.records == 0 and compacted.snapshot_seq == 5
    for j in (full, compacted):
        j.append("tenant_unquarantine", tenant="t0", reason="done")
        j.append("publish_commit", params_version=2, ckpt_dir="/x/ckpt2")
    assert json.dumps(full.materialize().to_dict(), sort_keys=True) == \
        json.dumps(compacted.materialize().to_dict(), sort_keys=True)
    # Auto-compaction: the WAL never grows past the knob, and the
    # state still equals an uncompacted journal of the same ops.
    auto = FleetJournal(tmp_path / "auto", compact_every=3)
    ref = FleetJournal(tmp_path / "ref")
    _ops(auto), _ops(ref)
    assert auto.records < 3 and auto.seq == 5 and auto.snapshot_seq >= 3
    assert json.dumps(auto.materialize().to_dict(), sort_keys=True) == \
        json.dumps(ref.materialize().to_dict(), sort_keys=True)
    full.close(), compacted.close(), auto.close(), ref.close()


def test_journal_refuses_bad_knobs_and_ops(tmp_path):
    with pytest.raises(JournalError):
        FleetJournal(tmp_path / "x", fsync="sometimes")
    j = FleetJournal(tmp_path / "x")
    with pytest.raises(JournalError):
        j.append("tenant_obliterate", tenant="t0")
    j.close()


def test_journal_torn_write_chaos_point(tmp_path):
    """The injected crash: the fired append writes a torn record, the
    journal object refuses further writes (the process 'died'), and
    reopening the directory truncates + recovers everything before."""
    j = FleetJournal(tmp_path / "j")
    _ops(j)
    before = json.dumps(j.materialize().to_dict(), sort_keys=True)
    install(ChaosRegistry.parse("journal.torn_write@0"))
    try:
        j.append("tenant_threshold", tenant="t0", threshold=0.9)
    finally:
        install(None)
    with pytest.raises(JournalError):
        j.append("tenant_threshold", tenant="t0", threshold=0.9)
    j.close()
    j2 = FleetJournal(tmp_path / "j")
    assert json.dumps(j2.materialize().to_dict(), sort_keys=True) == before
    j2.close()


# --- supervisor: backoff, budget, probes (stub replicas, zero engines) ------


class _SupReplica(ReplicaHandle):
    def __init__(self, rid, alive=True, version=1):
        self.replica_id = rid
        self.alive = alive
        self.version = version
        self.registered: list[str] = []
        self.thresholds: dict[str, float] = {}
        self.quarantined: list[str] = []
        self.warmups = 0

    def submit(self, instance, deadline_s=None, tenant="default",
               trace=None):
        f: Future = Future()
        f.set_result({"label": "rel0", "tenant": tenant,
                      "replica": self.replica_id})
        return f

    def ping(self):
        if not self.alive:
            raise ConnectionError("down")
        return True

    def has_tenant(self, tenant):
        return tenant in self.registered

    def register_dataset(self, dataset, tenant, max_classes=None):
        self.registered.append(tenant)
        return []

    def set_nota_threshold(self, threshold, tenant):
        self.thresholds[tenant] = threshold

    def quarantine_tenant(self, tenant, reason=""):
        self.quarantined.append(tenant)

    def unquarantine_tenant(self, tenant, reason=""):
        pass

    def drop_tenant(self, tenant):
        pass

    def prepare_publish(self, params=None, ckpt_dir=None,
                        target_version=None):
        return ("txn", target_version)

    def commit_publish(self, txn):
        self.version = txn[1] if txn[1] is not None else self.version + 1
        return self.version

    def abort_publish(self, txn):
        pass

    @property
    def params_version(self):
        return self.version

    def stats_snapshot(self):
        return {"served": 0, "steady_recompiles": 0}

    def warmup(self):
        self.warmups += 1
        return 0

    def close(self):
        pass


def _Ds():
    """A tiny REAL dataset (wire-serializable, so journal round-trips
    and recovery can re-register it)."""
    from induction_network_on_fewrel_tpu.data.fewrel import (
        FewRelDataset,
        Instance,
    )

    inst = Instance(tokens=("alpha", "beta", "gamma"),
                    head_pos=(0,), tail_pos=(2,))
    return FewRelDataset({"rel0": [inst, inst], "rel1": [inst]})


def _sup_fleet(tmp_path, restart_fn, clock, **kw):
    replicas = {f"r{i}": _SupReplica(f"r{i}") for i in range(2)}
    router = FleetRouter(replicas)
    control = FleetControl(
        router, journal=FleetJournal(tmp_path / "journal")
    )
    for i in range(6):
        control.register_tenant(f"t{i}", _Ds())
    # The committed generation a restarted replica must catch up to
    # (the stub's prepare ignores the path and honors target_version).
    control.journal.append("publish_commit", params_version=1,
                           ckpt_dir="/x/ckpt")
    sup = ReplicaSupervisor(
        router, restart_fn, journal=control.journal,
        backoff_s=1.0, restart_budget=3, clock=clock, **kw
    )
    return router, control, sup


def test_supervisor_backoff_schedule_and_budget(tmp_path):
    """Failed restarts wait exactly backoff_s * 2^(attempt-1) plus the
    deterministic jitter; the budget exhausts into permanent-dead with
    one replica_restart_exhausted record; forgive() re-arms."""
    clock = {"t": 0.0}
    calls = {"n": 0}

    def restart_fn(rid):
        calls["n"] += 1
        raise RuntimeError("spawn refused")

    router, control, sup = _sup_fleet(
        tmp_path, restart_fn, lambda: clock["t"]
    )
    try:
        router.mark_replica_dead("r0", reason="test")
        assert sup.poll()["failed"] == ["r0"] and calls["n"] == 1
        d1 = sup.next_delay("r0", 1)
        assert 1.0 <= d1 <= 1.25          # base 1.0 + <=25% jitter
        # Jitter is a pure function — same inputs, same delay.
        assert d1 == sup.next_delay("r0", 1)
        assert deterministic_jitter("r0", 1) == deterministic_jitter(
            "r0", 1
        )
        clock["t"] = d1 - 1e-6
        p = sup.poll()
        assert calls["n"] == 1 and p["failed"] == []   # inside backoff
        clock["t"] = d1 + 1e-6
        assert sup.poll()["failed"] == ["r0"] and calls["n"] == 2
        d2 = sup.next_delay("r0", 2)
        assert 2.0 <= d2 <= 2.5           # doubled
        clock["t"] += d2 + 1e-6
        p = sup.poll()                    # attempt 3: budget burned
        assert p["exhausted"] == ["r0"] and calls["n"] == 3
        assert sup.exhausted("r0")
        clock["t"] += 1000.0
        assert sup.poll()["failed"] == [] and calls["n"] == 3  # permanent
        sup.forgive("r0")
        assert sup.poll()["failed"] == ["r0"] and calls["n"] == 4
    finally:
        control.journal.close()
        router.close()


def test_supervisor_restart_reregisters_catches_up_revives(tmp_path):
    """A successful restart: fresh handle adopted, its directory
    tenants re-registered (threshold + quarantine carried), caught up
    to the journaled committed version, warmed, revived in placement —
    and its breaker history reset."""
    clock = {"t": 0.0}
    adopted = {}

    def restart_fn(rid):
        adopted["handle"] = _SupReplica(rid, version=0)
        return adopted["handle"]

    router, control, sup = _sup_fleet(
        tmp_path, restart_fn, lambda: clock["t"]
    )
    router.breaker = CircuitBreaker(failure_threshold=1, open_s=9.0)
    try:
        control.set_nota_threshold("t0", 0.4)
        victim = router.directory["t0"].owner   # owns >= t0 by choice
        mine = [t for t, e in router.directory.items()
                if e.owner == victim]
        control.quarantine_tenant(mine[0])
        router.breaker.record_failure(victim)   # opened pre-restart
        router.mark_replica_dead(victim, reason="test")
        p = sup.poll()
        assert p["restarted"] == [victim]
        fresh = adopted["handle"]
        assert router.replicas[victim] is fresh
        assert router.placement.state(victim) == UP
        assert sorted(fresh.registered) == sorted(mine)
        assert fresh.thresholds["t0"] == 0.4
        assert mine[0] in fresh.quarantined
        assert fresh.version == 1               # caught up (journal v1)
        assert fresh.warmups >= 1
        assert router.breaker.state(victim) == "closed"
    finally:
        control.journal.close()
        router.close()


def test_supervisor_probe_failure_marks_dead(tmp_path):
    clock = {"t": 0.0}
    router, control, sup = _sup_fleet(
        tmp_path, lambda rid: _SupReplica(rid), lambda: clock["t"]
    )
    try:
        router.replicas["r1"].alive = False
        p = sup.poll()
        assert p["marked_dead"] == ["r1"]
        assert router.placement.state("r1") == DEAD
    finally:
        control.journal.close()
        router.close()


# --- router recovery over stubs ---------------------------------------------


def test_router_recover_rebuilds_directory_from_journal(tmp_path):
    """Directory rows (owner/threshold/quarantine) rebuild bitwise from
    the journal on a FRESH router; a params-only publish (no ckpt) on a
    stale replica surfaces replica_stale_params instead of inventing a
    catch-up."""
    replicas = {f"r{i}": _SupReplica(f"r{i}") for i in range(2)}
    router = FleetRouter(replicas)
    journal = FleetJournal(tmp_path / "j")
    control = FleetControl(router, journal=journal)
    for i in range(5):
        control.register_tenant(f"t{i}", _Ds())
    control.set_nota_threshold("t1", 0.3)
    control.quarantine_tenant("t2", reason="hold")
    journal.append("publish_commit", params_version=4, ckpt_dir=None)
    view = router.directory_view()
    router.close()

    fresh = {f"r{i}": _SupReplica(f"r{i}", version=0) for i in range(2)}
    logger = MetricsLogger(tmp_path / "run", quiet=True)
    router2 = FleetRouter(fresh, logger=logger)
    summary = router2.recover(journal)
    assert summary["tenants"] == 5
    # Both fresh replicas lost their registries: every tenant
    # re-registers on its (identical, pure-rendezvous) owner.
    assert summary["reregistered"] == 5
    assert router2.directory_view() == view
    assert router2.directory["t1"].nota_threshold == 0.3
    assert router2.directory["t2"].quarantined is True
    logger.close()
    recs = [json.loads(line) for line in
            (tmp_path / "run" / "metrics.jsonl").read_text().splitlines()]
    stale = [r for r in recs
             if r.get("action") == "replica_stale_params"]
    assert len(stale) == 2        # both replicas at v0 < journaled v4
    assert [r for r in recs if r.get("action") == "recovered"]
    journal.close()
    router2.close()


def test_router_deadline_miss_is_load_not_health():
    """A server-side DeadlineExceeded on the future must NOT feed the
    replica breaker (TimeoutError IS an OSError subclass — the exact
    trap); a TransportTimeout (wedged peer) MUST."""
    class _DL(_SupReplica):
        def __init__(self, rid, exc):
            super().__init__(rid)
            self.exc = exc

        def submit(self, instance, deadline_s=None, tenant="default",
                   trace=None):
            f: Future = Future()
            f.set_exception(self.exc)
            return f

    for exc, expect_open in (
        (DeadlineExceeded("expired in queue"), False),
        (TransportTimeout("peer wedged"), True),
    ):
        replicas = {"r0": _DL("r0", exc)}
        router = FleetRouter(
            replicas,
            breaker=CircuitBreaker(failure_threshold=1, open_s=30.0),
        )
        control = FleetControl(router)
        control.register_tenant("t0", _Ds())
        fut = router.submit("q", tenant="t0")
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=5.0)
        assert (router.breaker.state("r0") == "open") is expect_open, exc
        router.close()


# --- socket transport: per-call deadline, retry, net chaos ------------------


class _WedgedServer:
    """Accepts connections, reads forever, never answers — the wedged
    peer a per-call deadline exists for."""

    def __init__(self):
        self._srv = socket.socket()
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(4)
        self.address = self._srv.getsockname()
        self._conns = []
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        try:
            while True:
                c, _ = self._srv.accept()
                self._conns.append(c)   # hold it open, say nothing
        except OSError:
            pass

    def close(self):
        self._srv.close()
        for c in self._conns:
            try:
                c.close()
            except OSError:
                pass


class _EchoHandler(socketserver.StreamRequestHandler):
    def handle(self):
        for line in self.rfile:
            if not line.strip():
                continue
            req = json.loads(line)
            self.server.ops.append(req["op"])  # type: ignore[attr-defined]
            resp = {"ok": True, "version": 7, "has": True,
                    "stats": {}, "compiled": 0, "classes": []}
            self.wfile.write((json.dumps(resp) + "\n").encode())
            self.wfile.flush()


def _echo_server():
    srv = socketserver.ThreadingTCPServer(
        ("127.0.0.1", 0), _EchoHandler, bind_and_activate=True
    )
    srv.daemon_threads = True
    srv.ops = []  # type: ignore[attr-defined]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def test_socket_per_call_deadline_typed_timeout():
    """A wedged peer surfaces as the typed TransportTimeout (a
    DeadlineExceeded) within the per-call deadline instead of blocking
    the calling thread forever — and the connection re-dials next
    call."""
    srv = _WedgedServer()
    try:
        rep = SocketReplica("w0", srv.address, call_deadline_s=0.3,
                            retries=0)
        with pytest.raises(DeadlineExceeded) as exc:
            _ = rep.params_version
        assert isinstance(exc.value, TransportTimeout)
        rep.close()
    finally:
        srv.close()


def test_socket_idempotent_retry_and_net_chaos():
    """net.partition on the FIRST attempt of an idempotent call is
    retried within the bounded budget (deterministic backoff);
    exhausting the budget surfaces ConnectionError; net.drop
    invalidates the connection; classify never retries."""
    srv = _echo_server()
    try:
        rep = SocketReplica("e0", srv.server_address[:2],
                            call_deadline_s=5.0, retries=2,
                            retry_backoff_s=0.001)
        # One partition, then clean: the retry heals it.
        install(ChaosRegistry.parse("net.partition@0:e0"))
        assert rep.params_version == 7
        install(None)
        # More partitions than the budget: typed connection failure.
        install(ChaosRegistry.parse("net.partition@0*9:e0"))
        with pytest.raises(ConnectionError):
            _ = rep.params_version
        install(None)
        # net.drop: request sent, response "lost", conn invalidated —
        # an idempotent op retries onto a FRESH connection and lands.
        install(ChaosRegistry.parse("net.drop@0:e0"))
        assert rep.has_tenant("t0") is True
        install(None)
        # classify (NOT idempotent): the same injected partition
        # surfaces instead of being silently resent.
        install(ChaosRegistry.parse("net.partition@0:e0"))
        fut = rep.submit({"tokens": ["a"]}, deadline_s=1.0)
        with pytest.raises(ConnectionError):
            fut.result(timeout=10.0)
        install(None)
        # net.slow: ARG is the delay PAYLOAD (never a filter) — the
        # call still lands, measurably later.
        import time as _time

        install(ChaosRegistry.parse("net.slow@0:0.05"))
        t0 = _time.monotonic()
        assert rep.params_version == 7
        assert _time.monotonic() - t0 >= 0.05
        install(None)
        rep.close()
    finally:
        install(None)
        srv.shutdown()
        srv.server_close()


def test_adapt_exhausted_latch_survives_via_journal(tmp_path):
    """The journaled adapt_exhausted latch is READ BACK: a recovered
    controller absorbs the quarantined flapper's drift triggers (no
    retrain storm), while other tenants still arm."""
    from induction_network_on_fewrel_tpu.obs.adapt import (
        AdaptationController,
    )

    journal = FleetJournal(tmp_path / "j")
    ctl = AdaptationController(
        train_fn=lambda *a, **k: (_ for _ in ()).throw(
            AssertionError("must not train")
        ),
        canary_fn=None,
        publish_fn=lambda *a, **k: 0,
        journal=journal,
    )
    # Simulate a prior life's exhaustion having been journaled...
    journal.append("adapt_exhausted", tenant="flapper", attempts=3.0)
    # ...and a restarted controller re-priming from the replay.
    ctl2 = AdaptationController(
        train_fn=lambda *a, **k: None, canary_fn=None,
        publish_fn=lambda *a, **k: 0,
    )
    ctl2.restore_exhausted(journal.materialize().adapt_exhausted)
    assert ctl2.trigger("flapper") is False      # absorbed: PERMANENT
    assert ctl2.trigger("healthy") is True       # others arm normally
    journal.close()


# --- slow lane: supervised restart over the REAL socket transport ----------


@pytest.mark.slow
def test_supervisor_restart_over_socket_transport(tmp_path):
    """The ISSUE 15 socket-mode arc end to end: a journaled 2-replica
    socket fleet, one replica's server process 'dies' (server stopped,
    engine closed), the supervisor's probe marks it dead, restart_fn
    spawns a FRESH engine + server + SocketReplica, and the adopted
    replica is re-registered + caught up to the journaled committed
    generation before taking traffic again."""
    import jax

    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.data import (
        make_synthetic_fewrel,
        make_synthetic_glove,
    )
    from induction_network_on_fewrel_tpu.data.tokenizer import (
        GloveTokenizer,
    )
    from induction_network_on_fewrel_tpu.fleet.transport import (
        ReplicaServer,
        SocketReplica,
    )
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.serving.buckets import zero_batch
    from induction_network_on_fewrel_tpu.serving.engine import (
        InferenceEngine,
    )
    from induction_network_on_fewrel_tpu.train.checkpoint import (
        CheckpointManager,
    )
    from induction_network_on_fewrel_tpu.train.steps import init_state

    cfg = ExperimentConfig(
        model="induction", encoder="cnn", hidden_size=16,
        vocab_size=122, word_dim=8, pos_dim=2, max_length=16,
        induction_dim=8, ntn_slices=4, routing_iters=2,
        n=3, train_n=3, k=2, q=2, device="cpu",
    )
    vocab = make_synthetic_glove(vocab_size=cfg.vocab_size - 2,
                                 word_dim=cfg.word_dim)
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    model = build_model(cfg, glove_init=vocab.vectors)
    params = model.init(
        jax.random.key(0),
        zero_batch(cfg.max_length, (1, cfg.n, cfg.k)),
        zero_batch(cfg.max_length, (1, 2)),
    )
    state = init_state(
        model, cfg,
        zero_batch(cfg.max_length, (1, cfg.n, cfg.k)),
        zero_batch(cfg.max_length, (1, cfg.total_q)),
    )
    ckpt = str(tmp_path / "ckpt")
    mngr = CheckpointManager(ckpt, cfg, stage="off")
    try:
        mngr.save(0, state, val_accuracy=0.0)
        mngr.wait()
    finally:
        mngr.close()
    datasets = [
        make_synthetic_fewrel(num_relations=3, instances_per_relation=8,
                              vocab_size=cfg.vocab_size - 2, seed=s)
        for s in range(2)
    ]

    def mk_engine():
        return InferenceEngine(model, params, cfg, tok, k=cfg.k,
                               buckets=(1, 2))

    engines = [mk_engine() for _ in range(2)]
    servers = [ReplicaServer(e).start() for e in engines]
    spawned: list = []
    router = None
    try:
        clients = {
            f"r{i}": SocketReplica(f"r{i}", srv.address,
                                   call_deadline_s=10.0)
            for i, srv in enumerate(servers)
        }
        router = FleetRouter(dict(clients))
        journal = FleetJournal(tmp_path / "journal")
        control = FleetControl(router, journal=journal)
        for i in range(4):
            control.register_tenant(f"t{i}", datasets[i % 2])
        for c in clients.values():
            c.warmup()
        assert control.publish_checkpoint(ckpt) == 1   # journaled
        pools = [
            [inst for r in ds.rel_names
             for inst in ds.instances[r][cfg.k:]]
            for ds in datasets
        ]
        victim = router.directory["t0"].owner
        vi = int(victim[1:])
        servers[vi].stop()
        engines[vi].close()

        def restart_fn(rid):
            assert rid == victim
            eng = mk_engine()
            srv = ReplicaServer(eng).start()
            spawned.append((srv, eng))
            return SocketReplica(rid, srv.address, call_deadline_s=10.0)

        sup = ReplicaSupervisor(router, restart_fn, journal=journal,
                                backoff_s=0.01)
        p = sup.poll()                      # probe fails -> dead
        assert victim in p["marked_dead"]
        p = sup.poll()                      # restart + adopt
        assert p["restarted"] == [victim]
        assert router.replicas[victim].params_version == 1  # caught up
        assert router.replicas[victim].has_tenant("t0")
        v = router.classify(pools[0][0], 15.0, tenant="t0")
        assert v["tenant"] == "t0" and not v.get("degraded")
        journal.close()
    finally:
        if router is not None:
            router.close()
        for srv, eng in spawned:
            srv.stop()
            eng.close()
        for srv in servers:
            try:
                srv.stop()
            except Exception:  # noqa: BLE001 — already stopped above
                pass
        for e in engines:
            e.close()


# --- the committed artifact + miniature replay ------------------------------


def _latest_recovery_artifact():
    paths = sorted(glob.glob(os.path.join(_REPO, "RECOVERY_r*.json")))
    assert paths, "no committed RECOVERY_r*.json artifact"
    return json.loads(open(paths[-1]).read())


def test_recovery_artifact_complete():
    """Acceptance shape: all three arms present and green, the
    zero-bands zero, the drill passed."""
    art = _latest_recovery_artifact()
    assert art["passed"]
    rk = art["router_kill"]
    assert rk["directory_bitwise"] and rk["placement_identical"]
    assert rk["tenants_lost"] == 0 and rk["errors"] == 0
    assert rk["reregistered"] >= 1 and rk["caught_up"] >= 1
    assert rk["params_version_uniform"] and rk["quarantine_survived"]
    rep = art["replica_kill"]
    assert rep["backoff_honored"] and rep["recovered"]
    assert rep["params_version_uniform"]
    assert rep["dropped_during_catchup"] == 0
    assert rep["steady_recompiles"] == 0
    tt = art["torn_tail"]
    assert tt["append_refused_after_tear"] and tt["prefix_recovered"]
    assert tt["appendable_after_heal"]
    assert art["zero_bands"] == {
        "tenants_lost": 0, "steady_recompiles": 0,
        "dropped_during_catchup": 0,
    }


def test_recovery_tier1_regression_gate(tmp_path):
    """Replay the committed artifact's miniature drill in-process: the
    durability invariants must hold EXACTLY (placement and journal
    replay are pure functions of the ids — a hash/framing change must
    re-emit RECOVERY_r*.json), and the telemetry it emits is
    schema-clean."""
    art = _latest_recovery_artifact()
    logger = MetricsLogger(tmp_path, quiet=True)
    try:
        res = loadgen.recovery_tier1_drill(
            seed=int(art["seed"]), logger=logger
        )
    finally:
        logger.close()
    assert res["passed"], res
    assert res["placement_distribution"] == art["placement_distribution"]
    assert res["router_kill"]["lost_replica"] == \
        art["router_kill"]["lost_replica"]
    assert res["router_kill"]["reregistered"] == \
        art["router_kill"]["reregistered"]
    assert res["replica_kill"]["victim"] == art["replica_kill"]["victim"]
    assert res["replica_kill"]["restart_attempts"] == \
        art["replica_kill"]["restart_attempts"]
    assert res["zero_bands"] == art["zero_bands"]
    n, errors = obs_report.check_schema(tmp_path / "metrics.jsonl")
    assert errors == [], errors
