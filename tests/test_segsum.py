"""Matmul-gradient embedding lookup (ops/segsum.py) vs the scatter reference.

The custom VJP must be numerically indistinguishable from autodiff's native
gather/scatter pair: forward is literally the same gather, and the backward
sums identical per-token terms (different order, f32 accumulation), so a
1e-6 tolerance holds at these magnitudes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.ops.segsum import (
    _MIN_CHUNK,
    lookup_matmul_grad,
)


def _ref_lookup(table, ids):
    return table[ids]


@pytest.mark.parametrize("shape", [(37,), (16, 40), (3, 5, 11)])
def test_forward_matches_gather(shape):
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.normal(size=(80, 5)), jnp.float32)
    ids = jnp.asarray(rng.integers(0, 80, size=shape), jnp.int32)
    np.testing.assert_array_equal(
        lookup_matmul_grad(table, ids), _ref_lookup(table, ids)
    )


@pytest.mark.parametrize(
    "rows,dim,n_ids",
    [
        (80, 5, 64),            # position-table shape, tiny
        (80, 5, 3 * _MIN_CHUNK + 7),  # multi-chunk with ragged tail
        (1654, 50, 2 * _MIN_CHUNK),   # lazy word-table shape
    ],
)
def test_grad_matches_scatter(rows, dim, n_ids):
    rng = np.random.default_rng(1)
    table = jnp.asarray(rng.normal(size=(rows, dim)), jnp.float32)
    ids = jnp.asarray(rng.integers(0, rows, size=(n_ids,)), jnp.int32)
    # Nonuniform downstream weighting so every token's cotangent differs.
    w = jnp.asarray(rng.normal(size=(n_ids, dim)), jnp.float32)

    def loss(fn, t):
        return jnp.sum(jnp.tanh(fn(t, ids)) * w)

    g_new = jax.jit(jax.grad(lambda t: loss(lookup_matmul_grad, t)))(table)
    g_ref = jax.jit(jax.grad(lambda t: loss(_ref_lookup, t)))(table)
    np.testing.assert_allclose(g_new, g_ref, rtol=1e-6, atol=1e-6)
    # Untouched rows get exactly zero from both paths.
    untouched = np.setdiff1d(np.arange(rows), np.asarray(ids))
    if untouched.size:
        np.testing.assert_array_equal(np.asarray(g_new)[untouched], 0.0)


def test_grad_through_embedding_module():
    """The Embedding module's matmul-grad path == a plain-gather twin."""
    from induction_network_on_fewrel_tpu.models.embedding import Embedding

    rng = np.random.default_rng(2)
    vocab, L = 120, 12
    emb = Embedding(vocab_size=vocab, word_dim=8, pos_dim=3, max_length=L)
    word = jnp.asarray(rng.integers(0, vocab, size=(6, L)), jnp.int32)
    pos1 = jnp.asarray(rng.integers(0, 2 * L, size=(6, L)), jnp.int32)
    pos2 = jnp.asarray(rng.integers(0, 2 * L, size=(6, L)), jnp.int32)
    params = emb.init(jax.random.PRNGKey(0), word, pos1, pos2)

    def loss(p):
        return jnp.sum(jnp.sin(emb.apply(p, word, pos1, pos2)))

    # Reference: same math with native gathers (scatter backward).
    def loss_ref(p):
        pp = p["params"]
        out = jnp.concatenate(
            [
                pp["word_embedding"][word],
                pp["pos1_embedding"][pos1],
                pp["pos2_embedding"][pos2],
            ],
            axis=-1,
        )
        return jnp.sum(jnp.sin(out))

    g = jax.grad(loss)(params)["params"]
    g_ref = jax.grad(loss_ref)(params)["params"]
    for k in ("word_embedding", "pos1_embedding", "pos2_embedding"):
        np.testing.assert_allclose(g[k], g_ref[k], rtol=1e-6, atol=1e-6)


def test_grad_matches_scatter_chunked_path(monkeypatch):
    """Force the scan-chunked backward (big-table regime) on small shapes."""
    import induction_network_on_fewrel_tpu.ops.segsum as segsum

    monkeypatch.setattr(segsum, "_ONEHOT_BYTES", 1)  # chunk floors to _MIN_CHUNK
    rng = np.random.default_rng(3)
    rows, dim, n_ids = 80, 5, 3 * _MIN_CHUNK + 7  # ragged tail across chunks
    table = jnp.asarray(rng.normal(size=(rows, dim)), jnp.float32)
    ids = jnp.asarray(rng.integers(0, rows, size=(n_ids,)), jnp.int32)
    w = jnp.asarray(rng.normal(size=(n_ids, dim)), jnp.float32)

    def loss(fn, t):
        return jnp.sum(jnp.tanh(fn(t, ids)) * w)

    g_new = jax.grad(lambda t: loss(lookup_matmul_grad, t))(table)
    g_ref = jax.grad(lambda t: loss(_ref_lookup, t))(table)
    # Chunked accumulation reassociates the per-row sums across chunk
    # boundaries: observed ~5e-6 relative vs the scatter at 3 chunks.
    np.testing.assert_allclose(g_new, g_ref, rtol=1e-5, atol=1e-6)
