"""Golden parity vs a torch-CPU twin (SURVEY.md §4.2).

With the reference mount empty there is nothing to diff against, so
correctness of the math is established by re-implementing each module
independently in torch (2.13 CPU, installed) with the SAME weights and
asserting the JAX outputs match to ~1e-5. The torch code below is written
from the paper equations, not from the JAX code, so a shared bug would have
to be made twice independently.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from induction_network_on_fewrel_tpu.models.encoders import CNNEncoder
from induction_network_on_fewrel_tpu.models.induction import Induction, RelationNTN
from induction_network_on_fewrel_tpu.ops import squash


def torch_squash(x, eps=1e-12):
    sq = (x**2).sum(-1, keepdim=True)
    return (sq / (1 + sq)) * x / torch.sqrt(sq + eps)


def test_squash_parity():
    x = np.random.default_rng(0).normal(size=(6, 13)).astype(np.float32)
    j = np.asarray(squash(jnp.asarray(x)))
    t = torch_squash(torch.tensor(x)).numpy()
    np.testing.assert_allclose(j, t, atol=1e-6)


def test_induction_routing_parity():
    """Full induction module: shared transform + squash + 3 routing iters."""
    rng = np.random.default_rng(1)
    B, N, K, D, C = 2, 3, 4, 16, 8
    support = rng.normal(size=(B, N, K, D)).astype(np.float32)

    ind = Induction(induction_dim=C, routing_iters=3)
    params = ind.init(jax.random.key(0), jnp.asarray(support))
    W = np.asarray(params["params"]["Dense_0"]["kernel"])  # [D, C]
    b = np.asarray(params["params"]["Dense_0"]["bias"])
    j = np.asarray(ind.apply(params, jnp.asarray(support)))

    # torch twin, straight from Geng et al. §3.2
    sup = torch.tensor(support)
    e_hat = torch_squash(sup @ torch.tensor(W) + torch.tensor(b))  # [B,N,K,C]
    bij = torch.zeros(B, N, K)
    for _ in range(3):
        d = torch.softmax(bij, dim=-1)
        c = torch_squash(torch.einsum("bnk,bnkc->bnc", d, e_hat))
        bij = bij + torch.einsum("bnkc,bnc->bnk", e_hat, c)
    d = torch.softmax(bij, dim=-1)
    c = torch_squash(torch.einsum("bnk,bnkc->bnc", d, e_hat))
    np.testing.assert_allclose(j, c.numpy(), atol=1e-5)


def test_ntn_parity():
    rng = np.random.default_rng(2)
    B, N, TQ, C, H = 2, 3, 7, 8, 5
    cvec = rng.normal(size=(B, N, C)).astype(np.float32)
    qry = rng.normal(size=(B, TQ, C)).astype(np.float32)

    ntn = RelationNTN(slices=H)
    params = ntn.init(jax.random.key(0), jnp.asarray(cvec), jnp.asarray(qry))
    M = np.asarray(params["params"]["tensor_slices"])          # [H, C, C]
    Wv = np.asarray(params["params"]["Dense_0"]["kernel"])     # [H, 1]
    bv = np.asarray(params["params"]["Dense_0"]["bias"])
    j = np.asarray(ntn.apply(params, jnp.asarray(cvec), jnp.asarray(qry)))

    c_t, q_t = torch.tensor(cvec), torch.tensor(qry)
    # v_iq = relu(c_i^T M^[1:h] e_q), logit = W_v v + b_v  (paper §3.3)
    v = torch.relu(torch.einsum("bnc,hcd,bqd->bqnh", c_t, torch.tensor(M), q_t))
    logit = v @ torch.tensor(Wv) + torch.tensor(bv)
    np.testing.assert_allclose(j, logit[..., 0].numpy(), atol=1e-4)


def test_cnn_encoder_parity():
    rng = np.random.default_rng(3)
    M_, L_, D_, Hf = 6, 10, 12, 16
    emb = rng.normal(size=(M_, L_, D_)).astype(np.float32)
    mask = (rng.random((M_, L_)) > 0.2).astype(np.float32)
    mask[:, 0] = 1.0  # at least one valid token

    enc = CNNEncoder(hidden_size=Hf, window=3)
    params = enc.init(jax.random.key(0), jnp.asarray(emb), jnp.asarray(mask))
    Wc = np.asarray(params["params"]["Conv_0"]["kernel"])  # [3, D, Hf]
    bc = np.asarray(params["params"]["Conv_0"]["bias"])
    j = np.asarray(enc.apply(params, jnp.asarray(emb), jnp.asarray(mask)))

    conv = torch.nn.Conv1d(D_, Hf, 3, padding=1)
    with torch.no_grad():
        conv.weight.copy_(torch.tensor(Wc).permute(2, 1, 0))  # [Hf, D, 3]
        conv.bias.copy_(torch.tensor(bc))
        x = torch.relu(conv(torch.tensor(emb).transpose(1, 2)))  # [M, Hf, L]
        x = x.masked_fill(torch.tensor(mask)[:, None, :] == 0, -1e30)
        t = x.max(dim=-1).values
    np.testing.assert_allclose(j, t.numpy(), atol=1e-4)
