"""Elasticity tier-1 tests (ISSUE 16): autoscaler hysteresis /
cool-down / bounds on the injectable clock (stub replicas, zero
engines), drain-in that retires only after the victim's queue empties
(never drops), the scale_stuck fault latching CRITICAL once and
re-arming on the next completed decision, WAL-tailing standby promotion
rebuilding the directory bitwise with the zombie primary's appends
fenced, the read-only tailer's torn-tail + compaction behavior, and the
miniature elasticity drill replayed against the committed
ELASTIC_r*.json band (the fleet-miniature discipline)."""

import glob
import json
import os
import sys
from concurrent.futures import Future

import pytest

from induction_network_on_fewrel_tpu.fleet import (
    DRAINING,
    FleetAutoscaler,
    FleetControl,
    FleetJournal,
    FleetRouter,
    HotStandby,
    JournalError,
    JournalLease,
    JournalTailer,
    ReplicaHandle,
)
from induction_network_on_fewrel_tpu.fleet.journal import WAL_NAME
from induction_network_on_fewrel_tpu.obs.health import HealthWatchdog
from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOLS = os.path.join(_REPO, "tools")
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

import loadgen  # noqa: E402
import obs_report  # noqa: E402


class _Replica(ReplicaHandle):
    """Stub replica with settable queue depth/occupancy — the policy
    loop's mechanics without an engine in sight."""

    def __init__(self, rid, version=1):
        self.replica_id = rid
        self.version = version
        self.registered: list[str] = []
        self.thresholds: dict[str, float] = {}
        self.quarantined: list[str] = []
        self.warmups = 0
        self.queue_depth = 0
        self.occupancy = 0.0
        self.closed = False

    def submit(self, instance, deadline_s=None, tenant="default",
               trace=None):
        f: Future = Future()
        f.set_result({"label": "rel0", "tenant": tenant,
                      "replica": self.replica_id})
        return f

    def has_tenant(self, tenant):
        return tenant in self.registered

    def register_dataset(self, dataset, tenant, max_classes=None):
        self.registered.append(tenant)
        return []

    def set_nota_threshold(self, threshold, tenant):
        self.thresholds[tenant] = threshold

    def quarantine_tenant(self, tenant, reason=""):
        self.quarantined.append(tenant)

    def unquarantine_tenant(self, tenant, reason=""):
        pass

    def drop_tenant(self, tenant):
        pass

    def prepare_publish(self, params=None, ckpt_dir=None,
                        target_version=None):
        return ("txn", target_version)

    def commit_publish(self, txn):
        self.version = txn[1] if txn[1] is not None else self.version + 1
        return self.version

    def abort_publish(self, txn):
        pass

    @property
    def params_version(self):
        return self.version

    def stats_snapshot(self):
        return {"served": 0, "steady_recompiles": 0,
                "batch_occupancy": self.occupancy,
                "queue_depth": self.queue_depth}

    def warmup(self):
        self.warmups += 1
        return 1

    def close(self):
        self.closed = True


def _Ds():
    from induction_network_on_fewrel_tpu.data.fewrel import (
        FewRelDataset,
        Instance,
    )

    inst = Instance(tokens=("alpha", "beta", "gamma"),
                    head_pos=(0,), tail_pos=(2,))
    return FewRelDataset({"rel0": [inst, inst], "rel1": [inst]})


def _fleet(tmp_path, n=1, tenants=6, logger=None):
    replicas = {f"r{i:02d}": _Replica(f"r{i:02d}") for i in range(n)}
    router = FleetRouter(dict(replicas), logger=logger)
    journal = FleetJournal(tmp_path / "journal", logger=logger)
    control = FleetControl(router, journal=journal, logger=logger)
    for i in range(tenants):
        control.register_tenant(f"t{i}", _Ds())
    # The committed generation a spawned replica must catch up to (the
    # stub's prepare ignores the path and honors target_version).
    journal.append("publish_commit", params_version=1, ckpt_dir="/x/ckpt")
    return router, control, journal


def _scaler(control, spawned, clock, **kw):
    def spawn(rid):
        spawned[rid] = _Replica(rid, version=0)
        return spawned[rid]

    defaults = dict(
        min_replicas=1, max_replicas=3,
        high_occupancy=0.75, low_occupancy=0.20,
        high_windows=2, low_windows=2,
        cooldown_s=10.0, scale_budget_s=30.0,
        clock=lambda: clock["t"],
    )
    defaults.update(kw)
    return FleetAutoscaler(control, spawn, **defaults)


HOT = {"occupancy": 0.9}
COOL = {"occupancy": 0.0}


# --- autoscaler: hysteresis, cool-down, bounds ------------------------------


def test_autoscaler_hysteresis_needs_consecutive_pressure(tmp_path):
    """One hot tick never scales; a neutral tick resets the streak; the
    high_windows-th CONSECUTIVE hot tick scales out — with the newcomer
    caught up to the journaled generation and warmed BEFORE joining."""
    router, control, journal = _fleet(tmp_path, n=1)
    clock, spawned = {"t": 0.0}, {}
    sc = _scaler(control, spawned, clock)
    assert sc.tick(dict(HOT))["action"] == "none"
    clock["t"] = 1.0
    # Neither pressure nor idle: the streak must reset.
    assert sc.tick({"occupancy": 0.5})["action"] == "none"
    clock["t"] = 2.0
    assert sc.tick(dict(HOT))["action"] == "none"
    clock["t"] = 3.0
    assert sc.tick(dict(HOT))["action"] == "scale_out"
    assert sorted(router.replicas) == ["r00", "r01"]
    newcomer = spawned["r01"]
    assert newcomer.params_version == 1      # caught up pre-join
    assert newcomer.warmups == 1             # warmed pre-join
    assert newcomer.registered               # pre-registered its tenants
    # Journaled: a recovery replays the membership change.
    assert "r01" in journal.materialize().replicas
    router.close()
    journal.close()


def test_autoscaler_cooldown_blocks_new_decision_until_boundary(tmp_path):
    """After a completed decision no NEW decision starts inside
    cooldown_s — and the first tick AT the boundary may scale again."""
    router, control, journal = _fleet(tmp_path, n=1)
    clock, spawned = {"t": 0.0}, {}
    sc = _scaler(control, spawned, clock)
    sc.tick(dict(HOT))
    clock["t"] = 1.0
    assert sc.tick(dict(HOT))["action"] == "scale_out"   # completes at t=1
    clock["t"] = 10.999                                  # 1 + 10 - eps
    assert sc.tick(dict(HOT))["action"] == "cooldown"
    clock["t"] = 11.0                                    # the boundary
    assert sc.tick(dict(HOT))["action"] == "scale_out"
    assert len(router.replicas) == 3
    router.close()
    journal.close()


def test_autoscaler_respects_min_max_bounds(tmp_path):
    router, control, journal = _fleet(tmp_path, n=1)
    clock, spawned = {"t": 0.0}, {}
    sc = _scaler(control, spawned, clock, max_replicas=1, min_replicas=1)
    sc.tick(dict(HOT))
    clock["t"] = 1.0
    assert sc.tick(dict(HOT))["action"] == "at_max"
    clock["t"] = 2.0
    sc.tick(dict(COOL))
    clock["t"] = 3.0
    assert sc.tick(dict(COOL))["action"] == "at_min"
    assert sorted(router.replicas) == ["r00"]
    router.close()
    journal.close()


def test_autoscaler_drain_waits_for_inflight_then_retires(tmp_path):
    """Drain-in never drops: the victim keeps its registrations (and
    keeps serving) while requests are queued on it; only an EMPTY queue
    moves the tenants and retires the replica — journaled."""
    router, control, journal = _fleet(tmp_path, n=2)
    clock, spawned = {"t": 0.0}, {}
    sc = _scaler(control, spawned, clock)
    victim = router.replicas["r01"]
    victim.queue_depth = 2                    # in-flight work pinned
    sc.tick(dict(COOL))
    clock["t"] = 1.0
    assert sc.tick(dict(COOL))["action"] == "pending"
    # Drained out of placement but NOT retired, registrations intact.
    assert router.placement.state("r01") == DRAINING
    assert "r01" in router.replicas
    owned = [t for t, e in router.directory.items() if e.owner == "r01"]
    assert owned, "rendezvous should hand r01 some tenants"
    clock["t"] = 2.0
    assert sc.tick(dict(COOL))["action"] == "pending"
    victim.queue_depth = 0                    # the queue drains
    clock["t"] = 3.0
    assert sc.tick(dict(COOL))["action"] == "drain_in"
    assert sorted(router.replicas) == ["r00"]
    assert victim.closed
    assert len(router.directory) == 6         # every tenant moved, none lost
    assert all(e.owner == "r00" for e in router.directory.values())
    assert "r01" not in journal.materialize().replicas   # replayable
    router.close()
    journal.close()


def test_scale_stuck_latches_critical_once_and_rearms(tmp_path):
    """A decision that cannot complete within scale_budget_s emits ONE
    kind="fault" scale_stuck; the watchdog latches it CRITICAL once and
    re-arms only on a later completed scale event."""
    logger = MetricsLogger(tmp_path, quiet=True)
    wd = HealthWatchdog(logger=logger)
    logger.add_hook(wd.observe_record)
    router, control, journal = _fleet(tmp_path, n=1, logger=logger)
    clock = {"t": 0.0}
    broken = {"on": True}
    spawned = {}

    def spawn(rid):
        if broken["on"]:
            raise RuntimeError("spawn backend down (test)")
        spawned[rid] = _Replica(rid, version=0)
        return spawned[rid]

    sc = FleetAutoscaler(
        control, spawn, min_replicas=1, max_replicas=3,
        high_windows=2, low_windows=2, cooldown_s=2.0,
        scale_budget_s=5.0, clock=lambda: clock["t"], logger=logger,
    )
    for _ in range(10):                       # t=0..9: budget blown at 6
        assert sc.tick(dict(HOT))["action"] in ("none", "pending")
        clock["t"] += 1.0
    stuck = [e for e in wd.events if e.event == "scale_stuck"]
    assert len(stuck) == 1 and stuck[0].severity == "critical"
    assert "scale_out" in stuck[0].message
    # The loop kept retrying: fixing the backend completes the decision
    # (no cooldown applies to an in-progress decision)...
    broken["on"] = False
    assert sc.tick(dict(HOT))["action"] == "scale_out"
    # ...and the completed scale event re-armed the latch: a second
    # stuck decision pages again.
    broken["on"] = True
    clock["t"] += 10.0
    for _ in range(8):
        sc.tick(dict(HOT))
        clock["t"] += 1.0
    stuck = [e for e in wd.events if e.event == "scale_stuck"]
    assert len(stuck) == 2
    router.close()
    journal.close()
    logger.close()


# --- hot standby: tail, promote, fence --------------------------------------


def test_standby_promotion_is_bitwise_and_never_drops(tmp_path):
    """The tailed standby promotes into a router whose directory is
    BITWISE the primary's (owners, thresholds, quarantine flags) with
    identical placement; during the window known tenants get degraded
    NOTA (served, never dropped) and unknown tenants a loud refusal."""
    router, control, journal = _fleet(tmp_path, n=2)
    journal.acquire_lease("primary")
    control.set_nota_threshold("t1", 0.3)
    control.quarantine_tenant("t2", reason="hold")
    standby = HotStandby(tmp_path / "journal")
    assert standby.poll() > 0
    view = router.directory_view()
    owners = router.placement.owners(sorted(router.directory))
    # Kill-9: the primary object is gone; nothing was shut down.
    del router, control

    v = standby.classify("support me", tenant="t0")
    assert v["degraded"] and v["nota"] and v["label"]
    assert standby.degraded_served == 1
    with pytest.raises(ValueError):
        standby.classify("support me", tenant="t99")

    fresh = {f"r{i:02d}": _Replica(f"r{i:02d}", version=0)
             for i in range(2)}
    promo = standby.promote(fresh)
    assert standby.router.directory_view() == view
    assert standby.router.placement.owners(sorted(view)) == owners
    assert promo["reregistered"] == 6         # fresh registries rebuilt
    assert promo["lease_epoch"] == 2          # primary held epoch 1
    assert standby.router.directory["t1"].nota_threshold == 0.3
    assert standby.router.directory["t2"].quarantined is True
    # The front door is real now.
    assert standby.classify("x", tenant="t0")["label"] == "rel0"
    with pytest.raises(RuntimeError):
        standby.promote(fresh)                # no double takeover
    standby.router.close()
    standby.journal.close()
    journal.close()


def test_split_brain_append_refused_after_promotion(tmp_path):
    """The lease fence: once the standby acquires the lease, the zombie
    primary's next journaled op raises instead of split-braining the
    WAL — while the promoted writer's ops land fine."""
    router, control, journal = _fleet(tmp_path, n=2)
    journal.acquire_lease("primary")
    control.set_nota_threshold("t0", 0.4)     # leased primary appends fine
    standby = HotStandby(tmp_path / "journal")
    standby.poll()
    standby.promote(
        {f"r{i:02d}": _Replica(f"r{i:02d}", version=0) for i in range(2)}
    )
    with pytest.raises(JournalError):
        journal.append("tenant_threshold", tenant="t1", threshold=0.5)
    with pytest.raises(JournalError):
        control.quarantine_tenant("t3", reason="zombie op")
    # The promoted control plane is the single writer now.
    control2 = FleetControl(standby.router, journal=standby.journal)
    control2.set_nota_threshold("t1", 0.6)
    state = standby.journal.materialize()
    assert state.tenants["t1"]["nota_threshold"] == 0.6
    assert state.tenants["t3"]["quarantined"] is False
    router.close()
    standby.router.close()
    standby.journal.close()
    journal.close()


def test_tailer_never_truncates_a_torn_tail(tmp_path):
    """The read-only tailer stops at the last clean frame of a torn
    WAL and leaves the file byte-identical — a short tail is usually an
    append IN PROGRESS on the live primary, not corruption to repair."""
    journal = FleetJournal(tmp_path / "j")
    journal.append("tenant_register", tenant="t0", source=None,
                   max_classes=None, nota_threshold=0.5)
    journal.append("replica_add", replica="r0")
    journal.close()
    wal = tmp_path / "j" / WAL_NAME
    with open(wal, "ab") as fh:               # half a frame lands
        fh.write(b"\x40\x00\x00\x00\x99\x99")
    torn = wal.read_bytes()
    tailer = JournalTailer(tmp_path / "j")
    assert tailer.poll() == 2                 # the clean prefix applies
    assert tailer.state.replicas == {"r0": "up"}
    assert wal.read_bytes() == torn           # READ-ONLY: not repaired
    # A later completed append (the "in-progress" write finishing is
    # modeled by the writer repairing + appending) is picked up.
    j2 = FleetJournal(tmp_path / "j")         # the WRITER repairs
    j2.append("replica_add", replica="r1")
    assert tailer.poll() == 1
    assert set(tailer.state.replicas) == {"r0", "r1"}
    j2.close()


def test_tailer_follows_snapshot_compaction(tmp_path):
    """Compaction moves the WAL out from under the tailer's offset; the
    tailer rebases onto the snapshot and stays byte-equal with a full
    materialize()."""
    journal = FleetJournal(tmp_path / "j")
    tailer = JournalTailer(tmp_path / "j")
    for i in range(4):
        journal.append("tenant_register", tenant=f"t{i}", source=None,
                       max_classes=None, nota_threshold=0.5)
    assert tailer.poll() == 4
    journal.append("replica_add", replica="r0")
    journal.compact()
    journal.append("replica_add", replica="r1")
    tailer.poll()
    assert json.dumps(tailer.state.to_dict(), sort_keys=True) == \
        json.dumps(journal.materialize().to_dict(), sort_keys=True)
    journal.close()


def test_lease_epochs_are_monotonic(tmp_path):
    lease = JournalLease(tmp_path)
    assert lease.read() == {"owner": None, "epoch": 0}
    assert lease.acquire("a") == 1
    assert lease.acquire("b") == 2
    assert lease.acquire("a") == 3
    assert lease.read() == {"owner": "a", "epoch": 3}


# --- the committed artifact + miniature drill gate --------------------------


def _latest_elastic_artifact():
    paths = sorted(glob.glob(os.path.join(_REPO, "ELASTIC_r*.json")))
    assert paths, "no committed ELASTIC_r*.json artifact"
    return json.loads(open(paths[-1]).read())


def test_elastic_artifact_complete():
    """Acceptance shape: ramp/trough/kill legs present and green, the
    zero-bands zero, the drill passed."""
    art = _latest_elastic_artifact()
    assert art["passed"]
    so = art["scale_out"]
    assert so["actions"] == ["none", "scale_out"]
    assert so["replicas_after"] == 2 and so["warm_compiles"] >= 1
    assert so["params_version_uniform"] and so["errors"] == 0
    di = art["drain_in"]
    assert di["drained"] and di["victim_matches"]
    assert di["inflight_at_drain"] >= 1 and di["inflight_survived"]
    assert di["replicas_after"] == 1 and di["tenants_intact"]
    pr = art["promotion"]
    assert pr["directory_bitwise"] and pr["placement_identical"]
    assert pr["tenants_lost"] == 0
    assert pr["degraded_during_promotion"] >= 1
    assert pr["unknown_tenant_refused"] and pr["inflight_survived"]
    assert pr["final_tail_ops"] >= 1
    assert pr["split_brain_refused"] and pr["promoted_writer_ok"]
    assert art["zero_bands"] == {
        "dropped_during_scale": 0, "dropped_during_promotion": 0,
        "tenants_lost": 0, "steady_recompiles": 0,
    }


def test_elastic_tier1_regression_gate(tmp_path):
    """Replay the committed artifact's miniature drill in-process: the
    elasticity invariants must hold EXACTLY (placement, replica naming,
    and journal replay are pure functions of the ids — a hash/policy
    change must re-emit ELASTIC_r*.json), and the telemetry it emits is
    schema-clean."""
    art = _latest_elastic_artifact()
    logger = MetricsLogger(tmp_path, quiet=True)
    try:
        res = loadgen.elastic_tier1_drill(
            seed=int(art["seed"]), logger=logger
        )
    finally:
        logger.close()
    assert res["passed"], res
    assert res["scale_out"]["replica"] == art["scale_out"]["replica"]
    assert res["scale_out"]["warm_compiles"] == \
        art["scale_out"]["warm_compiles"]
    assert res["scale_out"]["moved"] == art["scale_out"]["moved"]
    assert res["drain_in"]["replica"] == art["drain_in"]["replica"]
    assert res["drain_in"]["inflight_at_drain"] == \
        art["drain_in"]["inflight_at_drain"]
    assert res["promotion"]["scale_out2_replica"] == \
        art["promotion"]["scale_out2_replica"]
    assert res["promotion"]["lease_epoch"] == \
        art["promotion"]["lease_epoch"]
    assert res["zero_bands"] == art["zero_bands"]
    n, errors = obs_report.check_schema(tmp_path / "metrics.jsonl")
    assert errors == [], errors
