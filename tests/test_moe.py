"""MoE expert layer: routing math, dense equivalence, ep-sharded equality.

The reference has no MoE (SURVEY.md §2.2 "Expert parallel: NO"); this suite
pins the framework's expert layer (models/moe.py) the same way the ring
suite pins sequence parallelism: math unit tests plus exact equality of the
ep-sharded path against the single-device one on the 8-virtual-CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    GloveTokenizer,
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.models.build import batch_to_model_inputs
from induction_network_on_fewrel_tpu.models.moe import MoeFfn
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler


def _init(module, x, seed=0):
    return module.init(jax.random.key(seed), x)


def test_moe_output_shape_and_finite():
    x = jax.random.normal(jax.random.key(1), (4, 6, 16))
    moe = MoeFfn(num_experts=4, d_ff=32, top_k=2)
    params = _init(moe, x)
    y = moe.apply(params, x)
    assert y.shape == x.shape
    assert np.isfinite(np.asarray(y)).all()


def test_moe_matches_dense_when_experts_identical():
    """With identical expert weights, no capacity drops, and renormalized
    gates, the routed layer must equal a single dense FFN exactly: routing
    becomes irrelevant when every expert computes the same function."""
    d, f = 16, 32
    x = jax.random.normal(jax.random.key(2), (3, 5, d))
    # capacity_factor large enough that every token fits everywhere.
    moe = MoeFfn(num_experts=4, d_ff=f, top_k=2, capacity_factor=100.0)
    params = _init(moe, x)

    w_up = jax.random.normal(jax.random.key(3), (d, f)) * 0.1
    w_down = jax.random.normal(jax.random.key(4), (f, d)) * 0.1
    p = jax.tree_util.tree_map_with_path(
        lambda path, leaf: _tile(path, leaf, w_up, w_down), params
    )
    y = moe.apply(p, x)

    def dense_ffn(t):
        return jax.nn.gelu(t @ w_up) @ w_down

    np.testing.assert_allclose(
        np.asarray(y), np.asarray(dense_ffn(x)), rtol=2e-5, atol=2e-5
    )


def _tile(path, leaf, w_up, w_down):
    name = str(path[-1])
    if "experts_up_bias" in name or "experts_down_bias" in name:
        return jnp.zeros_like(leaf)
    if "experts_up" in name:
        return jnp.broadcast_to(w_up[None], leaf.shape).astype(leaf.dtype)
    if "experts_down" in name:
        return jnp.broadcast_to(w_down[None], leaf.shape).astype(leaf.dtype)
    return leaf


def test_moe_capacity_drops_tokens():
    """With capacity 1 slot per expert, most tokens are dropped -> output
    rows for dropped tokens are exactly zero (residual carries them)."""
    d = 8
    x = jax.random.normal(jax.random.key(5), (1, 16, d))
    moe = MoeFfn(num_experts=2, d_ff=16, top_k=1, capacity_factor=1e-9)
    params = _init(moe, x)
    y = np.asarray(moe.apply(params, x)).reshape(16, d)
    zero_rows = int((np.abs(y).sum(axis=-1) < 1e-12).sum())
    assert zero_rows >= 14  # 16 tokens, 2 experts x 1 slot


def test_moe_aux_loss_sown_and_near_one_for_uniform_router():
    """Uniform routing: f_e = p_e = 1/E -> aux = E * E*(1/E^2) = 1."""
    d = 8
    x = jax.random.normal(jax.random.key(6), (2, 8, d))
    moe = MoeFfn(num_experts=4, d_ff=16, top_k=1)
    params = _init(moe, x)
    # Zero the router -> exactly uniform probs (argmax ties pick expert 0,
    # so f is NOT uniform, but p is; aux = E * sum(f_e * 1/E) = 1).
    params = jax.tree_util.tree_map_with_path(
        lambda path, leaf: (
            jnp.zeros_like(leaf) if "router" in str(jax.tree_util.keystr(path))
            else leaf
        ),
        params,
    )
    _, sown = moe.apply(params, x, mutable="losses")
    (aux,) = jax.tree.leaves(sown)
    np.testing.assert_allclose(float(aux), 1.0, atol=1e-5)


@pytest.fixture(scope="module")
def moe_episode_setup():
    cfg = ExperimentConfig(
        model="proto", encoder="transformer", train_n=3, n=3, k=2, q=2,
        batch_size=4, max_length=12, vocab_size=302,
        compute_dtype="float32", tfm_layers=2, tfm_model=32, tfm_heads=2,
        tfm_ff=64, moe_experts=4, moe_top_k=2, moe_every=2,
        lr=1e-3, weight_decay=0.0,
    )
    vocab = make_synthetic_glove(vocab_size=300)
    ds = make_synthetic_fewrel(
        num_relations=6, instances_per_relation=8, vocab_size=300
    )
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    sampler = EpisodeSampler(ds, tok, cfg.train_n, cfg.k, cfg.q,
                             batch_size=cfg.batch_size, seed=0)
    model = build_model(cfg, glove_init=vocab.vectors)
    sup, qry, label = batch_to_model_inputs(sampler.sample_batch())
    return cfg, model, sampler, sup, qry, label


def test_moe_transformer_end_to_end_step(moe_episode_setup):
    """A full train step through the MoE transformer: loss finite, params
    (including expert weights AND the router, via the aux loss) get
    gradients."""
    from induction_network_on_fewrel_tpu.train.steps import (
        init_state, make_train_step,
    )

    cfg, model, sampler, sup, qry, label = moe_episode_setup
    state = init_state(model, cfg, sup, qry)

    def leaves_with(params, frag):
        return [
            leaf for path, leaf in jax.tree_util.tree_leaves_with_path(params)
            if frag in jax.tree_util.keystr(path)
        ]

    # Snapshot before the step: the jitted step donates its input state.
    before_by_frag = {
        frag: [np.asarray(x) for x in leaves_with(state.params, frag)]
        for frag in ("experts_up", "router")
    }
    step = make_train_step(model, cfg)
    new_state, metrics = step(state, sup, qry, label)
    assert np.isfinite(float(metrics["loss"]))

    for frag in ("experts_up", "router"):
        before = before_by_frag[frag]
        after = leaves_with(new_state.params, frag)
        assert before and len(before) == len(after)
        moved = any(
            not np.allclose(np.asarray(b), np.asarray(a))
            for b, a in zip(before, after)
        )
        assert moved, f"{frag} params did not update"


@pytest.mark.slow
def test_moe_ep_sharded_step_matches_single_device(moe_episode_setup):
    """GSPMD (dp=2, ep=4) training step == single-device step, metrics and
    params, on the virtual 8-CPU mesh."""
    from induction_network_on_fewrel_tpu.parallel import make_mesh
    from induction_network_on_fewrel_tpu.parallel.sharding import (
        make_sharded_train_step,
    )
    from induction_network_on_fewrel_tpu.train.steps import (
        init_state, make_train_step,
    )

    cfg, model, sampler, sup, qry, label = moe_episode_setup
    cfg = cfg.replace(dp=2, ep=4, batch_size=4)

    state_a = init_state(model, cfg, sup, qry)
    state_b = jax.tree.map(
        lambda x: x.copy() if hasattr(x, "copy") else x, state_a
    )

    single = make_train_step(model, cfg)
    mesh = make_mesh(dp=2, ep=4, devices=jax.devices()[:8])
    sharded = make_sharded_train_step(model, cfg, mesh, state_a)

    # Tolerances are looser than the dense-model parallel tests: GSPMD's
    # different reduction order shifts router logits by float-epsilon, and a
    # near-tie argmax route flipping for one token is a legitimate (tiny)
    # trajectory divergence — not a sharding bug. Real sharding errors show
    # up orders of magnitude above these bounds.
    for _ in range(3):
        sup_b, qry_b, label_b = batch_to_model_inputs(sampler.sample_batch())
        state_a, m_a = single(state_a, sup_b, qry_b, label_b)
        state_b, m_b = sharded(state_b, sup_b, qry_b, label_b)
        np.testing.assert_allclose(
            float(m_a["loss"]), float(m_b["loss"]), rtol=1e-4, atol=1e-5
        )

    flat_a = jax.tree.leaves(jax.device_get(state_a.params))
    flat_b = jax.tree.leaves(jax.device_get(state_b.params))
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(a, b, rtol=5e-3, atol=1e-3)


def test_moe_mask_excludes_pads_from_capacity_and_aux():
    """Pad tokens must consume no expert slots: with capacity sized for the
    real tokens only, heavy padding must not cause real-token drops, pad
    outputs must be zero, and the aux statistics must count real tokens."""
    d = 8
    M, L = 2, 16
    x = jax.random.normal(jax.random.key(7), (M, L, d))
    mask = jnp.zeros((M, L), jnp.int32).at[:, :4].set(1)  # 8 real / 32 total
    moe = MoeFfn(num_experts=2, d_ff=16, top_k=1, capacity_factor=1.0)
    params = _init_with_mask(moe, x, mask)
    y = np.asarray(moe.apply(params, x, mask)).reshape(M * L, d)
    flat_mask = np.asarray(mask).reshape(-1)
    # Pad positions produce exactly zero (residual carries them).
    assert np.abs(y[flat_mask == 0]).max() == 0.0
    # Real positions all got routed (capacity C = ceil(1*32/2*1.0) = 16
    # >> 8 real tokens, so none can drop even though pads outnumber them).
    assert (np.abs(y[flat_mask == 1]).sum(axis=-1) > 0).all()
    # Aux is computed over real tokens: still ~O(1), not diluted by pads.
    _, sown = moe.apply(params, x, mask, mutable="losses")
    (aux,) = jax.tree.leaves(sown)
    assert 0.5 < float(aux) < 4.0


def _init_with_mask(module, x, mask, seed=0):
    return module.init(jax.random.key(seed), x, mask)


def test_moe_grouped_routing_matches_dense_when_experts_identical():
    """Grouping is a memory layout, not a semantics change, in the no-drop
    regime: with identical experts the output still equals the dense FFN
    even when tokens span several routing groups."""
    d, f = 16, 32
    x = jax.random.normal(jax.random.key(8), (4, 8, d))  # T=32
    moe = MoeFfn(num_experts=4, d_ff=f, top_k=2, capacity_factor=100.0,
                 group_size=8)  # 4 groups of 8
    params = _init(moe, x)
    w_up = jax.random.normal(jax.random.key(9), (d, f)) * 0.1
    w_down = jax.random.normal(jax.random.key(10), (f, d)) * 0.1
    p = jax.tree_util.tree_map_with_path(
        lambda path, leaf: _tile(path, leaf, w_up, w_down), params
    )
    y = moe.apply(p, x)

    def dense_ffn(t):
        return jax.nn.gelu(t @ w_up) @ w_down

    np.testing.assert_allclose(
        np.asarray(y), np.asarray(dense_ffn(x)), rtol=2e-5, atol=2e-5
    )


def test_moe_group_padding_roundtrip():
    """T not divisible by group_size: the pad-to-groups path must keep
    shapes and not leak padding into outputs."""
    x = jax.random.normal(jax.random.key(11), (3, 5, 8))  # T=15
    moe = MoeFfn(num_experts=2, d_ff=16, top_k=1, group_size=4)  # G=4, pad=1
    params = _init(moe, x)
    y = moe.apply(params, x)
    assert y.shape == x.shape
    assert np.isfinite(np.asarray(y)).all()
