"""Math unit tests (SURVEY.md §4.1): squash, masked reductions."""

import jax
import jax.numpy as jnp
import numpy as np

from induction_network_on_fewrel_tpu.ops import masked_max, masked_mean, masked_softmax, squash


def test_squash_norm_range():
    x = jax.random.normal(jax.random.key(0), (32, 16)) * 5.0
    y = squash(x)
    norms = jnp.linalg.norm(y, axis=-1)
    assert (norms >= 0).all() and (norms < 1).all()


def test_squash_direction_preserved():
    x = jax.random.normal(jax.random.key(1), (8, 16))
    y = squash(x)
    cos = jnp.sum(x * y, -1) / (
        jnp.linalg.norm(x, axis=-1) * jnp.linalg.norm(y, axis=-1)
    )
    np.testing.assert_allclose(np.asarray(cos), 1.0, atol=1e-5)


def test_squash_formula():
    x = jnp.array([[3.0, 4.0]])  # ||x|| = 5
    y = squash(x)
    expect = (25.0 / 26.0) * (np.array([[3.0, 4.0]]) / 5.0)
    np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-5)


def test_squash_zero_safe():
    y = squash(jnp.zeros((4, 8)))
    assert np.isfinite(np.asarray(y)).all()


def test_masked_softmax():
    scores = jnp.array([[1.0, 2.0, 3.0, 4.0]])
    mask = jnp.array([[1.0, 1.0, 0.0, 1.0]])
    p = np.asarray(masked_softmax(scores, mask))
    assert p[0, 2] == 0.0
    np.testing.assert_allclose(p.sum(), 1.0, atol=1e-5)
    e = np.exp([1.0, 2.0, 4.0])
    np.testing.assert_allclose(p[0, [0, 1, 3]], e / e.sum(), rtol=1e-5)


def test_masked_max_mean():
    x = jnp.array([[1.0, 5.0, 3.0]])
    mask = jnp.array([[1.0, 0.0, 1.0]])
    assert float(masked_max(x, mask, axis=-1)[0]) == 3.0
    np.testing.assert_allclose(float(masked_mean(x, mask, axis=-1)[0]), 2.0, atol=1e-6)
