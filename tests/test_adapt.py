"""Self-healing adaptation controller tier-1 gates (ISSUE 14).

Two layers, the tests/test_chaos.py discipline:

* Pure state-machine pins on ``obs/adapt.AdaptationController`` with
  stub train/canary/publish functions and an injected clock — every arm
  of armed -> triggered -> training -> canary -> publishing -> verifying
  -> cooldown/exhausted, the exponential backoff, the flap damper, the
  rollback paths, and the one-home knob resolution
  (``config.resolve_adapt_policy`` / ``parse_canary_plan``) plus the
  library canary verdict math (``tools/scenarios.canary_verdict``).
* The miniature IN-PROCESS drill (``tools/loadgen.adapt_tier1_drill``,
  the same world ``--adapt_drill`` stamps into the committed
  ``ADAPT_r*.json``): the success arm must run inject-shift -> drift
  CRITICAL -> mixture-ramp fine-tune -> canary pass -> fan-out publish
  (0 dropped, 0 steady recompiles, params_version uniform) -> NOTA rate
  back in band -> detector re-armed, and the failure arm (chaos
  ``adapt.canary_fail``) must discard the candidate with ZERO publishes,
  honor the backoff, and latch ``adapt_exhausted`` after the retry
  budget — gated structurally against the committed artifact.
"""

import glob
import json
import os
import sys
import time

import pytest

from induction_network_on_fewrel_tpu.config import (
    ExperimentConfig,
    parse_canary_plan,
    resolve_adapt_policy,
)
from induction_network_on_fewrel_tpu.datapipe.mixture import MixtureSchedule
from induction_network_on_fewrel_tpu.obs.adapt import (
    ARMED,
    COOLDOWN,
    EXHAUSTED,
    TRIGGERED,
    VERIFYING,
    AdaptationController,
)
from induction_network_on_fewrel_tpu.obs.chaos import ChaosRegistry, install
from induction_network_on_fewrel_tpu.obs.health import CRITICAL, HealthEvent
from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOLS = os.path.join(_REPO, "tools")
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

import loadgen  # noqa: E402
import obs_report  # noqa: E402
import scenarios  # noqa: E402


# --- state-machine harness --------------------------------------------------


class _Stub:
    """Programmable train/canary/publish fns with call accounting."""

    def __init__(self, train_ok=True, canary_ok=True, publish_ok=True):
        self.train_ok = train_ok
        self.canary_ok = canary_ok
        self.publish_ok = publish_ok
        self.trained = 0
        self.canaried = 0
        self.published = []
        self.cleaned = []
        self.quarantined = []
        self.version = 0

    def train(self, tenant, attempt, step_budget, wall_budget_s):
        self.trained += 1
        if not self.train_ok:
            raise RuntimeError("stub fine-tune failure")
        return f"cand_{self.trained}"

    def canary(self, candidate):
        self.canaried += 1
        ok = self.canary_ok
        return {"passed": ok,
                "failures": [] if ok else ["in_domain: below floor"]}

    def publish(self, candidate):
        if not self.publish_ok:
            raise RuntimeError("stub publish refusal")
        self.version += 1
        self.published.append(candidate)
        return self.version

    def cleanup(self, candidate):
        self.cleaned.append(candidate)

    def quarantine(self, tenant, reason=""):
        self.quarantined.append(tenant)


def _controller(stub, **kw):
    kw.setdefault("retry_budget", 2)
    kw.setdefault("backoff_s", 10.0)
    kw.setdefault("cooldown_s", 100.0)
    kw.setdefault("verify_window_s", 50.0)
    return AdaptationController(
        stub.train, stub.canary, stub.publish,
        cleanup_fn=stub.cleanup, quarantine_fn=stub.quarantine, **kw,
    )


def test_success_loop_verifies_and_cools_down():
    """No detector wired: publish implies verified on the next tick;
    cooldown suppresses triggers until it expires, then re-arms."""
    stub = _Stub()
    c = _controller(stub)
    assert c.state_of("t") == ARMED
    assert c.trigger("t", feature="nota_rate", now=0.0)
    assert c.state_of("t") == TRIGGERED
    assert c.run_once(now=1.0) == "t"
    assert c.state_of("t") == VERIFYING
    assert stub.published == ["cand_1"]
    c.tick(now=2.0)
    assert c.state_of("t") == COOLDOWN
    info = c.loop_info("t")
    assert info["loops"] == 1 and info["attempts"] == 0
    assert not c.trigger("t", now=50.0)          # cooldown absorbs
    assert c.trigger("t", now=2.0 + 100.0 + 1)   # expired: re-arms
    actions = [r["action"] for r in c.records]
    assert actions[:4] == ["trigger", "train", "canary", "publish"]
    assert "verified" in actions


def test_canary_failure_discards_and_never_publishes():
    stub = _Stub(canary_ok=False)
    c = _controller(stub)
    c.trigger("t", now=0.0)
    c.run_once(now=1.0)
    assert stub.published == []
    assert stub.cleaned == ["cand_1"]
    assert c.state_of("t") == TRIGGERED       # backing off for retry
    assert c.loop_info("t")["attempts"] == 1
    canary = [r for r in c.records if r["action"] == "canary"]
    assert canary and canary[0]["passed"] == 0.0
    assert "first_failure" in canary[0]


def test_backoff_is_exponential_and_honored():
    """attempt N's retry waits backoff_s * 2**(N-1); an early run_once
    is a no-op."""
    stub = _Stub(canary_ok=False)
    c = _controller(stub, retry_budget=5, backoff_s=10.0)
    c.trigger("t", now=0.0)
    assert c.run_once(now=0.0) == "t"             # attempt 1 fails
    assert c.run_once(now=9.9) is None            # < 10s: honored
    assert c.run_once(now=10.1) == "t"            # attempt 2 fails
    assert c.run_once(now=10.1 + 19.9) is None    # < 20s after fail 2
    assert c.loop_info("t")["not_before"] == pytest.approx(10.1 + 20.0)
    assert c.run_once(now=10.1 + 20.1) == "t"     # attempt 3
    assert stub.trained == 3


def test_retry_budget_exhausts_quarantines_and_latches_once():
    stub = _Stub(train_ok=False)
    c = _controller(stub, retry_budget=2, backoff_s=1.0)
    c.trigger("t", now=0.0)
    c.run_once(now=0.0)
    assert c.state_of("t") == TRIGGERED
    c.run_once(now=5.0)
    assert c.state_of("t") == EXHAUSTED
    assert stub.quarantined == ["t"]
    events = [e for e in c.events if e.event == "adapt_exhausted"]
    assert len(events) == 1 and events[0].data["tenant"] == "t"
    # Permanent: triggers absorbed, nothing ever runs again.
    assert not c.trigger("t", now=100.0)
    assert c.run_once(now=100.0) is None
    assert stub.trained == 2
    # Operator escape hatch.
    c.unquarantine("t")
    assert c.state_of("t") == ARMED and c.loop_info("t")["attempts"] == 0


def test_publish_refusal_counts_failed_with_cleanup():
    stub = _Stub(publish_ok=False)
    c = _controller(stub)
    c.trigger("t", now=0.0)
    c.run_once(now=0.0)
    assert stub.published == []
    assert stub.cleaned == ["cand_1"]
    assert c.loop_info("t")["attempts"] == 1
    pub = [r for r in c.records if r["action"] == "publish"]
    assert pub and pub[0]["ok"] == 0.0 and "error" in pub[0]


def test_retrip_during_verification_rolls_back_to_prior():
    """A drift CRITICAL inside the verification window republishes the
    prior artifact and counts the attempt failed."""
    stub = _Stub()
    live = {"artifact": "base"}
    orig = stub.publish

    def publish(candidate):
        v = orig(candidate)
        live["artifact"] = candidate
        return v

    c = AdaptationController(
        stub.train, stub.canary, publish,
        current_fn=lambda: live["artifact"], cleanup_fn=stub.cleanup,
        retry_budget=3, backoff_s=1.0, verify_window_s=50.0,
    )
    c.trigger("t", now=0.0)
    c.run_once(now=0.0)
    assert c.state_of("t") == VERIFYING
    assert stub.published == ["cand_1"]
    assert not c.trigger("t", now=5.0)    # re-trip: flips the verdict bit
    c.tick(now=5.0)
    assert stub.published == ["cand_1", "base"]   # prior republished
    assert stub.cleaned == ["cand_1"]
    assert c.state_of("t") == TRIGGERED
    assert c.loop_info("t")["attempts"] == 1
    rb = [r for r in c.records if r["action"] == "rollback"]
    assert rb and "re-trip" in rb[0]["reason"]


class _NeverArms:
    """Detector stub that never re-arms (verification can only expire)."""

    band_sigma, baseline_n, nota_rate_floor = 4.0, 16, 0.05
    on_event = None

    def armed(self, tenant):
        return False

    def baseline_for(self, tenant):
        return None


def test_verify_window_expiry_rolls_back():
    """With a detector wired but never re-arming, the window expiring
    un-verified is a failure, not a silent success."""
    stub = _Stub()
    c = AdaptationController(
        stub.train, stub.canary, stub.publish, drift=_NeverArms(),
        cleanup_fn=stub.cleanup, retry_budget=3, backoff_s=1.0,
        verify_window_s=50.0,
    )
    c.trigger("t", now=0.0)
    c.run_once(now=0.0)
    c.tick(now=49.0)
    assert c.state_of("t") == VERIFYING   # window still open
    c.tick(now=51.0)
    assert c.state_of("t") == TRIGGERED
    assert c.loop_info("t")["attempts"] == 1
    rb = [r for r in c.records if r["action"] == "rollback"]
    assert rb and "expired" in rb[0]["reason"]


def test_verify_deadline_anchored_at_publish_not_trigger():
    """A wall-clock-long fine-tune must not consume the verification
    window: the deadline is anchored at PUBLISH completion (the
    attempt's real elapsed wall is added to the injected clock), so a
    slow attempt still leaves the full window for post-publish traffic
    to re-baseline the detector."""
    stub = _Stub()
    orig = stub.train

    def slow_train(*a):
        time.sleep(1.0)
        return orig(*a)

    c = AdaptationController(
        slow_train, stub.canary, stub.publish, drift=_NeverArms(),
        cleanup_fn=stub.cleanup, retry_budget=3, backoff_s=1.0,
        verify_window_s=0.5,
    )
    c.trigger("t", now=0.0)
    c.run_once(now=0.0)
    # Past trigger + window, but publish completed ~1.0 s of wall later:
    # the window is still open (the buggy anchoring would roll back).
    c.tick(now=0.6)
    assert c.state_of("t") == VERIFYING
    c.tick(now=2.5)   # now genuinely past publish + window
    assert c.state_of("t") == TRIGGERED
    rb = [r for r in c.records if r["action"] == "rollback"]
    assert rb and "expired" in rb[0]["reason"]


def test_bind_is_idempotent_and_chains_prev_subscriber():
    """Re-binding the same detector is a no-op: the guard compares the
    INSTALLED fanout closure, so a second bind can never chain the
    fanout to itself (infinite recursion on the first drift event). The
    detector's pre-existing subscriber keeps firing exactly once."""

    class _Drift:
        on_event = None

        def baseline_for(self, tenant):
            return None

    stub = _Stub()
    d = _Drift()
    seen = []
    d.on_event = seen.append
    c = _controller(stub)
    c.bind(d)
    c.bind(d)   # second bind: must be absorbed by the guard
    ev = HealthEvent(
        event="prediction_drift", severity=CRITICAL, step=1,
        message="drift", data={"tenant": "t", "feature": "nota_rate"},
    )
    d.on_event(ev)   # would RecursionError with a self-referential chain
    assert seen == [ev]                  # prior subscriber fired once
    assert c.state_of("t") == TRIGGERED  # and the controller triggered


def test_failed_rollback_publish_keeps_live_candidate():
    """If the rollback republish refuses, the fleet is still SERVING
    the candidate — it must NOT be deleted (it backs the live
    params_version and every later fine-tune reads it)."""
    stub = _Stub()
    live = {"artifact": "base"}
    calls = {"n": 0}

    def publish(candidate):
        calls["n"] += 1
        if calls["n"] == 2:     # the rollback republish refuses
            raise RuntimeError("fan-out refusal")
        stub.version += 1
        live["artifact"] = candidate
        return stub.version

    c = AdaptationController(
        stub.train, stub.canary, publish, drift=_NeverArms(),
        current_fn=lambda: live["artifact"], cleanup_fn=stub.cleanup,
        retry_budget=3, backoff_s=1.0, verify_window_s=0.5,
    )
    c.trigger("t", now=0.0)
    c.run_once(now=0.0)
    c.tick(now=10.0)    # window expired -> rollback; republish fails
    assert stub.cleaned == []                  # still live: kept
    assert live["artifact"] == "cand_1"
    assert c.loop_info("t")["attempts"] == 1
    rb = [r for r in c.records if r["action"] == "rollback"]
    assert rb and "FAILED" in rb[0]["reason"]


def test_raising_telemetry_does_not_wedge_tenant():
    """A raising jsonl write between the guarded stages must not strand
    the tenant in a state neither run_once nor tick can schedule: the
    attempt counts failed (state repaired BEFORE telemetry), the error
    surfaces, and the retry works once the logger heals."""

    class _BadLogger:
        def __init__(self):
            self.fail = True

        def log(self, step, **kw):
            if (self.fail and kw.get("kind") == "adapt"
                    and kw.get("action") == "train"):
                self.fail = False
                raise OSError("disk full")

    stub = _Stub()
    c = AdaptationController(
        stub.train, stub.canary, stub.publish, cleanup_fn=stub.cleanup,
        retry_budget=3, backoff_s=1.0, verify_window_s=50.0,
        logger=_BadLogger(),
    )
    c.trigger("t", now=0.0)
    with pytest.raises(OSError):
        c.run_once(now=0.0)
    assert c.state_of("t") == TRIGGERED        # schedulable, not wedged
    assert c.loop_info("t")["attempts"] == 1
    assert c.run_once(now=5.0) == "t"          # retry past the backoff
    assert c.state_of("t") == VERIFYING


def test_one_finetune_at_a_time_fleetwide():
    """Two triggered tenants: one run_once serves one tenant; the other
    waits its turn (the fine-tune owns the device)."""
    stub = _Stub()
    c = _controller(stub)
    c.trigger("a", now=0.0)
    c.trigger("b", now=0.0)
    assert c.run_once(now=0.0) == "a"
    assert c.state_of("b") == TRIGGERED
    assert c.run_once(now=0.0) == "b"
    assert stub.trained == 2


def test_chaos_train_raise_counts_failed_attempt():
    stub = _Stub()
    c = _controller(stub, retry_budget=2, backoff_s=1.0)
    install(ChaosRegistry.parse("adapt.train_raise@0:t"))
    try:
        c.trigger("t", now=0.0)
        c.run_once(now=0.0)
    finally:
        install(None)
    assert stub.trained == 0              # never reached the real fn
    assert c.loop_info("t")["attempts"] == 1
    train = [r for r in c.records if r["action"] == "train"]
    assert train and train[0]["ok"] == 0.0


# --- knob resolution / canary math ------------------------------------------


def test_parse_canary_plan():
    assert parse_canary_plan("off") == {}
    assert parse_canary_plan("") == {}
    assert parse_canary_plan("in_domain:0.3,target:0.25") == {
        "in_domain": 0.3, "target": 0.25,
    }
    with pytest.raises(ValueError, match="must be 'leg:floor'"):
        parse_canary_plan("in_domain")
    with pytest.raises(ValueError, match="in \\[0, 1\\]"):
        parse_canary_plan("in_domain:1.5")
    with pytest.raises(ValueError, match="twice"):
        parse_canary_plan("a:0.1,a:0.2")


def test_resolve_adapt_policy_one_home():
    assert resolve_adapt_policy(ExperimentConfig()) is None   # off
    cfg = ExperimentConfig(adapt=True, adapt_retries=5,
                           adapt_canary="in_domain:0.4")
    policy = resolve_adapt_policy(cfg)
    assert policy["retry_budget"] == 5
    assert policy["canary_floors"] == {"in_domain": 0.4}
    assert policy["step_budget"] == ExperimentConfig().adapt_step_budget

    class _Args:   # argparse-namespace shape: unset knobs are None
        adapt = True
        adapt_retries = None
        adapt_backoff_s = None
        adapt_cooldown_s = None
        adapt_step_budget = 7
        adapt_wall_s = None
        adapt_verify_s = None
        adapt_canary = None

    # Unset CLI knobs fall back to the checkpoint's stamped policy.
    merged = resolve_adapt_policy(_Args(), base=cfg)
    assert merged["retry_budget"] == 5        # from the stamped config
    assert merged["step_budget"] == 7         # CLI override wins
    with pytest.raises(ValueError, match="adapt_retries"):
        resolve_adapt_policy(ExperimentConfig(adapt=True, adapt_retries=0))
    with pytest.raises(ValueError, match="adapt_step_budget"):
        resolve_adapt_policy(
            ExperimentConfig(adapt=True, adapt_step_budget=0)
        )


def test_canary_verdict_math():
    floors = {"in_domain": 0.6, "target": 0.5}
    ok = scenarios.canary_verdict(
        {"in_domain": {"accuracy": 0.7}, "target": {"accuracy": 0.5}},
        floors,
    )
    assert ok["passed"] and ok["failures"] == []
    bad = scenarios.canary_verdict(
        {"in_domain": {"accuracy": 0.59}, "target": {"accuracy": 0.9}},
        floors,
    )
    assert not bad["passed"]
    assert "in_domain" in bad["failures"][0]
    # A floor with no evaluated leg FAILS — the gate never silently
    # skips a bar.
    missing = scenarios.canary_verdict(
        {"in_domain": {"accuracy": 0.9}}, floors,
    )
    assert not missing["passed"]
    assert any("no evaluated leg" in f for f in missing["failures"])
    # Extra legs without floors are recorded, not judged.
    extra = scenarios.canary_verdict(
        {"in_domain": {"accuracy": 0.9}, "adversarial": {"accuracy": 0.1}},
        {"in_domain": 0.6},
    )
    assert extra["passed"] and "ok" not in extra["legs"]["adversarial"]


def test_floors_from_headline_applies_tier1_band():
    head = {"in_domain_accuracy": 0.9, "cross_domain_accuracy": 0.4,
            "da_mixture_accuracy": 0.8}
    floors = scenarios.floors_from_headline(head)
    tol = scenarios.TIER1_BAND["accuracy_abs"]
    assert floors["in_domain_accuracy"] == pytest.approx(0.9 - tol)
    assert set(floors) == {"in_domain_accuracy", "cross_domain_accuracy",
                           "da_mixture_accuracy"}


def test_mixture_ramp_spelling():
    sched = MixtureSchedule.ramp(start_weight=0.2, parity_at=100)
    assert sched.names == ("src", "tgt")
    w0 = dict(zip(sched.names, sched.weights_at(0)))
    w_mid = dict(zip(sched.names, sched.weights_at(50)))
    w_end = dict(zip(sched.names, sched.weights_at(100)))
    assert w0["src"] == 1.0 and w0["tgt"] == pytest.approx(0.2)
    assert 0.2 < w_mid["tgt"] < 1.0
    assert w_end["tgt"] == pytest.approx(1.0)
    with pytest.raises(ValueError, match="parity_at"):
        MixtureSchedule.ramp(parity_at=0)


# --- the miniature in-process drill (the ISSUE 14 acceptance gate) ----------


def _latest_adapt_artifact() -> dict:
    paths = sorted(glob.glob(os.path.join(_REPO, "ADAPT_r*.json")))
    assert paths, "no ADAPT_r*.json artifact in the repo root"
    with open(paths[-1]) as f:
        return json.load(f)


@pytest.mark.slow
def test_adapt_drill_both_arms(tmp_path):
    """The committed drill replayed in-process: every structural flag on
    both arms must hold (wall times excepted — sandbox-unstable), the
    zero-bands must be exactly zero, and the emitted kind="adapt"
    telemetry must pass obs_report's schema gate and render the
    adaptation section with the time-to-recover headline."""
    committed = _latest_adapt_artifact()
    assert committed["passed"], "committed ADAPT artifact is red"

    logger = MetricsLogger(tmp_path, quiet=True)
    try:
        drill = loadgen.adapt_tier1_drill(
            seed=committed["seed"], logger=logger
        )
    finally:
        logger.close()
    assert drill["passed"], (
        "adapt drill red: success="
        f"{drill['success']} failure={drill['canary_failure']}"
    )

    s, f = drill["success"], drill["canary_failure"]
    # Success arm: inject shift -> trip -> fine-tune -> canary pass ->
    # fan-out publish -> back in band -> re-armed.
    assert s["baseline_armed"] and s["tripped"]
    assert s["canary_passed"] and s["published"]
    assert s["versions_uniform"]
    assert s["dropped_during_publish"] == 0
    assert s["steady_recompiles"] == 0
    assert s["inflight_at_publish"] > 0       # the zero-drop proof rode
    assert s["rearmed"] and s["verified"]     # inside the publish
    assert s["nota_shifted"] >= 0.5           # the collapse was real
    assert s["loops"] == 1
    # Failure arm: discarded, zero publishes, backoff honored,
    # exhausted + quarantined after the budget.
    assert f["tripped"] and f["attempt1_failed"]
    assert f["backoff_honored"]
    assert f["exhausted"] and f["exhausted_criticals"] == 1
    assert f["quarantined"] and f["retrigger_absorbed"]
    assert f["candidates_cleaned"]
    assert f["unexpected_publishes"] == 0
    assert f["canary_fail_records"] == f["retry_budget"]
    # The committed artifact's structural view must match the replay
    # (the scenarios-artifact discipline: re-emitting via --adapt_drill
    # is the one sanctioned way to move it).
    assert committed["zero_bands"] == {
        "dropped_during_publish": s["dropped_during_publish"],
        "steady_recompiles": s["steady_recompiles"],
        "unexpected_publishes": f["unexpected_publishes"],
    }
    assert committed["canary_failure"]["retry_budget"] == f["retry_budget"]

    # Telemetry gate: schema-clean, adapt section renders with the
    # loop-outcome table + recover headline.
    n, errors = obs_report.check_schema(tmp_path / "metrics.jsonl")
    assert errors == [] and n > 0
    recs = obs_report.load_records(tmp_path / "metrics.jsonl")
    adapt = obs_report.adapt_summary(recs)
    assert adapt is not None
    assert adapt["verified_loops"] >= 1
    assert adapt["time_to_recover_s"] is not None
    row = adapt["loops"]["tenant0"]
    assert row["verified"] >= 1 and row["exhausted"] == 1
    assert row["canary_fail"] == f["retry_budget"]
