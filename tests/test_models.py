"""Model-level tests: shapes, routing properties, NOTA head, determinism."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    GloveTokenizer,
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.models.build import batch_to_model_inputs
from induction_network_on_fewrel_tpu.models.induction import Induction, RelationNTN
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler

L = 16
BASE = ExperimentConfig(
    n=5, k=2, q=3, batch_size=2, max_length=L, vocab_size=302, compute_dtype="float32"
)


@pytest.fixture(scope="module")
def episode():
    vocab = make_synthetic_glove(vocab_size=300)
    ds = make_synthetic_fewrel(num_relations=8, instances_per_relation=10, vocab_size=300)
    tok = GloveTokenizer(vocab, max_length=L)
    s = EpisodeSampler(ds, tok, n=5, k=2, q=3, batch_size=2, seed=0)
    return vocab, batch_to_model_inputs(s.sample_batch())


@pytest.mark.parametrize("encoder", ["cnn", "bilstm"])
def test_forward_shapes(episode, encoder):
    vocab, (sup, qry, label) = episode
    model = build_model(BASE.replace(encoder=encoder), glove_init=vocab.vectors)
    params = model.init(jax.random.key(0), sup, qry)
    logits = model.apply(params, sup, qry)
    assert logits.shape == (2, 15, 5)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_forward_deterministic(episode):
    vocab, (sup, qry, label) = episode
    model = build_model(BASE.replace(encoder="cnn"), glove_init=vocab.vectors)
    params = model.init(jax.random.key(0), sup, qry)
    l1 = model.apply(params, sup, qry)
    l2 = model.apply(params, sup, qry)
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))


def test_induction_class_vectors_squashed():
    ind = Induction(induction_dim=32, routing_iters=3)
    support = jax.random.normal(jax.random.key(0), (2, 5, 4, 64))
    params = ind.init(jax.random.key(1), support)
    c = ind.apply(params, support)
    assert c.shape == (2, 5, 32)
    norms = jnp.linalg.norm(c, axis=-1)
    assert (norms < 1.0).all()


def test_induction_permutation_invariant():
    """Class vectors must not depend on the order of the K support shots."""
    ind = Induction(induction_dim=32, routing_iters=3)
    support = jax.random.normal(jax.random.key(0), (1, 3, 4, 64))
    params = ind.init(jax.random.key(1), support)
    c1 = ind.apply(params, support)
    c2 = ind.apply(params, support[:, :, ::-1, :])
    np.testing.assert_allclose(np.asarray(c1), np.asarray(c2), atol=1e-5)


def test_ntn_shapes():
    ntn = RelationNTN(slices=7)
    c = jax.random.normal(jax.random.key(0), (2, 5, 32))
    q = jax.random.normal(jax.random.key(1), (2, 11, 32))
    params = ntn.init(jax.random.key(2), c, q)
    out = ntn.apply(params, c, q)
    assert out.shape == (2, 11, 5)


def test_nota_head():
    vocab = make_synthetic_glove(vocab_size=300)
    ds = make_synthetic_fewrel(num_relations=8, instances_per_relation=10, vocab_size=300)
    tok = GloveTokenizer(vocab, max_length=L)
    s = EpisodeSampler(ds, tok, n=5, k=2, q=3, batch_size=2, na_rate=1, seed=0)
    sup, qry, label = batch_to_model_inputs(s.sample_batch())
    cfg = BASE.replace(encoder="cnn", na_rate=1)
    model = build_model(cfg, glove_init=vocab.vectors)
    params = model.init(jax.random.key(0), sup, qry)
    logits = model.apply(params, sup, qry)
    assert logits.shape == (2, cfg.total_q, 6)  # N+1 classes
    assert int(label.max()) == 5


def test_bf16_compute_path(episode):
    vocab, (sup, qry, label) = episode
    model = build_model(
        BASE.replace(encoder="cnn", compute_dtype="bfloat16"), glove_init=vocab.vectors
    )
    params = model.init(jax.random.key(0), sup, qry)
    logits = model.apply(params, sup, qry)
    assert logits.dtype == jnp.float32  # logits promoted for the loss
    assert np.isfinite(np.asarray(logits)).all()
