"""Native C++ sampler: parity with the Python sampler's episode semantics,
determinism under threading, and prefetch-pipeline stream equality.

The Python sampler (sampling/episodes.py) is the executable specification;
these tests hold the native implementation to the same contract (SURVEY.md
§2.1 "Episodic sampler"). RNG streams differ between the two (numpy
Generator vs xoshiro), so parity is on SEMANTICS (composition, labeling,
disjointness), not bitwise batches.
"""

import shutil

import numpy as np
import pytest

from induction_network_on_fewrel_tpu.data import (
    GloveTokenizer,
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.native import (
    NativeEpisodeSampler,
    make_sampler,
)
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler

# Skip ONLY when no compiler exists at all (e.g. a stripped runtime image).
# With g++ present, a broken native build must FAIL the tests, not skip them
# — load_native_lib() raising inside the tests surfaces the compile error.
# (shutil.which is cheap, so collection doesn't trigger a build.)
pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None, reason="no C++ toolchain on PATH"
)

N, K, Q, L, B = 5, 2, 3, 16, 2
R = 10  # relations in the synthetic corpus


@pytest.fixture(scope="module")
def corpus():
    vocab = make_synthetic_glove(vocab_size=300)
    ds = make_synthetic_fewrel(
        num_relations=R, instances_per_relation=20, vocab_size=300
    )
    tok = GloveTokenizer(vocab, max_length=L)
    return ds, tok


@pytest.fixture(scope="module")
def row_to_relation(corpus):
    """Map each tokenized sentence (as bytes) -> its relation index.

    Synthetic sentences are distinct with overwhelming probability, so this
    lets tests verify that a sampled row really came from the claimed class.
    """
    ds, tok = corpus
    out = {}
    for r, rel in enumerate(ds.rel_names):
        for inst in ds.instances[rel]:
            out[tok(inst).word.tobytes()] = r
    return out


def test_shapes_and_counts(corpus):
    ds, tok = corpus
    s = NativeEpisodeSampler(ds, tok, n=N, k=K, q=Q, batch_size=B, seed=1)
    b = s.sample_batch()
    assert b.support_word.shape == (B, N, K, L)
    assert b.support_mask.shape == (B, N, K, L)
    assert b.support_mask.dtype == np.float32
    assert b.query_word.shape == (B, N * Q, L)
    assert b.label.shape == (B, N * Q)
    for e in range(B):
        assert (np.bincount(b.label[e], minlength=N) == Q).all()
    s.close()


def test_rows_come_from_claimed_relations(corpus, row_to_relation):
    ds, tok = corpus
    s = NativeEpisodeSampler(ds, tok, n=N, k=K, q=Q, batch_size=4, seed=2)
    b = s.sample_batch()
    for e in range(4):
        # class -> source relation, via the support rows
        cls_rel = {}
        for c in range(N):
            rels = {
                row_to_relation[b.support_word[e, c, j].tobytes()]
                for j in range(K)
            }
            assert len(rels) == 1, "support rows of one class from >1 relation"
            cls_rel[c] = rels.pop()
        assert len(set(cls_rel.values())) == N, "episode relations not distinct"
        # queries labeled c must come from cls_rel[c]
        for i in range(N * Q):
            r = row_to_relation[b.query_word[e, i].tobytes()]
            assert r == cls_rel[b.label[e, i]]
    s.close()


def test_support_query_disjoint(corpus):
    ds, tok = corpus
    s = NativeEpisodeSampler(ds, tok, n=N, k=K, q=Q, batch_size=1, seed=3)
    b = s.sample_batch()
    sup = {row.tobytes() for row in b.support_word[0].reshape(-1, L)}
    qry = {row.tobytes() for row in b.query_word[0]}
    assert not sup & qry
    s.close()


def test_nota_labels_and_outside_sampling(corpus, row_to_relation):
    ds, tok = corpus
    na_rate = 2
    s = NativeEpisodeSampler(
        ds, tok, n=N, k=K, q=Q, batch_size=4, na_rate=na_rate, seed=5
    )
    b = s.sample_batch()
    tq = N * Q + na_rate * Q
    assert b.query_word.shape == (4, tq, L)
    for e in range(4):
        counts = np.bincount(b.label[e], minlength=N + 1)
        assert (counts[:N] == Q).all()
        assert counts[N] == na_rate * Q
        episode_rels = {
            row_to_relation[b.support_word[e, c, 0].tobytes()] for c in range(N)
        }
        for i in range(tq):
            if b.label[e, i] == N:  # NOTA: from OUTSIDE the episode
                assert row_to_relation[b.query_word[e, i].tobytes()] not in episode_rels
    s.close()


def test_determinism_and_seed_sensitivity(corpus):
    ds, tok = corpus
    def stream(seed, steps=3):
        s = NativeEpisodeSampler(ds, tok, n=N, k=K, q=Q, batch_size=B, seed=seed)
        out = [s.sample_batch() for _ in range(steps)]
        s.close()
        return out
    a, b = stream(7), stream(7)
    for x, y in zip(a, b):
        for f, g in zip(x, y):
            np.testing.assert_array_equal(f, g)
    c = stream(8)
    assert any((x.label != y.label).any() for x, y in zip(a, c))


@pytest.mark.parametrize("num_threads", [1, 3])
def test_prefetch_stream_equals_direct(corpus, num_threads):
    """The threaded pipeline must yield the exact direct-call sequence."""
    ds, tok = corpus
    direct = NativeEpisodeSampler(ds, tok, n=N, k=K, q=Q, batch_size=B, seed=11)
    pre = NativeEpisodeSampler(
        ds, tok, n=N, k=K, q=Q, batch_size=B, seed=11,
        prefetch=3, num_threads=num_threads,
    )
    for _ in range(10):
        bd, bp = direct.sample_batch(), pre.sample_batch()
        for f, g in zip(bd, bp):
            np.testing.assert_array_equal(f, g)
    direct.close()
    pre.close()


def test_prefetch_stress_no_deadlock(corpus):
    """Many batches through a deep pipeline with more threads than depth
    headroom — regression test for the out-of-order slot-claim deadlock."""
    ds, tok = corpus
    s = NativeEpisodeSampler(
        ds, tok, n=N, k=K, q=Q, batch_size=2, seed=13,
        prefetch=8, num_threads=4,
    )
    ref = NativeEpisodeSampler(ds, tok, n=N, k=K, q=Q, batch_size=2, seed=13)
    for i in range(2000):
        b = s.sample_batch()
        r = ref.sample_batch()
        if i % 250 == 0:  # spot-check stream equality along the way
            np.testing.assert_array_equal(b.label, r.label)
            np.testing.assert_array_equal(b.query_word, r.query_word)
    s.close()
    ref.close()


def test_factory_fallback(corpus):
    ds, tok = corpus
    s = make_sampler(ds, tok, N, K, Q, batch_size=B, backend="python")
    assert isinstance(s, EpisodeSampler)
    s2 = make_sampler(ds, tok, N, K, Q, batch_size=B, backend="auto")
    b = s2.sample_batch()
    assert b.support_word.shape == (B, N, K, L)
    with pytest.raises(ValueError):
        make_sampler(ds, tok, N, K, Q, backend="cuda")


def test_needs_enough_relations(corpus):
    ds, tok = corpus
    with pytest.raises(ValueError):
        NativeEpisodeSampler(ds, tok, n=R + 1, k=K, q=Q)
    with pytest.raises(ValueError):
        NativeEpisodeSampler(ds, tok, n=R, k=K, q=Q, na_rate=1)


# --- index-mode sampler (device-resident cache paths) ----------------------


def test_index_sampler_episode_invariants():
    """NativeIndexSampler: rows in-range and from N distinct relations,
    support/query disjoint, per-class query counts, NOTA from outside."""
    from induction_network_on_fewrel_tpu.native.sampler import NativeIndexSampler

    sizes = [7, 9, 11, 8, 10, 12, 7, 9]
    offsets = np.cumsum([0] + sizes)

    def owner(row):
        return int(np.searchsorted(offsets, row, side="right") - 1)

    s = NativeIndexSampler(sizes, n=3, k=2, q=2, batch_size=4, na_rate=1, seed=3)
    sup, qry, lab = s.sample_fused(16)
    assert sup.shape == (16, 4, 3, 2) and qry.shape == (16, 4, 3 * 2 + 2)
    assert sup.min() >= 0 and sup.max() < offsets[-1]
    assert qry.min() >= 0 and qry.max() < offsets[-1]
    for t in range(16):
        for e in range(4):
            cls_rel = {}
            for c in range(3):
                rels = {owner(r) for r in sup[t, e, c]}
                assert len(rels) == 1
                cls_rel[c] = rels.pop()
            assert len(set(cls_rel.values())) == 3
            assert len(set(sup[t, e].ravel())) == 6  # no support dup rows
            for i, row in enumerate(qry[t, e]):
                c = lab[t, e, i]
                if c == 3:  # NOTA: from OUTSIDE the episode
                    assert owner(row) not in cls_rel.values()
                else:
                    assert owner(row) == cls_rel[c]
                    assert row not in sup[t, e, c]  # disjoint from support
            counts = np.bincount(lab[t, e], minlength=4)
            assert (counts[:3] == 2).all() and counts[3] == 2
    s.close()


def test_index_sampler_determinism_and_fused_equals_sequential():
    from induction_network_on_fewrel_tpu.native.sampler import NativeIndexSampler

    sizes = [10] * 8
    a = NativeIndexSampler(sizes, n=3, k=2, q=2, batch_size=2, seed=7)
    b = NativeIndexSampler(sizes, n=3, k=2, q=2, batch_size=2, seed=7)
    sup_a, qry_a, lab_a = a.sample_fused(6)
    # One fused call == the same batches drawn one by one (sequence-seeded).
    for i in range(6):
        bb = b.sample_batch()
        np.testing.assert_array_equal(sup_a[i], bb.support_idx)
        np.testing.assert_array_equal(qry_a[i], bb.query_idx)
        np.testing.assert_array_equal(lab_a[i], bb.label)
    c = NativeIndexSampler(sizes, n=3, k=2, q=2, batch_size=2, seed=8)
    assert not np.array_equal(c.sample_fused(1)[0], sup_a[:1])
    a.close(); b.close(); c.close()


def test_index_sampler_factory():
    from induction_network_on_fewrel_tpu.native.sampler import make_index_sampler
    from induction_network_on_fewrel_tpu.train.feature_cache import (
        FeatureEpisodeSampler,
    )

    sizes = [10] * 6
    py = make_index_sampler(sizes, 3, 2, 2, batch_size=2, backend="python")
    assert isinstance(py, FeatureEpisodeSampler)
    sup, qry, lab = py.sample_fused(3)
    assert sup.shape == (3, 2, 3, 2)
    auto = make_index_sampler(sizes, 3, 2, 2, batch_size=2, backend="auto")
    assert auto.sample_batch().support_idx.shape == (2, 3, 2)
    with pytest.raises(ValueError):
        make_index_sampler(sizes, 3, 2, 2, backend="cuda")
