"""Real-file end-to-end CLI rehearsal (VERDICT r1 #8; SURVEY.md §7
"real-data runs are config-swap only").

Writes a tiny REAL-FORMAT ``glove.6B.50d.txt``-style embedding file and
FewRel-schema JSON splits to disk, then drives ``train_main`` and
``test_main`` through ``--glove``/``--train_file``/... — the full CLI file
path, not the synthetic fallback, exactly as a user with the real corpora
would run it.
"""

import json

import numpy as np
import pytest

from induction_network_on_fewrel_tpu.cli import test_main as run_test_cli  # noqa: E501
from induction_network_on_fewrel_tpu.cli import train_main as run_train_cli

DIM = 50
N_WORDS = 40


@pytest.fixture()
def corpus_files(tmp_path):
    rng = np.random.default_rng(0)
    words = [f"w{i}" for i in range(N_WORDS)] + ["alpha", "beta", "gamma"]

    glove = tmp_path / "glove.6B.50d.txt"
    with glove.open("w") as f:
        for w in words:
            vec = " ".join(f"{v:.5f}" for v in rng.normal(0, 0.3, DIM))
            f.write(f"{w} {vec}\n")

    def instance(trigger):
        # FewRel schema: tokens + h/t = [name, wikidata-ish id, [[span]]].
        toks = [words[rng.integers(N_WORDS)] for _ in range(8)]
        toks[2] = trigger          # class-separating trigger token
        toks[0], toks[5] = "alpha", "beta"
        return {
            "tokens": toks,
            "h": ["alpha", "Q1", [[0]]],
            "t": ["beta", "Q2", [[5]]],
        }

    def split(seed):
        r = np.random.default_rng(seed)
        return {
            f"P{seed}{c}": [
                instance(words[c % N_WORDS]) for _ in range(8 + int(r.integers(3)))
            ]
            for c in range(4)
        }

    train = tmp_path / "train_wiki.json"
    val = tmp_path / "val_wiki.json"
    train.write_text(json.dumps(split(1)))
    val.write_text(json.dumps(split(2)))
    return glove, train, val


@pytest.mark.slow
def test_train_and_test_from_real_files(corpus_files, tmp_path):
    glove, train, val = corpus_files
    ckpt = tmp_path / "ckpt"
    rc = run_train_cli([
        "--encoder", "cnn", "--N", "2", "--K", "2", "--Q", "2",
        "--batch_size", "2", "--max_length", "12", "--hidden_size", "16",
        "--induction_dim", "8", "--ntn_slices", "4",
        "--glove", str(glove),
        "--train_file", str(train), "--val_file", str(val),
        "--train_iter", "30", "--val_step", "15", "--val_iter", "8",
        "--save_ckpt", str(ckpt), "--device", "cpu", "--sampler", "python",
        "--dp", "1",
    ])
    assert rc == 0
    assert (ckpt / "config.json").exists()
    # The loaded vocab pins the architecture: N_WORDS + 3 extras + UNK/BLANK.
    cfg = json.loads((ckpt / "config.json").read_text())
    assert cfg["vocab_size"] == N_WORDS + 3 + 2
    assert cfg["word_dim"] == DIM

    # test.py restores the best checkpoint and evaluates the val file.
    rc = run_test_cli([
        "--N", "2", "--K", "2", "--Q", "2", "--batch_size", "2",
        "--glove", str(glove), "--test_file", str(val),
        "--load_ckpt", str(ckpt), "--test_iter", "8",
        "--device", "cpu", "--sampler", "python", "--dp", "1",
    ])
    assert rc == 0


def test_train_rejects_missing_file(corpus_files, tmp_path):
    glove, train, _ = corpus_files
    with pytest.raises(FileNotFoundError):
        run_train_cli([
            "--encoder", "cnn", "--N", "2", "--K", "2", "--Q", "2",
            "--glove", str(glove),
            "--train_file", str(tmp_path / "nope.json"),
            "--train_iter", "1", "--device", "cpu",
        ])
