"""Request-scoped tracing + per-tenant SLO burn-rate engine (ISSUE 9).

Covers: trace-context propagation across threads (fan-in links on the
batch spans), the engine's per-request segment records summing to the
measured end-to-end latency, rate-0 zero-allocation short-circuit, the
tracing-tax A/B gate (< 2% of p50 exec at the production sampling rate),
the SLO engine's multi-window burn rates (fast-window CRITICAL, once-
latched, re-armed, auto-captured diagnostics), flight-dump integrity when
the dump fires mid-execute on the continuous batcher's worker threads,
the true-reservoir bound on per-tenant latency accumulators, and the
Prometheus histogram exemplars.
"""

import json
import threading
import time

import jax
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.data.tokenizer import GloveTokenizer
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.obs import (
    CounterRegistry,
    DiagnosticsCapture,
    FlightRecorder,
    SLOEngine,
    SLOObjective,
    SpanTracker,
    TraceSampler,
    set_tracker,
)
from induction_network_on_fewrel_tpu.serving.batcher import ContinuousBatcher
from induction_network_on_fewrel_tpu.serving.buckets import zero_batch
from induction_network_on_fewrel_tpu.serving.engine import InferenceEngine
from induction_network_on_fewrel_tpu.serving.stats import (
    ServingStats,
    _Reservoir,
)
from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

import os
import sys

_TOOLS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
)
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

import obs_report  # noqa: E402

CFG = ExperimentConfig(
    model="induction", encoder="cnn", hidden_size=16,
    vocab_size=122, word_dim=8, pos_dim=2, max_length=16,
    induction_dim=8, ntn_slices=4, routing_iters=2,
    n=3, train_n=3, k=2, q=2, device="cpu",
)


@pytest.fixture(scope="module")
def world():
    vocab = make_synthetic_glove(vocab_size=CFG.vocab_size - 2,
                                 word_dim=CFG.word_dim)
    tok = GloveTokenizer(vocab, max_length=CFG.max_length)
    model = build_model(CFG, glove_init=vocab.vectors)
    params = model.init(
        jax.random.key(0),
        zero_batch(CFG.max_length, (1, CFG.n, CFG.k)),
        zero_batch(CFG.max_length, (1, 2)),
    )
    ds = make_synthetic_fewrel(
        num_relations=4, instances_per_relation=8,
        vocab_size=CFG.vocab_size - 2, seed=1,
    )
    return tok, model, params, ds


def _engine(world, **kw):
    tok, model, params, ds = world
    # Lean bucket set: every bucket is one AOT compile per engine, and
    # this file builds several engines — (1, 8) covers every drain size
    # the tests submit while keeping tier-1 wall time down.
    eng = InferenceEngine(
        model, params, CFG, tok, k=CFG.k,
        buckets=kw.pop("buckets", (1, 8)), start=False, **kw,
    )
    eng.register_dataset(ds, tenant="acme")
    eng.warmup()
    return eng


def _pool(world):
    tok, model, params, ds = world
    return [i for r in ds.rel_names for i in ds.instances[r][CFG.k:]]


def _drain(eng):
    while eng.batcher.queue_depth:
        eng.batcher.drain_once(block_s=0.01)


# --- trace context / spans -------------------------------------------------


def test_trace_context_cross_thread_propagation_and_links():
    t = SpanTracker(capacity=32, xplane_bridge=False)
    with t.trace() as ctx:
        with t.span("client/submit"):
            pass
    assert ctx.span_id != 0        # first span became the originating span

    def worker():
        with t.trace(ctx):          # adopt the carried context
            with t.span("worker/execute", links=("other-trace",)):
                pass

    th = threading.Thread(target=worker)
    th.start()
    th.join()
    spans = {s["name"]: s for s in t.snapshot()}
    sub, ex = spans["client/submit"], spans["worker/execute"]
    assert sub["trace_id"] == ex["trace_id"] == ctx.trace_id
    # Cross-thread stitch: the worker's top-level span parents to the
    # originating submit span.
    assert ex["parent_id"] == sub["span_id"]
    assert ex["links"] == ["other-trace"]
    assert sub["thread"] != ex["thread"]


def test_span_parent_ids_within_thread():
    t = SpanTracker(capacity=8, xplane_bridge=False)
    with t.span("outer"):
        with t.span("inner"):
            pass
    inner, outer = t.snapshot()
    assert inner["parent_id"] == outer["span_id"]
    assert outer.get("parent_id") is None


def test_trace_sampler_deterministic_and_rate_zero_noop():
    s = TraceSampler(0.5)
    picks = [s.maybe_trace() is not None for _ in range(6)]
    assert picks == [True, False, True, False, True, False]
    off = TraceSampler(0.0)
    assert off.stride == 0 and off._count is None   # nothing allocated
    assert off.maybe_trace() is None
    assert TraceSampler(1.0).stride == 1            # every request


# --- engine data plane -----------------------------------------------------


def test_engine_waterfall_segments_sum_and_report(tmp_path, world):
    logger = MetricsLogger(tmp_path, quiet=True)
    eng = _engine(world, logger=logger, trace_sample=1.0)
    try:
        pool = _pool(world)
        futs = [eng.submit(pool[i % len(pool)], tenant="acme")
                for i in range(6)]
        _drain(eng)
        verdicts = [f.result(timeout=10) for f in futs]
        # Every verdict of a traced request carries its trace id.
        assert all("trace_id" in v for v in verdicts)
        eng.publish_params(eng.params)   # control-plane trace record
    finally:
        eng.close()
        logger.close()

    recs = [json.loads(l)
            for l in (tmp_path / "metrics.jsonl").read_text().splitlines()]
    traces = [r for r in recs if r["kind"] == "trace" and "total_ms" in r]
    assert len(traces) == 6
    for r in traces:
        segs = r["queue_ms"] + r["pack_ms"] + r["execute_ms"] + r["respond_ms"]
        # Acceptance bar is 5%; the construction makes it rounding-exact.
        assert segs == pytest.approx(r["total_ms"], rel=0.05)
        assert segs == pytest.approx(r["total_ms"], abs=0.01)
        assert r["tenant"] == "acme" and r["scheduler"] == "continuous"
    control = [r for r in recs if r["kind"] == "trace" and r.get("op")]
    assert control and control[-1]["op"] == "publish"

    # The execute span linked the sampled trace ids (fan-in).
    from induction_network_on_fewrel_tpu.obs.spans import get_tracker

    ex = [s for s in get_tracker().snapshot()
          if s["name"] == "serve/execute" and s.get("links")]
    assert ex, "no serve/execute span carries fan-in links"
    linked = {tid for s in ex for tid in s["links"]}
    assert {t["trace_id"] for t in traces} <= linked

    # obs_report: schema-clean, waterfall rendered, sums verified.
    assert obs_report.main([str(tmp_path), "--check"]) == 0
    recs2 = obs_report.load_records(tmp_path / "metrics.jsonl")
    summary = obs_report.trace_summary(recs2)
    assert summary["sampled_requests"] == 6
    assert summary["segments_sum_ok_frac"] == 1.0
    assert any("waterfall" in k for k in summary)
    assert any("queue" in line for line in summary["waterfall"])


def test_engine_rate_zero_short_circuits(tmp_path, world):
    logger = MetricsLogger(tmp_path, quiet=True)
    eng = _engine(world, logger=logger, trace_sample=0.0)
    try:
        pool = _pool(world)
        futs = [eng.submit(pool[i % len(pool)], tenant="acme")
                for i in range(4)]
        _drain(eng)
        verdicts = [f.result(timeout=10) for f in futs]
        assert all("trace_id" not in v for v in verdicts)
        assert eng._tracer.stride == 0 and eng._tracer._count is None
        assert eng.stats.trace_summary() is None
    finally:
        eng.close()
        logger.close()
    recs = [json.loads(l)
            for l in (tmp_path / "metrics.jsonl").read_text().splitlines()]
    assert not [r for r in recs if r["kind"] == "trace"]


@pytest.mark.slow
def test_tracing_tax_under_2pct_of_p50_exec(tmp_path):
    """The tier-1 overhead gate (ISSUE 9 satellite): the SAME engine and
    programs driven with tracing off vs on; the per-batch wall-time
    delta, stated against the measured p50 exec, must stay under 2%.

    Sampling density: rate 1/20 on 32-row launches ≈ 1.6 sampled
    requests per launch — the SAME per-launch density the flagship
    serves at the production rate 0.1 with its 16-row buckets. The
    measured per-sampled-request cost is ~20-25 µs (ctx + submit span
    ~8 µs, segment record + locks ~15 µs — microbenched), constant in
    batch shape; this toy engine's CPU exec (~3 ms) is already 3-5x
    smaller than the flagship batch's, so the gate is strictly harsher
    than production on the denominator while matching it on the
    numerator.

    Robustness choices: a representative-size engine (the tiny 3-way
    fixture above executes in ~0.2 ms, where 2% is 4 µs — below what ANY
    per-record bookkeeping can meet; the flagship serving batch executes
    in 5-20 ms), exec p50 measured from the engine's own serve/execute
    spans, the cyclic GC paused (a triggered gen-collection costs ∝
    every live object in the process, not this path), and the statistic
    is the MEDIAN OF TRIAD DELTAS (off, on, off — the A/B delta is on
    minus the mean of its bracketing offs): a min- or mean-based A/B is
    swung tens of µs by one lucky outlier drive in either arm, while
    the bracketed median is immune to outliers and drift.

    Validity check: each measurement also computes the A/A noise floor
    (median |off2 - off1| within the same triads). This sandbox shows
    run-long contamination modes (neighbor bursts) where wall-clock A/B
    deltas of 100-300 µs appear with NO code-path difference — when the
    floor says the measurement cannot resolve the 2% bar, the gate
    falls back to a contention-resistant bound: min-of-tight-loop cost
    of the actual per-trace operations (ctx + submit span, segment
    record + retention) times the sampled-per-launch density, which
    must fit in 2% of p50 exec. The fallback counts exactly the work
    tracing adds, so it can't wave through a real regression."""
    cfg = ExperimentConfig(
        model="induction", encoder="cnn", hidden_size=128,
        vocab_size=302, word_dim=16, pos_dim=4, max_length=48,
        induction_dim=64, ntn_slices=8,
        n=5, train_n=5, k=5, q=2, device="cpu",
    )
    vocab = make_synthetic_glove(vocab_size=cfg.vocab_size - 2,
                                 word_dim=cfg.word_dim)
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    model = build_model(cfg, glove_init=vocab.vectors)
    params = model.init(
        jax.random.key(0),
        zero_batch(cfg.max_length, (1, cfg.n, cfg.k)),
        zero_batch(cfg.max_length, (1, 2)),
    )
    ds = make_synthetic_fewrel(
        num_relations=cfg.n, instances_per_relation=cfg.k + 6,
        vocab_size=cfg.vocab_size - 2, seed=3,
    )
    logger = MetricsLogger(tmp_path, quiet=True)
    # One bucket = one AOT compile: every drive submits exactly 32, so
    # the smaller buckets would only buy compile time the gate pays for.
    eng = InferenceEngine(
        model, params, cfg, tok, k=cfg.k, buckets=(32,),
        start=False, logger=logger, trace_sample=0.0,
    )
    try:
        eng.register_dataset(ds, tenant="acme")
        eng.warmup()
        pool = [i for r in ds.rel_names for i in ds.instances[r][cfg.k:]]
        off = TraceSampler(0.0)
        on = TraceSampler(0.05)   # flagship-shaped density; see docstring

        def drive_once():
            futs = [eng.submit(pool[i % len(pool)], tenant="acme")
                    for i in range(32)]
            t0 = time.perf_counter()
            _drain(eng)
            dt = time.perf_counter() - t0
            for f in futs:
                f.result(timeout=10)
            return dt

        # Warm both paths (compiles, file handle, allocator).
        for tracer in (off, on):
            eng._tracer = tracer
            drive_once()
        import gc

        def p50_exec_s() -> float:
            from induction_network_on_fewrel_tpu.obs.spans import (
                get_tracker,
            )

            xs = sorted(
                s["dur_s"] for s in get_tracker().snapshot()
                if s["name"] == "serve/execute"
                and s["attrs"].get("bucket") == 32
            )
            assert xs, "no serve/execute spans recorded"
            return xs[len(xs) // 2]

        def measure() -> tuple[float, float]:
            """(A/B tax seconds, A/A noise floor seconds) over 12
            off/on/off triads."""
            ab, aa = [], []
            gc.collect()
            gc.disable()
            try:
                for _ in range(12):
                    eng._tracer = off
                    o1 = drive_once()
                    eng._tracer = on
                    t1 = drive_once()
                    eng._tracer = off
                    o2 = drive_once()
                    ab.append(t1 - (o1 + o2) / 2)
                    aa.append(abs(o2 - o1))
            finally:
                gc.enable()
            ab.sort()
            aa.sort()
            return max(0.0, ab[len(ab) // 2]), aa[len(aa) // 2]

        bar_frac = 0.02
        verdict = None
        for _ in range(3):
            tax, floor = measure()
            p50 = p50_exec_s()
            print(f"tracing tax {tax * 1e6:.1f}us (A/A floor "
                  f"{floor * 1e6:.1f}us) on p50 exec {p50 * 1e3:.3f}ms "
                  f"-> {tax / p50:.4f}")
            if floor > 0.5 * bar_frac * p50:
                continue            # can't resolve the bar; re-measure
            verdict = tax / p50
            if verdict < bar_frac:
                break
        if verdict is not None:
            assert verdict < bar_frac, (
                f"tracing tax {verdict:.2%} of p50 exec (bar: 2%)"
            )
            return
        # Contended fallback: bound the tax from the per-trace operations
        # themselves (min-of-tight-loop is immune to neighbor bursts —
        # contention can only inflate iterations, and min discards them).
        from induction_network_on_fewrel_tpu.obs.spans import get_tracker

        tracker = get_tracker()

        def traced_ops():
            ctx = TraceSampler(1.0).maybe_trace()
            with tracker.trace(ctx):
                with tracker.span("serve/submit", xplane=False, tenant="t"):
                    pass
            eng.stats.record_trace({
                "trace_id": ctx.trace_id, "tenant": "t",
                "scheduler": "continuous", "bucket": 32.0, "rows": 32.0,
                "queue_ms": 1.0, "pack_ms": 0.1, "execute_ms": 3.0,
                "respond_ms": 0.1, "total_ms": 4.2,
            })

        reps, loops = 30, 50
        best = min(
            _timed_loop(traced_ops, loops) / loops for _ in range(reps)
        )
        density = 32 * 0.05     # sampled requests per launch at the rate
        p50 = p50_exec_s()
        bound = density * best
        print(f"contended fallback: {best * 1e6:.2f}us/trace x "
              f"{density:.1f}/launch = {bound * 1e6:.1f}us vs bar "
              f"{bar_frac * p50 * 1e6:.1f}us")
        assert bound < bar_frac * p50, (
            f"per-trace cost bound {bound * 1e6:.1f}us exceeds 2% of "
            f"p50 exec {p50 * 1e3:.3f}ms"
        )
    finally:
        eng.close()
        logger.close()


def _timed_loop(fn, n: int) -> float:
    t0 = time.perf_counter()
    for _ in range(n):
        fn()
    return time.perf_counter() - t0


# --- SLO burn-rate engine --------------------------------------------------


def _fill(slo, tenant, n, bad, t):
    for i in range(n):
        slo.record(tenant, latency_ms=500.0 if i < bad else 1.0, now=t + i / 100)


def test_slo_fast_window_trips_once_latched_and_rearms(tmp_path):
    rec = FlightRecorder(out_dir=tmp_path)
    slo = SLOEngine(
        SLOObjective(availability=0.99, latency_ms=50.0),
        recorder=rec,
        capture=DiagnosticsCapture(tmp_path, recorder=rec, profile=False),
    )
    t = 1000.0
    _fill(slo, "acme", n=40, bad=20, t=t)       # 50% bad >> 14.4x budget
    evs = slo.evaluate(now=t + 1)
    assert [e.event for e in evs] == ["slo_fast_burn", "slo_slow_burn"]
    assert evs[0].severity == "critical" and slo.tripped
    assert evs[0].data["tenant"] == "acme"
    # Once-latched: still burning, no new events.
    assert slo.evaluate(now=t + 2) == []
    # Diagnostics on disk: flight dump + host-span snapshot (profiler
    # disabled here — the CPU-honest fallback IS the guarantee).
    cap = slo.captured["slo_burn:acme:fast"]
    assert cap["flight_dump"] and os.path.exists(cap["flight_dump"])
    assert cap["span_snapshot"] and os.path.exists(cap["span_snapshot"])
    assert cap["profile_state"] == "disabled"
    # Recovery: a clean fast window re-arms; a second incident re-trips.
    for i in range(400):
        slo.record("acme", latency_ms=1.0, now=t + 400 + i)
    assert slo.evaluate(now=t + 800) == []
    assert slo.burn_rates("acme", now=t + 800)["burn_fast"] == 0.0
    # Second incident far enough out that the recovery traffic has left
    # the fast window: it must re-trip (the latch re-armed).
    _fill(slo, "acme", n=40, bad=20, t=t + 1200)
    evs = slo.evaluate(now=t + 1201)
    assert "slo_fast_burn" in [e.event for e in evs]


def test_slo_evaluate_cell_count_independent():
    """Round-10 scale follow-up PAID: per-tenant running-sum windows.
    A month-long slow window at 1 s buckets is 2.6M window cells — the
    old ring design allocated that many cells PER TENANT up front and
    summed O(window cells) per evaluate(). The running-sum design must
    hold only TOUCHED bucket cells and answer burn_rates from maintained
    totals, so this config is instant and tiny instead of gigabytes and
    seconds."""
    slo = SLOEngine(
        SLOObjective(availability=0.99, latency_ms=10.0),
        fast_window_s=300.0, slow_window_s=30 * 86400.0, bucket_s=1.0,
    )
    t = 0.0
    for tenant in ("a", "b", "c"):
        for i in range(100):
            slo.record(tenant, latency_ms=99.0, now=t + i * 0.5)  # all bad
    t0 = time.perf_counter()
    evs = slo.evaluate(now=t + 60)
    dt = time.perf_counter() - t0
    assert {e.data["tenant"] for e in evs
            if e.event == "slo_fast_burn"} == {"a", "b", "c"}
    # Structural pin (the real gate — not timing): storage per tenant is
    # bounded by TOUCHED buckets (100 records over 50 distinct seconds),
    # never by the 2.6M-cell window capacity.
    for tenant in ("a", "b", "c"):
        wins = slo._windows[tenant]
        assert len(wins["slow"].cells) <= 51
        assert len(wins["fast"].cells) <= 51
    # And the sweep is not proportional to window cells (generous bound
    # for sandbox noise; the old design took seconds here).
    assert dt < 1.0, f"evaluate() took {dt:.3f}s on a 2.6M-cell window"
    # Running sums stay honest as cells expire: far in the future the
    # fast window is empty, the month-long slow window still holds all.
    rates = slo.burn_rates("a", now=t + 400)
    assert rates["total_fast"] == 0 and rates["total_slow"] == 100
    # Reads are windows on BOTH sides and read-only: a query at an
    # EARLIER moment (buckets 0..10 of the 0..49 recorded) excludes the
    # later traffic instead of counting the whole history, and neither
    # that read nor the far-future one above destroyed any state.
    past = slo.burn_rates("a", now=t + 10)
    assert past["total_fast"] == 22 and past["total_slow"] == 22
    assert slo.burn_rates("a", now=t + 60)["total_slow"] == 100


def test_slo_min_count_guards_thin_windows():
    slo = SLOEngine(SLOObjective(availability=0.99, latency_ms=10.0))
    t = 0.0
    for i in range(SLOEngine.MIN_COUNT - 1):
        slo.record("t", latency_ms=99.0, now=t + i)
    assert slo.evaluate(now=t + 5) == []        # too few to judge
    slo.record("t", latency_ms=99.0, now=t + 9)
    assert [e.event for e in slo.evaluate(now=t + 9)] == [
        "slo_fast_burn", "slo_slow_burn"
    ]


def test_slo_sweep_trip_equivalence():
    """Round-10 regression pin for the single-lock sweep: evaluate()
    (one lock acquisition, one bucket index, _rates_locked per tenant)
    must trip EXACTLY the (tenant, window) pairs the public per-tenant
    burn_rates() read predicts against the engine thresholds, on the
    burn-drill tenant mix — clean, thin (< MIN_COUNT, burning hard),
    fast+slow burning, and slow-only burning."""
    slo = SLOEngine(SLOObjective(availability=0.99, latency_ms=10.0))
    t = 100.0
    _fill(slo, "clean", n=40, bad=0, t=t)
    _fill(slo, "thin", n=SLOEngine.MIN_COUNT - 1,
          bad=SLOEngine.MIN_COUNT - 1, t=t)
    _fill(slo, "hot", n=40, bad=20, t=t)    # 50% bad: fast AND slow trip
    _fill(slo, "warm", n=40, bad=4, t=t)    # 10% bad: slow-only trip
    now = t + 1
    expected = set()
    for tenant in slo.tenants():
        rates = slo.burn_rates(tenant, now=now)
        for label, threshold in (("fast", slo.fast_burn),
                                 ("slow", slo.slow_burn)):
            if (rates[f"burn_{label}"] >= threshold
                    and rates[f"total_{label}"] >= slo.MIN_COUNT):
                expected.add((tenant, f"slo_{label}_burn"))
    assert expected == {("hot", "slo_fast_burn"), ("hot", "slo_slow_burn"),
                        ("warm", "slo_slow_burn")}
    evs = slo.evaluate(now=now)
    assert {(e.data["tenant"], e.event) for e in evs} == expected
    # Latch equivalence: a second sweep of the same state emits nothing.
    assert slo.evaluate(now=now + 1) == []


def test_slo_per_tenant_objectives_and_isolation():
    slo = SLOEngine(SLOObjective(availability=0.99, latency_ms=100.0))
    slo.set_objective("strict", SLOObjective(availability=0.999,
                                             latency_ms=5.0))
    t = 0.0
    for i in range(20):
        slo.record("strict", latency_ms=50.0, now=t + i / 10)  # bad for strict
        slo.record("lax", latency_ms=50.0, now=t + i / 10)     # fine for lax
    evs = slo.evaluate(now=t + 3)
    tenants = {e.data["tenant"] for e in evs}
    assert tenants == {"strict"}


def test_serving_stats_feed_slo_outcomes():
    slo = SLOEngine(SLOObjective(availability=0.99, latency_ms=10.0))
    stats = ServingStats(slo=slo)
    now0 = time.monotonic()
    stats.record_done(0.002, tenant="a")                 # good (2 ms)
    stats.record_done(0.500, tenant="a")                 # bad (latency)
    stats.record_shed("a")                               # bad (error)
    stats.record_rejected(tenant="a")                    # bad (error)
    stats.record_deadline_miss(tenant="a")               # bad (error)
    rates = slo.burn_rates("a", now=now0 + 1)
    assert rates["total_fast"] == 5 and rates["bad_fast"] == 4


def test_engine_slo_trips_on_fully_shed_tenant(tmp_path, world):
    """Review regression: the submit-path SLO tick lives in a finally —
    a FULLY-REJECTED tenant (batcher saturated, zero batches executing,
    so the emit-path tick never fires) must still get its windows
    evaluated and trip from the rejection outcomes alone."""
    from induction_network_on_fewrel_tpu.serving.batcher import Saturated

    slo = SLOEngine(
        SLOObjective(availability=0.99),
        fast_window_s=0.8, slow_window_s=8.0,   # bucket ~0.067 s
        capture=DiagnosticsCapture(tmp_path, recorder=None, profile=False),
    )
    # start=False and never drained: the queue (bound 2) fills, then
    # every submit rejects.
    eng = _engine(world, slo=slo, max_queue_depth=2)
    try:
        pool = _pool(world)
        rejected = 0
        for i in range(40):
            try:
                eng.submit(pool[i % len(pool)], tenant="acme")
            except Saturated:
                rejected += 1
            if i % 10 == 9:
                time.sleep(0.08)   # cross a bucket so the tick evaluates
        assert rejected >= SLOEngine.MIN_COUNT
        assert slo.tripped, "fully-shed tenant never evaluated"
        assert "slo_burn:acme:fast" in slo.captured
    finally:
        eng.close()


def test_engine_slo_trip_captures_and_reports(tmp_path, world):
    logger = MetricsLogger(tmp_path, quiet=True)
    rec = FlightRecorder(out_dir=tmp_path)
    logger.add_hook(rec.record_metric)
    slo = SLOEngine(
        # latency_ms=0.0 would read as falsy-None ambiguity; 1e-6 makes
        # every real request "slow" — the drill-in-miniature.
        SLOObjective(availability=0.99, latency_ms=1e-6),
        fast_window_s=30.0, slow_window_s=300.0,
        logger=logger, recorder=rec,
        capture=DiagnosticsCapture(tmp_path, recorder=rec, profile=False),
    )
    eng = _engine(world, logger=logger, slo=slo, trace_sample=1.0)
    try:
        pool = _pool(world)
        futs = [eng.submit(pool[i % len(pool)], tenant="acme")
                for i in range(12)]
        _drain(eng)
        for f in futs:
            f.result(timeout=10)
        eng.emit_stats()                    # full evaluate sweep
        assert slo.tripped
        latch = "slo_burn:acme:fast"
        assert latch in slo.captured
        assert os.path.exists(slo.captured[latch]["span_snapshot"])
        assert (tmp_path / "flight_recorder.json").exists()
    finally:
        eng.close()
        logger.close()
    recs = [json.loads(l)
            for l in (tmp_path / "metrics.jsonl").read_text().splitlines()]
    slo_events = [r for r in recs if r["kind"] == "health"
                  and str(r.get("event", "")).startswith("slo_")]
    assert any(r["event"] == "slo_fast_burn" for r in slo_events)
    assert obs_report.main([str(tmp_path), "--check"]) == 0
    summary = obs_report.slo_summary(obs_report.load_records(
        tmp_path / "metrics.jsonl"
    ))
    assert "acme" in summary["tenants"]


# --- flight dump mid-execute (satellite 3) --------------------------------


def test_flight_dump_mid_execute_holds_all_threads(tmp_path):
    """The dump firing WHILE ContinuousBatcher worker threads are
    mid-execute: RLock reentrancy holds (no deadlock from the worker's
    own hook chain), and the dump carries spans from every thread."""
    tracker = SpanTracker(capacity=64, xplane_bridge=False)
    prev = set_tracker(tracker)
    try:
        rec = FlightRecorder(out_dir=tmp_path, tracker=tracker)
        dumped = threading.Event()

        def execute(group, batch):
            with tracker.span("serve/execute", rows=len(batch)):
                # Mid-execute, from the worker thread, through the
                # recorder (hook-chain order: metric first, dump second —
                # exactly what a watchdog critical does).
                rec.record_metric({"step": 1, "kind": "serve", "served": 0})
                rec.dump(reason="watchdog: queue_stall (mid-execute drill)")
                dumped.set()
            for r in batch:
                r.future.set_result({"ok": True})

        b = ContinuousBatcher(execute, buckets=(1, 2, 4), start=True)
        try:
            with tracker.span("client/submit"):
                pass                        # a completed main-thread span
            futs = [b.submit({}, 5.0, tenant="t") for _ in range(3)]
            for f in futs:
                f.result(timeout=10)
            assert dumped.wait(5)
        finally:
            b.close()
        # Direct RLock reentrancy: dumping while this thread already
        # holds the recorder lock must not deadlock.
        with rec._lock:
            rec.dump(reason="reentrant")
        payload = json.loads((tmp_path / "flight_recorder.json").read_text())
        threads = {s["thread"] for s in payload["spans"]}
        assert "MainThread" in threads
        assert any(t != "MainThread" for t in threads), (
            f"worker spans missing from dump: {threads}"
        )
        assert any(s["name"] == "serve/execute" for s in payload["spans"])
    finally:
        set_tracker(prev)


# --- reservoir + histogram -------------------------------------------------


def test_reservoir_bounded_and_uniform_ish():
    r = _Reservoir(cap=64)
    for i in range(10_000):
        r.add(float(i))
    assert len(r.ms) == 64 and r.n == 10_000
    # Uniform over the HISTORY, not a recency window: a healthy fraction
    # of retained samples predate the last 64 additions.
    assert sum(1 for x in r.ms if x < 9_936) > 32


def test_reservoir_percentile_convention_matches_loadgen():
    sys.path.insert(0, _TOOLS)
    from loadgen import pct

    lat_s = [0.001 * (i + 1) for i in range(37)]
    r = _Reservoir(cap=64)
    for x in lat_s:
        r.add(x * 1e3)
    for q in (50, 90, 99):
        assert r.percentile(q) == pytest.approx(pct(lat_s, q))


def test_tenant_stats_bounded_under_many_tenants():
    stats = ServingStats()
    for t in range(50):
        for i in range(ServingStats.TENANT_SAMPLES + 100):
            stats.record_done(0.001, tenant=f"t{t}")
    snap = stats.tenant_snapshot()
    assert len(snap) == 50
    for ts in stats._tenants.values():
        assert len(ts.lat.ms) == ServingStats.TENANT_SAMPLES


def test_histogram_prometheus_exemplars():
    reg = CounterRegistry(prefix="test")
    h = reg.histogram("latency_ms", help="request latency")
    h.observe(3.0, exemplar="aa-1")
    h.observe(7.0)
    h.observe(900.0, exemplar="aa-2")
    text = reg.to_prometheus()
    assert "# TYPE test_latency_ms histogram" in text
    assert 'test_latency_ms_bucket{le="5"} 1 # {trace_id="aa-1"} 3' in text
    assert 'test_latency_ms_bucket{le="+Inf"} 3' in text
    assert "test_latency_ms_count 3" in text
    assert 'trace_id="aa-2"' in text
    # snapshot stays scalar (observation count).
    assert reg.snapshot()["latency_ms"] == 3.0
    # Identity-checked unregister: a stale handle cannot remove the
    # successor's histogram.
    reg.unregister("latency_ms")
    h2 = reg.histogram("latency_ms")
    reg.unregister("latency_ms", inst=h)     # stale: no-op
    assert reg.histogram("latency_ms") is h2


def test_stats_histogram_binding_and_unbind():
    reg = CounterRegistry()
    stats = ServingStats()
    stats.bind_registry(reg)
    stats.record_done(0.004, tenant="a", trace_id="ex-1")
    text = reg.to_prometheus()
    assert "induction_serve_latency_ms_bucket" in text
    assert 'trace_id="ex-1"' in text
    stats.unbind_registry()
    assert "serve_latency_ms" not in reg.snapshot()


def test_trace_summary_medians():
    stats = ServingStats()
    for i in range(5):
        stats.record_trace({
            "trace_id": f"t-{i}", "tenant": "a",
            "queue_ms": float(i), "pack_ms": 0.5, "execute_ms": 2.0,
            "respond_ms": 0.1, "total_ms": float(i) + 2.6,
        })
    s = stats.trace_summary()
    assert s["sampled"] == 5
    # Nearest-rank median, the shared loadgen convention: for 5 samples
    # the rank is round(0.5*5)-1 = 1 (banker's rounding) -> element 1.
    assert s["queue_ms_p50"] == 1.0
    assert s["exemplar_trace_ids"][-1] == "t-4"
