"""LSTM recurrence: Pallas kernel (interpret mode) vs lax.scan reference vs
a torch.nn.LSTM golden twin (SURVEY.md §4.1/§4.2).

The Pallas kernel runs here through the interpreter (no chip needed), so the
exact kernel code that compiles on TPU is what gets checked — forward AND the
custom-VJP backward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.ops.lstm import (
    lstm_recurrence,
    lstm_recurrence_grouped,
    lstm_scan,
)

M, L, D, U = 10, 7, 12, 16  # deliberately NOT tile-aligned (exercises padding)


@pytest.fixture(scope="module")
def inputs():
    rng = np.random.default_rng(0)
    xg = rng.normal(size=(M, L, 4 * U)).astype(np.float32) * 0.5
    whh = (rng.normal(size=(U, 4 * U)) / np.sqrt(U)).astype(np.float32)
    return jnp.asarray(xg), jnp.asarray(whh)


def test_forward_parity_scan_vs_pallas(inputs):
    xg, whh = inputs
    hs_scan = lstm_scan(xg, whh)
    hs_pl = lstm_recurrence(xg, whh, backend="interpret")
    assert hs_pl.shape == (M, L, U)
    np.testing.assert_allclose(np.asarray(hs_scan), np.asarray(hs_pl), atol=1e-5)


def test_backward_parity_scan_vs_pallas(inputs):
    xg, whh = inputs
    rng = np.random.default_rng(1)
    ct = jnp.asarray(rng.normal(size=(M, L, U)).astype(np.float32))

    def loss(fn):
        return lambda xg_, whh_: jnp.sum(fn(xg_, whh_) * ct)

    g_scan = jax.grad(loss(lstm_scan), argnums=(0, 1))(xg, whh)
    g_pl = jax.grad(
        loss(lambda a, b: lstm_recurrence(a, b, backend="interpret")),
        argnums=(0, 1),
    )(xg, whh)
    np.testing.assert_allclose(np.asarray(g_scan[0]), np.asarray(g_pl[0]), atol=1e-4)
    np.testing.assert_allclose(np.asarray(g_scan[1]), np.asarray(g_pl[1]), atol=1e-4)


def test_golden_torch_lstm(inputs):
    """lstm_scan == torch.nn.LSTM with the same weights (gate order i,f,g,o)."""
    torch = pytest.importorskip("torch")
    rng = np.random.default_rng(2)
    x = rng.normal(size=(M, L, D)).astype(np.float32)
    w_ih = (rng.normal(size=(D, 4 * U)) / np.sqrt(D)).astype(np.float32)
    w_hh = (rng.normal(size=(U, 4 * U)) / np.sqrt(U)).astype(np.float32)
    b = rng.normal(size=(4 * U,)).astype(np.float32)

    xg = jnp.asarray(x) @ jnp.asarray(w_ih) + jnp.asarray(b)
    hs_j = np.asarray(lstm_scan(xg, jnp.asarray(w_hh)))

    lstm = torch.nn.LSTM(D, U, batch_first=True)
    with torch.no_grad():
        lstm.weight_ih_l0.copy_(torch.tensor(w_ih.T))  # torch: [4u, D]
        lstm.weight_hh_l0.copy_(torch.tensor(w_hh.T))
        lstm.bias_ih_l0.copy_(torch.tensor(b))
        lstm.bias_hh_l0.zero_()
        hs_t, _ = lstm(torch.tensor(x))
    np.testing.assert_allclose(hs_j, hs_t.numpy(), atol=1e-5)


def test_grouped_forward_backward_parity():
    """Grouped (per-direction-weight) kernel == per-group lax.scan, forward
    and backward — including group counts whose rows pad to different tiles."""
    rng = np.random.default_rng(7)
    Gc = 2
    xg = jnp.asarray(rng.normal(size=(Gc, M, L, 4 * U)).astype(np.float32) * 0.5)
    whh = jnp.asarray(
        (rng.normal(size=(Gc, U, 4 * U)) / np.sqrt(U)).astype(np.float32)
    )
    ct = jnp.asarray(rng.normal(size=(Gc, M, L, U)).astype(np.float32))

    hs_ref = jnp.stack([lstm_scan(xg[g], whh[g]) for g in range(Gc)])
    hs_pl = lstm_recurrence_grouped(xg, whh, backend="interpret")
    np.testing.assert_allclose(np.asarray(hs_ref), np.asarray(hs_pl), atol=1e-5)
    # Groups must NOT share weights: perturbing group 1's weights must leave
    # group 0's output untouched (this is the untied-direction contract).
    hs_pl2 = lstm_recurrence_grouped(
        xg, whh.at[1].mul(2.0), backend="interpret"
    )
    np.testing.assert_allclose(
        np.asarray(hs_pl[0]), np.asarray(hs_pl2[0]), atol=1e-6
    )
    assert not np.allclose(np.asarray(hs_pl[1]), np.asarray(hs_pl2[1]))

    def loss(fn):
        return lambda a, b: jnp.sum(fn(a, b) * ct)

    ref = loss(lambda a, b: jnp.stack(
        [lstm_scan(a[g], b[g]) for g in range(Gc)]
    ))
    g_ref = jax.grad(ref, argnums=(0, 1))(xg, whh)
    g_pl = jax.grad(
        loss(lambda a, b: lstm_recurrence_grouped(a, b, backend="interpret")),
        argnums=(0, 1),
    )(xg, whh)
    np.testing.assert_allclose(np.asarray(g_ref[0]), np.asarray(g_pl[0]), atol=1e-4)
    np.testing.assert_allclose(np.asarray(g_ref[1]), np.asarray(g_pl[1]), atol=1e-4)


def test_golden_torch_bidirectional_lstm():
    """Per-direction recurrence == torch.nn.LSTM(bidirectional=True) with
    INDEPENDENT forward/reverse weights (the reference family's convention;
    VERDICT r1 #1)."""
    torch = pytest.importorskip("torch")
    rng = np.random.default_rng(11)
    x = rng.normal(size=(M, L, D)).astype(np.float32)
    w_ih = (rng.normal(size=(2, D, 4 * U)) / np.sqrt(D)).astype(np.float32)
    w_hh = (rng.normal(size=(2, U, 4 * U)) / np.sqrt(U)).astype(np.float32)
    b = rng.normal(size=(2, 4 * U)).astype(np.float32)

    # JAX path, exactly as BiLSTMSelfAttnEncoder computes it: stack fwd and
    # flipped inputs on a direction axis, project with per-direction w_ih,
    # grouped recurrence with per-direction w_hh, re-flip the reverse half.
    both = jnp.stack([jnp.asarray(x), jnp.flip(jnp.asarray(x), axis=1)])
    xg = jnp.einsum("gmld,gdh->gmlh", both, jnp.asarray(w_ih)) + jnp.asarray(
        b
    )[:, None, None]
    hs = lstm_recurrence_grouped(xg, jnp.asarray(w_hh), backend="interpret")
    H_j = np.concatenate(
        [np.asarray(hs[0]), np.asarray(jnp.flip(hs[1], axis=1))], axis=-1
    )  # [M, L, 2U]

    lstm = torch.nn.LSTM(D, U, batch_first=True, bidirectional=True)
    with torch.no_grad():
        lstm.weight_ih_l0.copy_(torch.tensor(w_ih[0].T))
        lstm.weight_hh_l0.copy_(torch.tensor(w_hh[0].T))
        lstm.bias_ih_l0.copy_(torch.tensor(b[0]))
        lstm.bias_hh_l0.zero_()
        lstm.weight_ih_l0_reverse.copy_(torch.tensor(w_ih[1].T))
        lstm.weight_hh_l0_reverse.copy_(torch.tensor(w_hh[1].T))
        lstm.bias_ih_l0_reverse.copy_(torch.tensor(b[1]))
        lstm.bias_hh_l0_reverse.zero_()
        H_t, _ = lstm(torch.tensor(x))  # [M, L, 2U], fwd ++ reverse
    np.testing.assert_allclose(H_j, H_t.numpy(), atol=1e-5)


def test_golden_torch_bilstm_encoder_end_to_end():
    """Full BiLSTMSelfAttnEncoder == torch twin: bidirectional nn.LSTM with
    independent direction weights + structured self-attention."""
    torch = pytest.importorskip("torch")
    from induction_network_on_fewrel_tpu.models.encoders import (
        BiLSTMSelfAttnEncoder,
    )

    rng = np.random.default_rng(13)
    Mb, A = 6, 8
    emb = rng.normal(size=(Mb, L, D)).astype(np.float32)
    mask = (rng.random((Mb, L)) > 0.2).astype(np.float32)
    mask[:, 0] = 1.0

    enc = BiLSTMSelfAttnEncoder(lstm_hidden=U, att_dim=A, lstm_backend="scan")
    params = enc.init(jax.random.key(0), jnp.asarray(emb), jnp.asarray(mask))
    p = params["params"]
    out_j = np.asarray(enc.apply(params, jnp.asarray(emb), jnp.asarray(mask)))

    w_ih, w_hh, b = (np.asarray(p[k]) for k in ("w_ih", "w_hh", "bias"))
    W1 = np.asarray(p["att_w1"])  # [2U, A]
    w2 = np.asarray(p["att_w2"])  # [A, 1]

    lstm = torch.nn.LSTM(D, U, batch_first=True, bidirectional=True)
    with torch.no_grad():
        lstm.weight_ih_l0.copy_(torch.tensor(w_ih[0].T))
        lstm.weight_hh_l0.copy_(torch.tensor(w_hh[0].T))
        lstm.bias_ih_l0.copy_(torch.tensor(b[0]))
        lstm.bias_hh_l0.zero_()
        lstm.weight_ih_l0_reverse.copy_(torch.tensor(w_ih[1].T))
        lstm.weight_hh_l0_reverse.copy_(torch.tensor(w_hh[1].T))
        lstm.bias_ih_l0_reverse.copy_(torch.tensor(b[1]))
        lstm.bias_hh_l0_reverse.zero_()
        H, _ = lstm(torch.tensor(emb))                     # [Mb, L, 2U]
        scores = (torch.tanh(H @ torch.tensor(W1)) @ torch.tensor(w2))[..., 0]
        scores = scores.masked_fill(torch.tensor(mask) == 0, -1e30)
        att = torch.softmax(scores, dim=-1)
        out_t = torch.einsum("ml,mlh->mh", att, H)
    np.testing.assert_allclose(out_j, out_t.numpy(), atol=1e-5)


def test_encoder_backend_equivalence():
    """Same params -> same encoder output for scan and pallas backends
    (checkpoints are interchangeable across lstm_backend settings)."""
    from induction_network_on_fewrel_tpu.models.encoders import (
        BiLSTMSelfAttnEncoder,
    )

    rng = np.random.default_rng(3)
    emb = jnp.asarray(rng.normal(size=(6, L, D)).astype(np.float32))
    mask = jnp.asarray((rng.random((6, L)) > 0.2).astype(np.float32).copy())
    mask = mask.at[:, 0].set(1.0)

    enc_scan = BiLSTMSelfAttnEncoder(lstm_hidden=U, att_dim=8, lstm_backend="scan")
    enc_pl = BiLSTMSelfAttnEncoder(
        lstm_hidden=U, att_dim=8, lstm_backend="interpret"
    )
    params = enc_scan.init(jax.random.key(0), emb, mask)
    out_scan = enc_scan.apply(params, emb, mask)
    out_pl = enc_pl.apply(params, emb, mask)
    assert out_scan.shape == (6, 2 * U)
    np.testing.assert_allclose(
        np.asarray(out_scan), np.asarray(out_pl), atol=1e-5
    )


def test_unknown_backend(inputs):
    xg, whh = inputs
    with pytest.raises(ValueError):
        lstm_recurrence(xg, whh, backend="cuda")


def test_pallas_bf16_io_close_to_f32():
    """bf16-in -> bf16-out kernel (f32 internal recurrence) tracks the f32
    path to bf16 rounding, forward and backward."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from induction_network_on_fewrel_tpu.ops.lstm import lstm_recurrence

    M, L, u = 8, 10, 16
    key = jax.random.key(0)
    xg = jax.random.normal(key, (M, L, 4 * u), jnp.float32) * 0.5
    whh = jax.random.normal(jax.random.key(1), (u, 4 * u), jnp.float32) * 0.2

    hs32 = lstm_recurrence(xg, whh, backend="interpret")
    hs16 = lstm_recurrence(xg.astype(jnp.bfloat16), whh, backend="interpret")
    assert hs16.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(hs16, np.float32), np.asarray(hs32), rtol=0.05, atol=0.05
    )

    def loss32(x):
        return jnp.sum(lstm_recurrence(x, whh, backend="interpret") ** 2)

    def loss16(x):
        out = lstm_recurrence(
            x.astype(jnp.bfloat16), whh, backend="interpret"
        )
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g32 = jax.grad(loss32)(xg)
    g16 = jax.grad(loss16)(xg)
    # Grad errors compound through L bf16-rounded steps; only coarse
    # agreement is meaningful here.
    denom = np.abs(np.asarray(g32)).mean() + 1e-6
    rel = np.abs(np.asarray(g16) - np.asarray(g32)).mean() / denom
    assert rel < 0.15, f"bf16 grad relative error {rel}"


# ---------------------------------------------------------------------------
# Time-major bidirectional entry (bilstm_recurrence_tm): the reversal and
# direction select live in the kernel's index maps — check fwd + custom-VJP
# bwd against the scan twin, which flips/transposes explicitly.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tm_inputs():
    rng = np.random.default_rng(7)
    xg_t = rng.normal(size=(L, M, 8 * U)).astype(np.float32) * 0.5
    whh = (rng.normal(size=(2, U, 4 * U)) / np.sqrt(U)).astype(np.float32)
    return jnp.asarray(xg_t), jnp.asarray(whh)


def test_tm_forward_parity_scan_vs_pallas(tm_inputs):
    from induction_network_on_fewrel_tpu.ops.lstm import bilstm_recurrence_tm

    xg_t, whh = tm_inputs
    hs_scan = bilstm_recurrence_tm(xg_t, whh, backend="scan")
    hs_pl = bilstm_recurrence_tm(xg_t, whh, backend="interpret")
    np.testing.assert_allclose(hs_pl, hs_scan, rtol=1e-5, atol=1e-5)
    # Direction independence: scaling the reverse weights moves only the
    # reverse half of the output.
    hs_pl2 = bilstm_recurrence_tm(xg_t, whh.at[1].mul(2.0), backend="interpret")
    np.testing.assert_allclose(hs_pl2[..., :U], hs_pl[..., :U], rtol=1e-6)
    assert not np.allclose(hs_pl2[..., U:], hs_pl[..., U:])


def test_tm_backward_parity_scan_vs_pallas(tm_inputs):
    from induction_network_on_fewrel_tpu.ops.lstm import bilstm_recurrence_tm

    xg_t, whh = tm_inputs
    w = jnp.asarray(
        np.random.default_rng(8).normal(size=(L, M, 2 * U)), jnp.float32
    )

    def loss(backend):
        def f(a, b):
            return jnp.sum(bilstm_recurrence_tm(a, b, backend=backend) * w)

        return f

    g_scan = jax.grad(loss("scan"), argnums=(0, 1))(xg_t, whh)
    g_pl = jax.grad(loss("interpret"), argnums=(0, 1))(xg_t, whh)
    np.testing.assert_allclose(g_pl[0], g_scan[0], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(g_pl[1], g_scan[1], rtol=1e-4, atol=1e-5)


def test_tm_matches_grouped_layout(tm_inputs):
    """tm output == the grouped API fed the explicitly flipped layout."""
    from induction_network_on_fewrel_tpu.ops.lstm import bilstm_recurrence_tm

    xg_t, whh = tm_inputs
    G = 4 * U
    fwd = jnp.swapaxes(xg_t[..., :G], 0, 1)
    bwd = jnp.swapaxes(jnp.flip(xg_t[..., G:], 0), 0, 1)
    hs_g = lstm_recurrence_grouped(
        jnp.stack([fwd, bwd]), whh, backend="interpret"
    )
    want = jnp.concatenate(
        [hs_g[0], jnp.flip(hs_g[1], axis=1)], axis=-1
    )  # [M, L, 2u] nat time
    got = jnp.swapaxes(
        bilstm_recurrence_tm(xg_t, whh, backend="interpret"), 0, 1
    )
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# Fully-fused projection+recurrence entry (bilstm_encoder_tm): xg never
# materializes on the pallas path; parity vs the explicit scan twin covers
# the in-kernel projection, bias, demb, dwih, db and dwhh paths.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fused_inputs():
    rng = np.random.default_rng(11)
    emb_t = rng.normal(size=(L, M, D)).astype(np.float32) * 0.5
    wih = (rng.normal(size=(2, D, 4 * U)) / np.sqrt(D)).astype(np.float32)
    b = rng.normal(size=(2, 1, 4 * U)).astype(np.float32) * 0.1
    whh = (rng.normal(size=(2, U, 4 * U)) / np.sqrt(U)).astype(np.float32)
    return tuple(jnp.asarray(x) for x in (emb_t, wih, b, whh))


def test_fused_forward_parity_scan_vs_pallas(fused_inputs):
    from induction_network_on_fewrel_tpu.ops.lstm import bilstm_encoder_tm

    emb_t, wih, b, whh = fused_inputs
    hs_scan = bilstm_encoder_tm(emb_t, wih, b, whh, backend="scan")
    hs_pl = bilstm_encoder_tm(emb_t, wih, b, whh, backend="interpret")
    np.testing.assert_allclose(hs_pl, hs_scan, rtol=1e-5, atol=1e-5)


def test_fused_backward_parity_scan_vs_pallas(fused_inputs):
    from induction_network_on_fewrel_tpu.ops.lstm import bilstm_encoder_tm

    emb_t, wih, b, whh = fused_inputs
    w = jnp.asarray(
        np.random.default_rng(12).normal(size=(L, M, 2 * U)), jnp.float32
    )

    def loss(backend):
        def f(e, wi, bb, wh):
            return jnp.sum(bilstm_encoder_tm(e, wi, bb, wh, backend=backend) * w)

        return f

    g_scan = jax.grad(loss("scan"), argnums=(0, 1, 2, 3))(emb_t, wih, b, whh)
    g_pl = jax.grad(loss("interpret"), argnums=(0, 1, 2, 3))(emb_t, wih, b, whh)
    for name, gs, gp in zip(("demb", "dwih", "db", "dwhh"), g_scan, g_pl):
        np.testing.assert_allclose(
            gp, gs, rtol=1e-4, atol=1e-5, err_msg=name
        )


def test_cs_recompute_from_hs_rejected():
    """Measured evidence for the round-6 REJECTION of the hs-only forward
    (ops/lstm.py module doc): dropping the cs residual requires
    reconstructing the cell as c = atanh(h / o), whose conditioning is
    cosh²(c) — fine while |c| is small, catastrophically wrong once the
    cell saturates (tanh(c) rounds to ±1.0 in f32 for |c| ≳ 8.3 and the
    inversion returns the clip bound, not c). A forget-dominant cell
    reaches that regime within a normal sentence length, so the byte
    saving is not purchasable at training-grade numerics."""
    rng = np.random.default_rng(0)
    u = 8
    steps = 40
    # Forget-dominant regime: i ~ sigmoid(4), f ~ sigmoid(6), g ~ tanh(2),
    # o ~ sigmoid(0) — the integrator cell every LSTM learns for
    # long-range features. Recurrence replicated exactly as the kernel
    # computes it (f32, [i, f, g, o] gate order).
    c = np.zeros(u, np.float32)
    errs, cs = [], []
    for _ in range(steps):
        i = 1.0 / (1.0 + np.exp(-np.float32(4.0)))
        f = 1.0 / (1.0 + np.exp(-np.float32(6.0)))
        g = np.tanh(np.float32(2.0) + rng.normal(0, 0.1, u).astype(np.float32))
        o = 1.0 / (1.0 + np.exp(-rng.normal(0, 0.5, u).astype(np.float32)))
        c = (f * c + i * g).astype(np.float32)
        h = (o * np.tanh(c)).astype(np.float32)
        # The reconstruction the hs-only backward would have to run:
        ratio = np.clip(h / o, -1.0 + 1e-7, 1.0 - 1e-7)
        c_hat = np.arctanh(ratio.astype(np.float32))
        errs.append(np.abs(c_hat - c).max())
        cs.append(np.abs(c).max())
    errs, cs = np.asarray(errs), np.asarray(cs)
    # Early, unsaturated steps reconstruct fine — the idea is not absurd…
    assert errs[0] < 1e-4, errs[0]
    # …but the cell saturates within a sentence, and the reconstruction
    # error exceeds O(1) ABSOLUTE — gradients built from it (da_f uses
    # c_prev directly) would be garbage, not approximate.
    assert cs[-1] > 8.3, f"fixture failed to saturate: |c| = {cs[-1]}"
    assert errs[-1] > 1.0, (
        f"reconstruction unexpectedly survived saturation: err {errs[-1]} "
        f"at |c| {cs[-1]} — re-evaluate the ops/lstm.py rejection note"
    )


# ---------------------------------------------------------------------------
# Windowed-cs remat (round 8): the fused forward saves one (h, c) checkpoint
# pair per W natural-time steps instead of the full cs residual stream, and
# the backward replays each window ascending in VMEM from the seed. Pinned:
# parity vs the scan twin at EVERY window size (including W = 1, T % W != 0
# ragged last blocks, W = T, and W > T which clamps), bf16-residual drift
# bounds, and encoder-level equivalence — the knob is runtime-only.
# ---------------------------------------------------------------------------


def _fused_grads(fused_inputs, backend, **kw):
    emb_t, wih, b, whh = fused_inputs
    w = jnp.asarray(
        np.random.default_rng(21).normal(size=(L, M, 2 * U)), jnp.float32
    )

    def f(e, wi, bb, wh):
        from induction_network_on_fewrel_tpu.ops.lstm import bilstm_encoder_tm

        return jnp.sum(bilstm_encoder_tm(e, wi, bb, wh, backend=backend, **kw) * w)

    val, grads = jax.value_and_grad(f, argnums=(0, 1, 2, 3))(emb_t, wih, b, whh)
    return val, grads


@pytest.mark.parametrize("W", [1, 2, 3, L, 8, 64])
def test_fused_windowed_cs_parity_vs_scan(fused_inputs, W):
    """Windowed-cs fwd + bwd == the scan twin at 1e-5, for window sizes
    covering per-step checkpoints (W=1), ragged last blocks (L=7: W=2 and
    W=3 leave T % W != 0), exactly one window (W=L), and W > L (clamped to
    one window recomputed from the zero initial state). The recompute
    ascends FORWARD from a saved seed — the forward's own arithmetic
    replayed — so f32 parity must not degrade with W (unlike the rejected
    atanh inversion, test_cs_recompute_from_hs_rejected)."""
    from induction_network_on_fewrel_tpu.ops.lstm import bilstm_encoder_tm

    emb_t, wih, b, whh = fused_inputs
    hs_scan = bilstm_encoder_tm(emb_t, wih, b, whh, backend="scan")
    hs_win = bilstm_encoder_tm(
        emb_t, wih, b, whh, backend="interpret", cs_window=W
    )
    np.testing.assert_allclose(hs_win, hs_scan, rtol=1e-5, atol=1e-5)

    _, g_scan = _fused_grads(fused_inputs, "scan")
    _, g_win = _fused_grads(fused_inputs, "interpret", cs_window=W)
    for name, gs, gp in zip(("demb", "dwih", "db", "dwhh"), g_scan, g_win):
        np.testing.assert_allclose(
            gp, gs, rtol=1e-4, atol=1e-5, err_msg=f"W={W} {name}"
        )


def test_fused_windowed_matches_full_cs_kernel(fused_inputs):
    """The windowed backward's f32 gradients track the full-cs kernel's to
    tighter than scan parity: the in-window recompute replays the same f32
    recurrence the forward ran, so the two kernel paths see (near-)
    identical cell states — any real divergence here means the window
    seeding or the ragged-block masking is wrong, not rounding."""
    _, g_full = _fused_grads(fused_inputs, "interpret", cs_window=0)
    for W in (1, 3, L):
        _, g_win = _fused_grads(fused_inputs, "interpret", cs_window=W)
        for name, gf, gw in zip(("demb", "dwih", "db", "dwhh"), g_full, g_win):
            np.testing.assert_allclose(
                gw, gf, rtol=1e-6, atol=1e-6, err_msg=f"W={W} {name}"
            )


def _grad_cosine(ga, gb):
    """vdot-consistent global grad cosine — the same reduction the
    --grad_probe_every machinery logs (train/steps.py)."""
    num = sum(
        float(jnp.vdot(a.astype(jnp.float32), b.astype(jnp.float32)))
        for a, b in zip(ga, gb)
    )
    na = sum(float(jnp.vdot(a, a)) for a in ga) ** 0.5
    nb = sum(float(jnp.vdot(b, b)) for b in gb) ** 0.5
    return num / (na * nb + 1e-30)


def test_fused_bf16_residual_drift_bounded(fused_inputs):
    """bf16 residual storage (cs stream at W=0; checkpoint seeds at W>0)
    drifts from the f32 reference backward within the grad-probe band.
    Windowed mode rounds only the window SEEDS (ceil(L/W) values per row
    per direction) while full-cs mode rounds every step's cell state, so
    the windowed bf16 drift must not exceed the full-cs bf16 drift class
    — both far inside the 0.99 cosine the probe machinery alerts on."""
    _, g_ref = _fused_grads(fused_inputs, "interpret", cs_window=0)
    for W in (0, 3):
        _, g16 = _fused_grads(
            fused_inputs, "interpret", cs_window=W,
            residual_dtype=jnp.bfloat16,
        )
        cos = _grad_cosine(g_ref, g16)
        assert cos > 0.999, f"W={W}: bf16-residual grad cosine {cos}"
        for name, gr, gb16 in zip(("demb", "dwih", "db", "dwhh"), g_ref, g16):
            denom = float(jnp.abs(gr).max()) + 1e-12
            rel = float(jnp.abs(gb16 - gr).max()) / denom
            assert rel < 0.02, f"W={W} {name}: bf16 residual drift {rel}"


def test_encoder_windowed_cs_equivalence():
    """Encoder-level: cs_window / residual_dtype are pure runtime knobs —
    same params -> same output across {scan, full-cs kernel, windowed
    kernel, windowed + bf16 residuals} (checkpoints interchange across
    every setting; the residual knobs shape only what the BACKWARD reads,
    which the forward-only apply never touches, and bf16-residual grads
    are probed separately above)."""
    from induction_network_on_fewrel_tpu.models.encoders import (
        BiLSTMSelfAttnEncoder,
    )

    rng = np.random.default_rng(23)
    emb = jnp.asarray(rng.normal(size=(6, L, D)).astype(np.float32))
    mask = jnp.asarray((rng.random((6, L)) > 0.2).astype(np.float32).copy())
    mask = mask.at[:, 0].set(1.0)

    enc_scan = BiLSTMSelfAttnEncoder(
        lstm_hidden=U, att_dim=8, lstm_backend="scan"
    )
    params = enc_scan.init(jax.random.key(0), emb, mask)
    out_ref = np.asarray(enc_scan.apply(params, emb, mask))
    for kw in (
        dict(lstm_cs_window=0),
        dict(lstm_cs_window=3),
        dict(lstm_cs_window=3, lstm_residual_dtype=jnp.bfloat16),
    ):
        enc = BiLSTMSelfAttnEncoder(
            lstm_hidden=U, att_dim=8, lstm_backend="interpret", **kw
        )
        out = enc.apply(params, emb, mask)
        np.testing.assert_allclose(
            np.asarray(out), out_ref, atol=1e-5, err_msg=str(kw)
        )


def test_resolver_windowed_knobs():
    """models/build.resolve_runtime_backends: the ONE home for the
    TPU-aware knob resolution — on this CPU session lstm_backend=auto
    resolves to scan and the residual knobs go inert (0 / None); forcing
    a kernel backend engages them; bad lstm_residuals raises."""
    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.models.build import (
        resolve_runtime_backends,
    )

    cfg = ExperimentConfig(encoder="bilstm")
    r = resolve_runtime_backends(cfg)
    assert r["lstm_backend"] == "scan" and r["lstm_cs_window"] == 0
    assert r["lstm_residual_dtype"] is None

    cfg = cfg.replace(
        lstm_backend="interpret", lstm_cs_window=8, lstm_residuals="bf16"
    )
    r = resolve_runtime_backends(cfg)
    assert r["lstm_cs_window"] == 8
    assert r["lstm_residual_dtype"] == jnp.bfloat16
    r = resolve_runtime_backends(cfg.replace(lstm_residuals="f32"))
    assert r["lstm_residual_dtype"] == jnp.float32
    with pytest.raises(ValueError):
        resolve_runtime_backends(cfg.replace(lstm_residuals="fp8"))


def test_resolver_comms_knobs():
    """Round-10 additions to the same one home: async_collectives auto
    resolves off on CPU (on would claim latency hiding the backend can't
    deliver); grad_bucketing auto resolves off on CPU for ANY embed arm
    (TPU+lazy is the only auto-on combination — a dense table arm keeps
    compact demb, which is mutually exclusive with the outer shard_map);
    both force with \"on\"; bad spellings raise."""
    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.models.build import (
        resolve_runtime_backends,
    )

    cfg = ExperimentConfig(encoder="bilstm")
    r = resolve_runtime_backends(cfg)
    assert r["async_collectives"] == "off"
    assert r["grad_bucketing"] == "off"
    assert r["grad_bucket_count"] == 4

    r = resolve_runtime_backends(
        cfg.replace(grad_bucketing="on", async_collectives="on",
                    grad_bucket_count=2)
    )
    assert r["grad_bucketing"] == "on"
    assert r["async_collectives"] == "on"
    assert r["grad_bucket_count"] == 2

    with pytest.raises(ValueError):
        resolve_runtime_backends(cfg.replace(grad_bucketing="yes"))
    with pytest.raises(ValueError):
        resolve_runtime_backends(cfg.replace(async_collectives="maybe"))
