"""LSTM recurrence: Pallas kernel (interpret mode) vs lax.scan reference vs
a torch.nn.LSTM golden twin (SURVEY.md §4.1/§4.2).

The Pallas kernel runs here through the interpreter (no chip needed), so the
exact kernel code that compiles on TPU is what gets checked — forward AND the
custom-VJP backward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.ops.lstm import (
    lstm_recurrence,
    lstm_recurrence_grouped,
    lstm_scan,
)

M, L, D, U = 10, 7, 12, 16  # deliberately NOT tile-aligned (exercises padding)


@pytest.fixture(scope="module")
def inputs():
    rng = np.random.default_rng(0)
    xg = rng.normal(size=(M, L, 4 * U)).astype(np.float32) * 0.5
    whh = (rng.normal(size=(U, 4 * U)) / np.sqrt(U)).astype(np.float32)
    return jnp.asarray(xg), jnp.asarray(whh)


def test_forward_parity_scan_vs_pallas(inputs):
    xg, whh = inputs
    hs_scan = lstm_scan(xg, whh)
    hs_pl = lstm_recurrence(xg, whh, backend="interpret")
    assert hs_pl.shape == (M, L, U)
    np.testing.assert_allclose(np.asarray(hs_scan), np.asarray(hs_pl), atol=1e-5)


def test_backward_parity_scan_vs_pallas(inputs):
    xg, whh = inputs
    rng = np.random.default_rng(1)
    ct = jnp.asarray(rng.normal(size=(M, L, U)).astype(np.float32))

    def loss(fn):
        return lambda xg_, whh_: jnp.sum(fn(xg_, whh_) * ct)

    g_scan = jax.grad(loss(lstm_scan), argnums=(0, 1))(xg, whh)
    g_pl = jax.grad(
        loss(lambda a, b: lstm_recurrence(a, b, backend="interpret")),
        argnums=(0, 1),
    )(xg, whh)
    np.testing.assert_allclose(np.asarray(g_scan[0]), np.asarray(g_pl[0]), atol=1e-4)
    np.testing.assert_allclose(np.asarray(g_scan[1]), np.asarray(g_pl[1]), atol=1e-4)


def test_golden_torch_lstm(inputs):
    """lstm_scan == torch.nn.LSTM with the same weights (gate order i,f,g,o)."""
    torch = pytest.importorskip("torch")
    rng = np.random.default_rng(2)
    x = rng.normal(size=(M, L, D)).astype(np.float32)
    w_ih = (rng.normal(size=(D, 4 * U)) / np.sqrt(D)).astype(np.float32)
    w_hh = (rng.normal(size=(U, 4 * U)) / np.sqrt(U)).astype(np.float32)
    b = rng.normal(size=(4 * U,)).astype(np.float32)

    xg = jnp.asarray(x) @ jnp.asarray(w_ih) + jnp.asarray(b)
    hs_j = np.asarray(lstm_scan(xg, jnp.asarray(w_hh)))

    lstm = torch.nn.LSTM(D, U, batch_first=True)
    with torch.no_grad():
        lstm.weight_ih_l0.copy_(torch.tensor(w_ih.T))  # torch: [4u, D]
        lstm.weight_hh_l0.copy_(torch.tensor(w_hh.T))
        lstm.bias_ih_l0.copy_(torch.tensor(b))
        lstm.bias_hh_l0.zero_()
        hs_t, _ = lstm(torch.tensor(x))
    np.testing.assert_allclose(hs_j, hs_t.numpy(), atol=1e-5)


def test_grouped_forward_backward_parity():
    """Grouped (per-direction-weight) kernel == per-group lax.scan, forward
    and backward — including group counts whose rows pad to different tiles."""
    rng = np.random.default_rng(7)
    Gc = 2
    xg = jnp.asarray(rng.normal(size=(Gc, M, L, 4 * U)).astype(np.float32) * 0.5)
    whh = jnp.asarray(
        (rng.normal(size=(Gc, U, 4 * U)) / np.sqrt(U)).astype(np.float32)
    )
    ct = jnp.asarray(rng.normal(size=(Gc, M, L, U)).astype(np.float32))

    hs_ref = jnp.stack([lstm_scan(xg[g], whh[g]) for g in range(Gc)])
    hs_pl = lstm_recurrence_grouped(xg, whh, backend="interpret")
    np.testing.assert_allclose(np.asarray(hs_ref), np.asarray(hs_pl), atol=1e-5)
    # Groups must NOT share weights: perturbing group 1's weights must leave
    # group 0's output untouched (this is the untied-direction contract).
    hs_pl2 = lstm_recurrence_grouped(
        xg, whh.at[1].mul(2.0), backend="interpret"
    )
    np.testing.assert_allclose(
        np.asarray(hs_pl[0]), np.asarray(hs_pl2[0]), atol=1e-6
    )
    assert not np.allclose(np.asarray(hs_pl[1]), np.asarray(hs_pl2[1]))

    def loss(fn):
        return lambda a, b: jnp.sum(fn(a, b) * ct)

    ref = loss(lambda a, b: jnp.stack(
        [lstm_scan(a[g], b[g]) for g in range(Gc)]
    ))
    g_ref = jax.grad(ref, argnums=(0, 1))(xg, whh)
    g_pl = jax.grad(
        loss(lambda a, b: lstm_recurrence_grouped(a, b, backend="interpret")),
        argnums=(0, 1),
    )(xg, whh)
    np.testing.assert_allclose(np.asarray(g_ref[0]), np.asarray(g_pl[0]), atol=1e-4)
    np.testing.assert_allclose(np.asarray(g_ref[1]), np.asarray(g_pl[1]), atol=1e-4)


def test_golden_torch_bidirectional_lstm():
    """Per-direction recurrence == torch.nn.LSTM(bidirectional=True) with
    INDEPENDENT forward/reverse weights (the reference family's convention;
    VERDICT r1 #1)."""
    torch = pytest.importorskip("torch")
    rng = np.random.default_rng(11)
    x = rng.normal(size=(M, L, D)).astype(np.float32)
    w_ih = (rng.normal(size=(2, D, 4 * U)) / np.sqrt(D)).astype(np.float32)
    w_hh = (rng.normal(size=(2, U, 4 * U)) / np.sqrt(U)).astype(np.float32)
    b = rng.normal(size=(2, 4 * U)).astype(np.float32)

    # JAX path, exactly as BiLSTMSelfAttnEncoder computes it: stack fwd and
    # flipped inputs on a direction axis, project with per-direction w_ih,
    # grouped recurrence with per-direction w_hh, re-flip the reverse half.
    both = jnp.stack([jnp.asarray(x), jnp.flip(jnp.asarray(x), axis=1)])
    xg = jnp.einsum("gmld,gdh->gmlh", both, jnp.asarray(w_ih)) + jnp.asarray(
        b
    )[:, None, None]
    hs = lstm_recurrence_grouped(xg, jnp.asarray(w_hh), backend="interpret")
    H_j = np.concatenate(
        [np.asarray(hs[0]), np.asarray(jnp.flip(hs[1], axis=1))], axis=-1
    )  # [M, L, 2U]

    lstm = torch.nn.LSTM(D, U, batch_first=True, bidirectional=True)
    with torch.no_grad():
        lstm.weight_ih_l0.copy_(torch.tensor(w_ih[0].T))
        lstm.weight_hh_l0.copy_(torch.tensor(w_hh[0].T))
        lstm.bias_ih_l0.copy_(torch.tensor(b[0]))
        lstm.bias_hh_l0.zero_()
        lstm.weight_ih_l0_reverse.copy_(torch.tensor(w_ih[1].T))
        lstm.weight_hh_l0_reverse.copy_(torch.tensor(w_hh[1].T))
        lstm.bias_ih_l0_reverse.copy_(torch.tensor(b[1]))
        lstm.bias_hh_l0_reverse.zero_()
        H_t, _ = lstm(torch.tensor(x))  # [M, L, 2U], fwd ++ reverse
    np.testing.assert_allclose(H_j, H_t.numpy(), atol=1e-5)


def test_golden_torch_bilstm_encoder_end_to_end():
    """Full BiLSTMSelfAttnEncoder == torch twin: bidirectional nn.LSTM with
    independent direction weights + structured self-attention."""
    torch = pytest.importorskip("torch")
    from induction_network_on_fewrel_tpu.models.encoders import (
        BiLSTMSelfAttnEncoder,
    )

    rng = np.random.default_rng(13)
    Mb, A = 6, 8
    emb = rng.normal(size=(Mb, L, D)).astype(np.float32)
    mask = (rng.random((Mb, L)) > 0.2).astype(np.float32)
    mask[:, 0] = 1.0

    enc = BiLSTMSelfAttnEncoder(lstm_hidden=U, att_dim=A, lstm_backend="scan")
    params = enc.init(jax.random.key(0), jnp.asarray(emb), jnp.asarray(mask))
    p = params["params"]
    out_j = np.asarray(enc.apply(params, jnp.asarray(emb), jnp.asarray(mask)))

    w_ih, w_hh, b = (np.asarray(p[k]) for k in ("w_ih", "w_hh", "bias"))
    W1 = np.asarray(p["att_w1"])  # [2U, A]
    w2 = np.asarray(p["att_w2"])  # [A, 1]

    lstm = torch.nn.LSTM(D, U, batch_first=True, bidirectional=True)
    with torch.no_grad():
        lstm.weight_ih_l0.copy_(torch.tensor(w_ih[0].T))
        lstm.weight_hh_l0.copy_(torch.tensor(w_hh[0].T))
        lstm.bias_ih_l0.copy_(torch.tensor(b[0]))
        lstm.bias_hh_l0.zero_()
        lstm.weight_ih_l0_reverse.copy_(torch.tensor(w_ih[1].T))
        lstm.weight_hh_l0_reverse.copy_(torch.tensor(w_hh[1].T))
        lstm.bias_ih_l0_reverse.copy_(torch.tensor(b[1]))
        lstm.bias_hh_l0_reverse.zero_()
        H, _ = lstm(torch.tensor(emb))                     # [Mb, L, 2U]
        scores = (torch.tanh(H @ torch.tensor(W1)) @ torch.tensor(w2))[..., 0]
        scores = scores.masked_fill(torch.tensor(mask) == 0, -1e30)
        att = torch.softmax(scores, dim=-1)
        out_t = torch.einsum("ml,mlh->mh", att, H)
    np.testing.assert_allclose(out_j, out_t.numpy(), atol=1e-5)


def test_encoder_backend_equivalence():
    """Same params -> same encoder output for scan and pallas backends
    (checkpoints are interchangeable across lstm_backend settings)."""
    from induction_network_on_fewrel_tpu.models.encoders import (
        BiLSTMSelfAttnEncoder,
    )

    rng = np.random.default_rng(3)
    emb = jnp.asarray(rng.normal(size=(6, L, D)).astype(np.float32))
    mask = jnp.asarray((rng.random((6, L)) > 0.2).astype(np.float32).copy())
    mask = mask.at[:, 0].set(1.0)

    enc_scan = BiLSTMSelfAttnEncoder(lstm_hidden=U, att_dim=8, lstm_backend="scan")
    enc_pl = BiLSTMSelfAttnEncoder(
        lstm_hidden=U, att_dim=8, lstm_backend="interpret"
    )
    params = enc_scan.init(jax.random.key(0), emb, mask)
    out_scan = enc_scan.apply(params, emb, mask)
    out_pl = enc_pl.apply(params, emb, mask)
    assert out_scan.shape == (6, 2 * U)
    np.testing.assert_allclose(
        np.asarray(out_scan), np.asarray(out_pl), atol=1e-5
    )


def test_unknown_backend(inputs):
    xg, whh = inputs
    with pytest.raises(ValueError):
        lstm_recurrence(xg, whh, backend="cuda")


def test_pallas_bf16_io_close_to_f32():
    """bf16-in -> bf16-out kernel (f32 internal recurrence) tracks the f32
    path to bf16 rounding, forward and backward."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from induction_network_on_fewrel_tpu.ops.lstm import lstm_recurrence

    M, L, u = 8, 10, 16
    key = jax.random.key(0)
    xg = jax.random.normal(key, (M, L, 4 * u), jnp.float32) * 0.5
    whh = jax.random.normal(jax.random.key(1), (u, 4 * u), jnp.float32) * 0.2

    hs32 = lstm_recurrence(xg, whh, backend="interpret")
    hs16 = lstm_recurrence(xg.astype(jnp.bfloat16), whh, backend="interpret")
    assert hs16.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(hs16, np.float32), np.asarray(hs32), rtol=0.05, atol=0.05
    )

    def loss32(x):
        return jnp.sum(lstm_recurrence(x, whh, backend="interpret") ** 2)

    def loss16(x):
        out = lstm_recurrence(
            x.astype(jnp.bfloat16), whh, backend="interpret"
        )
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g32 = jax.grad(loss32)(xg)
    g16 = jax.grad(loss16)(xg)
    # Grad errors compound through L bf16-rounded steps; only coarse
    # agreement is meaningful here.
    denom = np.abs(np.asarray(g32)).mean() + 1e-6
    rel = np.abs(np.asarray(g16) - np.asarray(g32)).mean() / denom
    assert rel < 0.15, f"bf16 grad relative error {rel}"


# ---------------------------------------------------------------------------
# Time-major bidirectional entry (bilstm_recurrence_tm): the reversal and
# direction select live in the kernel's index maps — check fwd + custom-VJP
# bwd against the scan twin, which flips/transposes explicitly.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tm_inputs():
    rng = np.random.default_rng(7)
    xg_t = rng.normal(size=(L, M, 8 * U)).astype(np.float32) * 0.5
    whh = (rng.normal(size=(2, U, 4 * U)) / np.sqrt(U)).astype(np.float32)
    return jnp.asarray(xg_t), jnp.asarray(whh)


def test_tm_forward_parity_scan_vs_pallas(tm_inputs):
    from induction_network_on_fewrel_tpu.ops.lstm import bilstm_recurrence_tm

    xg_t, whh = tm_inputs
    hs_scan = bilstm_recurrence_tm(xg_t, whh, backend="scan")
    hs_pl = bilstm_recurrence_tm(xg_t, whh, backend="interpret")
    np.testing.assert_allclose(hs_pl, hs_scan, rtol=1e-5, atol=1e-5)
    # Direction independence: scaling the reverse weights moves only the
    # reverse half of the output.
    hs_pl2 = bilstm_recurrence_tm(xg_t, whh.at[1].mul(2.0), backend="interpret")
    np.testing.assert_allclose(hs_pl2[..., :U], hs_pl[..., :U], rtol=1e-6)
    assert not np.allclose(hs_pl2[..., U:], hs_pl[..., U:])


def test_tm_backward_parity_scan_vs_pallas(tm_inputs):
    from induction_network_on_fewrel_tpu.ops.lstm import bilstm_recurrence_tm

    xg_t, whh = tm_inputs
    w = jnp.asarray(
        np.random.default_rng(8).normal(size=(L, M, 2 * U)), jnp.float32
    )

    def loss(backend):
        def f(a, b):
            return jnp.sum(bilstm_recurrence_tm(a, b, backend=backend) * w)

        return f

    g_scan = jax.grad(loss("scan"), argnums=(0, 1))(xg_t, whh)
    g_pl = jax.grad(loss("interpret"), argnums=(0, 1))(xg_t, whh)
    np.testing.assert_allclose(g_pl[0], g_scan[0], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(g_pl[1], g_scan[1], rtol=1e-4, atol=1e-5)


def test_tm_matches_grouped_layout(tm_inputs):
    """tm output == the grouped API fed the explicitly flipped layout."""
    from induction_network_on_fewrel_tpu.ops.lstm import bilstm_recurrence_tm

    xg_t, whh = tm_inputs
    G = 4 * U
    fwd = jnp.swapaxes(xg_t[..., :G], 0, 1)
    bwd = jnp.swapaxes(jnp.flip(xg_t[..., G:], 0), 0, 1)
    hs_g = lstm_recurrence_grouped(
        jnp.stack([fwd, bwd]), whh, backend="interpret"
    )
    want = jnp.concatenate(
        [hs_g[0], jnp.flip(hs_g[1], axis=1)], axis=-1
    )  # [M, L, 2u] nat time
    got = jnp.swapaxes(
        bilstm_recurrence_tm(xg_t, whh, backend="interpret"), 0, 1
    )
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# Fully-fused projection+recurrence entry (bilstm_encoder_tm): xg never
# materializes on the pallas path; parity vs the explicit scan twin covers
# the in-kernel projection, bias, demb, dwih, db and dwhh paths.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fused_inputs():
    rng = np.random.default_rng(11)
    emb_t = rng.normal(size=(L, M, D)).astype(np.float32) * 0.5
    wih = (rng.normal(size=(2, D, 4 * U)) / np.sqrt(D)).astype(np.float32)
    b = rng.normal(size=(2, 1, 4 * U)).astype(np.float32) * 0.1
    whh = (rng.normal(size=(2, U, 4 * U)) / np.sqrt(U)).astype(np.float32)
    return tuple(jnp.asarray(x) for x in (emb_t, wih, b, whh))


def test_fused_forward_parity_scan_vs_pallas(fused_inputs):
    from induction_network_on_fewrel_tpu.ops.lstm import bilstm_encoder_tm

    emb_t, wih, b, whh = fused_inputs
    hs_scan = bilstm_encoder_tm(emb_t, wih, b, whh, backend="scan")
    hs_pl = bilstm_encoder_tm(emb_t, wih, b, whh, backend="interpret")
    np.testing.assert_allclose(hs_pl, hs_scan, rtol=1e-5, atol=1e-5)


def test_fused_backward_parity_scan_vs_pallas(fused_inputs):
    from induction_network_on_fewrel_tpu.ops.lstm import bilstm_encoder_tm

    emb_t, wih, b, whh = fused_inputs
    w = jnp.asarray(
        np.random.default_rng(12).normal(size=(L, M, 2 * U)), jnp.float32
    )

    def loss(backend):
        def f(e, wi, bb, wh):
            return jnp.sum(bilstm_encoder_tm(e, wi, bb, wh, backend=backend) * w)

        return f

    g_scan = jax.grad(loss("scan"), argnums=(0, 1, 2, 3))(emb_t, wih, b, whh)
    g_pl = jax.grad(loss("interpret"), argnums=(0, 1, 2, 3))(emb_t, wih, b, whh)
    for name, gs, gp in zip(("demb", "dwih", "db", "dwhh"), g_scan, g_pl):
        np.testing.assert_allclose(
            gp, gs, rtol=1e-4, atol=1e-5, err_msg=name
        )


def test_cs_recompute_from_hs_rejected():
    """Measured evidence for the round-6 REJECTION of the hs-only forward
    (ops/lstm.py module doc): dropping the cs residual requires
    reconstructing the cell as c = atanh(h / o), whose conditioning is
    cosh²(c) — fine while |c| is small, catastrophically wrong once the
    cell saturates (tanh(c) rounds to ±1.0 in f32 for |c| ≳ 8.3 and the
    inversion returns the clip bound, not c). A forget-dominant cell
    reaches that regime within a normal sentence length, so the byte
    saving is not purchasable at training-grade numerics."""
    rng = np.random.default_rng(0)
    u = 8
    steps = 40
    # Forget-dominant regime: i ~ sigmoid(4), f ~ sigmoid(6), g ~ tanh(2),
    # o ~ sigmoid(0) — the integrator cell every LSTM learns for
    # long-range features. Recurrence replicated exactly as the kernel
    # computes it (f32, [i, f, g, o] gate order).
    c = np.zeros(u, np.float32)
    errs, cs = [], []
    for _ in range(steps):
        i = 1.0 / (1.0 + np.exp(-np.float32(4.0)))
        f = 1.0 / (1.0 + np.exp(-np.float32(6.0)))
        g = np.tanh(np.float32(2.0) + rng.normal(0, 0.1, u).astype(np.float32))
        o = 1.0 / (1.0 + np.exp(-rng.normal(0, 0.5, u).astype(np.float32)))
        c = (f * c + i * g).astype(np.float32)
        h = (o * np.tanh(c)).astype(np.float32)
        # The reconstruction the hs-only backward would have to run:
        ratio = np.clip(h / o, -1.0 + 1e-7, 1.0 - 1e-7)
        c_hat = np.arctanh(ratio.astype(np.float32))
        errs.append(np.abs(c_hat - c).max())
        cs.append(np.abs(c).max())
    errs, cs = np.asarray(errs), np.asarray(cs)
    # Early, unsaturated steps reconstruct fine — the idea is not absurd…
    assert errs[0] < 1e-4, errs[0]
    # …but the cell saturates within a sentence, and the reconstruction
    # error exceeds O(1) ABSOLUTE — gradients built from it (da_f uses
    # c_prev directly) would be garbage, not approximate.
    assert cs[-1] > 8.3, f"fixture failed to saturate: |c| = {cs[-1]}"
    assert errs[-1] > 1.0, (
        f"reconstruction unexpectedly survived saturation: err {errs[-1]} "
        f"at |c| {cs[-1]} — re-evaluate the ops/lstm.py rejection note"
    )
