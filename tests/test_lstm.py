"""LSTM recurrence: Pallas kernel (interpret mode) vs lax.scan reference vs
a torch.nn.LSTM golden twin (SURVEY.md §4.1/§4.2).

The Pallas kernel runs here through the interpreter (no chip needed), so the
exact kernel code that compiles on TPU is what gets checked — forward AND the
custom-VJP backward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.ops.lstm import lstm_recurrence, lstm_scan

M, L, D, U = 10, 7, 12, 16  # deliberately NOT tile-aligned (exercises padding)


@pytest.fixture(scope="module")
def inputs():
    rng = np.random.default_rng(0)
    xg = rng.normal(size=(M, L, 4 * U)).astype(np.float32) * 0.5
    whh = (rng.normal(size=(U, 4 * U)) / np.sqrt(U)).astype(np.float32)
    return jnp.asarray(xg), jnp.asarray(whh)


def test_forward_parity_scan_vs_pallas(inputs):
    xg, whh = inputs
    hs_scan = lstm_scan(xg, whh)
    hs_pl = lstm_recurrence(xg, whh, backend="interpret")
    assert hs_pl.shape == (M, L, U)
    np.testing.assert_allclose(np.asarray(hs_scan), np.asarray(hs_pl), atol=1e-5)


def test_backward_parity_scan_vs_pallas(inputs):
    xg, whh = inputs
    rng = np.random.default_rng(1)
    ct = jnp.asarray(rng.normal(size=(M, L, U)).astype(np.float32))

    def loss(fn):
        return lambda xg_, whh_: jnp.sum(fn(xg_, whh_) * ct)

    g_scan = jax.grad(loss(lstm_scan), argnums=(0, 1))(xg, whh)
    g_pl = jax.grad(
        loss(lambda a, b: lstm_recurrence(a, b, backend="interpret")),
        argnums=(0, 1),
    )(xg, whh)
    np.testing.assert_allclose(np.asarray(g_scan[0]), np.asarray(g_pl[0]), atol=1e-4)
    np.testing.assert_allclose(np.asarray(g_scan[1]), np.asarray(g_pl[1]), atol=1e-4)


def test_golden_torch_lstm(inputs):
    """lstm_scan == torch.nn.LSTM with the same weights (gate order i,f,g,o)."""
    torch = pytest.importorskip("torch")
    rng = np.random.default_rng(2)
    x = rng.normal(size=(M, L, D)).astype(np.float32)
    w_ih = (rng.normal(size=(D, 4 * U)) / np.sqrt(D)).astype(np.float32)
    w_hh = (rng.normal(size=(U, 4 * U)) / np.sqrt(U)).astype(np.float32)
    b = rng.normal(size=(4 * U,)).astype(np.float32)

    xg = jnp.asarray(x) @ jnp.asarray(w_ih) + jnp.asarray(b)
    hs_j = np.asarray(lstm_scan(xg, jnp.asarray(w_hh)))

    lstm = torch.nn.LSTM(D, U, batch_first=True)
    with torch.no_grad():
        lstm.weight_ih_l0.copy_(torch.tensor(w_ih.T))  # torch: [4u, D]
        lstm.weight_hh_l0.copy_(torch.tensor(w_hh.T))
        lstm.bias_ih_l0.copy_(torch.tensor(b))
        lstm.bias_hh_l0.zero_()
        hs_t, _ = lstm(torch.tensor(x))
    np.testing.assert_allclose(hs_j, hs_t.numpy(), atol=1e-5)


def test_encoder_backend_equivalence():
    """Same params -> same encoder output for scan and pallas backends
    (checkpoints are interchangeable across lstm_backend settings)."""
    from induction_network_on_fewrel_tpu.models.encoders import (
        BiLSTMSelfAttnEncoder,
    )

    rng = np.random.default_rng(3)
    emb = jnp.asarray(rng.normal(size=(6, L, D)).astype(np.float32))
    mask = jnp.asarray((rng.random((6, L)) > 0.2).astype(np.float32).copy())
    mask = mask.at[:, 0].set(1.0)

    enc_scan = BiLSTMSelfAttnEncoder(lstm_hidden=U, att_dim=8, lstm_backend="scan")
    enc_pl = BiLSTMSelfAttnEncoder(
        lstm_hidden=U, att_dim=8, lstm_backend="interpret"
    )
    params = enc_scan.init(jax.random.key(0), emb, mask)
    out_scan = enc_scan.apply(params, emb, mask)
    out_pl = enc_pl.apply(params, emb, mask)
    assert out_scan.shape == (6, 2 * U)
    np.testing.assert_allclose(
        np.asarray(out_scan), np.asarray(out_pl), atol=1e-5
    )


def test_unknown_backend(inputs):
    xg, whh = inputs
    with pytest.raises(ValueError):
        lstm_recurrence(xg, whh, backend="cuda")


def test_pallas_bf16_io_close_to_f32():
    """bf16-in -> bf16-out kernel (f32 internal recurrence) tracks the f32
    path to bf16 rounding, forward and backward."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from induction_network_on_fewrel_tpu.ops.lstm import lstm_recurrence

    M, L, u = 8, 10, 16
    key = jax.random.key(0)
    xg = jax.random.normal(key, (M, L, 4 * u), jnp.float32) * 0.5
    whh = jax.random.normal(jax.random.key(1), (u, 4 * u), jnp.float32) * 0.2

    hs32 = lstm_recurrence(xg, whh, backend="interpret")
    hs16 = lstm_recurrence(xg.astype(jnp.bfloat16), whh, backend="interpret")
    assert hs16.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(hs16, np.float32), np.asarray(hs32), rtol=0.05, atol=0.05
    )

    def loss32(x):
        return jnp.sum(lstm_recurrence(x, whh, backend="interpret") ** 2)

    def loss16(x):
        out = lstm_recurrence(
            x.astype(jnp.bfloat16), whh, backend="interpret"
        )
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g32 = jax.grad(loss32)(xg)
    g16 = jax.grad(loss16)(xg)
    # Grad errors compound through L bf16-rounded steps; only coarse
    # agreement is meaningful here.
    denom = np.abs(np.asarray(g32)).mean() + 1e-6
    rel = np.abs(np.asarray(g16) - np.asarray(g32)).mean() / denom
    assert rel < 0.15, f"bf16 grad relative error {rel}"
