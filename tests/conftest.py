"""Test session setup: 8 virtual CPU devices (SURVEY.md §4.5).

Multi-device tests run on the CPU backend with
``--xla_force_host_platform_device_count=8`` so shard_map/psum paths are
exercised without a pod.

IMPORTANT environment quirk: this image's axon sitecustomize registers the
TPU PJRT plugin in every Python process and overrides ``jax_platforms`` to
"axon,cpu" — so the ``JAX_PLATFORMS=cpu`` env var is NOT enough (backend init
then dials the TPU tunnel and can block). The reliable sequence is: set
XLA_FLAGS before importing jax, then ``jax.config.update("jax_platforms",
"cpu")`` before any backend init. TPU-only smoke tests are run separately
(see tests/tpu/README.md).
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402  (after XLA_FLAGS)

jax.config.update("jax_platforms", "cpu")
