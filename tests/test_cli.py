"""CLI smoke tests: the actual ``train.py``/``test.py`` surface, as a user
runs it (subprocess, --device cpu, synthetic data).

The library-level suites cannot catch wiring mistakes in cli.py (flag
plumbing, sampler/step injection, checkpoint merge) — several review
findings lived exactly there, so the entry points get end-to-end coverage.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = {
    **os.environ,
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    "PYTHONPATH": REPO,
}
TINY = [
    "--N", "3", "--K", "2", "--Q", "2", "--batch_size", "2",
    "--max_length", "16", "--lr", "3e-3", "--device", "cpu",
    "--dp", "1",  # the env forces 8 virtual devices; stay single-device
]


def run_cli(script, *extra, timeout=240):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, script), *extra],
        capture_output=True, text=True, timeout=timeout, env=ENV, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    return proc.stdout, proc.stderr


def last_json(stdout: str) -> dict:
    return json.loads(stdout.strip().splitlines()[-1])


@pytest.mark.slow
def test_train_then_test_cycle(tmp_path):
    ckpt = str(tmp_path / "ck")
    out, _ = run_cli(
        "train.py", "--model", "induction", "--encoder", "cnn", *TINY,
        "--train_iter", "120", "--val_step", "60", "--val_iter", "10",
        "--steps_per_call", "6", "--save_ckpt", ckpt,
    )
    assert "final_val_accuracy" in last_json(out)
    # test.py recovers the architecture from config.json: no model/encoder
    # flags re-passed.
    out, _ = run_cli(
        "test.py", *TINY, "--test_iter", "20", "--load_ckpt", ckpt,
    )
    assert "test_accuracy" in last_json(out)


@pytest.mark.slow
def test_compile_cache_populates_and_reruns(tmp_path):
    """--compile_cache DIR: the run populates a persistent XLA cache and an
    identical rerun succeeds against the populated dir (warm restart —
    measured 2.6x faster end-to-end on the TPU flagship, BASELINE.md
    round 5; here only correctness is asserted, CPU timings are noise)."""
    ckpt = str(tmp_path / "ck")
    cache = tmp_path / "xla_cache"
    args = [
        "train.py", "--model", "induction", "--encoder", "cnn", *TINY,
        "--train_iter", "40", "--val_step", "20", "--val_iter", "6",
        "--steps_per_call", "4", "--compile_cache", str(cache),
    ]
    out, _ = run_cli(*args, "--save_ckpt", ckpt)
    assert "final_val_accuracy" in last_json(out)
    entries = list(cache.rglob("*"))
    assert entries, "compilation cache dir stayed empty"
    out, _ = run_cli(*args, "--save_ckpt", str(tmp_path / "ck2"))
    assert "final_val_accuracy" in last_json(out)
    # 'off' must not touch the dir.
    before = len(list(cache.rglob("*")))
    out, _ = run_cli(
        "train.py", "--model", "induction", "--encoder", "cnn", *TINY,
        "--train_iter", "20", "--val_step", "10", "--val_iter", "4",
        "--compile_cache", "off", "--save_ckpt", str(tmp_path / "ck3"),
    )
    assert len(list(cache.rglob("*"))) == before


@pytest.mark.slow
def test_feature_cache_cycle(tmp_path):
    ckpt = str(tmp_path / "ck")
    bert = ["--encoder", "bert", "--bert_frozen", "--bert_layers", "2",
            "--bert_vocab_size", "64"]
    out, _ = run_cli(
        "train.py", "--model", "induction", *bert, "--feature_cache", *TINY,
        "--train_iter", "60", "--val_step", "30", "--val_iter", "6",
        "--steps_per_call", "5", "--save_ckpt", ckpt,
    )
    assert "final_val_accuracy" in last_json(out)
    out, _ = run_cli(  # merge recovers feature_cache + bert_frozen
        "test.py", *TINY, "--test_iter", "10", "--load_ckpt", ckpt,
    )
    assert "test_accuracy" in last_json(out)


@pytest.mark.slow
def test_adv_fused_and_mesh(tmp_path):
    out, _ = run_cli(
        "train.py", "--model", "proto", "--encoder", "cnn", "--loss", "ce",
        *TINY, "--adv", "--steps_per_call", "5", "--train_iter", "40",
        "--val_step", "20", "--val_iter", "6",
        "--save_ckpt", str(tmp_path / "a"),
    )
    assert "final_val_accuracy" in last_json(out)
    out, err = run_cli(
        "train.py", "--model", "proto", "--encoder", "cnn", "--loss", "ce",
        "--N", "3", "--K", "2", "--Q", "2", "--batch_size", "8",
        "--max_length", "16", "--lr", "3e-3", "--device", "cpu",
        "--dp", "4", "--tp", "2", "--steps_per_call", "5",
        "--train_iter", "20", "--val_step", "10", "--val_iter", "4",
        "--save_ckpt", str(tmp_path / "b"),
    )
    assert "final_val_accuracy" in last_json(out)


@pytest.mark.slow
def test_bad_flag_combinations_fail_fast(tmp_path):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "train.py"), "--model", "pair",
         "--encoder", "cnn", *TINY, "--train_iter", "5",
         "--save_ckpt", str(tmp_path / "x")],
        capture_output=True, text=True, timeout=120, env=ENV, cwd=REPO,
    )
    assert proc.returncode != 0 and "encoder bert" in proc.stderr

    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "train.py"), "--feature_cache",
         "--encoder", "cnn", *TINY, "--train_iter", "5",
         "--save_ckpt", str(tmp_path / "y")],
        capture_output=True, text=True, timeout=120, env=ENV, cwd=REPO,
    )
    assert proc.returncode != 0 and "feature_cache" in proc.stderr


@pytest.mark.slow
def test_real_glove_txt_pins_embedding_shape(tmp_path):
    """A loaded GloVe decides vocab_size/word_dim: the CLI must pin the
    embedding table to it (regression: default 400002x50 vs real file)."""
    glove = tmp_path / "glove.tiny.3d.txt"
    glove.write_text(
        "".join(f"w{i} {0.1*i} {0.2*i} {0.3*i}\n" for i in range(20))
    )
    out, _ = run_cli(
        "train.py", "--model", "proto", "--encoder", "cnn", *TINY,
        "--glove", str(glove), "--train_iter", "20", "--val_step", "0",
        "--val_iter", "4", "--save_ckpt", str(tmp_path / "ck"),
    )
    assert "final_val_accuracy" in last_json(out)


def test_parallel_flag_validation_in_process():
    """Every parallelism flag family rejects invalid combos with a flag-
    named ValueError BEFORE any tracing starts (in-process: exercises the
    same make_trainer guards the subprocess tests hit, at unit-test cost)."""
    import pytest

    from induction_network_on_fewrel_tpu.cli import train_main

    tiny = ["--N", "2", "--K", "2", "--Q", "2", "--batch_size", "2",
            "--max_length", "12", "--vocab_size", "202", "--train_iter", "2",
            "--device", "cpu", "--sampler", "python"]

    with pytest.raises(ValueError, match="ring attention"):
        train_main(["--encoder", "cnn", "--sp", "2", *tiny])
    with pytest.raises(ValueError, match="pipeline"):
        train_main(["--encoder", "cnn", "--pp", "2", *tiny])
    with pytest.raises(ValueError, match="expert"):
        train_main(["--encoder", "cnn", "--ep", "2", *tiny])
    with pytest.raises(ValueError, match="divisible"):
        train_main(["--encoder", "transformer", "--ep", "2",
                    "--moe_experts", "3", *tiny])
    with pytest.raises(ValueError, match="token_cache"):
        train_main(["--encoder", "bilstm", "--token_cache", "--adv", *tiny])
    with pytest.raises(ValueError, match="batch_size"):
        train_main(["--encoder", "bilstm", "--dp", "8",
                    "--N", "2", "--K", "2", "--Q", "2", "--batch_size", "3",
                    "--max_length", "12", "--vocab_size", "202",
                    "--train_iter", "2", "--device", "cpu",
                    "--sampler", "python"])


@pytest.mark.slow
def test_fault_injection_then_resume(tmp_path):
    """--fault_step crashes the run mid-training; --resume restores the
    newest recovery-ring checkpoint and completes (SURVEY.md §5.3 failure
    detection / recovery, driven end-to-end through the real CLI)."""
    ckpt = str(tmp_path / "ck")
    args = ["--model", "induction", "--encoder", "cnn", *TINY,
            "--train_iter", "80", "--val_step", "20", "--val_iter", "6",
            "--save_ckpt", ckpt]
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "train.py"), *args,
         "--fault_step", "45"],
        capture_output=True, text=True, timeout=240, env=ENV, cwd=REPO,
    )
    assert proc.returncode != 0 and "injected fault" in proc.stderr
    # Resume with the SAME command line (fault flag included): the
    # injection fires only on fresh runs, so the resume completes instead
    # of looping crash/resume.
    out, err = run_cli("train.py", *args, "--fault_step", "45", "--resume")
    assert "final_val_accuracy" in last_json(out)
    # Resumed from the ring slot written at the last val boundary (40),
    # not from scratch.
    assert "restored checkpoint step=40" in err, err[-1500:]


def test_degenerate_mse_nota_guard():
    """--loss mse with --na_rate >= 3 is refused for training runs (the
    BASELINE.md all-NOTA collapse) unless --force; eval-only paths and
    --loss ce are unaffected."""
    from induction_network_on_fewrel_tpu.cli import (
        build_arg_parser,
        config_from_args,
    )

    train_p = build_arg_parser(train=True)
    with pytest.raises(ValueError, match="degenerate"):
        config_from_args(train_p.parse_args(["--loss", "mse", "--na_rate", "3"]))
    # explicit opt-in runs it anyway
    config_from_args(
        train_p.parse_args(["--loss", "mse", "--na_rate", "3", "--force"])
    )
    # CE does not collapse; na_rate below the threshold is fine
    config_from_args(train_p.parse_args(["--loss", "ce", "--na_rate", "5"]))
    config_from_args(train_p.parse_args(["--loss", "mse", "--na_rate", "2"]))
    # eval-only invocations compute no training loss
    config_from_args(
        train_p.parse_args(["--loss", "mse", "--na_rate", "5", "--only_test"])
    )
    test_p = build_arg_parser(train=False)
    config_from_args(test_p.parse_args(["--loss", "mse", "--na_rate", "5"]))


@pytest.mark.slow
def test_token_cache_fused_test_eval_parity(tmp_path):
    """test.py on the token-cache path: fused eval (bound to the TEST
    table) scores identically to per-batch eval — same seed, same episode
    stream, tail padding sliced off."""
    ckpt = str(tmp_path / "ck")
    run_cli(
        "train.py", "--model", "induction", "--encoder", "cnn",
        "--token_cache", *TINY, "--train_iter", "40", "--val_step", "20",
        "--val_iter", "6", "--steps_per_call", "4", "--save_ckpt", ckpt,
    )
    out_fused, _ = run_cli(
        "test.py", *TINY, "--token_cache", "--test_iter", "20",
        "--steps_per_call", "4", "--load_ckpt", ckpt,
    )
    out_single, _ = run_cli(
        "test.py", *TINY, "--token_cache", "--test_iter", "20",
        "--load_ckpt", ckpt,
    )
    assert (
        last_json(out_fused)["test_accuracy"]
        == last_json(out_single)["test_accuracy"]
    )


def test_new_flags_reach_config():
    """--zero_opt/--vocab_size/--divergence_guard land in ExperimentConfig."""
    from induction_network_on_fewrel_tpu.cli import (
        build_arg_parser,
        config_from_args,
    )

    args = build_arg_parser(train=True).parse_args([
        "--zero_opt", "--vocab_size", "1002", "--divergence_guard", "stop",
    ])
    cfg = config_from_args(args)
    assert cfg.zero_opt is True
    assert cfg.vocab_size == 1002
    assert cfg.divergence_guard == "stop"
